"""ProcessGroup async-Task API + executable cache (reference
process_group.h:47, process_group_nccl.h:37; see
paddle_tpu/distributed/collective/).

The CPU test mesh has 8 devices in ONE process, so the cross-process ring
degenerates to nranks=1 fast paths plus cache/Task mechanics — the same
situation as the reference's single-rank CI tier; the multi-device ring
math itself is exercised by building a ring over local devices."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.collective import P2POp, ProcessGroup, Task, batch_isend_irecv


def test_world1_fast_paths_and_task_api():
    pg = ProcessGroup()
    assert pg.nranks == 1
    t = paddle.to_tensor(np.arange(4, dtype=np.float32))
    task = pg.allreduce(t)
    assert task.wait() and task.is_completed()
    np.testing.assert_array_equal(np.asarray(task.result()), np.arange(4, dtype=np.float32))
    g = pg.allgather(t)
    assert np.asarray(g.result()).shape == (1, 4)
    b = pg.broadcast(t, src=0)
    assert b.is_completed()
    pg.barrier()


class _LocalRing(ProcessGroup):
    """Ring over local DEVICES (process_index is 0 for all 8 CPU devices) —
    exercises the compiled-collective path the multi-host ring uses."""

    def __init__(self, n):
        super().__init__(ranks=list(range(n)))

    def _ring_mesh(self):
        if self._mesh is None:
            devs = jax.devices()[: self.nranks]
            self._mesh = jax.sharding.Mesh(np.asarray(devs), ("ring",))
        return self._mesh

    def _global(self, value):
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = self._ring_mesh()
        sharding = NamedSharding(mesh, PartitionSpec("ring"))
        locals_ = [jnp.asarray(value + i)[None] for i in range(self.nranks)]
        arrs = [jax.device_put(l, d) for l, d in zip(locals_, mesh.devices.flat)]
        return jax.make_array_from_single_device_arrays(
            (self.nranks,) + tuple(locals_[0].shape[1:]), sharding, arrs
        )


def test_ring_allreduce_math_and_cache():
    pg = _LocalRing(4)
    v = jnp.ones((8,), jnp.float32)
    task = pg.allreduce(v)  # ranks contribute v+0, v+1, v+2, v+3
    out = np.asarray(task.result())
    np.testing.assert_allclose(out, (1 + 2 + 3 + 4) * np.ones(8, np.float32))
    assert pg.cache_size() == 1
    pg.allreduce(jnp.ones((8,), jnp.float32))  # same key -> cached
    assert pg.cache_size() == 1
    pg.allreduce(jnp.ones((16,), jnp.float32))  # new shape -> new entry
    assert pg.cache_size() == 2
    pg.allreduce(jnp.ones((8,), jnp.bfloat16))  # new dtype -> new entry
    assert pg.cache_size() == 3


def test_ring_allgather_broadcast():
    pg = _LocalRing(4)
    v = jnp.zeros((2,), jnp.float32)
    g = np.asarray(pg.allgather(v).result())
    np.testing.assert_allclose(g[:, 0], [0, 1, 2, 3])
    b = np.asarray(pg.broadcast(jnp.zeros((2,), jnp.float32), src=2).result())
    np.testing.assert_allclose(b, [2, 2])


def test_batch_isend_irecv_world1():
    t = paddle.to_tensor(np.zeros(2, np.float32))
    tasks = batch_isend_irecv([P2POp("isend", t, 0)])
    assert tasks[0].is_completed()
