"""Warm-start tier: engine AOT warmup, warm-standby readiness, and the
warmed-respawn compile-cache contract (serving/__init__.py `warmup`,
serving/cluster.py standby tier, docs/SERVING_CLUSTER.md; ROADMAP item 5).

Three tiers:

- **Detector units** (fake clock): `mark_warmed` ends the boot-grace
  carve-out — a worker that announced `warmed=True` and then stalls is
  declared dead within the NORMAL miss threshold, while cold boots keep
  the grace window.
- **Engine units**: `GenerationEngine.warmup()` AOT-compiles the macro
  -step executables against the engine's recorded geometry; the warmed
  executable is the one `step()` dispatches (identity, not just
  equality), streams are bit-identical to a lazily-compiled engine, and
  `EngineSnapshot.config()` exposes the recorded geometry that decides
  whether warm executables carry onto a restored engine.
- **Cluster e2e**: a warm standby that stalls (SIGSTOP) dies on the
  steady-state miss budget, never the boot grace; and (fresh per-test
  persistent cache) a SIGKILLed decode replica's respawned replacement
  boots with persistent compile-cache HITS > 0 — asserted from its boot
  report, not assumed.

This module forks standby/replica workers and SIGKILLs them: it rides a
DEDICATED tools/run_tier1.py isolated worker, never the shared shard."""

import os
import signal
import time

import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

from paddle_tpu.serving.router import FailureDetector  # noqa: E402

_HERE = os.path.dirname(os.path.abspath(__file__))
_MODEL_SPEC = os.path.join(_HERE, "cluster_common.py") + ":make_model"
_EKW = dict(max_batch=2, block_size=8, num_blocks=32, decode_chunk=2)


# ------------------------------------------------------- detector units
def test_mark_warmed_ends_boot_grace():
    """A warm worker that stalls is dead within the normal miss budget —
    the boot-grace carve-out exists only for cold boots still paying
    import + compile before their first heartbeat."""
    clock = {"t": 0.0}
    det = FailureDetector(100, 3, clock=lambda: clock["t"],
                          boot_grace_s=5.0)
    det.track("w")
    det.mark_warmed("w")
    # 0.3s = miss_threshold * heartbeat: dead NOW, grace does not apply
    clock["t"] = 0.35
    assert det.dead_ranks() == ["w"]


def test_cold_boot_keeps_grace_without_warm_report():
    clock = {"t": 0.0}
    det = FailureDetector(100, 3, clock=lambda: clock["t"],
                          boot_grace_s=5.0)
    det.track("w")
    clock["t"] = 0.35  # far past the miss budget, inside the grace
    assert det.dead_ranks() == []
    clock["t"] = 5.0
    assert det.dead_ranks() == ["w"]


def test_mark_warmed_restarts_miss_window_at_report():
    """The warm report itself is proof of life: the miss clock starts at
    the report, not at track() — a slow warmup must not instantly kill
    the worker that just finished it."""
    clock = {"t": 0.0}
    det = FailureDetector(100, 3, clock=lambda: clock["t"],
                          boot_grace_s=5.0)
    det.track("w")
    clock["t"] = 4.9  # warmup took nearly the whole grace window
    det.mark_warmed("w")
    clock["t"] = 5.0  # 0.1s after the report: one miss at most
    assert det.dead_ranks() == []
    clock["t"] = 5.3
    assert det.dead_ranks() == ["w"]


def test_mark_warmed_then_heartbeats_stay_alive():
    clock = {"t": 0.0}
    det = FailureDetector(100, 3, clock=lambda: clock["t"],
                          boot_grace_s=5.0)
    det.track("w")
    det.mark_warmed("w")
    for i in range(1, 20):
        clock["t"] = i * 0.1
        det.observe("w", i)
        assert det.dead_ranks() == []


# --------------------------------------------------------- engine units
def _make_engine(**over):
    import sys

    sys.path.insert(0, _HERE)
    from cluster_common import make_model
    from paddle_tpu.serving import GenerationEngine

    kw = dict(_EKW)
    kw.update(over)
    return GenerationEngine(make_model(), **kw)


def _drain(eng, reqs):
    for rid, prompt, opts in reqs:
        eng.add_request(rid, prompt, **opts)
    while eng.has_work():
        eng.step()
    return {rid: eng.result(rid) for rid, _p, _o in reqs}


_REQS = [
    ("a", [5, 9, 17, 33, 2, 8, 7, 4, 22, 3], dict(max_new_tokens=8)),
    ("b", [7, 11, 3], dict(max_new_tokens=6, temperature=5.0, seed=3)),
]


def test_warmup_compiles_the_executable_step_dispatches():
    eng = _make_engine()
    assert eng._step_fns == {}
    rep = eng.warmup()
    D = eng._effective_chunk()
    assert rep["chunks"] == [D]
    assert rep["seconds"] > 0
    compiled = eng._step_fns[D]
    got = _drain(eng, _REQS)
    assert all(got.values())
    # identity: serving dispatched the warmed executable, it did not
    # silently rebuild (a rebuild would mean warmup warmed nothing)
    assert eng._step_fns[D] is compiled


def test_warmed_streams_bit_identical_to_lazy():
    cold = _drain(_make_engine(), _REQS)
    warm_eng = _make_engine()
    warm_eng.warmup()
    warm = _drain(warm_eng, _REQS)
    assert warm == cold


def test_warmup_extra_chunks_and_validation():
    eng = _make_engine()
    rep = eng.warmup(chunks=[1, 2])
    assert rep["chunks"] == [1, 2]
    assert set(eng._step_fns) == {1, 2}
    with pytest.raises(ValueError):
        eng.warmup(chunks=[0])


def test_snapshot_config_records_geometry(tmp_path):
    from paddle_tpu.serving.snapshot import EngineSnapshot

    eng = _make_engine()
    store = EngineSnapshot(str(tmp_path / "snaps"))
    store.save(eng)
    cfg = store.config()
    assert cfg["max_batch"] == _EKW["max_batch"]
    assert cfg["block_size"] == _EKW["block_size"]
    assert cfg["num_blocks"] == _EKW["num_blocks"]
    assert not cfg["has_draft"]
    empty = EngineSnapshot(str(tmp_path / "none"))
    with pytest.raises(RuntimeError):
        empty.config()


def test_carries_executables_gates_on_geometry(tmp_path):
    from paddle_tpu.serving.cluster_worker import _carries_executables
    from paddle_tpu.serving.snapshot import EngineSnapshot

    eng = _make_engine()
    store = EngineSnapshot(str(tmp_path / "snaps"))
    store.save(eng)
    cfg = store.config()
    assert _carries_executables(eng, cfg)
    # a geometry mismatch (different pool) must NOT carry: the compiled
    # signature would not match the restored engine's buffers
    other = dict(cfg, num_blocks=cfg["num_blocks"] * 2)
    assert not _carries_executables(eng, other)


# ----------------------------------------------------------- cluster e2e
def test_stalled_warm_standby_dies_on_steady_state_budget(tmp_path):
    """A standby that reported ready and then stalls (SIGSTOP — the
    process is alive, so the parent-exit fast path never fires) is
    declared dead within the NORMAL miss budget, nowhere near the 30s
    boot grace: its warm report already armed steady-state accounting."""
    from paddle_tpu.serving.cluster import EngineCluster, cluster_stats

    c = EngineCluster(_MODEL_SPEC, num_replicas=1, num_prefill=0,
                      engine_kwargs=_EKW, workdir=str(tmp_path / "wd"),
                      heartbeat_ms=100, miss_threshold=10, standby=1)
    try:
        deadline = time.monotonic() + 180
        while cluster_stats()["standbys_warm"] < 1:
            c.poll()
            assert time.monotonic() < deadline, "standby never warmed"
            time.sleep(0.01)
        skey = next(k for k in c._workers if k[0] == "standby")
        assert c.detector.boot_grace_s >= 30.0  # the window NOT applied
        os.kill(c._workers[skey].proc.pid, signal.SIGSTOP)
        t0 = time.monotonic()
        try:
            # miss budget = 10 * 100ms; declared dead well within a
            # small multiple of it (poll jitter), never the boot grace
            while c._workers[skey].alive:
                c.poll()
                assert time.monotonic() - t0 < 10.0, \
                    "stalled warm standby outlived the miss budget"
                time.sleep(0.02)
        finally:
            try:  # burial SIGKILLs the stopped proc; pid may be reaped
                os.kill(c._workers[skey].proc.pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
        assert time.monotonic() - t0 < 10.0 < c.detector.boot_grace_s
    finally:
        c.shutdown()


def test_respawned_worker_boots_with_persistent_cache_hits(
        tmp_path, monkeypatch):
    """The warmed-respawn contract, asserted not assumed: gen-1 workers
    populate a FRESH persistent compile cache through the shared
    _core/compile_cache helper; the respawned replacement's boot report
    must then show persistent_cache_hits > 0 (its warmup was served from
    the cache the first generation wrote)."""
    from paddle_tpu.serving.cluster import (EngineCluster, cluster_stats,
                                            reset_cluster_stats)

    cache = tmp_path / "fresh_cache"
    monkeypatch.setenv("PADDLE_TPU_TEST_CACHE_DIR", str(cache))
    reset_cluster_stats()
    c = EngineCluster(_MODEL_SPEC, num_replicas=1, num_prefill=0,
                      engine_kwargs=_EKW, workdir=str(tmp_path / "wd"),
                      heartbeat_ms=100, miss_threshold=10,
                      snapshot_interval=1)
    try:
        c.submit("r0", [5, 9, 17, 33, 2, 8, 7, 4, 22, 3],
                 max_new_tokens=24)
        c.submit("r1", [7, 11, 3], max_new_tokens=24, temperature=5.0,
                 seed=3)
        deadline = time.monotonic() + 240
        while not c.router.request("r0").tokens:
            c.poll()
            assert time.monotonic() < deadline, "stream never started"
            time.sleep(0.005)
        os.kill(c._workers[("decode", 0)].proc.pid, signal.SIGKILL)
        c.serve(timeout_s=240)
        stats = cluster_stats()
        assert stats["respawns"] >= 1, stats
        # the replacement AOT-warmed (report folded into telemetry) and
        # its compiles were served from the persistent cache
        assert stats["warmups"] >= 2, stats
        assert stats["respawn_compile_hits"] > 0, stats
        assert c.result("r0") and c.result("r1")
    finally:
        c.shutdown()
