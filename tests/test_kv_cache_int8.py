"""Int8 paged-KV quantization (FLAGS_kv_cache_dtype='int8'): QuantPool
op-level accuracy, the serving-engine parity gate (greedy streams match
bf16 pools on short contexts, bounded logit drift on long ones), capacity
arithmetic, and composition with the prefix cache (docs/DECODE.md)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.ops import paged_attention as pa
from paddle_tpu.serving import GenerationEngine


def _model(seed=11, **kw):
    paddle.seed(seed)
    cfg = llama_tiny(vocab_size=128, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=256,
                     dtype="float32", **kw)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _drain(eng, reqs, **kw):
    for rid, p in reqs:
        eng.add_request(rid, p, **kw)
    while eng.has_work():
        eng.step()
    return {rid: eng.result(rid) for rid, _ in reqs}


# ------------------------------------------------------------ op-level tier
def test_quant_pool_alloc_and_bytes():
    k8, v8 = pa.alloc_paged_cache(8, 2, 16, 4, dtype="int8")
    kb, vb = pa.alloc_paged_cache(8, 2, 16, 4, dtype=jnp.bfloat16)
    assert isinstance(k8, pa.QuantPool) and isinstance(v8, pa.QuantPool)
    assert k8.data.dtype == jnp.int8 and k8.scale.shape == (8, 2)
    assert pa.pool_num_kv_heads(k8) == pa.pool_num_kv_heads(kb) == 2
    # payload halves vs bf16; tiny f32 scale sidecar rides along
    assert k8.data.nbytes * 2 == kb.nbytes
    assert pa.pool_nbytes(k8) == k8.data.nbytes + k8.scale.nbytes


def test_quant_write_gather_roundtrip_accuracy():
    """paged_write_chunk into an int8 pool then paged_gather recovers the
    stored values to int8 precision (per-block-per-head scales)."""
    rng = np.random.default_rng(0)
    kc, _ = pa.alloc_paged_cache(4, 2, 8, 4, dtype="int8")
    new = jnp.asarray(rng.normal(size=(1, 16, 2, 4)).astype(np.float32))
    tables = jnp.asarray([[0, 2, 3]], jnp.int32)
    positions = jnp.arange(16, dtype=jnp.int32)[None]
    kc = pa.paged_write_chunk(kc, new, tables, positions)
    got = pa.paged_gather(kc, tables)[0, :, :16]           # [Nkv, 16, H]
    want = jnp.moveaxis(new[0], 1, 0)                      # [Nkv, 16, H]
    # quantization step is amax/127 per (block, head): ~1% of the range
    amax = float(jnp.max(jnp.abs(want)))
    assert float(jnp.max(jnp.abs(got - want))) <= amax / 127.0 + 1e-6


def test_quant_running_max_rescales_resident_payload():
    """A decode write whose amax exceeds the block's scale grows the scale
    and RESCALES the resident payload — earlier tokens stay decodable."""
    kc, _ = pa.alloc_paged_cache(2, 1, 4, 2, dtype="int8")
    tables = jnp.asarray([[0]], jnp.int32)
    small = jnp.full((1, 1, 1, 2), 0.5, jnp.float32)
    big = jnp.full((1, 1, 1, 2), 8.0, jnp.float32)
    kc = pa.paged_write_chunk(kc, small, tables, jnp.asarray([[0]]))
    s0 = float(kc.scale[0, 0])
    kc = pa.paged_write_chunk(kc, big, tables, jnp.asarray([[1]]))
    assert float(kc.scale[0, 0]) > s0
    view = pa.paged_gather(kc, tables)[0, 0]               # [4, 2]
    np.testing.assert_allclose(np.asarray(view[0]), 0.5, atol=8.0 / 127 + 1e-6)
    np.testing.assert_allclose(np.asarray(view[1]), 8.0, atol=8.0 / 127 + 1e-6)


def test_quant_pour_blocks_resets_stale_scales():
    """paged_pour_blocks SETS fresh scales (prefill into recycled blocks):
    a block that once held huge values quantizes new small ones finely."""
    kc, _ = pa.alloc_paged_cache(2, 1, 4, 2, dtype="int8")
    tables = jnp.asarray([[0]], jnp.int32)
    kc = pa.paged_pour_blocks(kc, jnp.full((1, 1, 4, 2), 100.0), [0])
    kc = pa.paged_pour_blocks(kc, jnp.full((1, 1, 4, 2), 0.25), [0])
    assert float(kc.scale[0, 0]) == pytest.approx(0.25 / 127.0)
    view = pa.paged_gather(kc, tables)[0, 0]
    np.testing.assert_allclose(np.asarray(view), 0.25, rtol=0.02)


def test_quant_chunk_attention_matches_exact_reference():
    """paged_chunk_attention over an int8 pool tracks the full-precision
    pool's output within quantization tolerance at a LONG context."""
    rng = np.random.default_rng(1)
    b, t, n, h, bs, blocks_per_seq = 1, 2, 2, 8, 8, 16    # S = 128
    q = jnp.asarray(rng.normal(size=(b, t, n, h)).astype(np.float32))
    kf, vf = pa.alloc_paged_cache(blocks_per_seq, n, bs, h, jnp.float32)
    kq, vq = pa.alloc_paged_cache(blocks_per_seq, n, bs, h, "int8")
    tables = jnp.arange(blocks_per_seq, dtype=jnp.int32)[None]
    kv = rng.normal(size=(blocks_per_seq, n, bs, h)).astype(np.float32)
    vv = rng.normal(size=(blocks_per_seq, n, bs, h)).astype(np.float32)
    kf, vf = pa.paged_pour_blocks(kf, jnp.asarray(kv), range(blocks_per_seq)), \
        pa.paged_pour_blocks(vf, jnp.asarray(vv), range(blocks_per_seq))
    kq, vq = pa.paged_pour_blocks(kq, jnp.asarray(kv), range(blocks_per_seq)), \
        pa.paged_pour_blocks(vq, jnp.asarray(vv), range(blocks_per_seq))
    lens = jnp.asarray([blocks_per_seq * bs], jnp.int32)
    ref = pa.paged_chunk_attention(q, kf, vf, tables, lens)
    got = pa.paged_chunk_attention(q, kq, vq, tables, lens)
    # attention output is a convex combination of V rows: int8 V error is
    # ~amax/127 per element and the K error only perturbs the weights
    assert float(jnp.max(jnp.abs(got - ref))) < 0.15
    assert float(jnp.mean(jnp.abs(got - ref))) < 0.03


# ------------------------------------------------------- engine parity tier
def test_int8_engine_greedy_matches_bf16_short_contexts():
    """The parity gate: greedy token streams from int8 pools equal the
    full-precision pools' streams on short contexts — chunked decode and
    speculative tiers included."""
    m = _model()
    rng = np.random.default_rng(3)
    reqs = [("a", list(rng.integers(0, 128, 12))),
            ("b", list(rng.integers(0, 128, 7)))]

    for kw in ({}, {"decode_chunk": 4}):
        ref = _drain(GenerationEngine(m, max_batch=2, block_size=8,
                                      num_blocks=32, **kw),
                     reqs, max_new_tokens=8)
        got = _drain(GenerationEngine(m, max_batch=2, block_size=8,
                                      num_blocks=32, kv_cache_dtype="int8",
                                      **kw),
                     reqs, max_new_tokens=8)
        assert got == ref, f"engine kwargs {kw}"


def _first_decode_logits(eng):
    """Logits of slot 0's first decode forward over the RESIDENT pool —
    the same computation _build_step's scan body runs, minus sampling;
    the engine's state is left untouched (functional pool updates are
    discarded)."""
    from paddle_tpu._core.autograd import no_grad
    from paddle_tpu._core.tensor import Tensor
    from paddle_tpu.models.llama import _decode_layers_paged

    s = eng._slots[0]
    W = eng._max_blocks_per_seq
    row = list(s.blocks) + [s.blocks[-1]] * (W - len(s.blocks))
    tables = jnp.asarray([row], jnp.int32)
    lens = jnp.asarray([s.seq_len + 1], jnp.int32)
    tok = jnp.asarray([[s.last_token]], jnp.int32)
    model = eng.model
    with no_grad():
        h = model.model.embed_tokens(Tensor(tok))
        cos = model.model.rope_cos._value
        sin = model.model.rope_sin._value
        h, _, _ = _decode_layers_paged(
            model.model.layers, h, cos, sin,
            list(eng._kpools), list(eng._vpools), tables, lens)
        h = model.model.norm(h)
        return np.asarray(model._logits(h)._value[0, -1, :], np.float32)


def test_int8_engine_bounded_logit_drift_long_context():
    """Long contexts need not stay bit-identical — the gate is BOUNDED
    drift: the first decode forward's logits over a 150-token resident
    int8 pool stay close to the full-precision pool's logits, and the
    first generated token (produced by the exact, unquantized prefill
    forward) matches exactly."""
    m = _model(seed=12)
    prompt = list(np.random.default_rng(4).integers(0, 128, 150))

    def admit(**kw):
        eng = GenerationEngine(m, max_batch=1, block_size=8, num_blocks=64,
                               **kw)
        eng.add_request("r", prompt, max_new_tokens=4)
        assert eng._slots[0].active
        return eng

    ref_eng = admit()
    q_eng = admit(kv_cache_dtype="int8")
    # first token rides the prefill logits — exact on both paths
    assert q_eng._slots[0].last_token == ref_eng._slots[0].last_token
    ref = _first_decode_logits(ref_eng)
    got = _first_decode_logits(q_eng)
    spread = float(ref.max() - ref.min())
    drift = np.abs(got - ref)
    assert float(drift.max()) < 0.10 * spread
    assert float(drift.mean()) < 0.02 * spread


def test_int8_composes_with_prefix_cache():
    """A quantized pool caches quantized prefix pages.  On this SHORT
    shared prefix the composed streams equal int8 cache-off bit for bit;
    the general contract is only bounded drift — with the cache on, the
    suffix prefill attends DEQUANTIZED prefix K/V where a full re-prefill
    attends exact activations (docs/DECODE.md caveat), so long prefixes
    may diverge within the int8 drift budget."""
    m = _model()
    shared = list(np.random.default_rng(5).integers(0, 128, 16))
    reqs = [("a", shared + [3, 7]), ("b", shared + [9])]
    ref = _drain(GenerationEngine(m, max_batch=2, block_size=8,
                                  num_blocks=32, kv_cache_dtype="int8"),
                 reqs, max_new_tokens=6)
    got = _drain(GenerationEngine(m, max_batch=2, block_size=8,
                                  num_blocks=32, kv_cache_dtype="int8",
                                  prefix_cache=True),
                 reqs, max_new_tokens=6)
    assert got == ref


def test_int8_speculative_greedy_matches_full_precision():
    """Spec verify writes its whole K+1 chunk (including later-REJECTED
    draft tokens) through the running-max quant path before acceptance
    rolls lens back — a rejected outlier can grow a block's scale for
    good.  The gate: greedy spec streams still match the full-precision
    spec engine on short contexts."""
    target = _model(seed=41)
    paddle.seed(42)
    dcfg = llama_tiny(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=256,
                      dtype="float32")
    draft = LlamaForCausalLM(dcfg)
    draft.eval()
    rng = np.random.default_rng(8)
    reqs = [("a", list(rng.integers(0, 128, 12))),
            ("b", list(rng.integers(0, 128, 7)))]
    ref = _drain(GenerationEngine(target, max_batch=2, block_size=8,
                                  num_blocks=32, draft_model=draft),
                 reqs, max_new_tokens=8)
    got = _drain(GenerationEngine(target, max_batch=2, block_size=8,
                                  num_blocks=32, draft_model=draft,
                                  kv_cache_dtype="int8"),
                 reqs, max_new_tokens=8)
    assert got == ref


def test_int8_capacity_at_fixed_bytes():
    """The capacity claim, allocator-arithmetic form: at identical
    pool-block bytes an int8 pool admits >= 1.8x the resident requests of
    a bf16 pool (satellite twin of the bench_decode workload)."""
    paddle.seed(2)
    cfg = llama_tiny(vocab_size=128, hidden_size=64, intermediate_size=128,
                     num_attention_heads=4, num_key_value_heads=4,
                     max_position_embeddings=4096, dtype="bfloat16")
    m = LlamaForCausalLM(cfg)
    m.eval()
    nkv = cfg.num_key_value_heads
    hd = cfg.hidden_size // cfg.num_attention_heads
    elems = nkv * 16 * hd
    per_block_bf16 = cfg.num_hidden_layers * 2 * elems * 2
    per_block_int8 = cfg.num_hidden_layers * 2 * (elems + nkv * 4)
    nb_bf16 = 10
    nb_int8 = (nb_bf16 * per_block_bf16) // per_block_int8

    def admitted(kv_dtype, nb):
        eng = GenerationEngine(m, max_batch=nb, block_size=16, num_blocks=nb,
                               kv_cache_dtype=kv_dtype)
        rng = np.random.default_rng(3)
        count = 0
        while True:
            p = list(rng.integers(0, 128, 28))  # 2 blocks each (+4 new)
            if eng.add_request(f"c{count}", p, max_new_tokens=4) is None:
                return count
            count += 1

    res_bf16 = admitted("bf16", nb_bf16)
    res_int8 = admitted("int8", int(nb_int8))
    assert res_int8 / res_bf16 >= 1.8


def test_int8_rejects_mesh_and_bad_dtype():
    m = _model()
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        GenerationEngine(m, num_blocks=8, kv_cache_dtype="fp8")


def test_int8_plus_mesh_constructs_sharded():
    """The PR-6/PR-9 NotImplementedError is GONE: int8 pools compose with
    the TP mesh engine — QuantPool payload AND its per-block-per-head
    scales both come back committed to the KV-head sharding (the same
    PartitionSpec covers the rank-4 payload and the rank-2 scales), and
    the per-device telemetry reports the sharding-divided bytes.  Stream
    parity mesh-vs-single-device lives in tests/test_serving_mesh.py
    (isolated worker — this module rides a round-robin shard, so no
    multi-device decode dispatch happens here)."""
    from paddle_tpu.distributed import ProcessMesh
    from paddle_tpu.serving import decode_stats

    m = _model(seed=13)
    mesh = ProcessMesh(np.arange(2).reshape(2), ["mp"])
    eng = GenerationEngine(m, num_blocks=8, kv_cache_dtype="int8",
                           mesh=mesh)
    kp = eng._kpools[0]
    assert isinstance(kp, pa.QuantPool)
    assert "mp" in str(kp.data.sharding.spec)
    assert "mp" in str(kp.scale.sharding.spec)
    st = decode_stats()
    assert st["mesh_shape"] == "mp2"
    assert st["pool_bytes_per_device"] * 2 == st["pool_bytes"]
    # and each knob alone still works
    GenerationEngine(m, num_blocks=8, kv_cache_dtype="int8")
    GenerationEngine(_model(seed=13), num_blocks=8, kv_cache_dtype="bf16",
                     mesh=mesh)
