"""Numeric-vs-analytic gradient audit over a representative op sample
(reference OpTest.check_grad, test/legacy_test/op_test.py:2944)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils.op_test import check_grad, check_output

R = np.random.default_rng(0)


# constants for the sparse-attention case (a lambda must NOT redraw random
# tensors per call — numeric differencing would compare different functions)
_SA_K = paddle.to_tensor(np.random.default_rng(10).standard_normal((1, 1, 4, 8)).astype(np.float32))
_SA_V = paddle.to_tensor(np.random.default_rng(11).standard_normal((1, 1, 4, 8)).astype(np.float32))
# lower-triangular CSR: row i attends to columns 0..i
_SA_OFF = paddle.to_tensor(np.array([[[0, 1, 3, 6, 10]]], np.int32))
_SA_COL = paddle.to_tensor(np.array([[[0, 0, 1, 0, 1, 2, 0, 1, 2, 3]]], np.int32))

GRAD_CASES = [
    ("matmul", lambda a, b: paddle.matmul(a, b), (R.standard_normal((3, 4)), R.standard_normal((4, 2)))),
    ("add_bcast", lambda a, b: a + b, (R.standard_normal((3, 4)), R.standard_normal((4,)))),
    ("mul", lambda a, b: a * b, (R.standard_normal((3, 3)), R.standard_normal((3, 3)))),
    ("tanh", lambda a: paddle.tanh(a), (R.standard_normal((5,)),)),
    ("sigmoid", lambda a: paddle.nn.functional.sigmoid(a), (R.standard_normal((5,)),)),
    ("softmax", lambda a: paddle.nn.functional.softmax(a, axis=-1), (R.standard_normal((2, 6)),)),
    ("mean", lambda a: a.mean(), (R.standard_normal((4, 4)),)),
    ("logsumexp", lambda a: paddle.logsumexp(a), (R.standard_normal((6,)),)),
    ("layer_norm_fn", lambda a: paddle.nn.functional.layer_norm(a, (6,)), (R.standard_normal((3, 6)),)),
    ("gelu", lambda a: paddle.nn.functional.gelu(a), (R.standard_normal((5,)),)),
    ("exp", lambda a: paddle.exp(a), (0.3 * R.standard_normal((4,)),)),
    ("sqrt", lambda a: paddle.sqrt(a), (np.abs(R.standard_normal((4,))) + 0.5,)),
    ("transpose_reshape", lambda a: paddle.reshape(paddle.transpose(a, [1, 0]), [-1]) * 2.0, (R.standard_normal((3, 4)),)),
    ("concat", lambda a, b: paddle.concat([a, b], axis=0).sum(axis=0), (R.standard_normal((2, 3)), R.standard_normal((2, 3)))),
    ("gather", lambda a: paddle.gather(a, paddle.to_tensor(np.array([2, 0], np.int32))), (R.standard_normal((4, 3)),)),
    ("masked_scatter", lambda a, v: paddle.masked_scatter(a, paddle.to_tensor(np.array([True, False, True, False])), v), (R.standard_normal((4,)), R.standard_normal((4,)))),
    ("where", lambda a, b: paddle.where(paddle.to_tensor(np.array([True, False, True])), a, b), (R.standard_normal((3,)), R.standard_normal((3,)))),
    ("maximum", lambda a, b: paddle.maximum(a, b), (R.standard_normal((4,)), R.standard_normal((4,)) + 2.0)),
    ("pow", lambda a: paddle.pow(a, 3.0), (np.abs(R.standard_normal((4,))) + 0.5,)),
    ("cross_entropy", lambda a: paddle.nn.functional.cross_entropy(a, paddle.to_tensor(np.array([1, 0], np.int32))), (R.standard_normal((2, 4)),)),
    # round-2 surface-closure differentiable ops
    ("pdist", lambda a: paddle.pdist(a), (R.standard_normal((4, 3)),)),
    ("diagonal_scatter", lambda a, v: paddle.diagonal_scatter(a, v), (R.standard_normal((3, 3)), R.standard_normal((3,)))),
    ("select_scatter", lambda a, v: paddle.select_scatter(a, v, 0, 1), (R.standard_normal((3, 4)), R.standard_normal((4,)))),
    ("index_fill", lambda a: paddle.index_fill(a, paddle.to_tensor(np.array([0], np.int32)), 0, 2.0), (R.standard_normal((3, 2)),)),
    ("unflatten", lambda a: paddle.unflatten(a, 0, [2, 3]), (R.standard_normal((6,)),)),
    ("grid_sample", lambda a, g: paddle.nn.functional.grid_sample(a, g), (R.standard_normal((1, 1, 4, 4)), 0.5 * R.standard_normal((1, 3, 3, 2)))),
    ("pairwise_distance", lambda a, b: paddle.nn.functional.pairwise_distance(a, b), (R.standard_normal((3, 4)), R.standard_normal((3, 4)) + 1.0)),
    ("multi_margin", lambda a: paddle.nn.functional.multi_margin_loss(a, paddle.to_tensor(np.array([1, 0], np.int32))), (R.standard_normal((2, 4)),)),
    ("sparse_attention_grad", lambda q: paddle.nn.functional.sparse_attention(
        q, _SA_K, _SA_V, _SA_OFF, _SA_COL
    ), (R.standard_normal((1, 1, 4, 8)),)),
    ("margin_ce", lambda a: paddle.nn.functional.margin_cross_entropy(
        paddle.tanh(a) * 0.9, paddle.to_tensor(np.array([1, 0], np.int32)),
        margin1=1.0, margin2=0.1, margin3=0.0, scale=4.0), (0.3 * R.standard_normal((2, 4)),)),
    ("softmax_mask_fuse_tri", lambda a: paddle.incubate.softmax_mask_fuse_upper_triangle(a), (R.standard_normal((1, 3, 3)),)),
]


@pytest.mark.parametrize("name,fn,arrays", GRAD_CASES, ids=[c[0] for c in GRAD_CASES])
def test_numeric_vs_analytic_grad(name, fn, arrays):
    check_grad(fn, *arrays)


def test_check_output_utility():
    check_output(
        lambda a, b: paddle.matmul(a, b),
        lambda a, b: a @ b,
        R.standard_normal((3, 4)).astype(np.float32),
        R.standard_normal((4, 2)).astype(np.float32),
    )
