"""Numeric-vs-analytic gradient audit over a representative op sample
(reference OpTest.check_grad, test/legacy_test/op_test.py:2944)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils.op_test import check_grad, check_output

R = np.random.default_rng(0)


GRAD_CASES = [
    ("matmul", lambda a, b: paddle.matmul(a, b), (R.standard_normal((3, 4)), R.standard_normal((4, 2)))),
    ("add_bcast", lambda a, b: a + b, (R.standard_normal((3, 4)), R.standard_normal((4,)))),
    ("mul", lambda a, b: a * b, (R.standard_normal((3, 3)), R.standard_normal((3, 3)))),
    ("tanh", lambda a: paddle.tanh(a), (R.standard_normal((5,)),)),
    ("sigmoid", lambda a: paddle.nn.functional.sigmoid(a), (R.standard_normal((5,)),)),
    ("softmax", lambda a: paddle.nn.functional.softmax(a, axis=-1), (R.standard_normal((2, 6)),)),
    ("mean", lambda a: a.mean(), (R.standard_normal((4, 4)),)),
    ("logsumexp", lambda a: paddle.logsumexp(a), (R.standard_normal((6,)),)),
    ("layer_norm_fn", lambda a: paddle.nn.functional.layer_norm(a, (6,)), (R.standard_normal((3, 6)),)),
    ("gelu", lambda a: paddle.nn.functional.gelu(a), (R.standard_normal((5,)),)),
    ("exp", lambda a: paddle.exp(a), (0.3 * R.standard_normal((4,)),)),
    ("sqrt", lambda a: paddle.sqrt(a), (np.abs(R.standard_normal((4,))) + 0.5,)),
    ("transpose_reshape", lambda a: paddle.reshape(paddle.transpose(a, [1, 0]), [-1]) * 2.0, (R.standard_normal((3, 4)),)),
    ("concat", lambda a, b: paddle.concat([a, b], axis=0).sum(axis=0), (R.standard_normal((2, 3)), R.standard_normal((2, 3)))),
    ("gather", lambda a: paddle.gather(a, paddle.to_tensor(np.array([2, 0], np.int32))), (R.standard_normal((4, 3)),)),
    ("masked_scatter", lambda a, v: paddle.masked_scatter(a, paddle.to_tensor(np.array([True, False, True, False])), v), (R.standard_normal((4,)), R.standard_normal((4,)))),
    ("where", lambda a, b: paddle.where(paddle.to_tensor(np.array([True, False, True])), a, b), (R.standard_normal((3,)), R.standard_normal((3,)))),
    ("maximum", lambda a, b: paddle.maximum(a, b), (R.standard_normal((4,)), R.standard_normal((4,)) + 2.0)),
    ("pow", lambda a: paddle.pow(a, 3.0), (np.abs(R.standard_normal((4,))) + 0.5,)),
    ("cross_entropy", lambda a: paddle.nn.functional.cross_entropy(a, paddle.to_tensor(np.array([1, 0], np.int32))), (R.standard_normal((2, 4)),)),
]


@pytest.mark.parametrize("name,fn,arrays", GRAD_CASES, ids=[c[0] for c in GRAD_CASES])
def test_numeric_vs_analytic_grad(name, fn, arrays):
    check_grad(fn, *arrays)


def test_check_output_utility():
    check_output(
        lambda a, b: paddle.matmul(a, b),
        lambda a, b: a @ b,
        R.standard_normal((3, 4)).astype(np.float32),
        R.standard_normal((4, 2)).astype(np.float32),
    )
