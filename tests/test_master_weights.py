"""fp32 master weights for low-precision params (reference multi_precision:
python/paddle/optimizer/adamw.py, fleet/utils/mix_precision_utils.py).

Round-1 regression: the optimizer recomputed "master" from the bf16 param
each step, so updates below the bf16 ulp were silently lost."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _tiny_mlp(dtype):
    paddle.seed(0)
    m = nn.Sequential(
        nn.Linear(8, 32),
        nn.ReLU(),
        nn.Linear(32, 1),
    )
    if dtype != "float32":
        for p in m.parameters():
            p._bind(p._value.astype(dtype))
    return m


def test_tiny_updates_not_lost():
    """lr*g below the bf16 ulp must still accumulate in the master copy."""
    p = paddle.to_tensor(np.ones(4, np.float32), dtype="bfloat16")
    p.stop_gradient = False
    opt = paddle.optimizer.SGD(learning_rate=1e-5, parameters=[p])
    # grad of 1.0: update = 1e-5 per step, bf16 ulp at 1.0 is ~7.8e-3
    for _ in range(100):
        p.grad = paddle.to_tensor(np.ones(4, np.float32))
        opt.step()
    master = opt._accumulators[("master_weight", id(p))]._value
    np.testing.assert_allclose(np.asarray(master), 1.0 - 1e-5 * 100, rtol=1e-5)
    # without a master, p stays exactly 1.0 forever; with one, the visible
    # param moves as soon as the master crosses a representable bf16 value
    assert float(master[0]) != 1.0


@pytest.mark.slow
def test_bf16_tracks_fp32_adamw():
    """200 steps of bf16-with-master AdamW stays close to a pure fp32 run."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 8)).astype(np.float32)
    y = (x @ rng.standard_normal((8, 1))).astype(np.float32)

    losses = {}
    for dtype in ("float32", "bfloat16"):
        m = _tiny_mlp(dtype)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters(), weight_decay=0.0)
        xb = paddle.to_tensor(x, dtype=dtype)
        yb = paddle.to_tensor(y, dtype=dtype)
        hist = []
        for _ in range(200):
            out = m(xb)
            loss = ((out - yb) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            hist.append(float(loss.astype("float32").numpy()))
        losses[dtype] = hist

    # same trajectory within bf16 forward noise; final losses comparable
    assert losses["bfloat16"][-1] < losses["bfloat16"][0] * 0.1
    assert abs(losses["bfloat16"][-1] - losses["float32"][-1]) < 0.2 * max(losses["float32"][0], 1e-3)


def test_master_in_state_dict_roundtrip():
    p = paddle.to_tensor(np.ones(4, np.float32), dtype="bfloat16")
    p.stop_gradient = False
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=[p])
    p.grad = paddle.to_tensor(np.full(4, 0.1, np.float32))
    opt.step()
    sd = opt.state_dict()
    assert any(k.startswith("master_weight") for k in sd), list(sd)
