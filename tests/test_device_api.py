

def test_hard_sync_barriers_and_passthrough():
    """hard_sync returns its argument and forces a host readback on jax
    arrays, Tensor-likes (._value) and pytrees (syncs the last leaf)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.device import hard_sync

    a = jnp.arange(8.0)
    assert hard_sync(a) is a
    t = paddle.to_tensor([1.0, 2.0])
    assert hard_sync(t) is t
    tree = {"x": jnp.ones((2, 2)), "y": [jnp.zeros(3)]}
    assert hard_sync(tree) is tree
    assert hard_sync(3.5) == 3.5  # no array leaves: no-op
