"""Speculative decode over the TP mesh — the last engine feature to
compose (ROADMAP item 1, docs/DECODE.md sharded-serving section).

Contract: a speculative engine built with ``mesh=`` shards the TARGET
and the DRAFT (weights via shard_llama, each model's pools over its own
KV-head count) and emits token streams bit-identical to the
single-device speculative engine — which itself emits the plain
engine's streams, so the whole chain is anchored to ordinary decode.
Composes with int8 pools (draft pools quantize too) and with adapter
packs (the draft proposes with the BASE model; the target verifies
through each row's adapter, so acceptance only ever keeps tokens the
adapted model would decode).

Multi-device GSPMD dispatches over the in-process XLA:CPU communicator —
this module rides a DEDICATED tools/run_tier1.py isolated worker.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel import ProcessMesh
from paddle_tpu.nn.lora import apply_lora, lora_state_dict
from paddle_tpu.serving import GenerationEngine

_KW = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=4, max_position_embeddings=64,
           dtype="float32")


def _cfg(**kw):
    from paddle_tpu.models.llama import llama_tiny

    base = dict(_KW)
    base.update(kw)
    return llama_tiny(**base)


def _model(seed=41, **kw):
    from paddle_tpu.models.llama import LlamaForCausalLM

    paddle.seed(seed)
    m = LlamaForCausalLM(_cfg(**kw))
    m.eval()
    return m


def _draft(seed=77):
    from paddle_tpu.models.llama import LlamaForCausalLM

    paddle.seed(seed)
    # a REAL (smaller) draft: 2 KV heads still divide mp=2; mp=4 rides
    # the replicated-draft-pool fallback path (warned, still correct)
    m = LlamaForCausalLM(_cfg(hidden_size=16, intermediate_size=32,
                              num_hidden_layers=1, num_attention_heads=2,
                              num_key_value_heads=2))
    m.eval()
    return m


def _mesh(mp):
    return ProcessMesh(np.arange(mp), ["mp"])


def _drain(eng):
    while eng.has_work():
        eng.step()


def _run(eng):
    eng.add_request("a", [5, 9, 17, 33, 2], max_new_tokens=9)
    eng.step()
    eng.add_request("b", [7, 11, 3], max_new_tokens=6)  # joins mid-flight
    _drain(eng)
    return {"a": eng.result("a"), "b": eng.result("b")}


@pytest.mark.parametrize("kv_dtype,mp", [("bf16", 2), ("int8", 2),
                                         ("bf16", 4)])
def test_spec_engine_mesh_matches_single_device(mp, kv_dtype):
    """Speculative × mesh (× int8): streams bit-identical to the
    single-device speculative engine, including a mid-flight join.  The
    PR-9/10 'not combined with the tensor-parallel mesh engine'
    ValueError is gone."""
    def build(mesh):
        return GenerationEngine(_model(), max_batch=2, block_size=8,
                                num_blocks=32, draft_model=_draft(),
                                num_speculative_tokens=3,
                                kv_cache_dtype=kv_dtype, mesh=mesh)

    ref = _run(build(None))
    if mp == 4:
        # draft nkv=2 does not divide mp=4: the draft pools replicate
        # (warned) while the target pools stay sharded — still bit-exact
        with pytest.warns(UserWarning, match="draft KV pool replicated"):
            eng = build(_mesh(mp))
    else:
        eng = build(_mesh(mp))
        dk = eng._d_kpools[0]
        assert "mp" in str(getattr(dk, "data", dk).sharding.spec)
    kp = eng._kpools[0]
    assert "mp" in str(getattr(kp, "data", kp).sharding.spec)
    got = _run(eng)
    assert got == ref
    st = eng.spec_stats()
    assert st["ticks"] >= 1 and st["accepted"] >= 0


def test_spec_mesh_matches_plain_engine():
    """The sharded speculative engine's streams equal the PLAIN
    single-device engine's — acceptance semantics survive the mesh, not
    just the spec-vs-spec comparison."""
    plain = GenerationEngine(_model(), max_batch=2, block_size=8,
                             num_blocks=32)
    ref = _run(plain)
    eng = GenerationEngine(_model(), max_batch=2, block_size=8,
                           num_blocks=32, draft_model=_draft(),
                           num_speculative_tokens=3, mesh=_mesh(2))
    assert _run(eng) == ref


def _adapter_sd(base, key_seed, rank=4):
    from paddle_tpu.models.llama import LlamaForCausalLM

    ft = LlamaForCausalLM(_cfg())
    ft.set_state_dict(base.state_dict())
    ft.eval()
    apply_lora(ft, rank=rank, alpha=8)
    key = jax.random.PRNGKey(key_seed)
    for name, p in ft.named_parameters():
        if name.endswith(("lora_A", "lora_B")):
            key, sk = jax.random.split(key)
            scale = 0.2 if name.endswith("lora_B") else 0.05
            p._bind(jax.random.normal(sk, p._value.shape,
                                      jnp.float32) * scale)
    return lora_state_dict(ft)


def test_spec_adapters_mesh_full_compose():
    """The whole stack at once — speculative × adapters × mesh: a batch
    mixing two tenants and a base row on a 2-device mesh emits EXACTLY
    the single-device plain adapter engine's streams (the base-model
    draft proposes, the sharded adapted target verifies)."""
    base = _model()
    sds = {f"t{i}": _adapter_sd(base, key_seed=10 + i) for i in range(2)}
    reqs = {"a0": ("t0", [5, 9, 17, 33, 2]), "a1": ("t1", [7, 11, 3, 20]),
            "base": (None, [5, 9, 17, 33, 2])}

    def run(draft, mesh):
        eng = GenerationEngine(_model(), max_batch=3, block_size=8,
                               num_blocks=32, draft_model=draft,
                               num_speculative_tokens=3,
                               adapters={"rank": 4, "max_adapters": 2},
                               mesh=mesh)
        for name, sd in sds.items():
            eng.register_adapter(name, sd, alpha=8)
        for rid, (ad, prompt) in reqs.items():
            eng.add_request(rid, prompt, max_new_tokens=6, adapter=ad)
        _drain(eng)
        return {rid: eng.result(rid) for rid in reqs}

    ref = run(None, None)  # plain single-device adapter engine
    assert len({tuple(v) for v in ref.values()}) == 3
    assert run(_draft(), _mesh(2)) == ref


def test_spec_sampled_slots_still_rejected_on_mesh():
    """Speculative slots stay greedy-only on the mesh (sampled acceptance
    needs rejection sampling — unchanged contract)."""
    eng = GenerationEngine(_model(), max_batch=2, block_size=8,
                           num_blocks=32, draft_model=_draft(),
                           mesh=_mesh(2))
    with pytest.raises(ValueError, match="greedy-only"):
        eng.add_request("r", [1, 2, 3], max_new_tokens=4, temperature=0.7)
