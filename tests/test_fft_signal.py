"""paddle.fft / paddle.signal parity tests vs numpy.fft / scipy.fft /
scipy.signal (reference model: test/legacy_test/test_fft.py,
test_signal.py, test_stft_op.py)."""

import numpy as np
import pytest
import scipy.fft as sfft
import scipy.signal as ssig

import paddle_tpu as paddle
from paddle_tpu import fft, signal


def npv(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


RNG = np.random.default_rng(0)
X1 = RNG.normal(size=32).astype(np.float32)
XC = (RNG.normal(size=32) + 1j * RNG.normal(size=32)).astype(np.complex64)
X2 = RNG.normal(size=(8, 16)).astype(np.float32)


class TestFFT:
    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_fft_ifft_roundtrip(self, norm):
        y = fft.fft(XC, norm=norm)
        np.testing.assert_allclose(npv(y), np.fft.fft(XC, norm=norm), rtol=1e-4, atol=1e-4)
        back = fft.ifft(y, norm=norm)
        np.testing.assert_allclose(npv(back), XC, rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_rfft_irfft(self, norm):
        y = fft.rfft(X1, norm=norm)
        np.testing.assert_allclose(npv(y), np.fft.rfft(X1, norm=norm), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(npv(fft.irfft(y, norm=norm)), X1, rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_hfft_ihfft(self, norm):
        h = XC[:17]
        np.testing.assert_allclose(npv(fft.hfft(h, norm=norm)), np.fft.hfft(h, norm=norm), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(npv(fft.ihfft(X1, norm=norm)), np.fft.ihfft(X1, norm=norm), rtol=1e-4, atol=1e-4)

    def test_fft2_family(self):
        np.testing.assert_allclose(npv(fft.fft2(X2)), np.fft.fft2(X2), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(npv(fft.rfft2(X2)), np.fft.rfft2(X2), rtol=1e-3, atol=1e-3)
        c = np.fft.rfft2(X2)
        np.testing.assert_allclose(npv(fft.irfft2(c)), X2, rtol=1e-3, atol=1e-4)

    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_hfft2_vs_scipy(self, norm):
        h = (RNG.normal(size=(6, 9)) + 1j * RNG.normal(size=(6, 9))).astype(np.complex64)
        np.testing.assert_allclose(
            npv(fft.hfft2(h, norm=norm)), sfft.hfft2(np.asarray(h, np.complex128), norm=norm),
            rtol=1e-3, atol=1e-3,
        )

    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_ihfftn_vs_scipy(self, norm):
        np.testing.assert_allclose(
            npv(fft.ihfftn(X2, norm=norm)), sfft.ihfftn(np.asarray(X2, np.float64), norm=norm),
            rtol=1e-3, atol=1e-4,
        )

    def test_fftn_ifftn(self):
        x3 = RNG.normal(size=(4, 5, 6)).astype(np.float32)
        np.testing.assert_allclose(npv(fft.fftn(x3)), np.fft.fftn(x3), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(
            npv(fft.ifftn(fft.fftn(x3))), x3.astype(np.complex64), rtol=1e-3, atol=1e-4
        )

    def test_freq_shift(self):
        np.testing.assert_allclose(npv(fft.fftfreq(10, 0.1)), np.fft.fftfreq(10, 0.1), rtol=1e-6)
        np.testing.assert_allclose(npv(fft.rfftfreq(10, 0.1)), np.fft.rfftfreq(10, 0.1), rtol=1e-6)
        np.testing.assert_allclose(npv(fft.fftshift(X1)), np.fft.fftshift(X1))
        np.testing.assert_allclose(npv(fft.ifftshift(np.fft.fftshift(X1))), X1)


class TestSignal:
    def test_frame_axis_last(self):
        x = np.arange(10, dtype=np.float32)
        f = npv(signal.frame(x, 4, 2))
        assert f.shape == (4, 4)  # frame_length x num_frames
        np.testing.assert_allclose(f[:, 0], [0, 1, 2, 3])
        np.testing.assert_allclose(f[:, 1], [2, 3, 4, 5])
        np.testing.assert_allclose(f[:, 3], [6, 7, 8, 9])

    def test_frame_axis0(self):
        x = np.arange(10, dtype=np.float32)
        f = npv(signal.frame(x, 4, 2, axis=0))
        assert f.shape == (4, 4)  # num_frames x frame_length
        np.testing.assert_allclose(f[0], [0, 1, 2, 3])
        np.testing.assert_allclose(f[1], [2, 3, 4, 5])

    def test_frame_batched(self):
        x = RNG.normal(size=(3, 20)).astype(np.float32)
        f = npv(signal.frame(x, 5, 3))
        assert f.shape == (3, 5, 6)

    def test_overlap_add_inverts_frame_nonoverlap(self):
        x = np.arange(12, dtype=np.float32)
        f = signal.frame(x, 4, 4)
        back = npv(signal.overlap_add(f, 4))
        np.testing.assert_allclose(back, x)

    def test_overlap_add_sums_overlap(self):
        frames = np.ones((4, 3), np.float32)  # frame_length 4, 3 frames
        out = npv(signal.overlap_add(frames, 2))
        # length = 2*2+4 = 8; middles overlap twice
        np.testing.assert_allclose(out, [1, 1, 2, 2, 2, 2, 1, 1])

    def test_stft_matches_scipy(self):
        x = RNG.normal(size=512).astype(np.float64)
        n_fft, hop = 64, 16
        w = np.hanning(n_fft).astype(np.float64)
        mine = npv(signal.stft(x, n_fft, hop_length=hop, window=w, center=True, pad_mode="reflect"))
        _, _, ref = ssig.stft(
            x, window=w, nperseg=n_fft, noverlap=n_fft - hop, boundary="even",
            padded=False, return_onesided=True,
        )
        # scipy scales by 1/win.sum(); align scaling
        ref = ref * w.sum()
        n = min(mine.shape[-1], ref.shape[-1])
        np.testing.assert_allclose(mine[..., 1:n-1], ref[..., 1:n-1], rtol=1e-4, atol=1e-4)

    def test_stft_istft_roundtrip(self):
        x = RNG.normal(size=400).astype(np.float32)
        n_fft, hop = 64, 16
        w = np.hanning(n_fft).astype(np.float32)
        spec = signal.stft(x, n_fft, hop_length=hop, window=w)
        back = npv(signal.istft(spec, n_fft, hop_length=hop, window=w, length=400))
        np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-3)

    def test_istft_invalid_combo_raises(self):
        spec = signal.stft(RNG.normal(size=256).astype(np.float32), 32)
        with pytest.raises(ValueError):
            signal.istft(spec, 32, onesided=True, return_complex=True)

    def test_stft_complex_requires_twosided(self):
        xc = (RNG.normal(size=256) + 1j * RNG.normal(size=256)).astype(np.complex64)
        with pytest.raises(ValueError):
            signal.stft(xc, 32)
        spec = npv(signal.stft(xc, 32, onesided=False))
        assert spec.shape[0] == 32

    def test_stft_batched_onesided_shape(self):
        x = RNG.normal(size=(2, 256)).astype(np.float32)
        spec = npv(signal.stft(x, 32, hop_length=8))
        assert spec.shape[0] == 2 and spec.shape[1] == 17
