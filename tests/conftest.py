"""Test config: run everything on an 8-device virtual CPU mesh.

This is the TPU-native analog of the reference's fake-device / Gloo tricks
(SURVEY.md §4): XLA's host platform is forced to expose 8 devices so all
sharding/collective paths execute for real without TPU hardware.

Note: this image's sitecustomize registers a remote-TPU PJRT plugin ("axon")
and pins jax_platforms to it; tests must override via jax.config (env vars
are ignored because the plugin wins at interpreter startup).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# float32 means float32 in numeric tests; TPU runs keep the fast MXU default.
jax.config.update("jax_default_matmul_precision", "highest")

# Persist XLA compilations across test runs AND across the sharded
# tier-1 runner's subprocesses (tools/run_tier1.py exports
# PADDLE_TPU_TEST_CACHE_DIR so every shard warms the same cache).
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("PADDLE_TPU_TEST_CACHE_DIR", "/tmp/jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
