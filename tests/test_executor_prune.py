"""Executor fetch-frontier prune — last-writer-wins regression.

append_backward re-binds the loss vid to the grad super-op's own loss
output (static/autodiff.py share_loss alias) precisely so the compiled step
can drop the original forward chain: the grad op's value_and_grad already
runs the forward once.  A prune that never retires superseded producers
keeps BOTH, so the compiled step traces the forward twice — wasted compute,
and a collective-carrying forward duplicated that way can deadlock XLA:CPU
(static/autodiff.py module docstring).  These tests count actual op-fn
trace executions inside the compiled step.
"""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static


import contextlib


@contextlib.contextmanager
def _trace_counting_flags():
    """Disable every flag that executes op fns outside the compiled step —
    the fusion pattern scan and verify mode's abstract eval + differential
    replay would otherwise inflate the trace counters."""
    from paddle_tpu._core import flags

    prev = {"FLAGS_use_pallas_fusion": flags.flag("FLAGS_use_pallas_fusion"),
            "FLAGS_verify_programs": flags.flag("FLAGS_verify_programs"),
            "FLAGS_verify_sharding": flags.flag("FLAGS_verify_sharding")}
    paddle.set_flags({"FLAGS_use_pallas_fusion": False,
                      "FLAGS_verify_programs": False,
                      # mesh lint abstractly traces op fns on the compile
                      # path too (static/mesh_lint.py)
                      "FLAGS_verify_sharding": False})
    try:
        yield
    finally:
        paddle.set_flags(prev)


def _count_op_traces(program, op_type):
    """Wrap every `op_type` op's fn with a Python-side trace counter (the fn
    runs exactly once per inclusion in a compiled step's trace)."""
    counter = {"n": 0}
    for op in program.global_block().ops:
        if op.type == op_type:
            inner = op.fn

            def fn(*a, _inner=inner, **kw):
                counter["n"] += 1
                return _inner(*a, **kw)

            op.fn = fn
    return counter


def test_compiled_step_traces_forward_exactly_once():
    paddle.seed(0)
    main = static.Program()
    layer = nn.Linear(4, 4)
    with static.program_guard(main):
        x = static.data("x", [2, 4], "float32")
        y = layer(x)
        loss = paddle.sum(y * y)
        p_g = static.append_backward(loss, parameter_list=[layer.weight])

    # the captured forward matmul/linear op must execute ONCE in the
    # compiled step: the grad super-op re-runs the forward internally and
    # share_loss re-binds the loss vid to its output, so the original
    # forward producer is superseded
    fwd_ops = [op.type for op in main.global_block().ops
               if op.type not in ("grad", "share_loss")]
    assert fwd_ops, "expected captured forward ops"
    counter = _count_op_traces(main, fwd_ops[0])

    exe = static.Executor()
    xv = np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32)
    # fusion pass off: its pattern scan traces op fns too, which would
    # count pass-time traces instead of compiled-step traces; verify mode
    # likewise executes op fns (abstract eval + differential replay)
    with _trace_counting_flags():
        fetches = exe.run(main, feed={"x": xv},
                          fetch_list=[loss] + [g for _, g in p_g])
    # exactly ONE trace: the grad super-op's internal value_and_grad
    # forward.  The superseded original producer contributes the second
    # trace when the prune is not last-writer-wins.
    assert counter["n"] == 1, (
        f"forward op traced {counter['n']} times inside the compiled step "
        "— expected exactly one (the grad super-op's own forward); the "
        "fetch-frontier prune kept the superseded chain")

    # numerics unchanged by the prune
    w = np.asarray(layer.weight._value)
    b = np.asarray(layer.bias._value)
    out = xv @ w + b
    np.testing.assert_allclose(fetches[0], np.sum(out * out), rtol=1e-5)
    np.testing.assert_allclose(fetches[1], xv.T @ (2 * out), rtol=1e-4)


def test_forward_only_fetch_still_runs_forward():
    """Last-writer-wins must not over-prune: with no grad op, the forward
    producer IS the live chain."""
    paddle.seed(0)
    main = static.Program()
    layer = nn.Linear(4, 4)
    with static.program_guard(main):
        x = static.data("x", [2, 4], "float32")
        y = layer(x)
    op_type = main.global_block().ops[-1].type
    counter = _count_op_traces(main, op_type)
    exe = static.Executor()
    xv = np.random.default_rng(1).standard_normal((2, 4)).astype(np.float32)
    with _trace_counting_flags():
        (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    assert counter["n"] == 1
    ref = xv @ np.asarray(layer.weight._value) + np.asarray(layer.bias._value)
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_read_then_rebind_keeps_prior_producer():
    """An op that READS a vid its successor re-binds must keep the original
    producer alive (the rebinding op consumes the old value)."""
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3], "float32")
        a = paddle.tanh(x)      # producer of a
        b = a + a               # reads a
    exe = static.Executor()
    xv = np.asarray([0.1, 0.2, 0.3], np.float32)
    (bv,) = exe.run(main, feed={"x": xv}, fetch_list=[b])
    np.testing.assert_allclose(bv, 2 * np.tanh(xv), rtol=1e-6)
