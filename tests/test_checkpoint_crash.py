"""Crash-consistency matrix: a subprocess trainer is hard-killed (SIGKILL,
via FLAGS_checkpoint_kill_point) at every injected point of the commit
protocol, and the parent asserts latest_step() always recovers the newest
VALID checkpoint — plus the full kill-and-resume run whose per-step losses
must match an uninterrupted run bit-for-bit (docs/CHECKPOINT.md)."""

import os
import signal
import subprocess
import sys

import pytest

from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.checkpoint.manager import KILL_POINTS

_TRAINER = r"""
import sys
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.io import DataLoader, Dataset, DistributedBatchSampler

ckpt_dir, loss_log, total, interval, kill_point, kill_at = sys.argv[1:7]
total, interval, kill_at = int(total), int(interval), int(kill_at)

class DS(Dataset):
    def __init__(self):
        self.data = np.random.RandomState(1).randn(8, 4).astype(np.float32)
    def __len__(self):
        return 8
    def __getitem__(self, i):
        return self.data[i]

paddle.seed(7)
m = nn.Linear(4, 4)
sched = opt.lr.CosineAnnealingDecay(learning_rate=0.1, T_max=20)
o = opt.Adam(learning_rate=sched, parameters=m.parameters())
ds = DS()
sampler = DistributedBatchSampler(ds, batch_size=2, shuffle=True, seed=11)
dl = DataLoader(ds, batch_sampler=sampler)

# sync save: the SIGKILL lands inside save() at a deterministic step, so the
# loss log is an exact prefix; the commit path is identical to async
mgr = CheckpointManager(ckpt_dir, save_interval_steps=interval, async_save=False)
start = mgr.restore(model=m, optimizer=o, lr_scheduler=sched, dataloader=dl) or 0

step = start
epoch = sampler.epoch
while step < total:
    sampler.set_epoch(epoch)
    for batch in dl:
        step += 1
        x = paddle.to_tensor(np.asarray(batch))
        noise = paddle.rand([1])  # per-step RNG draw: resume must match it
        loss = (m(x) ** 2).mean() * (1.0 + 0.01 * noise.mean())
        loss.backward()
        o.step()
        o.clear_grad()
        sched.step()
        with open(loss_log, "a") as f:
            f.write("%d %s\n" % (step, float(loss).hex()))
        if step == kill_at and kill_point:
            paddle.set_flags({"FLAGS_checkpoint_kill_point": kill_point})
        mgr.maybe_save(step, model=m, optimizer=o, lr_scheduler=sched, dataloader=dl)
        if step >= total:
            break
    epoch += 1
print("DONE", mgr.latest_step())
"""


def _run_trainer(tmp_path, ckpt_dir, log, total, interval, kill_point="", kill_at=0):
    script = tmp_path / "trainer.py"
    script.write_text(_TRAINER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""))
    return subprocess.run(
        [sys.executable, str(script), str(ckpt_dir), str(log),
         str(total), str(interval), kill_point, str(kill_at)],
        capture_output=True, text=True, timeout=180, env=env,
    )


def _read_log(path):
    out = {}
    for line in path.read_text().splitlines():
        step, hexval = line.split()
        out[int(step)] = hexval
    return out


@pytest.mark.parametrize("kill_point", KILL_POINTS)
def test_crash_matrix_recovers_newest_valid(tmp_path, kill_point):
    """SIGKILL mid-commit at each protocol point (after a clean save at step
    2, during the save at step 4): the prior checkpoint stays loadable and
    latest_step() lands on it; only a kill AFTER the atomic rename exposes
    step 4."""
    ckpt_dir = tmp_path / "ckpt"
    r = _run_trainer(tmp_path, ckpt_dir, tmp_path / "log", total=6, interval=2,
                     kill_point=kill_point, kill_at=4)
    assert r.returncode == -signal.SIGKILL, r.stderr[-2000:]

    mgr = CheckpointManager(str(ckpt_dir))
    expected = 4 if kill_point == "after-commit" else 2
    assert mgr.latest_step() == expected
    # the torn temp dir (if any) is invisible to the step listing
    assert expected in mgr.all_steps()


def test_kill_resume_bit_identical(tmp_path):
    """Uninterrupted 8 steps vs. SIGKILL right after the step-6 commit +
    auto-resume: per-step losses are BIT-identical (hex-compared), proving
    model, optimizer moments, LR schedule, RNG stream, and the mid-epoch
    sampler position all restored exactly."""
    log_a = tmp_path / "a.log"
    r = _run_trainer(tmp_path, tmp_path / "ckpt_a", log_a, total=8, interval=3)
    assert "DONE" in r.stdout, r.stderr[-2000:]

    ckpt_b = tmp_path / "ckpt_b"
    log_b = tmp_path / "b.log"
    r = _run_trainer(tmp_path, ckpt_b, log_b, total=8, interval=3,
                     kill_point="after-commit", kill_at=6)
    assert r.returncode == -signal.SIGKILL, r.stderr[-2000:]
    assert max(_read_log(log_b)) == 6

    r = _run_trainer(tmp_path, ckpt_b, log_b, total=8, interval=3)
    assert "DONE" in r.stdout, r.stderr[-2000:]
    assert _read_log(log_b) == _read_log(log_a)
