"""Inference Config/Predictor depth: precision variants, weight-only int8,
warn-or-work switches, warmup, profiling, clone.

Reference: paddle/fluid/inference/api/paddle_analysis_config.h:676
(Precision modes, EnableTensorRtEngine), analysis_predictor.h:100
(Clone, profiling); the variant model is the TRT build-per-precision
engine flow re-done for XLA (built at export, selected at load).
"""

import os
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import static
from paddle_tpu import inference


def _export_mlp(tmp_path, **save_kwargs):
    paddle.seed(11)
    l1, l2 = nn.Linear(64, 256), nn.Linear(256, 16)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 64], "float32")
        out = l2(paddle.tanh(l1(x)))
    prefix = str(tmp_path / "m" / "net")
    static.save_inference_model(prefix, [x], [out], static.Executor(),
                                program=main, **save_kwargs)
    xv = np.random.default_rng(0).standard_normal((4, 64)).astype(np.float32)
    ref = np.tanh(xv @ np.asarray(l1.weight._value) + np.asarray(l1.bias._value))
    ref = ref @ np.asarray(l2.weight._value) + np.asarray(l2.bias._value)
    return prefix, xv, ref


def test_weight_only_int8_export_serves_close_and_smaller(tmp_path):
    prefix, xv, ref = _export_mlp(tmp_path)
    fp32_size = os.path.getsize(prefix + ".pdmodel")

    prefix8, _, _ = _export_mlp(tmp_path / "q", precision="int8")
    int8_size = os.path.getsize(prefix8 + ".pdmodel")
    pred = inference.Predictor(prefix8)
    (ov,) = pred.run([xv])
    # per-channel int8 weight quantization: close, not bit-equal
    assert np.abs(ov - ref).max() < 0.05 * max(1.0, np.abs(ref).max())
    # int8 weights baked -> artifact visibly smaller than the fp32 one
    assert int8_size < fp32_size * 0.6, (int8_size, fp32_size)


def _dequant_oracle(W, bits):
    W32 = np.asarray(W, np.float32)
    amax = np.abs(W32).max(axis=0)
    qmax = 7.0 if bits == 4 else 127.0
    scale = np.where(amax > 0, amax / qmax, 1.0).astype(np.float32)
    q = np.clip(np.round(W32 / scale), -qmax - 1, qmax)
    return q * scale


def test_weight_only_int4_export_matches_dequant_oracle(tmp_path):
    paddle.seed(11)
    l1, l2 = nn.Linear(64, 256), nn.Linear(256, 16)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 64], "float32")
        out = l2(paddle.tanh(l1(x)))
    prefix = str(tmp_path / "m4" / "net")
    static.save_inference_model(prefix, [x], [out], static.Executor(),
                                program=main, precision="weight_only_int4")
    xv = np.random.default_rng(0).standard_normal((4, 64)).astype(np.float32)
    (ov,) = inference.Predictor(prefix).run([xv])
    # exact oracle: the served program must equal fake-quantized numpy math
    w1 = _dequant_oracle(l1.weight._value, 4)
    w2 = _dequant_oracle(l2.weight._value, 4)
    ref = np.tanh(xv @ w1 + np.asarray(l1.bias._value)) @ w2 + np.asarray(
        l2.bias._value)
    np.testing.assert_allclose(ov, ref, atol=2e-5, rtol=1e-4)


def test_precision_variant_selected_at_load(tmp_path):
    prefix, xv, ref = _export_mlp(
        tmp_path, extra_precisions=["bfloat16", "weight_only_int8"])
    assert os.path.exists(prefix + ".bfloat16.pdmodel")

    cfg = inference.Config(prefix)
    cfg.set_precision(inference.PrecisionType.Bfloat16)
    (ov,) = inference.create_predictor(cfg).run([xv])
    np.testing.assert_allclose(ov, ref, atol=0.1, rtol=0.1)  # bf16 tolerance

    cfg8 = inference.Config(prefix)
    cfg8.set_precision("int8")
    (ov8,) = inference.create_predictor(cfg8).run([xv])
    assert np.abs(ov8 - ref).max() < 0.05 * max(1.0, np.abs(ref).max())


def test_missing_int8_variant_raises_listing_available(tmp_path):
    prefix, _, _ = _export_mlp(tmp_path)
    cfg = inference.Config(prefix)
    cfg.set_precision("int8")
    with pytest.raises(RuntimeError, match="float32"):
        inference.create_predictor(cfg)


def test_bf16_without_variant_warns_and_serves_fp32(tmp_path):
    prefix, xv, ref = _export_mlp(tmp_path)
    cfg = inference.Config(prefix)
    cfg.set_precision("bf16")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        pred = inference.create_predictor(cfg)
    assert any("no such variant" in str(x.message) for x in w)
    (ov,) = pred.run([xv])
    np.testing.assert_allclose(ov, ref, atol=1e-5)


def test_config_switches_work_or_warn(tmp_path):
    cfg = inference.Config()
    for call in (
        lambda: cfg.enable_memory_optim(),
        lambda: cfg.switch_ir_optim(False),
        lambda: cfg.enable_mkldnn(),
        lambda: cfg.set_cpu_math_library_num_threads(4),
        lambda: cfg.enable_tensorrt_engine(precision="float16"),
        lambda: cfg.enable_use_gpu(memory_pool_init_size_mb=512),
    ):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            call()
        assert w, f"{call} silently did nothing"
    # the TRT precision request DID carry over
    assert cfg._precision == "float16"
    # working switches do their thing quietly
    cfg.set_optim_cache_dir("/tmp/jax_cache")
    cfg.disable_glog_info()
    with pytest.raises(ValueError):
        cfg.set_precision("int3")


def test_warmup_profile_and_clone(tmp_path):
    prefix, xv, ref = _export_mlp(tmp_path)
    cfg = inference.Config(prefix)
    cfg.enable_warmup()
    cfg.enable_profile()
    pred = inference.create_predictor(cfg)  # warmup ran inside
    (ov,) = pred.run([xv])
    np.testing.assert_allclose(ov, ref, atol=1e-5)
    stats = pred.profile_stats()
    assert stats["count"] == 1 and stats["last_ms"] > 0.0

    twin = pred.clone()
    h = twin.get_input_handle("x")
    h.copy_from_cpu(xv)
    (tv,) = twin.run()
    np.testing.assert_allclose(tv, ov, atol=1e-6)
    # bindings are separate, weights shared
    assert twin._inputs is not pred._inputs
    assert twin._exported is pred._exported
    assert twin.profile_stats()["count"] == 1  # its own counters


def test_llama_int8_predictor_path(tmp_path):
    """The quantized-LLM serving path end-to-end (VERDICT r4 item 4):
    jit.save tiny-LLaMA logits with weight-only int8 -> Predictor serves
    them close to the fp32 eager forward, from a visibly smaller artifact."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    import paddle_tpu.jit as jit

    paddle.seed(0)
    m = LlamaForCausalLM(llama_tiny(dtype="float32"))
    m.eval()
    ids = np.random.default_rng(1).integers(1, 250, (1, 12)).astype(np.int32)
    with paddle.no_grad():
        out = m(paddle.to_tensor(ids))
        ref = np.asarray((out[0] if isinstance(out, (tuple, list)) else out)._value)

    path = str(tmp_path / "llama_fp32")
    jit.save(m, path, input_spec=[static.InputSpec([1, 12], "int32", "ids")])
    path8 = str(tmp_path / "llama_int8")
    jit.save(m, path8, input_spec=[static.InputSpec([1, 12], "int32", "ids")],
             precision="int8")
    assert os.path.getsize(path8 + ".pdmodel") < os.path.getsize(path + ".pdmodel") * 0.6

    pred = inference.Predictor(path8)
    (logits,) = pred.run([ids])
    if logits.ndim == ref.ndim + 1 and logits.shape[0] == 1 and ref.shape[0] != 1:
        logits = logits[0]
    # int8 weight-only: argmax (the decoded tokens) should agree almost
    # everywhere and values stay close
    agree = (logits.argmax(-1) == ref.argmax(-1)).mean()
    assert agree >= 0.9, agree
    assert np.abs(logits - ref).max() < 0.25 * max(1.0, np.abs(ref).max())


def test_int8_export_bakes_trained_scope_weights(tmp_path):
    """Executor training persists params to the SCOPE (param_inits keeps the
    init); the quant pass must bake the trained values, not the inits."""
    import jax.numpy as jnp
    from paddle_tpu.static.executor import global_scope

    paddle.seed(2)
    l = nn.Linear(16, 8)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 16], "float32")
        out = l(x)
    exe = static.Executor()
    xv = np.random.default_rng(4).standard_normal((2, 16)).astype(np.float32)
    exe.run(main, feed={"x": xv}, fetch_list=[out])  # materialize scope state
    scope = global_scope()
    wvid = next(v for v in main.param_inits
                if tuple(np.shape(main.param_inits[v])) == (16, 8))
    trained = np.full((16, 8), 0.5, np.float32)  # quantizes EXACTLY (q=127)
    scope.set_var(wvid, jnp.asarray(trained))

    prefix = str(tmp_path / "net")
    static.save_inference_model(prefix, [x], [out], exe, program=main,
                                precision="int8")
    (ov,) = inference.Predictor(prefix).run([xv])
    ref = xv @ trained + np.asarray(l.bias._value)
    np.testing.assert_allclose(ov, ref, atol=1e-5)


def test_precision_alias_matches_export_at_load(tmp_path):
    """'int8' at export and 'int8' at load must meet in one canonical name
    (the manifest stores weight_only_int8)."""
    prefix, xv, _ = _export_mlp(tmp_path, precision="int8")
    import json as _json

    with open(prefix + ".json") as f:
        assert _json.load(f)["precision"] == "weight_only_int8"
    cfg = inference.Config(prefix)
    cfg.set_precision("int8")  # alias -> canonical -> matches main artifact
    pred = inference.create_predictor(cfg)
    pred.run([xv])
