"""Tests for geometric, audio, text (viterbi), quantization (reference
models: test/legacy_test/test_graph_send_recv_op.py, test_segment_ops.py,
test/legacy_test/test_audio_functions.py, test_viterbi_decode_op.py,
test/quantization/)."""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import audio, geometric, quantization, text


def npv(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


class TestGeometric:
    DATA = np.array([[1, 2, 3], [3, 2, 1], [4, 5, 6]], np.float32)
    IDS = np.array([0, 0, 1])

    def test_segment_ops(self):
        np.testing.assert_allclose(npv(geometric.segment_sum(self.DATA, self.IDS)), [[4, 4, 4], [4, 5, 6]])
        np.testing.assert_allclose(npv(geometric.segment_mean(self.DATA, self.IDS)), [[2, 2, 2], [4, 5, 6]])
        np.testing.assert_allclose(npv(geometric.segment_min(self.DATA, self.IDS)), [[1, 2, 1], [4, 5, 6]])
        np.testing.assert_allclose(npv(geometric.segment_max(self.DATA, self.IDS)), [[3, 2, 3], [4, 5, 6]])

    def test_segment_empty_segment_fills_zero(self):
        data = np.array([[1.0, 2.0]], np.float32)
        ids = np.array([2])
        out = npv(geometric.segment_max(data, ids))
        np.testing.assert_allclose(out, [[0, 0], [0, 0], [1, 2]])

    def test_send_u_recv(self):
        x = np.array([[0, 2, 3], [1, 4, 5], [2, 6, 7]], np.float32)
        src = np.array([0, 1, 2, 0])
        dst = np.array([1, 2, 1, 0])
        out = npv(geometric.send_u_recv(x, src, dst, "sum"))
        expected = np.zeros((3, 3), np.float32)
        for s, d in zip(src, dst):
            expected[d] += x[s]
        np.testing.assert_allclose(out, expected)

    def test_send_u_recv_mean_max(self):
        x = np.array([[1.0], [3.0], [5.0]], np.float32)
        src = np.array([0, 1])
        dst = np.array([2, 2])
        np.testing.assert_allclose(npv(geometric.send_u_recv(x, src, dst, "mean")), [[0], [0], [2]])
        np.testing.assert_allclose(npv(geometric.send_u_recv(x, src, dst, "max")), [[0], [0], [3]])

    def test_send_ue_recv(self):
        x = np.array([[1.0, 1.0], [2.0, 2.0]], np.float32)
        e = np.array([[0.5, 0.5], [1.0, 1.0]], np.float32)
        src = np.array([0, 1])
        dst = np.array([1, 0])
        out = npv(geometric.send_ue_recv(x, e, src, dst, "mul", "sum"))
        np.testing.assert_allclose(out, [[2.0, 2.0], [0.5, 0.5]])

    def test_send_uv(self):
        x = np.array([[1.0], [2.0], [3.0]], np.float32)
        y = np.array([[10.0], [20.0], [30.0]], np.float32)
        src = np.array([0, 2])
        dst = np.array([1, 0])
        out = npv(geometric.send_uv(x, y, src, dst, "add"))
        np.testing.assert_allclose(out, [[21.0], [13.0]])

    def test_reindex_graph(self):
        x = np.array([0, 5, 9])
        neighbors = np.array([5, 9, 7, 0, 7])
        count = np.array([2, 2, 1])
        src, dst, nodes = geometric.reindex_graph(x, neighbors, count)
        np.testing.assert_array_equal(npv(nodes), [0, 5, 9, 7])
        np.testing.assert_array_equal(npv(src), [1, 2, 3, 0, 3])
        np.testing.assert_array_equal(npv(dst), [0, 0, 1, 1, 2])

    def test_sample_neighbors(self):
        # CSC: node 0 has nbrs [1,2,3], node 1 has [0], node 2 has []
        row = np.array([1, 2, 3, 0])
        colptr = np.array([0, 3, 4, 4])
        nbrs, cnt = geometric.sample_neighbors(row, colptr, np.array([0, 1, 2]), sample_size=2)
        c = npv(cnt)
        assert c[0] == 2 and c[1] == 1 and c[2] == 0
        assert set(npv(nbrs)[:2]).issubset({1, 2, 3})

    def test_weighted_sample_neighbors(self):
        row = np.array([1, 2, 3])
        colptr = np.array([0, 3])
        w = np.array([0.1, 0.1, 10.0], np.float32)
        nbrs, cnt = geometric.weighted_sample_neighbors(row, colptr, w, np.array([0]), sample_size=1)
        assert npv(cnt)[0] == 1


class TestAudioFunctional:
    def test_mel_hz_roundtrip(self):
        freqs = np.array([100.0, 440.0, 1000.0, 4000.0], np.float32)
        mel = audio.functional.hz_to_mel(paddle.to_tensor(freqs))
        back = audio.functional.mel_to_hz(mel)
        np.testing.assert_allclose(npv(back), freqs, rtol=1e-3)
        # htk scale known value: 1000 Hz ≈ 999.99 mel? (2595*log10(1+1000/700))
        m = audio.functional.hz_to_mel(1000.0, htk=True)
        np.testing.assert_allclose(m, 2595 * np.log10(1 + 1000 / 700), rtol=1e-5)

    def test_fbank_matches_librosa_formula(self):
        fb = npv(audio.functional.compute_fbank_matrix(16000, 512, n_mels=40))
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        # each filter has unit-ish area under slaney norm; just check nonzero rows
        assert (fb.sum(1) > 0).all()

    def test_window_functions(self):
        import scipy.signal as ss

        for name in ["hann", "hamming", "blackman", "bartlett", "nuttall", "cosine"]:
            w = npv(audio.functional.get_window(name, 32))
            ref = ss.get_window(name, 32, fftbins=True)
            np.testing.assert_allclose(w, ref, rtol=1e-4, atol=1e-5)
        w = npv(audio.functional.get_window(("kaiser", 12.0), 32))
        ref = ss.get_window(("kaiser", 12.0), 32)
        np.testing.assert_allclose(w, ref, rtol=1e-4, atol=1e-5)
        w = npv(audio.functional.get_window(("gaussian", 7), 32))
        ref = ss.get_window(("gaussian", 7), 32)
        np.testing.assert_allclose(w, ref, rtol=1e-4, atol=1e-5)

    def test_power_to_db(self):
        x = np.array([1.0, 10.0, 100.0], np.float32)
        db = npv(audio.functional.power_to_db(paddle.to_tensor(x), top_db=None))
        np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-4)

    def test_create_dct_ortho(self):
        import scipy.fft as sfft

        d = npv(audio.functional.create_dct(13, 40))
        # columns should match scipy dct-II ortho basis
        eye = np.eye(40)
        ref = sfft.dct(eye, type=2, norm="ortho")[:, :13]
        np.testing.assert_allclose(d, ref, rtol=1e-4, atol=1e-5)


class TestAudioFeatures:
    def test_melspectrogram_pipeline(self):
        sig = np.sin(2 * np.pi * 440 * np.arange(8000) / 8000).astype(np.float32)
        mel = audio.features.MelSpectrogram(sr=8000, n_fft=256, hop_length=64, n_mels=32, f_min=0.0)
        out = npv(mel(paddle.to_tensor(sig[None])))
        assert out.shape[0] == 1 and out.shape[1] == 32
        assert np.isfinite(out).all() and out.max() > 0

    def test_mfcc_shape(self):
        sig = np.random.default_rng(0).normal(size=4000).astype(np.float32)
        mfcc = audio.features.MFCC(sr=8000, n_mfcc=13, n_fft=256, hop_length=128, n_mels=32, f_min=0.0)
        out = npv(mfcc(paddle.to_tensor(sig[None])))
        assert out.shape[0] == 1 and out.shape[1] == 13
        assert np.isfinite(out).all()

    def test_wav_save_load_roundtrip(self):
        sig = (0.5 * np.sin(2 * np.pi * 220 * np.arange(1600) / 8000)).astype(np.float32)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "t.wav")
            audio.save(p, paddle.to_tensor(sig[None]), 8000)
            back, sr = audio.load(p)
            assert sr == 8000
            np.testing.assert_allclose(npv(back)[0], sig, atol=1e-3)
            meta = audio.backends.info(p)
            assert meta.sample_rate == 8000 and meta.num_channels == 1


class TestViterbi:
    def _brute_force(self, pot, trans, length, include_bos_eos):
        import itertools

        n = pot.shape[-1]
        best, best_path = -np.inf, None
        for path in itertools.product(range(n), repeat=length):
            s = 0.0
            if include_bos_eos:
                s += trans[n - 1, path[0]]
            s += pot[0, path[0]]
            for i in range(1, length):
                s += trans[path[i - 1], path[i]] + pot[i, path[i]]
            if include_bos_eos:
                s += trans[path[-1], n - 2]
            if s > best:
                best, best_path = s, path
        return best, list(best_path)

    @pytest.mark.parametrize("include", [True, False])
    def test_matches_brute_force(self, include):
        rng = np.random.default_rng(3)
        b, t, n = 2, 5, 4
        pot = rng.normal(size=(b, t, n)).astype(np.float32)
        trans = rng.normal(size=(n, n)).astype(np.float32)
        lens = np.array([5, 3], np.int64)
        scores, paths = text.viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans), paddle.to_tensor(lens), include
        )
        for i in range(b):
            ref_s, ref_p = self._brute_force(pot[i], trans, int(lens[i]), include)
            np.testing.assert_allclose(npv(scores)[i], ref_s, rtol=1e-4)
            assert list(npv(paths)[i][: lens[i]]) == ref_p

    def test_decoder_layer(self):
        rng = np.random.default_rng(4)
        trans = paddle.to_tensor(rng.normal(size=(3, 3)).astype(np.float32))
        dec = text.ViterbiDecoder(trans, include_bos_eos_tag=False)
        pot = paddle.to_tensor(rng.normal(size=(1, 4, 3)).astype(np.float32))
        scores, paths = dec(pot, paddle.to_tensor(np.array([4], np.int64)))
        assert npv(paths).shape == (1, 4)

    def test_dataset_requires_local_file(self):
        with pytest.raises(RuntimeError, match="local copy"):
            text.UCIHousing(data_file=None)

    def test_uci_housing_parsing(self):
        rng = np.random.default_rng(5)
        raw = rng.normal(size=(50, 14))
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "housing.data")
            np.savetxt(p, raw)
            ds = text.UCIHousing(data_file=p, mode="train")
            assert len(ds) == 40
            x, y = ds[0]
            assert x.shape == (13,) and y.shape == (1,)


class TestQuantization:
    def test_fake_quant_levels(self):
        import jax.numpy as jnp

        from paddle_tpu.quantization import _fake_quant

        x = jnp.linspace(-2, 2, 101)
        out = np.asarray(_fake_quant(x, jnp.asarray(1.0), 127.0))
        # values clamp to [-scale*(128/127), scale] and lie on the grid
        assert out.max() <= 1.0 + 1e-6
        grid = out * 127
        np.testing.assert_allclose(grid, np.round(grid), atol=1e-4)

    def test_straight_through_gradient(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.quantization import _fake_quant

        g = jax.grad(lambda x: jnp.sum(_fake_quant(x, jnp.asarray(1.0), 127.0)))(
            jnp.array([-2.0, -0.5, 0.5, 2.0])
        )
        np.testing.assert_allclose(np.asarray(g), [0, 1, 1, 0])

    def test_qat_quantize_and_train(self):
        import paddle_tpu.optimizer as opt
        from paddle_tpu.quantization import (
            QAT,
            FakeQuanterWithAbsMaxObserver,
            QuantConfig,
            quanter,
        )

        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(8, 16)
                self.fc2 = nn.Linear(16, 1)

            def forward(self, x):
                return self.fc2(paddle.tanh(self.fc1(x)))

        model = Net()
        q = quanter(FakeQuanterWithAbsMaxObserver, moving_rate=0.9, quant_bits=8)
        cfg = QuantConfig(activation=q, weight=q)
        qat = QAT(cfg)
        qmodel = qat.quantize(model, inplace=False)
        # quantable layers got wrapped
        from paddle_tpu.quantization import _QuantedWrapper

        assert isinstance(qmodel._sub_layers["fc1"], _QuantedWrapper)

        optimizer = opt.Adam(1e-2, parameters=qmodel.parameters())
        x = paddle.randn([32, 8])
        y = paddle.randn([32, 1])
        losses = []
        for _ in range(25):
            loss = paddle.mean((qmodel(x) - y) ** 2)
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        # observer collected a scale
        s = float(npv(qmodel._sub_layers["fc1"].activation_quanter.scales()))
        assert s > 0.1

    def test_ptq_calibration(self):
        from paddle_tpu.quantization import PTQ, AbsMaxObserver, QuantConfig, quanter

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return self.fc(x)

        model = Net()
        cfg = QuantConfig(activation=quanter(AbsMaxObserver), weight=None)
        ptq = PTQ(cfg)
        qmodel = ptq.quantize(model)
        for _ in range(3):
            qmodel(paddle.randn([8, 4]))
        qmodel = ptq.convert(qmodel)
        obs = qmodel._sub_layers["fc"].activation_quanter
        assert float(npv(obs.scales())) > 0
