"""Profiler + memory stats (reference python/paddle/profiler/)."""

import json
import os

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.profiler as profiler


def test_profiler_records_ops_and_exports(tmp_path):
    p = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU])
    with p:
        x = paddle.ones([8, 8])
        for _ in range(3):
            x = paddle.matmul(x, x) * 0.5
        with profiler.RecordEvent("my_region"):
            _ = paddle.sum(x)
    spans = p._buffer.spans
    names = {s.name for s in spans}
    assert "op::matmul" in names and "my_region" in names

    path = str(tmp_path / "trace.json")
    p.export_chrome_tracing(path)
    data = json.load(open(path))
    assert len(data["traceEvents"]) >= 4
    table = p.summary()
    assert "matmul" in table  # op:: namespace stripped in the Operator table


def test_scheduler_states():
    sch = profiler.make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sch(i) for i in range(4)]
    assert states[0] == profiler.ProfilerState.CLOSED
    assert states[1] == profiler.ProfilerState.READY
    assert states[2] == profiler.ProfilerState.RECORD
    assert states[3] == profiler.ProfilerState.RECORD_AND_RETURN


def test_profiler_inactive_has_no_overhead_hook():
    x = paddle.ones([4])
    y = paddle.exp(x)  # no profiler active: no spans recorded anywhere
    assert profiler._active_profiler is None


def test_memory_stats():
    import paddle_tpu.device as device

    x = paddle.ones([1024, 1024])
    allocated = device.memory_allocated()
    assert allocated > 0
    assert device.max_memory_allocated() >= 0


def test_op_cost_model_profile_and_roofline(tmp_path):
    """Cost model (reference python/paddle/cost_model/ +
    static_op_benchmark.json): profiled table + roofline estimates."""
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.cost_model import OpCostModel, device_peaks

    m = OpCostModel()
    x = jnp.ones((128, 128), jnp.float32)
    dt = m.measure("matmul_128", lambda a: a @ a, x, iters=3, warmup=1)
    assert dt > 0 and m.query("matmul_128") == dt
    # roofline: compute- vs bandwidth-bound regimes ordered sensibly
    t_small = m.flops_time(1e6, 1e4)
    t_big = m.flops_time(1e12, 1e9)
    assert t_big > t_small > 0
    peaks = device_peaks()
    assert peaks[0] > 0 and peaks[1] > 0
    p = tmp_path / "op_table.json"
    m.save(str(p))
    m2 = OpCostModel.load(str(p))
    assert m2.query("matmul_128") == dt
    assert m2.query("missing", default=1.0) == 1.0


def test_cost_analysis_and_mfu_report():
    """XLA-compiler-sourced cost table + MFU report (the reference profiles
    per-op costs into static_op_benchmark.json; here the compiler reports
    them directly)."""
    import jax.numpy as jnp

    import paddle_tpu.profiler as prof

    def f(a, b):
        return (a @ b).sum()

    a = np.random.default_rng(0).normal(size=(256, 256)).astype(np.float32)
    b = np.random.default_rng(1).normal(size=(256, 256)).astype(np.float32)
    ca = prof.cost_analysis(f, jnp.asarray(a), jnp.asarray(b))
    assert ca.get("flops", 0) >= 2 * 256**3 * 0.9  # matmul dominates

    rep = prof.estimate_mfu(f, jnp.asarray(a), jnp.asarray(b))
    assert rep["flops"] >= 2 * 256**3 * 0.9
    assert rep["runtime_s"] > 0
    assert rep["mfu"] == 0.0  # CPU: no peak


def test_summary_statistics_tables_over_real_train_step():
    """VERDICT r3 #8 (reference profiler_statistic.py): per-op aggregated
    tables — Overview with category ratios + Operator table with
    Calls/Total/Avg/Max/Min/Ratio — over a real train step, sortable."""
    import re

    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu import profiler

    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    o = opt.SGD(0.1, parameters=m.parameters())
    x = paddle.randn([4, 8]); y = paddle.randn([4, 1])

    p = profiler.Profiler()
    p.start()
    for _ in range(3):
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        p.step()
    p.stop()

    table = p.summary(time_unit="us")
    assert "Overview Summary" in table and "Operator Summary" in table
    # per-op rows have all six stat columns
    assert re.search(r"Calls\s+Total\(us\)\s+Avg\(us\)\s+Max\(us\)\s+Min\(us\)\s+Ratio", table)
    assert "linear" in table  # the Linear op rows, op:: prefix stripped
    # ratios are percentages
    ratios = [float(v) for v in re.findall(r"(\d+\.\d\d)\n", table)]
    assert ratios and all(0.0 <= r <= 100.0 for r in ratios)

    # sorted_by respects SortedKeys: CPUMin ascending vs CPUTotal descending
    t_total = p.summary(sorted_by=profiler.SortedKeys.CPUTotal)
    t_min = p.summary(sorted_by=profiler.SortedKeys.CPUMin)
    assert t_total != t_min or "linear" not in t_total

    # views filter
    t_ops = p.summary(views=["Operator"])
    assert "Operator Summary" in t_ops and "UserDefined Summary" not in t_ops

    # invalid unit is loud
    import pytest as _pytest

    with _pytest.raises(ValueError):
        p.summary(time_unit="h")
