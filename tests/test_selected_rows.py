"""SelectedRows row-sparse embedding gradients (VERDICT r2 item 8).

Reference: paddle/phi/core/selected_rows.h; the lookup_table sparse-grad
branch and Adam lazy_mode row updates (phi/kernels/funcs/adam_functors.h).
"""

import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework.selected_rows import SelectedRows


def test_selected_rows_coalesce_and_dense():
    sr = SelectedRows(np.int64([2, 0, 2]), np.float32([[1, 1], [2, 2], [3, 3]]), height=4)
    assert sr.shape == (4, 2)
    co = sr.coalesce()
    assert sorted(np.asarray(co.rows).tolist()) == [0, 2]
    d = np.asarray(sr.to_dense())
    np.testing.assert_allclose(d[2], [4.0, 4.0])
    np.testing.assert_allclose(d[0], [2.0, 2.0])
    np.testing.assert_allclose(d[1], 0.0)
    np.testing.assert_allclose(np.asarray(co.to_dense()), d)


def test_sparse_embedding_grad_is_selected_rows_and_matches_dense():
    paddle.seed(0)
    V, H = 64, 8
    ids = paddle.to_tensor(np.int64([[1, 5, 1], [9, 5, 3]]))

    def run(sparse):
        paddle.seed(0)
        emb = nn.Embedding(V, H, sparse=sparse)
        out = emb(ids)
        (out * out).sum().backward()
        return emb

    dense_emb = run(False)
    sparse_emb = run(True)
    assert isinstance(sparse_emb.weight.grad, SelectedRows)
    np.testing.assert_allclose(
        np.asarray(sparse_emb.weight.grad.to_dense()),
        np.asarray(dense_emb.weight.grad._value),
        rtol=1e-5, atol=1e-6,
    )


@pytest.mark.parametrize("opt_name", ["SGD", "Momentum", "Adam", "AdamW"])
def test_sparse_update_matches_dense_update(opt_name):
    """One optimizer step from identical states: the lazy row update must
    reproduce the dense update on TOUCHED rows and (for SGD/Momentum with
    zero grads elsewhere) leave untouched rows unchanged."""
    paddle.seed(3)
    V, H = 32, 4
    ids = paddle.to_tensor(np.int64([[0, 3, 3, 7]]))

    def run(sparse):
        paddle.seed(3)
        emb = nn.Embedding(V, H, sparse=sparse)
        kwargs = dict(learning_rate=0.1, parameters=emb.parameters())
        opt = getattr(paddle.optimizer, opt_name)(**kwargs)
        init = np.asarray(emb.weight._value).copy()
        out = emb(ids)
        (out * 2.0).sum().backward()
        opt.step()
        return init, np.asarray(emb.weight._value)

    init, w_dense = run(False)
    _, w_sparse = run(True)
    touched = [0, 3, 7]
    np.testing.assert_allclose(w_sparse[touched], w_dense[touched], rtol=1e-5, atol=1e-6)
    untouched = [i for i in range(V) if i not in touched]
    # lazy semantics (reference lazy_mode): untouched rows NEVER move under
    # the sparse update — including AdamW, whose dense path decays every row
    np.testing.assert_allclose(w_sparse[untouched], init[untouched], rtol=1e-6, atol=1e-7)
    if opt_name != "AdamW":
        np.testing.assert_allclose(w_sparse[untouched], w_dense[untouched], rtol=1e-5, atol=1e-6)


def test_sparse_embedding_padding_idx_rows_get_no_grad():
    V, H = 16, 4
    emb = nn.Embedding(V, H, padding_idx=0, sparse=True)
    ids = paddle.to_tensor(np.int64([[0, 2, 0, 5]]))
    out = emb(ids)
    out.sum().backward()
    g = emb.weight.grad
    assert isinstance(g, SelectedRows)
    dense = np.asarray(g.to_dense())
    np.testing.assert_allclose(dense[0], 0.0)
    assert np.abs(dense[2]).sum() > 0


def test_grad_accumulation_across_backwards():
    V, H = 16, 4
    emb = nn.Embedding(V, H, sparse=True)
    ids = paddle.to_tensor(np.int64([[1, 2]]))
    emb(ids).sum().backward()
    emb(ids).sum().backward()  # second backward accumulates
    g = emb.weight.grad
    assert isinstance(g, SelectedRows)
    dense = np.asarray(g.to_dense())
    np.testing.assert_allclose(dense[1], 2.0)  # d(sum)/dw = 1 per lookup, twice


@pytest.mark.slow
def test_sparse_update_faster_than_dense_on_large_vocab():
    """The point of SelectedRows: on a 200k-vocab embedding with a small
    batch, backward+update must beat the dense path (which materializes and
    scans the full [V, H] gradient)."""
    V, H, B = 200_000, 64, 256
    ids_np = np.random.default_rng(0).integers(0, V, (B,)).astype(np.int64)

    def timed(sparse, iters=5):
        paddle.seed(0)
        emb = nn.Embedding(V, H, sparse=sparse)
        opt = paddle.optimizer.SGD(0.1, parameters=emb.parameters())
        ids = paddle.to_tensor(ids_np)

        def one():
            out = emb(ids)
            out.sum().backward()
            opt.step()
            opt.clear_grad()

        one()  # warmup / compile
        t0 = time.perf_counter()
        for _ in range(iters):
            one()
        import jax

        jax.block_until_ready(emb.weight._value)
        return (time.perf_counter() - t0) / iters

    dense_t = timed(False)
    sparse_t = timed(True)
    assert sparse_t < dense_t, (sparse_t, dense_t)


def test_mixed_sparse_and_dense_weight_use():
    """A dense read of the sparse-embedding weight in the same graph (tied
    head / weight regularizer) must accumulate with the SelectedRows grad,
    not crash."""
    V, H = 16, 4
    emb = nn.Embedding(V, H, sparse=True)
    ids = paddle.to_tensor(np.int64([[1, 2]]))
    loss = emb(ids).sum() + (emb.weight * emb.weight).sum()
    loss.backward()
    g = emb.weight.grad
    assert hasattr(g, "_value")  # densified by the mixed accumulation
    dense = np.asarray(g._value)
    w = np.asarray(emb.weight._value)
    np.testing.assert_allclose(dense[1], 1.0 + 2 * w[1], rtol=1e-5)
    np.testing.assert_allclose(dense[5], 2 * w[5], rtol=1e-5)
