"""Comm watchdog + cross-rank static checks (reference comm_task.h:127
CommTask/IsTimeout, comm_task_manager.h:37, static_check.cc)."""

import io
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.communication import watchdog as wd


def test_watchdog_reports_hung_task(capsys):
    """A deliberately hung comm task is detected and reported."""
    mgr = wd.CommTaskManager.instance()
    mgr._interval = 0.05
    task = wd.CommTask("fake_all_reduce", "ranks=[0,1]", timeout=0.1)
    mgr.register(task)
    try:
        deadline = time.time() + 5
        while not task.reported and time.time() < deadline:
            time.sleep(0.05)
        assert task.reported, "watchdog never flagged the hung task"
        err = capsys.readouterr().err
        assert "fake_all_reduce" in err and "blocked" in err
    finally:
        mgr.complete(task)


def test_watchdog_quiet_on_completed_task(capsys):
    with wd.comm_watch("quick_barrier", timeout=0.2):
        pass
    time.sleep(0.3)
    assert "quick_barrier" not in capsys.readouterr().err


class _FakeStore:
    def __init__(self):
        self.kv = {}

    def set(self, k, v):
        self.kv[k] = v

    def get(self, k, timeout_ms=0):
        if k not in self.kv:
            raise TimeoutError(k)
        return self.kv[k]


def test_static_check_catches_cross_rank_mismatch(monkeypatch):
    import paddle_tpu._core.flags as flags

    store = _FakeStore()
    wd.set_rendezvous_store(store)
    flags.set_flags({"FLAGS_check_collective_shapes": True})
    try:
        t_rank0 = paddle.to_tensor(np.zeros((4, 4), np.float32))
        t_rank1 = paddle.to_tensor(np.zeros((2, 8), np.float32))
        # simulate rank 1 publishing first (same seq counter on both "ranks")
        seq = wd._check_seq.get(("all_reduce", "world"), 0) + 1
        store.set(f"ccheck/world/all_reduce/{seq}/1", b"(2, 8)|float32")
        with pytest.raises(RuntimeError, match="cross-rank mismatch"):
            wd.static_check("all_reduce", t_rank0, rank=0, world=2, timeout=1)
        # matching shapes pass
        seq = wd._check_seq.get(("all_reduce", "world"), 0) + 1
        store.set(f"ccheck/world/all_reduce/{seq}/1", b"(4, 4)|float32")
        wd.static_check("all_reduce", t_rank0, rank=0, world=2, timeout=1)
    finally:
        flags.set_flags({"FLAGS_check_collective_shapes": False})
        wd.set_rendezvous_store(None)


def test_static_check_disabled_is_noop():
    wd.set_rendezvous_store(None)
    t = paddle.to_tensor(np.zeros(3, np.float32))
    wd.static_check("all_reduce", t, rank=0, world=2)  # must not raise
