"""Every listed public tensor op either traces under jax.jit or raises the
documented DynamicShapeError (VERDICT round-1 weak #4: numpy-backed ops broke
silently under jit).  Reference analog: OpTest's dygraph/static consistency
checks (test/legacy_test/op_test.py:417)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.tensor._ops_common import DynamicShapeError

F32 = lambda *s: np.random.default_rng(0).standard_normal(s).astype(np.float32)
POS = lambda *s: (np.abs(F32(*s)) + 0.1).astype(np.float32)
I32 = lambda *s: np.random.default_rng(1).integers(0, 4, s).astype(np.int32)
BOOL = lambda *s: np.random.default_rng(2).integers(0, 2, s).astype(bool)

# (name, lambda over Tensors, tuple of raw inputs)
TRACEABLE = [
    ("abs", lambda x: paddle.abs(x), (F32(3, 4),)),
    ("add", lambda x, y: paddle.add(x, y), (F32(3, 4), F32(3, 4))),
    ("addmm", lambda a, b, c: paddle.addmm(a, b, c), (F32(3, 3), F32(3, 3), F32(3, 3))),
    ("allclose", lambda x, y: paddle.allclose(x, y), (F32(3), F32(3))),
    ("argmax", lambda x: paddle.argmax(x, axis=1), (F32(3, 4),)),
    ("argsort", lambda x: paddle.argsort(x, axis=-1), (F32(3, 4),)),
    ("as_strided", lambda x: paddle.as_strided(x, [2, 3], [1, 2]), (F32(12),)),
    ("broadcast_to", lambda x: paddle.broadcast_to(x, [3, 4]), (F32(1, 4),)),
    ("cast", lambda x: paddle.cast(x, "bfloat16"), (F32(3, 4),)),
    ("chunk", lambda x: paddle.chunk(x, 2, axis=1)[0], (F32(3, 4),)),
    ("clip", lambda x: paddle.clip(x, -1, 1), (F32(3, 4),)),
    ("concat", lambda x, y: paddle.concat([x, y], axis=0), (F32(2, 3), F32(2, 3))),
    ("combinations", lambda x: paddle.combinations(x, 2), (F32(4),)),
    ("cumprod", lambda x: paddle.cumprod(x, dim=1), (F32(3, 4),)),
    ("cumsum", lambda x: paddle.cumsum(x, axis=1), (F32(3, 4),)),
    ("diag", lambda x: paddle.diag(x), (F32(4),)),
    ("diff", lambda x: paddle.diff(x), (F32(5),)),
    ("dist", lambda x, y: paddle.dist(x, y, p=2), (F32(3), F32(3))),
    ("dot", lambda x, y: paddle.dot(x, y), (F32(4), F32(4))),
    ("einsum", lambda x, y: paddle.einsum("ij,jk->ik", x, y), (F32(2, 3), F32(3, 2))),
    ("erf", lambda x: paddle.erf(x), (F32(3),)),
    ("exp", lambda x: paddle.exp(x), (F32(3),)),
    ("flatten", lambda x: paddle.flatten(x), (F32(2, 3),)),
    ("flip", lambda x: paddle.flip(x, axis=0), (F32(3, 2),)),
    ("full_like", lambda x: paddle.full_like(x, 7.0), (F32(3),)),
    ("gather", lambda x, i: paddle.gather(x, i), (F32(4, 2), I32(3))),
    ("gather_nd", lambda x, i: paddle.gather_nd(x, i), (F32(4, 2), I32(3, 1))),
    ("histogramdd", lambda x: paddle.histogramdd(x, bins=4, ranges=[(-3, 3), (-3, 3)])[0], (F32(10, 2),)),
    ("index_select", lambda x, i: paddle.index_select(x, i), (F32(4, 2), I32(3))),
    ("isnan", lambda x: paddle.isnan(x), (F32(3),)),
    ("kron", lambda x, y: paddle.kron(x, y), (F32(2, 2), F32(2, 2))),
    ("kthvalue", lambda x: paddle.kthvalue(x, 2)[0], (F32(3, 4),)),
    ("log", lambda x: paddle.log(x), (POS(3),)),
    ("logcumsumexp", lambda x: paddle.logcumsumexp(x, axis=0), (F32(4),)),
    ("logsumexp", lambda x: paddle.logsumexp(x), (F32(3, 4),)),
    ("masked_fill", lambda x, m: paddle.masked_fill(x, m, 0.5), (F32(3, 4), BOOL(3, 4))),
    ("masked_scatter", lambda x, m, v: paddle.masked_scatter(x, m, v), (F32(3, 4), BOOL(3, 4), F32(12))),
    ("matmul", lambda x, y: paddle.matmul(x, y), (F32(3, 4), F32(4, 3))),
    ("max", lambda x: paddle.max(x, axis=1), (F32(3, 4),)),
    ("maximum", lambda x, y: paddle.maximum(x, y), (F32(3), F32(3))),
    ("mean", lambda x: paddle.mean(x), (F32(3, 4),)),
    ("median", lambda x: paddle.median(x, axis=1), (F32(3, 5),)),
    ("mode", lambda x: paddle.mode(x)[0], (I32(3, 5).astype(np.float32),)),
    ("moveaxis", lambda x: paddle.moveaxis(x, 0, 1), (F32(2, 3),)),
    ("nanmean", lambda x: paddle.nanmean(x), (F32(3, 4),)),
    ("norm", lambda x: paddle.linalg.norm(x), (F32(3, 4),)),
    ("one_hot", lambda i: paddle.nn.functional.one_hot(i, 5), (I32(4),)),
    ("outer", lambda x, y: paddle.outer(x, y), (F32(3), F32(4))),
    ("pow", lambda x: paddle.pow(x, 2.0), (F32(3),)),
    ("prod", lambda x: paddle.prod(x, axis=0), (F32(3, 4),)),
    ("put_along_axis", lambda x, i, v: paddle.put_along_axis(x, i, v, axis=1), (F32(3, 4), I32(3, 1), F32(3, 1))),
    ("quantile", lambda x: paddle.quantile(x, 0.5, axis=1), (F32(3, 5),)),
    ("reshape", lambda x: paddle.reshape(x, [4, 3]), (F32(3, 4),)),
    ("roll", lambda x: paddle.roll(x, 1, axis=0), (F32(3, 4),)),
    ("scatter", lambda x, i, u: paddle.scatter(x, i, u), (F32(4, 2), I32(2), F32(2, 2))),
    ("searchsorted", lambda s, v: paddle.searchsorted(s, v), (np.sort(F32(5)), F32(3))),
    ("sign", lambda x: paddle.sign(x), (F32(3),)),
    ("sin", lambda x: paddle.sin(x), (F32(3),)),
    ("slice", lambda x: paddle.slice(x, [0], [0], [2]), (F32(4, 3),)),
    ("sort", lambda x: paddle.sort(x, axis=-1), (F32(3, 4),)),
    ("split", lambda x: paddle.split(x, 2, axis=0)[1], (F32(4, 3),)),
    ("squeeze", lambda x: paddle.squeeze(x, axis=1), (F32(3, 1, 4),)),
    ("stack", lambda x, y: paddle.stack([x, y]), (F32(3), F32(3))),
    ("std", lambda x: paddle.std(x), (F32(3, 4),)),
    ("take_along_axis", lambda x, i: paddle.take_along_axis(x, i, axis=1), (F32(3, 4), I32(3, 2))),
    ("tile", lambda x: paddle.tile(x, [2, 1]), (F32(2, 3),)),
    ("topk", lambda x: paddle.topk(x, 2)[0], (F32(3, 5),)),
    ("trace", lambda x: paddle.trace(x), (F32(3, 3),)),
    ("transpose", lambda x: paddle.transpose(x, [1, 0]), (F32(3, 4),)),
    ("tril", lambda x: paddle.tril(x), (F32(3, 3),)),
    ("unbind", lambda x: paddle.unbind(x, axis=0)[0], (F32(3, 2),)),
    ("unfold", lambda x: paddle.unfold(x, 0, 3, 2), (F32(8),)),
    ("unsqueeze", lambda x: paddle.unsqueeze(x, 0), (F32(3),)),
    ("unstack", lambda x: paddle.unstack(x)[0], (F32(3, 2),)),
    ("var", lambda x: paddle.var(x), (F32(3, 4),)),
    ("where", lambda c, x, y: paddle.where(c, x, y), (BOOL(3), F32(3), F32(3))),
    # linalg (device solvers)
    ("cholesky", lambda x: paddle.linalg.cholesky(x @ x.transpose([1, 0]) + 3 * paddle.eye(3)), (F32(3, 3),)),
    ("det", lambda x: paddle.linalg.det(x), (F32(3, 3),)),
    ("eigh", lambda x: paddle.linalg.eigh(x + x.transpose([1, 0]))[0], (F32(3, 3),)),
    ("inv", lambda x: paddle.linalg.inv(x + 3 * paddle.eye(3)), (F32(3, 3),)),
    ("matrix_power", lambda x: paddle.linalg.matrix_power(x, 2), (F32(3, 3),)),
    ("pinv", lambda x: paddle.linalg.pinv(x), (F32(3, 4),)),
    ("qr", lambda x: paddle.linalg.qr(x)[0], (F32(3, 3),)),
    ("slogdet", lambda x: paddle.linalg.slogdet(x)[0], (F32(3, 3),)),
    ("solve", lambda x, y: paddle.linalg.solve(x + 3 * paddle.eye(3), y), (F32(3, 3), F32(3))),
    ("svd", lambda x: paddle.linalg.svd(x)[1], (F32(3, 4),)),
    # round-2 surface-closure ops
    ("unflatten", lambda x: paddle.unflatten(x, 1, [2, 2]), (F32(3, 4),)),
    ("index_fill", lambda x, i: paddle.index_fill(x, i, 0, 5.0), (F32(4, 2), I32(2))),
    ("diagonal_scatter", lambda x, y: paddle.diagonal_scatter(x, y), (F32(4, 4), F32(4))),
    ("select_scatter", lambda x, y: paddle.select_scatter(x, y, 0, 1), (F32(3, 4), F32(4))),
    ("pdist", lambda x: paddle.pdist(x), (F32(5, 3),)),
    ("add_n", lambda x, y: paddle.add_n([x, y]), (F32(3), F32(3))),
    ("reverse", lambda x: paddle.reverse(x, 0), (F32(4),)),
    ("inverse", lambda x: paddle.inverse(x), (F32(3, 3) + 3 * np.eye(3, dtype=np.float32),)),
    ("linalg_cond", lambda x: paddle.linalg.cond(x), (F32(3, 3) + 3 * np.eye(3, dtype=np.float32),)),
    ("multiplex", lambda x, y, i: paddle.multiplex([x, y], i), (F32(3, 2), F32(3, 2), np.array([[0], [1], [0]], np.int32))),
    ("seq_mask", lambda x: paddle.nn.functional.sequence_mask(x, maxlen=5), (I32(3),)),
    ("pairwise_distance", lambda x, y: paddle.nn.functional.pairwise_distance(x, y), (F32(3, 4), F32(3, 4))),
    ("grid_sample", lambda x, g: paddle.nn.functional.grid_sample(x, g), (F32(1, 1, 4, 4), F32(1, 4, 4, 2))),
    ("temporal_shift", lambda x: paddle.nn.functional.temporal_shift(x, 2), (F32(4, 4, 2, 2),)),
    ("maxpool_mask", lambda x: paddle.nn.functional.max_pool2d(x, 2, return_mask=True)[1], (F32(1, 1, 4, 4),)),
    ("max_unpool2d", lambda x, i: paddle.nn.functional.max_unpool2d(x, i, 2), (F32(1, 1, 2, 2), np.array([[[[0, 3], [9, 14]]]], np.int32))),
    ("multi_margin", lambda x, y: paddle.nn.functional.multi_margin_loss(x, y), (F32(3, 4), I32(3))),
    ("hsigmoid", lambda x, y, w: paddle.nn.functional.hsigmoid_loss(x, y, 4, w), (F32(3, 5), I32(3), F32(3, 5))),
    ("top_p", lambda x, p: paddle.tensor.top_p_sampling(x, p, seed=7)[1], (POS(2, 6), POS(2))),
]

# ops whose OUTPUT SHAPE depends on data: must raise the documented error
DYNAMIC = [
    ("masked_select", lambda x, m: paddle.masked_select(x, m), (F32(3, 4), BOOL(3, 4))),
    ("nonzero", lambda x: paddle.nonzero(x), (F32(3, 4),)),
    ("unique", lambda x: paddle.unique(x), (I32(8),)),
    ("unique_consecutive", lambda x: paddle.unique_consecutive(x), (I32(8),)),
    ("bincount", lambda x: paddle.bincount(x), (I32(8),)),
    ("repeat_interleave_t", lambda x, r: paddle.repeat_interleave(x, r), (F32(3), I32(3) + 1)),
    ("eig", lambda x: paddle.linalg.eig(x)[0], (F32(3, 3),)),
    ("eigvals", lambda x: paddle.linalg.eigvals(x), (F32(3, 3),)),
]


def _run_jitted(fn, raw_inputs):
    def jfn(*vals):
        out = fn(*[Tensor(v) for v in vals])
        leaves = jax.tree_util.tree_leaves(
            out, is_leaf=lambda o: isinstance(o, Tensor)
        )
        return [l._value if isinstance(l, Tensor) else l for l in leaves]

    return jax.jit(jfn)(*[jnp.asarray(v) for v in raw_inputs])


@pytest.mark.parametrize("name,fn,inputs", TRACEABLE, ids=[t[0] for t in TRACEABLE])
def test_op_traces_under_jit(name, fn, inputs):
    jitted = _run_jitted(fn, inputs)
    eager = fn(*[Tensor(jnp.asarray(v)) for v in inputs])
    e_leaves = jax.tree_util.tree_leaves(eager, is_leaf=lambda o: isinstance(o, Tensor))
    for jv, ev in zip(jitted, e_leaves):
        np.testing.assert_allclose(
            np.asarray(jv, np.float32),
            np.asarray(ev._value if isinstance(ev, Tensor) else ev, np.float32),
            rtol=2e-4, atol=2e-4,
        )


@pytest.mark.parametrize("name,fn,inputs", DYNAMIC, ids=[t[0] for t in DYNAMIC])
def test_dynamic_op_raises_documented_error(name, fn, inputs):
    # eager works
    fn(*[Tensor(jnp.asarray(v)) for v in inputs])
    # traced raises the documented error
    with pytest.raises(DynamicShapeError):
        _run_jitted(fn, inputs)


def test_unique_with_static_size_traces():
    """TPU extension: unique(size=N) is jit-traceable with padded outputs."""
    x = np.array([3, 1, 3, 2, 1], np.int32)

    def fn(v):
        u = paddle.unique(Tensor(v), size=5)
        return u._value

    out = jax.jit(fn)(jnp.asarray(x))
    got = np.asarray(out)
    assert set(got[:3].tolist()) == {1, 2, 3}
    assert got.shape == (5,)  # padded to the static bound
    # inverse under jit too
    def fn2(v):
        u, inv = paddle.unique(Tensor(v), return_inverse=True, size=5)
        return u._value, inv._value

    u2, inv = jax.jit(fn2)(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(u2)[np.asarray(inv).reshape(-1)], x)
