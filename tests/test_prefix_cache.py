"""Radix/prefix KV reuse in the serving tier (FLAGS_prefix_cache):
page-granularity prefix matching over the paged pool, per-block refcounts
in the allocator, LRU eviction of reclaimable leaves, and graceful
pool-exhaustion queueing (docs/DECODE.md)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (GenerationEngine, RadixPrefixCache,
                                decode_stats, reset_decode_stats)


def _model(seed=41, **kw):
    paddle.seed(seed)
    kw.setdefault("num_hidden_layers", 2)
    cfg = llama_tiny(vocab_size=128, hidden_size=32, intermediate_size=64,
                     num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=128,
                     dtype="float32", **kw)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _drain(eng, reqs, **kw):
    for rid, p in reqs:
        eng.add_request(rid, p, **kw)
    while eng.has_work():
        eng.step()
    return {rid: eng.result(rid) for rid, _ in reqs}


# ---------------------------------------------------- radix tree unit tier
def test_radix_match_insert_page_granularity():
    t = RadixPrefixCache(block_size=4)
    toks = list(range(12))
    assert t.insert(toks, [10, 11, 12]) == [10, 11, 12]
    # full match, longest-prefix semantics at page granularity
    assert t.match(toks) == [10, 11, 12]
    assert t.match(toks[:8]) == [10, 11]
    assert t.match(toks[:7]) == [10]          # partial 2nd block: no match
    assert t.match([9] + toks[1:]) == []      # diverges in block 0
    # max_blocks caps the walk (the (s0-1)//bs admission cap)
    assert t.match(toks, max_blocks=1) == [10]
    # a diverging SUFFIX forks the tree without disturbing the shared run
    fork = toks[:8] + [99, 98, 97, 96]
    assert t.insert(fork, [10, 11, 20]) == [20]  # first 2 nodes exist
    assert t.match(fork) == [10, 11, 20]
    assert t.match(toks) == [10, 11, 12]
    # first writer wins: re-inserting an existing chunk keeps its block
    assert t.insert(toks[:4], [33]) == []
    assert t.match(toks[:4]) == [10]


def test_radix_eviction_refcount_and_lru():
    t = RadixPrefixCache(block_size=2)
    ref = {b: 0 for b in range(100)}
    t.insert([1, 2, 3, 4], [5, 6])    # chain 5 -> 6
    t.insert([7, 8], [9])
    ref[5] = 1                        # a live request still reads block 5

    # refcounted blocks are impossible to evict; interior nodes are
    # untouchable while a child exists — so only 6 and 9 are reclaimable
    freed = t.evict(10, ref)
    assert 5 not in freed and set(freed) == {6, 9}
    assert t.evict(10, ref) == []     # 5 is a leaf now but refcounted
    ref[5] = 0
    assert t.evict(10, ref) == [5]
    assert len(t) == 0

    # LRU order: the least-recently matched chain goes first
    t.insert([1, 2], [70])
    t.insert([3, 4], [71])
    t.match([1, 2])                   # touch 70: 71 becomes the LRU leaf
    assert t.evict(1, ref) == [71]


# ---------------------------------------------------- engine parity tier
def test_prefix_cache_streams_bit_identical():
    """Token streams with the prefix cache on equal the cache-off streams
    bit for bit: greedy and seeded sampling, unchunked and chunked
    prefill.  The second request shares a 16-token prefix (2 pages at
    bs=8) with the first."""
    m = _model()
    shared = list(np.random.default_rng(0).integers(0, 128, 16))
    reqs = [("a", shared + [3, 7, 11]), ("b", shared + [9, 1])]

    for chunk in (None, 5):
        ref = _drain(GenerationEngine(m, max_batch=2, block_size=8,
                                      num_blocks=32, prefill_chunk=chunk),
                     reqs, max_new_tokens=6)
        got = _drain(GenerationEngine(m, max_batch=2, block_size=8,
                                      num_blocks=32, prefill_chunk=chunk,
                                      prefix_cache=True),
                     reqs, max_new_tokens=6)
        assert got == ref, f"prefill_chunk={chunk}"

    sref = _drain(GenerationEngine(m, max_batch=2, block_size=8,
                                   num_blocks=32),
                  reqs, max_new_tokens=6, temperature=2.0, seed=5)
    sgot = _drain(GenerationEngine(m, max_batch=2, block_size=8,
                                   num_blocks=32, prefix_cache=True),
                  reqs, max_new_tokens=6, temperature=2.0, seed=5)
    assert sgot == sref  # sampled streams ride the same (seed, nonce) keys


def test_prefix_cache_reuses_pages_and_counts():
    """The second same-prefix admission takes REFERENCES to cached pages
    (fewer fresh allocations) and the telemetry records the avoided
    prefill; a hot block is shared — both slots' tables point at it."""
    m = _model()
    shared = list(np.random.default_rng(1).integers(0, 128, 24))
    reset_decode_stats()
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=32,
                           prefix_cache=True)
    eng.add_request("a", shared + [3], max_new_tokens=4)
    free_after_a = len(eng._free)
    eng.add_request("b", shared + [9], max_new_tokens=4)
    # b matched 3 full pages: it allocated 3 fewer fresh blocks than a
    # (a: 4 prompt-ish blocks + headroom; b: same minus 3 shared)
    used_by_a = 32 - free_after_a
    used_by_b = free_after_a - len(eng._free)
    assert used_by_b == used_by_a - 3
    shared_block = eng._slots[0].blocks[0]
    assert eng._slots[1].blocks[0] == shared_block
    assert eng._ref[shared_block] == 2
    st = decode_stats()
    assert st["prefix_hits"] == 1 and st["prefix_misses"] == 1
    assert st["prefix_hit_tokens"] == 24
    assert st["resident_peak"] == 2 and st["pool_bytes"] > 0
    while eng.has_work():
        eng.step()
    # drained: refs drop to zero but cached pages stay resident
    # (reclaimable), NOT on the free list
    assert eng._ref[shared_block] == 0
    assert eng._prefix.holds(shared_block)
    assert shared_block not in eng._free


def test_pool_pressure_evicts_lru_then_queues():
    """Admission under pressure evicts reclaimable (refcount-zero) cached
    pages LRU-first; when live requests pin everything, the request
    queues and retries at the next macro-step boundary."""
    m = _model()
    rng = np.random.default_rng(2)
    pa_ = list(rng.integers(0, 128, 16))
    pb = list(rng.integers(0, 128, 16))
    pc = list(rng.integers(0, 128, 16))
    # pool of 6: one request needs 3 blocks (16 prompt + 4 new @ bs=8)
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=6,
                           prefix_cache=True)
    _drain(eng, [("a", pa_)], max_new_tokens=4)   # 2 cached pages, ref 0
    _drain(eng, [("b", pb)], max_new_tokens=4)    # 2 more; 2 blocks free
    assert sum(eng._prefix.holds(x) for x in range(6)) == 4
    reset_decode_stats()
    _drain(eng, [("c", pc)], max_new_tokens=4)    # needs 3: evicts the LRU
    assert decode_stats()["prefix_evictions"] >= 1
    assert len(eng._prefix.match(pb)) == 2        # recently used survives
    assert len(eng._prefix.match(pa_)) < 2        # a's chain lost its leaf

    # live requests pin every block -> the newcomer queues (free slot,
    # no free/reclaimable pages), then admits once the others drain
    eng2 = GenerationEngine(m, max_batch=3, block_size=8, num_blocks=4,
                            prefix_cache=True)
    r1 = list(rng.integers(0, 128, 8))
    r2 = list(rng.integers(0, 128, 8))
    r3 = list(rng.integers(0, 128, 8))
    assert eng2.add_request("x", r1, max_new_tokens=4) is not None
    assert eng2.add_request("y", r2, max_new_tokens=4) is not None
    assert eng2.add_request("z", r3, max_new_tokens=4) is None  # queued
    assert eng2.pending_requests() == ["z"]
    while eng2.has_work():
        eng2.step()
    assert len(eng2.result("z")) == 4


def test_queued_request_matches_immediate_admission():
    """Satellite regression: a rejected-then-retried request produces the
    SAME tokens as an immediately-admitted one — greedy and sampled (the
    PRNG nonce is reserved at submit time, so retry timing can't shift
    the stream)."""
    m = _model()
    p1 = list(np.random.default_rng(3).integers(0, 128, 8))
    p2 = list(np.random.default_rng(4).integers(0, 128, 8))

    def run(num_blocks):
        eng = GenerationEngine(m, max_batch=2, block_size=8,
                               num_blocks=num_blocks)
        eng.add_request("a", p1, max_new_tokens=6)  # 2 blocks (14 tokens)
        eng.add_request("b", p2, max_new_tokens=6, temperature=2.0, seed=9)
        while eng.has_work():
            eng.step()
        return eng.result("a"), eng.result("b")

    roomy = run(num_blocks=16)        # both admitted immediately
    tight_eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=2)
    tight_eng.add_request("a", p1, max_new_tokens=6)
    assert tight_eng.add_request("b", p2, max_new_tokens=6,
                                 temperature=2.0, seed=9) is None
    while tight_eng.has_work():
        tight_eng.step()
    assert (tight_eng.result("a"), tight_eng.result("b")) == roomy


def test_queued_first_token_surfaces_in_step_output():
    """Code-review regression: a queue-admitted request's prefill first
    token (add_request returned None for it) must surface through step()
    — as a LIST for that rid, led by the first token — not only via
    result() polling.  Streaming callers lose token #1 otherwise."""
    m = _model()
    rng = np.random.default_rng(7)
    p1 = list(rng.integers(0, 128, 8))
    p2 = list(rng.integers(0, 128, 8))
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=2)
    assert eng.add_request("a", p1, max_new_tokens=3) is not None
    assert eng.add_request("b", p2, max_new_tokens=3) is None  # queued
    streamed = {}
    while eng.has_work():
        for rid, v in eng.step().items():
            streamed.setdefault(rid, []).extend(
                v if isinstance(v, list) else [v])
    assert streamed["b"] == eng.result("b")  # token #1 included
    assert streamed["a"] == eng.result("a")[1:]  # a's came via add_request


def test_prefix_cache_covers_speculative_draft_pools():
    """Draft-pool sharing: cached pages index the draft pools at the same
    block ids, so a matched prefix skips BOTH prefills and speculative
    streams stay bit-identical to the cache-off engine."""
    target = _model(seed=41, num_hidden_layers=2)
    draft = _model(seed=42, num_hidden_layers=1)
    shared = list(np.random.default_rng(6).integers(0, 128, 16))
    reqs = [("a", shared + [3]), ("b", shared + [9, 4])]

    ref = _drain(GenerationEngine(target, max_batch=2, block_size=8,
                                  num_blocks=32, draft_model=draft),
                 reqs, max_new_tokens=6)
    reset_decode_stats()
    eng = GenerationEngine(target, max_batch=2, block_size=8, num_blocks=32,
                           draft_model=draft, prefix_cache=True)
    got = _drain(eng, reqs, max_new_tokens=6)
    assert got == ref
    assert decode_stats()["prefix_hits"] == 1  # b reused a's pages


def test_flags_wire_prefix_cache_and_invalidate_steps():
    """FLAGS_prefix_cache drives the constructor default, and set_flags
    on either new flag drops live engines' compiled macro-steps (the
    standard invalidation contract)."""
    m = _model()
    try:
        paddle.set_flags({"FLAGS_prefix_cache": True})
        eng = GenerationEngine(m, max_batch=1, block_size=8, num_blocks=8)
        assert eng._prefix is not None
    finally:
        paddle.set_flags({"FLAGS_prefix_cache": False})
    eng = GenerationEngine(m, max_batch=1, block_size=8, num_blocks=8,
                           decode_chunk=2)
    assert eng._prefix is None  # flag restored -> default off

    eng.add_request("r", [5, 9, 2], max_new_tokens=40)
    eng.step()
    assert eng._step_fns
    paddle.set_flags({"FLAGS_prefix_cache": True})
    assert not eng._step_fns  # invalidated
    paddle.set_flags({"FLAGS_prefix_cache": False})
    eng.step()
    assert eng._step_fns
    paddle.set_flags({"FLAGS_kv_cache_dtype": "int8"})
    try:
        assert not eng._step_fns  # invalidated (pools keep their dtype)
    finally:
        paddle.set_flags({"FLAGS_kv_cache_dtype": "bf16"})
    while eng.has_work():
        eng.step()

    with pytest.raises(ValueError, match="kv_cache_dtype"):
        GenerationEngine(m, num_blocks=8, kv_cache_dtype="fp4")
