import paddle_tpu as paddle
from paddle_tpu.jit import to_static


@to_static
def entry(x):
    if x.sum() > 0:
        return _helper(x)
    return x


def _helper(x):  # defined AFTER entry is decorated
    return x * 2
