"""Wiring tests for the sharded, crash-isolated tier-1 runner
(tools/run_tier1.py, ROADMAP item 5) — the check_bench_regression.py
pattern: the TOOLING is tested mechanically, not trusted.

Covered: deterministic shard partitioning, the isolated-worker routing of
the known 8-device collective suites, a crash in one shard reported
WITHOUT killing siblings, signal-death retry semantics (isolated shards
retry intermittent crashes; genuine failures never retry), and the
shared-compile-cache env plumbing.  The fake shard payloads import no jax
— each subprocess is milliseconds of pytest, so the whole file stays
cheap."""

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from tools.run_tier1 import (
    ISOLATED_DEFAULT,
    Shard,
    build_plan,
    partition_files,
    run_shard,
    run_isolated_test,
)

_REPO_TESTS = os.path.dirname(os.path.abspath(__file__))


# ------------------------------------------------------------ partitioning
def test_partition_deterministic_and_covering():
    files = [f"test_{c}.py" for c in "gecafdb"]
    a = partition_files(files, 3)
    b = partition_files(list(reversed(files)), 3)
    assert a == b  # input order never changes the plan
    flat = [f for bucket in a for f in bucket]
    assert sorted(flat) == sorted(files)  # every file exactly once
    # round-robin over the SORTED list
    assert a[0] == ["test_a.py", "test_d.py", "test_g.py"]
    assert a[1] == ["test_b.py", "test_e.py"]
    assert a[2] == ["test_c.py", "test_f.py"]


def test_build_plan_isolates_collective_modules():
    plan = build_plan(_REPO_TESTS, shards=4)
    iso = [s for s in plan if s.isolated]
    rest = [s for s in plan if not s.isolated]
    # every present isolated module got a DEDICATED single-file worker
    iso_names = {os.path.basename(s.files[0]) for s in iso}
    present = {f for f in ISOLATED_DEFAULT
               if os.path.exists(os.path.join(_REPO_TESTS, f))}
    assert iso_names == present
    assert all(len(s.files) == 1 for s in iso)
    # and no isolated module leaked into a round-robin shard
    rest_files = {os.path.basename(f) for s in rest for f in s.files}
    assert not (rest_files & present)
    # identical call, identical plan
    plan2 = build_plan(_REPO_TESTS, shards=4)
    assert [(s.name, s.files) for s in plan] == \
        [(s.name, s.files) for s in plan2]
    # the multi-tenant LoRA modules ride ordinary round-robin shards —
    # no 8-device collectives, so no dedicated isolated worker
    for mod in ("test_lora.py", "test_serving_lora.py",
                "test_bench_lora.py"):
        assert mod in rest_files, mod
    # the decode-chain schedule-search module is single-device (interpret
    # Pallas + one-process engines): ordinary round-robin, no isolation
    assert "test_decode_chain.py" in rest_files
    # the TP-sharded serving modules dispatch GSPMD decode programs over
    # the in-process multi-device communicator every test: DEDICATED
    # isolated workers, never round-robin (and never slow-marked).  The
    # snapshot topology-migration module restores engines ONTO meshes —
    # same crash class, same containment.
    for mod in ("test_serving_mesh.py", "test_serving_mesh_spec.py",
                "test_engine_snapshot_mesh.py"):
        assert mod in iso_names, mod
    # the engine-snapshot core + subprocess SIGKILL-matrix modules are
    # single-device (kills land in SUBPROCESS serving loops): ordinary
    # round-robin shards
    for mod in ("test_engine_snapshot.py", "test_engine_snapshot_crash.py"):
        assert mod in rest_files, mod
    # the serving-CLUSTER modules fork and SIGKILL real router/replica
    # processes (heartbeat fail-over, drain migration, the cluster crash
    # matrix, the fail-over bench): DEDICATED isolated workers, never
    # round-robin, never slow-marked
    for mod in ("test_serving_cluster.py", "test_serving_cluster_crash.py",
                "test_bench_cluster.py"):
        assert mod in iso_names, mod
    # the warm-start module forks standby workers and SIGKILLs them
    # mid-warmup — same fork/SIGKILL crash class, same dedicated worker
    assert "test_cluster_warm.py" in iso_names
    # the pipeline-schedule parity suite dispatches split-backward GSPMD
    # pipeline programs over 4/8-device in-process meshes every test: a
    # DEDICATED isolated worker, never round-robin, never slow-marked
    assert "test_zb_schedules.py" in iso_names
    # while the bench-gate and simulator-only tests stay round-robin
    assert "test_bench_gate.py" in rest_files
    # the protocol-lint suite is pure abstraction (model checker + AST
    # pass — no fork, no ring, no device): ordinary round-robin shard
    assert "test_protocol_lint.py" in rest_files


# -------------------------------------------------------- crash isolation
def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_crash_in_one_shard_reported_siblings_complete(tmp_path):
    crash = _write(tmp_path, "test_crash.py", """\
        import os, signal

        def test_boom():
            os.kill(os.getpid(), signal.SIGSEGV)
        """)
    good = _write(tmp_path, "test_good.py", """\
        def test_fine():
            assert 1 + 1 == 2

        def test_also_fine():
            assert True
        """)
    s_crash = Shard(name="crashy", files=[crash])
    s_good = Shard(name="goody", files=[good])
    run_shard(s_crash, cache_dir=str(tmp_path / "cache"), timeout=120)
    run_shard(s_good, cache_dir=str(tmp_path / "cache"), timeout=120)
    # the crash is contained and NAMED...
    assert s_crash.crashed and s_crash.signal == signal.SIGSEGV
    assert not s_crash.ok
    # ...and the sibling's results are complete, not collateral damage
    assert s_good.ok and s_good.counts.get("passed") == 2
    assert s_good.retries == 0


def test_plain_failure_parsed_not_crash(tmp_path):
    mixed = _write(tmp_path, "test_mixed.py", """\
        def test_ok():
            assert True

        def test_bad():
            assert False, "genuine failure"
        """)
    shard = Shard(name="mixed", files=[mixed])
    run_shard(shard, cache_dir=str(tmp_path / "cache"), timeout=120)
    assert shard.rc == 1 and not shard.crashed
    assert shard.counts.get("passed") == 1
    assert shard.counts.get("failed") == 1


def test_isolated_shard_retries_intermittent_crash(tmp_path):
    # crash on the FIRST run (no sentinel), pass on the retry — the
    # intermittent 8-device communicator shape
    flaky = _write(tmp_path, "test_flaky.py", f"""\
        import os, signal

        _SENTINEL = {str(tmp_path / "ran_once")!r}

        def test_flaky_crash():
            if not os.path.exists(_SENTINEL):
                open(_SENTINEL, "w").close()
                os.kill(os.getpid(), signal.SIGSEGV)
            assert True
        """)
    shard = Shard(name="iso:flaky", files=[flaky], isolated=True)
    run_shard(shard, cache_dir=str(tmp_path / "cache"), timeout=120,
              retry_crashed=1)
    assert shard.ok and shard.retries == 1
    assert shard.counts.get("passed") == 1

    # a NON-isolated shard never retries: crash-class containment is for
    # the known communicator modules, not a blanket flake-hider
    os.remove(str(tmp_path / "ran_once"))
    shard2 = Shard(name="flaky2", files=[flaky], isolated=False)
    run_shard(shard2, cache_dir=str(tmp_path / "cache"), timeout=120,
              retry_crashed=1)
    assert shard2.crashed and shard2.retries == 0


def test_always_crashing_isolated_shard_exhausts_retries(tmp_path):
    hard = _write(tmp_path, "test_hard_crash.py", """\
        import os, signal

        def test_always_crashes():
            os.kill(os.getpid(), signal.SIGKILL)
        """)
    shard = Shard(name="iso:hard", files=[hard], isolated=True)
    run_shard(shard, cache_dir=str(tmp_path / "cache"), timeout=120,
              retry_crashed=1)
    assert shard.crashed and shard.signal == signal.SIGKILL
    assert shard.retries == 1  # retried once, then reported honestly


def test_cache_dir_env_reaches_shard(tmp_path):
    probe = _write(tmp_path, "test_probe_env.py", """\
        import os

        def test_cache_env():
            assert os.environ["PADDLE_TPU_TEST_CACHE_DIR"] == \\
                os.environ["_EXPECTED_CACHE"]
        """)
    cache = str(tmp_path / "shared_cache")
    os.environ["_EXPECTED_CACHE"] = cache
    try:
        shard = Shard(name="env", files=[probe])
        run_shard(shard, cache_dir=cache, timeout=120)
        assert shard.ok and shard.counts.get("passed") == 1
    finally:
        del os.environ["_EXPECTED_CACHE"]


# -------------------------------------------- in-suite isolation helper
def _ri_failing_payload():
    raise AssertionError("deliberate payload failure")


def _ri_hanging_payload():
    # parks far past any test timeout: every attempt times out no matter
    # how fast the worker bootstrap runs (a warm jax import can finish
    # inside 1s, so "the import eats the budget" is NOT deterministic)
    time.sleep(600)


def test_run_isolated_test_genuine_failure_no_retry():
    """rc > 0 (an assertion failure in the worker) fails IMMEDIATELY with
    the worker's tail in the message — retries are only for signal-deaths
    (the un-slow-marked test_fleet suite relies on exactly this split)."""
    with pytest.raises(AssertionError) as ei:
        run_isolated_test("tests.test_run_tier1", "_ri_failing_payload",
                          retries=3, timeout=180)
    msg = str(ei.value)
    assert "rc 1" in msg
    assert "1 attempt(s)" in msg  # never retried
    assert "deliberate payload failure" in msg


def test_run_isolated_test_timeout_retries_like_signal_death():
    """A HUNG worker is the deadlock half of the crash class this
    mechanism contains: TimeoutExpired must consume retries and surface
    as a signal-style failure, not escape as a raw exception."""
    with pytest.raises(AssertionError) as ei:
        # the payload parks forever, so every attempt times out
        # deterministically — regardless of how fast the worker
        # bootstrap (jax import) happens to be on a warm cache
        run_isolated_test("tests.test_run_tier1", "_ri_hanging_payload",
                          retries=1, timeout=1)
    msg = str(ei.value)
    assert "signal" in msg
    assert "2 attempt(s)" in msg  # retried once, then reported
    assert "timed out after 1s" in msg


def test_runner_entry_list_mode():
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(_REPO_TESTS),
                                      "tools", "run_tier1.py"), "--list"],
        stdout=subprocess.PIPE, text=True, timeout=60)
    assert out.returncode == 0
    assert "iso:test_fleet [isolated]" in out.stdout
    assert "shard0" in out.stdout
