"""Pluggable-device proof (VERDICT r2 item 7).

Reference: test/custom_runtime/test_custom_cpu_plugin.py:24-50 registers an
out-of-tree fake CPU device (fake_cpu_device.h:225) and runs ops on it.
Here the pluggable ABI is PJRT (device/plugin.py): the .so discovery path
is exercised with a stub library (broken plugins must fail loudly, not
crash startup), and a factory-registered custom backend runs a real op and
a collective end-to-end.  Runs in a subprocess: registration must precede
first backend init, which the test session has long passed.
"""

import os
import subprocess
import sys
import textwrap

import pytest

_SCRIPT = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, "__REPO__")
    import jax
    jax.config.update("jax_platforms", "cpu,fake_cpu")

    # ---- in-process factory backend: op + collective on the custom device
    from paddle_tpu.device.plugin import (
        load_custom_device_plugin,
        register_custom_backend,
        registered_custom_devices,
        scan_custom_device_plugins,
    )

    def fake_factory():
        import jaxlib._jax as _x
        return _x.get_tfrt_cpu_client(asynchronous=True)

    register_custom_backend("fake_cpu", fake_factory)
    assert "fake_cpu" in registered_custom_devices()

    import jax.numpy as jnp
    devs = jax.devices("fake_cpu")
    assert devs, "no devices from the registered custom backend"
    x = jax.device_put(jnp.ones((4, 4), jnp.float32), devs[0])
    assert float((x @ x).sum()) == 64.0

    import numpy as np
    from jax.sharding import Mesh, PartitionSpec
    from jax import lax, shard_map
    mesh = Mesh(np.array(devs[:1]), ("x",))
    f = jax.jit(shard_map(lambda v: lax.psum(v, "x"), mesh=mesh,
                          in_specs=PartitionSpec(), out_specs=PartitionSpec()))
    assert np.allclose(np.asarray(f(jnp.ones(3))), 1.0)

    # paddle surface: tensors created while the custom device is default
    import paddle_tpu as paddle
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    assert float((t @ t).sum()._value) == 8.0

    # ---- .so discovery path (reference CUSTOM_DEVICE_ROOT scan):
    # (a) a corrupt plugin is skipped with a warning — startup survives
    import tempfile, warnings
    root = tempfile.mkdtemp()
    with open(os.path.join(root, "libpjrt_corrupt.so"), "wb") as f:
        f.write(b"not a real shared object")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        found = scan_custom_device_plugins(root)
    assert found == [], found
    assert any("corrupt" in str(x.message) for x in w), [str(x.message) for x in w]

    # (b) a REAL (if useless) shared object registers through the scan; a
    # stub GetPjrtApi means first USE fails cleanly, never a crash
    import subprocess as sp
    src = os.path.join(root, "stub.cc")
    with open(src, "w") as f:
        f.write(
            "#include <cstddef>\\n"
            "#include <cstring>\\n"
            "// minimal PJRT_Api-shaped blob: struct_size + extension + version\\n"
            "struct StubApi { size_t struct_size; void* ext;\\n"
            "  struct { size_t struct_size; void* ext; int major_v; int minor_v; } v;\\n"
            "  void* fns[256]; };\\n"
            "static StubApi api;\\n"
            "extern \\"C\\" const void* GetPjrtApi() {\\n"
            "  std::memset(&api, 0, sizeof api);\\n"
            "  api.struct_size = sizeof api;\\n"
            "  api.v.struct_size = sizeof api.v;\\n"
            "  api.v.major_v = 0; api.v.minor_v = 1;\\n"
            "  return &api; }\\n"
        )
    sp.run(["g++", "-shared", "-fPIC", "-o",
            os.path.join(root, "libpjrt_stubdev.so"), src], check=True)
    try:
        load_custom_device_plugin("stubdev", os.path.join(root, "libpjrt_stubdev.so"))
        registered = True
    except BaseException as e:  # clean python-level rejection is the point
        registered = False
        print("stub registration rejected:", type(e).__name__, str(e)[:120], flush=True)
    if registered:
        assert "stubdev" in registered_custom_devices()
        try:
            jax.config.update("jax_platforms", "cpu,fake_cpu,stubdev")
            jax.extend.backend.get_backend("stubdev")
            raise SystemExit("stub plugin unexpectedly initialized")
        except RuntimeError:
            pass

    # missing path errors immediately
    try:
        load_custom_device_plugin("ghost", "/nonexistent/libpjrt_ghost.so")
        raise SystemExit("missing plugin path did not raise")
    except FileNotFoundError:
        pass
    print("PLUGIN OK", flush=True)
    """
)


@pytest.mark.slow
def test_custom_device_plugin_end_to_end(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "plugin_worker.py"
    script.write_text(_SCRIPT.replace("__REPO__", repo))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    out = subprocess.run(
        [sys.executable, str(script)],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    assert "PLUGIN OK" in out.stdout
