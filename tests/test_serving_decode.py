"""Serving decode tier: paged-KV block attention vs naive concat cache
(reference block_multihead_attention serving kernel,
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu)."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def _model_and_prompt(gqa=False):
    paddle.seed(0)
    kw = {"num_key_value_heads": 2} if gqa else {}
    cfg = llama_tiny(dtype="float32", **kw)
    m = LlamaForCausalLM(cfg)
    m.eval()
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 9)).astype(np.int32))
    return m, ids


@pytest.mark.parametrize("gqa", [pytest.param(False, marks=pytest.mark.slow), True])
def test_paged_matches_naive_decode(gqa):
    m, ids = _model_and_prompt(gqa)
    naive = np.asarray(m.generate(ids, max_new_tokens=8, cache="naive")._value)
    paged = np.asarray(m.generate(ids, max_new_tokens=8, cache="paged", block_size=4)._value)
    np.testing.assert_array_equal(naive, paged)


def test_paged_ops_roundtrip():
    from paddle_tpu.ops import paged_attention as pa

    b, nkv, bs, h, nb = 2, 2, 4, 8, 6
    kc, vc = pa.alloc_paged_cache(nb, nkv, bs, h, jnp.float32)
    tables = jnp.asarray(np.arange(nb, dtype=np.int32).reshape(b, 3))
    rng = np.random.default_rng(1)
    toks = [jnp.asarray(rng.standard_normal((b, nkv, h)).astype(np.float32)) for _ in range(5)]
    for i, t in enumerate(toks):
        kc = pa.paged_write(kc, t, tables, jnp.full((b,), i, jnp.int32))
    view = pa.paged_gather(kc, tables)  # [B, Nkv, 12, H]
    for i, t in enumerate(toks):
        np.testing.assert_allclose(np.asarray(view[:, :, i, :]), np.asarray(t))


def test_block_multihead_attention_api():
    import paddle_tpu.incubate.nn.functional as IF
    from paddle_tpu.ops import paged_attention as pa

    b, n, h, bs = 2, 4, 8, 4
    kc, vc = pa.alloc_paged_cache(4, n, bs, h, jnp.float32)
    tables = np.arange(4, dtype=np.int32).reshape(b, 2)
    rng = np.random.default_rng(2)
    qkv = rng.standard_normal((b, 3 * n * h)).astype(np.float32)
    lens = np.array([1, 1], np.int32)
    out, kc2, vc2 = IF.block_multihead_attention(
        paddle.to_tensor(qkv), kc, vc, paddle.to_tensor(tables), paddle.to_tensor(lens),
        num_heads=n, head_dim=h,
    )
    # single token, len 1: attention over itself -> out == v
    v = qkv[:, 2 * n * h :]
    np.testing.assert_allclose(np.asarray(out._value), v, rtol=1e-5, atol=1e-5)


def test_fused_ec_moe():
    import paddle_tpu.incubate.nn.functional as IF

    rng = np.random.default_rng(3)
    b, s, d, e, dff = 2, 3, 8, 4, 16
    x = rng.standard_normal((b, s, d)).astype(np.float32)
    gw = rng.standard_normal((d, e)).astype(np.float32)
    w0 = rng.standard_normal((e, d, dff)).astype(np.float32) * 0.1
    b0 = np.zeros((e, dff), np.float32)
    w1 = rng.standard_normal((e, dff, d)).astype(np.float32) * 0.1
    b1 = np.zeros((e, d), np.float32)
    out = IF.fused_ec_moe(
        paddle.to_tensor(x), paddle.to_tensor(gw), paddle.to_tensor(w0),
        paddle.to_tensor(b0), paddle.to_tensor(w1), paddle.to_tensor(b1), "gelu"
    )
    # numpy oracle
    import scipy.special as sp  # noqa — avoid dependency; do manual softmax

    def softmax(a):
        ex = np.exp(a - a.max(-1, keepdims=True))
        return ex / ex.sum(-1, keepdims=True)

    probs = softmax(x @ gw)
    def gelu(v):
        return 0.5 * v * (1 + np.tanh(np.sqrt(2 / np.pi) * (v + 0.044715 * v**3)))
    ref = np.zeros_like(x)
    for ei in range(e):
        hh = gelu(x @ w0[ei] + b0[ei])
        ref += (hh @ w1[ei] + b1[ei]) * probs[..., ei : ei + 1]
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=2e-4, atol=2e-4)
