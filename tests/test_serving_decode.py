"""Serving decode tier: paged-KV block attention vs naive concat cache
(reference block_multihead_attention serving kernel,
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu)."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def _model_and_prompt(gqa=False):
    paddle.seed(0)
    kw = {"num_key_value_heads": 2} if gqa else {}
    cfg = llama_tiny(dtype="float32", **kw)
    m = LlamaForCausalLM(cfg)
    m.eval()
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 9)).astype(np.int32))
    return m, ids


@pytest.mark.parametrize("gqa", [pytest.param(False, marks=pytest.mark.slow), True])
def test_paged_matches_naive_decode(gqa):
    m, ids = _model_and_prompt(gqa)
    naive = np.asarray(m.generate(ids, max_new_tokens=8, cache="naive")._value)
    paged = np.asarray(m.generate(ids, max_new_tokens=8, cache="paged", block_size=4)._value)
    np.testing.assert_array_equal(naive, paged)


def test_paged_ops_roundtrip():
    from paddle_tpu.ops import paged_attention as pa

    b, nkv, bs, h, nb = 2, 2, 4, 8, 6
    kc, vc = pa.alloc_paged_cache(nb, nkv, bs, h, jnp.float32)
    tables = jnp.asarray(np.arange(nb, dtype=np.int32).reshape(b, 3))
    rng = np.random.default_rng(1)
    toks = [jnp.asarray(rng.standard_normal((b, nkv, h)).astype(np.float32)) for _ in range(5)]
    for i, t in enumerate(toks):
        kc = pa.paged_write(kc, t, tables, jnp.full((b,), i, jnp.int32))
    view = pa.paged_gather(kc, tables)  # [B, Nkv, 12, H]
    for i, t in enumerate(toks):
        np.testing.assert_allclose(np.asarray(view[:, :, i, :]), np.asarray(t))


def test_block_multihead_attention_api():
    import paddle_tpu.incubate.nn.functional as IF
    from paddle_tpu.ops import paged_attention as pa

    b, n, h, bs = 2, 4, 8, 4
    kc, vc = pa.alloc_paged_cache(4, n, bs, h, jnp.float32)
    tables = np.arange(4, dtype=np.int32).reshape(b, 2)
    rng = np.random.default_rng(2)
    qkv = rng.standard_normal((b, 3 * n * h)).astype(np.float32)
    lens = np.array([1, 1], np.int32)
    out, kc2, vc2 = IF.block_multihead_attention(
        paddle.to_tensor(qkv), kc, vc, paddle.to_tensor(tables), paddle.to_tensor(lens),
        num_heads=n, head_dim=h,
    )
    # single token, len 1: attention over itself -> out == v
    v = qkv[:, 2 * n * h :]
    np.testing.assert_allclose(np.asarray(out._value), v, rtol=1e-5, atol=1e-5)


def test_fused_ec_moe():
    import paddle_tpu.incubate.nn.functional as IF

    rng = np.random.default_rng(3)
    b, s, d, e, dff = 2, 3, 8, 4, 16
    x = rng.standard_normal((b, s, d)).astype(np.float32)
    gw = rng.standard_normal((d, e)).astype(np.float32)
    w0 = rng.standard_normal((e, d, dff)).astype(np.float32) * 0.1
    b0 = np.zeros((e, dff), np.float32)
    w1 = rng.standard_normal((e, dff, d)).astype(np.float32) * 0.1
    b1 = np.zeros((e, d), np.float32)
    out = IF.fused_ec_moe(
        paddle.to_tensor(x), paddle.to_tensor(gw), paddle.to_tensor(w0),
        paddle.to_tensor(b0), paddle.to_tensor(w1), paddle.to_tensor(b1), "gelu"
    )
    # numpy oracle
    import scipy.special as sp  # noqa — avoid dependency; do manual softmax

    def softmax(a):
        ex = np.exp(a - a.max(-1, keepdims=True))
        return ex / ex.sum(-1, keepdims=True)

    probs = softmax(x @ gw)
    def gelu(v):
        return 0.5 * v * (1 + np.tanh(np.sqrt(2 / np.pi) * (v + 0.044715 * v**3)))
    ref = np.zeros_like(x)
    for ei in range(e):
        hh = gelu(x @ w0[ei] + b0[ei])
        ref += (hh @ w1[ei] + b1[ei]) * probs[..., ei : ei + 1]
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=2e-4, atol=2e-4)


def _tiny_lm(fuse=False, n_layers=2, seed=11):
    paddle.seed(seed)
    from paddle_tpu.models.llama import llama_tiny

    cfg = llama_tiny(vocab_size=64, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=n_layers, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=64,
                     dtype="float32", fuse_layer_stack=fuse)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def test_chunked_generate_parity_across_chunk_sizes():
    """Macro-step decoding (decode_chunk=D) must emit BIT-IDENTICAL token
    streams for every D — greedy and seeded sampling — on both the
    unrolled loop and the LayerStack scan layout, including the
    max_new_tokens % D tail chunk (max_new 10: D=4 -> 2 full + tail 2,
    D=8 -> 1 full + tail 1)."""
    loop_m, scan_m = _tiny_lm(False), _tiny_lm(True)
    scan_m.set_state_dict(loop_m.state_dict())
    prompt = paddle.to_tensor(
        np.random.default_rng(6).integers(0, 64, (2, 7)).astype(np.int32))

    with paddle.no_grad():
        ref = np.asarray(loop_m.generate(
            prompt, max_new_tokens=10, cache="paged", block_size=4,
            decode_chunk=1)._value)
        sref = np.asarray(loop_m.generate(
            prompt, max_new_tokens=10, cache="paged", block_size=4,
            do_sample=True, temperature=1.5, seed=3, decode_chunk=1)._value)
        for m, name in ((loop_m, "loop"), (scan_m, "scan")):
            for D in (4, 8):
                got = np.asarray(m.generate(
                    prompt, max_new_tokens=10, cache="paged", block_size=4,
                    decode_chunk=D)._value)
                np.testing.assert_array_equal(got, ref, err_msg=f"{name} D={D}")
                sgot = np.asarray(m.generate(
                    prompt, max_new_tokens=10, cache="paged", block_size=4,
                    do_sample=True, temperature=1.5, seed=3,
                    decode_chunk=D)._value)
                np.testing.assert_array_equal(sgot, sref,
                                              err_msg=f"{name} D={D} sampled")

    import pytest as _pytest

    with _pytest.raises(ValueError, match="decode_chunk"):
        loop_m.generate(prompt, max_new_tokens=4, decode_chunk=0)


def test_chunked_engine_parity_and_macro_boundaries():
    """GenerationEngine macro-stepping: chunked token streams equal the
    per-token engine's for greedy AND per-slot sampled requests; requests
    admitted between step() calls join at macro-step boundaries; a request
    hitting EOS mid-chunk retires with its surplus lanes dropped; the
    step() return contract is {rid: tok} at D=1 and {rid: [toks]} at
    D>1."""
    from paddle_tpu.serving import GenerationEngine

    p1, p2 = [5, 9, 17, 33, 2], [7, 11, 3]

    def run(D, eos=None):
        eng = GenerationEngine(_tiny_lm(), max_batch=2, block_size=8,
                               num_blocks=16, eos_token_id=eos,
                               decode_chunk=D)
        eng.add_request("a", p1, max_new_tokens=9)
        first = eng.step()  # "b" joins at the next macro-step boundary
        eng.add_request("b", p2, max_new_tokens=6, temperature=5.0, seed=42)
        while eng.has_work():
            eng.step()
        return first, eng.result("a"), eng.result("b")

    f1, a1, b1 = run(1)
    assert isinstance(f1["a"], int)  # D=1 keeps the scalar contract
    for D in (4, 8):
        fD, aD, bD = run(D)
        assert isinstance(fD["a"], list) and len(fD["a"]) <= D
        assert (aD, bD) == (a1, b1), f"D={D}"

    # EOS discovered mid-chunk: same early stop as the per-token engine
    eos = a1[1]
    _, ae1, be1 = run(1, eos=eos)
    assert ae1[-1] == eos and len(ae1) < len(a1)
    for D in (4, 8):
        _, aeD, beD = run(D, eos=eos)
        assert (aeD, beD) == (ae1, be1), f"D={D} eos"


def test_decode_scan_is_depth_constant_and_pool_safe():
    """The LayerStack decode scan traces ONE layer body regardless of
    depth (the loop path traces one per layer), and a chunked engine on a
    scan model still recycles pool pages cleanly after mid-chunk
    completion (no headroom blocks needed: masked lanes write scratch)."""
    import paddle_tpu.models.llama as llama_mod
    from paddle_tpu.serving import GenerationEngine

    prompt = paddle.to_tensor(np.array([[5, 9, 1]], np.int32))
    counts = {}
    real = llama_mod._decode_layer_paged

    def counting(*a, **kw):
        counts["n"] = counts.get("n", 0) + 1
        return real(*a, **kw)

    def traced_body_runs(fuse, n_layers):
        m = _tiny_lm(fuse, n_layers=n_layers)
        counts["n"] = 0
        llama_mod._decode_layer_paged = counting
        try:
            with paddle.no_grad():
                m.generate(prompt, max_new_tokens=5, cache="paged",
                           block_size=8, decode_chunk=4)
        finally:
            llama_mod._decode_layer_paged = real
        return counts["n"]

    scan2, scan4 = traced_body_runs(True, 2), traced_body_runs(True, 4)
    loop4 = traced_body_runs(False, 4)
    assert scan2 == scan4, (scan2, scan4)  # depth-constant trace
    assert loop4 >= 4 * scan4 / 2, (loop4, scan4)  # loop pays per layer

    # pool hygiene on the scan + chunk path: pages all return to the pool
    m = _tiny_lm(True)
    eng = GenerationEngine(m, max_batch=1, block_size=8, num_blocks=4,
                           decode_chunk=4)
    free0 = len(eng._free)
    eng.add_request("one", [4, 8, 15], max_new_tokens=5)
    while eng.has_work():
        eng.step()
    assert len(eng._free) == free0
    assert len(eng.result("one")) == 5


def test_generate_sampling_surface():
    """decode_strategy='sampling' (reference generate() surface):
    deterministic per seed, top_k=1 == greedy, naive == paged sampling
    with the same seed, and temperature drives diversity."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(11)
    cfg = llama_tiny(vocab_size=64, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=64,
                     dtype="float32")
    m = LlamaForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(np.array([[3, 9, 1]], np.int32))

    with paddle.no_grad():
        greedy = np.asarray(m.generate(ids, max_new_tokens=6, cache="naive")._value)
        # top_k=1 sampling is argmax regardless of seed
        k1 = np.asarray(m.generate(ids, max_new_tokens=6, cache="naive",
                                   do_sample=True, top_k=1, seed=7)._value)
        np.testing.assert_array_equal(k1, greedy)

        s1 = np.asarray(m.generate(ids, max_new_tokens=6, cache="naive",
                                   do_sample=True, temperature=1.5, seed=3)._value)
        s2 = np.asarray(m.generate(ids, max_new_tokens=6, cache="naive",
                                   do_sample=True, temperature=1.5, seed=3)._value)
        np.testing.assert_array_equal(s1, s2)  # same seed -> same tokens

        p1 = np.asarray(m.generate(ids, max_new_tokens=6, cache="paged",
                                   block_size=8, do_sample=True,
                                   temperature=1.5, seed=3)._value)
        np.testing.assert_array_equal(p1, s1)  # naive == paged per seed

        outs = {tuple(np.asarray(m.generate(
            ids, max_new_tokens=6, cache="naive", do_sample=True,
            temperature=2.0, seed=s)._value).ravel()) for s in range(6)}
        assert len(outs) > 1  # hot sampling really varies across seeds

        # invalid knobs are loud
        import pytest as _pytest

        with _pytest.raises(ValueError, match="top_p"):
            m.generate(ids, do_sample=True, top_p=0.0)
        with _pytest.raises(ValueError, match="decode_strategy"):
            m.generate(ids, decode_strategy="diverse_sibling")

        # greedy must NOT advance the global RNG stream
        paddle.seed(123)
        r1 = np.asarray(paddle.randn([3])._value)
        paddle.seed(123)
        m.generate(ids, max_new_tokens=2, cache="naive")  # greedy
        r2 = np.asarray(paddle.randn([3])._value)
        np.testing.assert_array_equal(r1, r2)

        # top_p nucleus keeps outputs within the plausible set but is
        # still deterministic per seed
        n1 = np.asarray(m.generate(ids, max_new_tokens=6, cache="naive",
                                   do_sample=True, top_p=0.8, seed=9)._value)
        n2 = np.asarray(m.generate(ids, max_new_tokens=6, cache="naive",
                                   decode_strategy="sampling", top_p=0.8,
                                   seed=9)._value)
        np.testing.assert_array_equal(n1, n2)


def test_beam_search_decode():
    """decode_strategy='beam_search': beam total log-prob >= greedy's, the
    K=1 degenerate case equals greedy, and batches decode independently."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(23)
    cfg = llama_tiny(vocab_size=32, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=64,
                     dtype="float32")
    m = LlamaForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(np.array([[3, 9, 1], [7, 2, 5]], np.int32))
    L = 5

    def seq_logprob(prompt_row, gen_row):
        """Sum of log p(token | prefix) under teacher forcing."""
        full = np.concatenate([prompt_row, gen_row])[None]
        with paddle.no_grad():
            logits = m(paddle.to_tensor(full.astype(np.int32)))
        lp = jax.nn.log_softmax(np.asarray(logits._value)[0], -1)
        s0 = len(prompt_row)
        return float(sum(lp[s0 - 1 + t, gen_row[t]] for t in range(len(gen_row))))

    with paddle.no_grad():
        greedy = np.asarray(m.generate(ids, max_new_tokens=L, cache="naive")._value)
        beams = np.asarray(m.generate(ids, max_new_tokens=L,
                                      decode_strategy="beam_search",
                                      num_beams=6)._value)
    assert beams.shape == (2, L)
    p = np.asarray(ids._value)
    for r in range(2):
        gs = seq_logprob(p[r], greedy[r])
        bs = seq_logprob(p[r], beams[r])
        assert bs >= gs - 1e-4, (r, gs, bs)  # beam never worse than greedy

    # K=1 beam_search IS greedy; sampling + beams conflict is loud
    import pytest as _pytest

    with paddle.no_grad():
        k1 = np.asarray(m.generate(ids, max_new_tokens=L,
                                   decode_strategy="beam_search",
                                   num_beams=1)._value)
    np.testing.assert_array_equal(k1, greedy)
    with _pytest.raises(ValueError, match="beam"):
        m.generate(ids, do_sample=True, num_beams=4)

    # batch independence: row 0 alone decodes to the same beam
    with paddle.no_grad():
        solo = np.asarray(m.generate(paddle.to_tensor(p[:1]), max_new_tokens=L,
                                     num_beams=6)._value)
    np.testing.assert_array_equal(solo[0], beams[0])
