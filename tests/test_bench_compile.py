"""Tier-1 smoke of benchmarks/bench_compile.py.

Like test_bench_dispatch: the scan-over-layers compile benchmark must keep
emitting the one-line JSON payload the driver parses, and its built-in
loss-trajectory parity gate (scan vs unrolled over 5 train steps) must
hold — so the depth-constant-compile path can't bitrot unexercised
between measured rounds.
"""

import json
import os
import subprocess
import sys


def test_bench_compile_smoke_emits_valid_json():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PADDLE_TPU_BENCH_SMOKE="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "benchmarks", "bench_compile.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo)
    assert out.returncode == 0, (out.stderr or out.stdout)[-800:]
    line = next(ln for ln in reversed(out.stdout.splitlines()) if ln.startswith("{"))
    payload = json.loads(line)
    assert payload["metric"] == "scan_layers_ttfs_speedup"
    assert payload["unit"] == "x"
    assert payload["value"] > 0
    assert "vs_baseline" in payload
    assert payload["loss_trajectories_match"] is True
    detail = payload["detail"]
    for section in ("unrolled", "scan"):
        assert detail[section]["ttfs_s"] > 0
        assert detail[section]["steps_per_sec"] > 0
        assert len(detail[section]["losses"]) >= 5
    # the acceptance direction: scan must beat the unrolled loop on
    # time-to-first-step even at smoke sizes (>= 12 layers)
    assert payload["value"] > 1.5, payload
    # warm start ran and the second process actually hit the disk cache
    warm = detail["warm_start"]
    assert "error" not in warm.get("cold", {}), warm
    assert warm["warm"]["hits"] > 0
    assert warm["warm"]["misses"] == 0
