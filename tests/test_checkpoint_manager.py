"""CheckpointManager: atomic commits, retention, auto-resume exactness,
corruption detection, supervised async IO (distributed/checkpoint/manager.py,
docs/CHECKPOINT.md).  The subprocess SIGKILL matrix lives in
test_checkpoint_crash.py; everything here is in-process."""

import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.checkpoint import CheckpointManager, checkpoint_stats
from paddle_tpu.distributed.checkpoint import manager as manager_mod
from paddle_tpu.io import DataLoader, Dataset, DistributedBatchSampler


class _ArrayDataset(Dataset):
    def __init__(self, n=16, dim=4, seed=0):
        self.data = np.random.RandomState(seed).randn(n, dim).astype(np.float32)

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


def _make_trainer(seed=7):
    paddle.seed(seed)
    m = nn.Linear(4, 4)
    sched = opt.lr.CosineAnnealingDecay(learning_rate=0.1, T_max=10)
    o = opt.Adam(learning_rate=sched, parameters=m.parameters())
    ds = _ArrayDataset()
    sampler = DistributedBatchSampler(ds, batch_size=4, shuffle=True, seed=11)
    dl = DataLoader(ds, batch_sampler=sampler)
    return m, o, sched, dl, sampler


def _train(m, o, sched, dl, sampler, start_step, total_steps, on_step=None):
    """Deterministic loop exercising every restored component: shuffled
    sampler feeds the batches, eager RNG noise folds into the loss, Adam
    moments + cosine LR evolve per step."""
    losses = []
    step = start_step
    epoch = sampler.epoch
    while step < total_steps:
        sampler.set_epoch(epoch)
        for batch in dl:
            step += 1
            x = paddle.to_tensor(np.asarray(batch))
            noise = paddle.rand([1])  # advances the global RNG counter
            loss = (m(x) ** 2).mean() * (1.0 + 0.01 * noise.mean())
            loss.backward()
            o.step()
            o.clear_grad()
            sched.step()
            losses.append(float(loss))
            if on_step is not None:
                on_step(step)
            if step >= total_steps:
                break
        epoch += 1
    return losses


def test_commit_layout_and_manifest(tmp_path):
    m, o, sched, dl, sampler = _make_trainer()
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=1, async_save=False)
    mgr.save(3, model=m, optimizer=o, lr_scheduler=sched, dataloader=dl)

    assert mgr.all_steps() == [3]
    step_dir = tmp_path / "step_00000003"
    names = sorted(os.listdir(step_dir))
    assert "MANIFEST.json" in names and "extras.pkl" in names and "metadata.json" in names
    # no temp dirs survive a successful commit
    assert not [n for n in os.listdir(tmp_path) if n.startswith("_tmp_")]
    manifest = json.loads((step_dir / "MANIFEST.json").read_text())
    assert manifest["step"] == 3
    # every payload file is checksummed (the manifest itself is not listed)
    assert sorted(manifest["files"]) == [n for n in names if n != "MANIFEST.json"]
    for rec in manifest["files"].values():
        assert set(rec) == {"sha256", "size"}
    assert mgr.latest_step() == 3


def test_kill_and_resume_bit_identical(tmp_path):
    """Resume from a mid-run checkpoint into FRESH objects and finish: the
    per-step losses must match the uninterrupted run bit-for-bit — model,
    optimizer moments, LR schedule, global RNG, and the mid-epoch sampler
    position all restored."""
    m, o, sched, dl, sampler = _make_trainer(seed=7)
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=3, async_save=True)
    full = _train(m, o, sched, dl, sampler, 0, 10,
                  on_step=lambda s: mgr.maybe_save(
                      s, model=m, optimizer=o, lr_scheduler=sched, dataloader=dl))
    mgr.wait()
    assert mgr.latest_step() == 9

    # "crash": throw everything away, rebuild with a DIFFERENT seed so any
    # component the restore misses changes the losses
    m2, o2, sched2, dl2, sampler2 = _make_trainer(seed=999)
    mgr2 = CheckpointManager(str(tmp_path), save_interval_steps=3)
    start = mgr2.restore(model=m2, optimizer=o2, lr_scheduler=sched2, dataloader=dl2, step=6)
    assert start == 6
    resumed = _train(m2, o2, sched2, dl2, sampler2, 6, 10)
    assert resumed == full[6:], "resumed losses diverge from uninterrupted run"
    mgr.close()
    mgr2.close()


def test_latest_step_skips_torn_checkpoints(tmp_path):
    m, o, _, _, _ = _make_trainer()
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=1, async_save=False)
    for s in (1, 2, 3):
        mgr.save(s, model=m, optimizer=o)
    base = checkpoint_stats()["corrupt_skipped"]

    # bit-rot the newest shard file; garble the next one's manifest
    shard = next(p for p in (tmp_path / "step_00000003").iterdir() if p.suffix == ".npz")
    shard.write_bytes(shard.read_bytes()[:-7])
    (tmp_path / "step_00000002" / "MANIFEST.json").write_text("{ torn")

    fresh = CheckpointManager(str(tmp_path))  # no _verify_dir cache
    assert fresh.latest_step() == 1
    assert checkpoint_stats()["corrupt_skipped"] - base == 2
    with pytest.raises(RuntimeError, match="corrupt"):
        fresh.restore(model=m, step=3)


def test_gc_retention_and_last_valid_survival(tmp_path):
    m, _, _, _, _ = _make_trainer()
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=1, max_to_keep=2,
                            async_save=False)
    for s in (1, 2, 3, 4):
        mgr.save(s, model=m)
    assert mgr.all_steps() == [3, 4]  # retention

    # an invalid dir OLDER than the newest valid checkpoint is GC'd; a torn
    # dir NEWER than every valid one is kept for post-mortem (and skipped)
    os.makedirs(tmp_path / "step_00000002")
    (tmp_path / "step_00000002" / "junk").write_text("x")
    os.makedirs(tmp_path / "step_00000009")
    (tmp_path / "step_00000009" / "junk").write_text("x")
    mgr.save(5, model=m)
    steps = mgr.all_steps()
    assert 2 not in steps
    assert 9 in steps
    assert mgr.latest_step() == 5


def test_async_failure_reraises_and_backpressure(tmp_path, monkeypatch):
    m, _, _, _, _ = _make_trainer()
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=1, async_save=True)

    real_savez = manager_mod.np.savez

    def slow_savez(*a, **kw):
        time.sleep(0.15)
        return real_savez(*a, **kw)

    monkeypatch.setattr(manager_mod.np, "savez", slow_savez)
    base = checkpoint_stats()["backpressure_seconds"]
    for s in (1, 2, 3):  # 3rd save must block on the bounded queue
        mgr.save(s, model=m)
    mgr.wait()
    assert checkpoint_stats()["backpressure_seconds"] > base
    assert mgr.latest_step() == 3

    def broken_savez(*a, **kw):
        raise OSError("disk gone")

    monkeypatch.setattr(manager_mod.np, "savez", broken_savez)
    mgr.save(4, model=m)
    with pytest.raises(RuntimeError, match="background write failed"):
        mgr.wait()
    # the error is consumed; the manager keeps working afterwards
    monkeypatch.setattr(manager_mod.np, "savez", real_savez)
    mgr.save(5, model=m)
    mgr.wait()
    assert mgr.latest_step() == 5
    mgr.close()


def test_resave_same_step_and_verify_on_save(tmp_path):
    m, _, _, _, _ = _make_trainer()
    paddle.set_flags({"FLAGS_checkpoint_verify_on_save": True})
    try:
        mgr = CheckpointManager(str(tmp_path), save_interval_steps=1, async_save=False)
        mgr.save(1, model=m)
        mgr.save(1, model=m)  # overwrite, not error
        assert mgr.all_steps() == [1]
        assert mgr.latest_step() == 1
    finally:
        paddle.set_flags({"FLAGS_checkpoint_verify_on_save": False})


def test_resharded_resume_through_manager(tmp_path):
    """Save under one sharding, restore under another — the manager routes
    tensor state through load_state_dict's reshard-on-load."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    full = np.arange(64, dtype=np.float32).reshape(8, 8)
    mesh_a = Mesh(np.array(jax.devices()[:4]), ("x",))
    arr_a = jax.device_put(jnp.asarray(full), NamedSharding(mesh_a, P("x", None)))
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(1, model={"w": paddle.Tensor(arr_a)})

    mesh_b = Mesh(np.array(jax.devices()[:2]), ("y",))
    target = jax.device_put(jnp.zeros((8, 8), jnp.float32), NamedSharding(mesh_b, P(None, "y")))
    state = {"w": paddle.Tensor(target)}
    assert mgr.restore(model=state) == 1
    out = state["w"]._value
    assert len(out.sharding.device_set) == 2
    np.testing.assert_array_equal(np.asarray(out), full)


def test_preemption_handler(tmp_path):
    import signal

    m, _, _, _, _ = _make_trainer()
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100, async_save=False)
    mgr.install_preemption_handler()
    try:
        assert not mgr.maybe_save(7, model=m)  # off-interval: no save
        os.kill(os.getpid(), signal.SIGTERM)  # "preemption notice"
        assert mgr.preemption_requested
        assert mgr.maybe_save(8, model=m)  # next step boundary: final save
        assert mgr.preemption_saved
        assert mgr.latest_step() == 8
    finally:
        mgr.close()


def test_restore_extra_state_and_missing_tensor_warns(tmp_path):
    m, _, _, _, _ = _make_trainer()
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(1, model=m, extra_state={"best_acc": 0.91})
    m2, _, _, _, _ = _make_trainer(seed=3)
    m2.extra_p = paddle.create_parameter([2], "float32")
    with pytest.warns(UserWarning, match="no tensor"):
        assert mgr.restore(model=m2) == 1
    assert mgr.restored_extra_state == {"best_acc": 0.91}
    np.testing.assert_array_equal(
        np.asarray(m2.weight._value), np.asarray(m.weight._value))


# --------------------------------------------------------------- schedulers

_SCHED_FACTORIES = [
    ("NoamDecay", lambda: opt.lr.NoamDecay(d_model=64, warmup_steps=4)),
    ("ExponentialDecay", lambda: opt.lr.ExponentialDecay(0.5, gamma=0.9)),
    ("NaturalExpDecay", lambda: opt.lr.NaturalExpDecay(0.5, 0.1)),
    ("InverseTimeDecay", lambda: opt.lr.InverseTimeDecay(0.5, 0.1)),
    ("PolynomialDecay", lambda: opt.lr.PolynomialDecay(0.5, decay_steps=6, cycle=True)),
    ("LinearWarmup", lambda: opt.lr.LinearWarmup(0.5, warmup_steps=3, start_lr=0.0, end_lr=0.5)),
    ("LinearWarmup_nested", lambda: opt.lr.LinearWarmup(
        opt.lr.MultiplicativeDecay(0.5, lr_lambda=lambda e: 0.9),
        warmup_steps=2, start_lr=0.0, end_lr=0.5)),
    ("PiecewiseDecay", lambda: opt.lr.PiecewiseDecay(boundaries=[2, 4], values=[0.5, 0.2, 0.1])),
    ("CosineAnnealingDecay", lambda: opt.lr.CosineAnnealingDecay(0.5, T_max=6)),
    ("CosineAnnealingWarmRestarts", lambda: opt.lr.CosineAnnealingWarmRestarts(0.5, T_0=3)),
    ("StepDecay", lambda: opt.lr.StepDecay(0.5, step_size=2)),
    ("MultiStepDecay", lambda: opt.lr.MultiStepDecay(0.5, milestones=[2, 4])),
    ("LambdaDecay", lambda: opt.lr.LambdaDecay(0.5, lr_lambda=lambda e: 0.95 ** e)),
    ("MultiplicativeDecay", lambda: opt.lr.MultiplicativeDecay(0.5, lr_lambda=lambda e: 0.9)),
    ("ReduceOnPlateau", lambda: opt.lr.ReduceOnPlateau(0.5, patience=1, cooldown=1)),
    ("OneCycleLR", lambda: opt.lr.OneCycleLR(max_learning_rate=0.5, total_steps=10)),
    ("CyclicLR", lambda: opt.lr.CyclicLR(base_learning_rate=0.1, max_learning_rate=0.5, step_size_up=3)),
    ("LinearLR", lambda: opt.lr.LinearLR(0.5, total_steps=6)),
    ("ConstantLR", lambda: opt.lr.ConstantLR(0.5)),
]

_PLATEAU_METRICS = [1.0, 0.9, 0.95, 0.96, 0.97, 0.98, 0.99, 1.0]


def _step_sched(s, i):
    if isinstance(s, opt.lr.ReduceOnPlateau):
        s.step(metrics=_PLATEAU_METRICS[i])
    else:
        s.step()


@pytest.mark.parametrize("name,factory", _SCHED_FACTORIES, ids=[n for n, _ in _SCHED_FACTORIES])
def test_lr_scheduler_round_trip_via_manager(tmp_path, name, factory):
    """Every scheduler survives CheckpointManager.save/restore (not just an
    in-memory dict copy): after restore, the next 4 LR values match a never-
    interrupted twin exactly — including the stateful ones (ReduceOnPlateau
    counters, MultiplicativeDecay running product, LinearWarmup's wrapped
    scheduler)."""
    ref, live = factory(), factory()
    for i in range(4):
        _step_sched(ref, i)
        _step_sched(live, i)
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(4, lr_scheduler=live)

    restored = factory()
    assert mgr.restore(lr_scheduler=restored) == 4
    for i in range(4, 8):
        _step_sched(ref, i)
        _step_sched(restored, i)
        assert restored.get_lr() == ref.get_lr(), f"{name} diverged at step {i}"


def test_lbfgs_round_trip_via_manager(tmp_path):
    """LBFGS curvature history (s/y/rho/H_diag) rides the extras file and is
    restored by the new set_state_dict: the resumed trajectory matches the
    uninterrupted one bit-for-bit."""
    x = paddle.to_tensor(np.random.RandomState(0).randn(16, 3).astype(np.float32))
    w_true = np.array([[1.5], [-2.0], [0.5]], np.float32)
    y = paddle.to_tensor(np.asarray(x._value) @ w_true)

    def make():
        paddle.seed(42)
        m = nn.Linear(3, 1)
        o = opt.LBFGS(learning_rate=0.9, max_iter=3, parameters=m.parameters())
        return m, o

    def closure_for(m, o):
        def closure():
            o.clear_grad()
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            return loss
        return closure

    m1, o1 = make()
    losses = [float(o1.step(closure_for(m1, o1))) for _ in range(4)]
    mgr = CheckpointManager(str(tmp_path), async_save=False)

    m2, o2 = make()
    for _ in range(2):
        o2.step(closure_for(m2, o2))
    mgr.save(2, model=m2, optimizer=o2)
    m3, o3 = make()
    assert mgr.restore(model=m3, optimizer=o3) == 2
    assert o3._rho_hist == o2._rho_hist and o3._H_diag == o2._H_diag
    resumed = [float(o3.step(closure_for(m3, o3))) for _ in range(2)]
    assert resumed == losses[2:], "LBFGS resume diverged (history not restored?)"


# ------------------------------------------------------- sampler / dataloader

def test_sampler_seed_regression():
    """Two differently-seeded jobs must NOT shuffle identically (the old
    RandomState(epoch) ignored the seed), while (seed, epoch) stays fully
    deterministic and epochs still reshuffle."""
    ds = _ArrayDataset(n=32)

    def order(seed, epoch):
        s = DistributedBatchSampler(ds, batch_size=4, shuffle=True, seed=seed)
        s.set_epoch(epoch)
        return [i for b in s for i in b]

    assert order(0, 0) != order(1, 0)  # seed matters
    assert order(0, 0) == order(0, 0)  # deterministic
    assert order(0, 0) != order(0, 1)  # epochs reshuffle
    assert order(5, 3) == order(5, 3)


def test_dataloader_map_style_resume():
    ds = _ArrayDataset(n=24)
    sampler = DistributedBatchSampler(ds, batch_size=4, shuffle=True, seed=3)
    dl = DataLoader(ds, batch_sampler=sampler)
    full = [np.asarray(b) for b in dl]

    it = iter(dl)
    for _ in range(2):
        next(it)
    state = dl.state_dict()
    assert state["batches_yielded"] == 2
    assert state["sampler"] == {"epoch": 0, "seed": 3}

    sampler2 = DistributedBatchSampler(ds, batch_size=4, shuffle=True, seed=999)
    dl2 = DataLoader(ds, batch_sampler=sampler2)
    dl2.set_state_dict(state)
    rest = [np.asarray(b) for b in dl2]
    assert len(rest) == len(full) - 2
    for a, b in zip(rest, full[2:]):
        np.testing.assert_array_equal(a, b)
    # the NEXT epoch starts from the top again (skip is one-shot)
    assert len(list(dl2)) == len(full)


def test_dataloader_iterable_resume():
    from paddle_tpu.io import IterableDataset

    class Stream(IterableDataset):
        def __iter__(self):
            yield from (np.full(2, i, np.float32) for i in range(10))

    dl = DataLoader(Stream(), batch_size=2)
    full = [np.asarray(b) for b in dl]
    dl2 = DataLoader(Stream(), batch_size=2)
    dl2.set_state_dict({"batches_yielded": 3})
    rest = [np.asarray(b) for b in dl2]
    for a, b in zip(rest, full[3:]):
        np.testing.assert_array_equal(a, b)
    assert len(rest) == len(full) - 3


# ------------------------------------------------------------ stats plumbing

def test_checkpoint_stats_and_summary_footer(tmp_path):
    import paddle_tpu.profiler as profiler
    from paddle_tpu.profiler.statistics import checkpoint_line

    assert checkpoint_line(manager_mod._zero_stats()) == ""

    m, _, _, _, _ = _make_trainer()
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(1, model=m)
    mgr.restore(model=m)
    stats = profiler.checkpoint_stats()
    assert stats["saves"] >= 1 and stats["commits"] >= 1 and stats["restores"] >= 1
    assert stats["bytes_written"] > 0
    line = checkpoint_line(stats)
    assert line.startswith("Checkpoint:") and "restores=" in line

    prof = profiler.Profiler()
    prof.start()
    prof.stop()
    assert "Checkpoint:" in prof.summary()


# ------------------------------------------- save_state_dict async (satellite)

def test_save_state_dict_async_reraises(tmp_path, monkeypatch):
    """The old async path was a fire-and-forget daemon thread: failures
    vanished.  Now wait_async_save() re-raises them."""
    import paddle_tpu.distributed.checkpoint as ckpt
    from paddle_tpu.framework import io_utils

    sd = {"w": paddle.to_tensor(np.ones((2, 2), np.float32))}

    def boom(*a, **kw):
        raise OSError("shard write failed")

    monkeypatch.setattr(ckpt.np, "savez", boom)
    th = ckpt.save_state_dict(sd, str(tmp_path), async_save=True)
    th.join(timeout=30)
    with pytest.raises(RuntimeError, match="async checkpoint write") as exc:
        io_utils.wait_async_save()
    assert "shard write failed" in str(exc.value.__cause__)


def test_save_state_dict_atomic_metadata(tmp_path, monkeypatch):
    """A failed re-save can never tear an existing metadata.json: the write
    goes to a temp file that is os.replace'd only on success."""
    import paddle_tpu.distributed.checkpoint as ckpt

    sd = {"w": paddle.to_tensor(np.ones((2, 2), np.float32))}
    ckpt.save_state_dict(sd, str(tmp_path))
    good = (tmp_path / "metadata.json").read_text()

    monkeypatch.setattr(ckpt.Metadata, "to_json", lambda self: (_ for _ in ()).throw(OSError("meta boom")))
    with pytest.raises(OSError, match="meta boom"):
        ckpt.save_state_dict(sd, str(tmp_path))
    assert (tmp_path / "metadata.json").read_text() == good
    assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]
