"""REAL multi-controller collectives: 2 local processes, jax.distributed +
gloo CPU collectives, the eager ProcessGroup ring (reference test pattern:
TestDistBase/start_local_trainers spawning workers over localhost NCCL,
test/legacy_test/test_dist_base.py:962)."""

import os
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    rank, world, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port, num_processes=world, process_id=rank
    )
    sys.path.insert(0, "__REPO__")
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.distributed.collective import ProcessGroup

    pg = ProcessGroup()
    assert pg.nranks == world
    t = pg.allreduce(jnp.full((4,), float(rank + 1), jnp.float32))
    t.wait()
    assert np.allclose(np.asarray(t.result()), sum(range(1, world + 1)))
    g = np.asarray(pg.allgather(jnp.full((2,), float(rank), jnp.float32)).result())
    assert np.allclose(g[:, 0], np.arange(world))
    b = np.asarray(pg.broadcast(jnp.full((2,), float(rank), jnp.float32), src=1).result())
    assert np.allclose(b, 1.0)
    # executable cache reuse across calls: repeating a shape adds no entry
    before = pg.cache_size()
    pg.allreduce(jnp.ones((4,), jnp.float32)).wait()
    assert pg.cache_size() == before, (before, pg.cache_size())

    # the public communication API routes its multi-process branch here
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    t = paddle.to_tensor(np.full(3, float(rank + 1), np.float32))
    dist.all_reduce(t)
    assert np.allclose(np.asarray(t._value), 3.0)

    # Fleet-style imperative multi-controller DP: each rank computes grads
    # on its batch shard, grad-allreduce(avg), identical updates everywhere
    import paddle_tpu.nn as nn

    paddle.seed(0)  # same init on both ranks
    model = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
    rng = np.random.default_rng(rank)  # DIFFERENT data per rank
    x = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    y = paddle.to_tensor(np.zeros((8, 1), np.float32))
    losses = []
    for step in range(4):
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        for p in model.parameters():
            dist.all_reduce(p.grad, op=dist.ReduceOp.AVG)
        opt.step()
        opt.clear_grad()
        losses.append(float(loss._value))
    # weights must be bit-identical across ranks after synced updates
    wsum = float(np.asarray(model.parameters()[0]._value).sum())
    t2 = paddle.to_tensor(np.full(1, wsum, np.float32))
    dist.all_reduce(t2, op=dist.ReduceOp.MAX)
    assert abs(float(t2._value[0]) - wsum) < 1e-6, "weights diverged across ranks"
    assert losses[-1] < losses[0]

    # ---- point-to-point: ProcessGroup send/recv (ppermute pair) ----
    if rank == 0:
        pg.send(jnp.arange(4, dtype=jnp.float32), dst=1)
    else:
        got = pg.recv(jnp.zeros((4,), jnp.float32), src=0)
        assert np.allclose(np.asarray(got.result()), np.arange(4.0)), np.asarray(got.result())

    # public isend/irecv API
    if rank == 0:
        dist.isend(paddle.to_tensor(np.full(3, 7.0, np.float32)), dst=1).wait()
    else:
        t3 = paddle.to_tensor(np.zeros(3, np.float32))
        dist.irecv(t3, src=0).wait()
        assert np.allclose(np.asarray(t3._value), 7.0)

    # batch_isend_irecv ring exchange (both ranks send AND receive)
    from paddle_tpu.distributed.collective import P2POp, batch_isend_irecv

    peer = 1 - rank
    send_t = paddle.to_tensor(np.full(2, float(rank), np.float32))
    recv_t = paddle.to_tensor(np.zeros(2, np.float32))
    for task in batch_isend_irecv([
        P2POp("isend", send_t, peer), P2POp("irecv", recv_t, peer)
    ]):
        task.wait()
    assert np.allclose(np.asarray(recv_t._value), float(peer)), np.asarray(recv_t._value)

    # scatter: each rank keeps src 0's chunk for its index
    sc = pg.scatter(jnp.arange(4, dtype=jnp.float32), src=0)
    assert np.allclose(np.asarray(sc.result()), [2.0 * rank, 2.0 * rank + 1])

    # alltoall: chunk i of my input goes to rank i
    at = pg.alltoall(jnp.asarray([rank * 10.0, rank * 10.0 + 1], jnp.float32))
    assert np.allclose(np.asarray(at.result()), [float(rank), 10.0 + rank]), np.asarray(at.result())
    print("rank " + str(rank) + " OK", flush=True)
    """
)


@pytest.mark.slow
def test_two_process_process_group(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.replace("__REPO__", repo))
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        free_port = s.getsockname()[1]
    world, port = 2, str(free_port)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers manage their own platform config
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(r), str(world), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for r in range(world)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=480)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append((p.returncode, out))
    for rc, out in outs:
        assert rc == 0, out[-2000:]
    assert any("rank 0 OK" in o for _, o in outs)
    assert any("rank 1 OK" in o for _, o in outs)


_SPMD_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    rank, world, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize("127.0.0.1:" + port, num_processes=world, process_id=rank)
    assert jax.device_count() == 8 and jax.local_device_count() == 4
    sys.path.insert(0, "__REPO__")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed import ProcessMesh, ShardedTrainStep
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny, shard_gpt

    paddle.seed(0)
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
    model = shard_gpt(GPTForCausalLM(gpt_tiny()), mesh)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, opt, lambda m, i: m(i, labels=i)[0], mesh)
    rng = np.random.default_rng(0)  # same global batch on all procs
    ids = paddle.to_tensor(rng.integers(0, 512, (4, 32)).astype(np.int32))
    losses = [float(step(ids).astype("float32")) for _ in range(3)]
    assert losses[-1] < losses[0], losses
    print("rank " + str(rank) + " SPMD " + ",".join(f"{l:.6f}" for l in losses), flush=True)
    """
)


@pytest.mark.slow
def test_two_process_global_mesh_spmd_training(tmp_path):
    """TRUE multi-host SPMD: 2 processes x 4 virtual devices = one 8-device
    GLOBAL mesh; the sharded GPT train step (dp2 x mp4) compiles and runs
    across processes with identical losses on every rank — the production
    multi-controller GSPMD path (SURVEY §4: fake-cluster CI strategy)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "spmd_worker.py"
    script.write_text(_SPMD_WORKER.replace("__REPO__", repo))
    import socket

    with socket.socket() as s:  # grab a free port; stale 29791 binds hung this test
        s.bind(("127.0.0.1", 0))
        free_port = s.getsockname()[1]
    world, port = 2, str(free_port)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(r), str(world), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for r in range(world)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=700)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append((p.returncode, out))
    for rc, out in outs:
        assert rc == 0, out[-2000:]
    lines = [l for _, o in outs for l in o.splitlines() if "SPMD" in l]
    assert len(lines) == 2
    # identical loss trajectories on both ranks
    assert lines[0].split("SPMD")[1] == lines[1].split("SPMD")[1], lines


_HANG_WORKER = textwrap.dedent(
    """
    import os, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    rank, world, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port, num_processes=world, process_id=rank
    )
    sys.path.insert(0, "__REPO__")
    import jax.numpy as jnp
    from paddle_tpu.distributed.collective import ProcessGroup

    jax.devices()  # gloo client creation itself rendezvouses: init BOTH ranks
    if rank == 1:
        # backend up, but never joins the collective: a stuck/dead peer
        time.sleep(30)
        sys.exit(0)
    pg = ProcessGroup()
    pg.allreduce(jnp.ones((4,), jnp.float32)).wait()  # hangs forever
    print("UNREACHABLE", flush=True)
    """
)


@pytest.mark.slow
def test_watchdog_aborts_hung_collective(tmp_path):
    """Reference comm_task_manager.h:37 + FLAGS_enable_async_trace: a rank
    stuck in a collective whose peer never arrives gets a loud watchdog
    report (op name, group, elapsed, creation stack) and an abort instead
    of an indefinite hang."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "hang_worker.py"
    script.write_text(_HANG_WORKER.replace("__REPO__", repo))
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        free_port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["FLAGS_comm_timeout_s"] = "6"
    env["FLAGS_comm_timeout_abort"] = "1"
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(r), "2", str(free_port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for r in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append((p.returncode, out))
    rc0, out0 = outs[0]
    assert rc0 == 124, (rc0, out0[-2000:])
    assert "comm watchdog" in out0
    assert "allreduce" in out0
    assert "Task created at" in out0
    assert "UNREACHABLE" not in out0


_P2P_PATTERN_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    rank, world, port, store_port = (int(sys.argv[1]), int(sys.argv[2]),
                                     sys.argv[3], sys.argv[4])
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port, num_processes=world, process_id=rank
    )
    sys.path.insert(0, "__REPO__")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed.bootstrap import host_or_connect, store_barrier
    from paddle_tpu.distributed.communication.watchdog import set_rendezvous_store
    from paddle_tpu.distributed.collective import (
        P2POp, UnmatchedP2PError, batch_isend_irecv, _coordinated_batch,
    )

    server, client = host_or_connect("127.0.0.1:" + store_port, rank == 0)
    set_rendezvous_store(client)
    peer = 1 - rank

    def T(fill):
        return paddle.to_tensor(np.full(3, float(fill), np.float32))

    # ---- four-directions-style pattern, DIFFERENTLY-ORDERED lists ----
    # two transfers each way; rank1's list interleaves recv/send in a
    # different order than rank0's send/send/recv/recv
    s_n, s_s = T(10 * rank + 1), T(10 * rank + 2)
    r_n, r_s = T(0), T(0)
    if rank == 0:
        ops = [P2POp("isend", s_n, 1), P2POp("isend", s_s, 1),
               P2POp("irecv", r_n, 1), P2POp("irecv", r_s, 1)]
    else:
        ops = [P2POp("irecv", r_n, 0), P2POp("isend", s_n, 0),
               P2POp("irecv", r_s, 0), P2POp("isend", s_s, 0)]
    for t in batch_isend_irecv(ops):
        t.wait()
    # FIFO per directed pair: first recv matches first send
    assert np.allclose(np.asarray(r_n._value), 10 * peer + 1), np.asarray(r_n._value)
    assert np.allclose(np.asarray(r_s._value), 10 * peer + 2), np.asarray(r_s._value)

    # ---- partially-overlapping batches: one batch vs two calls ----
    a, b = T(100 + rank), T(0)
    if rank == 0:
        for t in batch_isend_irecv([P2POp("isend", a, 1), P2POp("irecv", b, 1)]):
            t.wait()
    else:
        for t in batch_isend_irecv([P2POp("irecv", b, 0)]):
            t.wait()
        for t in batch_isend_irecv([P2POp("isend", a, 0)]):
            t.wait()
    assert np.allclose(np.asarray(b._value), 100 + peer), np.asarray(b._value)

    # ---- MIRROR overlap: the sender side splits across two calls ----
    c, d = T(200 + rank), T(0)
    if rank == 0:
        for t in batch_isend_irecv([P2POp("isend", c, 1), P2POp("irecv", d, 1)]):
            t.wait()
    else:
        for t in batch_isend_irecv([P2POp("isend", c, 0)]):
            t.wait()
        for t in batch_isend_irecv([P2POp("irecv", d, 0)]):
            t.wait()
    assert np.allclose(np.asarray(d._value), 200 + peer), np.asarray(d._value)

    # ---- genuinely unmatched: LOUD error, not a hang ----
    if rank == 0:
        try:
            _coordinated_batch([P2POp("irecv", T(0), 1)], client, 0,
                               timeout_ms=2000)
            raise SystemExit("expected UnmatchedP2PError")
        except UnmatchedP2PError as e:
            assert "no counterpart" in str(e)
    store_barrier(client, "p2p_probe_done", world)

    # ---- after the failed probe, the SAME direction still matches ----
    # (tag rollback: the probe must not desync the FIFO counters)
    e_, f_ = T(300 + rank), T(0)
    if rank == 0:
        ops2 = [P2POp("irecv", f_, 1)]
    else:
        ops2 = [P2POp("isend", e_, 0)]
    for t in batch_isend_irecv(ops2):
        t.wait()
    if rank == 0:
        assert np.allclose(np.asarray(f_._value), 301.0), np.asarray(f_._value)
    store_barrier(client, "p2p_done", world)
    print("rank " + str(rank) + " P2P OK", flush=True)
    """
)


@pytest.mark.slow
def test_two_process_unmatched_p2p_patterns(tmp_path):
    """VERDICT r3 #9: store-coordinated batch p2p resolves differently-
    ordered and partially-overlapping send/recv patterns (four-directions
    capability) and raises loudly on a missing counterpart."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "p2p_worker.py"
    script.write_text(_P2P_PATTERN_WORKER.replace("__REPO__", repo))
    import socket

    ports = []
    for _ in range(2):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
    world = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(r), str(world), str(ports[0]), str(ports[1])],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for r in range(world)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=480)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append((p.returncode, out))
    for rc, out in outs:
        assert rc == 0, out[-2000:]
    assert any("rank 0 P2P OK" in o for _, o in outs)
    assert any("rank 1 P2P OK" in o for _, o in outs)
