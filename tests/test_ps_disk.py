"""Persistent/async PS tier: SSDSparseTable (ssd_sparse_table.cc analog),
AsyncPsClient staleness bound, GeoPsClient delta training, and the
crash-resume story over a 10M-row id space.

Reference: paddle/fluid/distributed/ps/table/ssd_sparse_table.cc (rocksdb
tier + memory cache), async/geo update modes of the PS services.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.distributed.ps import (
    AsyncPsClient, GeoPsClient, PsClient, SSDSparseTable, SparseTable,
)


def test_ssd_table_matches_memory_table(tmp_path):
    mem = SparseTable(8, optimizer="adagrad", lr=0.05)
    ssd = SSDSparseTable(8, str(tmp_path / "t"), optimizer="adagrad", lr=0.05)
    rng = np.random.default_rng(0)
    for _ in range(5):
        ids = rng.integers(0, 50, 16)
        np.testing.assert_allclose(mem.pull(ids), ssd.pull(ids), atol=1e-7)
        g = rng.standard_normal((16, 8)).astype(np.float32)
        mem.push(ids, g)
        ssd.push(ids, g)
    ids = np.arange(50)
    np.testing.assert_allclose(mem.pull(ids), ssd.pull(ids), atol=1e-6)
    assert mem.n_rows() == ssd.n_rows()


def test_ssd_lru_bounded_and_evictions_persist(tmp_path):
    ssd = SSDSparseTable(4, str(tmp_path / "t"), cache_rows=32, lr=0.1,
                         optimizer="sgd")
    first = ssd.pull(np.arange(16)).copy()
    ssd.push(np.arange(16), np.ones((16, 4), np.float32))
    ssd.pull(np.arange(16, 200))  # force way past the cache budget
    assert ssd.cached_rows() <= 32
    # evicted dirty rows round-trip from disk with the update applied
    np.testing.assert_allclose(ssd.pull(np.arange(16)), first - 0.1, atol=1e-6)


def test_ssd_reopen_rebuilds_index_and_truncates_torn_record(tmp_path):
    path = str(tmp_path / "t")
    ssd = SSDSparseTable(4, path, n_buckets=2, lr=0.1)
    vals = ssd.pull(np.arange(10)).copy()
    ssd.close()
    # simulate a crash that tore the last record of bucket 0
    b0 = os.path.join(path, "bucket_0000.bin")
    with open(b0, "ab") as f:
        f.write(b"\x01" * 11)
    re = SSDSparseTable(4, path, n_buckets=2, lr=0.1)
    np.testing.assert_allclose(re.pull(np.arange(10)), vals, atol=1e-7)
    assert os.path.getsize(b0) % re._buckets[0].rec_size == 0


_CRASH_WORKER = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, "__REPO__")
    import numpy as np
    from paddle_tpu.distributed.ps import SSDSparseTable

    path, phase = sys.argv[1], sys.argv[2]
    # 10M-row id space, sparse touch; write_through => every push durable
    t = SSDSparseTable(16, path, optimizer="adagrad", lr=0.05,
                       write_through=True, cache_rows=4096)
    rng = np.random.default_rng(7)
    steps = range(0, 6) if phase == "crash" else range(6, 12)
    # id stream is deterministic: consume the prefix this phase skips
    for s in range(12):
        ids = rng.integers(0, 10_000_000, 64)
        g = rng.standard_normal((64, 16)).astype(np.float32)
        if s in steps:
            t.push(ids, g)
            print(f"pushed {s}", flush=True)
    if phase == "crash":
        os._exit(9)  # kill -9 analog: no flush, no close
    t.close()
    print("DONE", flush=True)
    """
)


@pytest.mark.slow
def test_ssd_crash_resume_identical_convergence(tmp_path):
    """train -> kill -9 -> resume; the resumed run's final table must be
    IDENTICAL to an uninterrupted oracle run (write-through durability +
    crash-rebuilt index)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "crash_worker.py"
    script.write_text(_CRASH_WORKER.replace("__REPO__", repo))

    crash_dir = str(tmp_path / "crash")
    r1 = subprocess.run([sys.executable, str(script), crash_dir, "crash"],
                        capture_output=True, text=True, timeout=300)
    assert r1.returncode == 9 and "pushed 5" in r1.stdout, r1.stdout
    r2 = subprocess.run([sys.executable, str(script), crash_dir, "resume"],
                        capture_output=True, text=True, timeout=300)
    assert r2.returncode == 0 and "DONE" in r2.stdout, r2.stdout

    crashed = SSDSparseTable(16, crash_dir, write_through=True)
    # oracle: the same 12-step stream applied without any crash
    oracle_t = SSDSparseTable(16, str(tmp_path / "oracle"),
                              optimizer="adagrad", lr=0.05)
    rng = np.random.default_rng(7)
    for s in range(12):
        ids = rng.integers(0, 10_000_000, 64)
        g = rng.standard_normal((64, 16)).astype(np.float32)
        oracle_t.push(ids, g)
    assert crashed.n_rows() == oracle_t.n_rows()
    sample = sorted(oracle_t.state_dict()["rows"])[:500]
    np.testing.assert_allclose(
        crashed.pull(np.asarray(sample)), oracle_t.pull(np.asarray(sample)),
        atol=1e-6)


def test_async_client_staleness_bound_and_final_state(tmp_path):
    table = SparseTable(4, optimizer="sgd", lr=0.1)
    sync_table = SparseTable(4, optimizer="sgd", lr=0.1)
    a = AsyncPsClient(PsClient(table=table), max_staleness=2)
    rng = np.random.default_rng(1)
    for _ in range(50):
        ids = rng.integers(0, 20, 8)
        g = rng.standard_normal((8, 4)).astype(np.float32)
        # pull-then-push on BOTH (push ignores never-pulled rows)
        a.pull(ids)
        sync_table.pull(ids)
        a.push(ids, g)
        sync_table.push(ids, g)
        assert a.pending() <= 2 + 1  # the bound (one may be mid-apply)
    a.wait()
    ids = np.arange(20)
    np.testing.assert_allclose(table.pull(ids), sync_table.pull(ids), atol=1e-5)
    a.close()


def test_geo_client_delta_push_converges(tmp_path):
    glob = SparseTable(4, optimizer="sgd", lr=1.0)  # geo merges raw deltas
    geo = GeoPsClient(PsClient(table=glob), dim=4, geo_steps=4, lr=0.1)
    rng = np.random.default_rng(3)
    ids = np.arange(8)
    target = rng.standard_normal((8, 4)).astype(np.float32)
    for _ in range(40):
        cur = geo.pull(ids)
        geo.push(ids, (cur - target).astype(np.float32))  # grad of 0.5||w-t||^2
    geo.sync()
    final = glob.pull(ids)
    assert np.abs(final - target).mean() < 0.05, np.abs(final - target).mean()


def test_geo_push_only_rows_propagate():
    """Rows FIRST touched via push() — never pulled through the geo client
    — must still reach the global table on sync().  They previously never
    entered _base (only the wrapped pull seeded it), so sync() skipped
    them forever and their training was silently lost (ADVICE.md)."""
    glob = SparseTable(4, optimizer="sgd", lr=1.0)
    ids = np.arange(5)
    before = glob.pull(ids).copy()  # materialize + snapshot global rows
    geo = GeoPsClient(PsClient(table=glob), dim=4, geo_steps=100, lr=0.5)
    g = np.ones((5, 4), np.float32)
    geo.push(ids, g)  # push-only: no prior geo.pull for these rows
    geo.sync()
    after = glob.pull(ids)
    # local applied -lr*g to the pulled base; the delta push must land it
    np.testing.assert_allclose(after, before - 0.5 * g, atol=1e-6)
    # and the rows keep training through the normal pull/push cycle
    cur = geo.pull(ids)
    geo.push(ids, np.full((5, 4), -1.0, np.float32))
    geo.sync()
    np.testing.assert_allclose(glob.pull(ids), cur + 0.5, atol=1e-6)


def test_sparse_embedding_over_ssd_table(tmp_path):
    """Integration: the lookup-table layer trains against the DISK tier."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu.distributed.ps import SparseEmbedding

    table = SSDSparseTable(8, str(tmp_path / "emb"), optimizer="adagrad",
                           lr=0.2, cache_rows=64)
    emb = SparseEmbedding(PsClient(table=table), dim=8)
    ids = paddle.to_tensor(np.arange(16, dtype=np.int64))
    target = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((16, 8)).astype(np.float32))
    losses = []
    for _ in range(30):
        out = emb(ids)
        loss = ((out - target) ** 2).mean()
        loss.backward()
        losses.append(float(loss._value))
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])
    table.flush()
    # rows survived on disk
    re = SSDSparseTable(8, str(tmp_path / "emb"), optimizer="adagrad", lr=0.2)
    assert re.n_rows() == 16


def test_push_delta_over_rpc():
    """Geo-SGD's delta protocol round-trips through the rpc tier too."""
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import PsServer

    rpc.init_rpc("ps_geo0", rank=0, world_size=1,
                 master_endpoint="127.0.0.1:29637")
    try:
        PsServer.register_table(SparseTable(dim=4, name="emb_geo_rpc"))
        client = PsClient(server="ps_geo0", table_name="emb_geo_rpc")
        before = client.pull([7]).copy()
        client.push_delta([7], np.full((1, 4), 0.25, np.float32))
        after = client.pull([7])
        np.testing.assert_allclose(after, before - 0.25, atol=1e-6)
    finally:
        rpc.shutdown()


def test_async_client_surfaces_worker_errors(tmp_path):
    class Boom:
        def push(self, ids, grads):
            raise RuntimeError("table exploded")

        def pull(self, ids):
            return np.zeros((len(np.atleast_1d(ids)), 4), np.float32)

    a = AsyncPsClient(Boom(), max_staleness=8)
    a.push([1], np.ones((1, 4), np.float32))
    with pytest.raises(RuntimeError, match="table exploded"):
        a.wait()
    a.close()


def test_ssd_state_dict_roundtrip(tmp_path):
    src = SSDSparseTable(4, str(tmp_path / "a"), optimizer="adagrad", lr=0.1)
    src.pull(np.arange(6))
    src.push(np.arange(6), np.ones((6, 4), np.float32))
    state = src.state_dict()
    dst = SSDSparseTable(4, str(tmp_path / "b"), optimizer="adagrad", lr=0.1)
    dst.set_state_dict(state)
    np.testing.assert_allclose(dst.pull(np.arange(6)), src.pull(np.arange(6)),
                               atol=1e-7)
    # adagrad accumulators restored too: next identical push matches
    src.push([0], np.ones((1, 4), np.float32))
    dst.push([0], np.ones((1, 4), np.float32))
    np.testing.assert_allclose(dst.pull([0]), src.pull([0]), atol=1e-7)
