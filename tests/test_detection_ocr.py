"""PP-YOLOE-class detector + PP-OCR-class recognizer (BASELINE.md rows).

Reference lineage: the PP-YOLO family (yolo_box decode,
paddle/phi/kernels/gpu/yolo_box_kernel.cu) and the PP-OCR recognition
pipeline (CRNN + warpctc, paddle/phi/kernels/gpu/warpctc_kernel.cu).
"""

import numpy as np
import pytest
import jax

import paddle_tpu as paddle
from paddle_tpu.vision.models import (
    CRNN, PPYoloDet, ctc_greedy_decode, ppocr_rec_tiny, ppyolo_tiny,
)


def test_detector_forward_shapes_and_decode():
    paddle.seed(0)
    model = ppyolo_tiny(num_classes=4)
    model.eval()
    B, H = 2, 64
    x = paddle.randn([B, 3, H, H])
    with paddle.no_grad():
        outs = model(x)
    assert len(outs) == 3
    per_anchor = 3
    for out, ds in zip(outs, model.downsample_ratios):
        assert tuple(out.shape) == (B, per_anchor * (5 + 4), H // ds, H // ds)
    boxes, scores = model.decode(outs, H)
    n = sum(per_anchor * (H // d) ** 2 for d in model.downsample_ratios)
    assert tuple(boxes.shape) == (B, n, 4)
    assert tuple(scores.shape) == (B, n, 4)  # [B, N, num_classes]
    assert np.isfinite(np.asarray(boxes._value)).all()


def test_detector_trains_and_jits():
    """A dense regression objective over the head maps decreases under the
    compiled TrainStep (detection-loss plumbing is model-external, like the
    reference's separate loss modules)."""
    from paddle_tpu.jit import TrainStep

    paddle.seed(1)
    model = ppyolo_tiny(num_classes=2)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.normal(size=(2, 3, 64, 64)).astype(np.float32))

    def loss_fn(m, xb):
        outs = m(xb)
        return sum((o ** 2).mean() for o in outs)

    step = TrainStep(model, opt, loss_fn)
    losses = [float(step(x)._value) for _ in range(4)]
    assert losses[-1] < losses[0], losses


def test_crnn_shapes_ctc_loss_and_decode():
    paddle.seed(3)
    model = ppocr_rec_tiny(num_classes=10)
    model.eval()
    B, W = 2, 64
    x = paddle.randn([B, 3, 32, W])
    with paddle.no_grad():
        logp = model(x)
    assert tuple(logp.shape) == (B, W // 4, 11)
    # log-softmax rows sum to 1
    np.testing.assert_allclose(
        np.exp(np.asarray(logp._value)).sum(-1), 1.0, rtol=1e-4)

    labels = paddle.to_tensor(np.array([[1, 2, 3], [4, 5, 0]], np.int64))
    lens = paddle.to_tensor(np.array([3, 2], np.int64))
    loss = model.loss(logp, labels, lens)
    assert np.isfinite(float(loss._value)) and float(loss._value) > 0

    decoded = ctc_greedy_decode(logp)
    assert len(decoded) == B and all(isinstance(s, list) for s in decoded)


@pytest.mark.slow  # 121s: 60 eager train iterations to convergence — the
# heaviest single test in the fast tier (--durations); CRNN shape/CTC-loss/
# decode coverage stays fast via the two sibling tests below
def test_crnn_overfits_one_sample():
    """CTC training drives the greedy decode to the target sequence on a
    single fixed input — end-to-end recognition learning."""
    paddle.seed(5)
    model = ppocr_rec_tiny(num_classes=6)
    opt = paddle.optimizer.AdamW(5e-3, parameters=model.parameters())
    rng = np.random.default_rng(2)
    x = paddle.to_tensor(rng.normal(size=(1, 3, 32, 48)).astype(np.float32))
    target = [2, 4, 1]
    labels = paddle.to_tensor(np.array([target], np.int64))
    lens = paddle.to_tensor(np.array([3], np.int64))

    losses = []
    for _ in range(60):
        logp = model(x)
        loss = model.loss(logp, labels, lens)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss._value))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    model.eval()
    with paddle.no_grad():
        decoded = ctc_greedy_decode(model(x))
    assert decoded[0] == target, (decoded, target)


def test_ctc_loss_matches_torch_oracle():
    """ctc_loss forward AND gradient against torch.nn.functional.ctc_loss
    (reference kernel lineage: warpctc)."""
    import torch
    import jax.numpy as jnp
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(7)
    T, B, C = 10, 4, 6
    logits = rng.normal(size=(T, B, C)).astype(np.float32)
    labels = np.array([[2, 4, 1], [3, 3, 0], [5, 0, 0], [0, 0, 0]], np.int64)
    llens = np.array([3, 2, 1, 0], np.int64)   # incl. an EMPTY target
    ilens = np.array([10, 8, 10, 6], np.int64)

    lp_t = torch.log_softmax(torch.tensor(logits, requires_grad=True), dim=-1)
    lp_t.retain_grad()
    ref = torch.nn.functional.ctc_loss(
        lp_t, torch.tensor(labels), torch.tensor(ilens), torch.tensor(llens),
        blank=0, reduction="mean", zero_infinity=False)
    ref.backward()

    def ours(lg):
        lp = jax.nn.log_softmax(lg, axis=-1)
        with paddle.no_grad():
            return F.ctc_loss(
                paddle.Tensor(lp), paddle.to_tensor(labels),
                paddle.to_tensor(ilens), paddle.to_tensor(llens), blank=0,
                reduction="mean")._value

    got = float(ours(jnp.asarray(logits)))
    np.testing.assert_allclose(got, float(ref), rtol=1e-4, atol=1e-5)
    g = jax.grad(lambda lg: ours(lg))(jnp.asarray(logits))
    assert np.isfinite(np.asarray(g)).all()
    # torch grads flow to raw logits through its own log_softmax; compare
    # against torch's logits-gradient for the full chain
    torch_logits = torch.tensor(logits, requires_grad=True)
    ref2 = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch_logits, dim=-1), torch.tensor(labels),
        torch.tensor(ilens), torch.tensor(llens), blank=0, reduction="mean")
    ref2.backward()
    np.testing.assert_allclose(np.asarray(g), torch_logits.grad.numpy(),
                               rtol=2e-3, atol=2e-4)
