"""Widened paddle.static.nn roster (reference python/paddle/static/nn/
__init__.py __all__): dense layer functions + the TPU-native sequence
(LoD) tier over explicit offsets (reference sequence_lod.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static.nn as snn


def _t(a):
    return paddle.to_tensor(np.asarray(a))


# ---------------------------------------------------------------- dense tier


def test_dense_layer_functions_shapes_and_finiteness():
    paddle.seed(0)
    rng = np.random.default_rng(0)
    x4 = _t(rng.standard_normal((2, 3, 8, 8)).astype("float32"))
    assert snn.conv2d_transpose(x4, 5, 3).shape[1] == 5
    assert snn.group_norm(x4, groups=3).shape == x4.shape
    assert snn.instance_norm(x4).shape == x4.shape
    assert snn.layer_norm(x4, begin_norm_axis=1).shape == x4.shape
    assert snn.prelu(x4, "channel").shape == x4.shape
    x5 = _t(rng.standard_normal((1, 2, 4, 6, 6)).astype("float32"))
    assert snn.conv3d(x5, 3, 3).shape[1] == 3
    assert snn.conv3d_transpose(x5, 3, 3).shape[1] == 3
    w = _t(rng.standard_normal((6, 4)).astype("float32"))
    sn_w = snn.spectral_norm(w)
    assert sn_w.shape == w.shape
    # spectral norm scales the top singular value to ~1
    s = np.linalg.svd(np.asarray(sn_w._value), compute_uv=False)
    assert s[0] < 2.0
    x2 = _t(rng.standard_normal((4, 6)).astype("float32"))
    y2 = _t(rng.standard_normal((4, 3)).astype("float32"))
    assert tuple(snn.bilinear_tensor_product(x2, y2, 5).shape) == (4, 5)
    dn = snn.data_norm(x2)
    assert dn.shape == x2.shape and np.isfinite(np.asarray(dn._value)).all()
    ids = _t(rng.integers(0, 10, (4, 3)).astype("int64"))
    emb = snn.sparse_embedding(ids, size=[10, 6])
    assert tuple(emb.shape) == (4, 3, 6)


def test_prelu_matches_definition():
    x = _t(np.array([[-2.0, 3.0]], dtype="float32"))
    out = snn.prelu(x, "all")
    np.testing.assert_allclose(np.asarray(out._value),
                               [[-0.5, 3.0]], rtol=1e-6)  # alpha init 0.25


def test_nce_loss_positive_and_grad():
    paddle.seed(1)
    rng = np.random.default_rng(1)
    x = paddle.to_tensor(rng.standard_normal((4, 8)).astype("float32"),
                         stop_gradient=False)
    y = _t(rng.integers(0, 20, (4, 1)).astype("int64"))
    loss = snn.nce(x, y, num_total_classes=20, num_neg_samples=5, seed=7)
    assert tuple(loss.shape) == (4, 1)
    vals = np.asarray(loss._value)
    assert (vals > 0).all() and np.isfinite(vals).all()
    loss.sum().backward()
    assert np.abs(np.asarray(x.grad._value)).max() > 0


def test_row_conv_dense_lookahead():
    x = _t(np.ones((1, 4, 2), dtype="float32"))
    paddle.seed(2)
    out = snn.row_conv(x, future_context_size=1)
    v = np.asarray(out._value)
    assert v.shape == (1, 4, 2)
    # last timestep sees only itself (no future), so differs from interior
    assert not np.allclose(v[0, -1], v[0, 0])


def test_static_pylayer_custom_backward():
    x = paddle.to_tensor(np.array([1.0, 2.0], "float32"), stop_gradient=False)
    out = snn.static_pylayer(
        lambda a: a * 2.0, [x],
        backward_fn=lambda a, g: g * 10.0)  # deliberately not the true vjp
    np.testing.assert_allclose(np.asarray(out._value), [2.0, 4.0])
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), [10.0, 10.0])


# -------------------------------------------------------------- sequence tier


def _flat_and_lod():
    # sequences: [0..2], [3..6], [] , [7]
    x = np.arange(8, dtype="float32").reshape(8, 1)
    lod = np.array([0, 3, 7, 7, 8])
    return _t(x), _t(lod), lod


def test_sequence_requires_lod():
    x, _, _ = _flat_and_lod()
    with pytest.raises(ValueError, match="lod"):
        snn.sequence_softmax(x)


def test_sequence_softmax_and_pool():
    x, lod, lod_np = _flat_and_lod()
    sm = np.asarray(snn.sequence_softmax(x, lod=lod)._value).ravel()
    for s, e in zip(lod_np[:-1], lod_np[1:]):
        if e > s:
            np.testing.assert_allclose(sm[s:e].sum(), 1.0, rtol=1e-5)
    pooled = np.asarray(snn.sequence_pool(x, "sum", lod=lod)._value).ravel()
    np.testing.assert_allclose(pooled, [0 + 1 + 2, 3 + 4 + 5 + 6, 0.0, 7.0])
    mx = np.asarray(snn.sequence_pool(x, "max", lod=lod,
                                      pad_value=-1.0)._value).ravel()
    np.testing.assert_allclose(mx, [2.0, 6.0, -1.0, 7.0])
    first = np.asarray(snn.sequence_first_step(x, lod=lod)._value).ravel()
    last = np.asarray(snn.sequence_last_step(x, lod=lod)._value).ravel()
    np.testing.assert_allclose(first[[0, 1, 3]], [0.0, 3.0, 7.0])
    np.testing.assert_allclose(last[[0, 1, 3]], [2.0, 6.0, 7.0])


def test_sequence_reverse_pad_unpad_roundtrip():
    x, lod, lod_np = _flat_and_lod()
    rev = np.asarray(snn.sequence_reverse(x, lod=lod)._value).ravel()
    np.testing.assert_allclose(rev, [2, 1, 0, 6, 5, 4, 3, 7])
    padded, lens = snn.sequence_pad(x, _t(np.float32(-9.0)), lod=lod)
    p = np.asarray(padded._value)
    assert p.shape == (4, 4, 1)
    np.testing.assert_allclose(p[0].ravel(), [0, 1, 2, -9])
    np.testing.assert_allclose(np.asarray(lens._value), [3, 4, 0, 1])
    flat, lod2 = snn.sequence_unpad(padded, lens)
    np.testing.assert_allclose(np.asarray(flat._value), np.asarray(x._value))
    np.testing.assert_allclose(np.asarray(lod2._value), lod_np)


def test_sequence_concat_slice_expand():
    a = _t(np.array([[1.0], [2.0], [3.0]], "float32"))
    a_lod = _t(np.array([0, 2, 3]))
    b = _t(np.array([[10.0], [20.0]], "float32"))
    b_lod = _t(np.array([0, 1, 2]))
    flat, lod = snn.sequence_concat([a, b], lod=[a_lod, b_lod])
    np.testing.assert_allclose(np.asarray(flat._value).ravel(),
                               [1, 2, 10, 3, 20])
    np.testing.assert_allclose(np.asarray(lod._value), [0, 3, 5])

    x, xlod, _ = _flat_and_lod()
    sl, sl_lod = snn.sequence_slice(x, _t(np.array([1, 0, 0, 0])),
                                    _t(np.array([2, 1, 0, 1])), lod=xlod)
    np.testing.assert_allclose(np.asarray(sl._value).ravel(), [1, 2, 3, 7])
    np.testing.assert_allclose(np.asarray(sl_lod._value), [0, 2, 3, 3, 4])

    dense = _t(np.array([[1.0], [2.0]], "float32"))
    ylod = _t(np.array([0, 2, 5]))
    ex, ex_lod = snn.sequence_expand(dense, None, y_lod=ylod)
    np.testing.assert_allclose(np.asarray(ex._value).ravel(), [1, 1, 2, 2, 2])
    ex2, _ = snn.sequence_expand_as(dense, None, y_lod=ylod)
    np.testing.assert_allclose(np.asarray(ex2._value).ravel(),
                               [1, 1, 2, 2, 2])


def test_sequence_reshape_enumerate_scatter():
    x = _t(np.arange(12, dtype="float32").reshape(6, 2))
    lod = _t(np.array([0, 2, 6]))
    flat, new_lod = snn.sequence_reshape(x, 4, lod=lod)
    assert np.asarray(flat._value).shape == (3, 4)
    np.testing.assert_allclose(np.asarray(new_lod._value), [0, 1, 3])

    ids = _t(np.array([5, 6, 7, 1], "int64"))
    idlod = _t(np.array([0, 3, 4]))
    win = np.asarray(snn.sequence_enumerate(ids, 2, pad_value=0,
                                            lod=idlod)._value)
    np.testing.assert_array_equal(win, [[5, 6], [6, 7], [7, 0], [1, 0]])

    dense = _t(np.zeros((2, 4), "float32"))
    upd = _t(np.array([1.0, 2.0, 3.0], "float32"))
    out = snn.sequence_scatter(dense, _t(np.array([0, 2, 1], "int64")), upd,
                               index_lod=_t(np.array([0, 2, 3])))
    np.testing.assert_allclose(np.asarray(out._value),
                               [[1, 0, 2, 0], [0, 3, 0, 0]])


def test_sequence_ops_differentiable():
    x = paddle.to_tensor(np.arange(8, dtype="float32").reshape(8, 1),
                         stop_gradient=False)
    lod = _t(np.array([0, 3, 7, 7, 8]))
    out = snn.sequence_pool(snn.sequence_softmax(x, lod=lod), "sum", lod=lod)
    out.sum().backward()
    g = np.asarray(x.grad._value)
    assert np.isfinite(g).all()


def test_trailing_empty_sequence_and_act_strings():
    # trailing empty sequences must not crash segment mapping
    x = _t(np.arange(3, dtype="float32").reshape(3, 1))
    lod = _t(np.array([0, 3, 3]))
    pooled = np.asarray(snn.sequence_pool(x, "sum", lod=lod)._value).ravel()
    np.testing.assert_allclose(pooled, [3.0, 0.0])
    # unknown act raises instead of silently skipping the activation
    x4 = _t(np.ones((1, 2, 4, 4), "float32"))
    with pytest.raises(ValueError, match="unsupported act"):
        snn.group_norm(x4, groups=2, act="definitely_not_an_act")
    s = np.asarray(snn.group_norm(x4, groups=2, act="sigmoid")._value)
    assert ((s >= 0) & (s <= 1)).all()


def test_conv2d_transpose_derives_kernel_from_output_size():
    paddle.seed(5)
    x = _t(np.random.default_rng(5).standard_normal((1, 2, 8, 8))
           .astype("float32"))
    out = snn.conv2d_transpose(x, 3, output_size=17, stride=2)
    assert tuple(out.shape)[2:] == (17, 17)
    with pytest.raises(ValueError, match="filter_size or output_size"):
        snn.conv2d_transpose(x, 3)


def test_data_norm_accumulates_running_stats():
    # Reference data_norm accumulates batch_size/batch_sum/batch_square_sum
    # every training step (the op's synthetic-gradient trick); repeated
    # executor runs over ONE program must drive the normalized output toward
    # (x - mean(x)) / rms(x) of the streamed data.
    from paddle_tpu import static

    rng = np.random.default_rng(7)
    xv = (rng.standard_normal((256, 5)) * 3.0 + 2.0).astype("float32")

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [256, 5], "float32")
        out = snn.data_norm(x)
    exe = static.Executor()
    outs = [exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
            for _ in range(60)]
    # init stats (batch_size=1e4, sum=0, sq=1e4) give ~identity at first ...
    np.testing.assert_allclose(outs[0], xv, rtol=1e-3, atol=1e-3)
    # ... and accumulation dominates the init prior after enough batches
    expect = (xv - xv.mean(axis=0)) / np.sqrt((xv * xv).mean(axis=0))
    err0 = np.abs(outs[0] - expect).mean()
    errN = np.abs(outs[-1] - expect).mean()
    assert errN < err0 * 0.2, (err0, errN)


def test_data_norm_honors_data_layout():
    from paddle_tpu import static

    rng = np.random.default_rng(8)
    xv = rng.standard_normal((2, 3, 4, 4)).astype("float32")
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 3, 4, 4], "float32")
        out_nchw = snn.data_norm(x, data_layout="NCHW")  # channel axis 1
    assert tuple(out_nchw.shape) == (2, 3, 4, 4)


def test_conv_transpose_output_padding_strictly_below_stride():
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(3)
    x = _t(rng.standard_normal((1, 2, 8)).astype("float32"))
    w = _t(rng.standard_normal((2, 3, 3)).astype("float32"))
    # stride 2: reachable window is [default, default + stride - 1]
    assert F.conv1d_transpose(x, w, stride=2, output_size=[18]).shape[-1] == 18
    with pytest.raises(ValueError, match=r"outside \[0, stride\)"):
        F.conv1d_transpose(x, w, stride=2, output_size=[19])


# ------------------------------------------------------------- IR property


def test_wide_static_programs_pass_ir_verification():
    """Property: every Program this module's static paths build — the
    data_norm running-stats programs plus a dense snn capture run through
    the executor with the fusion pipeline on — passes the IR verifier
    (static/verify.py; sweep the whole suite with tools/lint_ir.py)."""
    from paddle_tpu import static
    from paddle_tpu.static.verify import ProgramVerifier, track_programs

    paddle.seed(0)
    rng = np.random.default_rng(0)
    with track_programs() as programs:
        test_data_norm_accumulates_running_stats()
        test_data_norm_honors_data_layout()

        main = static.Program()
        with static.program_guard(main):
            x = static.data("xw", [2, 3, 8, 8], "float32")
            h = snn.group_norm(x, groups=3)
            out = snn.layer_norm(h, begin_norm_axis=1).mean()
        static.Executor().run(
            main, feed={"xw": rng.standard_normal((2, 3, 8, 8)).astype("float32")},
            fetch_list=[out])

    assert len(programs) >= 3
    verifier = ProgramVerifier()
    for prog in programs:
        violations = verifier.verify(prog)
        assert violations == [], (
            f"program with ops {[op.type for op in prog.global_block().ops]} "
            f"failed verification: {[str(v) for v in violations]}")
