"""Semi-auto parallel API + distributed train step on the 8-device CPU mesh.

Mirrors the reference's reshard pair tests (test/auto_parallel/reshard_*.py)
and semi-auto api tests (test/auto_parallel/test_shard_tensor_api.py), which
run multi-process NCCL — here one process, 8 XLA host devices.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import Partial, ProcessMesh, Replicate, Shard


@pytest.fixture(scope="module")
def mesh():
    return ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])


def test_shard_tensor_placements(mesh):
    x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    d = dist.shard_tensor(x, mesh, [Shard(0), Shard(1)])
    assert d.is_dist()
    assert d.placements == [Shard(0), Shard(1)]
    np.testing.assert_array_equal(np.asarray(d._value), np.asarray(x._value))
    # physical layout: dim0 split over dp(2), dim1 over mp(4)
    shard_shape = d._value.sharding.shard_shape(d._value.shape)
    assert shard_shape == (4, 2)


def test_shard_tensor_replicate_default(mesh):
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    d = dist.shard_tensor(x, mesh)
    assert all(p.is_replicated() for p in d.placements)
    assert d._value.sharding.shard_shape(d._value.shape) == (4, 4)


def test_reshard_s_to_r_and_back(mesh):
    x = paddle.to_tensor(np.random.rand(8, 16).astype(np.float32))
    s = dist.shard_tensor(x, mesh, [Shard(0), Replicate()])
    r = dist.reshard(s, mesh, [Replicate(), Replicate()])
    np.testing.assert_array_equal(np.asarray(r._value), np.asarray(x._value))
    s2 = dist.reshard(r, mesh, [Replicate(), Shard(1)])
    assert s2._value.sharding.shard_shape(s2._value.shape) == (8, 4)
    np.testing.assert_array_equal(np.asarray(s2._value), np.asarray(x._value))


def test_partial_folds_to_replicate(mesh):
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    d = dist.shard_tensor(x, mesh, [Partial(), Replicate()])
    assert all(not p.is_partial() for p in d.placements)


def test_dtensor_from_fn(mesh):
    d = dist.dtensor_from_fn(paddle.ones, mesh, [Shard(0)], [8, 4])
    assert d.is_dist()
    np.testing.assert_array_equal(np.asarray(d._value), np.ones((8, 4), np.float32))


def test_unshard(mesh):
    x = paddle.to_tensor(np.random.rand(8, 8).astype(np.float32))
    d = dist.shard_tensor(x, mesh, [Shard(0)])
    u = dist.unshard_dtensor(d)
    assert not u.is_dist()
    np.testing.assert_array_equal(np.asarray(u._value), np.asarray(x._value))


def test_eager_op_on_dist_tensors(mesh):
    """Computation follows data: eager ops on sharded inputs stay sharded."""
    a = dist.shard_tensor(paddle.to_tensor(np.random.rand(8, 16).astype(np.float32)), mesh, [Shard(0)])
    b = dist.shard_tensor(paddle.to_tensor(np.random.rand(16, 8).astype(np.float32)), mesh, [Replicate()])
    c = paddle.matmul(a, b)
    np.testing.assert_allclose(
        np.asarray(c._value),
        np.asarray(a._value) @ np.asarray(b._value),
        rtol=1e-5,
    )


def test_shard_layer_default_replicates(mesh):
    lin = paddle.nn.Linear(8, 8)
    dist.shard_layer(lin, mesh)
    for p in lin.parameters():
        assert p.is_dist()
        assert all(pl.is_replicated() for pl in p.placements)


@pytest.mark.slow
def test_sharded_train_step_tp_dp():
    """Full distributed train step: dp=2 x mp=4 TP llama + zero-1, matches
    the single-device step numerically."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny, shard_llama
    from paddle_tpu.jit import TrainStep

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, size=(4, 16)).astype(np.int32)
    labels = rng.integers(0, 256, size=(4, 16)).astype(np.int64)

    def loss_fn(m, i, l):
        loss, _ = m(i, labels=l)
        return loss

    def run(dist_mode):
        paddle.seed(42)
        cfg = llama_tiny(vocab_size=256, hidden_size=64, intermediate_size=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=4, max_position_embeddings=32,
                         dtype="float32")
        model = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
        if dist_mode:
            shard_llama(model, mesh, mp_axis="mp")
            step = dist.ShardedTrainStep(model, opt, loss_fn, mesh,
                                         batch_spec=PartitionSpec("dp"), zero_stage=1)
        else:
            step = TrainStep(model, opt, loss_fn)
        losses = []
        for _ in range(4):
            losses.append(float(step(paddle.to_tensor(ids), paddle.to_tensor(labels))._value))
        return losses

    ref = run(False)
    got = run(True)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-4)
    assert got[-1] < got[0]


@pytest.mark.slow
def test_global_norm_clip_across_mesh_axes():
    """HybridParallelOptimizer glue: ClipGradByGlobalNorm inside a tp x dp
    sharded step must clip by the same global norm as single-device
    (reference hybrid_parallel_optimizer.py:270 cross-axis norm; GSPMD makes
    the norm a compiled cross-shard reduction here)."""
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.sharded_step import ShardedTrainStep
    from paddle_tpu.distributed.auto_parallel.api import _mark_dist
    from paddle_tpu.distributed.auto_parallel.placement import Replicate, Shard

    def build():
        paddle.seed(11)
        m = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 8))
        return m

    rng = np.random.default_rng(11)
    x = rng.standard_normal((8, 8)).astype(np.float32) * 10  # big grads -> clip active
    y = rng.standard_normal((8, 8)).astype(np.float32)

    # single-device reference
    ref = build()
    ref_opt = paddle.optimizer.SGD(
        0.1, parameters=ref.parameters(), grad_clip=nn.ClipGradByGlobalNorm(0.5)
    )
    loss = ((ref(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2).mean()
    loss.backward()
    ref_opt.step()
    ref_opt.clear_grad()

    # tp2 x dp4 sharded step with the same clip
    mesh = dist.ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "mp"])
    m2 = build()
    _mark_dist(m2[0].weight, mesh, [Replicate(), Shard(1)])
    _mark_dist(m2[2].weight, mesh, [Shard(0), Replicate()])
    opt2 = paddle.optimizer.SGD(
        0.1, parameters=m2.parameters(), grad_clip=nn.ClipGradByGlobalNorm(0.5)
    )
    step = ShardedTrainStep(m2, opt2, lambda mm, a, b: ((mm(a) - b) ** 2).mean(), mesh)
    step(paddle.to_tensor(x), paddle.to_tensor(y))

    for p_ref, p_sh in zip(ref.parameters(), m2.parameters()):
        np.testing.assert_allclose(
            np.asarray(p_ref._value), np.asarray(p_sh._value), rtol=2e-4, atol=2e-5
        )


def test_cross_mesh_reshard():
    """Reshard across DIFFERENT meshes and placements: values must be
    preserved exactly and the new sharding must land on the target mesh
    (reference: reshard/*.cc pairwise converters incl. cross-mesh
    same_status; here one XLA resharding device_put)."""
    from paddle_tpu.distributed.auto_parallel.api import reshard, shard_tensor

    data = np.arange(64, dtype=np.float32).reshape(8, 8)
    mesh_a = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["x", "y"])
    mesh_b = dist.ProcessMesh(np.arange(8).reshape(4, 2), ["p", "q"])

    t = shard_tensor(data, mesh_a, [Shard(0), Shard(1)])
    # r_to_s, s_to_r, s_to_s and the cross-mesh move, value-checked each hop
    hops = [
        (mesh_a, [Replicate(), Replicate()]),
        (mesh_a, [Shard(1), Replicate()]),
        (mesh_b, [Shard(0), Shard(1)]),
        (mesh_b, [Replicate(), Shard(0)]),
        (mesh_a, [Shard(0), Shard(1)]),
    ]
    cur = t
    for mesh, placements in hops:
        cur = reshard(cur, mesh, placements)
        np.testing.assert_array_equal(np.asarray(cur._value), data)
        shard_mesh = cur._value.sharding.mesh
        assert tuple(shard_mesh.axis_names) == tuple(mesh._jax_mesh.axis_names)


def test_cross_mesh_reshard_inside_jit():
    """Resharding constraints compile into a jitted program (the GSPMD
    path the static Engine rides)."""
    from paddle_tpu.distributed.auto_parallel.api import reshard, shard_tensor

    data = np.arange(32, dtype=np.float32).reshape(4, 8)
    mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
    t = shard_tensor(data, mesh, [Shard(0), Replicate()])

    import jax

    from paddle_tpu.distributed.auto_parallel.api import sharding_of

    @jax.jit
    def f(v):
        v2 = jax.lax.with_sharding_constraint(v * 2.0, sharding_of(mesh, [Replicate(), Shard(1)]))
        return v2 + 1.0

    out = f(t._value)
    np.testing.assert_array_equal(np.asarray(out), data * 2 + 1)
