"""paddle.regularizer (reference python/paddle/regularizer.py): L1Decay /
L2Decay at the optimizer level and as per-parameter overrides."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.regularizer import L1Decay, L2Decay


def _param(val):
    import jax.numpy as jnp

    return paddle.Parameter(jnp.asarray(np.asarray(val, "float32")))


def _step(p, opt):
    (p * 0.0).sum().backward()  # zero loss grad: isolates the reg term
    opt.step()
    opt.clear_grad()
    return np.asarray(p._value)


def test_l2decay_matches_float_weight_decay():
    p1, p2 = _param([1.0, -2.0]), _param([1.0, -2.0])
    o1 = paddle.optimizer.SGD(0.1, parameters=[p1], weight_decay=0.01)
    o2 = paddle.optimizer.SGD(0.1, parameters=[p2], weight_decay=L2Decay(0.01))
    np.testing.assert_allclose(_step(p1, o1), _step(p2, o2), rtol=1e-7)


def test_l1decay_applies_sign_penalty():
    p = _param([1.0, -2.0, 0.0])
    opt = paddle.optimizer.SGD(0.1, parameters=[p], weight_decay=L1Decay(0.5))
    got = _step(p, opt)
    # grad = 0.5 * sign(w); w -= lr * grad
    np.testing.assert_allclose(got, [1.0 - 0.05, -2.0 + 0.05, 0.0], rtol=1e-6)


def test_per_parameter_regularizer_overrides_optimizer_level():
    # coefficients chosen so the override and fallback paths DIVERGE:
    # a broken override (optimizer L2 0.5*2 = 1.0) would give 1.9, not 1.98
    p_own, p_plain = _param([2.0]), _param([2.0])
    p_own.regularizer = L1Decay(0.2)
    opt = paddle.optimizer.SGD(0.1, parameters=[p_own, p_plain],
                               weight_decay=L2Decay(0.5))
    (p_own * 0.0 + p_plain * 0.0).sum().backward()
    opt.step()
    # p_own: L1 term sign(2)*0.2 -> 2 - 0.1*0.2 = 1.98
    np.testing.assert_allclose(np.asarray(p_own._value), [1.98], rtol=1e-6)
    # p_plain: optimizer-level L2 0.5*2 = 1.0 -> 2 - 0.1*1.0 = 1.9
    np.testing.assert_allclose(np.asarray(p_plain._value), [1.9], rtol=1e-6)
    # zero-coeff per-param regularizer = "disable decay for this param"
    p_off = _param([2.0])
    p_off.regularizer = L2Decay(0.0)
    opt2 = paddle.optimizer.SGD(0.1, parameters=[p_off],
                                weight_decay=L2Decay(0.5))
    (p_off * 0.0).sum().backward()
    opt2.step()
    np.testing.assert_allclose(np.asarray(p_off._value), [2.0], rtol=1e-7)


def test_adamw_decoupled_ignores_optimizer_level_regularizer_path():
    """AdamW's decay is decoupled; an optimizer-level L2Decay must not be
    double-applied through the gradient — and its COEFFICIENT must be
    honored (a coeff different from the 0.01 default guards against a
    silent fallback)."""
    p1, p2 = _param([1.0]), _param([1.0])
    o1 = paddle.optimizer.AdamW(0.1, parameters=[p1], weight_decay=0.07)
    o2 = paddle.optimizer.AdamW(0.1, parameters=[p2], weight_decay=L2Decay(0.07))
    np.testing.assert_allclose(_step(p1, o1), _step(p2, o2), rtol=1e-7)
    p3 = _param([1.0])
    o3 = paddle.optimizer.AdamW(0.1, parameters=[p3], weight_decay=0.01)
    assert abs(float(_step(p3, o3)[0]) - float(np.asarray(p1._value)[0])) > 1e-6


def test_param_attr_regularizer_reaches_optimizer():
    """ParamAttr(regularizer=...) flows through layer creation to the
    update (the reference's end-to-end path)."""
    import paddle_tpu.nn as nn

    paddle.seed(0)
    lin = nn.Linear(2, 2,
                    weight_attr=paddle.ParamAttr(regularizer=L1Decay(0.5)),
                    bias_attr=paddle.ParamAttr(regularizer=L2Decay(0.0)))
    assert isinstance(lin.weight.regularizer, L1Decay)
    w0 = np.asarray(lin.weight._value).copy()
    opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
    x = paddle.to_tensor(np.zeros((1, 2), "float32"))
    (lin(x) * 0.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(np.asarray(lin.weight._value),
                               w0 - 0.1 * 0.5 * np.sign(w0), rtol=1e-6)


def test_adamw_rejects_l1decay():
    import pytest

    p = _param([1.0])
    with pytest.raises(TypeError, match="L2Decay"):
        paddle.optimizer.AdamW(0.1, parameters=[p], weight_decay=L1Decay(0.1))
