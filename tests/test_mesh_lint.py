"""Mesh lint (static/mesh_lint.py, docs/MESH_LINT.md).

Every violation class gets a minimal failing fixture AND a passing twin
(the PR-4 verifier discipline, extended to the mesh): mismatched
collective axis, axis-size mismatch, conditional collective, bad
ppermute/axis_index_groups participation, bad/duplicate/indivisible
placements, replicated-giant, use-after-donation, over-budget memory.
Everything is abstract — no fixture ever launches a device collective,
so this suite cannot trip the 8-device SIGSEGV class it guards against.

The wiring tier checks FLAGS_verify_sharding raises with a named site at
every entry (Executor compile path, pass boundaries, ShardedTrainStep
build, GenerationEngine construction) and that the canonical GREEN
distributed/serving paths lint clean under the flag.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import ProcessMesh
from paddle_tpu.distributed.auto_parallel.placement import Replicate, Shard
from paddle_tpu.distributed.shard_map_compat import shard_map
from paddle_tpu.static.mesh_lint import (
    MeshLinter,
    MeshLintError,
    lint_engine,
    lint_program,
    lint_train_step,
    mesh_lint_stats,
    reset_mesh_lint_stats,
)


def _codes(violations):
    return {v.code for v in violations}


def _dp8():
    return Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))


def _dpmp():
    return Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dp", "mp"))


_AVAL = jax.ShapeDtypeStruct((8, 4), jnp.float32)


def _train_program(seed=0, din=4, dout=4, opt_cls=None):
    """Captured train-step program: forward + grad + optimizer_update with
    state writes (the donated-buffer shape every real step has)."""
    paddle.seed(seed)
    layer = nn.Linear(din, dout)
    opt_cls = opt_cls or paddle.optimizer.SGD
    opt = opt_cls(learning_rate=0.1, parameters=layer.parameters())
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, din], "float32")
        y = static.data("y", [4, dout], "float32")
        loss = paddle.mean((layer(x) - y) ** 2)
        opt.minimize(loss)
    return prog, loss


# ------------------------------------------- family 2: collective congruence
def test_collective_axis_clean_and_unknown():
    linter = MeshLinter(mesh=_dp8())
    assert linter.lint_callable(lambda x: lax.psum(x, "dp"), _AVAL) == []
    bad = linter.lint_callable(lambda x: lax.psum(x, "qq"), _AVAL)
    assert _codes(bad) == {"unknown-axis"}
    assert "qq" in str(bad[0])


def test_shard_map_wrong_axis_and_size_mismatch():
    linter = MeshLinter(mesh=_dp8())
    # twin: a shard_map binding dp at the session size is clean
    ok = shard_map(lambda v: lax.psum(v, "dp"), mesh=_dp8(),
                   in_specs=P("dp"), out_specs=P())
    assert linter.lint_callable(ok, _AVAL) == []
    # an 'mp' shard_map on a dp-only session mesh: the collective would
    # never line up with the session topology
    mp2 = Mesh(np.array(jax.devices()[:2]), ("mp",))
    wrong = shard_map(lambda v: lax.psum(v, "mp"), mesh=mp2,
                      in_specs=P("mp"), out_specs=P())
    assert "unknown-axis" in _codes(linter.lint_callable(wrong, _AVAL))
    # same NAME, different size: built for another topology
    dp2 = Mesh(np.array(jax.devices()[:2]), ("dp",))
    small = shard_map(lambda v: lax.psum(v, "dp"), mesh=dp2,
                      in_specs=P("dp"), out_specs=P())
    assert "axis-size-mismatch" in _codes(linter.lint_callable(small, _AVAL))


def test_conditional_collective_flagged_and_twins():
    linter = MeshLinter(mesh=_dp8())

    def cond_body(v):
        return lax.cond(v.sum() > 0, lambda t: lax.psum(t, "dp"),
                        lambda t: t, v)

    conditional = shard_map(cond_body, mesh=_dp8(), in_specs=P("dp"),
                            out_specs=P("dp"))
    bad = linter.lint_callable(conditional, _AVAL)
    assert "conditional-collective" in _codes(bad)

    # twin 1: the unconditional collective is clean
    flat = shard_map(lambda v: lax.psum(v, "dp"), mesh=_dp8(),
                     in_specs=P("dp"), out_specs=P())
    assert linter.lint_callable(flat, _AVAL) == []

    # twin 2: a collective inside lax.scan is NOT conditional (static trip
    # count — every device runs every iteration)
    def scan_body(v):
        def one(c, x):
            return c + lax.psum(x, "dp"), None

        out, _ = lax.scan(one, jnp.zeros_like(v[0]), v)
        return out[None]

    scanned = shard_map(scan_body, mesh=_dp8(), in_specs=P("dp"),
                        out_specs=P("dp"))
    assert linter.lint_callable(
        scanned, jax.ShapeDtypeStruct((8, 3, 4), jnp.float32)) == []

    # while_loop bodies ARE data-dependent (plain axis-env form: jax
    # 0.4.37's shard_map cannot even trace while+collective — real code
    # reaches this shape through pass super-ops running under a mesh)
    def while_body(v):
        return lax.while_loop(lambda s: s.sum() < 100.0,
                              lambda s: lax.psum(s, "dp"), v)

    assert "conditional-collective" in _codes(
        linter.lint_callable(while_body, _AVAL))


def test_ppermute_participation():
    linter = MeshLinter(mesh=_dp8())

    def sm(perm):
        return shard_map(lambda v: lax.ppermute(v, "dp", perm),
                         mesh=_dp8(), in_specs=P("dp"), out_specs=P("dp"))

    # twin: the ring rotation every pipeline stage uses is clean
    ring = [(i, (i + 1) % 8) for i in range(8)]
    assert linter.lint_callable(sm(ring), _AVAL) == []
    # duplicate source / duplicate destination / out-of-range rank: jax
    # traces all three happily — only the lint catches them
    assert "bad-permutation" in _codes(
        linter.lint_callable(sm([(0, 1), (0, 2)]), _AVAL))
    assert "bad-permutation" in _codes(
        linter.lint_callable(sm([(0, 1), (2, 1)]), _AVAL))
    assert "bad-permutation" in _codes(
        linter.lint_callable(sm([(0, 9)]), _AVAL))


def test_axis_index_groups_participation():
    # plain axis-env form: jax 0.4.37's shard_map rejects
    # axis_index_groups outright, but pmap-style/compat paths still carry
    # them — the lint validates the partition wherever it appears
    linter = MeshLinter(mesh=_dp8())

    def gfn(groups):
        return lambda v: lax.psum(v, "dp", axis_index_groups=groups)

    # twin: halves partition the axis uniformly
    assert linter.lint_callable(
        gfn([[0, 1, 2, 3], [4, 5, 6, 7]]), _AVAL) == []
    # non-uniform group sizes
    assert "bad-groups" in _codes(linter.lint_callable(
        gfn([[0, 1, 2], [3, 4, 5, 6, 7]]), _AVAL))
    # not a partition (rank 7 never rendezvouses)
    assert "bad-groups" in _codes(linter.lint_callable(
        gfn([[0, 1, 2, 3], [4, 5, 6, 6]]), _AVAL))


# ------------------------------------------------ family 1: placements
def test_placement_unknown_axis_and_twin():
    linter = MeshLinter(mesh=_dpmp())
    aval = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    assert "unknown-axis" in _codes(
        linter.lint_placements([("w", aval, P("dp", "qq"))]))
    assert linter.lint_placements([("w", aval, P("dp", "mp"))]) == []


def test_placement_bad_shard_dim_and_twin():
    linter = MeshLinter(mesh=_dpmp())
    aval = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    assert "bad-shard-dim" in _codes(linter.lint_placements(
        [("w", aval, [Shard(5), Replicate()])]))
    assert "bad-shard-dim" in _codes(linter.lint_placements(
        [("w", aval, P("dp", "mp", None))]))  # 3 entries, rank 2
    assert linter.lint_placements(
        [("w", aval, [Shard(0), Replicate()])]) == []


def test_duplicate_axis_and_indivisible_shard():
    linter = MeshLinter(mesh=_dpmp())
    aval = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    assert "duplicate-axis" in _codes(
        linter.lint_placements([("w", aval, P("dp", "dp"))]))
    odd = jax.ShapeDtypeStruct((6, 16), jnp.float32)  # 6 % dp(4) != 0
    assert "indivisible-shard" in _codes(
        linter.lint_placements([("w", odd, P("dp", None))]))
    assert linter.lint_placements([("w", aval, P("dp", "mp"))]) == []


def test_replicated_giant_and_twins():
    linter = MeshLinter(mesh=_dp8(), replicated_bytes=2 ** 20)
    big = jax.ShapeDtypeStruct((1024, 1024), jnp.float32)  # 4 MiB
    bad = linter.lint_placements([("embedding", big, None)])
    assert _codes(bad) == {"replicated-giant"}
    assert "per device" in str(bad[0])
    # twin 1: the same tensor sharded is clean
    assert linter.lint_placements([("embedding", big, P("dp", None))]) == []
    # twin 2: small tensors replicate freely (biases, norms)
    small = jax.ShapeDtypeStruct((1024,), jnp.float32)
    assert linter.lint_placements([("bias", small, None)]) == []
    # twin 3: no mesh, no flag — single-device replication is meaningless
    assert MeshLinter(mesh=None, replicated_bytes=2 ** 20).lint_placements(
        [("embedding", big, None)]) == []


# ----------------------------------------- family 4: per-device memory
def test_memory_estimate_and_budget():
    big = jax.ShapeDtypeStruct((1024, 1024), jnp.float32)  # 4 MiB
    groups = {"params": [("w", big, P("dp", None))],
              "optimizer": [("m", big, P("dp", None))]}
    # twin: budget off (0) never flags
    ok, est = MeshLinter(mesh=_dp8(),
                         budget_bytes=0).estimate_device_bytes(groups)
    assert ok == [] and est["params"] == est["optimizer"] == 2 ** 19
    assert est["total"] == 2 ** 20
    # sharding divides the estimate: replicated would be 4 MiB each
    bad, est2 = MeshLinter(mesh=_dp8(),
                           budget_bytes=2 ** 19).estimate_device_bytes(groups)
    assert _codes(bad) == {"over-budget"}
    assert est2 == est
    # twin: a budget above the estimate is clean
    ok2, _ = MeshLinter(mesh=_dp8(),
                        budget_bytes=2 ** 21).estimate_device_bytes(groups)
    assert ok2 == []


# ------------------------------------------------ family 3: donation
def test_use_after_donation_fetch_and_twin():
    prog, loss = _train_program()
    donated = next(iter(prog.writes))  # a written state var (param)
    bad = lint_program(prog, [loss._vid, donated], mesh=_dp8())
    assert "use-after-donation" in _codes(bad)
    assert "PRE-update" in next(str(v) for v in bad
                                if v.code == "use-after-donation")
    # twin: fetching the UPDATED value (the write source) is the contract
    updated = prog.writes[donated]
    assert lint_program(prog, [loss._vid, updated], mesh=_dp8()) == []


def test_duplicate_donation_in_train_step():
    class Shared(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = self.create_parameter([4, 4])
            self.b = self.create_parameter([4, 4])
            self.b._bind(self.a._value)  # two params, ONE buffer

        def forward(self, x):
            return x @ self.a + x @ self.b

    paddle.seed(0)
    model = Shared()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    step = paddle.jit.TrainStep(model, opt,
                                lambda m, x: paddle.mean(m(x) ** 2))
    bad, _est = lint_train_step(
        step, jax.ShapeDtypeStruct((2, 4), jnp.float32))
    assert "use-after-donation" in _codes(bad)
    assert "donates it twice" in next(
        str(v) for v in bad if v.code == "use-after-donation")

    # twin: independent buffers lint clean
    paddle.seed(0)
    model2 = nn.Linear(4, 4)
    opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=model2.parameters())
    step2 = paddle.jit.TrainStep(model2, opt2,
                                 lambda m, x: paddle.mean(m(x) ** 2))
    ok, _ = lint_train_step(step2, jax.ShapeDtypeStruct((2, 4), jnp.float32))
    assert ok == []


# ----------------------------------------------------------- wiring tier
def _set_flags(**kv):
    prev = {k: paddle.get_flags(k)[k] for k in kv}
    paddle.set_flags(kv)
    return prev


def test_executor_compile_path_raises_under_flag():
    prog, loss = _train_program(seed=1)
    donated = next(iter(prog.writes))
    feed = {"x": np.zeros((4, 4), np.float32),
            "y": np.zeros((4, 4), np.float32)}
    prev = _set_flags(FLAGS_verify_sharding=True)
    try:
        exe = static.Executor()
        loss_var = prog._var_by_vid[loss._vid]
        donated_var = prog._var_by_vid[donated]
        with pytest.raises(MeshLintError, match="use-after-donation"):
            exe.run(prog, feed=feed, fetch_list=[loss_var, donated_var])
        # twin: the clean fetch set compiles and runs under the flag
        out = exe.run(prog, feed=feed, fetch_list=[loss_var])
        assert np.isfinite(out[0]).all()
    finally:
        paddle.set_flags(prev)


def test_pass_boundary_names_failing_stage():
    from paddle_tpu.static.passes import ProgramPassManager

    prog, loss = _train_program(seed=2)
    donated = next(iter(prog.writes))
    prev = _set_flags(FLAGS_verify_sharding=True)
    try:
        pm = ProgramPassManager([], fetch_vids=[loss._vid, donated])
        with pytest.raises(MeshLintError, match="BEFORE pass pipeline"):
            pm.run(prog)
    finally:
        paddle.set_flags(prev)


def test_sharded_train_step_lint_abstract_raise():
    """A big fully-replicated param on an 8-device mesh is flagged at
    BUILD time — abstractly, before any sharded dispatch could hang."""
    mesh = ProcessMesh(np.arange(8).reshape(8), ["dp"])
    paddle.seed(3)
    model = nn.Linear(512, 600)  # ~1.2 MiB weight, replicated
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    step = dist.ShardedTrainStep(
        model, opt, lambda m, x, y: paddle.mean((m(x) - y) ** 2), mesh,
        zero_stage=0)
    bx = jax.ShapeDtypeStruct((8, 512), jnp.float32)
    by = jax.ShapeDtypeStruct((8, 600), jnp.float32)
    with pytest.raises(MeshLintError, match="replicated-giant"):
        lint_train_step(step, bx, by, replicated_bytes=2 ** 20,
                        raise_on_error=True)
    # twin: the default threshold (8 MiB) tolerates this size
    ok, est = lint_train_step(step, bx, by)
    assert ok == []
    assert est["total"] > 0


def test_engine_wiring_raises_on_replicated_pools():
    """num_key_value_heads % mp != 0 falls back to REPLICATED pools (the
    PR-6 warning path) — under FLAGS_verify_sharding with a tight
    replicated threshold, engine construction fails loudly instead."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import GenerationEngine

    paddle.seed(4)
    cfg = LlamaConfig(vocab_size=64, hidden_size=48, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=6,
                      num_key_value_heads=3, max_position_embeddings=128)
    mesh = ProcessMesh(np.arange(2).reshape(2), ["mp"])
    prev = _set_flags(FLAGS_verify_sharding=True,
                      FLAGS_mesh_lint_replicated_mb=0.001)
    try:
        with pytest.warns(UserWarning, match="KV pool replicated"):
            with pytest.raises(MeshLintError, match="replicated-giant"):
                GenerationEngine(LlamaForCausalLM(cfg), num_blocks=16,
                                 mesh=mesh)
    finally:
        paddle.set_flags(prev)
    # twin: divisible KV heads shard the pools — constructs clean under
    # the same flags
    paddle.seed(4)
    cfg2 = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, max_position_embeddings=128)
    prev = _set_flags(FLAGS_verify_sharding=True)
    try:
        eng = GenerationEngine(LlamaForCausalLM(cfg2), num_blocks=16,
                               mesh=mesh)
        violations, est = lint_engine(eng)
        assert violations == []
        assert est["kv_pools"] > 0
    finally:
        paddle.set_flags(prev)


def test_single_device_objects_ignore_session_mesh():
    """A plain TrainStep / mesh=None engine is single-device BY CONTRACT:
    an active multi-device session mesh must not reclassify its
    (correctly) replicated state as replication blowups."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import GenerationEngine

    mesh = ProcessMesh(np.arange(8).reshape(8), ["dp"])
    dist.set_mesh(mesh)
    prev = _set_flags(FLAGS_mesh_lint_replicated_mb=0.001)
    try:
        paddle.seed(9)
        model = nn.Linear(64, 64)  # 16 KiB weight > the tiny threshold
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        step = paddle.jit.TrainStep(model, opt,
                                    lambda m, x: paddle.mean(m(x) ** 2))
        ok, _ = lint_train_step(
            step, jax.ShapeDtypeStruct((2, 64), jnp.float32))
        assert ok == []

        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=1,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=64)
        eng = GenerationEngine(LlamaForCausalLM(cfg), num_blocks=8)
        ok, _ = lint_engine(eng)
        assert ok == []
    finally:
        paddle.set_flags(prev)
        dist.set_mesh(None)


def test_stats_and_summary_footer(capsys):
    reset_mesh_lint_stats()
    linter = MeshLinter(mesh=_dp8())
    linter.lint_callable(lambda x: lax.psum(x, "dp"), _AVAL)
    prog, loss = _train_program(seed=5)
    lint_program(prog, [loss._vid], mesh=_dp8())
    stats = mesh_lint_stats()
    assert stats["entries_linted"] == 1
    assert stats["collectives_checked"] >= 1
    assert stats["violations"] == 0

    from paddle_tpu import profiler

    assert profiler.mesh_lint_stats() == stats
    prof = profiler.Profiler(timer_only=True)
    prof.start()
    prof.stop()
    out = prof.summary()
    assert "Mesh lint:" in out
    assert "violations=0" in out
    capsys.readouterr()


# ------------------------------------------------- green tier-1 sweep
def test_green_distributed_serving_paths_zero_violations():
    """The canonical green paths — ZeRO-rewritten captured program through
    the Executor, dp x mp ShardedTrainStep, TP-sharded GenerationEngine —
    produce ZERO violations under FLAGS_verify_sharding=1 (the tier-1
    acceptance sweep; tools/lint_mesh.py battery is the standalone twin)."""
    reset_mesh_lint_stats()
    prev = _set_flags(FLAGS_verify_sharding=True)
    try:
        # executor path with the ZeRO rewrite
        from paddle_tpu.static.passes import apply_pass

        prog, loss = _train_program(seed=6, din=16, dout=8)
        apply_pass(prog, "auto_parallel_sharding", mesh=_dp8(), stage=2)
        exe = static.Executor()
        rng = np.random.default_rng(0)
        out = exe.run(prog, feed={"x": rng.normal(size=(4, 16)).astype(np.float32),
                                  "y": rng.normal(size=(4, 8)).astype(np.float32)},
                      fetch_list=[prog._var_by_vid[loss._vid]])
        assert np.isfinite(out[0]).all()

        # ShardedTrainStep build + lint (abstract: no sharded dispatch)
        mesh = ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "mp"])
        paddle.seed(7)
        model = nn.Linear(16, 16)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        step = dist.ShardedTrainStep(
            model, opt, lambda m, x, y: paddle.mean((m(x) - y) ** 2), mesh,
            batch_spec=P("dp"))
        violations, _ = lint_train_step(
            step, jax.ShapeDtypeStruct((8, 16), jnp.float32),
            jax.ShapeDtypeStruct((8, 16), jnp.float32))
        assert violations == []

        # serving engine (wired lint ran at construction)
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.serving import GenerationEngine

        paddle.seed(8)
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=128)
        GenerationEngine(LlamaForCausalLM(cfg), num_blocks=16)

        stats = mesh_lint_stats()
        assert stats["entries_linted"] >= 4
        assert stats["entries_failed"] == 0
        assert stats["violations"] == 0
    finally:
        paddle.set_flags(prev)


def test_engine_adapter_pack_covered_with_twin():
    """Multi-tenant LoRA satellite: an adapter-pack engine's per-device
    estimate includes the pack bytes (via the params-style placements
    path), a tight HBM budget flags them (failing fixture), and the same
    engine constructs clean under FLAGS_verify_sharding at the default
    budget (passing twin)."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import GenerationEngine

    paddle.seed(11)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=64,
                      dtype="float32")
    prev = _set_flags(FLAGS_verify_sharding=True)
    try:
        # passing twin: adapter engine lints clean at construction and
        # the estimate carries the pack's exact bytes as its own group
        eng = GenerationEngine(LlamaForCausalLM(cfg), num_blocks=8,
                               adapters={"rank": 4, "max_adapters": 2})
        violations, est = lint_engine(eng)
        assert violations == []
        assert est["adapter_pack"] == eng._pack.nbytes > 0
        # the pack-less twin has no adapter_pack group at all
        eng2 = GenerationEngine(LlamaForCausalLM(cfg), num_blocks=8)
        _ok, est2 = lint_engine(eng2)
        assert "adapter_pack" not in est2
    finally:
        paddle.set_flags(prev)

    # failing fixture: a budget below the pack-inclusive estimate names
    # the over-budget site at engine construction
    prev = _set_flags(FLAGS_verify_sharding=True,
                      FLAGS_mesh_lint_hbm_budget_gb=1e-6)
    try:
        with pytest.raises(MeshLintError, match="over-budget"):
            GenerationEngine(LlamaForCausalLM(cfg), num_blocks=8,
                             adapters={"rank": 4, "max_adapters": 2})
    finally:
        paddle.set_flags(prev)


def test_sharded_engine_budget_uses_per_device_estimate():
    """Sharded-serving satellite: FLAGS_mesh_lint_hbm_budget_gb is a
    PER-DEVICE budget, judged against the sharding-divided estimate.
    Passing twin: a budget between the sharded per-device estimate and
    the single-device estimate constructs CLEAN on a 2-device mesh —
    the same engine on one device blows the identical budget (the pool
    'fits' only because the mesh divides it).  Failing fixture: a budget
    below even the per-device estimate flags the sharded engine at
    construction, with the sharded (divided) bytes in the message."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import GenerationEngine

    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128,
                      dtype="float32")
    mesh = ProcessMesh(np.arange(2).reshape(2), ["mp"])

    def build(mesh_arg):
        paddle.seed(4)
        return GenerationEngine(LlamaForCausalLM(cfg), num_blocks=16,
                                kv_cache_dtype="int8", mesh=mesh_arg)

    _ok, est_single = lint_engine(build(None))
    eng = build(mesh)
    violations, est_tp = lint_engine(eng)
    assert violations == []
    # the estimate really is per-device: pools AND int8 scales divided
    assert est_tp["kv_pools"] * 2 == est_single["kv_pools"]
    assert est_tp["kv_scales"] * 2 == est_single["kv_scales"]
    assert est_tp["total"] < est_single["total"]

    mid_gb = (est_tp["total"] + est_single["total"]) / 2 / 2 ** 30
    prev = _set_flags(FLAGS_verify_sharding=True,
                      FLAGS_mesh_lint_hbm_budget_gb=mid_gb)
    try:
        build(mesh)  # passing twin: per-device fits the budget
        with pytest.raises(MeshLintError, match="over-budget"):
            build(None)  # one device holds everything: same budget blows
    finally:
        paddle.set_flags(prev)

    # failing fixture: below the per-device estimate, the SHARDED engine
    # is flagged too — and with the divided estimate, not the global one
    low_gb = est_tp["total"] / 2 / 2 ** 30
    prev = _set_flags(FLAGS_verify_sharding=True,
                      FLAGS_mesh_lint_hbm_budget_gb=low_gb)
    try:
        with pytest.raises(MeshLintError, match="over-budget") as ei:
            build(mesh)
    finally:
        paddle.set_flags(prev)
    assert "per device" in str(ei.value)
