"""Tier-1 smoke of benchmarks/bench_dispatch.py.

Unlike the slow-marked bench.py harness test, this runs in every tier-1
pass (tiny sizes): the dispatch-cache perf harness must keep emitting the
one-line JSON payload the driver parses, and its built-in cache-on vs
cache-off numerics gate must hold — so the perf path can't bitrot
unexercised between measured rounds.
"""

import json
import os
import subprocess
import sys


def test_bench_dispatch_smoke_emits_valid_json():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PADDLE_TPU_BENCH_SMOKE="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "benchmarks", "bench_dispatch.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=repo)
    assert out.returncode == 0, (out.stderr or out.stdout)[-800:]
    line = next(ln for ln in reversed(out.stdout.splitlines()) if ln.startswith("{"))
    payload = json.loads(line)
    assert payload["metric"] == "eager_dispatch_cached_train_speedup"
    assert payload["unit"] == "x"
    assert payload["value"] > 0
    assert "vs_baseline" in payload
    assert payload["numerics_identical"] is True
    detail = payload["detail"]
    for section in ("train", "grad_ops", "fwd_ops"):
        assert detail[section]["on_per_sec"] > 0
        assert detail[section]["off_per_sec"] > 0
    # the cached runs actually exercised the cache
    assert detail["train"]["cache_hits"] > 0
