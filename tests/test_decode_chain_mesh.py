"""Sharded decode-chain fusion: schedule search over the mesh
(ops/decode_chain.py mesh view + serving adoption; docs/SCHEDULE_SEARCH.md
mesh section).

The contract under test: a DecodeChainSpec carrying the engine's mesh is
a first-class search subject — its verdict caches under the (device kind,
mesh shape) key and NEVER cross-serves the single-device verdict (or vice
versa); its roofline costs PER-DEVICE traffic from
``NamedSharding.shard_shape`` plus the epilogue's psum bytes; its kernel
builds inside shard_map over the committed fsdp×tp pool layout and every
candidate passes parity against the SHARDED XLA twin (the mesh adds NO
drift: bf16 chains stay bit-exact leaf for leaf).  An engine that adopts
a fused mesh verdict emits token streams BIT-IDENTICAL to the
single-device engine — full-precision and int8 pools, plain and
LoRA-adapter-pack workloads, on 2/4/8-device CPU meshes.  The K-tiled
fused prefill-attention candidate (PrefillChainSpec) rides the same
search with a bit-exact gate.

Every engine test dispatches GSPMD-partitioned decode programs (now with
an interpret-mode Pallas body inside shard_map) over the in-process
multi-device XLA:CPU communicator — the intermittent SIGSEGV class
tools/run_tier1.py contains — so this module rides a DEDICATED isolated
worker (ISOLATED_DEFAULT); the 4- and 8-device stream-parity cases
additionally run through run_isolated_test subprocess workers so they
stay in tier-1 un-slow-marked.
"""

import json
import os
import tempfile

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.distributed.auto_parallel import ProcessMesh
from paddle_tpu.ops import autotune as at
from paddle_tpu.ops import decode_chain as dc
from paddle_tpu.static import schedule_search as ss


@pytest.fixture()
def tmp_cache(tmp_path):
    """Fresh autotune cache under a tmp dir + zeroed search counters."""
    paddle.set_flags({"FLAGS_autotune_cache_dir": str(tmp_path)})
    at._CACHES.clear()
    ss.reset_schedule_search_stats()
    serving.reset_schedule_decode_stats()
    yield tmp_path
    paddle.set_flags({"FLAGS_autotune_cache_dir": ""})
    at._CACHES.clear()
    ss.reset_schedule_search_stats()
    serving.reset_schedule_decode_stats()


def _mesh(mp):
    return ProcessMesh(np.arange(mp), ["mp"])


def _spec(kv="bf16", mp=None, **kw):
    base = dict(batch=2, num_heads=4, num_kv_heads=2, head_dim=8,
                block_size=4, max_blocks=2, num_blocks=8, kv=kv,
                dtype=np.float32)
    base.update(kw)
    if mp:
        base.setdefault("mesh", _mesh(mp))
    return dc.DecodeChainSpec(**base)


def _win(fn, args, *, label, config):
    return 0.4 if config is not None else 1.0


def _lose(fn, args, *, label, config):
    return 4.0 if config is not None else 1.0


# ------------------------------------------------------------ spec tier


def test_mesh_key_carries_mesh_shape():
    """(device kind, mesh shape) keying: the cache file is already per
    device kind; the key dict grows a 'mesh' entry ONLY when a mesh is
    set, so existing single-device key strings stay byte-stable."""
    single, meshed = _spec(), _spec(mp=2)
    assert "mesh" not in single.key()
    k = meshed.key()
    assert k["mesh"] == "mp2"
    assert {kk: v for kk, v in k.items() if kk != "mesh"} == single.key()
    assert _spec(mp=4, num_kv_heads=4).key()["mesh"] == "mp4"
    assert "mesh=mp2" in meshed.label()


def test_device_spec_divides_heads_via_shard_shape():
    """The per-device replica's head counts come from
    NamedSharding.shard_shape over the committed pool/head layouts — the
    same source pool_device_nbytes uses — and the mesh spec's roofline
    inputs (traffic, flops, vmem) are the PER-DEVICE numbers."""
    meshed = _spec(mp=2)
    local = meshed.device_spec()
    assert local.mesh is None
    assert (local.num_heads, local.num_kv_heads) == (2, 1)
    cfg = {"layout": "batch", "gather": "take"}
    # head-local layout: zero in-kernel collectives, so per-device
    # traffic IS the local spec's traffic — and less than the global twin
    assert meshed.collective_bytes(cfg) == 0
    assert meshed.traffic_bytes(cfg) == local.traffic_bytes(cfg)
    assert meshed.traffic_bytes(cfg) < _spec().traffic_bytes(cfg)
    assert meshed.flops() == local.flops() < _spec().flops()
    assert meshed.vmem_bytes(cfg) == local.vmem_bytes(cfg)


def test_non_divisible_heads_cost_psum_and_refuse_build():
    """A geometry whose kv groups would split across devices costs the
    epilogue psum honestly ([b, n_local, h] f32) and build() refuses it
    loudly — no candidate implements the reduction."""
    bad = _spec(mp=2, num_kv_heads=1)  # n=4 divides, nkv=1 doesn't
    cfg = {"layout": "batch", "gather": "take"}
    assert bad.collective_bytes(cfg) == 2 * 2 * 8 * 4  # b * ceil(n/mp) * h * 4
    with pytest.raises(ValueError, match="divisible"):
        bad.build(cfg)


@pytest.mark.parametrize("kv", ["bf16", "int8"])
def test_mesh_candidates_parity_vs_sharded_twin(kv):
    """The PR-11 contract holds THROUGH the mesh: every sharded candidate
    passes parity against the sharded XLA twin (synthetic args committed
    to the engine's layout), and bf16 chains stay bit-exact leaf for
    leaf — the mesh adds NO drift."""
    spec = _spec(kv, mp=2)
    args = spec.synthetic_args()
    ref = jax.jit(spec.reference())(*args)
    for cfg in spec.enumerate_configs():
        fn = jax.jit(spec.build(cfg))
        assert spec.parity_ok(fn, args, ref), cfg
        if kv == "bf16":
            got = fn(*args)
            for r, g in zip(jax.tree_util.tree_leaves(ref),
                            jax.tree_util.tree_leaves(got)):
                assert bool((r == g).all()), cfg


def test_verdict_cache_never_cross_served(tmp_cache):
    """The pollution regression (satellite): a cached single-device
    verdict is NEVER served to the mesh spec of the same geometry, and
    vice versa — each side searches fresh, persists its own entry, and a
    cold reload serves each spec ITS OWN verdict.  Proven by making the
    two verdicts DIFFER (accept vs disable) in both directions."""
    single, meshed = _spec(), _spec(mp=2)
    with ss.measure_override(_win):
        assert dc.ensure_decision(single).status == "accepted"
    with ss.measure_override(_lose):
        # pollution would serve the accepted single-device config here
        assert dc.ensure_decision(meshed).status == "disabled"
    # the opposite direction on a second geometry: mesh accepts first
    single4, meshed4 = _spec(batch=4), _spec(batch=4, mp=2)
    with ss.measure_override(_win):
        assert dc.ensure_decision(meshed4).status == "accepted"
    with ss.measure_override(_lose):
        assert dc.ensure_decision(single4).status == "disabled"
    # distinct persisted entries under one kernel namespace, keyed apart
    raw = json.load(open(os.path.join(
        str(tmp_cache), at.device_kind_slug() + ".json")))
    keys = list(raw["schedule/decode_bf16"])
    assert len(keys) == 4
    assert sum("mesh=mp2" in k for k in keys) == 2
    # cold reload: zero measures, each spec gets its OWN verdict back
    at._CACHES.clear()
    calls = []

    def counting(fn, args, *, label, config):
        calls.append(label)
        return 1.0

    with ss.measure_override(counting):
        assert dc.ensure_decision(single).status == "cache"
        assert dc.ensure_decision(meshed).status == "cache_disabled"
        assert dc.ensure_decision(meshed4).status == "cache"
        assert dc.ensure_decision(single4).status == "cache_disabled"
    assert calls == []


def test_lint_decode_chain_own_and_foreign_mesh():
    """The pre-dispatch static check tools/lint_mesh.py also runs: the
    head-local sharded kernel walks with ZERO collectives against its
    own mesh; judged against a foreign session mesh it is flagged, never
    dispatched."""
    from jax.sharding import Mesh
    from paddle_tpu.static.mesh_lint import lint_decode_chain

    cfg = {"layout": "batch", "gather": "take"}
    assert lint_decode_chain(_spec("int8", mp=2), cfg) == []
    assert lint_decode_chain(_spec("bf16"), cfg) == []  # single-device
    foreign = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    viol = lint_decode_chain(_spec("int8", mp=2), cfg, mesh=foreign)
    assert viol and {v.code for v in viol} == {"unknown-axis"}


# ------------------------------------------------- prefill chain (spec)


def test_prefill_candidates_pin_full_chunk_tile():
    """block_q is pinned to the WHOLE chunk (a sub-tile's re-fused XLA
    reduction can drift ~1e-7, shape-dependently — even past the parity
    gate's geometry), and single-token chunks enumerate NOTHING:
    jax.nn.dot_product_attention special-cases single-row queries with a
    re-associated reduction."""
    spec = dc.PrefillChainSpec(seq=4, kv_len=8, num_heads=2, head_dim=4)
    cfgs = spec.enumerate_configs()
    assert cfgs and {c["block_q"] for c in cfgs} == {4}
    assert {c["stage"] for c in cfgs} == {"take", "loop"}
    for c in cfgs:
        if c["stage"] == "loop":
            assert 8 % c["kchunk"] == 0
    assert dc.PrefillChainSpec(seq=1, kv_len=4, num_heads=2,
                               head_dim=4).enumerate_configs() == []


@pytest.mark.parametrize("seq,kv_len", [(8, 8), (8, 16)])
def test_prefill_all_candidates_bit_exact(seq, kv_len):
    """Every prefill candidate — square first chunk AND bottom-right
    mid-prompt chunk — is BIT-EXACT vs the _core XLA twin; staging K/V
    in kchunk pieces is pure data movement."""
    spec = dc.PrefillChainSpec(seq=seq, kv_len=kv_len, num_heads=4,
                               head_dim=8)
    args = spec.synthetic_args()
    ref = jax.jit(spec.reference())(*args)
    for cfg in spec.enumerate_configs():
        fn = jax.jit(spec.build(cfg))
        assert spec.parity_ok(fn, args, ref), cfg
        assert bool((fn(*args) == ref).all()), cfg


def test_fused_prefill_attention_public_entry():
    """The adoption entry point models/llama uses: derives the spec from
    live shapes and stays bit-exact under jit — the engine seam always
    dispatches it through the jitted apply funnel, so jit is the honest
    comparison context — including the K-staged loop path."""
    key = jax.random.PRNGKey(0)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, 8, 4, 8), jnp.float32)
    k = jax.random.normal(kk, (1, 16, 4, 8), jnp.float32)
    v = jax.random.normal(kv_, (1, 16, 4, 8), jnp.float32)
    spec = dc.PrefillChainSpec(seq=8, kv_len=16, num_heads=4, head_dim=8)
    ref = jax.jit(spec.reference())(q, k, v)
    for cfg in ({"block_q": 8, "stage": "take"},
                {"block_q": 8, "stage": "loop", "kchunk": 4}):
        fused = jax.jit(lambda a, b, c, _cfg=cfg: dc.fused_prefill_attention(
            a, b, c, block_q=_cfg["block_q"], stage=_cfg["stage"],
            kchunk=_cfg.get("kchunk", 1)))
        assert bool((fused(q, k, v) == ref).all()), cfg


# ----------------------------------------------------------- engine tier


def _model(seed=41, n=4, nkv=2, hidden=32):
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(seed)
    m = LlamaForCausalLM(llama_tiny(
        vocab_size=128, hidden_size=hidden, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=n,
        num_key_value_heads=nkv, max_position_embeddings=64,
        dtype="float32"))
    m.eval()
    return m


def _workload(eng):
    """Greedy + mid-flight seeded-sampling join — the stream shape every
    fused-vs-unfused comparison replays identically."""
    eng.add_request("g", [5, 9, 17, 33, 2], max_new_tokens=8)
    eng.step()
    eng.add_request("s", [7, 11, 3], max_new_tokens=6, temperature=3.0,
                    seed=42)
    while eng.has_work():
        eng.step()
    return {"g": eng.result("g"), "s": eng.result("s")}


def _stream_parity_body(mp, n, nkv, kv="bf16", cache_dir=None):
    """Shared payload: single-device search-off engine vs mp-device
    search-on engine — streams must be bit-identical AND the mesh engine
    must have adopted a fused verdict (decode_chains_mesh_fused > 0)."""
    from paddle_tpu.serving import GenerationEngine, schedule_decode_stats

    cache_dir = cache_dir or tempfile.mkdtemp(prefix="dcm_cache_")
    paddle.set_flags({"FLAGS_autotune_cache_dir": cache_dir})
    at._CACHES.clear()
    serving.reset_schedule_decode_stats()
    kw = dict(max_batch=2, block_size=8, num_blocks=16, kv_cache_dtype=kv)
    try:
        ref = _workload(GenerationEngine(_model(n=n, nkv=nkv), **kw))
        paddle.set_flags({"FLAGS_schedule_search": True})
        with ss.measure_override(_win):
            got = _workload(GenerationEngine(_model(n=n, nkv=nkv),
                                             mesh=_mesh(mp), **kw))
    finally:
        paddle.set_flags({"FLAGS_schedule_search": False,
                          "FLAGS_autotune_cache_dir": ""})
        at._CACHES.clear()
    assert got == ref, (got, ref)
    stats = schedule_decode_stats()
    assert stats["decode_chains_mesh_fused"] >= 1, stats


@pytest.mark.parametrize("kv", ["bf16", "int8"])
def test_mesh_fused_streams_match_single_device(tmp_cache, kv):
    """The acceptance crux over the mesh: a 2-device engine that ADOPTED
    a fused sharded decode chain emits streams bit-identical to the
    single-device engine — greedy and seeded sampling, bf16 AND int8
    pools."""
    _stream_parity_body(2, n=4, nkv=2, kv=kv, cache_dir=str(tmp_cache))


def _adapter_sd(base, key_seed, n=4, nkv=4, rank=4):
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    from paddle_tpu.nn.lora import apply_lora, lora_state_dict

    ft = LlamaForCausalLM(llama_tiny(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=n,
        num_key_value_heads=nkv, max_position_embeddings=64,
        dtype="float32"))
    ft.set_state_dict(base.state_dict())
    ft.eval()
    apply_lora(ft, rank=rank, alpha=8)
    key = jax.random.PRNGKey(key_seed)
    for name, p in ft.named_parameters():
        if name.endswith(("lora_A", "lora_B")):
            key, sk = jax.random.split(key)
            scale = 0.2 if name.endswith("lora_B") else 0.05
            p._bind(jax.random.normal(sk, p._value.shape,
                                      jnp.float32) * scale)
    return lora_state_dict(ft)


def test_mesh_fused_chain_composes_with_adapter_packs(tmp_cache):
    """LoRA adapter packs × fused sharded chain: a 2-device adapter
    engine that adopted the fused decode chain serves mixed-tenant
    batches (two tenants + a base row + a sampled adapter row)
    bit-identical to the single-device adapter engine with search off."""
    from paddle_tpu.serving import GenerationEngine, schedule_decode_stats

    base = _model(n=4, nkv=4)
    sds = {f"t{i}": _adapter_sd(base, key_seed=10 + i) for i in range(2)}

    def run(mesh):
        eng = GenerationEngine(_model(n=4, nkv=4), max_batch=4,
                               block_size=8, num_blocks=32,
                               adapters={"rank": 4, "max_adapters": 2},
                               mesh=mesh)
        for name, sd in sds.items():
            eng.register_adapter(name, sd, alpha=8)
        prompts = {"a0": ([5, 9, 17, 33, 2], "t0"),
                   "a1": ([7, 11, 3, 20], "t1"),
                   "base": ([5, 9, 17, 33, 2], None)}
        for rid, (prompt, ad) in prompts.items():
            eng.add_request(rid, prompt, max_new_tokens=6, adapter=ad)
        eng.add_request("samp", [15, 4, 40], max_new_tokens=5,
                        temperature=2.5, seed=9, adapter="t0")
        while eng.has_work():
            eng.step()
        return {rid: eng.result(rid) for rid in list(prompts) + ["samp"]}

    ref = run(None)
    assert len({tuple(v) for v in ref.values()}) >= 3  # tenants differ
    serving.reset_schedule_decode_stats()
    paddle.set_flags({"FLAGS_schedule_search": True})
    try:
        with ss.measure_override(_win):
            got = run(_mesh(2))
    finally:
        paddle.set_flags({"FLAGS_schedule_search": False})
    assert got == ref
    assert schedule_decode_stats()["decode_chains_mesh_fused"] >= 1


# ------------------------------------------- 4/8-device isolated workers


def _mp4_body():
    """4-device stream parity, run in a crash-isolated subprocess: the
    8-virtual-device XLA:CPU communicator under a shard_map'd Pallas
    body is squarely the intermittent SIGSEGV class run_tier1 contains."""
    _stream_parity_body(4, n=4, nkv=4)


def _mp8_body():
    """8-device twin of _mp4_body (n=nkv=8, head_dim 4)."""
    _stream_parity_body(8, n=8, nkv=8)


def test_mesh_fused_streams_match_single_device_mp4():
    """4-device case IN tier-1 (not slow-marked): the payload rides
    tools/run_tier1.py's crash-isolated worker — a SIGSEGV is a
    contained retry, an assertion failure fails immediately."""
    from tools.run_tier1 import run_isolated_test

    run_isolated_test("tests.test_decode_chain_mesh", "_mp4_body",
                      retries=2, timeout=300)


def test_mesh_fused_streams_match_single_device_mp8():
    """8-device twin — full mesh width, same containment."""
    from tools.run_tier1 import run_isolated_test

    run_isolated_test("tests.test_decode_chain_mesh", "_mp8_body",
                      retries=2, timeout=300)
