"""Double-backward (create_graph=True) on the imperative tape.

Reference: paddle.grad create_graph (python/paddle/base/dygraph/base.py:615)
backed by generated double-grad GradNodes; behavioral model
test/legacy_test/test_imperative_double_grad.py.  Here the tape computes each
first-order vjp THROUGH the funnel (autograd._vjp_through_tape), so returned
grads carry grad nodes; values are checked against jax.grad-of-grad oracles.
"""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle


def _param(arr):
    t = paddle.to_tensor(np.asarray(arr, np.float32))
    t.stop_gradient = False
    return t


def test_second_order_elementwise():
    x = _param([1.0, 2.0, 3.0])
    y = (x * x * x).sum()
    (gx,) = paddle.grad(y, x, create_graph=True)
    assert not gx.stop_gradient  # part of the graph
    np.testing.assert_allclose(gx.numpy(), 3 * np.array([1.0, 4.0, 9.0]), rtol=1e-6)
    (ggx,) = paddle.grad(gx.sum(), x)
    np.testing.assert_allclose(ggx.numpy(), 6 * np.array([1.0, 2.0, 3.0]), rtol=1e-6)


def test_third_order_chain():
    x = _param([2.0])
    y = x**4
    (g1,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad(g1, x, create_graph=True)
    (g3,) = paddle.grad(g2, x)
    np.testing.assert_allclose(g1.numpy(), [32.0], rtol=1e-6)  # 4x^3
    np.testing.assert_allclose(g2.numpy(), [48.0], rtol=1e-6)  # 12x^2
    np.testing.assert_allclose(g3.numpy(), [48.0], rtol=1e-6)  # 24x


def test_create_graph_default_retains():
    # retain_graph defaults to create_graph: the same first-order graph can
    # be differentiated again.
    x = _param([1.5])
    y = (x**3).sum()
    (gx,) = paddle.grad(y, x, create_graph=True)
    (ggx_a,) = paddle.grad(gx.sum(), x, create_graph=True)
    (ggx_b,) = paddle.grad(gx.sum(), x)  # second walk over the same graph
    np.testing.assert_allclose(ggx_a.numpy(), ggx_b.numpy(), rtol=1e-6)


def test_second_order_matmul_vs_jax():
    rng = np.random.default_rng(0)
    xv = rng.standard_normal((4, 5)).astype(np.float32)
    wv = rng.standard_normal((5, 3)).astype(np.float32)

    x, w = _param(xv), _param(wv)
    out = paddle.matmul(x, w)
    s = (out * out).sum()
    (gx,) = paddle.grad(s, x, create_graph=True)
    # scalar functional of the first-order grad, differentiated wrt w
    q = (gx * gx).sum()
    (gw,) = paddle.grad(q, w)

    def f(xa, wa):
        o = xa @ wa
        return (o * o).sum()

    def q_of_w(wa):
        gxa = jax.grad(f, argnums=0)(xv, wa)
        return (gxa * gxa).sum()

    oracle = jax.grad(q_of_w)(wv)
    np.testing.assert_allclose(gw.numpy(), np.asarray(oracle), rtol=1e-4, atol=1e-4)


def test_wgan_gp_gradient_penalty_vs_jax():
    """Gradient-penalty training step: penalty = (||d D(x)/d x|| - 1)^2
    backprops into D's parameters — the workload create_graph exists for."""
    rng = np.random.default_rng(1)
    xv = rng.standard_normal((8, 6)).astype(np.float32)
    w1v = (rng.standard_normal((6, 16)) * 0.4).astype(np.float32)
    w2v = (rng.standard_normal((16, 1)) * 0.4).astype(np.float32)

    x, w1, w2 = _param(xv), _param(w1v), _param(w2v)
    h = paddle.tanh(paddle.matmul(x, w1))
    d = paddle.matmul(h, w2).sum()
    (gx,) = paddle.grad(d, x, create_graph=True)
    norm = paddle.sqrt((gx * gx).sum(axis=1) + 1e-12)
    penalty = ((norm - 1.0) ** 2).mean()
    penalty.backward()

    def discriminator(xa, w1a, w2a):
        return (jnp.tanh(xa @ w1a) @ w2a).sum()

    def penalty_fn(w1a, w2a):
        gxa = jax.grad(discriminator, argnums=0)(xv, w1a, w2a)
        n = jnp.sqrt((gxa * gxa).sum(axis=1) + 1e-12)
        return ((n - 1.0) ** 2).mean()

    gw1, gw2 = jax.grad(penalty_fn, argnums=(0, 1))(w1v, w2v)
    np.testing.assert_allclose(w1.grad.numpy(), np.asarray(gw1), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(w2.grad.numpy(), np.asarray(gw2), rtol=1e-4, atol=1e-5)


def test_grad_outputs_tensor_participates():
    # A grad_outputs Tensor with its own graph keeps receiving gradient:
    # d/dv of <v, dy/dx-seeded-by-v> where y = x*x.
    x = _param([1.0, 2.0])
    v = _param([3.0, 4.0])
    y = x * x
    (gx,) = paddle.grad(y, x, grad_outputs=v, create_graph=True)  # 2x*v
    np.testing.assert_allclose(gx.numpy(), [6.0, 16.0], rtol=1e-6)
    (gv,) = paddle.grad(gx.sum(), v)  # d/dv sum(2x*v) = 2x
    np.testing.assert_allclose(gv.numpy(), [2.0, 4.0], rtol=1e-6)


def test_create_graph_inside_jit():
    """The whole double-backward step traces under jax.jit (tape composes
    with tracing — the TPU hot path)."""

    def step(xval):
        x = paddle.to_tensor(xval)
        x.stop_gradient = False
        y = (x**3).sum()
        (gx,) = paddle.grad(y, x, create_graph=True)
        (ggx,) = paddle.grad(gx.sum(), x)
        return ggx._value

    out = jax.jit(step)(jnp.array([1.0, 2.0], jnp.float32))
    np.testing.assert_allclose(np.asarray(out), [6.0, 12.0], rtol=1e-5)


def test_create_graph_on_released_graph_raises():
    # backward() without retain released the graph; create_graph over it must
    # raise loudly (reference: 'trying to backward a second time'), not
    # silently truncate at the released node.
    x = _param([2.0])
    a = x * x
    y = (a * a).sum()
    y.backward()
    try:
        paddle.grad(y, x, create_graph=True, allow_unused=True)
    except RuntimeError as e:
        assert "released" in str(e)
    else:
        raise AssertionError("expected released-node RuntimeError")


def test_create_graph_detects_inplace_mutation():
    # The rebuild path recomputes the forward; a set_value between forward
    # and the create_graph walk must error, not silently change the grad.
    x = _param([2.0])
    y = (x * x).sum()
    x.set_value(np.array([10.0], np.float32))
    try:
        paddle.grad(y, x, create_graph=True)
    except RuntimeError as e:
        assert "in-place" in str(e)
    else:
        raise AssertionError("expected in-place mutation RuntimeError")


def test_create_graph_explicit_retain_false_releases():
    # retain_graph=False with create_graph frees the first-order graph: the
    # returned grad stays differentiable, but a second walk over the
    # original graph raises.
    x = _param([3.0])
    y = (x**3).sum()
    (gx,) = paddle.grad(y, x, create_graph=True, retain_graph=False)
    (ggx,) = paddle.grad(gx.sum(), x)  # second-order graph still alive
    np.testing.assert_allclose(ggx.numpy(), [18.0], rtol=1e-6)
    try:
        paddle.grad(y, x)
    except RuntimeError:
        pass
    else:
        raise AssertionError("expected released-node RuntimeError")


def test_first_order_release_still_enforced():
    # Without create_graph nothing changed: second backward still raises.
    x = _param([1.0])
    y = (x * x).sum()
    y.backward()
    try:
        y.backward()
    except RuntimeError:
        pass
    else:
        raise AssertionError("expected released-node RuntimeError")


def test_create_graph_through_pylayer_differentiates_custom_backward():
    """Double backward through PyLayer must differentiate the CUSTOM
    backward, never re-autodiff the forward (straight-through semantics)."""
    from paddle_tpu.autograd import PyLayer

    class Cube(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, g):
            (x,) = ctx.saved_tensor()
            return g * x  # DELIBERATELY not the true derivative (3x^2)

    x = _param([2.0, 3.0])
    y = Cube.apply(x)
    (gx,) = paddle.grad(y.sum(), x, create_graph=True)
    np.testing.assert_allclose(gx.numpy(), [2.0, 3.0], rtol=1e-6)  # g*x = x
    (ggx,) = paddle.grad(gx.sum(), x)
    # d/dx of the CUSTOM backward's x is 1 — NOT forward's 6x
    np.testing.assert_allclose(ggx.numpy(), [1.0, 1.0], rtol=1e-6)


def test_create_graph_pylayer_second_order_matches_true_derivative():
    from paddle_tpu.autograd import PyLayer

    class Square(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, g):
            (x,) = ctx.saved_tensor()
            return g * 2.0 * x  # the true vjp, written by hand

    x = _param([1.5, -2.0])
    y = Square.apply(x)
    (gx,) = paddle.grad(y.sum(), x, create_graph=True)
    np.testing.assert_allclose(gx.numpy(), [3.0, -4.0], rtol=1e-6)
    (ggx,) = paddle.grad(gx.sum(), x)
    np.testing.assert_allclose(ggx.numpy(), [2.0, 2.0], rtol=1e-6)


def test_tape_double_grad_agrees_with_functional_hessian():
    """Two independent higher-order mechanisms — the tape's create_graph
    walk and the functional jax-transform hessian — must agree."""
    from paddle_tpu.autograd.functional import hessian

    rng = np.random.default_rng(5)
    xv = rng.standard_normal(4).astype(np.float32)

    def f(x):
        return (paddle.tanh(x) * x).sum()

    H = hessian(f, paddle.to_tensor(xv))
    H = np.asarray(H._value if hasattr(H, "_value") else H)

    # tape route: per-component second derivative via create_graph
    x = _param(xv)
    y = (paddle.tanh(x) * x).sum()
    (gx,) = paddle.grad(y, x, create_graph=True)
    rows = []
    for i in range(4):
        (row,) = paddle.grad(gx[i], x, retain_graph=True, create_graph=True)
        rows.append(np.asarray(row._value))
    np.testing.assert_allclose(np.stack(rows), H.reshape(4, 4), rtol=1e-4, atol=1e-5)


def test_create_graph_under_amp_autocast():
    """Gradient penalty computed inside amp.auto_cast: the amp_cast tape
    nodes must participate in the create_graph walk (bf16 tolerance vs the
    fp32 oracle)."""
    rng = np.random.default_rng(9)
    xv = rng.standard_normal((4, 8)).astype(np.float32)
    wv = (rng.standard_normal((8, 1)) * 0.5).astype(np.float32)

    def penalty(amp_on):
        x, w = _param(xv), _param(wv)
        with paddle.amp.auto_cast(enable=amp_on):
            d = paddle.matmul(paddle.tanh(x), w).sum()
        (gx,) = paddle.grad(d, x, create_graph=True)
        p = ((gx * gx).sum() - 1.0) ** 2
        p.backward()
        return np.asarray(w.grad._value, np.float32)

    np.testing.assert_allclose(penalty(True), penalty(False),
                               rtol=5e-2, atol=5e-2)
