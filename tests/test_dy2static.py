"""AST-mode dy2static: python control flow over tensors under to_static
(reference: python/paddle/jit/dy2static/ast_transformer.py + the
convert_operators runtime; executed here via lax.cond/while_loop — see
paddle_tpu/jit/dy2static/__init__.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static
from paddle_tpu.jit import to_static


@to_static
def _branchy(x):
    if x.sum() > 0:
        y = x * 2
    else:
        y = x - 1
    return y.sum()


def test_tensor_if_both_paths():
    a = paddle.to_tensor(np.ones(4, np.float32))
    assert float(_branchy(a)._value) == 8.0
    assert float(_branchy(paddle.to_tensor(-np.ones(4, np.float32)))._value) == -8.0


@to_static
def _dynstop(x, limit):
    s = paddle.zeros([1])
    i = paddle.zeros([1], dtype="int32")
    while s.sum() < limit.sum():
        s = s + x
        i = i + 1
    return i


def test_dynamic_stop_while():
    r = _dynstop(
        paddle.to_tensor(np.array([2.0], np.float32)),
        paddle.to_tensor(np.array([7.0], np.float32)),
    )
    assert int(np.asarray(r._value)[0]) == 4


@to_static
def _boolops(x):
    if x.sum() > 0 and x.max() < 10:
        return x * 1.5
    return x


def test_bool_ops_and_early_return():
    assert float(_boolops(paddle.to_tensor(np.ones(1, np.float32)))._value[0]) == 1.5
    assert float(_boolops(paddle.to_tensor(np.full(1, 20, np.float32)))._value[0]) == 20.0


@to_static
def _pyflow(x, flag=True):
    if flag:
        acc = 0.0
        for k in range(3):
            acc = acc + k
        return x + acc
    return x


def test_python_control_flow_preserved():
    assert float(_pyflow(paddle.to_tensor(np.zeros(1, np.float32)))._value[0]) == 3.0


class _Gate(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 8)
        self.fc2 = nn.Linear(8, 8)

    def forward(self, x):
        if x.mean() > 0:
            h = self.fc1(x)
        else:
            h = self.fc2(x)
        return h.sum()


def test_layer_branch_matches_eager():
    paddle.seed(0)
    m = _Gate()
    xp = paddle.to_tensor(np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32))
    eager = float(m(xp)._value)
    ms = to_static(_Gate())
    ms.set_state_dict(m.state_dict())
    for sign in (1.0, -1.0):
        xs = paddle.to_tensor(sign * np.asarray(xp._value))
        assert abs(float(m(xs)._value) - float(ms(xs)._value)) < 1e-5


def test_static_nn_cond_grad_through_captures():
    x = paddle.to_tensor(np.ones(4, np.float32))
    x.stop_gradient = False
    out = static.nn.cond(
        paddle.to_tensor(np.array(True)), lambda: (x * 3).sum(), lambda: x.sum()
    )
    out.backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), 3.0)


def test_static_nn_while_loop_traced():
    @to_static
    def g(n):
        i = paddle.zeros([], dtype="int32")
        s = paddle.ones([])
        iv, sv = static.nn.while_loop(
            lambda i, s: i < n, lambda i, s: (i + 1, s * 2.0), [i, s]
        )
        return sv

    assert float(g(paddle.to_tensor(np.array(6, np.int32)))._value) == 64.0


def test_switch_case_and_case():
    r = static.nn.switch_case(
        paddle.to_tensor(np.array(1, np.int32)),
        {0: lambda: paddle.to_tensor(0.0), 1: lambda: paddle.to_tensor(11.0)},
        default=lambda: paddle.to_tensor(-1.0),
    )
    assert float(r._value) == 11.0
    r2 = static.nn.case(
        [(paddle.to_tensor(np.array(False)), lambda: paddle.to_tensor(1.0)),
         (paddle.to_tensor(np.array(True)), lambda: paddle.to_tensor(2.0))],
        default=lambda: paddle.to_tensor(3.0),
    )
    assert float(r2._value) == 2.0


def test_forward_reference_resolves():
    # names bound AFTER decoration must resolve (live globals)
    import tests._dy2s_fwdref as mod

    r = mod.entry(paddle.to_tensor(np.ones(2, np.float32)))
    assert float(r._value.sum()) == 4.0


def test_guard_raise_not_merged():
    @to_static
    def guarded(x):
        if x.sum() > 1e6:
            raise ValueError("overflow")
        return x * 2

    # concrete path: fine below the guard... under trace the if stays python
    # and raises the tracer-bool error (documented), NOT the user exception
    with pytest.raises(Exception) as ei:
        guarded(paddle.to_tensor(np.ones(2, np.float32)))
    assert "overflow" not in str(ei.value)


def test_break_in_nested_loop_ok():
    @to_static
    def f(x):
        if x.sum() > 0:
            for k in range(3):
                if k == 1:
                    break
            y = x * 2
        else:
            y = x - 1
        return y.sum()

    assert float(f(paddle.to_tensor(np.ones(2, np.float32)))._value) == 4.0


def test_while_invariant_stays_python():
    @to_static
    def f(x):
        n = 3
        s = paddle.zeros([])
        while s < n:
            s = s + x.sum()
        acc = 0
        for k in range(n):  # n must still be a python int
            acc += k
        return s + acc

    r = f(paddle.to_tensor(np.array(2.0, np.float32)))
    assert float(r._value) == 7.0


@to_static
def _tensor_range_loop(n, x):
    acc = paddle.zeros([])
    for i in range(n):  # n is a Tensor -> traced while_loop
        acc = acc + x.sum() + i
    return acc


def test_for_over_tensor_range():
    n = paddle.to_tensor(np.array(4, np.int32))
    x = paddle.to_tensor(np.ones(2, np.float32))
    # sum over i in 0..3 of (2 + i) = 8 + 6 = 14
    assert float(_tensor_range_loop(n, x)._value) == 14.0


@to_static
def _python_range_loop(x):
    acc = 0.0
    for i in range(3):  # concrete: exact python semantics
        acc = acc + i
    return x + acc


def test_for_over_python_range_preserved():
    r = _python_range_loop(paddle.to_tensor(np.zeros(1, np.float32)))
    assert float(r._value[0]) == 3.0


# ------------------------------------------------------- breadth battery
# Mirrors test/dygraph_to_static's wide case matrix at small scale: every
# entry is (fn, args) checked for numeric equality between eager and
# to_static execution (reference test strategy, SURVEY §4).

def _eq(fn, *args, **kw):
    ref = fn(*args)
    got = to_static(fn)(*args)
    np.testing.assert_allclose(
        np.asarray(got._value), np.asarray(ref._value), rtol=1e-5, atol=1e-6, **kw)


def _t(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


def test_breadth_nested_tensor_if():
    def fn(x):
        if x.sum() > 0:
            if x.max() > 2:
                y = x * 3
            else:
                y = x * 2
        else:
            y = -x
        return y.mean()

    _eq(fn, _t([1.0, 2.5]))
    _eq(fn, _t([1.0, 0.5]))
    _eq(fn, _t([-1.0, -2.0]))


def test_breadth_if_with_multiple_live_vars():
    def fn(x):
        a = x + 1
        b = x * 2
        if (a * b).sum() > 0:
            a, b = b, a + b
        else:
            a = a - b
        return (a + b).sum()

    _eq(fn, _t([0.5, 1.5]))
    _eq(fn, _t([-3.0, -4.0]))


def test_breadth_while_accumulator():
    def fn(x):
        total = paddle.zeros([])
        i = paddle.zeros([])
        while i < 5:
            total = total + (x * i).sum()
            i = i + 1
        return total

    _eq(fn, _t([1.0, 2.0]))


def test_breadth_while_with_tensor_condition_on_value():
    def fn(x):
        while x.sum() < 10:
            x = x * 1.5
        return x.sum()

    _eq(fn, _t([1.0, 1.0]))


def test_breadth_ternary_and_compare_chain():
    def fn(x):
        y = x * 2 if x.mean() > 0 else x * -1
        return y.sum()

    _eq(fn, _t([1.0, 3.0]))
    _eq(fn, _t([-1.0, -3.0]))


def test_breadth_logical_combinations():
    def fn(x):
        if (x.sum() > 0) and (x.max() < 10) or (x.min() < -5):
            return x.sum() * 2
        return x.sum()

    _eq(fn, _t([1.0, 2.0]))
    _eq(fn, _t([-6.0, 1.0]))
    _eq(fn, _t([20.0, 1.0]))


def test_breadth_for_range_over_tensor_len_steps():
    def fn(x, n):
        acc = x
        for i in range(n):
            acc = acc + x * float(i)
        return acc.sum()

    ref = fn(_t([1.0, 2.0]), 4)
    got = to_static(fn)(_t([1.0, 2.0]), 4)
    np.testing.assert_allclose(float(got._value), float(ref._value), rtol=1e-5)


def test_breadth_grad_through_tensor_if():
    def fn(x):
        if x.sum() > 0:
            return (x ** 2).sum()
        return (x ** 3).sum()

    x1 = _t([1.0, 2.0]); x1.stop_gradient = False
    out = to_static(fn)(x1)
    out.backward()
    np.testing.assert_allclose(np.asarray(x1.grad._value), [2.0, 4.0], rtol=1e-5)

    x2 = _t([-1.0, -2.0]); x2.stop_gradient = False
    out2 = to_static(fn)(x2)
    out2.backward()
    np.testing.assert_allclose(np.asarray(x2.grad._value), [3.0, 12.0], rtol=1e-5)


def test_breadth_layer_with_state_and_branch():
    class Gate(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(3, 3)

        def forward(self, x):
            h = self.fc(x)
            if h.sum() > 0:
                return h * 2
            return h * -1

    paddle.seed(4)
    m = Gate()
    x = _t([[0.5, 1.0, -0.2]])
    ref = m(x)
    paddle.seed(4)
    sm = to_static(Gate())
    got = sm(x)
    np.testing.assert_allclose(np.asarray(got._value), np.asarray(ref._value), rtol=1e-5)


def test_breadth_while_loop_carrying_two_tensors():
    def fn(x):
        a = x
        b = paddle.zeros_like(x)
        i = paddle.zeros([])
        while i < 3:
            a, b = a * 2, b + a
            i = i + 1
        return (a + b).sum()

    _eq(fn, _t([1.0, -1.0]))


def test_breadth_early_return_before_branch():
    def fn(x, flag):
        if flag:  # python bool: resolved at trace time
            return x.sum()
        if x.sum() > 0:
            return x.mean()
        return x.max()

    ref = fn(_t([1.0, 2.0]), True)
    got = to_static(fn)(_t([1.0, 2.0]), True)
    np.testing.assert_allclose(float(got._value), float(ref._value))
    ref2 = fn(_t([1.0, 2.0]), False)
    got2 = to_static(fn)(_t([1.0, 2.0]), False)
    np.testing.assert_allclose(float(got2._value), float(ref2._value))


def test_static_arg_type_disambiguation():
    """1 / 1.0 / True are distinct trace-time constants (cache must not
    collide them on python equality)."""
    f = to_static(lambda x, n: x * n)
    xi = paddle.to_tensor(np.int32([2, 3]))
    out_int = f(xi, 1)
    out_float = f(xi, 1.0)
    assert str(out_int.dtype) != str(out_float.dtype), (out_int.dtype, out_float.dtype)


def test_ndarray_args_are_dynamic_not_baked():
    """Positional AND keyword ndarrays trace as dynamic inputs: new values
    give new results (no stale baked constants), without recompiles."""
    f = to_static(lambda x, w=None: (x * paddle.to_tensor(w)).sum())
    x = paddle.to_tensor(np.float32([1.0, 1.0]))
    a = np.float32([2.0, 2.0])
    b = np.float32([5.0, 5.0])
    assert float(f(x, w=a)._value) == 4.0
    assert float(f(x, w=b)._value) == 10.0
    assert len(f._cache) == 1  # same structure -> one compiled entry


def test_shape_dependent_output_structure():
    def fn(x):
        return [x[i] for i in range(x.shape[0])]

    f = to_static(fn)
    out2 = f(paddle.to_tensor(np.float32([1, 2])))
    assert len(out2) == 2
    out3 = f(paddle.to_tensor(np.float32([1, 2, 3])))
    assert len(out3) == 3


def test_multi_output_tuple_and_grad():
    def fn(x):
        return (x * 2).sum(), (x ** 2).sum()

    f = to_static(fn)
    x = paddle.to_tensor(np.float32([1.0, 3.0]))
    x.stop_gradient = False
    a, b = f(x)
    np.testing.assert_allclose(float(a._value), 8.0)
    np.testing.assert_allclose(float(b._value), 10.0)
    (a + b).backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), [4.0, 8.0])
