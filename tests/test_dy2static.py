"""AST-mode dy2static: python control flow over tensors under to_static
(reference: python/paddle/jit/dy2static/ast_transformer.py + the
convert_operators runtime; executed here via lax.cond/while_loop — see
paddle_tpu/jit/dy2static/__init__.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static
from paddle_tpu.jit import to_static


@to_static
def _branchy(x):
    if x.sum() > 0:
        y = x * 2
    else:
        y = x - 1
    return y.sum()


def test_tensor_if_both_paths():
    a = paddle.to_tensor(np.ones(4, np.float32))
    assert float(_branchy(a)._value) == 8.0
    assert float(_branchy(paddle.to_tensor(-np.ones(4, np.float32)))._value) == -8.0


@to_static
def _dynstop(x, limit):
    s = paddle.zeros([1])
    i = paddle.zeros([1], dtype="int32")
    while s.sum() < limit.sum():
        s = s + x
        i = i + 1
    return i


def test_dynamic_stop_while():
    r = _dynstop(
        paddle.to_tensor(np.array([2.0], np.float32)),
        paddle.to_tensor(np.array([7.0], np.float32)),
    )
    assert int(np.asarray(r._value)[0]) == 4


@to_static
def _boolops(x):
    if x.sum() > 0 and x.max() < 10:
        return x * 1.5
    return x


def test_bool_ops_and_early_return():
    assert float(_boolops(paddle.to_tensor(np.ones(1, np.float32)))._value[0]) == 1.5
    assert float(_boolops(paddle.to_tensor(np.full(1, 20, np.float32)))._value[0]) == 20.0


@to_static
def _pyflow(x, flag=True):
    if flag:
        acc = 0.0
        for k in range(3):
            acc = acc + k
        return x + acc
    return x


def test_python_control_flow_preserved():
    assert float(_pyflow(paddle.to_tensor(np.zeros(1, np.float32)))._value[0]) == 3.0


class _Gate(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 8)
        self.fc2 = nn.Linear(8, 8)

    def forward(self, x):
        if x.mean() > 0:
            h = self.fc1(x)
        else:
            h = self.fc2(x)
        return h.sum()


def test_layer_branch_matches_eager():
    paddle.seed(0)
    m = _Gate()
    xp = paddle.to_tensor(np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32))
    eager = float(m(xp)._value)
    ms = to_static(_Gate())
    ms.set_state_dict(m.state_dict())
    for sign in (1.0, -1.0):
        xs = paddle.to_tensor(sign * np.asarray(xp._value))
        assert abs(float(m(xs)._value) - float(ms(xs)._value)) < 1e-5


def test_static_nn_cond_grad_through_captures():
    x = paddle.to_tensor(np.ones(4, np.float32))
    x.stop_gradient = False
    out = static.nn.cond(
        paddle.to_tensor(np.array(True)), lambda: (x * 3).sum(), lambda: x.sum()
    )
    out.backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), 3.0)


def test_static_nn_while_loop_traced():
    @to_static
    def g(n):
        i = paddle.zeros([], dtype="int32")
        s = paddle.ones([])
        iv, sv = static.nn.while_loop(
            lambda i, s: i < n, lambda i, s: (i + 1, s * 2.0), [i, s]
        )
        return sv

    assert float(g(paddle.to_tensor(np.array(6, np.int32)))._value) == 64.0


def test_switch_case_and_case():
    r = static.nn.switch_case(
        paddle.to_tensor(np.array(1, np.int32)),
        {0: lambda: paddle.to_tensor(0.0), 1: lambda: paddle.to_tensor(11.0)},
        default=lambda: paddle.to_tensor(-1.0),
    )
    assert float(r._value) == 11.0
    r2 = static.nn.case(
        [(paddle.to_tensor(np.array(False)), lambda: paddle.to_tensor(1.0)),
         (paddle.to_tensor(np.array(True)), lambda: paddle.to_tensor(2.0))],
        default=lambda: paddle.to_tensor(3.0),
    )
    assert float(r2._value) == 2.0


def test_forward_reference_resolves():
    # names bound AFTER decoration must resolve (live globals)
    import tests._dy2s_fwdref as mod

    r = mod.entry(paddle.to_tensor(np.ones(2, np.float32)))
    assert float(r._value.sum()) == 4.0


def test_guard_raise_not_merged():
    @to_static
    def guarded(x):
        if x.sum() > 1e6:
            raise ValueError("overflow")
        return x * 2

    # concrete path: fine below the guard... under trace the if stays python
    # and raises the tracer-bool error (documented), NOT the user exception
    with pytest.raises(Exception) as ei:
        guarded(paddle.to_tensor(np.ones(2, np.float32)))
    assert "overflow" not in str(ei.value)


def test_break_in_nested_loop_ok():
    @to_static
    def f(x):
        if x.sum() > 0:
            for k in range(3):
                if k == 1:
                    break
            y = x * 2
        else:
            y = x - 1
        return y.sum()

    assert float(f(paddle.to_tensor(np.ones(2, np.float32)))._value) == 4.0


def test_while_invariant_stays_python():
    @to_static
    def f(x):
        n = 3
        s = paddle.zeros([])
        while s < n:
            s = s + x.sum()
        acc = 0
        for k in range(n):  # n must still be a python int
            acc += k
        return s + acc

    r = f(paddle.to_tensor(np.array(2.0, np.float32)))
    assert float(r._value) == 7.0


@to_static
def _tensor_range_loop(n, x):
    acc = paddle.zeros([])
    for i in range(n):  # n is a Tensor -> traced while_loop
        acc = acc + x.sum() + i
    return acc


def test_for_over_tensor_range():
    n = paddle.to_tensor(np.array(4, np.int32))
    x = paddle.to_tensor(np.ones(2, np.float32))
    # sum over i in 0..3 of (2 + i) = 8 + 6 = 14
    assert float(_tensor_range_loop(n, x)._value) == 14.0


@to_static
def _python_range_loop(x):
    acc = 0.0
    for i in range(3):  # concrete: exact python semantics
        acc = acc + i
    return x + acc


def test_for_over_python_range_preserved():
    r = _python_range_loop(paddle.to_tensor(np.zeros(1, np.float32)))
    assert float(r._value[0]) == 3.0
