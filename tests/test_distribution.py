"""paddle.distribution parity tests: log_prob/entropy/mean/variance checked
against scipy.stats, KL against numerical integration or closed forms,
transforms against autodiff jacobians (reference test model:
test/distribution/test_distribution_*.py)."""

import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
from paddle_tpu import distribution as D

RTOL = 1e-5
ATOL = 1e-6


def npv(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


@pytest.fixture(autouse=True)
def _seed():
    paddle.seed(7)


class TestScalarDistributions:
    CASES = [
        (lambda: D.Normal(1.5, 2.0), st.norm(1.5, 2.0), np.linspace(-4, 6, 11)),
        (lambda: D.Uniform(-1.0, 3.0), st.uniform(-1.0, 4.0), np.linspace(-0.9, 2.9, 7)),
        (lambda: D.Laplace(0.5, 1.5), st.laplace(0.5, 1.5), np.linspace(-3, 4, 9)),
        (lambda: D.Gumbel(0.3, 1.2), st.gumbel_r(0.3, 1.2), np.linspace(-2, 5, 9)),
        (lambda: D.Cauchy(0.0, 2.0), st.cauchy(0.0, 2.0), np.linspace(-5, 5, 9)),
        (lambda: D.Beta(2.0, 3.0), st.beta(2.0, 3.0), np.linspace(0.05, 0.95, 9)),
        (lambda: D.Gamma(2.5, 1.5), st.gamma(2.5, scale=1 / 1.5), np.linspace(0.1, 6, 9)),
        (lambda: D.Exponential(0.7), st.expon(scale=1 / 0.7), np.linspace(0.1, 5, 9)),
        (lambda: D.LogNormal(0.2, 0.8), st.lognorm(0.8, scale=np.exp(0.2)), np.linspace(0.2, 5, 9)),
        (lambda: D.StudentT(5.0, 0.5, 2.0), st.t(5.0, 0.5, 2.0), np.linspace(-4, 5, 9)),
    ]

    @pytest.mark.parametrize("mk,ref,xs", CASES, ids=lambda c: str(c)[:24])
    def test_log_prob(self, mk, ref, xs):
        d = mk()
        np.testing.assert_allclose(npv(d.log_prob(xs)), ref.logpdf(xs), rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("mk,ref,xs", CASES, ids=lambda c: str(c)[:24])
    def test_entropy(self, mk, ref, xs):
        d = mk()
        np.testing.assert_allclose(npv(d.entropy()), ref.entropy(), rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize(
        "mk,ref",
        [(c[0], c[1]) for c in CASES if "Cauchy" not in repr(c[0]())],
        ids=lambda c: str(c)[:24],
    )
    def test_mean_var(self, mk, ref):
        d = mk()
        np.testing.assert_allclose(npv(d.mean), ref.mean(), rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(npv(d.variance), ref.var(), rtol=1e-4, atol=1e-6)

    def test_sample_statistics(self):
        d = D.Normal(np.float32(2.0), np.float32(0.5))
        s = npv(d.sample((20000,)))
        assert abs(s.mean() - 2.0) < 0.02
        assert abs(s.std() - 0.5) < 0.02

    def test_rsample_gradient_flows(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu._core import random as rng

        def f(mu):
            with rng.key_scope(jax.random.key(0)):
                d = D.Normal(mu, 1.0)
                return jnp.mean(npv_traced(d.rsample((64,))))

        def npv_traced(t):
            return t._value

        g = jax.grad(f)(jnp.float32(0.3))
        np.testing.assert_allclose(g, 1.0, rtol=1e-4)


class TestDiscrete:
    def test_bernoulli(self):
        d = D.Bernoulli(0.3)
        ref = st.bernoulli(0.3)
        np.testing.assert_allclose(npv(d.log_prob(1.0)), ref.logpmf(1), rtol=1e-5)
        np.testing.assert_allclose(npv(d.entropy()), ref.entropy(), rtol=1e-5)
        np.testing.assert_allclose(npv(d.mean), 0.3, rtol=1e-6)

    def test_categorical(self):
        logits = np.log(np.array([0.2, 0.3, 0.5]))
        d = D.Categorical(logits)
        np.testing.assert_allclose(npv(d.log_prob(np.array(2))), np.log(0.5), rtol=1e-5)
        np.testing.assert_allclose(
            npv(d.entropy()), -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5)), rtol=1e-5
        )
        s = npv(d.sample((8000,)))
        freq = np.bincount(s, minlength=3) / 8000
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.03)

    def test_int_params_accepted(self):
        # constructors must cast python-int params to float for sampling
        for d in [D.Normal(0, 1), D.Uniform(0, 1), D.Laplace(0, 1), D.Gumbel(0, 1), D.Cauchy(0, 2)]:
            s = npv(d.sample((4,)))
            assert s.shape == (4,)

    def test_geometric_mean_matches_samples(self):
        d = D.Geometric(0.5)
        s = npv(d.sample((20000,)))
        np.testing.assert_allclose(npv(d.mean), s.mean(), atol=0.05)
        np.testing.assert_allclose(npv(d.mean), 1.0, atol=1e-6)

    def test_geometric(self):
        d = D.Geometric(0.25)
        ref = st.geom(0.25, loc=-1)  # scipy counts trials; shift to failures
        for k in [0, 1, 2, 5]:
            np.testing.assert_allclose(npv(d.log_prob(float(k))), ref.logpmf(k), rtol=1e-5)

    def test_poisson(self):
        d = D.Poisson(3.5)
        ref = st.poisson(3.5)
        ks = np.arange(0, 10, dtype=np.float32)
        np.testing.assert_allclose(npv(d.log_prob(ks)), ref.logpmf(ks), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(npv(d.entropy()), ref.entropy(), rtol=1e-3)

    def test_binomial(self):
        d = D.Binomial(10, 0.4)
        ref = st.binom(10, 0.4)
        ks = np.arange(0, 11, dtype=np.float32)
        np.testing.assert_allclose(npv(d.log_prob(ks)), ref.logpmf(ks), rtol=1e-4, atol=1e-5)

    def test_multinomial(self):
        p = np.array([0.3, 0.3, 0.4])
        d = D.Multinomial(6, p)
        ref = st.multinomial(6, p)
        x = np.array([2.0, 1.0, 3.0])
        np.testing.assert_allclose(npv(d.log_prob(x)), ref.logpmf(x), rtol=1e-5)
        s = npv(d.sample((50,)))
        assert s.shape == (50, 3)
        np.testing.assert_allclose(s.sum(-1), 6)


class TestMultivariate:
    def test_dirichlet(self):
        a = np.array([2.0, 3.0, 5.0])
        d = D.Dirichlet(a)
        ref = st.dirichlet(a)
        x = np.array([0.2, 0.3, 0.5])
        np.testing.assert_allclose(npv(d.log_prob(x)), ref.logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(npv(d.entropy()), ref.entropy(), rtol=1e-5)
        np.testing.assert_allclose(npv(d.mean), a / a.sum(), rtol=1e-6)

    def test_mvn(self):
        mu = np.array([1.0, -0.5])
        cov = np.array([[2.0, 0.3], [0.3, 1.0]])
        d = D.MultivariateNormal(mu, covariance_matrix=cov)
        ref = st.multivariate_normal(mu, cov)
        x = np.array([0.5, 0.5])
        np.testing.assert_allclose(npv(d.log_prob(x)), ref.logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(npv(d.entropy()), ref.entropy(), rtol=1e-5)
        s = npv(d.sample((30000,)))
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.06)


class TestKL:
    def test_normal_normal_closed_form(self):
        p, q = D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)
        expected = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(npv(D.kl_divergence(p, q)), expected, rtol=1e-5)

    @pytest.mark.parametrize(
        "p,q,dist",
        [
            (D.Beta(2.0, 3.0), D.Beta(3.0, 2.0), (st.beta(2, 3), st.beta(3, 2))),
            (D.Gamma(2.0, 1.0), D.Gamma(3.0, 2.0), (st.gamma(2.0), st.gamma(3.0, scale=0.5))),
            (D.Laplace(0.0, 1.0), D.Laplace(1.0, 2.0), (st.laplace(0, 1), st.laplace(1, 2))),
        ],
    )
    def test_kl_vs_numeric(self, p, q, dist):
        sp, sq = dist
        xs = np.linspace(1e-4, 0.9999, 200001) if isinstance(p, D.Beta) else np.linspace(-20, 30, 200001)
        px = sp.pdf(xs)
        integrand = np.where(px > 0, px * (sp.logpdf(xs) - sq.logpdf(xs)), 0.0)
        numeric = np.trapezoid(integrand, xs)
        np.testing.assert_allclose(npv(D.kl_divergence(p, q)), numeric, rtol=1e-2, atol=1e-4)

    def test_kl_expfamily_fallback_matches_closed_form(self):
        from paddle_tpu.distribution.kl import _kl_expfamily

        p, q = D.Normal(0.3, 1.2), D.Normal(-0.5, 0.8)
        np.testing.assert_allclose(
            npv(_kl_expfamily(p, q)), npv(D.kl_divergence(p, q)), rtol=1e-4
        )

    def test_registry_dispatch_custom(self):
        class MyNormal(D.Normal):
            pass

        @D.register_kl(MyNormal, MyNormal)
        def _kl_mine(p, q):
            return paddle.to_tensor(42.0)

        assert float(D.kl_divergence(MyNormal(0.0, 1.0), MyNormal(0.0, 1.0))) == 42.0
        # base pair still uses the builtin rule
        assert float(D.kl_divergence(D.Normal(0.0, 1.0), D.Normal(0.0, 1.0))) == 0.0


class TestTransforms:
    @pytest.mark.parametrize(
        "t,xs",
        [
            (D.ExpTransform(), np.linspace(-2, 2, 7)),
            (D.AffineTransform(1.0, 3.0), np.linspace(-2, 2, 7)),
            (D.SigmoidTransform(), np.linspace(-3, 3, 7)),
            (D.TanhTransform(), np.linspace(-2, 2, 7)),
            (D.PowerTransform(3.0), np.linspace(0.2, 2, 7)),
        ],
    )
    def test_roundtrip_and_jacobian(self, t, xs):
        y = npv(t.forward(xs.astype(np.float32)))
        back = npv(t.inverse(y))
        np.testing.assert_allclose(back, xs, rtol=1e-4, atol=1e-5)
        # |dy/dx| from finite differences
        import jax

        f = lambda x: t._forward(x)
        fd = np.asarray(jax.vmap(jax.grad(f))(np.float32(xs)))
        np.testing.assert_allclose(
            npv(t.forward_log_det_jacobian(xs.astype(np.float32))),
            np.log(np.abs(fd)),
            rtol=1e-3,
            atol=1e-4,
        )

    def test_chain(self):
        chain = D.ChainTransform([D.AffineTransform(0.0, 2.0), D.ExpTransform()])
        x = np.float32(0.5)
        np.testing.assert_allclose(npv(chain.forward(x)), np.exp(1.0), rtol=1e-5)
        np.testing.assert_allclose(npv(chain.inverse(np.exp(1.0))), 0.5, rtol=1e-5)
        np.testing.assert_allclose(
            npv(chain.forward_log_det_jacobian(x)), np.log(2.0) + 1.0, rtol=1e-5
        )

    def test_stickbreaking(self):
        t = D.StickBreakingTransform()
        x = np.array([0.3, -0.2, 0.8], np.float32)
        y = npv(t.forward(x))
        assert y.shape == (4,)
        np.testing.assert_allclose(y.sum(), 1.0, rtol=1e-5)
        np.testing.assert_allclose(npv(t.inverse(y)), x, rtol=1e-4, atol=1e-5)
        # jacobian vs autodiff det of the (K-1)x(K-1) leading block
        import jax
        import jax.numpy as jnp

        J = jax.jacfwd(lambda v: t._forward(v)[:-1])(x)
        np.testing.assert_allclose(
            npv(t.forward_log_det_jacobian(x)),
            np.log(np.abs(np.linalg.det(np.asarray(J)))),
            rtol=1e-4,
        )

    def test_transformed_distribution_lognormal(self):
        td = D.TransformedDistribution(D.Normal(0.2, 0.8), [D.ExpTransform()])
        ref = st.lognorm(0.8, scale=np.exp(0.2))
        xs = np.linspace(0.2, 5, 9).astype(np.float32)
        np.testing.assert_allclose(npv(td.log_prob(xs)), ref.logpdf(xs), rtol=1e-4)
        s = npv(td.sample((4000,)))
        assert (s > 0).all()


class TestIndependent:
    def test_log_prob_sums_event_dims(self):
        d = D.Independent(D.Normal(np.zeros((3, 4)), np.ones((3, 4))), 1)
        assert d.batch_shape == (3,)
        assert d.event_shape == (4,)
        x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
        expected = st.norm(0, 1).logpdf(x).sum(-1)
        np.testing.assert_allclose(npv(d.log_prob(x)), expected, rtol=1e-4)
        np.testing.assert_allclose(npv(d.entropy()), st.norm(0, 1).entropy() * 4 * np.ones(3), rtol=1e-5)
