"""TP-sharded serving: the WHOLE engine feature set over the mesh
(ROADMAP item 1, docs/DECODE.md sharded-serving section).

The contract under test: an engine built with ``mesh=`` must serve every
feature the single-device engine serves — full-precision AND int8 paged
pools (payload + quant scales sharded leaf-wise on the KV-head dim),
multi-tenant adapter packs (A/B factors on their base projections'
Megatron split), greedy AND seeded-sampling requests — with token
streams BIT-IDENTICAL to the single-device engine, on 2- and 4-device
meshes.  Hot-swapping an adapter on a sharded engine stays
zero-recompile, the mesh lint passes the sharded engine clean, and the
telemetry reports sharding-divided per-device pool bytes.

Every test here dispatches GSPMD-partitioned decode programs over the
in-process multi-device XLA:CPU communicator — the intermittent
SIGSEGV class tools/run_tier1.py contains — so this module rides a
DEDICATED isolated worker (ISOLATED_DEFAULT), never a round-robin shard.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.distributed.auto_parallel import ProcessMesh
from paddle_tpu.nn.lora import apply_lora, lora_state_dict
from paddle_tpu.ops import paged_attention as pa
from paddle_tpu.serving import GenerationEngine

_KW = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=4, max_position_embeddings=64,
           dtype="float32")


def _cfg(**kw):
    from paddle_tpu.models.llama import llama_tiny

    base = dict(_KW)
    base.update(kw)
    return llama_tiny(**base)


def _model(seed=41, **kw):
    from paddle_tpu.models.llama import LlamaForCausalLM

    paddle.seed(seed)
    m = LlamaForCausalLM(_cfg(**kw))
    m.eval()
    return m


def _mesh(mp):
    return ProcessMesh(np.arange(mp), ["mp"])


def _adapter_sd(base, key_seed, rank=4):
    from paddle_tpu.models.llama import LlamaForCausalLM

    ft = LlamaForCausalLM(_cfg())
    ft.set_state_dict(base.state_dict())
    ft.eval()
    apply_lora(ft, rank=rank, alpha=8)
    key = jax.random.PRNGKey(key_seed)
    for name, p in ft.named_parameters():
        if name.endswith(("lora_A", "lora_B")):
            key, sk = jax.random.split(key)
            scale = 0.2 if name.endswith("lora_B") else 0.05
            p._bind(jax.random.normal(sk, p._value.shape,
                                      jnp.float32) * scale)
    return lora_state_dict(ft)


def _drain(eng):
    while eng.has_work():
        eng.step()


# Greedy + seeded-sampled requests, with a mid-flight join: the workload
# every mesh-vs-single comparison below replays identically (submit order
# fixes the PRNG nonces, so sampled streams are comparable bit-for-bit).
def _run_workload(eng):
    eng.add_request("g", [5, 9, 17, 33, 2], max_new_tokens=8)
    eng.step()
    eng.add_request("s", [7, 11, 3], max_new_tokens=6,
                    temperature=3.0, seed=42)  # joins mid-flight
    _drain(eng)
    return {"g": eng.result("g"), "s": eng.result("s")}


# ------------------------------------------------ plain × {bf16, int8}
@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
@pytest.mark.parametrize("mp", [2, 4])
def test_plain_engine_mesh_matches_single_device(mp, kv_dtype):
    """Greedy AND seeded-sampling streams bit-identical mesh-vs-single
    for full-precision and int8 pools, on 2- and 4-device meshes."""
    ref = _run_workload(GenerationEngine(
        _model(), max_batch=2, block_size=8, num_blocks=16,
        kv_cache_dtype=kv_dtype))
    eng = GenerationEngine(_model(), max_batch=2, block_size=8,
                           num_blocks=16, kv_cache_dtype=kv_dtype,
                           mesh=_mesh(mp))
    # pools really committed to the KV-head sharding (scales too on int8)
    for _part, arr in pa.pool_parts(eng._kpools[0]):
        assert "mp" in str(arr.sharding.spec)
    got = _run_workload(eng)
    assert got == ref
    assert len(got["s"]) == 6 and got["s"] != got["g"][:6]


# ------------------------------------------------- adapters × mesh
_AD_PROMPTS = {"a0": [5, 9, 17, 33, 2], "a1": [7, 11, 3, 20],
               "base": [5, 9, 17, 33, 2]}
_AD_OF = {"a0": "t0", "a1": "t1", "base": None}


def _run_adapter_workload(eng, sds):
    for name, sd in sds.items():
        eng.register_adapter(name, sd, alpha=8)
    for rid, prompt in _AD_PROMPTS.items():
        eng.add_request(rid, prompt, max_new_tokens=6, adapter=_AD_OF[rid])
    eng.add_request("samp", [15, 4, 40], max_new_tokens=5,
                    temperature=2.5, seed=9, adapter="t0")
    _drain(eng)
    return {rid: eng.result(rid)
            for rid in list(_AD_PROMPTS) + ["samp"]}


@pytest.mark.parametrize("kv_dtype,mp", [("bf16", 2), ("bf16", 4),
                                         ("int8", 2)])
def test_adapter_engine_mesh_matches_single_device(mp, kv_dtype):
    """Mixed-adapter batches (two tenants + a base row + a sampled
    adapter row) decode in ONE sharded dispatch, bit-identical to the
    single-device adapter engine — the PR-10 adapters×mesh
    NotImplementedError is gone; int8×adapters×mesh composes too."""
    base = _model()
    sds = {f"t{i}": _adapter_sd(base, key_seed=10 + i) for i in range(2)}

    def build(mesh):
        return GenerationEngine(_model(), max_batch=4, block_size=8,
                                num_blocks=32, kv_cache_dtype=kv_dtype,
                                adapters={"rank": 4, "max_adapters": 2},
                                mesh=mesh)

    ref = _run_adapter_workload(build(None), sds)
    assert len({tuple(v) for v in ref.values()}) >= 3  # tenants differ
    eng = build(_mesh(mp))
    # pack factors ride the base projections' Megatron split: col targets
    # shard B's out dim, row targets shard A's in dim
    a_q, b_q = eng._pack.ab["self_attn.q_proj"]
    a_o, b_o = eng._pack.ab["self_attn.o_proj"]
    assert "mp" in str(b_q.sharding.spec) and "mp" not in str(
        a_q.sharding.spec)
    assert "mp" in str(a_o.sharding.spec) and "mp" not in str(
        b_o.sharding.spec)
    got = _run_adapter_workload(eng, sds)
    assert got == ref


def test_sharded_hot_swap_zero_recompiles():
    """Acceptance gate: adapter hot-swap on a SHARDED engine performs
    zero XLA recompiles after a warm swap cycle — set_slot's scatter
    re-commits every pack array to its recorded placement, so the swap
    executables and the decode step keep one argument-sharding
    signature across swaps (nn.AdapterPack._replace)."""
    model = _model()
    sd_a = _adapter_sd(model, key_seed=40)
    sd_b = _adapter_sd(model, key_seed=41)
    sd_w = _adapter_sd(model, key_seed=42)
    prompt = list(range(1, 25))

    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=32,
                           adapters={"rank": 4, "max_adapters": 1},
                           prefix_cache=True, mesh=_mesh(2))
    # warm cycle: swap machinery scatters + the eager dispatch cache's
    # prefill hotness ramp both settle before the measured window
    for name, sd in (("a", sd_a), ("w", sd_w)):
        eng.register_adapter(name, sd, alpha=8)
        eng.add_request(f"r_{name}", prompt, max_new_tokens=4, adapter=name)
        _drain(eng)

    c0 = profiler.compile_stats()["compiles"]
    eng.register_adapter("b", sd_b, alpha=8)  # evicts idle 'w': a swap
    eng.add_request("rb", prompt, max_new_tokens=4, adapter="b")
    _drain(eng)
    assert profiler.compile_stats()["compiles"] - c0 == 0
    assert eng.result("rb")  # the swapped tenant actually served
    # the pack stayed committed to its placements through the swap
    a_o, _b_o = eng._pack.ab["self_attn.o_proj"]
    assert "mp" in str(a_o.sharding.spec)


# ----------------------------------------- lint + telemetry satellites
def test_sharded_engine_lints_clean_and_reports_per_device():
    """A full-feature sharded engine (int8 + adapters, mp=2) constructs
    clean under FLAGS_verify_sharding, its HBM estimate divides the pool
    AND scale groups by the mesh, and decode_stats/summary report the
    per-device bytes + mesh shape."""
    from paddle_tpu.static.mesh_lint import lint_engine
    from paddle_tpu.serving import decode_stats

    prev = {"FLAGS_verify_sharding":
            paddle.get_flags("FLAGS_verify_sharding")["FLAGS_verify_sharding"]}
    paddle.set_flags({"FLAGS_verify_sharding": True})
    try:
        eng = GenerationEngine(_model(), max_batch=2, block_size=8,
                               num_blocks=16, kv_cache_dtype="int8",
                               adapters={"rank": 4, "max_adapters": 2},
                               mesh=_mesh(2))
        violations, est = lint_engine(eng)
        assert violations == []
        single = GenerationEngine(_model(), max_batch=2, block_size=8,
                                  num_blocks=16, kv_cache_dtype="int8",
                                  adapters={"rank": 4, "max_adapters": 2})
        _ok, est1 = lint_engine(single)
        # per-device pool/scale bytes are the single-device bytes / mp
        assert est["kv_pools"] * 2 == est1["kv_pools"]
        assert est["kv_scales"] * 2 == est1["kv_scales"]
    finally:
        paddle.set_flags(prev)

    # the LAST engine built was the single-device twin; rebuild sharded
    eng = GenerationEngine(_model(), max_batch=2, block_size=8,
                           num_blocks=16, mesh=_mesh(2))
    st = decode_stats()
    assert st["mesh_shape"] == "mp2"
    assert st["pool_bytes_per_device"] * 2 == st["pool_bytes"]
    eng.add_request("r", [5, 9, 17], max_new_tokens=3)
    _drain(eng)
    prof = profiler.Profiler(timer_only=True)
    with prof:
        pass
    out = prof.summary()
    assert "Sharded serving: mesh=mp2" in out
    assert "pool_bytes/device=%d" % st["pool_bytes_per_device"] in out


@pytest.fixture
def _sched_scratch(tmp_path):
    """Scratch autotune cache + clean decode counters for the schedule
    search adopt-path tests (verdicts must not land in checked-in
    seeds)."""
    from paddle_tpu.ops import autotune as at
    from paddle_tpu.serving import reset_schedule_decode_stats

    prev = paddle.get_flags("FLAGS_autotune_cache_dir")
    paddle.set_flags({"FLAGS_autotune_cache_dir": str(tmp_path)})
    at._CACHES.clear()
    reset_schedule_decode_stats()
    yield tmp_path
    paddle.set_flags(prev)
    at._CACHES.clear()


def _win(fn, args, *, label, config):
    return 0.4 if config is not None else 1.0


def test_sharded_engine_adopts_fused_decode_chain(_sched_scratch):
    """Schedule search OVER the mesh (docs/SCHEDULE_SEARCH.md): a
    TP-sharded engine whose head counts the mp axis divides searches the
    MESH spec — verdict keyed by (device kind, mesh shape), parity gated
    against the sharded XLA twin, kernel collectives statically linted —
    and an adoption runs the in-scan chain as one shard_map'd Pallas
    dispatch with streams BIT-IDENTICAL to the search-off sharded
    engine."""
    from paddle_tpu.serving import schedule_decode_stats
    from paddle_tpu.static import schedule_search as ss

    ref = _run_workload(GenerationEngine(
        _model(), max_batch=2, block_size=8, num_blocks=16, mesh=_mesh(2)))
    paddle.set_flags({"FLAGS_schedule_search": True})
    try:
        with ss.measure_override(_win):
            eng = GenerationEngine(_model(), max_batch=2, block_size=8,
                                   num_blocks=16, mesh=_mesh(2))
            got = _run_workload(eng)
    finally:
        paddle.set_flags({"FLAGS_schedule_search": False})
    assert got == ref
    stats = schedule_decode_stats()
    assert stats["decode_chains_mesh_fused"] >= 1
    assert stats["decode_chains_found"] >= 1
    assert stats["decode_chains_accepted"] >= 1
    assert stats["decode_chains_mesh_skipped"] == 0
    assert profiler.schedule_search_stats()["decode_chains_mesh_fused"] >= 1


def test_sharded_engine_skips_decode_chain_replicated_pools(_sched_scratch):
    """The counted mesh skip SURVIVES for engines whose pools ride
    replicated (head counts the mp axis doesn't divide — the
    constructor's fallback): there is no head-local layout to fuse over,
    so the searcher is never consulted and the streams stay the unfused
    sharded path."""
    from paddle_tpu.serving import schedule_decode_stats
    from paddle_tpu.static import schedule_search as ss

    kw = dict(num_attention_heads=4, num_key_value_heads=1)
    ref = _run_workload(GenerationEngine(
        _model(**kw), max_batch=2, block_size=8, num_blocks=16,
        mesh=_mesh(2)))
    paddle.set_flags({"FLAGS_schedule_search": True})
    try:
        with ss.measure_override(_win):
            got = _run_workload(GenerationEngine(
                _model(**kw), max_batch=2, block_size=8, num_blocks=16,
                mesh=_mesh(2)))
    finally:
        paddle.set_flags({"FLAGS_schedule_search": False})
    assert got == ref
    stats = schedule_decode_stats()
    assert stats["decode_chains_mesh_skipped"] >= 1
    assert stats["decode_chains_found"] == 0  # never consulted a searcher
    assert stats["decode_chains_mesh_fused"] == 0
    assert profiler.schedule_search_stats()["decode_chains_mesh_skipped"] >= 1
