"""LoRA training tier (nn/lora.py): LoRALinear surgery, frozen-base
fine-tuning through TrainStep, merge/unmerge, adapter-only checkpoints
through CheckpointManager, and the fine-tune -> save adapter ->
fresh-engine serve round trip (docs/LORA.md).
"""

import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit
from paddle_tpu import optimizer as opt
from paddle_tpu.nn.lora import (AdapterPack, LoRALinear, apply_lora,
                                lora_state_dict, parse_adapter_state_dict)

import jax
import jax.numpy as jnp


def _tiny_cfg(**kw):
    from paddle_tpu.models.llama import llama_tiny

    base = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=4, max_position_embeddings=64,
                dtype="float32")
    base.update(kw)
    return llama_tiny(**base)


def _base_model(seed=41, **kw):
    from paddle_tpu.models.llama import LlamaForCausalLM

    paddle.seed(seed)
    m = LlamaForCausalLM(_tiny_cfg(**kw))
    m.eval()
    return m


def _lora_clone(base, rank=4, alpha=8, b_scale=0.05, key_seed=7):
    """A LoRA-adapted copy of `base` with nonzero lora_B (a freshly
    initialized adapter is the identity — B starts at zero — so tests
    that need the adapter to DO something perturb B)."""
    from paddle_tpu.models.llama import LlamaForCausalLM

    ft = LlamaForCausalLM(_tiny_cfg())
    ft.set_state_dict(base.state_dict())
    ft.eval()
    apply_lora(ft, rank=rank, alpha=alpha)
    key = jax.random.PRNGKey(key_seed)
    for name, p in ft.named_parameters():
        if name.endswith("lora_B"):
            key, sk = jax.random.split(key)
            p._bind(jax.random.normal(sk, p._value.shape,
                                      jnp.float32) * b_scale)
    return ft


# ---------------------------------------------------------------- LoRALinear


def test_lora_linear_starts_at_base_and_matches_manual():
    import paddle_tpu.nn as nn

    paddle.seed(0)
    lin = nn.Linear(8, 6)
    lora = LoRALinear.from_linear(lin, rank=2, alpha=4)
    x = paddle.to_tensor(np.random.default_rng(0)
                         .standard_normal((3, 8), np.float32))
    # lora_B starts at zero: the adapted layer IS the base layer
    np.testing.assert_array_equal(np.asarray(lora(x)._value),
                                  np.asarray(lin(x)._value))
    # nonzero B: forward == base + (x A) B * alpha/rank
    lora.lora_B._bind(jnp.ones((2, 6), jnp.float32) * 0.1)
    want = (np.asarray(lin(x)._value)
            + (np.asarray(x._value) @ np.asarray(lora.lora_A._value)
               @ np.asarray(lora.lora_B._value)) * 2.0)
    np.testing.assert_allclose(np.asarray(lora(x)._value), want, rtol=1e-5)


def test_lora_linear_merge_unmerge_round_trip():
    import paddle_tpu.nn as nn

    paddle.seed(1)
    lin = nn.Linear(8, 6)
    lora = LoRALinear.from_linear(lin, rank=2, alpha=4)
    lora.lora_B._bind(jnp.asarray(np.random.default_rng(1)
                                  .standard_normal((2, 6), np.float32)))
    x = paddle.to_tensor(np.random.default_rng(2)
                         .standard_normal((3, 8), np.float32))
    want = np.asarray(lora(x)._value)
    w0 = np.asarray(lora.weight._value).copy()
    lora.merge()
    assert lora.merged
    # merged: the plain xW+b path computes the adapted function
    np.testing.assert_allclose(np.asarray(lora(x)._value), want, rtol=1e-5)
    lora.merge()  # idempotent
    lora.unmerge()
    np.testing.assert_allclose(np.asarray(lora.weight._value), w0,
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(lora(x)._value), want, rtol=1e-5)


def test_lora_linear_rejects_bad_rank():
    with pytest.raises(ValueError, match="rank"):
        LoRALinear(4, 4, rank=0)


# ----------------------------------------------------------------- apply_lora


def test_apply_lora_surgery_keeps_keys_and_freezes_base():
    m = _base_model()
    keys_before = set(m.state_dict())
    apply_lora(m, rank=4, alpha=8)
    keys_after = set(m.state_dict())
    # base keys unchanged (q_proj.weight stays q_proj.weight), only
    # lora_A/lora_B added — existing checkpoints keep loading
    assert keys_before <= keys_after
    added = keys_after - keys_before
    assert added and all(k.rsplit(".", 1)[-1] in ("lora_A", "lora_B")
                         for k in added)
    # frozen-base contract: only adapter params are trainable
    trainable = [n for n, p in m.named_parameters() if not p.stop_gradient]
    assert trainable
    assert all(n.endswith(("lora_A", "lora_B")) for n in trainable)
    # surgery hit exactly the q/k/v/o + MLP projections per layer
    n_layers = m.config.num_hidden_layers
    assert len(added) == 2 * 6 * n_layers


def test_apply_lora_raises_on_layer_stack_and_missing_targets():
    m = _base_model(fuse_layer_stack=True)
    with pytest.raises(ValueError, match="LayerStack"):
        apply_lora(m, rank=4)
    m2 = _base_model()
    with pytest.raises(ValueError, match="no Linear layer"):
        apply_lora(m2, rank=4, targets=("does_not_exist",))


def test_frozen_base_finetune_through_train_step():
    base = _base_model()
    base_vals = {k: np.asarray(v._value).copy()
                 for k, v in base.state_dict().items()}
    from paddle_tpu.models.llama import LlamaForCausalLM

    ft = LlamaForCausalLM(_tiny_cfg())
    ft.set_state_dict(base.state_dict())
    apply_lora(ft, rank=4, alpha=8)
    o = opt.AdamW(learning_rate=3e-2, parameters=ft.parameters())
    step = jit.TrainStep(ft, o, lambda mm, x, y: mm(x, y)[0])
    rng = np.random.default_rng(1)
    x = paddle.to_tensor(rng.integers(0, 128, (2, 8)).astype(np.int32))
    y = paddle.to_tensor(rng.integers(0, 128, (2, 8)).astype(np.int32))
    losses = [float(step(x, y)._value) for _ in range(12)]
    assert losses[-1] < losses[0]  # the adapters learn
    moved = False
    for k, v in ft.state_dict().items():
        leaf = k.rsplit(".", 1)[-1]
        if leaf in ("lora_A", "lora_B"):
            moved = True
            continue
        # every base tensor is BIT-identical to before training
        np.testing.assert_array_equal(np.asarray(v._value), base_vals[k],
                                      err_msg=k)
    assert moved


# ----------------------------------------- adapter state dicts + AdapterPack


def test_lora_state_dict_and_parse():
    base = _base_model()
    ft = _lora_clone(base)
    sd = lora_state_dict(ft)
    n_layers = base.config.num_hidden_layers
    assert len(sd) == 2 * 6 * n_layers
    from paddle_tpu.nn.lora import LLAMA_TARGETS

    arrays = parse_adapter_state_dict(sd, n_layers, LLAMA_TARGETS, rank=4)
    assert set(arrays) == set(LLAMA_TARGETS)
    A, B = arrays["self_attn.q_proj"]
    assert A.shape == (n_layers, 32, 4) and B.shape == (n_layers, 4, 32)
    # rank mismatch is loud — pack geometry is fixed
    with pytest.raises(ValueError, match="rank"):
        parse_adapter_state_dict(sd, n_layers, LLAMA_TARGETS, rank=8)
    # a key targeting a projection outside the pack's geometry is loud
    with pytest.raises(ValueError, match="does not cover"):
        parse_adapter_state_dict(sd, n_layers, ("self_attn.q_proj",), rank=4)
    with pytest.raises(ValueError, match="no LoRA parameters"):
        lora_state_dict(base)


def test_adapter_pack_geometry_and_slot_protocol():
    base = _base_model()
    pack = AdapterPack(base, rank=4, alpha=8, max_adapters=3)
    assert pack.num_slots == 4  # 3 usable + reserved slot 0
    assert pack.rank == 4
    A, B = pack.ab["self_attn.q_proj"]
    assert A.shape == (2, 4, 32, 4) and B.shape == (2, 4, 4, 32)
    assert float(pack.scaling[0]) == 0.0  # slot 0 = zero adapter
    ft = _lora_clone(base)
    arrays = parse_adapter_state_dict(lora_state_dict(ft), 2, pack.targets, 4)
    pack.set_slot(1, arrays, alpha=8)
    assert float(pack.scaling[1]) == 2.0
    assert np.abs(np.asarray(pack.ab["self_attn.q_proj"][0][:, 1])).sum() > 0
    pack.clear_slot(1)
    assert float(pack.scaling[1]) == 0.0
    assert np.abs(np.asarray(pack.ab["self_attn.q_proj"][0][:, 1])).sum() == 0
    # slot 0 is untouchable
    with pytest.raises(IndexError, match="slot 0"):
        pack.set_slot(0, arrays)
    with pytest.raises(IndexError):
        pack.clear_slot(0)
    # FLAGS_lora_max_adapters is the default slot budget
    paddle.set_flags({"FLAGS_lora_max_adapters": 2})
    try:
        assert AdapterPack(base, rank=4).num_slots == 3
    finally:
        paddle.set_flags({"FLAGS_lora_max_adapters": 8})
    # pack bytes are visible (mesh lint accounts them via parts())
    assert pack.nbytes == sum(a.nbytes for _n, a in pack.parts())


# ------------------------------------------- satellite: partial state loads


def test_set_state_dict_allow_partial_loads_adapter_only():
    base = _base_model()
    ft = _lora_clone(base, b_scale=0.1)
    sd = lora_state_dict(ft)
    # a fresh adapted model (zero B) partial-loads the trained adapter
    fresh = _lora_clone(base, b_scale=0.0)
    missing, unexpected = fresh.set_state_dict(sd, allow_partial=True)
    assert missing and not unexpected  # base keys missing BY DESIGN
    for k, v in lora_state_dict(fresh).items():
        np.testing.assert_array_equal(np.asarray(v._value),
                                      np.asarray(sd[k]._value), err_msg=k)
    # base weights untouched by the partial load
    np.testing.assert_array_equal(
        np.asarray(fresh.model.embed_tokens.weight._value),
        np.asarray(base.model.embed_tokens.weight._value))


def test_set_state_dict_allow_partial_unexpected_keys_still_loud():
    base = _base_model()
    ft = _lora_clone(base)
    sd = dict(lora_state_dict(ft))
    sd["not.a.real.key"] = paddle.to_tensor(np.zeros((2, 2), np.float32))
    before = {k: np.asarray(v._value).copy()
              for k, v in lora_state_dict(ft).items()}
    fresh = _lora_clone(base, b_scale=0.0)
    with pytest.raises(ValueError, match="cannot place"):
        fresh.set_state_dict(sd, allow_partial=True)
    # the refused load mutated NOTHING (checked before any set_value)
    for k, v in lora_state_dict(fresh).items():
        if k.endswith("lora_B"):
            assert np.abs(np.asarray(v._value)).sum() == 0.0
    del before


def test_set_state_dict_default_contract_unchanged():
    base = _base_model()
    ft = _lora_clone(base)
    sd = dict(lora_state_dict(ft))
    sd["bogus"] = paddle.to_tensor(np.zeros((1,), np.float32))
    fresh = _lora_clone(base, b_scale=0.0)
    # default path: nothing raises, the lists report
    missing, unexpected = fresh.set_state_dict(sd)
    assert "bogus" in unexpected
    assert any(k.endswith("embed_tokens.weight") for k in missing)


# --------------------------------------------------- checkpoint round trips


def test_finetune_checkpoint_fresh_engine_round_trip():
    """The acceptance round trip: fine-tune (frozen base) -> adapter-only
    checkpoint through CheckpointManager -> restore into a fresh process'
    model -> serve from a FRESH engine over the pristine base model, and
    the served stream matches the fine-tuned model's own generate()."""
    from paddle_tpu.distributed import CheckpointManager
    from paddle_tpu.models.llama import LlamaForCausalLM
    from paddle_tpu.serving import GenerationEngine

    base = _base_model()
    ft = LlamaForCausalLM(_tiny_cfg())
    ft.set_state_dict(base.state_dict())
    apply_lora(ft, rank=4, alpha=8)
    o = opt.AdamW(learning_rate=3e-2, parameters=ft.parameters())
    step = jit.TrainStep(ft, o, lambda mm, x, y: mm(x, y)[0])
    rng = np.random.default_rng(3)
    x = paddle.to_tensor(rng.integers(0, 128, (2, 8)).astype(np.int32))
    y = paddle.to_tensor(rng.integers(0, 128, (2, 8)).astype(np.int32))
    for _ in range(8):
        step(x, y)

    with tempfile.TemporaryDirectory() as d:
        CheckpointManager(d, async_save=False).save(
            1, model=lora_state_dict(ft))
        # "fresh process": a new adapted model restores ONLY the adapter
        fresh = LlamaForCausalLM(_tiny_cfg())
        fresh.set_state_dict(base.state_dict())
        apply_lora(fresh, rank=4, alpha=8)
        assert CheckpointManager(d, async_save=False).restore(
            model=lora_state_dict(fresh)) == 1

    ft.eval()
    prompt = [5, 9, 17, 33, 2]
    ref = ft.generate(paddle.to_tensor(np.asarray(prompt, np.int32)[None]),
                      max_new_tokens=6, cache="paged", block_size=8)
    ref = np.asarray(ref._value).reshape(-1).tolist()

    eng = GenerationEngine(base, max_batch=2, block_size=8, num_blocks=16,
                           adapters={"rank": 4})
    eng.register_adapter("ft", lora_state_dict(fresh), alpha=8)
    eng.add_request("r", prompt, max_new_tokens=6, adapter="ft")
    while eng.has_work():
        eng.step()
    assert eng.result("r") == ref


def test_apply_lora_gpt_finetunes_frozen_base():
    """The surgery helper covers GPT's projection names (q/k/v, out_proj,
    fc_in/fc_out) too — adapters train, base stays frozen."""
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    paddle.seed(3)
    m = GPTForCausalLM(gpt_tiny(vocab_size=128, hidden_size=32,
                                num_hidden_layers=2))
    apply_lora(m, rank=4, alpha=8)
    trainable = [n for n, p in m.named_parameters() if not p.stop_gradient]
    assert trainable and all(n.endswith(("lora_A", "lora_B"))
                             for n in trainable)
    # every gpt block projection got an adapter: attn q/k/v + out_proj +
    # fc_in + fc_out, per layer
    assert len(trainable) == 2 * 6 * 2
    o = opt.AdamW(learning_rate=3e-2, parameters=m.parameters())
    step = jit.TrainStep(m, o, lambda mm, x, y: mm(x, labels=y)[0])
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.integers(0, 128, (2, 8)).astype(np.int32))
    y = paddle.to_tensor(rng.integers(0, 128, (2, 8)).astype(np.int32))
    losses = [float(step(x, y)._value) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_parse_rejects_lopsided_layers_and_set_slot_validates_before_mutation():
    """Robustness twins: (a) a layer carrying only one of lora_A/lora_B
    (truncated checkpoint) is rejected instead of silently zero-filled;
    (b) set_slot validates EVERY target's A and B shapes before any
    scatter — a mismatch never leaves the slot half-mutated."""
    from paddle_tpu.nn.lora import LLAMA_TARGETS

    base = _base_model()
    ft = _lora_clone(base)
    sd = dict(lora_state_dict(ft))
    # (a) drop one half of one layer's pair
    del sd["model.layers.1.self_attn.q_proj.lora_B"]
    with pytest.raises(ValueError, match="lopsided"):
        parse_adapter_state_dict(sd, 2, LLAMA_TARGETS, rank=4)

    # (b) arrays with a wrong-shaped B for a LATE target (gate_up sorts
    # after the attention projections): nothing may be scattered
    pack = AdapterPack(base, rank=4, alpha=8, max_adapters=2)
    good = parse_adapter_state_dict(lora_state_dict(ft), 2, pack.targets, 4)
    bad = dict(good)
    A_gu, B_gu = bad["mlp.gate_up_proj"]
    bad["mlp.gate_up_proj"] = (A_gu, B_gu[:, :, :-1])  # truncated out dim
    before = {t: np.asarray(a).copy() for t, (a, _b) in pack.ab.items()}
    with pytest.raises(ValueError, match="pack slot expects"):
        pack.set_slot(1, bad)
    for t, (a, _b) in pack.ab.items():
        np.testing.assert_array_equal(np.asarray(a), before[t],
                                      err_msg=f"{t} mutated by failed set")
