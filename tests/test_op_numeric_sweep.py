"""Registry-driven numeric op sweep — OpTest density for the op surface.

Reference: test/legacy_test/op_test.py:417 (check_output:1997 vs NumPy,
check_grad:2944 finite differences) applied per-op across 1,340 test files.
Here ONE spec table drives the whole registered surface:

- every spec'd op: output checked against a NumPy oracle;
- every differentiable spec'd op: tape gradient checked against directional
  finite differences (utils/op_test.py check_grad_dir);
- every generated in-place variant: checked against its functional base;
- random ops: seeded statistical property checks;
- a coverage gate asserts the swept-op count stays >= the target so the
  registry cannot silently outgrow its numeric verification.

Per-op tolerances live in the spec (the reference keeps them in
test/white_list/op_accuracy_white_list.py).
"""

from __future__ import annotations

import importlib

import numpy as np
import pytest

sps = pytest.importorskip("scipy.special")

# numpy<2.0 (the declared floor) ships trapz, not trapezoid
_np_trapezoid = getattr(np, "trapezoid", None) or np.trapz

import paddle_tpu as paddle
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.framework.op_registry import build_registry
from paddle_tpu.utils.op_test import check_grad_dir

_rng = np.random.default_rng(42)


def S(*shape):  # symmetric floats in (-1, 1)
    return (_rng.uniform(-1.0, 1.0, shape) * 0.9).astype(np.float32)


def U(*shape):  # positive floats in (0.5, 1.5)
    return _rng.uniform(0.5, 1.5, shape).astype(np.float32)


def P(*shape):  # floats in (0.1, 0.9) — probability-like / logit domain
    return _rng.uniform(0.1, 0.9, shape).astype(np.float32)


def I(hi, *shape):
    return _rng.integers(0, hi, shape).astype(np.int64)


def B(*shape):
    return _rng.integers(0, 2, shape).astype(bool)


def PSD(n):  # symmetric positive-definite matrix
    a = _rng.uniform(-1, 1, (n, n)).astype(np.float32)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


def op(args, ref, grad=None, kwargs=None, rtol=1e-5, atol=1e-6,
       grtol=5e-3, gatol=2e-3, out=None, eps=1e-3, raw=False,
       shape_only=False, call=None, ref_post=None):
    """One spec row.  args: inputs (wrapped as Tensors at call time unless
    raw=True); kwargs: python kwargs; ref: numpy oracle over the raw args;
    grad: argnums to gradient-check (None = output-only); out: index when
    the op returns a tuple/list and ref covers just that element; call:
    custom invocation `call(fn, tensors)` for odd signatures; shape_only:
    compare shape/dtype, not values (e.g. empty)."""
    return dict(args=args, kwargs=kwargs or {}, ref=ref, grad=grad,
                rtol=rtol, atol=atol, grtol=grtol, gatol=gatol, out=out,
                eps=eps, raw=raw, shape_only=shape_only, call=call,
                ref_post=ref_post)


x23, y23 = S(2, 3), S(2, 3)
u23, v23 = U(2, 3), U(2, 3)
p23 = P(2, 3)
i23 = I(8, 2, 3)
j23 = I(8, 2, 3)
b23 = B(2, 3)
m44 = S(4, 4)
psd4 = PSD(4)

SPEC = {}

# --------------------------------------------------------------- math: unary
SPEC.update({
    "abs": op((x23,), np.abs, grad=[0]),
    "acos": op((p23,), np.arccos, grad=[0]),
    "acosh": op((1.5 + u23,), np.arccosh, grad=[0]),
    "asin": op((p23,), np.arcsin, grad=[0]),
    "asinh": op((x23,), np.arcsinh, grad=[0]),
    "atan": op((x23,), np.arctan, grad=[0]),
    "atanh": op((p23 * 0.8,), np.arctanh, grad=[0]),
    "ceil": op((x23 * 3,), np.ceil),
    "conj": op((x23,), np.conj),
    "cos": op((x23,), np.cos, grad=[0]),
    "cosh": op((x23,), np.cosh, grad=[0]),
    "deg2rad": op((x23 * 90,), np.deg2rad, grad=[0]),
    "digamma": op((u23 + 1,), sps.psi, grad=[0]),
    "erf": op((x23,), sps.erf, grad=[0]),
    "erfinv": op((p23 * 0.8,), sps.erfinv, grad=[0]),
    "exp": op((x23,), np.exp, grad=[0]),
    "expm1": op((x23,), np.expm1, grad=[0]),
    "floor": op((x23 * 3,), np.floor),
    "frac": op((x23 * 3,), lambda a: a - np.trunc(a), grad=[0]),
    "i0": op((x23,), sps.i0, grad=[0]),
    "i0e": op((x23,), sps.i0e),
    "i1": op((x23,), sps.i1),
    "i1e": op((x23,), sps.i1e),
    "lgamma": op((u23 + 1,), sps.gammaln, grad=[0]),
    "log": op((u23,), np.log, grad=[0]),
    "log10": op((u23,), np.log10, grad=[0]),
    "log1p": op((u23,), np.log1p, grad=[0]),
    "log2": op((u23,), np.log2, grad=[0]),
    "logit": op((p23,), lambda a: np.log(a / (1 - a)), grad=[0]),
    "nan_to_num": op((np.array([[1.0, np.nan], [np.inf, -np.inf]], np.float32),),
                     np.nan_to_num),
    "neg": op((x23,), np.negative, grad=[0]),
    "rad2deg": op((x23,), np.rad2deg, grad=[0]),
    "real": op((x23,), np.real),
    "imag": op((x23,), np.imag),
    "reciprocal": op((u23,), np.reciprocal, grad=[0]),
    "round": op((x23 * 3,), np.round),
    "rsqrt": op((u23,), lambda a: 1 / np.sqrt(a), grad=[0]),
    "sigmoid": op((x23,), lambda a: 1 / (1 + np.exp(-a)), grad=[0]),
    "sign": op((x23,), np.sign),
    "sgn": op((x23,), np.sign),
    "signbit": op((x23,), np.signbit),
    "sin": op((x23,), np.sin, grad=[0]),
    "sinh": op((x23,), np.sinh, grad=[0]),
    "sqrt": op((u23,), np.sqrt, grad=[0]),
    "square": op((x23,), np.square, grad=[0]),
    "stanh": op((x23,), lambda a: 1.7159 * np.tanh(0.67 * a), grad=[0]),
    "gamma": op((u23 + 1,), sps.gamma, rtol=1e-4, atol=1e-5),
    "tan": op((x23,), np.tan, grad=[0]),
    "tanh": op((x23,), np.tanh, grad=[0]),
    "trunc": op((x23 * 3,), np.trunc),
    "angle": op((x23,), np.angle),
    "exponent": op((u23,), lambda a: np.frexp(a)[1].astype(np.int32)),
    "multigammaln": op((u23 + 3,), lambda a: sps.multigammaln(a, 2),
                       kwargs=dict(p=2), grad=[0]),
    "polygamma": op((u23 + 1,), lambda a: sps.polygamma(1, a), kwargs=dict(n=1)),
    "isfinite": op((np.array([[1.0, np.nan], [np.inf, 2.0]], np.float32),), np.isfinite),
    "isinf": op((np.array([[1.0, np.nan], [np.inf, 2.0]], np.float32),), np.isinf),
    "isnan": op((np.array([[1.0, np.nan], [np.inf, 2.0]], np.float32),), np.isnan),
    "isneginf": op((np.array([[1.0, -np.inf], [np.inf, 2.0]], np.float32),), np.isneginf),
    "isposinf": op((np.array([[1.0, -np.inf], [np.inf, 2.0]], np.float32),), np.isposinf),
    "isreal": op((x23,), np.isreal),
    "scale": op((x23,), lambda a: a * 2.0 + 1.0,
                kwargs=dict(scale=2.0, bias=1.0), grad=[0]),
    "increment": op((np.float32([3.0]),), lambda a: a + 1.0),
    "clip": op((x23,), lambda a: np.clip(a, -0.5, 0.5),
               kwargs=dict(min=-0.5, max=0.5), grad=[0]),
    "frexp": op((u23,), lambda a: np.frexp(a)[0], out=0),
})

# -------------------------------------------------------------- math: binary
SPEC.update({
    "add": op((x23, y23), np.add, grad=[0, 1]),
    "subtract": op((x23, y23), np.subtract, grad=[0, 1]),
    "multiply": op((x23, y23), np.multiply, grad=[0, 1]),
    "divide": op((x23, u23), np.divide, grad=[0, 1]),
    "divide_no_nan": op((x23, np.where(np.abs(y23) < 0.3, 0, y23).astype(np.float32)),
                        lambda a, b: np.where(b == 0, 0, a / np.where(b == 0, 1, b))),
    "pow": op((u23, y23), np.power, grad=[0]),
    "maximum": op((x23, y23), np.maximum, grad=[0, 1]),
    "minimum": op((x23, y23), np.minimum, grad=[0, 1]),
    "fmax": op((x23, y23), np.fmax),
    "fmin": op((x23, y23), np.fmin),
    "mod": op((x23 * 4, u23), np.mod),
    "floor_mod": op((x23 * 4, u23), np.mod),
    "remainder": op((x23 * 4, u23), np.mod),
    "floor_divide": op((x23 * 4, u23), np.floor_divide),
    "hypot": op((x23, y23), np.hypot, grad=[0, 1]),
    "ldexp": op((x23, I(4, 2, 3)), lambda a, b: np.ldexp(a, b)),
    "gcd": op((I(20, 2, 3), I(20, 2, 3)), np.gcd),
    "lcm": op((I(10, 2, 3) + 1, I(10, 2, 3) + 1), np.lcm),
    "logaddexp": op((x23, y23), np.logaddexp, grad=[0, 1]),
    "atan2": op((x23, u23), np.arctan2, grad=[0, 1]),
    "nextafter": op((x23, y23), np.nextafter),
    "copysign": op((x23, y23), np.copysign),
    "heaviside": op((x23, u23), np.heaviside),
    "lerp": op((x23, y23, np.float32(0.3)),
               lambda a, b, w: a + w * (b - a), grad=[0, 1]),
    "inner": op((x23, y23), np.inner, grad=[0, 1]),
    "outer": op((S(3), S(4)), np.outer, grad=[0, 1]),
    "kron": op((S(2, 2), S(2, 3)), np.kron, grad=[0, 1]),
    "dot": op((S(4), S(4)), np.dot, grad=[0, 1]),
    "cross": op((S(4, 3), S(4, 3)), lambda a, b: np.cross(a, b, axis=1),
              kwargs=dict(axis=1), grad=[0, 1]),
})

# ---------------------------------------------------------- math: reductions
SPEC.update({
    "sum": op((x23,), lambda a: np.sum(a), grad=[0]),
    "mean": op((x23,), lambda a: np.mean(a), grad=[0]),
    "max": op((x23,), lambda a: np.max(a), grad=[0]),
    "min": op((x23,), lambda a: np.min(a), grad=[0]),
    "amax": op((x23,), lambda a: np.max(a)),
    "amin": op((x23,), lambda a: np.min(a)),
    "prod": op((u23,), lambda a: np.prod(a), grad=[0]),
    "nansum": op((np.where(b23, np.nan, x23).astype(np.float32),), np.nansum),
    "nanmean": op((np.where(b23, np.nan, x23).astype(np.float32),), np.nanmean),
    "logsumexp": op((x23,), lambda a: np.log(np.sum(np.exp(a))), grad=[0]),
    "all": op((b23,), np.all),
    "any": op((b23,), np.any),
    "count_nonzero": op((i23,), np.count_nonzero),
    "cumsum": op((x23,), lambda a: np.cumsum(a.reshape(-1)), grad=[0]),
    "cumprod": op((u23,), lambda a: np.cumprod(u23.reshape(-1)),
                  kwargs=dict(dim=None), grad=[0]),
    "logcumsumexp": op((x23,), lambda a: np.log(np.cumsum(np.exp(a.reshape(-1)))),
                       grad=[0], grtol=1e-2),
    "cummax": op((x23,), lambda a: np.maximum.accumulate(a, -1),
                 kwargs=dict(axis=-1), out=0),
    "cummin": op((x23,), lambda a: np.minimum.accumulate(a, -1),
                 kwargs=dict(axis=-1), out=0),
    "trace": op((m44,), np.trace, grad=[0]),
    "diff": op((x23,), lambda a: np.diff(a, axis=-1), grad=[0]),
    "trapezoid": op((x23,), lambda a: _np_trapezoid(a, axis=-1), grad=[0]),
    "cumulative_trapezoid": op(
        (x23,),
        lambda a: np.stack([_np_trapezoid(a[:, :k + 2], axis=-1) for k in range(a.shape[-1] - 1)], -1),
        grad=[0]),
    "add_n": op(([x23, y23],), lambda ls: ls[0] + ls[1]),
})

# ------------------------------------------------------------- math: linear
SPEC.update({
    "matmul": op((S(2, 4), S(4, 3)), np.matmul, grad=[0, 1]),
    "mm": op((S(2, 4), S(4, 3)), np.matmul, grad=[0, 1]),
    "bmm": op((S(2, 3, 4), S(2, 4, 2)), np.matmul, grad=[0, 1]),
    "mv": op((S(3, 4), S(4)), lambda a, b: a @ b, grad=[0, 1]),
    "addmm": op((S(2, 3), S(2, 4), S(4, 3)),
                lambda c, a, b: c + a @ b, grad=[0, 1, 2]),
    "vander": op((S(4),), lambda a: np.vander(a, increasing=False)),
    "diagonal": op((m44,), lambda a: np.diagonal(a, 0, 0, 1), grad=[0]),
    "histogram": op((U(20) * 10,),
                    lambda a: np.histogram(a, bins=5, range=(0, 10))[0],
                    kwargs=dict(bins=5, min=0, max=10)),
    "histogramdd": op((U(10, 2) * 4,),
                      lambda a: np.histogramdd(a, bins=(4, 4), range=[(0, 4), (0, 4)])[0],
                      kwargs=dict(bins=(4, 4), ranges=((0, 4), (0, 4))), out=0),
    "bincount": op((I(6, 10),), np.bincount),
    "renorm": op((S(3, 4),),
                 lambda a: a * np.minimum(1.0, 1.0 / (np.sqrt((a ** 2).sum(axis=(1,))) + 1e-7))[:, None],
                 kwargs=dict(p=2, axis=0, max_norm=1.0), rtol=1e-4, atol=1e-5),
    "multiplex": op(([x23, y23], np.int64([0, 1])),
                    lambda ls, idx: np.stack([ls[idx[r]][r] for r in range(len(idx))])),
    "pdist": op((S(4, 3),),
                lambda a: np.sqrt(((a[:, None] - a[None]) ** 2).sum(-1))[np.triu_indices(4, 1)],
                rtol=1e-4, atol=1e-5),
})

# -------------------------------------------------------------------- logic
SPEC.update({
    "equal": op((i23, j23), np.equal),
    "not_equal": op((i23, j23), np.not_equal),
    "greater_than": op((x23, y23), np.greater),
    "greater_equal": op((x23, y23), np.greater_equal),
    "less_than": op((x23, y23), np.less),
    "less_equal": op((x23, y23), np.less_equal),
    "equal_all": op((i23, i23.copy()), lambda a, b: np.array(np.array_equal(a, b))),
    "logical_and": op((b23, B(2, 3)), np.logical_and),
    "logical_or": op((b23, B(2, 3)), np.logical_or),
    "logical_xor": op((b23, B(2, 3)), np.logical_xor),
    "logical_not": op((b23,), np.logical_not),
    "bitwise_and": op((i23, j23), np.bitwise_and),
    "bitwise_or": op((i23, j23), np.bitwise_or),
    "bitwise_xor": op((i23, j23), np.bitwise_xor),
    "bitwise_not": op((i23,), np.bitwise_not),
    "bitwise_left_shift": op((i23, I(3, 2, 3)), np.left_shift),
    "bitwise_right_shift": op((i23 * 4, I(3, 2, 3)), np.right_shift),
    "allclose": op((x23, x23 + 1e-9), lambda a, b: np.array(np.allclose(a, b))),
    "isclose": op((x23, x23 + 1e-9), np.isclose),
    "isin": op((i23, np.int64([1, 3, 5])), np.isin),
    "in1d": op((I(6, 8), np.int64([1, 3])), lambda a, b: np.isin(a, b)),
    "is_empty": op((x23,), lambda a: np.array(a.size == 0)),
    "is_tensor": op((x23,), lambda a: True),
})

# ------------------------------------------------------------- manipulation
_sc_x = S(5, 3)
_sc_idx = np.int64([3, 1])
_sc_upd = S(2, 3)
SPEC.update({
    "reshape": op((x23,), lambda a: a.reshape(3, 2), kwargs=dict(shape=[3, 2]), grad=[0]),
    "transpose": op((S(2, 3, 4),), lambda a: a.transpose(1, 0, 2),
                    kwargs=dict(perm=[1, 0, 2]), grad=[0]),
    "t": op((x23,), lambda a: a.T, grad=[0]),
    "concat": op(([x23, y23],), lambda ls: np.concatenate(ls, 0)),
    "stack": op(([x23, y23],), lambda ls: np.stack(ls, 0)),
    "split": op((S(4, 3),), lambda a: np.split(a, 2, 0),
                kwargs=dict(num_or_sections=2, axis=0), out=0,
                ref_post=lambda r: r[0]),
    "chunk": op((S(4, 3),), lambda a: np.split(a, 2, 0),
                kwargs=dict(chunks=2, axis=0), out=0, ref_post=lambda r: r[0]),
    "squeeze": op((S(2, 1, 3),), np.squeeze, grad=[0]),
    "unsqueeze": op((x23,), lambda a: a[:, None],
                    kwargs=dict(axis=1), grad=[0]),
    "flip": op((x23,), lambda a: np.flip(a, 0), kwargs=dict(axis=0), grad=[0]),
    "fliplr": op((x23,), np.fliplr),
    "flipud": op((x23,), np.flipud),
    "reverse": op((x23,), lambda a: np.flip(a, 0), kwargs=dict(axis=0)),
    "roll": op((x23,), lambda a: np.roll(a, 1, 0),
               kwargs=dict(shifts=1, axis=0), grad=[0]),
    "tile": op((x23,), lambda a: np.tile(a, (2, 1)),
               kwargs=dict(repeat_times=[2, 1]), grad=[0]),
    "repeat_interleave": op((x23,), lambda a: np.repeat(a, 2, 0),
                            kwargs=dict(repeats=2, axis=0), grad=[0]),
    "gather": op((S(5, 3), np.int64([3, 1])), lambda a, idx: a[idx]),
    "gather_nd": op((S(3, 4), np.int64([[0, 1], [2, 3]])),
                    lambda a, idx: a[idx[:, 0], idx[:, 1]]),
    "scatter": op((_sc_x, _sc_idx, _sc_upd),
                  lambda a, idx, u: _np_scatter(a, idx, u)),
    "scatter_nd": op((np.int64([[1], [3]]), S(2, 4)),
                     lambda idx, u: _np_scatter_nd(idx, u, (6, 4)),
                     call=lambda fn, t: fn(t[0], t[1], [6, 4])),
    "scatter_nd_add": op((S(6, 4), np.int64([[1], [3]]), S(2, 4)),
                         lambda a, idx, u: _np_scatter_nd_add(a, idx, u)),
    "index_select": op((S(5, 3), np.int64([0, 3])), lambda a, i: a[i]),
    "index_sample": op((S(3, 5), I(5, 3, 2)),
                       lambda a, i: np.take_along_axis(a, i, axis=1)),
    "index_add": op((S(5, 3), np.int64([1, 3]), S(2, 3)),
                    lambda a, i, v: _np_index_add(a, i, v),
                    kwargs=dict(axis=0),
                    call=lambda fn, t: fn(t[0], t[1], 0, t[2])),
    "index_fill": op((S(5, 3), np.int64([1, 3])),
                     lambda a, i: _np_index_fill(a, i, 0.5),
                     call=lambda fn, t: fn(t[0], t[1], 0, 0.5)),
    "index_put": op((S(5,), (np.int64([1, 3]),), S(2)),
                    lambda a, i, v: _np_index_put(a, i[0], v),
                    call=lambda fn, t: fn(t[0], (Tensor(np.int64([1, 3])),), t[2])),
    "masked_fill": op((x23, b23), lambda a, m: np.where(m, 0.5, a),
                      call=lambda fn, t: fn(t[0], t[1], 0.5)),
    "masked_scatter": op((x23, b23, S(6)),
                         lambda a, m, v: _np_masked_scatter(a, m, v)),
    "masked_select": op((x23, b23), lambda a, m: a[m]),
    "take": op((S(4, 3), I(12, 5)), lambda a, i: a.reshape(-1)[i]),
    "take_along_axis": op((S(3, 4), I(4, 3, 2)),
                          lambda a, i: np.take_along_axis(a, i, 1),
                          kwargs=dict(axis=1)),
    "put_along_axis": op((S(3, 4), I(4, 3, 1), np.float32(9.5)),
                         lambda a, i, v: _np_put_along_axis(a, i, 9.5),
                         kwargs=dict(axis=1)),
    "flatten": op((S(2, 3, 4),), lambda a: a.reshape(2, 12),
                  kwargs=dict(start_axis=1, stop_axis=2), grad=[0]),
    "broadcast_to": op((S(1, 3),), lambda a: np.broadcast_to(a, (4, 3)),
                       kwargs=dict(shape=[4, 3]), grad=[0]),
    "expand": op((S(1, 3),), lambda a: np.broadcast_to(a, (4, 3)),
                 kwargs=dict(shape=[4, 3]), grad=[0]),
    "expand_as": op((S(1, 3), S(4, 3)), lambda a, b: np.broadcast_to(a, b.shape)),
    "broadcast_shape": op(([2, 1, 3], [4, 3]),
                          lambda s1, s2: list(np.broadcast_shapes(s1, s2)),
                          raw=True),
    "broadcast_tensors": op(([S(1, 3), S(4, 1)],),
                            lambda ls: np.broadcast_arrays(*ls)[0], out=0),
    "where": op((b23, x23, y23), np.where, grad=[1, 2]),
    "diag": op((S(4),), np.diag),
    "diagflat": op((x23,), np.diagflat),
    "diag_embed": op((S(2, 3),),
                     lambda a: np.stack([np.diag(r) for r in a])),
    "tril": op((m44,), np.tril, grad=[0]),
    "triu": op((m44,), np.triu, grad=[0]),
    "rot90": op((m44,), np.rot90),
    "moveaxis": op((S(2, 3, 4),), lambda a: np.moveaxis(a, 0, 2),
                   kwargs=dict(source=0, destination=2)),
    "swapaxes": op((S(2, 3, 4),), lambda a: np.swapaxes(a, 0, 1),
                   kwargs=dict(axis0=0, axis1=1)),
    "unbind": op((S(3, 4),), lambda a: a[0], out=0),
    "unstack": op((S(3, 4),), lambda a: a[0], out=0),
    "unflatten": op((S(2, 12),), lambda a: a.reshape(2, 3, 4),
                    kwargs=dict(axis=1, shape=[3, 4])),
    "unfold": op((S(8,),), lambda a: np.stack([a[i:i + 4] for i in range(0, 5, 2)]),
                 kwargs=dict(axis=0, size=4, step=2)),
    "as_strided": op((S(12,),), lambda a: np.lib.stride_tricks.as_strided(
        a, (3, 4), (4 * a.strides[-1] // a.itemsize * a.itemsize, a.strides[-1])),
        kwargs=dict(shape=[3, 4], stride=[4, 1])),
    "view": op((S(2, 6),), lambda a: a.reshape(3, 4),
               kwargs=dict(shape_or_dtype=[3, 4])),
    "view_as": op((S(2, 6), S(3, 4)), lambda a, b: a.reshape(b.shape)),
    "atleast_1d": op((np.float32(3.0),), np.atleast_1d),
    "atleast_2d": op((S(3),), np.atleast_2d),
    "atleast_3d": op((S(3, 4),), np.atleast_3d),
    "hstack": op(([S(2, 3), S(2, 2)],), lambda ls: np.hstack(ls)),
    "vstack": op(([S(2, 3), S(1, 3)],), lambda ls: np.vstack(ls)),
    "dstack": op(([S(2, 3), S(2, 3)],), lambda ls: np.dstack(ls)),
    "column_stack": op(([S(4), S(4)],), lambda ls: np.column_stack(ls)),
    "row_stack": op(([S(2, 3), S(1, 3)],), lambda ls: np.vstack(ls)),
    "hsplit": op((S(2, 4),), lambda a: np.hsplit(a, 2)[0],
                 kwargs=dict(num_or_indices=2), out=0),
    "vsplit": op((S(4, 2),), lambda a: np.vsplit(a, 2)[0],
                 kwargs=dict(num_or_indices=2), out=0),
    "dsplit": op((S(2, 2, 4),), lambda a: np.dsplit(a, 2)[0],
                 kwargs=dict(num_or_indices=2), out=0),
    "tensor_split": op((S(5, 2),), lambda a: np.array_split(a, 2, 0)[0],
                       kwargs=dict(num_or_indices=2), out=0),
    "tensordot": op((S(2, 3, 4), S(3, 4, 5)),
                    lambda a, b: np.tensordot(a, b, 2), kwargs=dict(axes=2),
                    rtol=1e-4, atol=1e-5),
    "crop": op((S(4, 5),), lambda a: a[1:3, 2:5],
               kwargs=dict(shape=[2, 3], offsets=[1, 2])),
    "pad": op((S(2, 3),), lambda a: np.pad(a, ((2, 2), (1, 1))),
              kwargs=dict(pad=[2, 2, 1, 1], mode="constant", value=0.0, data_format=None)),
    "numel": op((x23,), lambda a: np.array(a.size)),
    "rank": op((x23,), lambda a: np.array(a.ndim)),
    "shape": op((x23,), lambda a: list(a.shape)),
    "cast": op((x23,), lambda a: a.astype(np.float64), kwargs=dict(dtype="float64")),
    "astype": op((x23,), lambda a: a.astype(np.float64), kwargs=dict(dtype="float64")),
    "slice": op((S(4, 5),), lambda a: a[1:3, 0:2],
                kwargs=dict(axes=[0, 1], starts=[1, 0], ends=[3, 2])),
    "strided_slice": op((S(6, 5),), lambda a: a[0:6:2, 1:4:1],
                        kwargs=dict(axes=[0, 1], starts=[0, 1], ends=[6, 4], strides=[2, 1])),
    "select_scatter": op((S(3, 4), S(4)),
                         lambda a, v: _np_select_scatter(a, v, 0, 1),
                         kwargs=dict(axis=0, index=1)),
    "diagonal_scatter": op((m44, S(4)),
                           lambda a, v: _np_diagonal_scatter(a, v)),
    "shard_index": op((I(20, 6, 1),),
                      lambda a: _np_shard_index(a, 20, 2, 0, -1),
                      kwargs=dict(index_num=20, nshards=2, shard_id=0)),
    "one_hot": op((I(5, 4),), lambda a: np.eye(5, dtype=np.float32)[a],
                  kwargs=dict(num_classes=5)),
    "as_complex": op((S(3, 2),), lambda a: a[..., 0] + 1j * a[..., 1]),
    "as_real": op((S(3, 2),), lambda a: a,
                  call=lambda fn, t: fn(paddle.as_complex(t[0]))),
    "complex": op((x23, y23), lambda a, b: a + 1j * b),
    "polar": op((u23, x23), lambda r, t: r * np.cos(t) + 1j * r * np.sin(t),
                rtol=1e-5, atol=1e-5),
    "fill_diagonal_": op((m44.copy(),), lambda a: _np_fill_diag(a, 7.0),
                         kwargs=dict(value=7.0)),
})

# ----------------------------------------------------------------- creation
SPEC.update({
    "zeros": op(([2, 3],), lambda s: np.zeros(s, np.float32), raw=True),
    "ones": op(([2, 3],), lambda s: np.ones(s, np.float32), raw=True),
    "full": op(([2, 3], 7.0), lambda s, v: np.full(s, v, np.float32), raw=True),
    "zeros_like": op((x23,), np.zeros_like),
    "ones_like": op((x23,), np.ones_like),
    "full_like": op((x23,), lambda a: np.full_like(a, 7.0),
                    call=lambda fn, t: fn(t[0], 7.0)),
    "empty_like": op((x23,), lambda a: np.empty_like(a), shape_only=True),
    "empty": op(([2, 3],), lambda s: np.empty(s, np.float32), raw=True, shape_only=True),
    "arange": op((0, 10, 2), lambda a, b, st: np.arange(a, b, st), raw=True),
    "linspace": op((0.0, 1.0, 5), lambda a, b, n: np.linspace(a, b, n), raw=True),
    "logspace": op((0.0, 2.0, 3), lambda a, b, n: np.logspace(a, b, n), raw=True,
                   rtol=1e-4, atol=1e-4),
    "eye": op((3, 4), lambda n, m: np.eye(n, m, dtype=np.float32), raw=True),
    "tril_indices": op((4, 4, 0), lambda r, c, o: np.stack(np.tril_indices(r, o, c)), raw=True),
    "triu_indices": op((4, 4, 0), lambda r, c, o: np.stack(np.triu_indices(r, o, c)), raw=True),
    "meshgrid": op(([S(3), S(4)],),
                   lambda ls: np.meshgrid(*ls, indexing="ij")[0], out=0),
    "to_tensor": op((x23,), lambda a: a),
    "clone": op((x23,), lambda a: a.copy(), grad=[0]),
    "assign": op((x23,), lambda a: a.copy()),
    "create_tensor": op((x23,), lambda a: a,
                        call=lambda fn, t: paddle.assign(t[0], fn(dtype="float32"))),
    "cartesian_prod": op(([S(2), S(3)],),
                         lambda ls: np.stack(np.meshgrid(*ls, indexing="ij"), -1).reshape(-1, 2)),
    "combinations": op((S(4),),
                       lambda a: np.stack([[a[i], a[j]] for i in range(4) for j in range(i + 1, 4)])),
})

# ---------------------------------------------------------- oracle helpers

def _np_scatter(a, idx, u):
    r = a.copy()
    r[idx] = u
    return r


def _np_scatter_nd(idx, u, shape):
    r = np.zeros(shape, u.dtype)
    np.add.at(r, tuple(idx.T), u)
    return r


def _np_scatter_nd_add(a, idx, u):
    r = a.copy()
    np.add.at(r, tuple(idx.T), u)
    return r


def _np_index_add(a, i, v):
    r = a.copy()
    np.add.at(r, i, v)
    return r


def _np_index_fill(a, i, v):
    r = a.copy()
    r[i] = v
    return r


def _np_index_put(a, i, v):
    r = a.copy()
    r[i] = v
    return r


def _np_masked_scatter(a, m, v):
    r = a.copy()
    r[m] = v[: int(m.sum())]
    return r


def _np_put_along_axis(a, i, v):
    r = a.copy()
    np.put_along_axis(r, i, v, 1)
    return r


def _np_select_scatter(a, v, axis, index):
    r = a.copy()
    r[index] = v
    return r


def _np_diagonal_scatter(a, v):
    r = a.copy()
    np.fill_diagonal(r, v)
    return r


def _np_fill_diag(a, v):
    r = a.copy()
    np.fill_diagonal(r, v)
    return r


def _np_shard_index(a, index_num, nshards, shard_id, ignore):
    size = index_num // nshards
    lo, hi = shard_id * size, (shard_id + 1) * size
    return np.where((a >= lo) & (a < hi), a - lo, ignore)


# ------------------------------------------------------------------- linalg
SPEC.update({
    "det": op((psd4,), np.linalg.det, rtol=1e-4, atol=1e-4, grad=[0], grtol=2e-2, gatol=5e-2),
    "slogdet": op((psd4,), lambda a: np.linalg.slogdet(a)[1], out=1,
                  rtol=1e-4, atol=1e-5),
    "inv": op((psd4,), np.linalg.inv, rtol=1e-4, atol=1e-4),
    "inverse": op((psd4,), np.linalg.inv, rtol=1e-4, atol=1e-4),
    "pinv": op((S(4, 3),), np.linalg.pinv, rtol=1e-3, atol=1e-4),
    "solve": op((psd4, S(4, 2)), np.linalg.solve, rtol=1e-4, atol=1e-4),
    "cholesky": op((psd4,), np.linalg.cholesky, rtol=1e-4, atol=1e-4),
    "cholesky_solve": op((S(4, 2), np.linalg.cholesky(psd4).astype(np.float32)),
                         lambda b, l: np.linalg.solve(l @ l.T, b),
                         kwargs=dict(upper=False), rtol=1e-3, atol=1e-3),
    "triangular_solve": op((np.tril(psd4).astype(np.float32), S(4, 2)),
                           lambda l, b: np.linalg.solve(l, b),
                           kwargs=dict(upper=False), rtol=1e-3, atol=1e-3),
    "lstsq": op((S(5, 3), S(5, 2)),
                lambda a, b: np.linalg.lstsq(a, b, rcond=None)[0], out=0,
                rtol=1e-3, atol=1e-3),
    "matrix_power": op((psd4,), lambda a: np.linalg.matrix_power(a, 3),
                       kwargs=dict(n=3), rtol=1e-3, atol=1e-2),
    "matrix_rank": op((psd4,), lambda a: np.array(np.linalg.matrix_rank(a))),
    "matrix_norm": op((m44,), lambda a: np.linalg.norm(a, "fro"),
                      kwargs=dict(p="fro"), rtol=1e-4, atol=1e-5),
    "vector_norm": op((S(5),), np.linalg.norm, rtol=1e-4, atol=1e-5),
    "norm": op((S(5),), np.linalg.norm, rtol=1e-4, atol=1e-5, grad=[0]),
    "cond": op((psd4,), lambda a: np.array(np.linalg.cond(a), np.float32),
               rtol=1e-3, atol=1e-3),
    "multi_dot": op(([S(2, 3), S(3, 4), S(4, 2)],),
                    lambda ls: np.linalg.multi_dot(ls), rtol=1e-4, atol=1e-5),
    "dist": op((x23, y23), lambda a, b: np.array(np.linalg.norm((a - b).reshape(-1)), np.float32),
               rtol=1e-4, atol=1e-5),
    "cdist": op((S(3, 4), S(2, 4)),
                lambda a, b: np.sqrt(((a[:, None] - b[None]) ** 2).sum(-1)),
                rtol=1e-4, atol=1e-4),
    "cov": op((S(3, 8),), lambda a: np.cov(a), rtol=1e-4, atol=1e-4),
    "corrcoef": op((S(3, 8),), lambda a: np.corrcoef(a), rtol=1e-4, atol=1e-4),
})


def _sym_expm(a):
    w, v = np.linalg.eigh(a)
    return (v * np.exp(w)) @ v.T


SPEC["matrix_exp"] = op((psd4 / 4,), _sym_expm, rtol=1e-3, atol=1e-3)

# property-checked linalg (sign/phase ambiguity): reconstruct instead
PROPERTY_OPS = {}


def prop(args, check, kwargs=None, call=None):
    return dict(args=args, kwargs=kwargs or {}, check=check, call=call)


def _svd_check(res, a):
    u, s, vh = (np.asarray(r._value) for r in res)
    np.testing.assert_allclose((u * s) @ vh, a, rtol=1e-3, atol=1e-3)


def _qr_check(res, a):
    q, r = (np.asarray(t._value) for t in res)
    np.testing.assert_allclose(q @ r, a, rtol=1e-3, atol=1e-3)


def _eigh_check(res, a):
    w, v = (np.asarray(t._value) for t in res)
    np.testing.assert_allclose((v * w) @ v.T, a, rtol=1e-3, atol=1e-3)


def _eigvalsh_check(res, a):
    w = np.asarray(res._value)
    np.testing.assert_allclose(np.sort(w), np.sort(np.linalg.eigvalsh(a)),
                               rtol=1e-3, atol=1e-3)


def _eig_check(res, a):
    w, v = (np.asarray(t._value) for t in res)
    np.testing.assert_allclose(
        np.sort_complex(w), np.sort_complex(np.linalg.eigvals(a)), rtol=1e-3, atol=1e-3)


def _eigvals_check(res, a):
    w = np.asarray(res._value)
    np.testing.assert_allclose(
        np.sort_complex(w), np.sort_complex(np.linalg.eigvals(a)), rtol=1e-3, atol=1e-3)


def _lu_check(res, a):
    lu, piv = (np.asarray(t._value) for t in res[:2])
    l = np.tril(lu, -1) + np.eye(a.shape[0], dtype=lu.dtype)
    u = np.triu(lu)
    perm = np.arange(a.shape[0])
    for i, p in enumerate(piv - 1):
        perm[[i, p]] = perm[[p, i]]
    np.testing.assert_allclose((l @ u), a[perm], rtol=1e-3, atol=1e-3)


def _lu_unpack_check(res, a):
    p, l, u = res
    np.testing.assert_allclose(
        np.asarray(p._value) @ np.asarray(l._value) @ np.asarray(u._value),
        a, rtol=1e-3, atol=1e-3)


def _orth_check(res, a):
    q = np.asarray(res._value)
    np.testing.assert_allclose(q.T @ q, np.eye(q.shape[1]), rtol=1e-3, atol=1e-3)


def _hh_inputs():
    import scipy.linalg as sla

    a = S(4, 3).astype(np.float64)
    qr_raw, tau = sla.qr(a, mode="raw")[0]
    return (np.ascontiguousarray(qr_raw).astype(np.float32),
            np.ascontiguousarray(tau).astype(np.float32))


def _householder_check(res, a):
    q = np.asarray(res._value)
    np.testing.assert_allclose(q.T @ q, np.eye(q.shape[1]), rtol=1e-3, atol=1e-3)


def _lowrank_check(res, a):
    u, s, v = (np.asarray(t._value) for t in res)
    np.testing.assert_allclose((u * s) @ v.T, a, rtol=0.2, atol=0.2)


def _pca_check(res, a):
    u, s, v = (np.asarray(t._value) for t in res)
    c = a - a.mean(0, keepdims=True)
    np.testing.assert_allclose((u * s) @ v.T, c, rtol=0.25, atol=0.25)


PROPERTY_OPS.update({
    "svd": prop((S(4, 3),), _svd_check, kwargs=dict(full_matrices=False)),
    "qr": prop((S(4, 3),), _qr_check),
    "eigh": prop((psd4,), _eigh_check),
    "eigvalsh": prop((psd4,), _eigvalsh_check),
    "eig": prop((psd4,), _eig_check),
    "eigvals": prop((psd4,), _eigvals_check),
    "lu": prop((psd4,), _lu_check),
    "lu_unpack": prop((psd4,), _lu_unpack_check,
                      call=lambda fn, t: fn(*paddle.linalg.lu(t[0])[:2])),
    "orthogonalize": prop((S(4, 3),), _orth_check),
    "householder_product": prop(_hh_inputs(), _householder_check),
    "svd_lowrank": prop((S(5, 4),), _lowrank_check, kwargs=dict(q=4)),
    "pca_lowrank": prop((S(5, 4),), _pca_check, kwargs=dict(q=4)),
})

# ------------------------------------------------------------- search / stat
_srt = S(8)
SPEC.update({
    "argmax": op((x23,), lambda a: np.array(np.argmax(a))),
    "argmin": op((x23,), lambda a: np.array(np.argmin(a))),
    "argsort": op((_srt,), np.argsort),
    "sort": op((_srt,), np.sort),
    "topk": op((_srt,), lambda a: np.sort(a)[::-1][:3], kwargs=dict(k=3), out=0),
    "kthvalue": op((_srt,), lambda a: np.sort(a)[1], kwargs=dict(k=2), out=0),
    "mode": op((np.float32([[1, 2, 2, 3]]),), lambda a: np.float32([2]), out=0),
    "searchsorted": op((np.sort(S(8)), x23), lambda s, v: np.searchsorted(s, v)),
    "bucketize": op((x23, np.sort(S(5))), lambda v, b: np.searchsorted(b, v)),
    "nonzero": op((np.float32([[0, 1], [2, 0]]),),
                  lambda a: np.stack(np.nonzero(a), -1)),
    "median": op((x23,), lambda a: np.median(a)),
    "nanmedian": op((np.where(b23, np.nan, x23).astype(np.float32),), np.nanmedian),
    "quantile": op((x23,), lambda a: np.quantile(a, 0.3), kwargs=dict(q=0.3),
                   rtol=1e-5, atol=1e-6),
    "nanquantile": op((np.where(b23, np.nan, x23).astype(np.float32),),
                      lambda a: np.nanquantile(a, 0.3), kwargs=dict(q=0.3)),
    "std": op((x23,), lambda a: np.std(a, ddof=1), grad=[0]),
    "var": op((x23,), lambda a: np.var(a, ddof=1), grad=[0]),
    "unique": op((I(4, 10),), np.unique),
    "unique_consecutive": op((np.int64([1, 1, 2, 2, 3, 1]),),
                             lambda a: np.int64([1, 2, 3, 1])),
    "einsum": op(("ij,jk->ik", S(2, 3), S(3, 4)),
                 lambda eq, a, b: np.einsum(eq, a, b),
                 call=lambda fn, t: fn("ij,jk->ik", t[1], t[2]),
                 rtol=1e-4, atol=1e-5),
})

# ---------------------------------------------------------------- drivers

def _resolve(name):
    info = build_registry()[name]
    mod = importlib.import_module(info.module)
    return getattr(mod, name)


def _wrap(a):
    if isinstance(a, np.ndarray):
        return Tensor(a)
    if isinstance(a, (list, tuple)) and a and all(isinstance(v, np.ndarray) for v in a):
        return [Tensor(v) for v in a]
    return a


def _invoke(fn, spec):
    args = spec["args"]
    if spec["call"] is not None:
        tensors = [_wrap(a) for a in args]
        return spec["call"](fn, tensors)
    if spec["raw"]:
        return fn(*args, **spec["kwargs"])
    return fn(*[_wrap(a) for a in args], **spec["kwargs"])


def _np_args(spec):
    return [a for a in spec["args"]]


def _extract(res, spec):
    if spec["out"] is not None and isinstance(res, (tuple, list)):
        res = res[spec["out"]]
    return res


@pytest.mark.parametrize("name", sorted(SPEC))
def test_op_output(name):
    spec = SPEC[name]
    fn = _resolve(name)
    res = _extract(_invoke(fn, spec), spec)
    expect = spec["ref"](*_np_args(spec))
    if spec["ref_post"] is not None:
        expect = spec["ref_post"](expect)
    if isinstance(res, Tensor):
        got = np.asarray(res._value)
    elif isinstance(res, (list, tuple)):
        got = np.asarray([np.asarray(getattr(r, "_value", r)) for r in res])
        expect = np.asarray(expect)
    else:
        got = np.asarray(res)
    expect = np.asarray(expect)
    if spec["shape_only"]:
        assert tuple(got.shape) == tuple(expect.shape)
        return
    if got.dtype != expect.dtype and expect.dtype.kind in "fc":
        got = got.astype(expect.dtype)
    if expect.dtype.kind in "iub":
        np.testing.assert_array_equal(got, np.asarray(expect))
    else:
        np.testing.assert_allclose(got, expect, rtol=spec["rtol"], atol=spec["atol"])


GRAD_OPS = sorted(n for n, s in SPEC.items() if s["grad"])


@pytest.mark.parametrize("name", GRAD_OPS)
def test_op_grad(name):
    spec = SPEC[name]
    fn = _resolve(name)

    def run(*tensors):
        args = list(spec["args"])
        ts = iter(tensors)
        filled = []
        for a in args:
            filled.append(next(ts) if isinstance(a, np.ndarray) else a)
        if spec["call"] is not None:
            out = spec["call"](fn, filled)
        else:
            out = fn(*[_wrap(a) if not isinstance(a, Tensor) else a for a in filled],
                     **spec["kwargs"])
        return _extract(out, spec)

    arrays = [a for a in spec["args"] if isinstance(a, np.ndarray)]
    check_grad_dir(run, *arrays, argnums=spec["grad"],
                   rtol=spec["grtol"], atol=spec["gatol"], eps=spec["eps"])


@pytest.mark.parametrize("name", sorted(PROPERTY_OPS))
def test_op_property(name):
    spec = PROPERTY_OPS[name]
    fn = _resolve(name)
    if spec.get("call") is not None:
        res = spec["call"](fn, [_wrap(a) for a in spec["args"]])
    else:
        res = fn(*[_wrap(a) for a in spec["args"]], **spec["kwargs"])
    spec["check"](res, spec["args"][0])


# ------------------------------------------------------- in-place variants

INPLACE_SKIP = {
    # need non-generic call patterns; base op already numerically verified
    "fill_diagonal_", "index_put_", "masked_scatter_", "put_along_axis_",
    "index_fill_", "masked_fill_", "index_add_", "renorm_", "lerp_",
    "addmm_", "clip_", "scale_",
    # random in-place: distribution checked in test_random_ops
    "bernoulli_", "cauchy_", "exponential_", "geometric_", "log_normal_",
    "normal_", "uniform_", "randint_like", "zero_", "fill_",
    "equal_",  # comparison in-place: dtype-changing, checked via base
    "where_",  # mutates its SECOND arg (x), not arg 0 — probed in sweep dev
}


def _inplace_pairs():
    reg = build_registry()
    pairs = []
    for name, info in reg.items():
        if not name.endswith("_") or name in INPLACE_SKIP:
            continue
        base = name[:-1]
        if base in SPEC and base in reg:
            spec = SPEC[base]
            if spec["call"] is None and not spec["raw"] and spec["out"] is None:
                pairs.append((name, base))
    return sorted(pairs)


@pytest.mark.parametrize("name,base", _inplace_pairs())
def test_inplace_variant_matches_functional(name, base):
    """Generated `op_` tier (op_registry generate_inplace_variants):
    numerically identical to the functional op and actually in-place at the
    python level (same Tensor object rebound)."""
    spec = SPEC[base]
    fn = _resolve(base)
    ifn = _resolve(name)
    args = [_wrap(a.copy() if isinstance(a, np.ndarray) else a) for a in spec["args"]]
    expect = fn(*args, **spec["kwargs"])
    args2 = [_wrap(a.copy() if isinstance(a, np.ndarray) else a) for a in spec["args"]]
    got = ifn(*args2, **spec["kwargs"])
    np.testing.assert_allclose(
        np.asarray(got._value), np.asarray(expect._value), rtol=1e-6, atol=1e-7)
    assert got is args2[0], f"{name} did not rebind its first argument"


# ------------------------------------------------------------- random ops

def test_random_ops_statistics():
    """Seeded statistical checks for the random tier (reference
    test/legacy_test/test_uniform_random_op.py etc. assert moments)."""
    paddle.seed(1234)
    n = 20_000

    u = np.asarray(paddle.uniform([n], min=-1.0, max=1.0)._value)
    assert abs(u.mean()) < 0.03 and u.min() >= -1 and u.max() < 1

    g = np.asarray(paddle.normal(mean=2.0, std=3.0, shape=[n])._value)
    assert abs(g.mean() - 2.0) < 0.1 and abs(g.std() - 3.0) < 0.1

    r = np.asarray(paddle.rand([n])._value)
    assert 0 <= r.min() and r.max() < 1 and abs(r.mean() - 0.5) < 0.02

    rn = np.asarray(paddle.randn([n])._value)
    assert abs(rn.mean()) < 0.05 and abs(rn.std() - 1.0) < 0.05

    ri = np.asarray(paddle.randint(0, 10, [n])._value)
    assert ri.min() >= 0 and ri.max() <= 9 and abs(ri.mean() - 4.5) < 0.1

    rp = np.asarray(paddle.randperm(500)._value)
    assert sorted(rp.tolist()) == list(range(500))

    b = np.asarray(paddle.bernoulli(paddle.full([n], 0.3))._value)
    assert abs(b.mean() - 0.3) < 0.02

    p = np.asarray(paddle.poisson(paddle.full([n], 4.0))._value)
    assert abs(p.mean() - 4.0) < 0.1 and abs(p.var() - 4.0) < 0.3

    m = np.asarray(paddle.multinomial(paddle.to_tensor(
        np.float32([0.2, 0.3, 0.5])), num_samples=n, replacement=True)._value)
    freq = np.bincount(m, minlength=3) / n
    np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)

    gm = np.asarray(paddle.standard_gamma(paddle.full([n], 2.0))._value)
    assert abs(gm.mean() - 2.0) < 0.1

    bi = np.asarray(paddle.binomial(paddle.full([n], 10.0),
                                    paddle.full([n], 0.4))._value)
    assert abs(bi.mean() - 4.0) < 0.1

    _rand_mod = importlib.import_module("paddle_tpu.tensor.random")
    gs = np.asarray(_rand_mod.gaussian([n], mean=1.0, std=2.0)._value)
    assert abs(gs.mean() - 1.0) < 0.1 and abs(gs.std() - 2.0) < 0.1

    sn = np.asarray(paddle.standard_normal([n])._value)
    assert abs(sn.mean()) < 0.05

    # in-place random tier: right distribution AND rebinds in place
    t = paddle.zeros([n])
    t2 = t.uniform_(min=0.0, max=2.0)
    arr = np.asarray(t._value)
    assert t2 is t and abs(arr.mean() - 1.0) < 0.05

    t = paddle.zeros([n]).normal_(mean=-1.0, std=0.5)
    assert abs(np.asarray(t._value).mean() + 1.0) < 0.05

    t = paddle.zeros([n]).exponential_(lam=2.0)
    assert abs(np.asarray(t._value).mean() - 0.5) < 0.05

    t = _rand_mod.log_normal(mean=0.0, std=0.25, shape=[n])
    assert abs(np.asarray(t._value).mean() - np.exp(0.03125)) < 0.1

    t = paddle.zeros([n]).geometric_(probs=0.25)
    assert abs(np.asarray(t._value).mean() - 4.0) < 0.3

    t = paddle.zeros([n]).cauchy_()
    med = np.median(np.asarray(t._value))
    assert abs(med) < 0.1

    t = paddle.zeros([n]).bernoulli_(p=0.7)
    assert abs(np.asarray(t._value).mean() - 0.7) < 0.02

    ry = np.asarray(_rand_mod.rayleigh(paddle.full([n], 2.0))._value)
    assert abs(ry.mean() - 2.0 * np.sqrt(np.pi / 2)) < 0.1

    sh2 = _rand_mod.shuffle(paddle.arange(100))
    assert sorted(np.asarray(sh2._value).tolist()) == list(range(100))


def test_top_p_sampling_property():
    paddle.seed(7)
    logits = paddle.to_tensor(np.float32([[0.1, 0.2, 8.0, 0.1]]))
    probs = paddle.nn.functional.softmax(logits, axis=-1)
    _search_mod = importlib.import_module("paddle_tpu.tensor.search")
    out = _search_mod.top_p_sampling(probs, paddle.to_tensor(np.float32([0.5])))
    ids = out[1] if isinstance(out, (tuple, list)) else out
    assert int(np.asarray(ids._value).reshape(-1)[0]) == 2


# ---------------------------------------------------------- coverage gate

def test_sweep_coverage_target():
    """>= 300 registered ops numerically verified by this file (VERDICT r2
    item 2).  Counted: oracle specs + property-checked linalg + in-place
    variants vs base + random statistical tier."""
    reg = build_registry()
    covered = set(SPEC) | set(PROPERTY_OPS)
    covered |= {n for n, _ in _inplace_pairs()}
    random_ops = {n for n, i in reg.items() if i.category == "random"}
    covered |= random_ops
    covered &= set(reg)
    uncovered = sorted(set(reg) - covered)
    assert len(covered) >= 300, (
        f"only {len(covered)} ops covered; uncovered: {uncovered}")


# ---------------------------------------------- odd-signature in-place tier

def _t(a):
    return Tensor(np.asarray(a))


_ip_x = S(2, 3)
_ip_y = S(2, 3)
_ip_c = S(2, 3)
_ip_a24, _ip_b43 = S(2, 4), S(4, 3)
_ip_rows = S(5, 3)


def _np_lerp(a, b, w):
    return a + w * (b - a)


def _np_indexfill(a, idx, v):
    r = a.copy()
    r[idx] = v
    return r


@pytest.mark.parametrize("name,call,expected", [
    ("clip_", lambda: paddle.clip_(_t(_ip_x.copy()), min=-0.2, max=0.2),
     np.clip(_ip_x, -0.2, 0.2)),
    ("scale_", lambda: paddle.scale_(_t(_ip_x.copy()), scale=3.0, bias=1.0),
     _ip_x * 3.0 + 1.0),
    ("lerp_", lambda: _t(_ip_x.copy()).lerp_(_t(_ip_y), 0.25),
     _np_lerp(_ip_x, _ip_y, 0.25)),
    ("addmm_", lambda: _t(_ip_c.copy()).addmm_(_t(_ip_a24), _t(_ip_b43)),
     _ip_c + _ip_a24 @ _ip_b43),
    ("index_fill_", lambda: _t(_ip_rows.copy()).index_fill_(_t(np.int64([0, 2])), 0, 9.0),
     _np_indexfill(_ip_rows, [0, 2], 9.0)),
    ("zero_", lambda: _t(_ip_x.copy()).zero_(), np.zeros_like(_ip_x)),
    ("fill_", lambda: _t(_ip_x.copy()).fill_(2.5), np.full_like(_ip_x, 2.5)),
])
def test_odd_signature_inplace_ops(name, call, expected):
    """In-place variants whose signatures don't fit the generic pair test:
    each result is value-compared against the NumPy expectation computed
    from the SAME input."""
    out = call()
    np.testing.assert_allclose(
        np.asarray(out._value), expected, rtol=1e-4, atol=1e-5)


def test_inplace_semantics_rebind():
    """x.op_() must rebind x itself for the odd-signature tier too."""
    x = _t(u23.copy())
    out = x.clip_(min=0.6, max=1.2)
    assert out is x
    np.testing.assert_allclose(np.asarray(x._value), np.clip(u23, 0.6, 1.2), rtol=1e-6)
    x2 = _t(u23.copy())
    out2 = x2.scale_(scale=2.0)
    assert out2 is x2
    np.testing.assert_allclose(np.asarray(x2._value), u23 * 2.0, rtol=1e-6)
