"""Weight-only quantization (reference python/paddle/nn/quant/
quantized_linear.py) + fused transformer layer classes."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn.quant import weight_dequantize, weight_only_linear, weight_quantize


def test_int8_roundtrip_and_linear():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((16, 8)).astype(np.float32)
    x = rng.standard_normal((4, 16)).astype(np.float32)
    qw, scale = weight_quantize(paddle.to_tensor(w), algo="weight_only_int8")
    assert str(qw._value.dtype) == "int8" and list(scale.shape) == [8]
    wd = np.asarray(weight_dequantize(qw, scale)._value)
    np.testing.assert_allclose(wd, w, atol=np.abs(w).max() / 127 + 1e-6)
    y = weight_only_linear(paddle.to_tensor(x), qw, weight_scale=scale)
    np.testing.assert_allclose(np.asarray(y._value), x @ w, rtol=0.05, atol=0.05)


def test_int4_pack_roundtrip_and_linear():
    rng = np.random.default_rng(1)
    w = rng.standard_normal((16, 8)).astype(np.float32)
    x = rng.standard_normal((2, 16)).astype(np.float32)
    qw, scale = weight_quantize(paddle.to_tensor(w), algo="weight_only_int4")
    assert list(qw.shape) == [8, 8]  # packed two-per-byte on input dim
    wd = np.asarray(weight_dequantize(qw, scale, algo="weight_only_int4")._value)
    assert wd.shape == w.shape
    np.testing.assert_allclose(wd, w, atol=np.abs(w).max() / 7 + 1e-6)
    y = weight_only_linear(paddle.to_tensor(x), qw, weight_scale=scale, weight_dtype="int4")
    # exact vs the dequantized weight (quant error itself is checked above)
    np.testing.assert_allclose(np.asarray(y._value), x @ wd, rtol=1e-4, atol=1e-4)


def test_weight_only_linear_under_jit():
    from paddle_tpu.jit import to_static

    rng = np.random.default_rng(2)
    w = rng.standard_normal((8, 4)).astype(np.float32)
    qw, scale = weight_quantize(paddle.to_tensor(w))

    @to_static
    def f(a):
        return weight_only_linear(a, qw, weight_scale=scale)

    x = paddle.to_tensor(rng.standard_normal((2, 8)).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(f(x)._value), np.asarray(x._value) @ w, rtol=0.05, atol=0.05
    )


def test_fused_transformer_layers():
    from paddle_tpu.incubate.nn import FusedMultiTransformer, FusedTransformerEncoderLayer

    paddle.seed(0)
    layer = FusedTransformerEncoderLayer(32, 4, 64, dropout_rate=0.0)
    x = paddle.to_tensor(np.random.default_rng(3).standard_normal((2, 8, 32)).astype(np.float32))
    y = layer(x)
    assert list(y.shape) == [2, 8, 32]
    stack = FusedMultiTransformer(32, 4, 64, num_layers=2, dropout_rate=0.0)
    z = stack(x)
    assert np.isfinite(np.asarray(z._value)).all()
