"""Benchmark regression gate (reference tools/ci_op_benchmark.sh +
check_op_benchmark_result.py: relative-regression CI gating)."""

import json
import sys

sys.path.insert(0, ".")
from tools.check_bench_regression import load_payload, main


def _w(tmp_path, name, payload):
    p = tmp_path / name
    p.write_text(json.dumps(payload))
    return str(p)


def test_regression_detected_and_gated(tmp_path):
    old = _w(tmp_path, "old.json",
             {"metric": "m", "value": 100.0, "unit": "x", "vs_baseline": 1.0})
    new = _w(tmp_path, "new.json",
             {"metric": "m", "value": 90.0, "unit": "x", "vs_baseline": 0.9})
    assert main([old, new, "--threshold", "0.05"]) == 1    # -10% fails
    assert main([old, new, "--threshold", "0.15"]) == 0    # within 15%
    ok = _w(tmp_path, "ok.json",
            {"metric": "m", "value": 101.0, "unit": "x", "vs_baseline": 1.0})
    assert main([old, ok]) == 0                            # improvement


def test_driver_wrapper_payloads(tmp_path):
    # the driver records {"rc", "tail"}; rc!=0 or value 0 must SKIP, not gate
    bad = _w(tmp_path, "bad.json", {"rc": 3, "tail": '{"metric": "m", "value": 0.0}'})
    good = _w(tmp_path, "good.json",
              {"rc": 0, "tail": 'warning line\n{"metric": "m", "value": 50.0, "unit": "x"}'})
    assert load_payload(bad)[0] is None
    assert load_payload(good)[0] == ("m", 50.0)
    assert main([bad, good]) == 0   # unhealthy old run never gates
    # and the real driver files from previous rounds parse without crashing
    import os

    for f in ("BENCH_r01.json", "BENCH_r02.json", "BENCH_r03.json"):
        if os.path.exists(f):
            load_payload(f)


def test_mismatched_metrics_skip(tmp_path):
    a = _w(tmp_path, "a.json", {"metric": "a", "value": 10.0})
    b = _w(tmp_path, "b.json", {"metric": "b", "value": 10.0})
    assert main([a, b]) == 0
