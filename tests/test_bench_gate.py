"""Benchmark regression gate (reference tools/ci_op_benchmark.sh +
check_op_benchmark_result.py: relative-regression CI gating)."""

import json
import sys

sys.path.insert(0, ".")
from tools.check_bench_regression import load_payload, main


def _w(tmp_path, name, payload):
    p = tmp_path / name
    p.write_text(json.dumps(payload))
    return str(p)


def test_regression_detected_and_gated(tmp_path):
    old = _w(tmp_path, "old.json",
             {"metric": "m", "value": 100.0, "unit": "x", "vs_baseline": 1.0})
    new = _w(tmp_path, "new.json",
             {"metric": "m", "value": 90.0, "unit": "x", "vs_baseline": 0.9})
    assert main([old, new, "--threshold", "0.05"]) == 1    # -10% fails
    assert main([old, new, "--threshold", "0.15"]) == 0    # within 15%
    ok = _w(tmp_path, "ok.json",
            {"metric": "m", "value": 101.0, "unit": "x", "vs_baseline": 1.0})
    assert main([old, ok]) == 0                            # improvement


def test_driver_wrapper_payloads(tmp_path):
    # the driver records {"rc", "tail"}; rc!=0 or value 0 must SKIP, not gate
    bad = _w(tmp_path, "bad.json", {"rc": 3, "tail": '{"metric": "m", "value": 0.0}'})
    good = _w(tmp_path, "good.json",
              {"rc": 0, "tail": 'warning line\n{"metric": "m", "value": 50.0, "unit": "x"}'})
    assert load_payload(bad)[0] is None
    assert load_payload(good)[0] == ("m", 50.0)
    assert main([bad, good]) == 0   # unhealthy old run never gates
    # and the real driver files from previous rounds parse without crashing
    import os

    for f in ("BENCH_r01.json", "BENCH_r02.json", "BENCH_r03.json"):
        if os.path.exists(f):
            load_payload(f)


def test_mismatched_metrics_skip(tmp_path):
    a = _w(tmp_path, "a.json", {"metric": "a", "value": 10.0})
    b = _w(tmp_path, "b.json", {"metric": "b", "value": 10.0})
    assert main([a, b]) == 0


def _slo_payload(ttft_p95=20.0, itl_p95=2.0):
    return {
        "metric": "serving_decode_chunked_speedup", "value": 5.0,
        "unit": "x", "detail": {"slo": {
            "tp_tokens_match": True,
            "single": {
                "ttft_ms": {"p50": 10.0, "p95": ttft_p95, "p99": 30.0},
                "itl_ms": {"p50": 1.0, "p95": itl_p95, "p99": 3.0},
            },
            "tp": None,
        }},
    }


def test_slo_percentile_gate(tmp_path):
    """Serving SLO wiring: latency percentiles gate with the direction
    INVERTED (growth is the regression) at the wider --slo-threshold;
    payloads without the section — every pre-SLO round — skip silently;
    the throughput metric keeps gating independently."""
    old = _w(tmp_path, "old.json", _slo_payload())
    same = _w(tmp_path, "same.json", _slo_payload())
    worse = _w(tmp_path, "worse.json", _slo_payload(ttft_p95=40.0))
    assert main([old, same]) == 0          # unchanged latencies pass
    assert main([old, worse]) == 1         # p95 TTFT doubled: regression
    assert main([old, worse, "--slo-threshold", "1.5"]) == 0  # within 150%
    assert main([worse, old]) == 0         # latency IMPROVED: never gates
    # inter-token latency gates too, independently of TTFT
    worse_itl = _w(tmp_path, "worse_itl.json", _slo_payload(itl_p95=4.0))
    assert main([old, worse_itl]) == 1
    # a pre-SLO payload on either side skips the latency gate
    pre = _w(tmp_path, "pre.json",
             {"metric": "serving_decode_chunked_speedup", "value": 5.0})
    assert main([pre, worse]) == 0
    assert main([worse, pre]) == 0
    # and a throughput regression still gates even with clean latencies
    slow = _w(tmp_path, "slow.json", dict(_slo_payload(), value=2.0))
    assert main([old, slow]) == 1


def _pipe_payload(zb=0.111, f1b=0.158, value=100.0):
    return {
        "metric": "llama_pretrain_tokens_per_sec_per_chip", "value": value,
        "unit": "tokens/s",
        "configs": [{"config": "B4", "tokens_per_sec": value, "mfu": 0.6}],
        "detail": {"pipeline": {
            "S": 4, "M": 16,
            "schedules": {"FThenB": 0.158, "1F1B": f1b, "ZB-H1": zb},
            "peak_residency": {"FThenB": 16.0, "1F1B": 4.0, "ZB-H1": 4.0},
        }},
    }


def test_pipeline_schedule_gate(tmp_path):
    """Pipeline wiring (bench.py detail.pipeline): per-schedule simulator
    bubble fractions gate LOWER-is-better at the regular threshold;
    pre-schedule payloads skip silently; an improved bubble never gates;
    the throughput headline keeps gating independently."""
    old = _w(tmp_path, "p_old.json", _pipe_payload())
    same = _w(tmp_path, "p_same.json", _pipe_payload())
    assert main([old, same]) == 0
    # ZB-H1 bubble grew 50%: a schedule-table regression, gated
    worse = _w(tmp_path, "p_worse.json", _pipe_payload(zb=0.166))
    assert main([old, worse]) == 1
    assert main([old, worse, "--threshold", "0.6"]) == 0
    assert main([worse, old]) == 0        # bubble SHRANK: never gates
    # the 1F1B entry gates independently of ZB-H1
    worse_1f1b = _w(tmp_path, "p_w1.json", _pipe_payload(f1b=0.2))
    assert main([old, worse_1f1b]) == 1
    # pre-schedule payloads (every earlier round) skip the gate silently
    pre = _w(tmp_path, "p_pre.json",
             {"metric": "llama_pretrain_tokens_per_sec_per_chip",
              "value": 100.0})
    assert main([pre, worse]) == 0
    assert main([worse, pre]) == 0
    # a throughput regression still gates with clean bubbles
    slow = _w(tmp_path, "p_slow.json", _pipe_payload(value=80.0))
    assert main([old, slow]) == 1
    # zero is the BEST bubble, not an unhealthy value: growth from a true
    # zero-bubble baseline gates; zero -> zero passes
    z_old = _w(tmp_path, "p_z0.json", _pipe_payload(zb=0.0))
    z_same = _w(tmp_path, "p_z1.json", _pipe_payload(zb=0.0))
    z_grew = _w(tmp_path, "p_z2.json", _pipe_payload(zb=0.05))
    assert main([z_old, z_same]) == 0
    assert main([z_old, z_grew]) == 1


def test_bench_payload_pipeline_section_shape():
    """The smoke/payload contract without running the model: bench.py's
    simulator section carries every registered schedule with ZB-H1
    strictly under 1F1B at the flagship (S, M), and the smoke assert
    accepts exactly the payload child() builds."""
    sys.path.insert(0, ".")
    import bench

    pl = bench._pipeline_detail()
    assert set(pl["schedules"]) >= {"FThenB", "1F1B", "ZB-H1"}
    assert pl["schedules"]["ZB-H1"] < pl["schedules"]["1F1B"]
    assert pl["peak_residency"]["ZB-H1"] <= pl["peak_residency"]["1F1B"]
    payload = {
        "value": 10.0, "configs": [
            {"config": "cpu_smoke", "tokens_per_sec": 10.0, "mfu": 0.0}],
        "detail": {"pipeline": pl},
    }
    bench._assert_smoke(payload)  # the CPU twin's field contract
    import pytest as _pytest

    with _pytest.raises(AssertionError):
        bench._assert_smoke({"value": 10.0, "configs": [],
                             "detail": {"pipeline": pl}})


def _snap_payload(save_ms=30.0, restore_ms=60.0):
    return {
        "metric": "serving_decode_chunked_speedup", "value": 5.0,
        "unit": "x", "detail": {"snapshot": {
            "save_ms": save_ms, "restore_ms": restore_ms,
            "bytes": 123456, "resume_tokens_match": True,
        }},
    }


def _overload_payload(chunked_ms=60.0, atomic_ms=400.0):
    return {
        "metric": "serving_decode_chunked_speedup", "value": 5.0,
        "unit": "x", "detail": {"overload": {
            "itl_p99_ms_chunked": chunked_ms,
            "itl_p99_ms_atomic": atomic_ms,
            "tokens_per_sec_chunked": 100.0, "tokens_per_sec_atomic": 95.0,
            "streams_identical": True, "prefill_chunks": 8,
            "preemptions": 1, "preempt_readmits": 1,
            "preempted_stream_identical": True,
        }},
    }


def _cluster_payload(detect_ms=40.0, recover_ms=400.0, value=900.0,
                     first_token=None):
    fo = {"detect_ms": detect_ms, "recover_ms": recover_ms,
          "lost": 0, "streams_match": True, "redispatches": 2}
    if first_token is not None:
        fo["first_token_ms"] = dict(first_token)
        fo["promotions"] = 1
        fo["respawn_compile_hits"] = 40
    return {
        "metric": "cluster_tokens_per_sec", "value": value,
        "unit": "tok/s", "tokens_match": True,
        "detail": {"failover": fo},
    }


def test_cluster_failover_gate(tmp_path):
    """Cluster fail-over wiring (bench_cluster.py): detect/recover walls
    gate lower-is-better at the SLO threshold; pre-cluster payloads skip
    silently; the two latencies gate independently of the throughput
    headline."""
    old = _w(tmp_path, "c_old.json", _cluster_payload())
    same = _w(tmp_path, "c_same.json", _cluster_payload())
    assert main([old, same]) == 0
    slow_detect = _w(tmp_path, "c_sd.json", _cluster_payload(detect_ms=200.0))
    assert main([old, slow_detect]) == 1     # detection 5x slower: gates
    assert main([old, slow_detect, "--slo-threshold", "9.0"]) == 0
    assert main([slow_detect, old]) == 0     # improvement never gates
    slow_recover = _w(tmp_path, "c_sr.json",
                      _cluster_payload(recover_ms=2500.0))
    assert main([old, slow_recover]) == 1    # recovery gates independently
    # throughput regression still caught by the headline metric gate
    slow_tps = _w(tmp_path, "c_tps.json", _cluster_payload(value=400.0))
    assert main([old, slow_tps]) == 1
    # pre-cluster payloads on either side skip the fail-over gate
    pre = _w(tmp_path, "c_pre.json",
             {"metric": "cluster_tokens_per_sec", "value": 900.0})
    assert main([pre, slow_detect]) == 0
    assert main([slow_detect, pre]) == 0
    # a run that LOST a request records rc != 0: skipped as unhealthy,
    # never used as a baseline that would mask the next regression
    lost = _w(tmp_path, "c_lost.json",
              {"rc": 1, "tail": json.dumps(_cluster_payload())})
    assert main([lost, same]) == 0


def test_cluster_first_token_gate(tmp_path):
    """Warm-start wiring (bench_cluster.py fail-over matrix): the
    per-recovery-mode detect->first-token numbers gate lower-is-better
    at the SLO threshold, each mode independently; payloads from before
    the warm-start round carry no first_token_ms dict and skip that
    sub-gate silently in either direction."""
    ft = {"cold": 2000.0, "warm_respawn": 1500.0, "standby": 120.0}
    old = _w(tmp_path, "f_old.json", _cluster_payload(first_token=ft))
    same = _w(tmp_path, "f_same.json", _cluster_payload(first_token=ft))
    assert main([old, same]) == 0
    # the standby (promotion) path regressing 5x gates even while cold
    # and warm_respawn are unchanged — each mode gates independently
    slow_sb = _w(tmp_path, "f_sb.json", _cluster_payload(
        first_token=dict(ft, standby=600.0)))
    assert main([old, slow_sb]) == 1
    assert main([old, slow_sb, "--slo-threshold", "9.0"]) == 0
    assert main([slow_sb, old]) == 0         # improvement never gates
    slow_cold = _w(tmp_path, "f_cold.json", _cluster_payload(
        first_token=dict(ft, cold=9000.0)))
    assert main([old, slow_cold]) == 1
    # pre-warm-start payloads (no first_token_ms) skip the sub-gate but
    # keep gating detect/recover
    pre = _w(tmp_path, "f_pre.json", _cluster_payload())
    assert main([pre, slow_sb]) == 0
    assert main([slow_sb, pre]) == 0
    pre_slow = _w(tmp_path, "f_preslow.json",
                  _cluster_payload(detect_ms=200.0))
    assert main([pre, pre_slow]) == 1


def test_overload_itl_gate(tmp_path):
    """Overload-discipline wiring (chunked prefill interleaving): the
    adversarial mix's resident-stream p99 ITL gates lower-is-better at
    the SLO threshold on BOTH the chunked side (the product) and the
    atomic side (the workload control); pre-chunking payloads skip
    silently in either direction."""
    old = _w(tmp_path, "o_old.json", _overload_payload())
    same = _w(tmp_path, "o_same.json", _overload_payload())
    assert main([old, same]) == 0            # unchanged timings pass
    worse = _w(tmp_path, "o_worse.json", _overload_payload(chunked_ms=180.0))
    assert main([old, worse]) == 1           # chunked p99 tripled: gates
    assert main([old, worse, "--slo-threshold", "3.0"]) == 0  # within 300%
    assert main([worse, old]) == 0           # improvement never gates
    worse_atomic = _w(tmp_path, "o_wa.json",
                      _overload_payload(atomic_ms=1600.0))
    assert main([old, worse_atomic]) == 1    # the control gates too
    # a pre-chunking payload on either side skips the overload gate
    pre = _w(tmp_path, "o_pre.json",
             {"metric": "serving_decode_chunked_speedup", "value": 5.0})
    assert main([pre, worse]) == 0
    assert main([worse, pre]) == 0


def test_snapshot_timing_gate(tmp_path):
    """Engine-snapshot wiring (serving fault tolerance): save/restore
    wall gates lower-is-better at the SLO threshold; pre-snapshot
    payloads skip silently; save and restore gate independently."""
    old = _w(tmp_path, "s_old.json", _snap_payload())
    same = _w(tmp_path, "s_same.json", _snap_payload())
    worse = _w(tmp_path, "s_worse.json", _snap_payload(save_ms=90.0))
    assert main([old, same]) == 0            # unchanged timings pass
    assert main([old, worse]) == 1           # save wall tripled: regression
    assert main([old, worse, "--slo-threshold", "3.0"]) == 0  # within 300%
    assert main([worse, old]) == 0           # IMPROVED: never gates
    worse_restore = _w(tmp_path, "s_wr.json", _snap_payload(restore_ms=200.0))
    assert main([old, worse_restore]) == 1   # restore gates independently
    # a pre-snapshot payload on either side skips the gate
    pre = _w(tmp_path, "s_pre.json",
             {"metric": "serving_decode_chunked_speedup", "value": 5.0})
    assert main([pre, worse]) == 0
    assert main([worse, pre]) == 0
