"""Fleet surface: topology math, collectives (axis mode), mpu TP layers,
sequence parallel, fleet facade e2e.

Reference models: test/collective/fleet/hybrid_parallel_communicate_group.py
(pure topology), test/collective/collective_allreduce_api.py (numerics),
hybrid_parallel_mp_layers.py (TP layer parity vs dense).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.distributed.shard_map_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import ProcessMesh
from paddle_tpu.distributed.fleet import CommunicateTopology, HybridCommunicateGroup
from paddle_tpu.distributed.communication import collective_axis_scope


# ------------------------------------------------------------------ topology
def test_topology_grid():
    topo = CommunicateTopology(["data", "pipe", "sharding", "sep", "model"], [2, 2, 1, 1, 2])
    assert topo.world_size() == 8
    assert topo.get_rank(data=1, pipe=0, sharding=0, sep=0, model=1) == 5
    assert topo.get_coord(5) == (1, 0, 0, 0, 1)
    # along model axis with other coords fixed
    comm = topo.get_comm_list("model")
    assert [0, 1] in comm and [6, 7] in comm
    assert topo.get_rank_from_stage(0, pipe=1) == 2


def test_hcg_groups():
    topo = CommunicateTopology(["data", "pipe", "sharding", "sep", "model"], [2, 2, 1, 1, 2])
    hcg = HybridCommunicateGroup(topo, global_rank=0)
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_pipe_parallel_world_size() == 2
    assert hcg.get_data_parallel_group().nranks == 2
    assert hcg.is_first_stage()
    m = hcg.as_process_mesh()
    assert m.dim_names == ["dp", "pp", "mp"]
    assert m.shape == [2, 2, 2]


# --------------------------------------------------------------- collectives
def _mesh1d(n=8, name="x"):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), (name,))


def test_all_reduce_axis_mode():
    mesh = _mesh1d(8)
    x = np.arange(8.0, dtype=np.float32).reshape(8, 1)

    def body(xl):
        t = paddle.to_tensor(xl)
        with collective_axis_scope({"x": "x"}):
            dist.all_reduce(t)
        return t._value

    out = shard_map(body, mesh=mesh, in_specs=P("x"), out_specs=P("x"))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))


def test_all_gather_and_alltoall_axis_mode():
    mesh = _mesh1d(8)
    x = np.arange(16.0, dtype=np.float32).reshape(8, 2)

    def body(xl):
        t = paddle.to_tensor(xl[0])
        with collective_axis_scope({"x": "x"}):
            gathered = dist.all_gather(None, t)
        return gathered._value[None]

    out = shard_map(body, mesh=mesh, in_specs=P("x"), out_specs=P("x"))(jnp.asarray(x))
    # every rank ends with the full gather
    np.testing.assert_allclose(np.asarray(out)[0], x)
    np.testing.assert_allclose(np.asarray(out)[7], x)


def test_reduce_scatter_axis_mode():
    mesh = _mesh1d(4, "r")
    x = np.ones((4, 8), dtype=np.float32)

    def body(xl):
        src = paddle.to_tensor(xl[0])  # [8] per rank
        out = paddle.zeros([2])
        with collective_axis_scope({"r": "r"}):
            dist.reduce_scatter(out, src)
        return out._value[None]

    out = shard_map(body, mesh=mesh, in_specs=P("r"), out_specs=P("r"))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), np.full((4, 2), 4.0))


def test_collectives_world1_noop():
    t = paddle.to_tensor(np.ones(4, np.float32))
    dist.all_reduce(t)
    np.testing.assert_allclose(np.asarray(t._value), np.ones(4))
    lst = []
    dist.all_gather(lst, t)
    assert len(lst) == 1
    dist.barrier()


# ---------------------------------------------------------------- mpu layers
def test_tp_layers_match_dense():
    from paddle_tpu.distributed.fleet.layers.mpu import (
        ColumnParallelLinear,
        RowParallelLinear,
        VocabParallelEmbedding,
    )

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
    dist.set_mesh(mesh)
    try:
        paddle.seed(0)
        col = ColumnParallelLinear(16, 32, gather_output=True)
        row = RowParallelLinear(32, 16, input_is_parallel=False)
        emb = VocabParallelEmbedding(64, 16)
        # dense twins with identical weights
        paddle.seed(0)
        dcol = paddle.nn.Linear(16, 32)
        drow = paddle.nn.Linear(32, 16)
        demb = paddle.nn.Embedding(64, 16)

        ids = paddle.to_tensor(np.random.default_rng(0).integers(0, 64, (4, 8)).astype(np.int32))
        h = emb(ids)
        h2 = demb(ids)
        np.testing.assert_allclose(np.asarray(h._value), np.asarray(h2._value), rtol=1e-6)

        y = row(col(h))
        y2 = drow(dcol(h2))
        np.testing.assert_allclose(np.asarray(y._value), np.asarray(y2._value), rtol=1e-4, atol=1e-5)
        # weights really sharded over mp
        assert col.weight._value.sharding.shard_shape(col.weight._value.shape) == (16, 8)
        assert row.weight._value.sharding.shard_shape(row.weight._value.shape) == (8, 16)
        assert emb.weight._value.sharding.shard_shape(emb.weight._value.shape) == (16, 16)
    finally:
        dist.set_mesh(None)


def test_sequence_parallel_ops():
    from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils import (
        AllGatherOp,
        ScatterOp,
    )

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
    dist.set_mesh(mesh)
    try:
        x = paddle.to_tensor(np.random.default_rng(0).normal(size=(2, 8, 16)).astype(np.float32))
        s = ScatterOp.apply(x, axis=1)
        g = AllGatherOp.apply(s, axis=1)
        np.testing.assert_allclose(np.asarray(g._value), np.asarray(x._value), rtol=1e-6)
    finally:
        dist.set_mesh(None)


# ------------------------------------------------------------------- facade
def test_fleet_e2e_mp_dp():
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.layers.mpu import ColumnParallelLinear, RowParallelLinear

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    try:
        assert fleet.is_initialized()
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == 4

        paddle.seed(3)

        class MLP(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.up = ColumnParallelLinear(16, 64, gather_output=False)
                self.down = RowParallelLinear(64, 16, input_is_parallel=True)

            def forward(self, x):
                return self.down(paddle.nn.functional.relu(self.up(x)))

        model = fleet.distributed_model(MLP())
        opt = fleet.distributed_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-2, parameters=model.parameters())
        )

        def loss_fn(m, x, y):
            return paddle.mean((m(x) - y) ** 2)

        step = fleet.make_train_step(model, opt, loss_fn)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(8, 16)).astype(np.float32))
        y = paddle.to_tensor(rng.normal(size=(8, 16)).astype(np.float32))
        losses = [float(step(x, y)._value) for _ in range(5)]
        assert losses[-1] < losses[0]
    finally:
        dist.set_mesh(None)


def _group_sharded_levels_body():
    """Payload of test_group_sharded_levels, run in a crash-isolated
    subprocess: ShardedTrainStep over the in-process 8-dev XLA:CPU
    communicator SIGSEGVs intermittently on jax 0.4.37 (same class as the
    slow-marked test_dist_passes zero+pp+tp compose and the MoE semi-auto
    train).  As a module function it is importable by the worker."""
    from paddle_tpu.distributed.sharding import group_sharded_parallel

    mesh = ProcessMesh(np.arange(8).reshape(8), ["dp"])
    dist.set_mesh(mesh)
    try:
        paddle.seed(0)
        model = paddle.nn.Linear(16, 16)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=model.parameters())
        model, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
        assert opt._zero_stage == 3
        # stage3: params physically sharded over dp
        w = model.weight._value
        assert w.sharding.shard_shape(w.shape) in ((2, 16), (16, 2))

        def loss_fn(m, x, y):
            return paddle.mean((m(x) - y) ** 2)

        step = dist.ShardedTrainStep(model, opt, loss_fn, mesh, batch_spec=P("dp"))
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(8, 16)).astype(np.float32))
        y = paddle.to_tensor(rng.normal(size=(8, 16)).astype(np.float32))
        losses = [float(step(x, y)._value) for _ in range(4)]
        assert losses[-1] < losses[0]
    finally:
        dist.set_mesh(None)


def test_group_sharded_levels():
    """Previously slow-marked: a mid-suite segfault killed the whole
    tier-1 process.  The payload now runs in tools/run_tier1.py's
    crash-isolated worker — a SIGSEGV is a contained retry (intermittent
    infra), an assertion failure still fails immediately — so the ZeRO
    stage-3 coverage is back in tier-1."""
    from tools.run_tier1 import run_isolated_test

    run_isolated_test("tests.test_fleet", "_group_sharded_levels_body",
                      retries=2, timeout=300)


def test_all_reduce_world_in_multi_axis_scope():
    """group=None inside a 2-axis scope reduces over BOTH axes (the world)."""
    import jax

    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("a", "b"))
    x = np.arange(4.0, dtype=np.float32).reshape(2, 2, 1)

    def body(xl):
        t = paddle.to_tensor(xl)
        with collective_axis_scope({"a": "a", "b": "b"}):
            dist.all_reduce(t)
        return t._value

    out = shard_map(body, mesh=mesh, in_specs=P("a", "b"), out_specs=P("a", "b"))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out).ravel(), np.full(4, 6.0))


def test_all_gather_world_multi_axis_scope_raises():
    import jax
    import pytest

    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("a", "b"))

    def body(xl):
        t = paddle.to_tensor(xl)
        with collective_axis_scope({"a": "a", "b": "b"}):
            with pytest.raises(RuntimeError, match="ambiguous"):
                dist.all_gather(None, t)
        return t._value

    shard_map(body, mesh=mesh, in_specs=P("a", "b"), out_specs=P("a", "b"))(
        jnp.zeros((2, 2, 1))
    )


def test_all_reduce_prod_signs_and_zeros():
    mesh = _mesh1d(4)
    x = np.array([[-2.0], [3.0], [-1.0], [0.5]], np.float32)  # prod = 3.0
    y = np.array([[-2.0], [0.0], [4.0], [1.0]], np.float32)  # prod = 0.0

    def body(xl):
        t = paddle.to_tensor(xl)
        with collective_axis_scope({"x": "x"}):
            dist.all_reduce(t, op=dist.ReduceOp.PROD)
        return t._value

    f = shard_map(body, mesh=Mesh(np.array(jax.devices()[:4]), ("x",)), in_specs=P("x"), out_specs=P("x"))
    np.testing.assert_allclose(np.asarray(f(jnp.asarray(x))).ravel(), np.full(4, 3.0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(f(jnp.asarray(y))).ravel(), np.zeros(4), atol=1e-7)


def test_hcg_groups_tagged_with_mesh_axes():
    topo = CommunicateTopology(["data", "pipe", "sharding", "sep", "model"], [2, 2, 1, 1, 2])
    hcg = HybridCommunicateGroup(topo, global_rank=0)
    assert hcg.get_model_parallel_group().axis == "mp"
    assert hcg.get_data_parallel_group().axis == "dp"
    assert hcg.get_pipe_parallel_group().axis == "pp"
