"""Eager dispatch fast path (FLAGS_eager_op_jit, _core/dispatch.py).

The cache must be observationally invisible: every covered behavior is
checked bit-identical against the flag-off slow path — forward, backward,
AMP auto_cast, tensor hooks, create_graph double backward, RNG streams —
while the counters prove the fast path actually serves hits.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu._core import autograd, dispatch


@pytest.fixture(autouse=True)
def _fresh_cache():
    paddle.set_flags({"FLAGS_eager_op_jit": True})
    dispatch.cache.clear()
    dispatch.cache.reset_stats()
    yield
    paddle.set_flags({"FLAGS_eager_op_jit": True})


def _stats():
    return dispatch.cache.stats()


def _x(shape=(3, 4), seed=0, grad=False):
    rng = np.random.default_rng(seed)
    return paddle.to_tensor(rng.standard_normal(shape).astype(np.float32),
                            stop_gradient=not grad)


# ------------------------------------------------------------------ counters


def test_hit_miss_counters_across_signatures():
    x = _x(grad=True)
    w = _x((4, 4), seed=1, grad=True)

    def step():
        x.clear_grad(); w.clear_grad()
        paddle.matmul(x, w).sum().backward()

    step()
    s0 = _stats()
    assert s0["misses"] >= 1
    # hits count compiled-path serves only: the hotness ramp (2 eager-served
    # repeats) shows up as bypasses, then call 4+ hits the jitted trace
    for _ in range(4):
        step()
    s1 = _stats()
    assert s1["hits"] > s0["hits"]
    assert s1["bypasses"] > s0["bypasses"]

    # new shape => new signature => miss, not a wrong-shape hit
    x8 = _x((8, 4), seed=2, grad=True)
    x8.clear_grad(); w.clear_grad()
    paddle.matmul(x8, w).sum().backward()
    assert _stats()["misses"] > s1["misses"]

    # new dtype => new signature
    before = _stats()["misses"]
    a16 = paddle.to_tensor(np.ones((3, 4), np.float32)).astype("bfloat16")
    b16 = paddle.to_tensor(np.ones((4, 4), np.float32)).astype("bfloat16")
    paddle.matmul(a16, b16)
    assert _stats()["misses"] > before

    # changed static closure value (transpose_y) => new signature
    before = _stats()["misses"]
    paddle.matmul(x, w, transpose_y=True)
    assert _stats()["misses"] > before


def test_grad_path_traces_amortized():
    x = _x(grad=True)
    for _ in range(6):
        x.clear_grad()
        paddle.tanh(x).sum().backward()
    s = _stats()
    # tanh fwd+vjp traced once, backward application traced once; the
    # remaining five iterations are hits without retraces
    assert s["hits"] >= 5
    assert s["traces"] <= 4, s


# ------------------------------------------------------- numerics parity


def _train_trace(steps=4):
    paddle.seed(0)
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt

    m = nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 2))
    o = opt.SGD(learning_rate=0.05, parameters=m.parameters())
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(2, 6) / 12.0)
    y = paddle.to_tensor(np.ones((2, 2), np.float32))
    losses = []
    for _ in range(steps):
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(np.asarray(loss._value).item())
    return losses


def test_forward_backward_bit_identical_on_off():
    paddle.set_flags({"FLAGS_eager_op_jit": True})
    on = _train_trace()
    on2 = _train_trace()  # second run: all cache hits
    paddle.set_flags({"FLAGS_eager_op_jit": False})
    off = _train_trace()
    assert on == off == on2


def test_amp_auto_cast_bit_identical_on_off():
    def run():
        with paddle.amp.auto_cast():
            a = _x((4, 8), grad=True)
            b = _x((8, 8), seed=1, grad=True)
            out = paddle.matmul(a, paddle.exp(b) * 0.1)
            out2 = paddle.matmul(a, paddle.exp(b) * 0.1)  # cached on 2nd run
            loss = out.astype("float32").sum() + out2.astype("float32").sum()
            loss.backward()
        return (np.asarray(out._value).copy(), np.asarray(a.grad._value).copy(),
                np.asarray(b.grad._value).copy(), str(out.dtype))

    paddle.set_flags({"FLAGS_eager_op_jit": True})
    run()  # populate
    on = run()
    paddle.set_flags({"FLAGS_eager_op_jit": False})
    off = run()
    assert on[3] == off[3]
    for a, b in zip(on[:3], off[:3]):
        np.testing.assert_array_equal(a, b)


def test_tensor_hooks_bit_identical_on_off():
    def run():
        x = _x((5,), grad=True)
        x.register_hook(lambda g: g * 3)
        (x * 2.0).sum().backward()
        return np.asarray(x.grad._value).copy()

    paddle.set_flags({"FLAGS_eager_op_jit": True})
    run()
    on = run()
    paddle.set_flags({"FLAGS_eager_op_jit": False})
    off = run()
    np.testing.assert_array_equal(on, off)
    np.testing.assert_array_equal(on, np.full(5, 6.0, np.float32))


def test_create_graph_double_backward_bypasses_cache():
    def run():
        x = paddle.to_tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
        y = (x * x * x).sum()
        (g,) = paddle.grad(y, x, create_graph=True)
        g.sum().backward()
        return np.asarray(x.grad._value).copy()

    paddle.set_flags({"FLAGS_eager_op_jit": True})
    run()
    before = _stats()
    on = run()
    after = _stats()
    # the _vjp_through_tape rebuild closes over the GradNode — uncacheable,
    # so the second-order walk bypasses rather than hitting a frozen trace
    assert after["bypasses"] > before["bypasses"]
    paddle.set_flags({"FLAGS_eager_op_jit": False})
    off = run()
    np.testing.assert_array_equal(on, off)
    np.testing.assert_array_equal(on, np.array([12.0, 18.0], np.float32))


def test_rng_stream_identical_on_off():
    """Stateful RNG inside op bodies must neither freeze nor drift: the
    cached-trace guard aborts such traces before a counter tick."""
    x = _x((16, 16))

    def run():
        paddle.seed(42)
        a = np.asarray(F.dropout(x, 0.5, training=True)._value).copy()
        b = np.asarray(F.rrelu(-x, training=True)._value).copy()
        c = np.asarray(F.dropout(x, 0.5, training=True)._value).copy()
        return a, b, c

    paddle.set_flags({"FLAGS_eager_op_jit": True})
    run()  # populate / mark bypasses
    on = run()
    assert not np.array_equal(on[0], on[2])  # randomness advances
    paddle.set_flags({"FLAGS_eager_op_jit": False})
    off = run()
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a, b)


# ------------------------------------------------------------ fn identity


def test_no_crosstalk_between_equal_code_different_closures():
    x = _x()

    def make(c):
        return lambda v: v * c

    a = autograd.apply("xtalk_scale", make(2.0), x)
    b = autograd.apply("xtalk_scale", make(3.0), x)
    a2 = autograd.apply("xtalk_scale", make(2.0), x)  # hits a's entry
    np.testing.assert_array_equal(np.asarray(a._value), np.asarray(x._value) * 2.0)
    np.testing.assert_array_equal(np.asarray(b._value), np.asarray(x._value) * 3.0)
    np.testing.assert_array_equal(np.asarray(a2._value), np.asarray(a._value))


def test_mutated_recording_closure_does_not_poison_cache():
    """The jit must be built from the fn of the call that crosses the
    hotness ramp, not the recording call's pinned fn: mutating a container
    the first closure referenced must not leak into later equal-keyed
    calls."""
    x = _x()

    def make(lst):
        return lambda v: v * lst[0]

    shared = [2.0]
    autograd.apply("mut_close", make(shared), x)  # records with value 2.0
    shared[0] = 5.0  # caller mutates the recorded closure's list
    for _ in range(4):  # fresh equal-valued closures: ramp then compile
        r = autograd.apply("mut_close", make([2.0]), x)
    np.testing.assert_array_equal(np.asarray(r._value), np.asarray(x._value) * 2.0)


def test_no_crosstalk_between_ops_sharing_fn():
    x = _x()
    import jax.numpy as jnp

    r1 = autograd.apply("op_one", jnp.negative, x)
    r2 = autograd.apply("op_two", jnp.negative, x)  # same fn, different name
    np.testing.assert_array_equal(np.asarray(r1._value), np.asarray(r2._value))
    assert _stats()["misses"] >= 2  # separate entries per op name


# ------------------------------------------------------- flags / lifecycle


def test_set_flags_clears_cache_and_restores_slow_path():
    x = _x(grad=True)
    for _ in range(2):
        x.clear_grad()
        paddle.tanh(x).sum().backward()
    assert _stats()["size"] > 0
    paddle.set_flags({"FLAGS_eager_op_jit": False})
    assert _stats()["size"] == 0  # invalidated
    dispatch.cache.reset_stats()
    x.clear_grad()
    paddle.tanh(x).sum().backward()
    s = _stats()
    # flag off: the funnel never consults the cache — exact pre-PR dispatch
    assert s["hits"] == s["misses"] == s["bypasses"] == 0 and not s["enabled"]
    np.testing.assert_allclose(np.asarray(x.grad._value),
                               1.0 - np.tanh(np.asarray(x._value)) ** 2,
                               rtol=1e-6)


def test_noop_set_flags_does_not_invalidate():
    x = _x(grad=True)
    for _ in range(4):
        x.clear_grad()
        paddle.tanh(x).sum().backward()
    size = _stats()["size"]
    assert size > 0
    # re-setting a flag to its current value must NOT wipe compiled traces
    paddle.set_flags({"FLAGS_check_nan_inf": False})
    paddle.set_flags({"FLAGS_eager_op_jit": True})
    assert _stats()["size"] == size


def test_cache_size_flag_bounds_entries_with_lru_eviction():
    paddle.set_flags({"FLAGS_eager_op_cache_size": 3})
    try:
        dispatch.cache.reset_stats()
        for n in range(2, 10):
            w = paddle.to_tensor(np.ones((n,), np.float32), stop_gradient=False)
            paddle.tanh(w).sum().backward()
        s = _stats()
        assert s["size"] <= 3
        assert s["evictions"] > 0
        assert s["capacity"] == 3
    finally:
        paddle.set_flags({"FLAGS_eager_op_cache_size": 1024})


def test_profiler_exposes_cache_stats():
    from paddle_tpu import profiler

    x = _x()
    for _ in range(3):
        F.softmax(x, axis=-1)
    s = profiler.dispatch_cache_stats()
    for key in ("hits", "misses", "traces", "evictions", "bypasses", "size",
                "capacity", "enabled"):
        assert key in s
    assert s["misses"] >= 1

    p = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU])
    with p:
        F.softmax(x, axis=-1)
    table = p.summary()
    assert "Eager dispatch cache" in table

    profiler.reset_dispatch_cache()
    s2 = profiler.dispatch_cache_stats()
    assert s2["size"] == 0 and s2["hits"] == 0


# ----------------------------------------------------- transparency edges


def test_data_dependent_shape_op_falls_back():
    x = paddle.to_tensor(np.array([[1.0, 0.0], [0.0, 2.0]], np.float32))

    def masked(v):
        import jax.numpy as jnp

        return v[np.asarray(v) > 0]  # numpy peek: untraceable, eager-only

    # call enough times to cross the hotness threshold so the jit attempt
    # actually fires (and fails -> entry marked eager-only)
    rs = [autograd.apply("data_dep", masked, x) for _ in range(5)]
    for r in rs[1:]:
        np.testing.assert_array_equal(np.asarray(rs[0]._value), np.asarray(r._value))


def test_pytree_roundtrip_restores_dist_slots():
    """_unflatten must initialize process_mesh/placements: a Tensor coming
    back from a jit/tree_map round-trip supports is_dist()."""
    import jax

    t = paddle.ones([2, 2])
    (rt,) = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda v: v, t))
    t2 = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(t), [rt])
    assert t2.is_dist() is False
    assert t2.process_mesh is None and t2.placements is None

    p = paddle.create_parameter([2, 2], "float32")
    flat, treedef = jax.tree_util.tree_flatten(p)
    p2 = jax.tree_util.tree_unflatten(treedef, flat)
    assert p2.is_dist() is False

    @jax.jit
    def ident(x):
        return x

    t3 = ident(t)
    assert t3.is_dist() is False


# ---------------------------------------------------- scan-body identity guard


def test_scan_body_guard_warns_on_body_shared_across_jit_traces():
    """FLAGS_scan_body_guard: the same lax.scan body function object traced
    under two distinct jit entries poisons jax's scan-jaxpr cache (PR 3,
    docs/SCAN_LAYERS.md) — the dev-mode guard must warn."""
    import warnings

    import jax
    import jax.numpy as jnp

    paddle.set_flags({"FLAGS_scan_body_guard": True})
    try:
        def shared_body(c, x):  # ONE body object, reused across traces
            return c + x, c

        def run(xs):
            return jax.lax.scan(shared_body, jnp.zeros(()), xs)[0]

        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            jax.jit(run)(jnp.ones(4))  # first trace: no warning
            assert not any(isinstance(w.message, dispatch.ScanBodyReuseWarning)
                           for w in caught)
            jax.jit(lambda xs: run(xs) * 2)(jnp.ones(4))  # second trace
        assert any(isinstance(w.message, dispatch.ScanBodyReuseWarning)
                   for w in caught), "shared scan body not flagged"
    finally:
        paddle.set_flags({"FLAGS_scan_body_guard": False})


def test_scan_body_guard_quiet_for_fresh_bodies_and_when_off():
    import warnings

    import jax
    import jax.numpy as jnp

    paddle.set_flags({"FLAGS_scan_body_guard": True})
    try:
        def run(xs):
            def body(c, x):  # defined INSIDE the traced fn — the fix
                return c + x, c

            return jax.lax.scan(body, jnp.zeros(()), xs)[0]

        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            jax.jit(run)(jnp.ones(4))
            jax.jit(lambda xs: run(xs) * 2)(jnp.ones(4))
        assert not any(isinstance(w.message, dispatch.ScanBodyReuseWarning)
                       for w in caught)
    finally:
        paddle.set_flags({"FLAGS_scan_body_guard": False})

    # flag off: a shared body stays silent (guard is dev-mode only)
    def shared(c, x):
        return c + x, c

    def run2(xs):
        return jax.lax.scan(shared, jnp.zeros(()), xs)[0]

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        jax.jit(run2)(jnp.ones(4))
        jax.jit(lambda xs: run2(xs) * 3)(jnp.ones(4))
    assert not any(isinstance(w.message, dispatch.ScanBodyReuseWarning)
                   for w in caught)
