"""Multi-tenant LoRA serving (docs/LORA.md): stacked adapter packs in the
jitted decode scan, hot-swap at macro-step boundaries with zero
recompiles, and the adapter-aware prefix cache.

Parity contract under test: a macro-step batching requests of DIFFERENT
adapters (plus base-model rows at slot 0) emits token streams
bit-identical to per-adapter serial runs — greedy and seeded sampling,
chunked and per-token dispatch, loop and LayerStack decoder layouts.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.nn.lora import apply_lora, lora_state_dict
from paddle_tpu.serving import GenerationEngine

import jax
import jax.numpy as jnp

_KW = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=4, max_position_embeddings=64,
           dtype="float32")


def _cfg(**kw):
    from paddle_tpu.models.llama import llama_tiny

    base = dict(_KW)
    base.update(kw)
    return llama_tiny(**base)


def _model(seed=41, **kw):
    from paddle_tpu.models.llama import LlamaForCausalLM

    paddle.seed(seed)
    m = LlamaForCausalLM(_cfg(**kw))
    m.eval()
    return m


def _adapter_sd(base, key_seed, b_scale=0.2, rank=4, alpha=8):
    """An adapter-only state dict whose deltas are large enough to shift
    greedy argmax (a zero-B adapter is the base model)."""
    from paddle_tpu.models.llama import LlamaForCausalLM

    ft = LlamaForCausalLM(_cfg())
    ft.set_state_dict(base.state_dict())
    ft.eval()
    apply_lora(ft, rank=rank, alpha=alpha)
    key = jax.random.PRNGKey(key_seed)
    for name, p in ft.named_parameters():
        if name.endswith(("lora_A", "lora_B")):
            key, sk = jax.random.split(key)
            scale = b_scale if name.endswith("lora_B") else 0.05
            p._bind(jax.random.normal(sk, p._value.shape,
                                      jnp.float32) * scale)
    return lora_state_dict(ft)


def _drain(eng):
    out = {}
    while eng.has_work():
        for rid, toks in eng.step().items():
            out.setdefault(rid, []).extend(
                toks if isinstance(toks, list) else [toks])
    return out


_PROMPTS = {
    "a0": [5, 9, 17, 33, 2],
    "a1": [7, 11, 3, 20],
    "a2": [15, 4, 40, 8, 22, 1],
    "base": [5, 9, 17, 33, 2],
}
_REQ_ADAPTERS = {"a0": "t0", "a1": "t1", "a2": "t2", "base": None}


def _register_all(eng, sds):
    for name, sd in sds.items():
        eng.register_adapter(name, sd, alpha=8)


@pytest.mark.parametrize("decode_chunk", [1, 4])
@pytest.mark.parametrize("fuse", [False, True])
def test_mixed_adapter_batch_bit_identical_to_serial(decode_chunk, fuse):
    """≥3 distinct adapters + a base-slot row in ONE macro-step: streams
    equal per-adapter serial runs bit-for-bit (greedy), on both decoder
    layouts and both dispatch widths."""
    model = _model(fuse_layer_stack=fuse)
    sds = {f"t{i}": _adapter_sd(model, key_seed=10 + i) for i in range(3)}

    serial = {}
    for rid, prompt in _PROMPTS.items():
        eng = GenerationEngine(model, max_batch=1, block_size=8,
                               num_blocks=16, decode_chunk=decode_chunk,
                               adapters={"rank": 4, "max_adapters": 3})
        _register_all(eng, sds)
        eng.add_request(rid, prompt, max_new_tokens=6,
                        adapter=_REQ_ADAPTERS[rid])
        _drain(eng)
        serial[rid] = eng.result(rid)
    # the three tenants genuinely decode differently
    assert len({tuple(v) for v in serial.values()}) >= 3

    mixed = GenerationEngine(model, max_batch=4, block_size=8, num_blocks=32,
                             decode_chunk=decode_chunk,
                             adapters={"rank": 4, "max_adapters": 3})
    _register_all(mixed, sds)
    for rid, prompt in _PROMPTS.items():
        mixed.add_request(rid, prompt, max_new_tokens=6,
                          adapter=_REQ_ADAPTERS[rid])
    _drain(mixed)
    for rid in _PROMPTS:
        assert mixed.result(rid) == serial[rid], rid


def test_mixed_adapter_sampled_streams_bit_identical():
    """Seeded per-request sampling across a mixed-adapter batch: each
    request's stream matches its serial run.  The PRNG key folds the
    SUBMIT-order nonce, so the serial engines pin their request counter
    to the mixed run's nonce — the same (seed, join order) contract the
    plain engine documents."""
    model = _model()
    sds = {f"t{i}": _adapter_sd(model, key_seed=20 + i) for i in range(3)}
    order = list(_PROMPTS)

    mixed = GenerationEngine(model, max_batch=4, block_size=8, num_blocks=32,
                             adapters={"rank": 4, "max_adapters": 3})
    _register_all(mixed, sds)
    for rid in order:
        mixed.add_request(rid, _PROMPTS[rid], max_new_tokens=6,
                          adapter=_REQ_ADAPTERS[rid],
                          temperature=0.9, seed=5)
    _drain(mixed)

    for nonce, rid in enumerate(order):
        eng = GenerationEngine(model, max_batch=1, block_size=8,
                               num_blocks=16,
                               adapters={"rank": 4, "max_adapters": 3})
        _register_all(eng, sds)
        eng._req_counter = nonce  # align the submit-order nonce
        eng.add_request(rid, _PROMPTS[rid], max_new_tokens=6,
                        adapter=_REQ_ADAPTERS[rid], temperature=0.9, seed=5)
        _drain(eng)
        assert eng.result(rid) == mixed.result(rid), rid


def test_slot0_base_parity_with_lora_free_engine():
    """Base-model requests on an adapter engine (slot 0, zero gathers)
    stream identically to a LoRA-free engine — even sharing a macro-step
    with adapted tenants."""
    model = _model()
    plain = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=16)
    plain.add_request("b", _PROMPTS["base"], max_new_tokens=8)
    _drain(plain)

    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=32,
                           adapters={"rank": 4, "max_adapters": 2})
    eng.register_adapter("t", _adapter_sd(model, key_seed=30), alpha=8)
    eng.add_request("b", _PROMPTS["base"], max_new_tokens=8)
    eng.add_request("l", _PROMPTS["a1"], max_new_tokens=8, adapter="t")
    _drain(eng)
    assert eng.result("b") == plain.result("b")


def test_hot_swap_zero_recompiles_and_subtree_invalidation():
    """Swapping an adapter on a live engine: (a) compile_stats shows ZERO
    new XLA compiles for the swap + the swapped tenant's serve, and
    (b) exactly the swapped slot's prefix-cache subtree is invalidated."""
    model = _model()
    sd_a = _adapter_sd(model, key_seed=40)
    sd_b = _adapter_sd(model, key_seed=41)
    sd_w = _adapter_sd(model, key_seed=42)
    sys_prompt = list(range(1, 25))  # 3 full blocks at block_size 8

    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=32,
                           adapters={"rank": 4, "max_adapters": 1},
                           prefix_cache=True)
    eng.register_adapter("a", sd_a, alpha=8)
    eng.add_request("r1", sys_prompt, max_new_tokens=4, adapter="a")
    _drain(eng)
    # second tenant under the same adapter shares the cached prefix
    eng.add_request("r2", sys_prompt, max_new_tokens=4, adapter="a")
    _drain(eng)
    st = profiler.decode_stats()
    assert st["prefix_hits"] >= 1 and st["prefix_hit_tokens"] >= 16
    assert eng.result("r2") == eng.result("r1")

    # one full warm swap cycle first: the swap machinery's scatter shapes
    # AND the eager dispatch cache's hotness ramp (prefill op signatures
    # jit-compile on their 4th call) both settle before the measured
    # window — what must be zero afterwards is ALL of it
    eng.register_adapter("w", sd_w, alpha=8)  # evicts idle 'a': a swap
    eng.add_request("rw", sys_prompt, max_new_tokens=4, adapter="w")
    _drain(eng)

    cached = len(eng._prefix)
    free0 = len(eng._free)
    c0 = profiler.compile_stats()["compiles"]
    eng.register_adapter("b", sd_b, alpha=8)     # swap again: evicts 'w'
    eng.add_request("r3", sys_prompt, max_new_tokens=4, adapter="b")
    _drain(eng)
    # the swap + the swapped tenant's full serve: ZERO new XLA compiles
    assert profiler.compile_stats()["compiles"] - c0 == 0
    assert eng.result("r3")  # the swapped tenant actually served

    # exactly the swapped slot's subtree (3 full prompt blocks) was
    # dropped at swap time and its reclaimable pages freed; r3 re-cached
    # 3 blocks under the NEW epoch afterwards, so the totals balance
    assert len(eng._prefix) == cached  # -3 dropped, +3 re-cached by r3
    assert len(eng._free) >= free0 - 3
    # the new tenant got a MISS (no cross-adapter/cross-epoch match) ...
    st = profiler.decode_stats()
    assert st["prefix_misses"] >= 2
    # ... and the new epoch's subtree serves hits again
    eng.add_request("r4", sys_prompt, max_new_tokens=4, adapter="b")
    _drain(eng)
    assert eng.result("r4") == eng.result("r3")
    assert profiler.decode_stats()["prefix_hits"] > st["prefix_hits"]


def test_adapter_prefix_namespaces_never_cross_match():
    """Same prompt under adapter A, adapter B, and the base slot: three
    distinct namespaces — each first admission misses, each second one
    hits its own namespace only."""
    from paddle_tpu.serving import decode_stats, reset_decode_stats

    model = _model()
    reset_decode_stats()
    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=64,
                           adapters={"rank": 4, "max_adapters": 2},
                           prefix_cache=True)
    eng.register_adapter("A", _adapter_sd(model, key_seed=50), alpha=8)
    eng.register_adapter("B", _adapter_sd(model, key_seed=51), alpha=8)
    prompt = list(range(1, 25))
    for i, ad in enumerate([None, "A", "B"]):
        eng.add_request(f"m{i}", prompt, max_new_tokens=3, adapter=ad)
        _drain(eng)
    assert decode_stats()["prefix_hits"] == 0
    assert decode_stats()["prefix_misses"] == 3
    for i, ad in enumerate([None, "A", "B"]):
        eng.add_request(f"h{i}", prompt, max_new_tokens=3, adapter=ad)
        _drain(eng)
        assert eng.result(f"h{i}") == eng.result(f"m{i}")
    assert decode_stats()["prefix_hits"] == 3


def test_slot_exhaustion_queues_and_matches_immediate_bit_for_bit():
    """An adapter request that cannot get a pack slot RIGHT NOW (every
    slot pinned by in-flight requests) queues — same FIFO retry contract
    as pool exhaustion — and its retried stream (seeded sampling) matches
    an immediate admission bit-for-bit."""
    model = _model()
    sd_a = _adapter_sd(model, key_seed=60)
    sd_b = _adapter_sd(model, key_seed=61)
    prompt = _PROMPTS["a0"]

    def run(max_adapters):
        eng = GenerationEngine(model, max_batch=2, block_size=8,
                               num_blocks=32,
                               adapters={"rank": 4,
                                         "max_adapters": max_adapters})
        eng.register_adapter("a", sd_a, alpha=8)
        first_long = eng.add_request("long", prompt, max_new_tokens=10,
                                     adapter="a", temperature=0.5, seed=11)
        slot_b = eng.register_adapter("b", sd_b, alpha=8)
        first_x = eng.add_request("x", prompt, max_new_tokens=6, adapter="b",
                                  temperature=0.8, seed=3)
        assert first_long is not None
        streams = _drain(eng)
        return eng, slot_b, first_x, streams

    ref, slot_imm, first_imm, _ = run(max_adapters=2)
    assert slot_imm is not None and first_imm is not None

    eng, slot_q, first_q, streams = run(max_adapters=1)
    # register while the only slot is in flight: registered, NOT raised,
    # install deferred; the request queues (add_request -> None)
    assert slot_q is None and first_q is None
    assert eng.result("x") == ref.result("x")
    assert eng.result("long") == ref.result("long")
    # step() surfaced the queued request's prefill first token: the full
    # per-step stream equals the result list (typing contract)
    assert streams["x"] == eng.result("x")


def test_evict_adapter_contract():
    model = _model()
    sd = _adapter_sd(model, key_seed=70)
    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=32,
                           adapters={"rank": 4, "max_adapters": 2})
    eng.register_adapter("t", sd, alpha=8)
    assert eng.adapter_slots() == {"t": 1}
    eng.add_request("r", _PROMPTS["a0"], max_new_tokens=8, adapter="t")
    with pytest.raises(RuntimeError, match="in-flight"):
        eng.evict_adapter("t")  # active request pins the slot
    _drain(eng)
    eng.evict_adapter("t")
    assert eng.adapter_slots() == {}
    with pytest.raises(KeyError):
        eng.evict_adapter("t")  # no longer registered
    with pytest.raises(KeyError, match="not registered"):
        eng.add_request("r2", _PROMPTS["a0"], max_new_tokens=4, adapter="t")


def test_adapterless_engine_and_bad_combos_are_loud():
    model = _model()
    eng = GenerationEngine(model, max_batch=1, block_size=8, num_blocks=16)
    with pytest.raises(RuntimeError, match="without adapters="):
        eng.register_adapter("t", {})
    with pytest.raises(RuntimeError, match="without adapters="):
        eng.add_request("r", [1, 2], max_new_tokens=2, adapter="t")
    with pytest.raises(TypeError, match="adapters"):
        GenerationEngine(model, max_batch=1, block_size=8, num_blocks=16,
                         adapters="rank4")


def test_speculative_adapter_engine_matches_plain_adapter_engine():
    """adapters= now composes with draft_model= (the PR-10 ValueError is
    gone): the draft proposes with the BASE model, the target verifies
    through each row's adapter, and greedy acceptance emits EXACTLY the
    plain adapter engine's streams — mixed tenants plus a base row."""
    model = _model()
    sds = {f"t{i}": _adapter_sd(model, key_seed=10 + i) for i in range(2)}
    reqs = {"a0": ("t0", _PROMPTS["a0"]), "a1": ("t1", _PROMPTS["a1"]),
            "base": (None, _PROMPTS["base"])}

    def run(draft):
        eng = GenerationEngine(model, max_batch=3, block_size=8,
                               num_blocks=32, draft_model=draft,
                               num_speculative_tokens=3,
                               adapters={"rank": 4, "max_adapters": 2})
        _register_all(eng, sds)
        for rid, (ad, prompt) in reqs.items():
            eng.add_request(rid, prompt, max_new_tokens=6, adapter=ad)
        _drain(eng)
        return {rid: eng.result(rid) for rid in reqs}

    ref = run(None)
    assert len({tuple(v) for v in ref.values()}) == 3  # tenants differ
    got = run(_model(seed=5))
    assert got == ref


def test_lora_stats_and_summary_footer(capsys):
    from paddle_tpu.serving import reset_lora_stats

    model = _model()
    reset_lora_stats()
    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=32,
                           adapters={"rank": 4, "max_adapters": 2})
    eng.register_adapter("t", _adapter_sd(model, key_seed=80), alpha=8)
    eng.add_request("r", _PROMPTS["a0"], max_new_tokens=4, adapter="t")
    _drain(eng)
    st = profiler.lora_stats()
    assert st["slots_total"] == 2
    assert st["slots_resident"] == 1
    assert st["swaps"] == 1
    assert st["gather_dispatches"] >= 1
    assert st["cache_epochs"] == 1
    prof = profiler.Profiler(timer_only=True)
    with prof:
        pass
    out = prof.summary()
    assert "LoRA serving:" in out
    assert "slots=1/2" in out


def test_reregister_resident_adapter_updates_in_place():
    """Re-registering a RESIDENT name must serve the NEW weights (and
    invalidate the slot's cached prefixes) — not silently keep v1; with
    in-flight requests it refuses (mid-stream weight changes are never
    right).  Regression: _try_install used to short-circuit on the
    resident slot and return it without re-scattering."""
    model = _model()
    sd_v1 = _adapter_sd(model, key_seed=90)
    sd_v2 = _adapter_sd(model, key_seed=91)
    prompt = _PROMPTS["a0"]

    # oracle: v2 served on a fresh engine
    ref = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=32,
                           adapters={"rank": 4, "max_adapters": 2})
    ref.register_adapter("t", sd_v2, alpha=8)
    ref.add_request("x", prompt, max_new_tokens=6, adapter="t")
    _drain(ref)

    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=32,
                           adapters={"rank": 4, "max_adapters": 2},
                           prefix_cache=True)
    slot1 = eng.register_adapter("t", sd_v1, alpha=8)
    eng.add_request("r1", prompt, max_new_tokens=10, adapter="t")
    with pytest.raises(RuntimeError, match="in-flight"):
        eng.register_adapter("t", sd_v2, alpha=8)  # active request
    _drain(eng)
    epoch0 = eng._slot_epochs[slot1]
    assert eng.register_adapter("t", sd_v2, alpha=8) == slot1
    assert eng._slot_epochs[slot1] == epoch0 + 1  # stale prefixes die
    eng.add_request("r2", prompt, max_new_tokens=6, adapter="t")
    _drain(eng)
    assert eng.result("r2") == ref.result("x")  # v2, not stale v1
    assert eng.result("r2") != eng.result("r1")[:6]


def test_reset_lora_stats_preserves_gauges():
    """slots_resident/slots_total describe LIVE engine state; a counter
    reset must not zero them (the summary footer would vanish or render
    slots=1/0 after the next swap)."""
    from paddle_tpu.serving import reset_lora_stats

    model = _model()
    eng = GenerationEngine(model, max_batch=1, block_size=8, num_blocks=16,
                           adapters={"rank": 4, "max_adapters": 3})
    reset_lora_stats()  # drop counters accumulated by earlier tests
    eng.register_adapter("t", _adapter_sd(model, key_seed=95), alpha=8)
    st = profiler.lora_stats(reset=True)
    assert st["swaps"] == 1
    after = profiler.lora_stats()
    assert after["swaps"] == 0  # counter cleared
    assert after["slots_resident"] == 1  # gauges survive
    assert after["slots_total"] == 3
