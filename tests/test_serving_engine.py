"""Continuous-batching generation engine (paddle_tpu/serving).

Reference lineage: block_multi_head_attention_kernel.cu + the
continuous-batching servers over it — requests share one KV block pool via
block tables, joining/leaving the decode batch between steps.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import GenerationEngine


def _model():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(41)
    cfg = llama_tiny(vocab_size=128, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=64,
                     dtype="float32")
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _ref_generate(model, prompt, n):
    out = model.generate(paddle.to_tensor(np.asarray(prompt, np.int32)[None]),
                         max_new_tokens=n, cache="paged", block_size=8)
    return np.asarray(out._value).reshape(-1).tolist()


def test_single_request_matches_generate():
    model = _model()
    prompt = [5, 9, 17, 33, 2]
    ref = _ref_generate(model, prompt, 8)
    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=16)
    eng.add_request("r", prompt, max_new_tokens=8)
    while eng.has_work():
        eng.step()
    assert eng.result("r") == ref


def test_continuous_batching_requests_join_mid_flight():
    """Two requests with different prompt lengths; the second is admitted
    after the first has already decoded two tokens — both must match their
    standalone generations exactly."""
    model = _model()
    p1, p2 = [5, 9, 17, 33, 2], [7, 11, 3]
    ref1 = _ref_generate(model, p1, 8)
    ref2 = _ref_generate(model, p2, 6)

    # decode_chunk=2: at the flag default (8) request 'a' would finish
    # inside the first macro-step and 'b' would decode alone — the
    # co-resident mid-flight join this test exists for needs short chunks
    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2)
    eng.add_request("a", p1, max_new_tokens=8)
    eng.step()
    eng.step()
    eng.add_request("b", p2, max_new_tokens=6)  # joins mid-flight
    while eng.has_work():
        eng.step()
    assert eng.result("a") == ref1
    assert eng.result("b") == ref2


def test_block_recycling_and_slot_reuse():
    """A completed request's pool pages return to the free list and a new
    request decodes correctly on the recycled pages."""
    model = _model()
    eng = GenerationEngine(model, max_batch=1, block_size=8, num_blocks=4)
    free0 = len(eng._free)
    p = [4, 8, 15]
    ref = _ref_generate(model, p, 5)
    eng.add_request("one", p, max_new_tokens=5)
    while eng.has_work():
        eng.step()
    assert eng.result("one") == ref
    assert len(eng._free) == free0  # pages recycled

    ref2 = _ref_generate(model, [16, 23], 5)
    eng.add_request("two", [16, 23], max_new_tokens=5)
    while eng.has_work():
        eng.step()
    assert eng.result("two") == ref2


def test_pool_exhaustion_queues_for_retry():
    """A request the pool can't hold RIGHT NOW is queued (add_request ->
    None) and admitted at a later macro-step boundary once blocks drain —
    with the same tokens an immediately-admitted run produces.  Requests
    that can NEVER fit (wider than the per-seq table) still raise."""
    model = _model()
    p = list(range(1, 9))
    ref = _ref_generate(model, p, 7)
    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=2)
    assert eng.add_request("a", p, max_new_tokens=7) is not None  # 2 blocks
    assert eng.add_request("b", p, max_new_tokens=7) is None      # queued
    assert eng.pending_requests() == ["b"]
    while eng.has_work():
        eng.step()
    assert eng.result("a") == ref
    assert eng.result("b") == ref  # retried request decodes identically

    with pytest.raises(RuntimeError, match="table width"):
        eng.add_request("w", list(range(40)), max_new_tokens=40)


def test_eos_stops_early():
    model = _model()
    # discover the greedy second token, then declare it the EOS id
    probe = GenerationEngine(model, max_batch=1, block_size=8, num_blocks=8)
    probe.add_request("p", [5, 9], max_new_tokens=4)
    while probe.has_work():
        probe.step()
    toks = probe.result("p")
    eos = toks[1]
    eng = GenerationEngine(model, max_batch=1, block_size=8, num_blocks=8,
                           eos_token_id=eos)
    eng.add_request("e", [5, 9], max_new_tokens=10)
    while eng.has_work():
        eng.step()
    got = eng.result("e")
    assert got[-1] == eos and len(got) <= len(toks)


# ------------------------------------------------------------- TP serving
# (VERDICT r3 #6: an mp>1 model must be servable; reference capability is
# analysis_predictor's multi-device serving path)


def test_mp_sharded_engine_matches_single_device():
    """Continuous-batching decode of an mp=2 model on the 8-device CPU mesh
    produces the same tokens as the single-device engine: weights carry
    Megatron placements, the paged-KV pool is sharded over KV heads, ONE
    compiled decode program serves the mesh."""
    import jax
    from jax.sharding import NamedSharding
    from paddle_tpu.distributed.auto_parallel import ProcessMesh

    p1, p2 = [5, 9, 17, 33, 2], [7, 11, 3]
    ref_model = _model()
    ref1 = _ref_generate(ref_model, p1, 8)
    ref2 = _ref_generate(ref_model, p2, 6)

    model = _model()  # same seed -> same weights
    mesh = ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "mp"])
    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=16,
                           mesh=mesh, mp_axis="mp")
    # weights really carry mp placements
    qw = model.model.layers[0].self_attn.q_proj.weight
    assert isinstance(qw._value.sharding, NamedSharding)
    assert "mp" in str(qw._value.sharding.spec)
    # pool pages sharded over the KV-head dim
    assert "mp" in str(eng._kpools[0].sharding.spec)

    eng.add_request("a", p1, max_new_tokens=8)
    eng.step()
    eng.add_request("b", p2, max_new_tokens=6)  # joins mid-flight
    while eng.has_work():
        eng.step()
    assert eng.result("a") == ref1
    assert eng.result("b") == ref2


def test_mp_predictor_runs_partitioned():
    """Predictor with Config.enable_tensor_parallel serves the exported
    program over the mesh with identical outputs."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec
    import paddle_tpu.nn as nn
    import paddle_tpu.static as static
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.static.program import Program, program_guard

    paddle.seed(7)
    fc1, fc2 = nn.Linear(16, 64), nn.Linear(64, 8)
    prog = Program()
    with program_guard(prog):
        xv = prog.add_feed(prog.new_var(
            jax.ShapeDtypeStruct((4, 16), np.float32), "x"))
        import paddle_tpu.nn.functional as Fn
        out = paddle.tanh(fc2(Fn.relu(fc1(xv))))
    import tempfile, os

    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "m")
        exe = static.Executor()
        static.save_inference_model(prefix, [xv], [out], exe, program=prog)

        x = np.random.default_rng(0).normal(size=(4, 16)).astype(np.float32)
        ref = create_predictor(Config(prefix)).run([x])[0]

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("mp",))
        cfg = Config(prefix)
        cfg.enable_tensor_parallel(mesh, input_specs=[PartitionSpec()])
        got = create_predictor(cfg).run([x])[0]
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_save_time_pass_and_precision_control():
    """Export-time optimization surface (reference AnalysisConfig
    pass_builder + precision mode): named passes + precision run over a
    clone before export; the manifest records them; numerics shift by at
    most low-precision rounding; the source program is untouched."""
    import tempfile, os
    import jax
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as Fn
    import paddle_tpu.static as static
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.static.program import Program, program_guard

    paddle.seed(3)
    fc1, fc2 = nn.Linear(16, 32), nn.Linear(32, 4)
    prog = Program()
    with program_guard(prog):
        xv = prog.add_feed(prog.new_var(
            jax.ShapeDtypeStruct((4, 16), np.float32), "x"))
        out = paddle.tanh(fc2(Fn.relu(fc1(xv))))
    types_before = [op.type for op in prog.global_block().ops]

    x = np.random.default_rng(0).normal(size=(4, 16)).astype(np.float32)
    with tempfile.TemporaryDirectory() as td:
        exe = static.Executor()
        p32 = os.path.join(td, "fp32")
        static.save_inference_model(p32, [xv], [out], exe, program=prog)
        ref = create_predictor(Config(p32)).run([x])[0]

        p16 = os.path.join(td, "bf16")
        static.save_inference_model(
            p16, [xv], [out], exe, program=prog,
            passes=["dead_code_elimination"], precision="bfloat16")
        import json as _json

        manifest = _json.load(open(p16 + ".json"))
        assert manifest["passes"] == ["dead_code_elimination",
                                      "auto_parallel_fp16:bfloat16"]
        got = create_predictor(Config(p16)).run([x])[0]
        np.testing.assert_allclose(got, ref, rtol=3e-2, atol=3e-2)
        assert not np.allclose(got, ref, rtol=1e-7, atol=1e-9)  # really bf16

    # the SOURCE program was cloned, not mutated
    assert [op.type for op in prog.global_block().ops] == types_before

    # invalid precision is loud
    import pytest as _pytest

    with _pytest.raises(ValueError, match="precision"):
        static.save_inference_model("/tmp/x", [xv], [out], program=prog,
                                    precision="int3")


def test_per_request_sampling_in_shared_program():
    """Greedy and temperature-sampled requests decode TOGETHER in the one
    compiled program: the greedy slot still matches standalone generate,
    the sampled slot is deterministic per (seed, join order)."""
    model = _model()
    p1, p2 = [5, 9, 17, 33, 2], [7, 11, 3]
    ref1 = _ref_generate(model, p1, 8)

    def run():
        eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=16)
        eng.add_request("greedy", p1, max_new_tokens=8)
        eng.add_request("hot", p2, max_new_tokens=6, temperature=5.0, seed=42)
        while eng.has_work():
            eng.step()
        return eng.result("greedy"), eng.result("hot")

    g1, h1 = run()
    g2, h2 = run()
    assert g1 == ref1 == g2          # greedy unaffected by the hot neighbor
    assert h1 == h2                  # deterministic per seed + join order
    assert all(0 <= t < 128 for t in h1)
    ref2 = _ref_generate(model, p2, 6)
    assert h1 != ref2                # hot sampling really deviates from greedy

    # same seed, two sampled requests: DISTINCT streams (per-request nonce)
    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=16)
    eng.add_request("a", p2, max_new_tokens=6, temperature=5.0, seed=1)
    eng.add_request("b", p2, max_new_tokens=6, temperature=5.0, seed=1)
    while eng.has_work():
        eng.step()
    assert eng.result("a") != eng.result("b")


def test_chunked_prefill_engine_matches_unchunked():
    """prefill_chunk processes long prompts in fixed-size chunks through
    the shared cached forward; decode output is identical to whole-prompt
    prefill (the bottom-right cross-length attention path)."""
    prompt = list(np.random.default_rng(11).integers(0, 128, 23))
    ref_eng = GenerationEngine(_model(), max_batch=2, block_size=8,
                               num_blocks=32)
    ref_eng.add_request("r", prompt, max_new_tokens=7)
    while ref_eng.has_work():
        ref_eng.step()
    chunked = GenerationEngine(_model(), max_batch=2, block_size=8,
                               num_blocks=32, prefill_chunk=5)
    chunked.add_request("r", prompt, max_new_tokens=7)
    while chunked.has_work():
        chunked.step()
    assert chunked.result("r") == ref_eng.result("r")


def test_speculative_engine_matches_plain_engine():
    """Continuous-batching speculative decoding: per-slot greedy
    acceptance over the shared paged pool produces EXACTLY the plain
    engine's tokens — including a request that joins mid-flight."""
    p1, p2 = [5, 9, 17, 33, 2], [7, 11, 3]
    ref = GenerationEngine(_model(), max_batch=2, block_size=8, num_blocks=32)
    ref.add_request("a", p1, max_new_tokens=9)
    ref.step()
    ref.add_request("b", p2, max_new_tokens=6)
    while ref.has_work():
        ref.step()

    paddle.seed(77)
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    draft = LlamaForCausalLM(llama_tiny(
        vocab_size=128, hidden_size=16, intermediate_size=32,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=64, dtype="float32"))
    draft.eval()
    eng = GenerationEngine(_model(), max_batch=2, block_size=8,
                           num_blocks=32, draft_model=draft,
                           num_speculative_tokens=3)
    eng.add_request("a", p1, max_new_tokens=9)
    eng.step()
    eng.add_request("b", p2, max_new_tokens=6)
    while eng.has_work():
        eng.step()
    assert eng.result("a") == ref.result("a")
    assert eng.result("b") == ref.result("b")


def test_speculative_engine_self_draft_accepts_everything():
    """Draft == target: all proposals accepted, output identical, and the
    whole request completes in ~N/(K+1) verify steps."""
    prompt = [5, 9, 17, 33, 2]
    ref = GenerationEngine(_model(), max_batch=2, block_size=8, num_blocks=32)
    ref.add_request("r", prompt, max_new_tokens=12)
    while ref.has_work():
        ref.step()
    target = _model()
    eng = GenerationEngine(target, max_batch=2, block_size=8, num_blocks=32,
                           draft_model=target, num_speculative_tokens=3)
    eng.add_request("r", prompt, max_new_tokens=12)
    steps = 0
    while eng.has_work():
        eng.step()
        steps += 1
    assert eng.result("r") == ref.result("r")
    assert steps <= -(-11 // 4) + 1, steps  # 11 post-prefill tokens, K+1=4


def test_speculative_engine_rejects_sampled_slots():
    target = _model()
    eng = GenerationEngine(target, max_batch=2, block_size=8, num_blocks=32,
                           draft_model=target)
    with pytest.raises(ValueError, match="greedy-only"):
        eng.add_request("r", [1, 2, 3], max_new_tokens=4, temperature=0.7)


def test_speculative_engine_zero_slack_blocks_no_corruption():
    """Verify overshoot near max_len must land in OWNED headroom pages,
    never through the table-padding column into trusted K/V: prompt 5 +
    max_new 11 = exactly 2 blocks of 8 with zero slack (the corruption
    geometry), K=3."""
    prompt = [5, 9, 17, 33, 2]
    ref = GenerationEngine(_model(), max_batch=2, block_size=8, num_blocks=32)
    ref.add_request("r", prompt, max_new_tokens=11)
    while ref.has_work():
        ref.step()
    target = _model()
    eng = GenerationEngine(target, max_batch=2, block_size=8, num_blocks=32,
                           draft_model=target, num_speculative_tokens=3)
    eng.add_request("r", prompt, max_new_tokens=11)
    while eng.has_work():
        eng.step()
    assert eng.result("r") == ref.result("r")


def test_spec_stats_observability():
    target = _model()
    eng = GenerationEngine(target, max_batch=2, block_size=8, num_blocks=32,
                           draft_model=target, num_speculative_tokens=3)
    eng.add_request("r", [5, 9, 17], max_new_tokens=9)
    while eng.has_work():
        eng.step()
    st = eng.spec_stats()
    assert st["ticks"] >= 1 and st["emitted"] >= 8
    assert st["accepted"] == st["proposed"]  # self-draft accepts all
    plain = GenerationEngine(_model(), max_batch=1, block_size=8,
                             num_blocks=16)
    assert plain.spec_stats() is None
