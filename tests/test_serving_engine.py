"""Continuous-batching generation engine (paddle_tpu/serving).

Reference lineage: block_multi_head_attention_kernel.cu + the
continuous-batching servers over it — requests share one KV block pool via
block tables, joining/leaving the decode batch between steps.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import GenerationEngine


def _model():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(41)
    cfg = llama_tiny(vocab_size=128, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=64,
                     dtype="float32")
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _ref_generate(model, prompt, n):
    out = model.generate(paddle.to_tensor(np.asarray(prompt, np.int32)[None]),
                         max_new_tokens=n, cache="paged", block_size=8)
    return np.asarray(out._value).reshape(-1).tolist()


def test_single_request_matches_generate():
    model = _model()
    prompt = [5, 9, 17, 33, 2]
    ref = _ref_generate(model, prompt, 8)
    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=16)
    eng.add_request("r", prompt, max_new_tokens=8)
    while eng.has_work():
        eng.step()
    assert eng.result("r") == ref


def test_continuous_batching_requests_join_mid_flight():
    """Two requests with different prompt lengths; the second is admitted
    after the first has already decoded two tokens — both must match their
    standalone generations exactly."""
    model = _model()
    p1, p2 = [5, 9, 17, 33, 2], [7, 11, 3]
    ref1 = _ref_generate(model, p1, 8)
    ref2 = _ref_generate(model, p2, 6)

    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=16)
    eng.add_request("a", p1, max_new_tokens=8)
    eng.step()
    eng.step()
    eng.add_request("b", p2, max_new_tokens=6)  # joins mid-flight
    while eng.has_work():
        eng.step()
    assert eng.result("a") == ref1
    assert eng.result("b") == ref2


def test_block_recycling_and_slot_reuse():
    """A completed request's pool pages return to the free list and a new
    request decodes correctly on the recycled pages."""
    model = _model()
    eng = GenerationEngine(model, max_batch=1, block_size=8, num_blocks=4)
    free0 = len(eng._free)
    p = [4, 8, 15]
    ref = _ref_generate(model, p, 5)
    eng.add_request("one", p, max_new_tokens=5)
    while eng.has_work():
        eng.step()
    assert eng.result("one") == ref
    assert len(eng._free) == free0  # pages recycled

    ref2 = _ref_generate(model, [16, 23], 5)
    eng.add_request("two", [16, 23], max_new_tokens=5)
    while eng.has_work():
        eng.step()
    assert eng.result("two") == ref2


def test_pool_exhaustion_raises():
    model = _model()
    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=2)
    eng.add_request("a", list(range(1, 9)), max_new_tokens=7)  # 2 blocks
    with pytest.raises(RuntimeError, match="pool exhausted|table width"):
        eng.add_request("b", list(range(1, 9)), max_new_tokens=7)


def test_eos_stops_early():
    model = _model()
    # discover the greedy second token, then declare it the EOS id
    probe = GenerationEngine(model, max_batch=1, block_size=8, num_blocks=8)
    probe.add_request("p", [5, 9], max_new_tokens=4)
    while probe.has_work():
        probe.step()
    toks = probe.result("p")
    eos = toks[1]
    eng = GenerationEngine(model, max_batch=1, block_size=8, num_blocks=8,
                           eos_token_id=eos)
    eng.add_request("e", [5, 9], max_new_tokens=10)
    while eng.has_work():
        eng.step()
    got = eng.result("e")
    assert got[-1] == eos and len(got) <= len(toks)
