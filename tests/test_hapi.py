"""hapi Model.fit/evaluate/predict + summary + flops (reference
python/paddle/hapi/model.py:1054)."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy


class _XorDataset(Dataset):
    def __init__(self, n=128):
        rng = np.random.default_rng(0)
        self.x = rng.standard_normal((n, 8)).astype(np.float32)
        w = rng.standard_normal((8, 1)).astype(np.float32)
        self.y = (self.x @ w > 0).astype(np.int64).reshape(-1)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _net():
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))


def test_model_fit_loss_decreases(capsys):
    paddle.seed(0)
    model = paddle.Model(_net())
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(), metrics=Accuracy())
    ds = _XorDataset()
    history = model.fit(ds, ds, batch_size=32, epochs=3, verbose=0)
    assert history["loss"][-1] < history["loss"][0]
    logs = model.evaluate(ds, batch_size=32, verbose=0)
    assert logs["eval_acc"] > 0.8


def test_model_predict_and_save_load(tmp_path):
    paddle.seed(1)
    model = paddle.Model(_net())
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    ds = _XorDataset(32)
    outs = model.predict(ds, batch_size=16, stack_outputs=True)
    assert outs[0].shape == (32, 2)

    path = str(tmp_path / "m")
    model.save(path)
    model2 = paddle.Model(_net())
    opt2 = paddle.optimizer.SGD(learning_rate=0.01, parameters=model2.parameters())
    model2.prepare(opt2, nn.CrossEntropyLoss())
    model2.load(path)
    o1 = model.predict(ds, batch_size=16, stack_outputs=True)[0]
    o2 = model2.predict(ds, batch_size=16, stack_outputs=True)[0]
    np.testing.assert_allclose(o1, o2, atol=1e-6)


def test_early_stopping_fires():
    paddle.seed(2)
    model = paddle.Model(_net())
    opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    ds = _XorDataset(32)
    es = paddle.callbacks.EarlyStopping(monitor="eval_loss", patience=1)
    model.fit(ds, ds, batch_size=16, epochs=10, verbose=0, callbacks=[es])
    assert model.stop_training


def test_summary_and_flops(capsys):
    net = _net()
    info = paddle.summary(net, (4, 8))
    out = capsys.readouterr().out
    assert "Total params" in out
    assert info["total_params"] == 8 * 32 + 32 + 32 * 2 + 2

    n = paddle.flops(net, [4, 8])
    # two matmuls dominate: 4*32*8*2 + 4*2*32*2
    assert n >= 4 * 32 * 8 * 2


def test_predict_keeps_ragged_tail():
    paddle.seed(4)
    model = paddle.Model(_net())
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    ds = _XorDataset(33)
    outs = model.predict(ds, batch_size=16, stack_outputs=True)
    assert outs[0].shape == (33, 2)


def test_async_save_and_in_memory_dataset(tmp_path):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.io import DataLoader, InMemoryDataset

    # async checkpoint: snapshot-now, write-later
    state = {"w": paddle.to_tensor(np.arange(6, dtype=np.float32))}
    p = str(tmp_path / "ck.pdparams")
    paddle.save(state, p, async_save=True)
    state["w"]._bind(state["w"]._value * 0)  # mutate AFTER snapshot
    paddle.wait_async_save()
    loaded = paddle.load(p)
    np.testing.assert_array_equal(np.asarray(loaded["w"]._value), np.arange(6, dtype=np.float32))

    # InMemoryDataset feed
    f = tmp_path / "data.txt"
    f.write_text("1 2\n3 4\n5 6\n")
    ds = InMemoryDataset(parse_fn=lambda line: np.asarray([int(v) for v in line.split()], np.int32))
    ds.load_into_memory([str(f)])
    ds.global_shuffle(seed=1)
    assert len(ds) == 3
    rows = [tuple(np.asarray(b)[0].tolist()) for b in DataLoader(ds, batch_size=1)]
    assert sorted(rows) == [(1, 2), (3, 4), (5, 6)]


def test_dataloader_prefetch_to_device():
    import numpy as np

    import jax
    from paddle_tpu.io import DataLoader, TensorDataset
    import paddle_tpu as paddle

    xs = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(12, 2))
    ys = paddle.to_tensor(np.arange(12, dtype=np.int32))
    dl = DataLoader(TensorDataset([xs, ys]), batch_size=4, prefetch_to_device=2)
    seen = []
    for xb, yb in dl:
        assert isinstance(xb._value, jax.Array)  # already device-resident
        seen.append(np.asarray(yb._value))
    np.testing.assert_array_equal(np.concatenate(seen), np.arange(12))


def test_reduce_lr_on_plateau_callback(tmp_path):
    """LR drops by `factor` after `patience` evals without improvement
    (reference hapi/callbacks.py ReduceLROnPlateau)."""
    from paddle_tpu.hapi.callbacks import ReduceLROnPlateau

    paddle.seed(2)
    model = paddle.Model(_net())
    opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1, verbose=0)
    cb.set_model(model)
    # Model.evaluate keys its logs eval_loss/eval_<metric>: monitor='loss'
    # must match them (the silent-no-op class of bug)
    cb.on_eval_end({"eval_loss": 1.0})   # best
    cb.on_eval_end({"eval_loss": 1.0})   # wait=1 >= patience -> reduce
    assert abs(opt.get_lr() - 0.25) < 1e-6
    cb.on_eval_end({"loss": 0.5})   # improvement: no change
    assert abs(opt.get_lr() - 0.25) < 1e-6
    # min_lr floor respected
    cb2 = ReduceLROnPlateau(monitor="loss", factor=0.1, patience=0,
                            min_lr=0.2, verbose=0)
    cb2.set_model(model)
    cb2.on_eval_end({"loss": 3.0})
    cb2.on_eval_end({"loss": 3.0})
    assert abs(opt.get_lr() - 0.2) < 1e-6


def test_visualdl_callback_writes_scalars(tmp_path, monkeypatch):
    """VisualDL callback logs train/eval scalars through Model.fit; the
    JSONL fallback (forced here so the test is env-independent) carries
    the same tags, with the eval_ key prefix folded into the tag."""
    import json as _json
    import sys as _sys

    from paddle_tpu.hapi.callbacks import VisualDL

    monkeypatch.setitem(_sys.modules, "visualdl", None)  # force fallback

    paddle.seed(3)
    model = paddle.Model(_net())
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    ds = _XorDataset()
    log_dir = tmp_path / "vdl"
    model.fit(ds, ds, batch_size=32, epochs=2, verbose=0,
              callbacks=[VisualDL(log_dir=str(log_dir))])
    path = log_dir / "scalars.jsonl"
    assert path.exists()
    rows = [_json.loads(l) for l in path.read_text().splitlines()]
    tags = {r["tag"] for r in rows}
    assert any(t.startswith("train/loss") for t in tags), tags
    assert any(t.startswith("eval/") for t in tags), tags
    assert not any(t.startswith("eval/eval_") for t in tags), tags
    steps = [r["step"] for r in rows if r["tag"].startswith("train/loss")]
    assert steps == sorted(steps) and len(steps) >= 2


def test_model_save_inference_export(tmp_path):
    """Model.save(training=False) = deployable inference artifact served by
    the Predictor (reference hapi Model.save contract)."""
    import os

    from paddle_tpu import inference, static

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 2))
    m = paddle.Model(net, inputs=[static.InputSpec([4, 8], "float32", "x")])
    path = str(tmp_path / "deploy")
    m.save(path, training=False)
    assert os.path.exists(path + ".pdmodel")
    xv = np.random.default_rng(0).standard_normal((4, 8)).astype(np.float32)
    net.eval()
    with paddle.no_grad():
        ref = np.asarray(net(paddle.to_tensor(xv))._value)
    (got,) = inference.Predictor(path).run([xv])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    # training=True stays the checkpoint path
    m.save(str(tmp_path / "ckpt"), training=True)
    assert os.path.exists(str(tmp_path / "ckpt") + ".pdparams")
