"""Engine-snapshot topology migration (serving/snapshot.py): a snapshot
taken on ONE topology restores onto a DIFFERENT one through the
reshard-on-load path — single-device ↔ TP mesh in both directions, bf16
and int8 pools, with the mesh lint validating placements at restore-time
construction.  Streams continue bit-identically vs an uninterrupted
single-device engine (the PR-11 sharded-parity contract extends across
the snapshot boundary).

This module dispatches GSPMD-partitioned decode programs over the
in-process multi-device communicator — the known SIGSEGV class — so it
rides a DEDICATED run_tier1 isolated worker (ISOLATED_DEFAULT), never a
round-robin shard."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel import ProcessMesh
from paddle_tpu.serving import GenerationEngine, restore_engine

_KW = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=4, max_position_embeddings=64,
           dtype="float32")

P1, P2 = [5, 9, 17, 33, 2], [7, 11, 3]


def _model(seed=41):
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(seed)
    m = LlamaForCausalLM(llama_tiny(**_KW))
    m.eval()
    return m


def _drain(eng):
    while eng.has_work():
        eng.step()


def _build(model, mesh=None, **kw):
    eng = GenerationEngine(model, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2, mesh=mesh, **kw)
    eng.add_request("g", P1, max_new_tokens=8)
    eng.add_request("s", P2, max_new_tokens=6, temperature=5.0, seed=3)
    return eng


def _reference(**kw):
    ref = _build(_model(), **kw)
    _drain(ref)
    return {r: ref.result(r) for r in ("g", "s")}


def test_single_to_mesh_restore_bit_identical(tmp_path):
    """A single-device snapshot restores onto an mp=2 mesh: pool pages
    commit to the KV-head sharding, weights get Megatron placements, the
    mesh lint runs at restore-time construction, and the continued
    greedy + sampled streams equal the uninterrupted single-device
    run."""
    from jax.sharding import NamedSharding

    ref = _reference()
    eng = _build(_model())
    eng.step()
    eng.snapshot(str(tmp_path))

    mesh = ProcessMesh(np.arange(2), ["mp"])
    m2 = _model()  # fresh unsharded weights, same seed
    paddle.set_flags({"FLAGS_verify_sharding": True})
    try:
        eng2 = restore_engine(m2, str(tmp_path), mesh=mesh)
    finally:
        paddle.set_flags({"FLAGS_verify_sharding": False})
    assert isinstance(eng2._kpools[0].sharding, NamedSharding)
    assert "mp" in str(eng2._kpools[0].sharding.spec)
    qw = m2.model.layers[0].self_attn.q_proj.weight
    assert "mp" in str(qw._value.sharding.spec)
    _drain(eng2)
    assert {r: eng2.result(r) for r in ("g", "s")} == ref


def test_mesh_to_single_restore_bit_identical(tmp_path):
    """The elastic scale-DOWN direction: an mp=2 engine's snapshot — its
    pool metadata holds per-shard records with global offsets — restores
    onto one device via drain(), the migration primitive, and finishes
    identically."""
    ref = _reference()
    eng = _build(_model(), mesh=ProcessMesh(np.arange(2), ["mp"]))
    eng.step()
    step = eng.drain(str(tmp_path))
    with pytest.raises(RuntimeError, match="draining"):
        eng.add_request("late", P2, max_new_tokens=3)

    eng2 = restore_engine(_model(), str(tmp_path), step=step)
    assert eng2._kpools[0].sharding is None or len(
        eng2._kpools[0].sharding.device_set) == 1
    _drain(eng2)
    assert {r: eng2.result(r) for r in ("g", "s")} == ref


def test_mesh_to_wider_mesh_int8_restore(tmp_path):
    """Reshard BETWEEN meshes with quantized pools: an mp=2 int8 engine's
    snapshot restores onto an mp=4 mesh — payload and per-block-per-head
    scales re-place leaf-wise — and the streams still match the
    uninterrupted single-device int8 engine."""
    ref = _reference(kv_cache_dtype="int8")
    eng = _build(_model(), mesh=ProcessMesh(np.arange(2), ["mp"]),
                 kv_cache_dtype="int8")
    eng.step()
    eng.snapshot(str(tmp_path))

    mesh4 = ProcessMesh(np.arange(4), ["mp"])
    eng2 = restore_engine(_model(), str(tmp_path), mesh=mesh4)
    assert eng2._kv_dtype == "int8"
    assert "mp" in str(eng2._kpools[0].data.sharding.spec)
    _drain(eng2)
    assert {r: eng2.result(r) for r in ("g", "s")} == ref
