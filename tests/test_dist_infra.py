"""Tests for launcher, elastic manager, rpc, auto_tuner (reference models:
test/legacy_test/test_run.py for launch, test/collective/fleet elastic
tests, test/rpc/, auto_tuner unit tests)."""

import os
import subprocess
import sys
import tempfile
import textwrap
import time

import numpy as np
import pytest

from paddle_tpu import _native

pytestmark = pytest.mark.skipif(not _native.AVAILABLE, reason="native lib unavailable")


class TestLauncher:
    def _run_launch(self, extra_args, script_body, nproc=2):
        with tempfile.TemporaryDirectory() as d:
            script = os.path.join(d, "train.py")
            with open(script, "w") as f:
                f.write(textwrap.dedent(script_body))
            log_dir = os.path.join(d, "logs")
            cmd = [
                sys.executable, "-m", "paddle_tpu.distributed.launch",
                f"--nproc_per_node={nproc}", f"--log_dir={log_dir}",
                *extra_args, script,
            ]
            env = dict(os.environ, PYTHONPATH="/root/repo", JAX_PLATFORMS="cpu")
            proc = subprocess.run(cmd, capture_output=True, timeout=120, env=env, cwd=d)
            logs = {}
            if os.path.isdir(log_dir):
                for fn in os.listdir(log_dir):
                    with open(os.path.join(log_dir, fn)) as f:
                        logs[fn] = f.read()
            return proc, logs

    def test_spawns_workers_with_env(self):
        proc, logs = self._run_launch([], """
            import os
            print("rank", os.environ["PADDLE_TRAINER_ID"],
                  "of", os.environ["PADDLE_TRAINERS_NUM"],
                  "local", os.environ["PADDLE_LOCAL_RANK"], flush=True)
        """)
        assert proc.returncode == 0, proc.stderr.decode()
        assert "rank 0 of 2" in logs["workerlog.0"]
        assert "rank 1 of 2" in logs["workerlog.1"]

    def test_worker_failure_kills_rest_and_propagates(self):
        proc, logs = self._run_launch([], """
            import os, sys, time
            if os.environ["PADDLE_TRAINER_ID"] == "1":
                sys.exit(3)
            time.sleep(60)
        """)
        assert proc.returncode == 3

    def test_elastic_restart(self):
        # first attempt fails, restart succeeds (state via a marker file)
        proc, logs = self._run_launch(["--elastic_level=1"], """
            import os, sys
            marker = "attempt.marker"
            if os.environ["PADDLE_TRAINER_ID"] == "0":
                if not os.path.exists(marker):
                    open(marker, "w").write("x")
                    sys.exit(1)
            print("second attempt ok", flush=True)
        """, nproc=1)
        assert proc.returncode == 0, proc.stderr.decode()
        assert "second attempt ok" in logs["workerlog.0"]


class TestElasticManager:
    def test_membership_and_transitions(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus

        port = _native.TCPStoreServer(0)
        endpoint = f"127.0.0.1:{port.port}"
        try:
            m1 = ElasticManager(endpoint, "node-a", "1:3", heartbeat_interval=0.1, timeout=1.0)
            m1.start()
            m2 = ElasticManager(endpoint, "node-b", "1:3", heartbeat_interval=0.1, timeout=1.0)
            m2.start()
            time.sleep(0.5)
            assert set(m1.world()) == {"node-a", "node-b"}
            trans = m1.pop_transitions()
            assert ("JOIN", "node-b") in trans
            # node-b dies
            m2.stop()
            time.sleep(1.5)
            assert m1.world() == ["node-a"]
            assert ("GONE", "node-b") in m1.pop_transitions()
            m1.stop()
        finally:
            port.stop()

    def test_np_range_policy(self):
        from paddle_tpu.distributed.fleet.elastic import _parse_np

        assert _parse_np("2:4") == (2, 4)
        assert _parse_np(3) == (3, 3)
        assert _parse_np("5") == (5, 5)


def _rpc_double(x):
    return x * 2


def _rpc_raise():
    raise ValueError("boom from remote")


class TestRPC:
    def test_rpc_sync_async_single_worker(self):
        from paddle_tpu.distributed import rpc

        os.environ["PADDLE_MASTER_ENDPOINT"] = "127.0.0.1:0"
        # pick a free port by starting our own store
        srv = _native.TCPStoreServer(0)
        try:
            rpc.init_rpc("worker0", rank=0, world_size=1,
                         master_endpoint=f"127.0.0.1:{srv.port}")
            info = rpc.get_worker_info("worker0")
            assert info.rank == 0
            assert rpc.rpc_sync("worker0", _rpc_double, args=(21,)) == 42
            fut = rpc.rpc_async("worker0", _rpc_double, args=(5,))
            assert fut.result(10) == 10
            with pytest.raises(ValueError, match="boom from remote"):
                rpc.rpc_sync("worker0", _rpc_raise)
            assert len(rpc.get_all_worker_infos()) == 1
            rpc.shutdown()
        finally:
            srv.stop()


class TestAutoTuner:
    CFG = {
        "num_devices": 8,
        "hbm_gb": 16,
        "model_cfg": {
            "hidden_size": 1024,
            "num_layers": 12,
            "num_attention_heads": 16,
            "vocab_size": 32000,
            "seq_length": 2048,
            "global_batch_size": 16,
        },
    }

    def test_grid_search_yields_valid_configs(self):
        from paddle_tpu.distributed.auto_tuner import AutoTuner

        tuner = AutoTuner(dict(self.CFG, task_limit=1000))
        seen = []
        while True:
            cfg = tuner.search_once()
            if cfg is None:
                break
            # every yielded config covers the mesh exactly
            prod = (cfg["dp_degree"] * cfg["mp_degree"] * cfg["pp_degree"]
                    * cfg["sharding_degree"])
            assert prod == 8
            assert 16 % cfg["mp_degree"] == 0  # heads divisible
            assert 12 % cfg["pp_degree"] == 0  # layers divisible
            seen.append(cfg)
            tuner.add_cfg(cfg)
        assert len(seen) > 4
        # no duplicates
        keys = [tuple(sorted(c.items())) for c in seen]
        assert len(keys) == len(set(keys))

    def test_memory_prune_rejects_oversized(self):
        from paddle_tpu.distributed.auto_tuner.memory_cost_model import get_metric_memory

        big = {"hidden_size": 8192, "num_layers": 80, "vocab_size": 128000,
               "seq_length": 4096}
        est_single = get_metric_memory(big, {"dp_degree": 1, "mp_degree": 1,
                                             "pp_degree": 1, "sharding_degree": 1,
                                             "micro_batch_size": 1})
        assert est_single > 64 * 1024**3  # 70B-ish model won't fit one chip
        est_sharded = get_metric_memory(big, {"dp_degree": 1, "mp_degree": 8,
                                              "pp_degree": 8, "sharding_degree": 4,
                                              "sharding_stage": 3,
                                              "micro_batch_size": 1,
                                              "use_recompute": True})
        assert est_sharded < est_single / 16

    def test_recorder(self):
        from paddle_tpu.distributed.auto_tuner import HistoryRecorder

        r = HistoryRecorder()
        r.add_cfg(dp_degree=2, throughput=100.0)
        r.add_cfg(dp_degree=4, throughput=250.0)
        r.add_cfg(dp_degree=8, throughput=None, error=True)
        best, err = r.get_best()
        assert not err and best["dp_degree"] == 4
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "h.csv")
            r.store_history(p)
            r2 = HistoryRecorder()
            r2.load_history(p)
            assert len(r2.history) == 3


# --------------------------------------------------------- cost model depth

def test_cost_model_from_bench_ops_table():
    from paddle_tpu.cost_model import OpCostModel

    data = {"device_kind": "TPU v5 lite",
            "ops": {"matmul": {"ms": 1.5}, "softmax": {"ms": 0.2},
                    "broken": {"error": "x"}}}
    m = OpCostModel.from_bench_ops(data)
    assert m.query("matmul") == 1.5e-3
    assert m.query("softmax") == 2e-4
    import pytest as _pytest

    with _pytest.raises(KeyError):
        m.query("broken")  # error entries are not silently zero-cost


def test_cost_model_estimate_step_ranks_configs():
    """The planner's question: which config is cheaper?  estimate_step
    (XLA cost analysis -> roofline) must rank a 4x-FLOPs step above the
    small one without ever executing either."""
    import jax.numpy as jnp

    from paddle_tpu.cost_model import OpCostModel

    m = OpCostModel()

    def small(a, b):
        return (a @ b).sum()

    def big(a, b):
        return ((a @ b) @ b).sum()  # strictly more flops, same operands

    a = jnp.ones((256, 256), jnp.float32)
    b = jnp.ones((256, 256), jnp.float32)
    t_small = m.estimate_step(small, a, b)
    t_big = m.estimate_step(big, a, b)
    assert 0 < t_small < t_big, (t_small, t_big)
    # roofline monotonicity in both axes
    assert m.flops_time(1e12, 0) < m.flops_time(2e12, 0)
    assert m.flops_time(0, 1e9) < m.flops_time(0, 2e9)
