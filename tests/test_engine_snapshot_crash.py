"""Serving-tier crash-consistency matrix (serving/snapshot.py,
docs/CHECKPOINT.md): a subprocess SERVING loop — snapshotting every
macro-step through the shared commit protocol — is hard-killed (SIGKILL
via FLAGS_checkpoint_kill_point) at every injected protocol point, and
the parent asserts the prior snapshot always restores, then proves the
killed-and-resumed engine's greedy AND seeded-sampled streams (including
a mid-flight join and prefix-cache state) match an uninterrupted run
token for token.  The training-side matrix lives in
test_checkpoint_crash.py; this file reuses the same kill points against
the engine-snapshot commit — one protocol, one matrix."""

import json
import os
import signal
import subprocess
import sys

import pytest

from paddle_tpu.distributed.checkpoint.manager import KILL_POINTS

_SERVER = r"""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
# pinned like tests/conftest.py and run_tier1's worker bootstrap
jax.config.update("jax_default_matmul_precision", "highest")

cache = os.environ.get("PADDLE_TPU_TEST_CACHE_DIR", "/tmp/jax_cache")
jax.config.update("jax_compilation_cache_dir", cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (EngineSnapshot, GenerationEngine,
                                restore_engine)

snap_dir, out_path, kill_point, kill_at, mode = sys.argv[1:6]
kill_at = int(kill_at)

paddle.seed(41)
cfg = llama_tiny(vocab_size=128, hidden_size=32, intermediate_size=64,
                 num_hidden_layers=2, num_attention_heads=4,
                 num_key_value_heads=4, max_position_embeddings=64,
                 dtype="float32")
m = LlamaForCausalLM(cfg)
m.eval()

# every macro-step boundary commits a snapshot; the SIGKILL then lands
# inside a deterministic commit (same flag-driven injection the training
# matrix uses)
paddle.set_flags({"FLAGS_engine_snapshot_dir": snap_dir,
                  "FLAGS_engine_snapshot_interval": 1})
store = EngineSnapshot(snap_dir)
max_new = 40 if mode in ("long", "preempt") else 10
if store.latest_step() is not None:
    eng = restore_engine(m, snap_dir)  # auto-resume: newest VALID snapshot
else:
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2, prefix_cache=True)
    eng.add_request("g", [5, 9, 17, 33, 2], max_new_tokens=max_new)
if mode == "preempt":
    eng.install_preemption_handler()

while eng.has_work():
    eng.step()
    print("STEP", eng._macro_steps, flush=True)
    if mode == "preempt" and eng.preemption_saved:
        print("PREEMPTED", store.latest_step(), flush=True)
        break
    # mid-flight join at boundary 1.  A resume FROM boundary 1 re-submits
    # here with the restored nonce counter, so the sampled stream is the
    # one the uninterrupted run drew — the counter itself is state.
    if eng._macro_steps == 1 and eng.result("s") is None:
        eng.add_request("s", [7, 11, 3], max_new_tokens=8,
                        temperature=5.0, seed=3)
    if kill_point and eng._macro_steps == kill_at:
        # armed AFTER this boundary's snapshot: the NEXT boundary's
        # commit hits the named protocol point and SIGKILLs
        paddle.set_flags({"FLAGS_checkpoint_kill_point": kill_point})

with open(out_path, "w") as f:
    json.dump({"g": eng.result("g"), "s": eng.result("s"),
               "latest": store.latest_step()}, f)
print("DONE", store.latest_step())
"""


def _run_server(tmp_path, snap_dir, out, kill_point="", kill_at=0,
                mode="std", popen=False):
    script = tmp_path / "server.py"
    script.write_text(_SERVER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.setdefault("PADDLE_TPU_TEST_CACHE_DIR", "/tmp/jax_cache")
    cmd = [sys.executable, str(script), str(snap_dir), str(out),
           kill_point, str(kill_at), mode]
    if popen:
        return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True, env=env)
    return subprocess.run(cmd, capture_output=True, text=True, timeout=300,
                          env=env)


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """Uninterrupted serving run: the token streams every killed-and-
    resumed variant must reproduce bit-for-bit."""
    td = tmp_path_factory.mktemp("snap_ref")
    out = td / "ref.json"
    r = _run_server(td, td / "snaps", out)
    assert "DONE" in r.stdout, (r.stdout + r.stderr)[-2000:]
    return json.loads(out.read_text())


@pytest.mark.parametrize("kill_point", KILL_POINTS)
def test_serving_kill_matrix_prior_snapshot_restorable(tmp_path, kill_point,
                                                       reference):
    """SIGKILL inside the engine-snapshot commit at each protocol point:
    the newest VALID snapshot is the boundary BEFORE the torn commit
    (or the freshly committed one for after-commit), and the resumed
    serving loop finishes both the greedy and the mid-flight sampled
    stream exactly as the uninterrupted run did."""
    from paddle_tpu.serving import EngineSnapshot

    snaps = tmp_path / "snaps"
    r = _run_server(tmp_path, snaps, tmp_path / "x.json",
                    kill_point=kill_point, kill_at=2)
    assert r.returncode == -signal.SIGKILL, (r.stdout + r.stderr)[-2000:]
    expected = 3 if kill_point == "after-commit" else 2
    assert EngineSnapshot(str(snaps)).latest_step() == expected

    out = tmp_path / "resumed.json"
    r2 = _run_server(tmp_path, snaps, out)
    assert "DONE" in r2.stdout, (r2.stdout + r2.stderr)[-2000:]
    resumed = json.loads(out.read_text())
    assert resumed["g"] == reference["g"]
    assert resumed["s"] == reference["s"]


def test_sigterm_preemption_end_to_end(tmp_path):
    """Production preemption shape: a REAL SIGTERM to a serving process
    flips the flag, the next macro-step boundary commits the final
    snapshot, the process exits cleanly, and the resumed process
    finishes the stream bit-identically vs an uninterrupted long run."""
    ref_out = tmp_path / "ref.json"
    r = _run_server(tmp_path, tmp_path / "snaps_ref", ref_out, mode="long")
    assert "DONE" in r.stdout, (r.stdout + r.stderr)[-2000:]
    ref = json.loads(ref_out.read_text())

    snaps = tmp_path / "snaps"
    proc = _run_server(tmp_path, snaps, tmp_path / "p.json", mode="preempt",
                       popen=True)
    try:
        for line in proc.stdout:
            if line.startswith("STEP"):
                proc.send_signal(signal.SIGTERM)  # handler flips a flag only
                break
        out, _ = proc.communicate(timeout=300)
    finally:
        proc.kill()
    assert "PREEMPTED" in out, out[-2000:]

    res_out = tmp_path / "resumed.json"
    r2 = _run_server(tmp_path, snaps, res_out, mode="long")
    assert "DONE" in r2.stdout, (r2.stdout + r2.stderr)[-2000:]
    assert json.loads(res_out.read_text())["g"] == ref["g"]
