"""nn Layer/functional tests (torch-free numpy references)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_forward_backward():
    paddle.seed(0)
    lin = nn.Linear(8, 4)
    x = paddle.randn([2, 8])
    y = lin(x)
    assert y.shape == [2, 4]
    np.testing.assert_allclose(
        y.numpy(), x.numpy() @ lin.weight.numpy() + lin.bias.numpy(), rtol=1e-5, atol=1e-6
    )
    loss = y.sum()
    loss.backward()
    assert lin.weight.grad.shape == [8, 4]
    assert lin.bias.grad.shape == [4]
    np.testing.assert_allclose(lin.bias.grad.numpy(), [2, 2, 2, 2])


def test_layer_registry_and_state_dict():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)
            self.act = nn.ReLU()

        def forward(self, x):
            return self.fc2(self.act(self.fc1(x)))

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert set(names) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
    sd = net.state_dict()
    assert set(sd.keys()) == set(names)
    # round trip
    net2 = Net()
    net2.set_state_dict({k: v for k, v in sd.items()})
    for (n1, p1), (n2, p2) in zip(net.named_parameters(), net2.named_parameters()):
        np.testing.assert_array_equal(p1.numpy(), p2.numpy())
    out = net(paddle.randn([3, 4]))
    assert out.shape == [3, 2]


def test_conv2d_matches_manual():
    paddle.seed(1)
    conv = nn.Conv2D(2, 3, 3, padding=1)
    x = paddle.randn([1, 2, 5, 5])
    y = conv(x)
    assert y.shape == [1, 3, 5, 5]
    # check one output position by manual correlation
    xn = np.pad(x.numpy(), [(0, 0), (0, 0), (1, 1), (1, 1)])
    w = conv.weight.numpy()
    b = conv.bias.numpy()
    manual = np.sum(xn[0, :, 1:4, 1:4] * w[1]) + b[1]
    np.testing.assert_allclose(y.numpy()[0, 1, 1, 1], manual, rtol=1e-4)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(4)
    x = paddle.randn([8, 4, 3, 3]) * 3.0 + 1.0
    bn.train()
    y = bn(x)
    # normalized output: near zero mean, unit var per channel
    yn = y.numpy()
    assert abs(yn.mean()) < 0.1
    assert abs(yn.std() - 1.0) < 0.1
    assert abs(float(bn._mean.numpy().mean()) - 0.1) < 0.5  # momentum update moved stats
    bn.eval()
    y2 = bn(x)
    assert y2.shape == y.shape


def test_layernorm_and_rmsnorm():
    ln = nn.LayerNorm(16)
    x = paddle.randn([4, 16]) * 5 + 2
    y = ln(x).numpy()
    np.testing.assert_allclose(y.mean(axis=-1), 0, atol=1e-5)
    np.testing.assert_allclose(y.std(axis=-1), 1, atol=1e-2)
    rn = nn.RMSNorm(16)
    y2 = rn(x).numpy()
    rms = np.sqrt((y2**2).mean(axis=-1))
    np.testing.assert_allclose(rms, 1.0, atol=1e-2)


def test_dropout_modes():
    x = paddle.ones([1000])
    drop = nn.Dropout(0.5)
    drop.train()
    y = drop(x).numpy()
    assert 0.3 < (y == 0).mean() < 0.7
    # upscale keeps expectation
    assert abs(y.mean() - 1.0) < 0.2
    drop.eval()
    np.testing.assert_array_equal(drop(x).numpy(), x.numpy())


def test_activations():
    x = paddle.to_tensor([-2.0, -0.5, 0.0, 0.5, 2.0])
    np.testing.assert_allclose(F.relu(x).numpy(), [0, 0, 0, 0.5, 2])
    np.testing.assert_allclose(F.sigmoid(x).numpy(), 1 / (1 + np.exp(-x.numpy())), rtol=1e-5)
    np.testing.assert_allclose(F.leaky_relu(x, 0.1).numpy(), [-0.2, -0.05, 0, 0.5, 2], rtol=1e-6)
    sm = F.softmax(x).numpy()
    np.testing.assert_allclose(sm.sum(), 1.0, rtol=1e-6)
    g = F.gelu(x).numpy()
    assert g[0] < 0 and g[-1] > 1.9


def test_cross_entropy():
    logits = paddle.to_tensor([[2.0, 1.0, 0.1], [0.5, 2.5, 0.3]])
    labels = paddle.to_tensor([0, 1])
    loss = F.cross_entropy(logits, labels)
    # numpy reference
    ln = logits.numpy()
    expected = -np.log(np.exp(ln[np.arange(2), [0, 1]]) / np.exp(ln).sum(-1)).mean()
    np.testing.assert_allclose(float(loss), expected, rtol=1e-5)
    # gradient flows
    logits.stop_gradient = False
    F.cross_entropy(logits, labels).backward()
    assert logits.grad is not None


def test_losses():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([1.5, 2.0, 2.0])
    np.testing.assert_allclose(float(F.mse_loss(a, b)), ((a.numpy() - b.numpy()) ** 2).mean(), rtol=1e-6)
    np.testing.assert_allclose(float(F.l1_loss(a, b)), np.abs(a.numpy() - b.numpy()).mean(), rtol=1e-6)
    p = paddle.to_tensor([0.8, 0.4])
    y = paddle.to_tensor([1.0, 0.0])
    expected = -(np.log(0.8) + np.log(0.6)) / 2
    np.testing.assert_allclose(float(F.binary_cross_entropy(p, y)), expected, rtol=1e-5)


def test_pooling():
    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    mp = F.max_pool2d(x, 2, 2).numpy()
    np.testing.assert_array_equal(mp[0, 0], [[5, 7], [13, 15]])
    ap = F.avg_pool2d(x, 2, 2).numpy()
    np.testing.assert_allclose(ap[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    gap = F.adaptive_avg_pool2d(x, 1).numpy()
    np.testing.assert_allclose(gap[0, 0, 0, 0], 7.5)


def test_embedding():
    emb = nn.Embedding(10, 4)
    ids = paddle.to_tensor([[1, 2], [3, 4]])
    out = emb(ids)
    assert out.shape == [2, 2, 4]
    np.testing.assert_array_equal(out.numpy()[0, 0], emb.weight.numpy()[1])
    # grad scatters back
    loss = out.sum()
    loss.backward()
    assert emb.weight.grad is not None
    g = emb.weight.grad.numpy()
    assert (g[1] == 1).all() and (g[0] == 0).all()


def test_mha_shapes_and_causal():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 6, 16])
    out = mha(x)
    assert out.shape == [2, 6, 16]


def test_sdpa_matches_reference():
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.attention import sdpa_reference

    paddle.seed(3)
    q = paddle.randn([2, 5, 2, 8])
    k = paddle.randn([2, 5, 2, 8])
    v = paddle.randn([2, 5, 2, 8])
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    ref = sdpa_reference(q._value, k._value, v._value, is_causal=True)
    np.testing.assert_allclose(out.numpy(), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_lstm_gru_shapes():
    lstm = nn.LSTM(8, 16, num_layers=2, direction="bidirect")
    x = paddle.randn([3, 7, 8])
    out, (h, c) = lstm(x)
    assert out.shape == [3, 7, 32]
    assert h.shape == [4, 3, 16] and c.shape == [4, 3, 16]
    gru = nn.GRU(8, 16)
    out, h = gru(x)
    assert out.shape == [3, 7, 16] and h.shape == [1, 3, 16]


def test_rnn_gradients_flow():
    lstm = nn.LSTM(4, 8)
    x = paddle.randn([2, 5, 4])
    out, _ = lstm(x)
    out.sum().backward()
    assert lstm.weight_ih_l0.grad is not None
    assert lstm.weight_hh_l0.grad is not None


def test_sequential_and_layerlist():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    out = net(paddle.randn([3, 4]))
    assert out.shape == [3, 2]
    assert len(net) == 3
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3
    assert len(list(ll.parameters())) == 6


def test_grad_clip_global_norm():
    clip = nn.ClipGradByGlobalNorm(1.0)
    p1 = paddle.Parameter(np.array([3.0, 4.0], np.float32))
    g1 = paddle.to_tensor([3.0, 4.0])
    out = clip([(p1, g1)])
    np.testing.assert_allclose(out[0][1].numpy(), [0.6, 0.8], rtol=1e-5)


def test_initializers():
    from paddle_tpu.nn import initializer as I

    w = paddle.nn.Layer().create_parameter([100, 50], default_initializer=I.XavierUniform())
    limit = np.sqrt(6.0 / 150)
    assert abs(w.numpy()).max() <= limit + 1e-6
    c = paddle.nn.Layer().create_parameter([10], default_initializer=I.Constant(3.0))
    np.testing.assert_array_equal(c.numpy(), np.full(10, 3.0, np.float32))


def test_forward_hooks():
    lin = nn.Linear(4, 4)
    calls = []
    h1 = lin.register_forward_pre_hook(lambda layer, inp: calls.append("pre"))
    h2 = lin.register_forward_post_hook(lambda layer, inp, out: calls.append("post"))
    lin(paddle.randn([1, 4]))
    assert calls == ["pre", "post"]
    h1.remove()
    h2.remove()
    calls.clear()
    lin(paddle.randn([1, 4]))
    assert calls == []


def test_train_eval_propagates():
    net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
    net.eval()
    assert not net[1].training
    net.train()
    assert net[1].training
