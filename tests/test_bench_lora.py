"""Tier-1 smoke of benchmarks/bench_lora.py.

Like test_bench_decode / test_bench_compile: the multi-tenant LoRA bench
must keep emitting the one-line JSON payload the driver parses, its
built-in greedy-parity gate (mixed-adapter batched streams == per-adapter
serial streams, bit for bit) must hold, and the payload must flow through
tools/check_bench_regression.py (the CI regression gate).
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_smoke():
    env = dict(os.environ, PADDLE_TPU_BENCH_SMOKE="1",
               PADDLE_TPU_BENCH_CPU="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "benchmarks", "bench_lora.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=_REPO)
    assert out.returncode == 0, (out.stderr or out.stdout)[-800:]
    line = next(ln for ln in reversed(out.stdout.splitlines())
                if ln.startswith("{"))
    return json.loads(line)


def test_bench_lora_smoke_emits_valid_json_and_parity():
    payload = _run_smoke()
    assert payload["metric"] == "serving_lora_mixed_batch_speedup"
    assert payload["unit"] == "x"
    assert payload["value"] > 0
    assert "vs_baseline" in payload
    # the acceptance direction: mixed-adapter batched streams must equal
    # the per-adapter serial ones bit-for-bit
    assert payload["tokens_match"] is True
    detail = payload["detail"]
    assert detail["adapters"] >= 3
    assert detail["batched_tokens_per_sec"] > 0
    assert detail["serial_tokens_per_sec"] > 0
    # the pack really swapped and really gathered inside the decode step
    assert detail["lora_stats"]["swaps"] >= detail["adapters"]
    assert detail["lora_stats"]["gather_dispatches"] > 0

    # regression-gate wiring: the payload round-trips through
    # tools/check_bench_regression.py (same-value comparison = ok, rc 0)
    sys.path.insert(0, _REPO)
    from tools.check_bench_regression import load_payload, main

    path = os.path.join(_REPO, "_bench_lora_smoke.json")
    try:
        with open(path, "w") as f:
            json.dump(payload, f)
        got, err = load_payload(path)
        assert err is None and got == (payload["metric"], payload["value"])
        assert main([path, path]) == 0
    finally:
        os.remove(path)
