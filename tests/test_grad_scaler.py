"""GradScaler inside a COMPILED TrainStep (round-1 weak #8: the scaler
branch was never compiled by any test).  The scaler state is device tensors
so dynamic loss scaling works identically eagerly and under jit."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.amp import GradScaler
from paddle_tpu.jit import TrainStep


def _model():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))


def test_compiled_scaler_step_trains():
    m = _model()
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
    scaler = GradScaler(init_loss_scaling=2.0**10, incr_every_n_steps=3)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((32, 8)).astype(np.float32))
    y = paddle.to_tensor((np.asarray(x._value) @ rng.standard_normal((8, 1))).astype(np.float32))

    step = TrainStep(m, opt, lambda mm, a, b: ((mm(a) - b) ** 2).mean(), scaler=scaler)
    losses = [float(step(x, y)) for _ in range(8)]
    assert losses[-1] < losses[0] * 0.7, losses
    # dynamic growth: incr_every=3 good steps doubles the scale at least once
    assert scaler.get_loss_scaling() > 2.0**10


def test_compiled_scaler_skips_on_inf():
    m = _model()
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
    scaler = GradScaler(init_loss_scaling=2.0**8, decr_every_n_nan_or_inf=1)
    x = paddle.to_tensor(np.full((4, 8), np.inf, np.float32))
    y = paddle.to_tensor(np.zeros((4, 1), np.float32))
    step = TrainStep(m, opt, lambda mm, a, b: ((mm(a) - b) ** 2).mean(), scaler=scaler)
    before = [np.asarray(p._value).copy() for p in m.parameters()]
    step(x, y)  # inf loss -> inf grads -> skip + scale halves
    after = [np.asarray(p._value) for p in m.parameters()]
    for b, a in zip(before, after):
        np.testing.assert_array_equal(b, a)
    assert scaler.get_loss_scaling() == 2.0**7


def test_eager_scaler_matches_semantics():
    m = _model()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    scaler = GradScaler(init_loss_scaling=4.0, incr_every_n_steps=2)
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    y = paddle.to_tensor(np.zeros((4, 1), np.float32))
    for i in range(2):
        loss = ((m(x) - y) ** 2).mean()
        scaler.scale(loss).backward()
        scaler.step(opt)
        opt.clear_grad()
    assert scaler.get_loss_scaling() == 8.0  # one doubling after 2 good steps


def test_check_nan_inf_fires_inside_jit():
    """FLAGS_check_nan_inf must catch NaN on the COMPILED path (round-1 weak
    #7: the check skipped tracers)."""
    import jax
    import paddle_tpu._core.flags as flags
    from paddle_tpu.jit import to_static

    flags.set_flags({"FLAGS_check_nan_inf": True})
    try:
        @to_static
        def f(a):
            return paddle.log(a)  # log(-1) -> nan

        with pytest.raises(Exception) as ei:
            out = f(paddle.to_tensor(np.array([-1.0], np.float32)))
            jax.block_until_ready(out._value)
        assert "NaN/Inf" in str(ei.value)
    finally:
        flags.set_flags({"FLAGS_check_nan_inf": False})
