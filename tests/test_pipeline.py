"""Pipeline-parallel engine tests on the 8-device CPU mesh.

Reference counterpart: test/collective/fleet pipeline tests
(hybrid_parallel_pp_layer.py etc.), which spawn N GPU procs — here one SPMD
program over 'pp'.
"""

import numpy as np
import pytest
import jax.numpy as jnp
from jax.sharding import PartitionSpec

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import ProcessMesh
from paddle_tpu.distributed.fleet.meta_parallel import PipelineStack


class Block(nn.Layer):
    def __init__(self, h):
        super().__init__()
        self.fc = nn.Linear(h, h)

    def forward(self, x):
        return paddle.tanh(self.fc(x))


def _blocks(n, h, seed=0):
    paddle.seed(seed)
    return [Block(h) for _ in range(n)]


@pytest.mark.slow
def test_pipeline_stack_forward_matches_sequential():
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    blocks = _blocks(8, 16)
    x = paddle.to_tensor(np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32))

    ref = x
    for b in blocks:
        ref = b(ref)

    stack = PipelineStack(_copy_blocks(blocks, 16), mesh, pp_axis="pp")
    out = stack(x)
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref._value), rtol=1e-5, atol=1e-5)


def _copy_blocks(blocks, h):
    out = []
    for b in blocks:
        nb = Block(h)
        nb.set_state_dict({k: v for k, v in b.state_dict().items()})
        out.append(nb)
    return out


@pytest.mark.slow
def test_pipeline_stack_gradients_match():
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    blocks = _blocks(8, 16, seed=1)
    x_np = np.random.default_rng(1).normal(size=(8, 16)).astype(np.float32)

    # sequential reference grads
    ref_blocks = _copy_blocks(blocks, 16)
    x1 = paddle.to_tensor(x_np)
    h = x1
    for b in ref_blocks:
        h = b(h)
    loss_ref = paddle.sum(h * h)
    loss_ref.backward()

    stack = PipelineStack(_copy_blocks(blocks, 16), mesh, pp_axis="pp")
    x2 = paddle.to_tensor(x_np)
    out = stack(x2)
    loss = paddle.sum(out * out)
    loss.backward()

    np.testing.assert_allclose(float(loss._value), float(loss_ref._value), rtol=1e-5)
    # stacked grad [S, Lps, ...] vs per-block grads
    sp = stack.stacked_parameters()
    keys = stack._keys
    for ki, key in enumerate(keys):
        g = np.asarray(sp[ki].grad._value).reshape((8,) + tuple(sp[ki].shape[2:]))
        for li, b in enumerate(ref_blocks):
            bg = np.asarray(b.state_dict()[key].grad._value)
            np.testing.assert_allclose(g[li], bg, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_llama_3d_hybrid_train_step():
    """dp2 x pp2 x mp2 llama training step matches single-device numerics."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny, pipeline_llama, shard_llama
    from paddle_tpu.jit import TrainStep

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, size=(4, 16)).astype(np.int32)
    labels = rng.integers(0, 128, size=(4, 16)).astype(np.int64)

    def loss_fn(m, i, l):
        loss, _ = m(i, labels=l)
        return loss

    def make_model():
        paddle.seed(7)
        cfg = llama_tiny(vocab_size=128, hidden_size=32, intermediate_size=64,
                         num_hidden_layers=4, num_attention_heads=4,
                         num_key_value_heads=4, max_position_embeddings=32,
                         dtype="float32")
        return LlamaForCausalLM(cfg)

    model = make_model()
    step = TrainStep(model, paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters()), loss_fn)
    ref_losses = [float(step(paddle.to_tensor(ids), paddle.to_tensor(labels))._value) for _ in range(3)]

    mesh = ProcessMesh(np.arange(8).reshape(2, 2, 2), ["dp", "pp", "mp"])
    model2 = make_model()
    shard_llama(model2, mesh, mp_axis="mp")
    pipeline_llama(model2, mesh, pp_axis="pp", num_microbatches=2)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model2.parameters())
    dstep = dist.ShardedTrainStep(model2, opt, loss_fn, mesh,
                                  batch_spec=PartitionSpec("dp"), zero_stage=1)
    got = [float(dstep(paddle.to_tensor(ids), paddle.to_tensor(labels))._value) for _ in range(3)]

    np.testing.assert_allclose(got, ref_losses, rtol=2e-3, atol=2e-4)
    assert got[-1] < got[0]


@pytest.mark.parametrize("schedule,M", [("1F1B", 8), ("1F1B", 16), ("FThenB", 8)])
@pytest.mark.slow
def test_pipeline_microbatch_schedules_match_sequential(schedule, M):
    """num_microbatches > stages (steady-state 1F1B, reference
    pipeline_parallel.py:431) and the FThenB schedule produce identical
    numerics; only the autodiff memory profile differs."""
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    blocks = _blocks(8, 16, seed=3)
    x_np = np.random.default_rng(3).normal(size=(M * 2, 16)).astype(np.float32)

    ref_blocks = _copy_blocks(blocks, 16)
    h = paddle.to_tensor(x_np)
    for b in ref_blocks:
        h = b(h)
    loss_ref = paddle.sum(h * h)
    loss_ref.backward()

    stack = PipelineStack(
        _copy_blocks(blocks, 16), mesh, pp_axis="pp", num_microbatches=M, schedule=schedule
    )
    out = stack(paddle.to_tensor(x_np))
    loss = paddle.sum(out * out)
    loss.backward()

    np.testing.assert_allclose(float(loss._value), float(loss_ref._value), rtol=1e-5)
    sp = stack.stacked_parameters()
    for ki, key in enumerate(stack._keys):
        g = np.asarray(sp[ki].grad._value).reshape((8,) + tuple(sp[ki].shape[2:]))
        for li, b in enumerate(ref_blocks):
            bg = np.asarray(b.state_dict()[key].grad._value)
            np.testing.assert_allclose(g[li], bg, rtol=1e-4, atol=1e-5)


def test_pipeline_scan_structure_and_bubble():
    """The engine is ONE lax.scan of T = M + S - 1 ticks (compile time
    independent of M) and bubble_fraction reports (S-1)/(M+S-1)."""
    import jax

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    S, M = 4, 12
    blocks = _blocks(4, 16, seed=4)
    stack = PipelineStack(blocks, mesh, pp_axis="pp", num_microbatches=M)
    assert abs(stack.bubble_fraction() - (S - 1) / (M + S - 1)) < 1e-9

    x = paddle.to_tensor(np.zeros((M, 16), np.float32))
    stack._bcast_template = []
    fn = stack._make_fn(M)
    jaxpr = str(jax.make_jaxpr(fn)(*[p._value for p in stack.stacked_parameters()],
                                   jnp.zeros((M, 1, 16), jnp.float32)))
    assert f"length={M + S - 1}" in jaxpr, "pipeline must scan over M+S-1 ticks"
    # exactly one scan: per-tick work is not unrolled
    assert jaxpr.count("scan[") == 1


@pytest.mark.slow
def test_vpp_interleaved_matches_sequential():
    """VPP (interleaved virtual stages, reference pipeline_parallel.py:890):
    v=2 chunks per device, numerics must match the sequential stack and the
    scan must run M*v + S - 1 ticks."""
    import jax

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    S, v, M = 4, 2, 8
    blocks = _blocks(8, 16, seed=5)
    x_np = np.random.default_rng(5).normal(size=(M, 16)).astype(np.float32)

    ref_blocks = _copy_blocks(blocks, 16)
    h = paddle.to_tensor(x_np)
    for b in ref_blocks:
        h = b(h)
    loss_ref = paddle.sum(h * h)
    loss_ref.backward()

    stack = PipelineStack(
        _copy_blocks(blocks, 16), mesh, pp_axis="pp", num_microbatches=M,
        schedule="VPP", num_virtual_stages=v,
    )
    assert abs(stack.bubble_fraction() - (S - 1) / (M * v + S - 1)) < 1e-9
    out = stack(paddle.to_tensor(x_np))
    loss = paddle.sum(out * out)
    loss.backward()
    np.testing.assert_allclose(float(loss._value), float(loss_ref._value), rtol=1e-5)

    # gradient parity: stacked grads live in VPP block order
    lpc = 8 // (S * v)
    order = [(j * S + d) * lpc + i for d in range(S) for j in range(v) for i in range(lpc)]
    sp = stack.stacked_parameters()
    for ki, key in enumerate(stack._keys):
        g = np.asarray(sp[ki].grad._value).reshape((8,) + tuple(sp[ki].shape[2:]))
        for pos, bi in enumerate(order):
            bg = np.asarray(ref_blocks[bi].state_dict()[key].grad._value)
            np.testing.assert_allclose(g[pos], bg, rtol=1e-4, atol=1e-5)

    # structural: one scan of M*v + S - 1 ticks
    stack._bcast_template = []
    fn = stack._make_fn(M)
    jaxpr = str(jax.make_jaxpr(fn)(*[p._value for p in stack.stacked_parameters()],
                                   jnp.zeros((M, 1, 16), jnp.float32)))
    assert f"length={M * v + S - 1}" in jaxpr


@pytest.mark.slow
def test_vpp_ragged_microbatch_count():
    """M not a multiple of S: trailing microbatches are injected a ring-cycle
    late; the tick count must cover them (round-2 review repro: S=4, v=2,
    M=6 silently returned zeros)."""
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    M = 6
    blocks = _blocks(8, 16, seed=6)
    x_np = np.random.default_rng(6).normal(size=(M, 16)).astype(np.float32)

    ref_blocks = _copy_blocks(blocks, 16)
    h = paddle.to_tensor(x_np)
    for b in ref_blocks:
        h = b(h)

    stack = PipelineStack(
        _copy_blocks(blocks, 16), mesh, pp_axis="pp", num_microbatches=M,
        schedule="VPP", num_virtual_stages=2,
    )
    out = stack(paddle.to_tensor(x_np))
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(h._value), rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_1f1b_memory_profile_below_fthenb():
    """The 1F1B schedule's bounded-activation claim, measured: XLA's memory
    analysis of the compiled backward shows smaller temp usage than FThenB
    at high microbatch count (per-tick remat stores boundary activations
    only; FThenB stores every stage's internals)."""
    import jax

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    H, M = 256, 32
    temps = {}
    for sched in ("FThenB", "1F1B"):
        paddle.seed(0)
        stack = PipelineStack(
            _blocks(4, H, seed=9), mesh, pp_axis="pp",
            num_microbatches=M, schedule=sched,
        )
        stack._bcast_template = []
        fn = stack._make_fn(M)
        params = [p._value for p in stack.stacked_parameters()]
        x = jnp.zeros((M, 4, H), jnp.float32)

        def loss(params_, xv):
            out = fn(*params_, xv)
            return (out * out).sum()

        g = jax.jit(jax.grad(loss))
        temps[sched] = g.lower(params, x).compile().memory_analysis().temp_size_in_bytes
    assert temps["1F1B"] < 0.75 * temps["FThenB"], temps


def test_full_model_pipeline_matches_single_device():
    """Embedding + trunk + norm/head all inside the pipelined region
    (reference SegmentLayers non-uniform cut, pp_layers.py:92): forward
    logits, loss, and the edge-layer gradients match single-device."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny, pipeline_llama

    rng = np.random.default_rng(3)
    ids = rng.integers(0, 96, size=(4, 12)).astype(np.int32)
    labels = rng.integers(0, 96, size=(4, 12)).astype(np.int64)

    def make_model():
        paddle.seed(11)
        cfg = llama_tiny(vocab_size=96, hidden_size=32, intermediate_size=64,
                         num_hidden_layers=4, num_attention_heads=4,
                         num_key_value_heads=4, max_position_embeddings=32,
                         dtype="float32")
        return LlamaForCausalLM(cfg)

    ref = make_model()
    ref_logits = ref(paddle.to_tensor(ids))
    ref_loss, _ = ref(paddle.to_tensor(ids), labels=paddle.to_tensor(labels))
    ref_loss.backward()

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    pm = make_model()
    pipeline_llama(pm, mesh, pp_axis="pp", num_microbatches=2)
    assert getattr(pm.model, "_pp_full", False)
    got_logits = pm(paddle.to_tensor(ids))
    np.testing.assert_allclose(
        np.asarray(got_logits._value), np.asarray(ref_logits._value), rtol=2e-4, atol=2e-4
    )
    loss, _ = pm(paddle.to_tensor(ids), labels=paddle.to_tensor(labels))
    np.testing.assert_allclose(float(loss._value), float(ref_loss._value), rtol=1e-4)
    loss.backward()
    np.testing.assert_allclose(
        np.asarray(pm.model.embed_tokens.weight.grad._value),
        np.asarray(ref.model.embed_tokens.weight.grad._value), rtol=2e-3, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(pm.lm_head.weight.grad._value),
        np.asarray(ref.lm_head.weight.grad._value), rtol=2e-3, atol=1e-5
    )


def test_segment_layers_cuts():
    """Reference SegmentLayers (pp_layers.py:92): uniform and
    parameter-weighted cut points."""
    from paddle_tpu.distributed.fleet.meta_parallel import segment_layers

    assert segment_layers([1] * 8, 4) == [0, 2, 4, 6, 8]
    assert segment_layers([1] * 7, 3) == [0, 3, 5, 7]  # remainder to the front
    # heavy tail: param-weighted shifts cuts right
    w = [1, 1, 1, 1, 10, 10]
    cuts = segment_layers(w, 2, method="param")
    assert cuts[0] == 0 and cuts[-1] == 6
    sums = [sum(w[cuts[i]:cuts[i + 1]]) for i in range(2)]
    assert abs(sums[0] - sums[1]) <= 10  # balanced within one heavy layer
    with pytest.raises(ValueError):
        segment_layers([1, 2], 3)


@pytest.mark.slow  # 50s: the interleaved-VPP variant of the 84s full-model
# pipeline test right above — edge-stage coverage stays fast through that
# test; the VPP schedule itself is also covered by the trunk VPP test
def test_full_model_vpp_matches_single_device():
    """Interleaved VPP with edge stages (embedding + head inside the
    pipelined region): numerics match single-device."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny, pipeline_llama

    rng = np.random.default_rng(9)
    ids = rng.integers(0, 96, size=(4, 12)).astype(np.int32)
    labels = rng.integers(0, 96, size=(4, 12)).astype(np.int64)

    def make_model():
        paddle.seed(13)
        cfg = llama_tiny(vocab_size=96, hidden_size=32, intermediate_size=64,
                         num_hidden_layers=4, num_attention_heads=4,
                         num_key_value_heads=4, max_position_embeddings=32,
                         dtype="float32")
        return LlamaForCausalLM(cfg)

    ref = make_model()
    ref_loss, _ = ref(paddle.to_tensor(ids), labels=paddle.to_tensor(labels))

    mesh = ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "pp"])
    pm = make_model()
    pipeline_llama(pm, mesh, pp_axis="pp", num_microbatches=2,
                   schedule="VPP", num_virtual_stages=2)
    loss, _ = pm(paddle.to_tensor(ids), labels=paddle.to_tensor(labels))
    np.testing.assert_allclose(float(loss._value), float(ref_loss._value), rtol=1e-4)
    loss.backward()
    ref_loss.backward()
    np.testing.assert_allclose(
        np.asarray(pm.lm_head.weight.grad._value),
        np.asarray(ref.lm_head.weight.grad._value), rtol=2e-3, atol=1e-5)


def test_pipeline_gpt_trunk_matches_single_device():
    """GPT trunk pipelining (tied head stays outside): loss matches the
    unpipelined model."""
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny
    from paddle_tpu.models.gpt import pipeline_gpt

    rng = np.random.default_rng(11)
    ids = rng.integers(0, 256, (4, 16)).astype(np.int32)

    def make():
        paddle.seed(23)
        return GPTForCausalLM(gpt_tiny(num_hidden_layers=4, vocab_size=256))

    ref = make()
    ref.eval()
    ref_loss, _ = ref(paddle.to_tensor(ids), labels=paddle.to_tensor(ids))

    mesh = ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "pp"])
    pm = make()
    pm.eval()
    pipeline_gpt(pm, mesh, pp_axis="pp", num_microbatches=2)
    loss, _ = pm(paddle.to_tensor(ids), labels=paddle.to_tensor(ids))
    np.testing.assert_allclose(float(loss._value), float(ref_loss._value), rtol=1e-4)
