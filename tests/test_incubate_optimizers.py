"""Incubate optimizer tier (reference python/paddle/incubate/optimizer/):
LARS, GradientMerge, DistributedFusedLamb."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.incubate.optimizer import LARS, DistributedFusedLamb, GradientMergeOptimizer


def _fit(opt_factory, steps=60):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    opt = opt_factory(m)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((32, 8)).astype(np.float32))
    y = paddle.to_tensor((np.asarray(x._value) @ rng.standard_normal((8, 1))).astype(np.float32))
    losses = []
    for _ in range(steps):
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss._value))
    return losses


def test_lars_trains():
    losses = _fit(lambda m: LARS(learning_rate=1.0, lars_coeff=0.05, parameters=m.parameters()))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_gradient_merge_matches_large_batch():
    # k accumulated micro-steps on the same batch == one step at same grads
    paddle.seed(1)
    m1 = nn.Linear(4, 1)
    m2 = nn.Linear(4, 1)
    m2.set_state_dict({k: v for k, v in m1.state_dict().items()})
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    y = paddle.to_tensor(np.zeros((4, 1), np.float32))

    o1 = paddle.optimizer.SGD(0.1, parameters=m1.parameters())
    for _ in range(2):  # plain: 2 full steps
        l = ((m1(x) - y) ** 2).mean()
        l.backward(); o1.step(); o1.clear_grad()

    o2 = GradientMergeOptimizer(paddle.optimizer.SGD(0.1, parameters=m2.parameters()), k_steps=2, avg=True)
    for _ in range(4):  # merged: 4 micro-steps -> 2 applies (same grads, avg)
        l = ((m2(x) - y) ** 2).mean()
        l.backward(); o2.step(); o2.clear_grad()

    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(np.asarray(p1._value), np.asarray(p2._value), rtol=1e-5, atol=1e-6)


def test_distributed_fused_lamb_trains_and_accumulates():
    losses = _fit(lambda m: DistributedFusedLamb(learning_rate=0.05, parameters=m.parameters(),
                                                 gradient_accumulation_steps=2), steps=40)
    assert losses[-1] < losses[0] * 0.7


def test_gradient_merge_inside_compiled_trainstep():
    """The micro-step cadence is DEVICE state: one compiled TrainStep must
    apply the inner step exactly every k-th call."""
    from paddle_tpu.jit import TrainStep

    paddle.seed(2)
    m = nn.Linear(4, 1)
    ref = nn.Linear(4, 1)
    ref.set_state_dict({k: v for k, v in m.state_dict().items()})
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    y = paddle.to_tensor(np.zeros((4, 1), np.float32))

    opt = GradientMergeOptimizer(paddle.optimizer.SGD(0.1, parameters=m.parameters()), k_steps=2)
    step = TrainStep(m, opt, lambda mm, a, b: ((mm(a) - b) ** 2).mean())
    p0 = np.asarray(m.parameters()[0]._value).copy()
    step(x, y)  # micro 1: accumulate only
    p1 = np.asarray(m.parameters()[0]._value)
    np.testing.assert_array_equal(p0, p1)
    step(x, y)  # micro 2: apply
    p2 = np.asarray(m.parameters()[0]._value)
    assert not np.allclose(p1, p2)

    # numerics: equals one plain step with the same (averaged) grads
    o_ref = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
    l = ((ref(x) - y) ** 2).mean()
    l.backward(); o_ref.step(); o_ref.clear_grad()
    np.testing.assert_allclose(p2, np.asarray(ref.parameters()[0]._value), rtol=1e-5, atol=1e-6)
