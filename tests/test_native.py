"""Tests for the native C++ runtime substrate (paddle_tpu/_native).

Covers the TPU-native equivalents of the reference's C++ runtime pieces:
TCPStore rendezvous (paddle/phi/core/distributed/store/tcp_store.h:121),
shared-memory DataLoader transport (python/paddle/io/dataloader worker
queues), and the host event recorder
(paddle/fluid/platform/profiler/host_event_recorder.h).
"""

import os
import threading

import numpy as np
import pytest

from paddle_tpu import _native

pytestmark = pytest.mark.skipif(not _native.AVAILABLE, reason="native lib unavailable")


class TestTCPStore:
    def test_set_get_add(self):
        srv = _native.TCPStoreServer()
        try:
            cli = _native.TCPStoreClient(port=srv.port)
            cli.set("k1", b"hello")
            assert cli.get("k1") == b"hello"
            assert cli.add("ctr", 3) == 3
            assert cli.add("ctr", 4) == 7
            cli.close()
        finally:
            srv.stop()

    def test_get_blocks_until_set(self):
        srv = _native.TCPStoreServer()
        try:
            cli = _native.TCPStoreClient(port=srv.port)
            result = {}

            def setter():
                c2 = _native.TCPStoreClient(port=srv.port)
                c2.set("late", b"v")
                c2.close()

            t = threading.Timer(0.2, setter)
            t.start()
            result["v"] = cli.get("late", timeout_ms=5000)
            t.join()
            assert result["v"] == b"v"
            cli.close()
        finally:
            srv.stop()

    def test_get_timeout(self):
        srv = _native.TCPStoreServer()
        try:
            cli = _native.TCPStoreClient(port=srv.port)
            with pytest.raises(TimeoutError):
                cli.get("never", timeout_ms=200)
            cli.close()
        finally:
            srv.stop()

    def test_wait_shares_one_deadline_across_keys(self):
        # Regression: wait() used to give EACH key a fresh timeout_ms, so
        # keys trickling in slower than the shared budget but faster than
        # a per-key budget let the total wait reach len(keys) x timeout_ms
        # without ever raising.  One shared deadline must time out here.
        import time

        srv = _native.TCPStoreServer()
        try:
            cli = _native.TCPStoreClient(port=srv.port)

            def setter():
                c2 = _native.TCPStoreClient(port=srv.port)
                for i in range(3):
                    time.sleep(0.4)
                    c2.set(f"w{i}", b"v")
                c2.close()

            t = threading.Thread(target=setter)
            t.start()
            t0 = time.monotonic()
            with pytest.raises(TimeoutError):
                cli.wait(["w0", "w1", "w2"], timeout_ms=700)
            elapsed = time.monotonic() - t0
            t.join()
            # the old per-key loop would have waited ~1.2s and RETURNED;
            # the shared deadline stops near 0.7s
            assert elapsed < 1.15, elapsed
            # and a wait whose keys are all present returns immediately
            cli.wait(["w0", "w1", "w2"], timeout_ms=700)
            cli.close()
        finally:
            srv.stop()

    def test_client_connects_before_server_starts(self):
        # Startup race: under load a worker's client routinely outraces
        # the server's bind — the constructor must retry with backoff
        # until its deadline instead of failing on the first refusal.
        import socket
        import time

        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        holder = {}

        def late_server():
            time.sleep(0.4)
            holder["srv"] = _native.TCPStoreServer(port)

        t = threading.Thread(target=late_server)
        t.start()
        try:
            cli = _native.TCPStoreClient(port=port, timeout_ms=10_000)
            cli.set("raced", b"ok")
            assert cli.get("raced") == b"ok"
            cli.close()
        finally:
            t.join()
            holder["srv"].stop()
        # a server that never comes up still fails, at the deadline
        with pytest.raises(ConnectionError):
            _native.TCPStoreClient(port=port, timeout_ms=300)

    def test_rendezvous_barrier_pattern(self):
        # the init_parallel_env bootstrap pattern: ranks add() then wait
        srv = _native.TCPStoreServer()
        try:
            nranks = 4
            def rank(r, out):
                c = _native.TCPStoreClient(port=srv.port)
                c.set(f"rank/{r}", str(r).encode())
                c.add("arrived", 1)
                for p in range(nranks):
                    out[r].append(int(c.get(f"rank/{p}", timeout_ms=5000)))
                c.close()

            outs = [[] for _ in range(nranks)]
            ts = [threading.Thread(target=rank, args=(r, outs)) for r in range(nranks)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            for r in range(nranks):
                assert outs[r] == list(range(nranks))
        finally:
            srv.stop()


class TestShmRing:
    def test_push_pop_order(self):
        ring = _native.ShmRing(f"/pt_test_{os.getpid()}_a", 1 << 20)
        try:
            for i in range(100):
                ring.push(f"item-{i}".encode())
            for i in range(100):
                assert ring.pop(timeout_ms=1000) == f"item-{i}".encode()
        finally:
            ring.close()
            ring.destroy()

    def test_pop_timeout(self):
        ring = _native.ShmRing(f"/pt_test_{os.getpid()}_b", 1 << 16)
        try:
            with pytest.raises(TimeoutError):
                ring.pop(timeout_ms=100)
        finally:
            ring.close()
            ring.destroy()

    def test_wraparound_large_items(self):
        ring = _native.ShmRing(f"/pt_test_{os.getpid()}_c", 1 << 16)
        try:
            blob = os.urandom(20_000)
            # more total bytes than capacity → must wrap; interleave push/pop
            for _ in range(10):
                ring.push(blob, timeout_ms=1000)
                assert ring.pop(timeout_ms=1000) == blob
        finally:
            ring.close()
            ring.destroy()

    def test_pop_buffer_growth_preserves_data(self):
        # item pushed while pop is blocked with a too-small initial buffer:
        # the -4 retry path must not consume the length header
        ring = _native.ShmRing(f"/pt_test_{os.getpid()}_e", 1 << 22)
        try:
            blob = os.urandom(300_000)  # > the 64KB floor buffer in pop()
            results = []

            def consumer():
                results.append(ring.pop(timeout_ms=5000))
                results.append(ring.pop(timeout_ms=5000))

            t = threading.Thread(target=consumer)
            t.start()
            import time

            time.sleep(0.1)  # let pop block on the empty ring first
            ring.push(blob)
            ring.push(b"after")
            t.join()
            assert results[0] == blob
            assert results[1] == b"after"
        finally:
            ring.close()
            ring.destroy()

    def test_attach_before_create_retries_until_deadline(self):
        # the ring-consumer half of the startup race: attach with a
        # deadline retries until the producer's create lands
        import time

        name = f"/pt_test_{os.getpid()}_late"
        holder = {}

        def late_create():
            time.sleep(0.3)
            holder["ring"] = _native.ShmRing(name, 1 << 16)

        t = threading.Thread(target=late_create)
        t.start()
        try:
            reader = _native.ShmRing(name, create=False,
                                     attach_timeout_ms=5_000)
            holder["ring"].push(b"raced")
            assert reader.pop(timeout_ms=1000) == b"raced"
        finally:
            t.join()
            holder["ring"].close()
            holder["ring"].destroy()
        # attach_timeout_ms=0 keeps the historical fail-fast contract
        with pytest.raises(OSError):
            _native.ShmRing(f"/pt_never_{os.getpid()}", create=False)
        # and a producer that never creates still fails, at the deadline
        with pytest.raises(OSError):
            _native.ShmRing(f"/pt_never_{os.getpid()}", create=False,
                            attach_timeout_ms=200)

    def test_cross_process(self):
        name = f"/pt_test_{os.getpid()}_d"
        ring = _native.ShmRing(name, 1 << 20)
        try:
            pid = os.fork()
            if pid == 0:
                try:
                    w = _native.ShmRing(name, create=False)
                    for i in range(50):
                        w.push(f"{i}".encode(), timeout_ms=5000)
                    os._exit(0)
                except BaseException:
                    os._exit(1)
            got = [int(ring.pop(timeout_ms=5000)) for _ in range(50)]
            _, status = os.waitpid(pid, 0)
            assert os.waitstatus_to_exitcode(status) == 0
            assert got == list(range(50))
        finally:
            ring.close()
            ring.destroy()


class TestMpDataLoader:
    def test_mp_shm_dataloader_order_and_values(self):
        from paddle_tpu.io import DataLoader, Dataset

        class Sq(Dataset):
            def __len__(self):
                return 37

            def __getitem__(self, i):
                return np.asarray([i * i], np.int64)

        dl = DataLoader(Sq(), batch_size=5, num_workers=3, use_shared_memory=True)
        flat = []
        for batch in dl:
            arr = np.asarray(batch)
            flat.extend(int(v) for v in arr.reshape(-1))
        assert flat == [i * i for i in range(37)]

    def test_worker_exception_propagates_with_traceback(self):
        from paddle_tpu.io import DataLoader, Dataset

        class Bad(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("corrupt sample 5")
                return np.asarray([i], np.int64)

        dl = DataLoader(Bad(), batch_size=2, num_workers=2, use_shared_memory=True)
        with pytest.raises(RuntimeError, match="corrupt sample 5"):
            list(dl)

    def test_mp_shm_dataloader_two_epochs(self):
        from paddle_tpu.io import DataLoader, Dataset

        class Ds(Dataset):
            def __len__(self):
                return 10

            def __getitem__(self, i):
                return np.asarray([i], np.float32)

        dl = DataLoader(Ds(), batch_size=4, num_workers=2, use_shared_memory=True)
        for _ in range(2):
            n = sum(np.asarray(b).size for b in dl)
            assert n == 10


class TestHostEventRecorder:
    def test_record_dump(self):
        rec = _native.HostEventRecorder()
        nid = rec.intern("matmul")
        t0 = rec.now_ns()
        rec.record(nid, t0, t0 + 100, tid=7)
        rec.record(rec.intern("add"), t0 + 200, t0 + 250, tid=7)
        events = rec.dump()
        assert [e[0] for e in events] == ["matmul", "add"]
        assert events[0][2] - events[0][1] == 100
        assert events[0][3] == 7
        assert rec.dump() == []  # cleared

    def test_many_events(self):
        rec = _native.HostEventRecorder()
        nid = rec.intern("op")
        for i in range(10_000):
            rec.record(nid, i, i + 1)
        assert len(rec.dump()) == 10_000
