"""ONNX export (reference python/paddle/onnx/export.py — delegation to
paddle2onnx; here a self-contained jaxpr->ONNX converter, see
paddle_tpu/onnx/__init__.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.onnx import export
from paddle_tpu.onnx._proto import parse_model


def test_export_mlp_structure(tmp_path):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    from paddle_tpu.static import InputSpec

    p = export(m, str(tmp_path / "mlp"), input_spec=[InputSpec([1, 8], "float32", "x")])
    buf = open(p, "rb").read()
    model = parse_model(buf)
    ops = [n["op_type"] for n in model["nodes"]]
    assert "MatMul" in ops and ("Max" in ops or "Relu" in ops), ops
    assert model["opset"] == 13
    assert model["inputs"] == ["input_0"]
    assert len(model["outputs"]) == 1
    # weights became initializers: 2 kernels + 2 biases at least
    w_inits = [i for i in model["initializers"] if i["dims"]]
    assert len(w_inits) >= 4


def test_export_softmax_classifier(tmp_path):
    paddle.seed(1)

    class Clf(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(6, 3)

        def forward(self, x):
            return nn.functional.softmax(self.fc(x), axis=-1)

    from paddle_tpu.static import InputSpec

    p = export(Clf(), str(tmp_path / "clf"), input_spec=[InputSpec([2, 6], "float32", "x")])
    model = parse_model(open(p, "rb").read())
    ops = [n["op_type"] for n in model["nodes"]]
    assert "Exp" in ops and any(o.startswith("Reduce") for o in ops), ops


def test_export_unsupported_raises(tmp_path):
    class Weird(nn.Layer):
        def forward(self, x):
            return paddle.linalg.qr(x)[0]

    from paddle_tpu.static import InputSpec

    with pytest.raises(NotImplementedError, match="unsupported primitive"):
        export(Weird(), str(tmp_path / "w"), input_spec=[InputSpec([3, 3], "float32", "x")])
