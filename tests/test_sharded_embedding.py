"""Mesh-sharded embedding table — the PS successor (VERDICT r3 #7).

Reference: paddle/fluid/distributed/ps/table/memory_sparse_table.h (sharded
accessor tables) + pull_sparse/push_sparse services; here: row-sharded
device table, all-to-all id exchange, SelectedRows-style per-shard updates,
host SparseTable spill tier, checkpoint round-trip.
"""

import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import MeshShardedEmbedding, SparseTable


def _mesh(w=8):
    return Mesh(np.array(jax.devices()[:w]), ("dp",))


def test_pull_matches_direct_gather():
    table = MeshShardedEmbedding(1000, 8, _mesh(), optimizer="sgd", seed=3)
    full = np.asarray(table.weight)[:1000]
    ids = np.array([0, 999, 5, 5, 123, 777, 64, 3], np.int64)
    rows = np.asarray(table.pull(ids))
    np.testing.assert_allclose(rows, full[ids], rtol=1e-6)
    # 2-D id batches keep their shape
    ids2 = ids.reshape(2, 4)
    rows2 = np.asarray(table.pull(ids2))
    assert rows2.shape == (2, 4, 8)
    np.testing.assert_allclose(rows2.reshape(8, 8), full[ids], rtol=1e-6)


def test_push_updates_only_touched_rows_sgd():
    table = MeshShardedEmbedding(512, 4, _mesh(), optimizer="sgd", lr=0.5)
    before = np.asarray(table.weight)[:512].copy()
    ids = np.array([7, 300, 511, 7], np.int64)  # dup id: grads accumulate
    g = np.ones((4, 4), np.float32)
    table.push(ids, g)
    after = np.asarray(table.weight)[:512]
    touched = {7, 300, 511}
    for r in range(512):
        if r in touched:
            expect = before[r] - 0.5 * (2.0 if r == 7 else 1.0)
            np.testing.assert_allclose(after[r], expect, rtol=1e-5,
                                       err_msg=str(r))
        else:
            np.testing.assert_array_equal(after[r], before[r])


def test_adagrad_lazy_second_moments():
    table = MeshShardedEmbedding(256, 4, _mesh(), optimizer="adagrad", lr=0.1)
    ids = np.array([10, 200], np.int64)
    g = np.full((2, 4), 2.0, np.float32)
    before = np.asarray(table.weight)[:256].copy()
    table.push(ids, g)
    acc = np.asarray(table._acc)[:256]
    assert np.allclose(acc[10], 4.0) and np.allclose(acc[200], 4.0)
    assert np.abs(acc).sum() == pytest.approx(2 * 4 * 4.0)  # only touched rows
    after = np.asarray(table.weight)[:256]
    np.testing.assert_allclose(
        after[10], before[10] - 0.1 * 2.0 / (np.sqrt(4.0) + 1e-8), rtol=1e-5)


def test_spill_tier_serves_overflow_ids():
    spill = SparseTable(dim=4, optimizer="sgd", lr=1.0)
    table = MeshShardedEmbedding(128, 4, _mesh(), optimizer="sgd",
                                 spill_table=spill, lr=1.0)
    ids = np.array([5, 127, 128, 1000], np.int64)  # last two overflow
    rows = np.asarray(table.pull(ids))
    assert rows.shape == (4, 4)
    assert spill.n_rows() == 2  # lazily created host rows
    g = np.ones((4, 4), np.float32)
    table.push(ids, g)
    # host rows moved by -lr*g; device overflow slots untouched
    np.testing.assert_allclose(spill.pull([128]), rows[2:3] - 1.0, rtol=1e-5)
    # without a spill table overflow is loud
    t2 = MeshShardedEmbedding(128, 4, _mesh(), optimizer="sgd")
    with pytest.raises(IndexError):
        t2.pull(np.array([4000], np.int64))


def test_checkpoint_round_trip():
    t1 = MeshShardedEmbedding(300, 4, _mesh(), optimizer="adagrad", seed=1)
    t1.push(np.array([3, 250], np.int64), np.ones((2, 4), np.float32))
    state = t1.state_dict()
    t2 = MeshShardedEmbedding(300, 4, _mesh(), optimizer="adagrad", seed=9)
    t2.set_state_dict(state)
    np.testing.assert_allclose(np.asarray(t2.weight)[:300],
                               np.asarray(t1.weight)[:300], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(t2._acc)[:300],
                               np.asarray(t1._acc)[:300], rtol=1e-6)


def test_embedding_trains_end_to_end_on_mesh():
    """Rows pulled into a jax loss, gradient pushed back; the looked-up
    embedding moves toward the target while the rest of the table stays."""
    table = MeshShardedEmbedding(4096, 8, _mesh(), optimizer="sgd", lr=0.25)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 4096, 64).astype(np.int64)
    target = jnp.asarray(rng.normal(size=(64, 8)).astype(np.float32))

    def loss_fn(rows):
        # per-row squared error summed over the feature dim: the gradient
        # scale is row-local, so the SGD factor is (1 - 2*lr) per step
        return ((rows - target) ** 2).sum()

    losses = []
    for _ in range(10):
        rows = table.pull(ids)
        losses.append(float(loss_fn(rows)))
        g = jax.grad(loss_fn)(rows)
        table.push(ids, np.asarray(g))
    assert losses[-1] < 0.2 * losses[0], losses


@pytest.mark.slow
def test_ten_million_rows_sparse_faster_than_replicated_dense():
    """VERDICT done-criterion: a 10M-row embedding trains on the 8-device
    mesh with per-shard lazy updates, measured faster than the replicated
    dense update."""
    V, d, n = 10_000_000, 8, 1024
    table = MeshShardedEmbedding(V, d, _mesh(), optimizer="sgd", lr=0.1)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, V, n).astype(np.int64)
    g = rng.normal(size=(n, d)).astype(np.float32)

    # replicated dense twin: full-table dense-gradient update each step
    w = jnp.zeros((V, d), jnp.float32)

    @jax.jit
    def dense_step(w, ids, g):
        dense_g = jnp.zeros_like(w).at[ids].add(g)
        return w - 0.1 * dense_g

    table.push(ids, g)  # compile
    w = dense_step(w, jnp.asarray(ids), jnp.asarray(g))  # compile
    jax.block_until_ready(w)

    def time_best(fn, reps=3, iters=3):
        # best-of-N: this is a PERF comparison on a shared CI core — the
        # minimum is the least load-contaminated sample
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                fn()
            best = min(best, (time.perf_counter() - t0) / iters)
        return best

    sparse_t = time_best(lambda: (table.push(ids, g),
                                  jax.block_until_ready(table.weight)))

    def dense_once():
        nonlocal w
        w = dense_step(w, jnp.asarray(ids), jnp.asarray(g))
        jax.block_until_ready(w)

    dense_t = time_best(dense_once)

    assert sparse_t < dense_t, (sparse_t, dense_t)
    # rows really trained
    assert float(jnp.abs(table.pull(ids[:4])).sum()) > 0


def test_configured_capacity_overflow_is_loud():
    """A too-small explicit capacity must refuse loudly instead of silently
    dropping lookups/gradients."""
    table = MeshShardedEmbedding(1024, 4, _mesh(), optimizer="sgd", capacity=2)
    # 32 ids all owned by shard 0 -> one rank's bucket needs >> 2 slots
    ids = np.zeros(32, np.int64)
    with pytest.raises(ValueError, match="capacity=2 overflows"):
        table.pull(ids)
    with pytest.raises(ValueError, match="overflows"):
        table.push(ids, np.ones((32, 4), np.float32))
    # a sufficient capacity still works
    t2 = MeshShardedEmbedding(1024, 4, _mesh(), optimizer="sgd", capacity=4)
    spread = np.arange(0, 1024, 32).astype(np.int64)  # even over shards
    rows = t2.pull(spread)
    assert rows.shape == (32, 4)


def test_pull_stays_on_device_without_spill():
    import jax

    table = MeshShardedEmbedding(256, 4, _mesh(), optimizer="sgd")
    out = table.pull(np.arange(16, dtype=np.int64))
    assert isinstance(out, jax.Array)  # no host round-trip on the hot path
