"""Cluster SIGKILL crash-injection matrix (serving/cluster.py,
docs/SERVING_CLUSTER.md; the serving-cluster extension of the
test_engine_snapshot_crash.py matrix).

A DRIVER subprocess runs a real cluster — router in the driver process, N
decode replicas + a prefill worker as its own OS child processes — over
the native TCPStore and ShmRing, serving a fixed greedy+sampled workload
with KV-page shipping.  Crash injection SIGKILLs one enumerated
participant at one enumerated protocol point:

- a DECODE REPLICA after accepting a request, mid-stream (intake-log
  replay fail-over), mid-stream with boundary snapshots armed
  (EngineSnapshot restore fail-over), and right after adopting shipped
  pages;
- the PREFILL WORKER before and in the middle of a page shipment;
- the WARM-STANDBY tier (ROADMAP item 5): a decode death with a warm
  standby parked is recovered by PROMOTION (the standby claims the dead
  replica's snapshot — no respawn), and a standby SIGKILLed mid-warmup
  degrades recovery to the respawn fallback without losing a request;
- the ROUTER itself right after journaling an acceptance and mid-serving
  (the driver process dies; a SECOND driver run over the same workdir
  replays the durable intake log, sweeps the orphaned workers, and
  finishes).

Every completed run must produce streams BIT-IDENTICAL to the unkilled
reference — zero accepted requests lost, no stream corrupted, no request
served twice (the router's canonical per-position merge enforces all
three).  The worker and standby matrices run TWICE — once over ShmRing
(single box) and once over the TcpRing socket data plane between two
localhost "hosts" (serving/transport.py), both compared against the ONE
shm reference, so a kill that tears live TCP connections mid-frame must
still recover bit-exactly.  This module forks and kills real processes:
it rides a DEDICATED tools/run_tier1.py isolated worker, never the
shared shard."""

import json
import os
import signal
import subprocess
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))

_DRIVER = r"""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
cache = os.environ.get("PADDLE_TPU_TEST_CACHE_DIR", "/tmp/jax_cache")
jax.config.update("jax_compilation_cache_dir", cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

from paddle_tpu.serving.cluster import EngineCluster, cluster_stats

(workdir, out_path, model_spec, router_kill, worker_role, worker_kill,
 snapshot_interval, standby, wait_standby, transport) = sys.argv[1:11]

worker_kill_map = {}
if worker_kill.startswith("{"):
    # multi-participant kills: {"role:idx": "point:nth", ...}
    for k, v in json.loads(worker_kill).items():
        role, idx = k.split(":")
        worker_kill_map[(role, int(idx))] = v
elif worker_kill:
    worker_kill_map[(worker_role, 0)] = worker_kill

EKW = dict(max_batch=2, block_size=8, num_blocks=32, decode_chunk=2)
SHARED = [5, 9, 17, 33, 2, 8, 7, 4]
WORKLOAD = [
    ("g1", SHARED + [22, 3], dict(max_new_tokens=8)),
    ("g2", SHARED + [9, 1], dict(max_new_tokens=8)),
    ("s1", [7, 11, 3], dict(max_new_tokens=6, temperature=5.0, seed=3)),
]

c = EngineCluster(model_spec, num_replicas=2, num_prefill=1,
                  engine_kwargs=EKW, workdir=workdir,
                  heartbeat_ms=100, miss_threshold=10,
                  snapshot_interval=int(snapshot_interval),
                  kill=router_kill, worker_kill=worker_kill_map,
                  standby=int(standby), transport=transport)
try:
    if int(wait_standby):
        # the case under test is PROMOTION: the kill must find a WARM
        # standby, not race its boot
        import time
        deadline = time.monotonic() + 180
        while cluster_stats()["standbys_warm"] < int(wait_standby):
            c.poll()
            if time.monotonic() > deadline:
                raise TimeoutError("standby tier never warmed")
            time.sleep(0.01)
    for rid, prompt, opts in WORKLOAD:
        c.submit(rid, prompt, max_new_tokens=opts["max_new_tokens"],
                 temperature=opts.get("temperature", 0.0),
                 seed=opts.get("seed", 0))
    c.serve(timeout_s=240)
    with open(out_path, "w") as f:
        json.dump({rid: c.result(rid) for rid, _p, _o in WORKLOAD}, f)
    print("STATS", json.dumps(cluster_stats()))
    print("DONE")
finally:
    c.shutdown()
"""

_MODEL_SPEC = os.path.join(_HERE, "cluster_common.py") + ":make_model"


def _run_driver(tmp_path, workdir, out, router_kill="", worker_role="",
                worker_kill="", snapshot_interval=0, standby=0,
                wait_standby=0, transport="shm"):
    script = tmp_path / "driver.py"
    script.write_text(_DRIVER)
    repo_root = os.path.dirname(_HERE)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo_root + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.setdefault("PADDLE_TPU_TEST_CACHE_DIR", "/tmp/jax_cache")
    cmd = [sys.executable, str(script), str(workdir), str(out),
           _MODEL_SPEC, router_kill, worker_role, worker_kill,
           str(snapshot_interval), str(standby), str(wait_standby),
           transport]
    return subprocess.run(cmd, capture_output=True, text=True, timeout=480,
                          env=env)


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """The unkilled (shm) cluster run: the streams every killed variant
    — on EITHER transport — must reproduce token for token.  Comparing
    tcp runs against the shm reference additionally pins stream
    transport-independence: the data plane may reorder wall-clock, never
    tokens."""
    td = tmp_path_factory.mktemp("cluster_ref")
    out = td / "ref.json"
    r = _run_driver(td, td / "wd", out)
    assert "DONE" in r.stdout, (r.stdout + r.stderr)[-3000:]
    return json.loads(out.read_text())


# shm: process-shared rings (single box); tcp: TcpRing sockets between
# two localhost "hosts" (serving/transport.py) — the FULL kill matrix
# re-runs on each data plane, bit-exact against the one shm reference
_TRANSPORTS = ["shm", "tcp"]

# (who dies, at which protocol point, boundary snapshots armed?)
_WORKER_MATRIX = [
    ("decode", "decode-after-accept:1", 0),
    ("decode", "decode-mid-stream:1", 0),   # intake-log replay fail-over
    ("decode", "decode-mid-stream:2", 1),   # EngineSnapshot restore fail-over
    ("decode", "decode-after-adopt:1", 0),  # dies holding shipped pages
    ("prefill", "prefill-before-ship:1", 0),
    ("prefill", "prefill-mid-ship:1", 0),   # partial shipment on the wire
]


@pytest.mark.parametrize("transport", _TRANSPORTS)
@pytest.mark.parametrize("role,point,snap", _WORKER_MATRIX,
                         ids=[p for _r, p, _s in _WORKER_MATRIX])
def test_worker_kill_matrix_streams_bit_identical(tmp_path, reference,
                                                  role, point, snap,
                                                  transport):
    """SIGKILL one worker process at the named point: the router detects
    the death (heartbeats/child-exit), re-dispatches every accepted-but-
    unfinished request (replayed from the intake log, restored from the
    dead replica's boundary snapshot, or re-shipped through a fresh
    prefill worker), and the completed streams equal the unkilled run's
    bit for bit — on the shm plane and again over TcpRing sockets, where
    the kill also tears the victim's live connections mid-frame."""
    out = tmp_path / "out.json"
    r = _run_driver(tmp_path, tmp_path / "wd", out, worker_role=role,
                    worker_kill=point, snapshot_interval=snap,
                    transport=transport)
    assert "DONE" in r.stdout, (r.stdout + r.stderr)[-3000:]
    got = json.loads(out.read_text())
    assert got == reference, (got, reference)
    stats = json.loads(
        [ln for ln in r.stdout.splitlines()
         if ln.startswith("STATS ")][-1][len("STATS "):])
    # the injected kill really happened: a replacement process spawned
    assert stats["respawns"] >= 1, stats
    if role == "decode" and not snap:
        # replay fail-over: requests genuinely moved (the restore path
        # instead CLAIMS them back via the replacement's resume report,
        # so redispatches may legitimately stay 0 there)
        assert stats["redispatches"] >= 1, stats
    if role == "prefill":
        assert stats["ship_retries"] >= 1, stats


@pytest.mark.parametrize("transport", _TRANSPORTS)
def test_standby_promotion_claims_snapshot_bit_identical(tmp_path,
                                                         reference,
                                                         transport):
    """Warm-standby fail-over (ROADMAP item 5): a decode replica is
    SIGKILLed mid-stream with boundary snapshots armed and a WARM standby
    parked.  The standby is PROMOTED — no process spawns — claims the
    dead replica's snapshot directory, restores its residents, and every
    completed stream equals the unkilled run's bit for bit (the
    bit-exact fail-over contract re-asserted on the promotion path, on
    both data planes)."""
    out = tmp_path / "out.json"
    r = _run_driver(tmp_path, tmp_path / "wd", out, worker_role="decode",
                    worker_kill="decode-mid-stream:2", snapshot_interval=1,
                    standby=1, wait_standby=1, transport=transport)
    assert "DONE" in r.stdout, (r.stdout + r.stderr)[-3000:]
    got = json.loads(out.read_text())
    assert got == reference, (got, reference)
    stats = json.loads(
        [ln for ln in r.stdout.splitlines()
         if ln.startswith("STATS ")][-1][len("STATS "):])
    # the warm standby took the slot; the respawn path never ran
    assert stats["promotions"] >= 1, stats
    assert stats["respawns"] == 0, stats


@pytest.mark.parametrize("transport", _TRANSPORTS)
def test_standby_killed_mid_warmup_falls_back_to_respawn(tmp_path,
                                                         reference,
                                                         transport):
    """The standby ITSELF is SIGKILLed mid-warmup, then a decode replica
    dies mid-stream before the backfilled standby can warm: recovery
    falls back to the (cache-warmed) respawn path.  Zero requests lost,
    streams bit-identical — a dead standby never weakens the fail-over
    contract, it only costs the fast path."""
    kills = json.dumps({"standby:0": "standby-mid-warmup:1",
                        "decode:0": "decode-mid-stream:1"})
    out = tmp_path / "out.json"
    r = _run_driver(tmp_path, tmp_path / "wd", out, worker_kill=kills,
                    snapshot_interval=1, standby=1, transport=transport)
    assert "DONE" in r.stdout, (r.stdout + r.stderr)[-3000:]
    got = json.loads(out.read_text())
    assert got == reference, (got, reference)
    stats = json.loads(
        [ln for ln in r.stdout.splitlines()
         if ln.startswith("STATS ")][-1][len("STATS "):])
    # the decode death was recovered by a respawn (the dead standby left
    # no warm candidate in time); promotions are not asserted zero —
    # the backfilled standby MAY win the race on a slow box, and either
    # recovery path must uphold the same stream contract
    assert stats["respawns"] >= 1 or stats["promotions"] >= 1, stats


@pytest.mark.parametrize("router_kill,snap,transport", [
    ("router-after-accept:1", 0, "shm"),
    ("router-mid-serving:1", 0, "shm"),
    # boundary snapshots armed: the restarted router's replicas RESTORE
    # and claim their residents via resume reports — the replay backlog
    # must hold for those claims instead of double-dispatching the same
    # rids onto other replicas
    ("router-mid-serving:1", 1, "shm"),
    # over TcpRing the restarted router binds FRESH listener ports and
    # re-publishes every ep:<ring> key on its new control store — the
    # orphan sweep plus endpoint re-publication path
    ("router-mid-serving:1", 0, "tcp"),
], ids=["after-accept", "mid-serving", "mid-serving-snapshots",
        "mid-serving-tcp"])
def test_router_kill_then_restart_replays_intake_log(tmp_path, reference,
                                                     router_kill, snap,
                                                     transport):
    """SIGKILL the ROUTER PROCESS itself (after journaling the first
    acceptance / after delivering the first token event): a fresh router
    over the same workdir sweeps the orphaned workers, replays the
    durable intake log — completed streams served from the journal,
    unfinished requests re-dispatched — and finishes every stream
    bit-identically.  An accepted request never dies with the router."""
    wd = tmp_path / "wd"
    r = _run_driver(tmp_path, wd, tmp_path / "x.json",
                    router_kill=router_kill, snapshot_interval=snap,
                    transport=transport)
    assert r.returncode == -signal.SIGKILL, (r.stdout + r.stderr)[-3000:]
    assert os.path.exists(wd / "intake.jsonl")

    out = tmp_path / "resumed.json"
    r2 = _run_driver(tmp_path, wd, out, snapshot_interval=snap,
                     transport=transport)
    assert "DONE" in r2.stdout, (r2.stdout + r2.stderr)[-3000:]
    got = json.loads(out.read_text())
    assert got == reference, (got, reference)
