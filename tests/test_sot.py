"""SOT-lite bytecode capture (VERDICT r2 item 3).

Reference test lineage: test/sot/test_01_basic.py (capture + numeric
equivalence), test_03_tuple / test_04_list (container opcodes),
test_break_graph.py (data-dependent branch -> graph break + resume),
test_guard_outputs.py (re-trace on guard miss), and the
fallback-to-dygraph contract of opcode_executor.py.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import to_static
from paddle_tpu.jit.sot import SOTFunction, sot_stats, symbolic_translate


def T(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


def test_basic_capture_matches_eager():
    def fn(x, y):
        z = x * 2.0 + y
        w = paddle.tanh(z)
        return w.sum()

    sot = symbolic_translate(fn)
    x, y = T([[1.0, 2.0], [3.0, -1.0]]), T([[0.5, 0.5], [0.5, 0.5]])
    ref = fn(x, y)
    got = sot(x, y)
    np.testing.assert_allclose(float(got._value), float(ref._value), rtol=1e-6)
    # capture recorded one straight-line segment
    assert len(sot._captures) == 1
    (cap,) = next(iter(sot._captures.values())).values()
    assert len(cap.segments) == 1 and cap.decisions == ()


def test_python_control_flow_interpreted_natively():
    def fn(x, n):
        acc = x
        for i in range(n):  # python loop: unrolled by the interpreter
            if i % 2 == 0:  # python branch: no graph break
                acc = acc + x
            else:
                acc = acc * 1.5
        return acc.mean()

    sot = symbolic_translate(fn)
    x = T([1.0, 2.0, 3.0])
    np.testing.assert_allclose(
        float(sot(x, 4)._value), float(fn(x, 4)._value), rtol=1e-6)
    # one segment: python-level control flow does not break the graph
    cap_tree = sot._captures[next(iter(sot._captures))]
    (cap,) = cap_tree.values()
    assert len(cap.segments) == 1


def test_data_dependent_branch_graph_breaks_and_both_paths_trace():
    def fn(x):
        y = x * 3.0
        if y.sum() > 0:  # tensor predicate -> graph break
            z = y + 10.0
        else:
            z = y - 10.0
        return z.mean()

    before = sot_stats()["graph_breaks"]
    sot = symbolic_translate(fn)
    pos, neg = T([1.0, 2.0]), T([-1.0, -2.0])
    np.testing.assert_allclose(float(sot(pos)._value), float(fn(pos)._value), rtol=1e-6)
    assert sot_stats()["graph_breaks"] == before + 1
    # same guard signature, other branch: re-traces the False path
    np.testing.assert_allclose(float(sot(neg)._value), float(fn(neg)._value), rtol=1e-6)
    tree = sot._captures[next(iter(sot._captures))]
    assert set(tree.keys()) == {(True,), (False,)}
    for cap in tree.values():
        assert len(cap.segments) == 2  # prefix + taken-branch continuation


def test_replay_uses_cached_segments():
    def fn(x):
        y = x * 2.0
        if y.sum() > 0:
            return (y + 1.0).mean()
        return (y - 1.0).mean()

    sot = symbolic_translate(fn)
    x = T([1.0, 2.0])
    first = float(sot(x)._value)
    replays_before = sot_stats()["replays"]
    second = float(sot(x)._value)  # same signature + same decision path
    assert sot_stats()["replays"] == replays_before + 1
    np.testing.assert_allclose(second, first, rtol=1e-6)


def test_guard_miss_on_new_shape_retraces():
    def fn(x):
        return (x * x).sum()

    sot = symbolic_translate(fn)
    sot(T([1.0, 2.0]))
    assert len(sot._captures) == 1
    sot(T([[1.0], [2.0], [3.0]]))  # new shape -> new guard entry
    assert len(sot._captures) == 2


def test_unsupported_construct_falls_back_not_crashes():
    def fn(x):
        # `with` compiles to BEFORE_WITH etc. — outside the supported subset
        with paddle.no_grad():
            y = x * 2.0
        return y.sum()

    before = sot_stats()["fallbacks"]
    sot = symbolic_translate(fn)
    x = T([1.0, 2.0])
    np.testing.assert_allclose(float(sot(x)._value), float(fn(x)._value), rtol=1e-6)
    assert sot_stats()["fallbacks"] == before + 1
    # signature marked eager-only: second call falls back immediately
    sot(x)
    assert sot_stats()["fallbacks"] == before + 2


def test_callee_branching_on_symbolic_tensor_falls_back():
    def helper(v):
        if float(v.sum()) > 0:  # concretizes inside a native call
            return v + 1.0
        return v - 1.0

    def fn(x):
        return helper(x * 2.0).sum()

    before = sot_stats()["fallbacks"]
    sot = symbolic_translate(fn)
    x = T([1.0, 2.0])
    np.testing.assert_allclose(float(sot(x)._value), float(fn(x)._value), rtol=1e-6)
    assert sot_stats()["fallbacks"] == before + 1


def test_containers_and_methods():
    def fn(x):
        parts = [x * 1.0, x * 2.0, x * 3.0]
        stacked = paddle.stack(parts, axis=0)
        a, b, c = parts
        d = {"k": a + b}
        return stacked.sum() + d["k"].mean() + c.max()

    sot = symbolic_translate(fn)
    x = T([1.0, -2.0, 3.0])
    np.testing.assert_allclose(float(sot(x)._value), float(fn(x)._value), rtol=1e-6)


def test_to_static_mode_sot():
    @to_static(mode="sot")
    def fn(x):
        if x.mean() > 0:
            return x * 2.0
        return x * -1.0

    assert isinstance(fn, SOTFunction)
    x = T([3.0, 1.0])
    np.testing.assert_allclose(np.asarray(fn(x)._value), [6.0, 2.0], rtol=1e-6)
    x2 = T([-3.0, -1.0])
    np.testing.assert_allclose(np.asarray(fn(x2)._value), [3.0, 1.0], rtol=1e-6)


def test_multiple_tensor_args_and_python_kwargs():
    def fn(x, y, scale=1.0):
        return (x * scale + y).sum()

    sot = symbolic_translate(fn)
    x, y = T([1.0, 2.0]), T([3.0, 4.0])
    np.testing.assert_allclose(
        float(sot(x, y, scale=2.5)._value), float(fn(x, y, scale=2.5)._value), rtol=1e-6)
    # different python kwarg value is a different guard
    np.testing.assert_allclose(
        float(sot(x, y, scale=0.5)._value), float(fn(x, y, scale=0.5)._value), rtol=1e-6)
    assert len(sot._captures) == 2


def test_early_return_in_branch_returns_data_not_variable():
    """Pass-through final segments (no recorded ops after the break) must
    still concretize: the op-less Program path."""
    def fn(x):
        if x.sum() > 0:
            return x
        return -x

    sot = symbolic_translate(fn)
    out = sot(T([1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(out._value), [1.0, 2.0])
    out2 = sot(T([-1.0, -2.0]))
    np.testing.assert_allclose(np.asarray(out2._value), [1.0, 2.0])


def test_unhashable_python_arg_runs_eagerly_with_fresh_values():
    def fn(x, cfg):
        return x * cfg[0]

    sot = symbolic_translate(fn)
    x = T([1.0, 2.0])
    np.testing.assert_allclose(np.asarray(sot(x, [2.0])._value), [2.0, 4.0])
    # different list contents MUST NOT replay the old constant
    np.testing.assert_allclose(np.asarray(sot(x, [3.0])._value), [3.0, 6.0])


def test_symbolic_while_loop_breaks_per_iteration():
    def fn(x):
        while x.sum() < 10.0:  # symbolic predicate: graph break per check
            x = x + 1.0
        return x

    sot = symbolic_translate(fn)
    out = sot(T([1.0, 2.0]))
    ref = fn(T([1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref._value))


# ---------------------------------------------------------------- inlining
# (VERDICT r3 #2: reference opcode_inline_executor.py — graph breaks and
# guards must compose at any call depth)

def test_callee_symbolic_branch_is_inlined_with_graph_break():
    """A helper branching on a symbolic tensor no longer poisons the whole
    signature: the callee is inlined and the break happens at depth."""
    def helper(v):
        if v.sum() > 0:  # symbolic predicate INSIDE the callee
            return v + 1.0
        return v - 1.0

    def fn(x):
        return helper(x * 2.0).sum()

    before_fb = sot_stats()["fallbacks"]
    before_brk = sot_stats()["graph_breaks"]
    sot = symbolic_translate(fn)
    x = T([1.0, 2.0])
    np.testing.assert_allclose(float(sot(x)._value), float(fn(x)._value), rtol=1e-6)
    assert sot_stats()["fallbacks"] == before_fb          # no fallback
    assert sot_stats()["graph_breaks"] == before_brk + 1  # break at depth
    # the negative path traces as a sibling capture under the same guard
    xn = T([-1.0, -2.0])
    np.testing.assert_allclose(float(sot(xn)._value), float(fn(xn)._value), rtol=1e-6)
    # and both paths replay
    np.testing.assert_allclose(float(sot(x)._value), float(fn(x)._value), rtol=1e-6)
    np.testing.assert_allclose(float(sot(xn)._value), float(fn(xn)._value), rtol=1e-6)


def test_nested_helpers_inline_to_one_segment():
    """Helpers calling helpers (no symbolic branches) capture as ONE
    segment — inlining composes with native framework calls."""
    def inner(v, s):
        return v * s + 1.0

    def outer(v):
        return inner(v, 2.0) + inner(v, 3.0)

    def fn(x):
        return outer(x).sum()

    sot = symbolic_translate(fn)
    before = sot_stats()["inlines"]
    x = T([1.0, 2.0, 3.0])
    np.testing.assert_allclose(float(sot(x)._value), float(fn(x)._value), rtol=1e-6)
    assert sot_stats()["inlines"] >= before + 3  # outer + 2x inner
    (capture,) = list(sot._captures.values())[0].values()
    assert len(capture.segments) == 1


def test_layer_forward_inlines_and_breaks_at_depth():
    """A hook-free user Layer's forward is inlined through the __call__
    sugar; a symbolic branch inside it breaks instead of falling back."""
    import paddle_tpu.nn as nn

    class Gate(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if h.mean() > 0:   # break at depth 2 (fn -> forward)
                return h * 2.0
            return h * -1.0

    paddle.seed(7)
    layer = Gate()

    def fn(x):
        return layer(x).sum()

    before_fb = sot_stats()["fallbacks"]
    sot = symbolic_translate(fn)
    x = T([[1.0, 2.0, 3.0, 4.0]])
    ref = fn(x)
    np.testing.assert_allclose(float(sot(x)._value), float(ref._value), rtol=1e-6)
    assert sot_stats()["fallbacks"] == before_fb


def test_multilayer_model_captures_as_one_segment():
    """VERDICT done-criterion: a multi-layer model forward (layers calling
    helper layers) captures as ONE segment with zero fallbacks."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    class Block(nn.Layer):
        def __init__(self, d):
            super().__init__()
            self.fc1 = nn.Linear(d, 2 * d)
            self.fc2 = nn.Linear(2 * d, d)

        def forward(self, x):
            return x + self.fc2(F.relu(self.fc1(x)))

    class Model(nn.Layer):
        def __init__(self, d=8, n=3):
            super().__init__()
            self.blocks = nn.LayerList([Block(d) for _ in range(n)])

        def forward(self, x):
            for b in self.blocks:
                x = b(x)
            return x.mean()

    paddle.seed(11)
    model = Model()
    before_fb = sot_stats()["fallbacks"]
    before_in = sot_stats()["inlines"]
    sot = symbolic_translate(model.forward)
    x = T(np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32))
    ref = model(x)
    np.testing.assert_allclose(float(sot(x)._value), float(ref._value), rtol=1e-5)
    assert sot_stats()["fallbacks"] == before_fb
    assert sot_stats()["inlines"] > before_in  # the 3 Block.forwards at least
    (capture,) = list(sot._captures.values())[0].values()
    assert len(capture.segments) == 1
    assert capture.decisions == ()
    # replay path
    np.testing.assert_allclose(float(sot(x)._value), float(ref._value), rtol=1e-5)


def test_real_llama_forward_capture_fraction():
    """Fallback fraction on real model code (VERDICT asks this be
    measured): the tiny LLaMA forward must capture (no eager fallback) and
    run as a single compiled segment."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny

    paddle.seed(3)
    model = LlamaForCausalLM(llama_tiny(dtype="float32"))
    model.eval()
    ids = paddle.randint(0, 256, [1, 8])
    ref = model(ids)
    ref_t = ref[0] if isinstance(ref, (tuple, list)) else ref

    before_fb = sot_stats()["fallbacks"]
    sot = symbolic_translate(model.forward)
    out = sot(ids)
    out_t = out[0] if isinstance(out, (tuple, list)) else out
    np.testing.assert_allclose(
        np.asarray(out_t._value), np.asarray(ref_t._value), rtol=1e-4, atol=1e-5)
    assert sot_stats()["fallbacks"] == before_fb, "llama forward fell back to eager"
    caps = list(sot._captures.values())
    assert len(caps) == 1
    (capture,) = caps[0].values()
    # whole forward = one segment: zero breaks on the happy path
    assert len(capture.segments) == 1


def test_kwarg_call_replays_in_parameter_order():
    """Replay must bind keyword tensors in parameter-declaration order,
    not sorted-name order (they differ for fn(b, a))."""
    def fn(b, a):
        return (b - a).sum()

    sot = symbolic_translate(fn)
    t1, t2 = T([5.0, 7.0]), T([1.0, 2.0])
    first = float(sot(b=t1, a=t2)._value)
    np.testing.assert_allclose(first, float(fn(b=t1, a=t2)._value), rtol=1e-6)
    before = sot_stats()["replays"]
    second = float(sot(b=t1, a=t2)._value)   # replay path
    assert sot_stats()["replays"] == before + 1
    np.testing.assert_allclose(second, first, rtol=1e-6)


def test_layer_with_custom_call_runs_natively_not_inlined():
    """A Layer overriding __call__ must NOT have its forward inlined —
    the custom __call__ body would be silently skipped."""
    import paddle_tpu.nn as nn

    class Doubler(nn.Layer):
        def __call__(self, x):
            return super().__call__(x) * 2.0  # logic outside forward

        def forward(self, x):
            return x + 1.0

    layer = Doubler()

    def fn(x):
        return layer(x).sum()

    sot = symbolic_translate(fn)
    x = T([1.0, 2.0])
    np.testing.assert_allclose(float(sot(x)._value), float(fn(x)._value), rtol=1e-6)


def test_fstring_in_inlined_helper():
    """f-strings over PYTHON values interpret fine (FORMAT_VALUE /
    BUILD_STRING); an f-string over a symbolic tensor falls back."""
    def helper(v, name):
        tag = f"scale[{name}]"
        return v * (2.0 if len(tag) > 3 else 1.0)

    def fn(x):
        return helper(x, "a").sum()

    before = sot_stats()["fallbacks"]
    sot = symbolic_translate(fn)
    x = T([1.0, 2.0])
    np.testing.assert_allclose(float(sot(x)._value), float(fn(x)._value), rtol=1e-6)
    assert sot_stats()["fallbacks"] == before

    def bad(x):
        s = f"{x}"  # formatting the symbolic tensor itself
        return x * float(len(s))

    sot2 = symbolic_translate(bad)
    np.testing.assert_allclose(
        np.asarray(sot2(x)._value), np.asarray(bad(x)._value), rtol=1e-6)
    assert sot_stats()["fallbacks"] == before + 1


def test_real_gpt_and_bert_forward_capture_fraction():
    """The zero-fallback single-segment criterion must hold across the
    transformer zoo, not just LLaMA — GPT (learned positions, gelu MLP)
    and BERT (token-type embeddings, pooler) exercise different forward
    code paths through the interpreter."""
    from paddle_tpu.models import (
        BertForSequenceClassification,
        GPTForCausalLM,
        bert_tiny,
        gpt_tiny,
    )

    paddle.seed(4)
    cases = []
    gpt = GPTForCausalLM(gpt_tiny())
    gpt.eval()
    ids = paddle.randint(0, 256, [1, 8])
    cases.append((gpt, (ids,)))
    bert = BertForSequenceClassification(bert_tiny())
    bert.eval()
    cases.append((bert, (paddle.randint(0, 256, [1, 8]),)))

    for model, args in cases:
        name = type(model).__name__
        ref = model(*args)
        ref_t = ref[0] if isinstance(ref, (tuple, list)) else ref
        before_fb = sot_stats()["fallbacks"]
        sot = symbolic_translate(model.forward)
        out = sot(*args)
        out_t = out[0] if isinstance(out, (tuple, list)) else out
        np.testing.assert_allclose(
            np.asarray(out_t._value), np.asarray(ref_t._value),
            rtol=1e-4, atol=1e-5, err_msg=name)
        assert sot_stats()["fallbacks"] == before_fb, f"{name} fell back"
        caps = list(sot._captures.values())
        assert len(caps) == 1, name
        (capture,) = caps[0].values()
        assert len(capture.segments) == 1, f"{name} broke into segments"


def test_real_resnet_forward_capture_fraction():
    """The vision family exercises conv/BN/pool/Sequential paths AND an
    inline `from ... import flatten` in the forward (the IMPORT_NAME /
    IMPORT_FROM opcodes — previously an eager fallback)."""
    from paddle_tpu.vision.models import resnet18

    paddle.seed(5)
    model = resnet18()
    model.eval()
    x = paddle.to_tensor(
        np.random.default_rng(5).standard_normal((1, 3, 32, 32))
        .astype("float32"))
    ref = model(x)
    before_fb = sot_stats()["fallbacks"]
    sot = symbolic_translate(model.forward)
    out = sot(x)
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref._value),
                               rtol=1e-4, atol=1e-5)
    assert sot_stats()["fallbacks"] == before_fb, "resnet forward fell back"
    caps = list(sot._captures.values())
    assert len(caps) == 1
    (capture,) = caps[0].values()
    assert len(capture.segments) == 1


def test_first_time_import_in_trace_runs_module_body_eagerly(tmp_path):
    """A module FIRST imported inside a traced forward executes its body
    eagerly — module-level paddle ops must not be recorded into the
    capture or leave symbolic Variables cached in the module."""
    import sys
    import textwrap

    mod_name = "sot_import_victim"
    (tmp_path / f"{mod_name}.py").write_text(textwrap.dedent("""
        import paddle_tpu as paddle
        SCALE = paddle.ones([1]) * 3.0
    """))
    sys.path.insert(0, str(tmp_path))
    sys.modules.pop(mod_name, None)
    try:
        def fn(x):
            import sot_import_victim
            return x * sot_import_victim.SCALE

        sot = symbolic_translate(fn)
        out = sot(T([2.0]))
        np.testing.assert_allclose(np.asarray(out._value), [6.0])
        victim = sys.modules[mod_name]
        # the module-level op ran for real: a concrete value, not a
        # program Variable
        from paddle_tpu.static.program import Variable

        assert not isinstance(victim.SCALE, Variable)
        assert float(victim.SCALE._value[0]) == 3.0
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop(mod_name, None)


def test_detector_and_crnn_forward_capture_fraction():
    """Every model family in the zoo holds the zero-fallback single-
    segment criterion — detection (CSP/FPN/yolo_box decode) and OCR
    (stride-collapsed conv + BiLSTM) close the set."""
    from paddle_tpu.vision.models.detection import ppyolo_tiny
    from paddle_tpu.vision.models.ocr import ppocr_rec_tiny

    paddle.seed(6)
    rng = np.random.default_rng(6)
    cases = [
        (ppyolo_tiny(num_classes=4),
         paddle.to_tensor(rng.standard_normal((1, 3, 64, 64)).astype("float32"))),
        (ppocr_rec_tiny(),
         paddle.to_tensor(rng.standard_normal((1, 3, 32, 64)).astype("float32"))),
    ]
    for model, x in cases:
        name = type(model).__name__
        model.eval()
        ref = model(x)
        ref_t = ref[0] if isinstance(ref, (tuple, list)) else ref
        before_fb = sot_stats()["fallbacks"]
        sot = symbolic_translate(model.forward)
        out = sot(x)
        out_t = out[0] if isinstance(out, (tuple, list)) else out
        np.testing.assert_allclose(
            np.asarray(out_t._value), np.asarray(ref_t._value),
            rtol=1e-4, atol=1e-5, err_msg=name)
        assert sot_stats()["fallbacks"] == before_fb, f"{name} fell back"
        (capture,) = list(sot._captures.values())[0].values()
        assert len(capture.segments) == 1, f"{name} broke into segments"


# ----------------------------------------------------------- binding guards

def test_rebound_global_helper_recaptures():
    """Rebinding a module-global helper between calls must invalidate the
    capture (reference guard.py chain), not replay the stale code."""
    import types as _types

    mod = _types.ModuleType("sot_guard_mod")

    def mk(body):
        code = compile(body, "<sot_guard>", "exec")
        exec(code, mod.__dict__)
        return mod.__dict__["fn"]

    mk("def helper(x):\n    return x * 2.0\n"
       "def fn(x):\n    return helper(x) + 1.0\n")
    fn = mod.__dict__["fn"]
    sot = symbolic_translate(fn)
    x = T([1.0, 2.0])
    np.testing.assert_allclose(np.asarray(sot(x)._value), [3.0, 5.0])
    np.testing.assert_allclose(np.asarray(sot(x)._value), [3.0, 5.0])  # replay

    before = sot_stats()["guard_misses"]
    exec(compile("def helper(x):\n    return x * 10.0\n", "<g2>", "exec"),
         mod.__dict__)
    np.testing.assert_allclose(np.asarray(sot(x)._value), [11.0, 21.0])
    assert sot_stats()["guard_misses"] > before


def test_rebound_closure_cell_recaptures():
    def make(factor):
        def helper(x):
            return x * factor
        return helper

    helper = make(2.0)

    def fn(x):
        return helper(x) + 0.0

    sot = symbolic_translate(fn)
    x = T([1.0, 3.0])
    np.testing.assert_allclose(np.asarray(sot(x)._value), [2.0, 6.0])
    np.testing.assert_allclose(np.asarray(sot(x)._value), [2.0, 6.0])
    # rebinding the test-local rebinds fn's closure cell to a function
    # with the SAME code but fresh cells (factory re-invocation) — the
    # closure-identity part of the guard must catch it
    helper = make(5.0)  # noqa: F841
    np.testing.assert_allclose(np.asarray(sot(x)._value), [5.0, 15.0])


def test_monkeypatched_layer_forward_recaptures():
    import paddle_tpu.nn as nn

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)

        def forward(self, x):
            return self.helper(x)

        def helper(self, x):
            return self.lin(x) * 1.0

    m = M()
    sot = symbolic_translate(m.forward)
    x = T(np.random.default_rng(0).standard_normal((2, 4)).astype("f4"))
    a = np.asarray(sot(x)._value)
    np.testing.assert_allclose(np.asarray(sot(x)._value), a)  # replay path
    M.helper = lambda self, x: self.lin(x) * -1.0  # monkey-patch the method
    try:
        b = np.asarray(sot(x)._value)
        np.testing.assert_allclose(b, -a, rtol=1e-6)
    finally:
        del M.helper  # restore class namespace for other tests
