"""paddle.reader decorators, paddle.hub, paddle.sysconfig, paddle.pir
(reference: python/paddle/reader/decorator.py, hub.py, sysconfig.py,
pir/)."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle


def _r(n=6):
    def reader():
        yield from range(n)
    return reader


def test_reader_decorators_compose():
    rd = paddle.reader
    assert list(rd.firstn(_r(), 3)()) == [0, 1, 2]
    assert list(rd.chain(_r(2), _r(3))()) == [0, 1, 0, 1, 2]
    assert list(rd.map_readers(lambda a, b: a + b, _r(3), _r(3))()) == [0, 2, 4]
    assert sorted(rd.shuffle(_r(), 4)()) == list(range(6))
    assert list(rd.buffered(_r(), 2)()) == list(range(6))
    got = list(rd.compose(_r(3), _r(3))())
    assert got == [(0, 0), (1, 1), (2, 2)]
    with pytest.raises(ValueError, match="different lengths"):
        list(rd.compose(_r(2), _r(4))())
    # cache: second pass replays without re-running the source
    calls = []
    def counting():
        calls.append(1)
        yield from range(3)
    c = rd.cache(counting)
    assert list(c()) == [0, 1, 2] and list(c()) == [0, 1, 2]
    assert len(calls) == 1


def test_xmap_readers_ordered_and_unordered():
    rd = paddle.reader
    out = list(rd.xmap_readers(lambda x: x * 10, _r(8), 3, 4, order=True)())
    assert out == [x * 10 for x in range(8)]
    out2 = sorted(rd.xmap_readers(lambda x: x * 10, _r(8), 3, 4)())
    assert out2 == [x * 10 for x in range(8)]
    merged = sorted(rd.multiprocess_reader([_r(3), _r(4)])())
    assert merged == sorted([*range(3), *range(4)])


def test_hub_local_source(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "def tiny_model(scale=1):\n"
        "    '''A tiny test model.'''\n"
        "    return {'scale': scale}\n"
        "def _private():\n"
        "    pass\n")
    assert paddle.hub.list(str(tmp_path), source="local") == ["tiny_model"]
    assert "tiny test model" in paddle.hub.help(str(tmp_path), "tiny_model",
                                                source="local")
    assert paddle.hub.load(str(tmp_path), "tiny_model", source="local",
                           scale=3) == {"scale": 3}
    with pytest.raises(RuntimeError, match="network access"):
        paddle.hub.load("some/repo", "m", source="github")
    with pytest.raises(RuntimeError, match="not found"):
        paddle.hub.load(str(tmp_path), "missing", source="local")


def test_sysconfig_paths():
    inc = paddle.sysconfig.get_include()
    assert os.path.isdir(inc) and any(
        f.endswith(".h") for f in os.listdir(inc))
    assert isinstance(paddle.sysconfig.get_lib(), str)


def test_pir_names_resolve():
    assert paddle.pir.is_pir_mode()
    prog = paddle.static.Program()
    assert paddle.pir.translate_to_pir(prog) is prog
    assert paddle.pir.Program is paddle.static.Program


def test_dataset_mnist_and_uci_readers(tmp_path):
    """paddle.dataset legacy reader tier adapts the class datasets
    (reference mnist.py normalization: [0,255] -> [-1,1] flat float32)."""
    import gzip
    import struct

    n = 4
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (n, 28, 28)).astype(np.uint8)
    labels = rng.integers(0, 10, n).astype(np.uint8)
    ip = str(tmp_path / "imgs.idx3-ubyte.gz")
    lp = str(tmp_path / "labels.idx1-ubyte.gz")
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28) + imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, n) + labels.tobytes())
    samples = list(paddle.dataset.mnist.train(image_path=ip,
                                              label_path=lp)())
    assert len(samples) == n
    x, y = samples[0]
    assert x.shape == (784,) and x.dtype == np.float32
    assert -1.0 <= x.min() and x.max() <= 1.0 and y == int(labels[0])

    raw = rng.normal(size=(50, 14))
    hp = str(tmp_path / "housing.data")
    np.savetxt(hp, raw)
    rows = list(paddle.dataset.uci_housing.train(data_file=hp)())
    assert len(rows) == 40 and rows[0][0].shape == (13,)

    with pytest.raises(RuntimeError, match="network access"):
        paddle.dataset.common.download("http://x/y.tgz", "mnist")


def test_reader_error_propagation_and_cache_integrity():
    rd = paddle.reader

    def bad():
        yield 1
        raise IOError("disk died")

    with pytest.raises(IOError, match="disk died"):
        list(rd.buffered(bad, 2)())
    with pytest.raises(IOError, match="disk died"):
        list(rd.multiprocess_reader([bad])())
    with pytest.raises(ZeroDivisionError):
        list(rd.xmap_readers(lambda x: 1 // x, _r(4), 2, 2)())
    with pytest.raises(IOError, match="disk died"):
        list(rd.xmap_readers(lambda x: x, bad, 2, 2, order=True)())

    # compose alignment check must survive numpy-array samples
    def np_r():
        for i in range(3):
            yield np.ones(4) * i
    got = list(rd.compose(np_r, np_r)())
    assert len(got) == 3 and len(got[0]) == 2

    # an abandoned first pass must not poison the cache
    c = rd.cache(_r(6))
    assert list(rd.firstn(c, 2)()) == [0, 1]
    assert list(c()) == [0, 1, 2, 3, 4, 5]
    assert list(c()) == [0, 1, 2, 3, 4, 5]


def test_imdb_reader_honors_caller_word_idx(tmp_path):
    import tarfile

    root = tmp_path / "aclImdb" / "train"
    (root / "pos").mkdir(parents=True)
    (root / "neg").mkdir(parents=True)
    (root / "pos" / "0.txt").write_text("good good film")
    (root / "neg" / "0.txt").write_text("bad film")
    arc = tmp_path / "aclImdb_v1.tar.gz"
    with tarfile.open(arc, "w:gz") as t:
        t.add(tmp_path / "aclImdb", arcname="aclImdb")
    word_idx = {"good": 7, "film": 3, "<unk>": 9}
    rows = list(paddle.dataset.imdb.train(word_idx, data_file=str(arc))())
    assert ([7, 7, 3], 0) in rows      # encoded with the CALLER's ids
    assert ([9, 3], 1) in rows         # oov -> caller's <unk>
