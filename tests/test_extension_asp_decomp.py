"""Tests for cpp_extension custom ops, ASP n:m sparsity, and program
decomposition (reference models: test/custom_op/, test/asp/,
test/prim/ + test/deprecated/ir/pir/test_decomp.py)."""

import os
import tempfile
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def npv(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


class TestCppExtension:
    @pytest.fixture(scope="class")
    def custom_mod(self):
        from paddle_tpu.utils import cpp_extension

        src = textwrap.dedent("""
            #include "paddle_tpu_ext.h"
            #include <cmath>
            extern "C" int custom_relu(const PTExtTensor* ins, int n_in,
                                       PTExtTensor* outs, int n_out) {
              const float* x = (const float*)ins[0].data;
              float* y = (float*)outs[0].data;
              int64_t n = pt_numel(&ins[0]);
              for (int64_t i = 0; i < n; ++i) y[i] = x[i] > 0 ? x[i] : 0;
              return 0;
            }
            // grad(ins: x, out, out_grad) -> x_grad
            extern "C" int custom_relu_grad(const PTExtTensor* ins, int n_in,
                                            PTExtTensor* outs, int n_out) {
              const float* x = (const float*)ins[0].data;
              const float* gy = (const float*)ins[2].data;
              float* gx = (float*)outs[0].data;
              int64_t n = pt_numel(&ins[0]);
              for (int64_t i = 0; i < n; ++i) gx[i] = x[i] > 0 ? gy[i] : 0;
              return 0;
            }
        """)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "custom_relu.cc")
            with open(path, "w") as f:
                f.write(src)
            yield cpp_extension.load(name="custom_relu", sources=[path])

    def test_forward(self, custom_mod):
        x = np.array([-1.0, 2.0, -3.0, 4.0], np.float32)
        out = custom_mod.custom_relu(paddle.to_tensor(x))
        np.testing.assert_allclose(npv(out), [0, 2, 0, 4])

    def test_forward_under_jit(self, custom_mod):
        import jax

        f = jax.jit(lambda x: custom_mod.custom_relu(paddle.to_tensor(x))._value)
        out = f(np.array([[-1.0, 5.0]], np.float32))
        np.testing.assert_allclose(np.asarray(out), [[0, 5]])

    def test_custom_grad(self, custom_mod):
        import jax
        import jax.numpy as jnp

        # float32 explicitly: the framework enables x64, so bare python
        # floats would build a float64 array the f32-only C op misreads
        x = jnp.array([-1.0, 2.0, -3.0, 4.0], jnp.float32)
        g = jax.grad(lambda v: jnp.sum(custom_mod.custom_relu(v)._value * 2))(x)
        np.testing.assert_allclose(np.asarray(g), [0, 2, 0, 2])

    def test_cuda_extension_rejects_cu(self):
        from paddle_tpu.utils import cpp_extension

        with pytest.raises(ValueError, match="Pallas"):
            cpp_extension.CUDAExtension(sources=["kernel.cu"])

    def test_build_error_surfaces_compiler_output(self):
        from paddle_tpu.utils import cpp_extension

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "bad.cc")
            with open(path, "w") as f:
                f.write("this is not C++")
            with pytest.raises(RuntimeError, match="build failed"):
                cpp_extension.load(name="bad_op", sources=[path])


class TestASP:
    def test_create_mask_2_4(self):
        from paddle_tpu.incubate import asp

        rng = np.random.default_rng(0)
        w = paddle.to_tensor(rng.normal(size=(8, 16)).astype(np.float32))
        mask = asp.create_mask(w)
        m = npv(mask)
        assert asp.check_sparsity(mask)
        # keeps exactly the 2 largest |w| per group of 4
        groups = npv(w).reshape(8, 4, 4)
        mg = m.reshape(8, 4, 4)
        for i in range(8):
            for g in range(4):
                kept = set(np.nonzero(mg[i, g])[0])
                top2 = set(np.argsort(-np.abs(groups[i, g]))[:2])
                assert kept == top2

    def test_prune_model_and_decorate(self):
        import paddle_tpu.optimizer as opt
        from paddle_tpu.incubate import asp

        paddle.seed(0)
        asp.ASPHelper.reset()

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(16, 32)
                self.fc2 = nn.Linear(32, 4)
                self.head = nn.Linear(4, 1)  # not 4-divisible → skipped

            def forward(self, x):
                return self.head(self.fc2(paddle.tanh(self.fc1(x))))

        model = Net()
        masks = asp.prune_model(model)
        assert len(masks) >= 2
        d = asp.calculate_density(model.fc1.weight)
        assert abs(d - 0.5) < 1e-6

        assert "head.weight" not in masks
        optimizer = asp.decorate(opt.SGD(0.05, parameters=model.parameters()))
        x, y = paddle.randn([8, 16]), paddle.randn([8, 1])
        for _ in range(5):
            loss = paddle.mean((model(x) - y) ** 2)
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
        # sparsity survives training steps
        assert abs(asp.calculate_density(model.fc1.weight) - 0.5) < 1e-6

    def test_minimize_reapplies_masks(self):
        import paddle_tpu.optimizer as opt
        from paddle_tpu.incubate import asp

        paddle.seed(1)
        asp.ASPHelper.reset()

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, x):
                return self.fc(x)

        model = Net()
        asp.prune_model(model)
        optimizer = asp.decorate(opt.SGD(0.1, parameters=model.parameters()))
        x, y = paddle.randn([4, 8]), paddle.randn([4, 8])
        loss = paddle.mean((model(x) - y) ** 2)
        optimizer.minimize(loss)
        assert abs(asp.calculate_density(model.fc.weight) - 0.5) < 1e-6

    def test_mask_2d_greedy(self):
        from paddle_tpu.incubate import asp

        rng = np.random.default_rng(2)
        w = paddle.to_tensor(rng.normal(size=(8, 8)).astype(np.float32))
        m = npv(asp.create_mask(w, func_name="mask_2d_greedy"))
        # per 4x4 tile: every row and column has exactly 2 kept entries
        for i in range(2):
            for j in range(2):
                tile = m[4*i:4*i+4, 4*j:4*j+4]
                assert (tile.sum(0) == 2).all() and (tile.sum(1) == 2).all()
        with pytest.raises(ValueError, match="unknown mask"):
            asp.create_mask(w, func_name="nope")

    def test_incubate_namespace(self):
        import paddle_tpu

        assert hasattr(paddle_tpu.incubate, "asp")

    def test_excluded_layers(self):
        from paddle_tpu.incubate import asp

        asp.ASPHelper.reset()

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, x):
                return self.fc(x)

        model = Net()
        name = dict(model.named_parameters()).keys()
        asp.set_excluded_layers(list(name))
        masks = asp.prune_model(model)
        assert masks == {}
        asp.reset_excluded_layers()


class TestDecomposition:
    def _capture(self, fn, *feeds):
        from paddle_tpu.static.program import Program, program_guard

        prog = Program()
        with program_guard(prog):
            vars_in = []
            for f in feeds:
                v = prog.new_var(None)
                import jax

                v._value = jax.ShapeDtypeStruct(f.shape, f.dtype)
                prog.add_feed(v)
                vars_in.append(v)
            out = fn(*vars_in)
        return prog, vars_in, out

    def test_softmax_decomposes_to_primitives(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu import decomposition
        from paddle_tpu.static.program import Program, program_guard
        import jax

        prog = Program()
        with program_guard(prog):
            v = prog.new_var(jax.ShapeDtypeStruct((4, 8), np.float32))
            prog.add_feed(v)
            out = F.softmax(v, axis=-1)
        types_before = [op.type for op in prog.global_block().ops]
        assert types_before == ["softmax"]
        decomposition.decompose(prog)
        types_after = [op.type for op in prog.global_block().ops]
        assert "softmax" not in types_after
        assert len(types_after) > 1  # exp/sub/reduce/div chain
        # numerics preserved, same fetch variable
        run, _, _ = prog.as_function([out._vid], feed_vids=[v._vid], state_vids=[])
        x = np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32)
        (res,), _ = run([x], [])
        ref = np.exp(x - x.max(-1, keepdims=True))
        ref = ref / ref.sum(-1, keepdims=True)
        np.testing.assert_allclose(np.asarray(res), ref, rtol=1e-5)

    def test_gelu_decompose_numerics(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu import decomposition
        from paddle_tpu.static.program import Program, program_guard
        import jax
        import jax.numpy as jnp

        prog = Program()
        with program_guard(prog):
            v = prog.new_var(jax.ShapeDtypeStruct((10,), np.float32))
            prog.add_feed(v)
            out = F.gelu(v)
        decomposition.decompose(prog)
        assert all(op.type != "gelu" for op in prog.global_block().ops)
        run, _, _ = prog.as_function([out._vid], feed_vids=[v._vid], state_vids=[])
        x = np.linspace(-3, 3, 10).astype(np.float32)
        (res,), _ = run([x], [])
        np.testing.assert_allclose(np.asarray(res), np.asarray(jax.nn.gelu(x, approximate=False)), rtol=1e-5)

    def test_whitelist_blacklist(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu import decomposition
        from paddle_tpu.static.program import Program, program_guard
        import jax

        prog = Program()
        with program_guard(prog):
            v = prog.new_var(jax.ShapeDtypeStruct((4,), np.float32))
            prog.add_feed(v)
            h = F.softmax(v)
            out = F.gelu(h)
        decomposition.decompose(prog, blacklist=["gelu"])
        types = [op.type for op in prog.global_block().ops]
        assert "gelu" in types and "softmax" not in types
