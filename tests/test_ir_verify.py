"""IR verifier + pass-differential checker (static/verify.py).

Each structural violation class gets a minimal failing Program and a
passing twin; the differential harness is proven on a resurrected
transpose-blind MatmulEpilogue fusion (the PR-2 bug, caught mechanically
here instead of by review); the PatternRewritePass use-def guard refuses
rewrites that consume values the fetch frontier still needs; and the
side-effect-aware DCE keeps RNG ops alive."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.static as static
from paddle_tpu.static.program import Operator, Program, program_guard
from paddle_tpu.static.rewrite import (
    MatmulEpiloguePattern,
    PallasFusionPass,
    PatternRewritePass,
    ProgramGraph,
    RewritePattern,
    _make_op,
)
from paddle_tpu.static.verify import (
    DifferentialError,
    ProgramVerifier,
    VerificationError,
    differential_check,
    track_programs,
    verify_program,
    verify_stats,
)

_SINGLE = jax.tree_util.tree_structure(0)


def _codes(violations):
    return {v.code for v in violations}


def _feed(prog, name, shape, dtype=np.float32):
    v = prog.new_var(jax.ShapeDtypeStruct(tuple(shape), dtype), name)
    prog.add_feed(v)
    return v


# --------------------------------------------------------------- unit tests


def test_clean_program_verifies():
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (2, 3))
        y = paddle.sum(paddle.tanh(x) * 2.0)
    assert ProgramVerifier().verify(prog, [y._vid]) == []


def test_dangling_vid_detected():
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (2,))
        y = paddle.exp(x)
    # twin passes
    assert verify_program(prog, [y._vid]) == []
    # rewire the op to read a vid nothing defines
    ghost = prog.new_var(jax.ShapeDtypeStruct((2,), np.float32), "ghost")
    op = prog.global_block().ops[0]
    op.arg_spec[0] = ("var", ghost._vid)
    bad = ProgramVerifier().verify(prog, [y._vid])
    assert "dangling-vid" in _codes(bad)


def test_dangling_fetch_detected():
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (2,))
        y = paddle.exp(x)
    orphan = prog.new_var(jax.ShapeDtypeStruct((2,), np.float32), "orphan")
    bad = ProgramVerifier().verify(prog, [y._vid, orphan._vid])
    assert "dangling-fetch" in _codes(bad)
    with pytest.raises(VerificationError, match="dangling-fetch"):
        verify_program(prog, [orphan._vid])


def test_unknown_op_type_detected():
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (2,))
        y = paddle.exp(x)
    prog.global_block().ops[0].type = "definitely_not_registered"
    bad = ProgramVerifier().verify(prog, [y._vid])
    assert "unknown-op-type" in _codes(bad)
    # namespaced spellings of REAL ops resolve (pass-rewritten programs)
    prog.global_block().ops[0].type = "wq::fp16::exp"
    assert ProgramVerifier().verify(prog, [y._vid]) == []


def test_missing_required_kwargs_detected():
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (4, 4))
        w = _feed(prog, "w", (4, 4))
        y = paddle.matmul(x, w, transpose_y=True)
    assert verify_program(prog, [y._vid]) == []  # twin: kwargs recorded
    mm = prog.global_block().ops[0]
    mm.kwargs.pop("transpose_y")
    bad = ProgramVerifier().verify(prog, [y._vid])
    assert "missing-kwargs" in _codes(bad)


def test_shape_and_dtype_mismatch_detected():
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (2, 3))
        y = paddle.tanh(x)
    op = prog.global_block().ops[0]
    op.fn = lambda v: jnp.zeros((5, 5), jnp.float32)  # rewrite changed shape
    bad = ProgramVerifier().verify(prog, [y._vid])
    assert "shape-mismatch" in _codes(bad)
    op.fn = lambda v: jnp.zeros((2, 3), jnp.int32)  # rewrite changed dtype
    bad = ProgramVerifier().verify(prog, [y._vid])
    assert "dtype-mismatch" in _codes(bad)
    op.fn = lambda v: (v, v)  # rewrite changed arity
    bad = ProgramVerifier().verify(prog, [y._vid])
    assert "arity-mismatch" in _codes(bad)


def _two_producer_program():
    """op1 produces (t, aux); share_loss-style alias re-binds t from u.
    With aux fetched, op1 cannot be pruned — so whether the program is
    legal depends on whether anything reads op1's t before the re-bind
    (the PR-2 executor-prune invariant, hand-built)."""
    prog = Program()
    x = _feed(prog, "x", (3,))
    t = prog.new_var(jax.ShapeDtypeStruct((3,), np.float32), "t")
    aux = prog.new_var(jax.ShapeDtypeStruct((3,), np.float32), "aux")
    u = prog.new_var(jax.ShapeDtypeStruct((3,), np.float32), "u")
    pair = jax.tree_util.tree_structure((0, 0))
    prog.global_block().ops.append(Operator(
        "grad", lambda v: (jnp.tanh(v), jnp.exp(v)), [("var", x._vid)], {},
        [t._vid, aux._vid], pair))
    prog.global_block().ops.append(Operator(
        "exp", jnp.exp, [("var", x._vid)], {}, [u._vid], _SINGLE))
    prog.global_block().ops.append(Operator(
        "share_loss", lambda v: v, [("var", u._vid)], {}, [t._vid], _SINGLE))
    prog.version += 1
    return prog, t, aux


def test_duplicate_live_producer_detected():
    """Two live producers of one vid reaching the fetch frontier — the
    executor-prune invariant PR 2 fixed, now checked mechanically."""
    prog, t, aux = _two_producer_program()
    bad = ProgramVerifier(abstract_eval=False).verify(prog, [t._vid, aux._vid])
    assert "duplicate-producer" in _codes(bad)

    # passing twin: a reader of op1's t BEFORE the re-bind makes the
    # earlier definition live-by-read (read-then-rebind is legal)
    prog2, t2, aux2 = _two_producer_program()
    r = prog2.new_var(jax.ShapeDtypeStruct((), np.float32), "r")
    reader = Operator("sum", jnp.sum, [("var", t2._vid)], {}, [r._vid], _SINGLE)
    prog2.global_block().ops.insert(1, reader)
    prog2.version += 1
    assert ProgramVerifier(abstract_eval=False).verify(
        prog2, [t2._vid, aux2._vid, r._vid]) == []


def test_bad_write_detected():
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (2,))
        y = paddle.exp(x)
    prog.writes[y._vid] = 987654  # source vid never defined
    bad = ProgramVerifier().verify(prog, [y._vid])
    assert "bad-write" in _codes(bad)


# ------------------------------------------------------ differential checker


def _gelu_matmul_program(transpose_y):
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (4, 4))
        w = _feed(prog, "w", (4, 4))
        y = F.gelu(paddle.matmul(x, w, transpose_y=transpose_y))
    return prog, y


def test_differential_catches_transpose_blind_epilogue_fusion():
    """Re-introduce the PR-2 MatmulEpilogue bug (fusing x @ w.T as x @ w —
    square weight, so no shape check can catch it) as a fixture pattern:
    the verifier's abstract eval passes, the differential checker fails."""
    prog, y = _gelu_matmul_program(transpose_y=True)
    ref = prog.clone()
    graph = ProgramGraph(prog, [y._vid])
    root = next(op for op in prog.global_block().ops if op.type == "gelu")
    mm = graph.def_op(root.arg_spec[0][1])
    x_vid, w_vid = mm.arg_spec[0][1], mm.arg_spec[1][1]

    def blind(xv, wv):  # the old pattern's kernel: transpose dropped
        return jax.nn.gelu(xv @ wv, approximate=False)

    graph.replace_op(root, _make_op("matmul_epilogue", blind, [x_vid, w_vid], root))

    # structurally valid — shapes/dtypes/arity all agree (square weight)
    assert ProgramVerifier().verify(prog, [y._vid]) == []
    # ... but numerically wrong: only the differential replay catches it
    bad = differential_check(ref, prog, [y._vid], raise_on_error=False)
    assert bad and _codes(bad) == {"differential-mismatch"}
    with pytest.raises(DifferentialError):
        differential_check(ref, prog, [y._vid])


def test_current_epilogue_pattern_refuses_transpose_and_passes_differential():
    prog, y = _gelu_matmul_program(transpose_y=True)
    ref = prog.clone()
    n = PatternRewritePass([MatmulEpiloguePattern()], [y._vid]).apply(prog)
    assert n == 0  # bails on the recorded transpose kwarg
    assert differential_check(ref, prog, [y._vid], raise_on_error=False) == []

    # and the untransposed twin both fuses AND stays numerically identical
    prog2, y2 = _gelu_matmul_program(transpose_y=False)
    ref2 = prog2.clone()
    n = PatternRewritePass([MatmulEpiloguePattern()], [y2._vid]).apply(prog2)
    assert n == 1
    assert differential_check(ref2, prog2, [y2._vid], raise_on_error=False) == []


def test_differential_catches_crashing_rewrite():
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (2, 2))
        y = paddle.tanh(x)
    ref = prog.clone()

    def broken(v):
        raise RuntimeError("broken kernel")

    old = prog.global_block().ops[0]
    prog.global_block().ops[0] = Operator("tanh", broken, list(old.arg_spec),
                                          {}, list(old.out_vids), old.out_tree)
    prog.version += 1
    bad = differential_check(ref, prog, [y._vid], raise_on_error=False)
    assert "differential-crash" in _codes(bad)


# ------------------------------------------- interior-consumer fusion guard


def _attention_program(B=1, N=2, S=32, D=8):
    prog = Program()
    with program_guard(prog):
        q = _feed(prog, "q", (B, N, S, D))
        k = _feed(prog, "k", (B, N, S, D))
        v = _feed(prog, "v", (B, N, S, D))
        probs = F.softmax(paddle.matmul(q, k, transpose_y=True) / (D ** 0.5),
                          axis=-1)
        attn = paddle.matmul(probs, v)
    return prog, probs, attn


def test_stock_patterns_refuse_when_intermediate_is_fetched():
    """An interior matched var in the fetch list blocks fusion (satellite
    regression: intermediate also fetched)."""
    prog, probs, attn = _attention_program()
    n = PallasFusionPass([attn._vid, probs._vid]).apply(prog)
    assert n == 0
    assert "flash_attention" not in [op.type for op in prog.global_block().ops]

    # twin: without the intermediate fetch the same program fuses
    prog2, probs2, attn2 = _attention_program()
    n = PallasFusionPass([attn2._vid]).apply(prog2)
    assert n == 1
    assert "flash_attention" in [op.type for op in prog2.global_block().ops]


class _EatsInterior(RewritePattern):
    """Adversarial pattern: consumes the softmax producer outright — what a
    buggy/aggressive pattern could do.  The driver's use-def guard must
    roll it back whenever the eaten var is still needed."""

    name = "eats_interior"
    root_type = "matmul"

    def match_and_rewrite(self, op, graph):
        if len(op.arg_spec) != 2 or any(s[0] != "var" for s in op.arg_spec):
            return False
        sm = graph.def_op(op.arg_spec[0][1], "softmax")
        if sm is None:
            return False
        scores_vid = sm.arg_spec[0][1]

        def fused(scores, v):
            return jax.nn.softmax(scores, axis=-1) @ v

        graph.replace_op(op, _make_op(
            "flash_attention", fused, [scores_vid, op.arg_spec[1][1]], op))
        graph.block.ops.remove(sm)  # removes the probs producer
        graph.program.version += 1
        return True


def test_driver_rolls_back_rewrite_that_eats_a_fetched_interior():
    prog, probs, attn = _attention_program()
    before = [op.type for op in prog.global_block().ops]
    drv = PatternRewritePass([_EatsInterior()], [attn._vid, probs._vid])
    assert drv.apply(prog) == 0
    assert drv.refused >= 1
    assert [op.type for op in prog.global_block().ops] == before  # rolled back

    # twin: interior NOT fetched → the same rewrite is accepted
    prog2, probs2, attn2 = _attention_program()
    drv2 = PatternRewritePass([_EatsInterior()], [attn2._vid])
    assert drv2.apply(prog2) == 1
    assert drv2.refused == 0
    types = [op.type for op in prog2.global_block().ops]
    assert "flash_attention" in types and "softmax" not in types


def test_generic_elementwise_fusion_respects_fetch_frontier():
    """A fetched interior value must survive chain fusion — the invariant
    the export path relies on by forwarding its fetch set to the fusion
    passes (static/io.py)."""
    from paddle_tpu.static.rewrite import GenericElementwiseFusionPass

    def build():
        prog = Program()
        with program_guard(prog):
            x = _feed(prog, "x", (8,))
            mid = paddle.tanh(paddle.exp(x) * 2.0)   # interior of the chain
            out = paddle.sqrt(paddle.abs(mid) + 1.0)
        return prog, mid, out

    prog, mid, out = build()
    GenericElementwiseFusionPass([out._vid, mid._vid], min_chain=2).apply(prog)
    assert verify_program(prog, [out._vid, mid._vid]) == []
    defined = set(prog.param_inits) | {v._vid for v in prog.feed_vars}
    for op in prog.global_block().ops:
        defined.update(op.out_vids)
    assert mid._vid in defined  # the fetched intermediate kept a producer

    # twin: with only the final fetch the whole chain fuses into one kernel
    prog2, mid2, out2 = build()
    n = GenericElementwiseFusionPass([out2._vid], min_chain=2).apply(prog2)
    assert n >= 1
    assert any(op.type.startswith("vpu_chain_")
               for op in prog2.global_block().ops)


# --------------------------------------------------- side-effect-aware DCE


def test_dce_keeps_side_effect_ops():
    from paddle_tpu.static.passes import DeadCodeEliminationPass

    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (8,))
        dropped = F.dropout(x, 0.5, training=True)  # RNG op, never fetched
        dead = x + 100.0                            # pure op, never fetched
        y = paddle.sum(x * 2.0)
    types_before = [op.type for op in prog.global_block().ops]
    assert "dropout" in types_before
    removed = DeadCodeEliminationPass([y._vid]).apply(prog)
    types = [op.type for op in prog.global_block().ops]
    # the pure dead chain goes; the RNG op stays (eliminating it would
    # shift every later op's key sequence — the old code path pruned it)
    assert removed >= 1
    assert "dropout" in types
    assert "add" not in [t for t in types]  # dead = x + 100 pruned


def test_dce_still_prunes_pure_ops():
    from paddle_tpu.static.passes import dead_code_elimination

    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (4,))
        dead1 = x + 100.0
        dead2 = dead1 * dead1
        y = paddle.sum(x)
    assert dead_code_elimination(prog, [y]) >= 2


# ----------------------------------------------------- verify-mode wiring


def _flag(name, value):
    paddle.set_flags({name: value})


def test_executor_verify_mode_runs_differential_on_live_feed():
    rng = np.random.default_rng(0)
    _flag("FLAGS_verify_programs", True)
    try:
        base = verify_stats()
        prog, probs, attn = _attention_program()
        exe = static.Executor()
        feed = {n: rng.normal(size=(1, 2, 32, 8)).astype(np.float32)
                for n in ("q", "k", "v")}
        (out,) = exe.run(prog, feed=feed, fetch_list=[attn])
        q, k, v = feed["q"], feed["k"], feed["v"]
        scores = (q @ np.swapaxes(k, -1, -2)) / np.sqrt(8.0)
        ref = jax.nn.softmax(scores, axis=-1) @ v
        np.testing.assert_allclose(out, np.asarray(ref), rtol=2e-3, atol=2e-3)
        stats = verify_stats()
        assert stats["differential_checks"] > base["differential_checks"]
        assert stats["differential_failures"] == base["differential_failures"]
        assert stats["programs_failed"] == base["programs_failed"]
    finally:
        _flag("FLAGS_verify_programs", False)


def test_pass_manager_verifies_between_passes():
    from paddle_tpu.static.passes import ProgramPass, ProgramPassManager

    class _Corruptor(ProgramPass):
        name = "corruptor"

        def apply(self, program):
            op = program.global_block().ops[0]
            op.arg_spec[0] = ("var", 424242)  # dangling read
            program.version += 1
            return 1

    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (2,))
        y = paddle.exp(x)
    _flag("FLAGS_verify_programs", True)
    try:
        with pytest.raises(VerificationError, match="corruptor"):
            ProgramPassManager([_Corruptor()], fetch_vids=[y._vid]).run(prog)
    finally:
        _flag("FLAGS_verify_programs", False)


def test_verify_flag_off_keeps_pass_manager_silent():
    from paddle_tpu.static.passes import ProgramPassManager

    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (2,))
        y = paddle.exp(x)
    assert ProgramPassManager([], fetch_vids=[y._vid]).run(prog) == 0


# ------------------------------------------------------- tier-1 property


def test_every_traced_program_verifies_with_fusion_on():
    """Property: every Program the canonical static paths build — capture,
    training step, control flow, executor-fused attention — passes
    verification with the fusion pipeline on."""
    paddle.seed(0)
    rng = np.random.default_rng(0)
    verifier = ProgramVerifier()
    with track_programs() as programs:
        # capture + run
        main = static.Program()
        with program_guard(main):
            x = static.data("px", [2, 3], "float32")
            y = paddle.sum(paddle.add(x, x) * 2.0)
        static.Executor().run(main, feed={"px": np.ones((2, 3), np.float32)},
                              fetch_list=[y])

        # training step
        layer = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=layer.parameters())
        train = static.Program()
        with program_guard(train):
            xt = static.data("tx", [8, 4], "float32")
            yt = static.data("ty", [8, 2], "float32")
            loss = paddle.mean((layer(xt) - yt) ** 2)
            opt.minimize(loss)
        exe = static.Executor()
        with static.scope_guard(static.Scope()):
            exe.run(train,
                    feed={"tx": rng.normal(size=(8, 4)).astype(np.float32),
                          "ty": rng.normal(size=(8, 2)).astype(np.float32)},
                    fetch_list=[loss])

        # fused attention through the executor pipeline (fusion flag is on
        # by default)
        att, probs, attn = _attention_program()
        static.Executor().run(
            att,
            feed={n: rng.normal(size=(1, 2, 32, 8)).astype(np.float32)
                  for n in ("q", "k", "v")},
            fetch_list=[attn])
        assert "flash_attention" in [op.type for op in att.global_block().ops]

    assert len(programs) >= 3
    for prog in programs:
        violations = verifier.verify(prog)
        assert violations == [], (
            f"program with ops {[op.type for op in prog.global_block().ops]} "
            f"failed verification: {[str(v) for v in violations]}")
