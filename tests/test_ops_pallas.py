"""Pallas kernel library numerics (interpret mode on the CPU test mesh) —
SURVEY.md §4 OpTest analog: each kernel vs a jnp oracle, fwd + grads."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops
from paddle_tpu.ops.flash_attention import flash_attention, flash_attention_reference


def _rand(*shape, dtype=np.float32, seed=0):
    return np.random.default_rng(seed).standard_normal(shape).astype(dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_forward(causal):
    b, s, n, h = 1, 256, 2, 64
    q, k, v = (jnp.asarray(_rand(b, s, n, h, seed=i)) for i in range(3))
    out = flash_attention(q, k, v, causal=causal)
    ref = flash_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_attention_gqa():
    b, s, n, nkv, h = 1, 256, 4, 2, 64
    q = jnp.asarray(_rand(b, s, n, h, seed=0))
    k = jnp.asarray(_rand(b, s, nkv, h, seed=1))
    v = jnp.asarray(_rand(b, s, nkv, h, seed=2))
    out = flash_attention(q, k, v, causal=True)
    ref = flash_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_attention_grads():
    b, s, n, h = 1, 128, 2, 64
    q, k, v = (jnp.asarray(_rand(b, s, n, h, seed=i)) for i in range(3))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(flash_attention_reference(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4, rtol=1e-4)


def test_fused_rms_norm_matches_reference():
    x = jnp.asarray(_rand(6, 256))
    w = jnp.asarray(_rand(256, seed=3))

    def ref(x, w, eps=1e-6):
        var = jnp.mean(x * x, -1, keepdims=True)
        return x * jax.lax.rsqrt(var + eps) * w

    np.testing.assert_allclose(
        np.asarray(ops.fused_rms_norm(x, w)), np.asarray(ref(x, w)), atol=1e-5, rtol=1e-5
    )
    g1 = jax.grad(lambda x, w: jnp.sum(ops.fused_rms_norm(x, w) ** 2), argnums=(0, 1))(x, w)
    g2 = jax.grad(lambda x, w: jnp.sum(ref(x, w) ** 2), argnums=(0, 1))(x, w)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_fused_layer_norm_matches_reference():
    x = jnp.asarray(_rand(6, 256))
    w = jnp.asarray(_rand(256, seed=4))
    b = jnp.asarray(_rand(256, seed=5))

    def ref(x, w, b, eps=1e-5):
        mean = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + eps) * w + b

    np.testing.assert_allclose(
        np.asarray(ops.fused_layer_norm(x, w, b)), np.asarray(ref(x, w, b)), atol=1e-5, rtol=1e-5
    )
    g1 = jax.grad(lambda *a: jnp.sum(ops.fused_layer_norm(*a) ** 2), argnums=(0, 1, 2))(x, w, b)
    g2 = jax.grad(lambda *a: jnp.sum(ref(*a) ** 2), argnums=(0, 1, 2))(x, w, b)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4, rtol=1e-4)


def test_fused_rope_matches_model_rope():
    from paddle_tpu.models.llama import _rope_tables

    b, s, n, h = 2, 16, 2, 64
    x = jnp.asarray(_rand(b, s, n, h))
    cos, sin = _rope_tables(h, 32, 10000.0)
    out = ops.fused_rotary_position_embedding(x, cos=cos, sin=sin)

    c = cos[:s][None, :, None, :]
    sn = sin[:s][None, :, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    ref = jnp.stack([x1 * c - x2 * sn, x2 * c + x1 * sn], axis=-1).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)

    # backward = inverse rotation: grad of sum(out * g) wrt x is rope^{-1}(g)
    g = jax.grad(lambda x: jnp.sum(ops.fused_rotary_position_embedding(x, cos=cos, sin=sin) * ref))(x)
    g_ref = jax.grad(lambda x: jnp.sum(
        jnp.stack([x[..., 0::2] * c - x[..., 1::2] * sn, x[..., 1::2] * c + x[..., 0::2] * sn], -1).reshape(x.shape) * ref
    ))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-5, rtol=1e-5)


def test_swiglu():
    x = jnp.asarray(_rand(4, 256))
    y = jnp.asarray(_rand(4, 256, seed=7))
    ref = x * jax.nn.sigmoid(x) * y
    np.testing.assert_allclose(np.asarray(ops.swiglu(x, y)), np.asarray(ref), atol=1e-5, rtol=1e-5)
    g1 = jax.grad(lambda x, y: jnp.sum(ops.swiglu(x, y) ** 2), argnums=(0, 1))(x, y)
    g2 = jax.grad(lambda x, y: jnp.sum((x * jax.nn.sigmoid(x) * y) ** 2), argnums=(0, 1))(x, y)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_incubate_functional_tape():
    """Fused ops through the Tensor tape: forward values + backward flow."""
    import paddle_tpu.incubate.nn.functional as FF

    x = paddle.to_tensor(_rand(4, 256))
    x.stop_gradient = False
    w = paddle.to_tensor(np.ones(256, np.float32))
    w.stop_gradient = False
    out = FF.fused_rms_norm(x, w)
    out.sum().backward()
    assert x.grad is not None and w.grad is not None
    assert out.shape == [4, 256]

    a = paddle.to_tensor(_rand(4, 128, seed=9))
    b = paddle.to_tensor(_rand(128, 64, seed=10))
    c = paddle.to_tensor(_rand(64, seed=11))
    y = FF.fused_matmul_bias(a, b, c)
    ref = np.asarray(a._value) @ np.asarray(b._value) + np.asarray(c._value)
    np.testing.assert_allclose(np.asarray(y._value), ref, atol=1e-5, rtol=1e-5)


def test_masked_multihead_attention_decode():
    """Decode-with-cache equals full attention on the prefix."""
    import paddle_tpu.incubate.nn.functional as FF

    b, n, h, smax = 2, 2, 32, 8
    np.random.seed(0)
    cache = paddle.to_tensor(np.zeros((2, b, n, smax, h), np.float32))
    xs = [_rand(b, 3 * n * h, seed=20 + t) for t in range(4)]
    outs = []
    for t, xv in enumerate(xs):
        out, cache = FF.masked_multihead_attention(
            paddle.to_tensor(xv), cache, num_heads=n, head_dim=h, position_offset=t
        )
        outs.append(np.asarray(out._value))

    # reference: full causal attention over the 4 tokens
    qkv = np.stack(xs).reshape(4, b, 3, n, h)  # [T, B, 3, N, H]
    q = np.moveaxis(qkv[:, :, 0], 0, 1)  # [B, T, N, H]
    k = np.moveaxis(qkv[:, :, 1], 0, 1)
    v = np.moveaxis(qkv[:, :, 2], 0, 1)
    ref = np.asarray(
        flash_attention_reference(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True)
    )  # [B, T, N, H]
    for t in range(4):
        np.testing.assert_allclose(outs[t], ref[:, t].reshape(b, n * h), atol=1e-4, rtol=1e-4)


def test_fused_rope_position_ids():
    from paddle_tpu.models.llama import _rope_tables

    b, s, n, h = 2, 8, 2, 32
    x = jnp.asarray(_rand(b, s, n, h, seed=30))
    cos, sin = _rope_tables(h, 64, 10000.0)
    pids = jnp.asarray(np.array([[5, 6, 7, 8, 9, 10, 11, 12], [0, 1, 2, 3, 4, 5, 6, 7]]))
    out = ops.fused_rotary_position_embedding(x, cos=cos, sin=sin, position_ids=pids)

    c = cos[np.asarray(pids).reshape(-1)].reshape(b, s, 1, h // 2)
    sn = sin[np.asarray(pids).reshape(-1)].reshape(b, s, 1, h // 2)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    ref = jnp.stack([x1 * c - x2 * sn, x2 * c + x1 * sn], axis=-1).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# TPU-lowering guards (round-1 regression: kernels only ever ran in interpret
# mode; on the chip, any 64-bit value in a kernel trace makes Mosaic's
# convert-helper recurse forever).  Guard 1 runs everywhere: scan every
# kernel's jaxpr for 64-bit types.  Guard 2 runs only with a real TPU:
# lower+compile each kernel for the chip.
# ---------------------------------------------------------------------------

def _kernel_calls():
    """(name, fn, example ShapeDtypeStruct args) for every Pallas entry."""
    import importlib

    # the ops package re-exports functions under the kernel-module names, so
    # attribute imports resolve to functions; go through importlib instead
    fused_norm = importlib.import_module("paddle_tpu.ops.fused_norm")
    swiglu_mod = importlib.import_module("paddle_tpu.ops.swiglu")
    fa = importlib.import_module("paddle_tpu.ops.flash_attention")

    bf = jnp.bfloat16
    B, S, N, H = 2, 256, 4, 64
    qs = jax.ShapeDtypeStruct((B, S, N, H), bf)
    x2 = jax.ShapeDtypeStruct((B * S, N * H), bf)
    w = jax.ShapeDtypeStruct((N * H,), bf)
    calls = []
    for causal in (False, True):
        calls.append((
            f"flash_fwd_causal{causal}",
            lambda q, k, v, c=causal: fa.flash_attention(q, k, v, causal=c),
            (qs, qs, qs),
        ))
        calls.append((
            f"flash_grad_causal{causal}",
            lambda q, k, v, c=causal: jax.grad(
                lambda a, b_, c_: fa.flash_attention(a, b_, c_, causal=c).astype(jnp.float32).sum(),
                argnums=(0, 1, 2),
            )(q, k, v),
            (qs, qs, qs),
        ))
    calls.append(("rms_norm", lambda x, w_: fused_norm.fused_rms_norm(x, w_), (x2, w)))
    calls.append((
        "rms_norm_grad",
        lambda x, w_: jax.grad(lambda a: fused_norm.fused_rms_norm(a, w_).astype(jnp.float32).sum())(x),
        (x2, w),
    ))
    calls.append(("layer_norm", lambda x, w_: fused_norm.fused_layer_norm(x, w_, w_), (x2, w)))
    calls.append(("swiglu", lambda x: swiglu_mod.swiglu(x, x), (x2,)))
    calls.append((
        "swiglu_grad",
        lambda x: jax.grad(lambda a: swiglu_mod.swiglu(a, a).astype(jnp.float32).sum())(x),
        (x2,),
    ))
    return calls


@pytest.mark.parametrize("name,fn,args", _kernel_calls(), ids=lambda v: v if isinstance(v, str) else "")
def test_kernel_jaxpr_no_64bit(name, fn, args):
    import re

    # the jaxpr print embeds function reprs ("<function ... at 0x7eb699f64...>")
    # whose heap addresses can contain "f64"/"i64" by sheer ASLR luck — strip
    # hex literals so only genuine dtype tokens can match
    jaxpr = re.sub(r"0x[0-9a-f]+", "0xADDR", str(jax.make_jaxpr(fn)(*args)))
    for bad in ("i64", "f64", "u64", "c128"):
        assert bad not in jaxpr, f"{name}: {bad} value in kernel trace breaks Mosaic lowering"


@pytest.mark.skipif(jax.default_backend() != "tpu", reason="needs real TPU")
@pytest.mark.parametrize("name,fn,args", _kernel_calls(), ids=lambda v: v if isinstance(v, str) else "")
def test_kernel_compiles_on_tpu(name, fn, args):
    jax.jit(fn).lower(*args).compile()


def test_flash_block_size_flags():
    """FLAGS_flash_block_q/_k apply only when a positive multiple of 8 that
    divides the sequence; anything else keeps the 128 default, and ragged
    lengths still reach the caller's reference fallback."""
    import paddle_tpu as paddle
    from paddle_tpu.ops.flash_attention import _block_sizes

    try:
        assert _block_sizes(1024, 1024) == (128, 128)
        assert _block_sizes(130, 130) == (128, 128)  # 130 % 128 != 0 -> caller falls back
        paddle.set_flags({"FLAGS_flash_block_q": 256, "FLAGS_flash_block_k": 64})
        assert _block_sizes(1024, 1024) == (256, 64)
        paddle.set_flags({"FLAGS_flash_block_q": 0, "FLAGS_flash_block_k": -64})
        assert _block_sizes(1024, 1024) == (128, 128)
        paddle.set_flags({"FLAGS_flash_block_q": 100, "FLAGS_flash_block_k": 128})
        assert _block_sizes(400, 400) == (128, 128)  # 100 not a sublane multiple
    finally:
        paddle.set_flags({"FLAGS_flash_block_q": 0, "FLAGS_flash_block_k": 0})


def test_flash_nondefault_blocks_match_reference():
    import numpy as np
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.ops import flash_attention as fa_fn
    from paddle_tpu.ops.flash_attention import flash_attention_reference

    q = jnp.asarray(np.random.default_rng(0).standard_normal((1, 256, 2, 128)).astype(np.float32))
    try:
        paddle.set_flags({"FLAGS_use_pallas": "true", "FLAGS_flash_block_q": 256, "FLAGS_flash_block_k": 64})
        out = fa_fn(q, q, q, causal=True)
    finally:
        paddle.set_flags({"FLAGS_use_pallas": "auto", "FLAGS_flash_block_q": 0, "FLAGS_flash_block_k": 0})
    ref = flash_attention_reference(q, q, q, causal=True)
    assert float(jnp.abs(out - ref).max()) < 2e-5


def test_flash_causal_cross_length_bottom_right_alignment():
    """Sq != Sk causal must be bottom-right aligned (kv-cache/decode
    convention), matching flash_attention_reference — fwd AND bwd.  The
    kernel previously used top-left (query i sees keys <= i), silently
    wrong for any chunked-prefill / cache-extension call."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.ops.flash_attention import _flash_bnsh

    ffn = flash_attention

    rng = jax.random.PRNGKey(0)
    B, N, H = 1, 2, 8
    Sq, Sk = 128, 256  # block-multiples: the Pallas path, not the fallback
    q, k, v = (jax.random.normal(kk, (B, Sq if i == 0 else Sk, N, H),
                                 jnp.float32)
               for i, kk in enumerate(jax.random.split(rng, 3)))

    out = ffn(q, k, v, causal=True)
    ref = flash_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)

    # bwd: compare flash vjp against autodiff through the reference
    def loss_flash(q, k, v):
        qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        return jnp.sum(_flash_bnsh(qt, kt, vt, H ** -0.5, True, 64, 64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(flash_attention_reference(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=name)
