"""TcpRing unit tier (serving/transport.py — ROADMAP item 1, the
multi-host data plane under docs/SERVING_CLUSTER.md).

Pins the ShmRing producer/consumer contract onto the socket ring:
whole-frame framing round-trips (both directions, empty through large),
torn-frame / partial-read tolerance (a frame dribbled across many TCP
segments assembles invisibly), backpressure-vs-peer-death discipline (a
full ring and a silent wire raise TimeoutError; only a GRACEFUL close
raises BrokenPipeError — connection loss is silence, never a death
verdict), dial-before-listen attach retries, reconnect-after-drop with
at-least-once delivery of the in-flight frame, and endpoint discovery
over the real native TCPStore (the exact path EngineCluster workers
take).  Threads and sockets only — no fork, no engine — so this module
rides the shared tier-1 shard."""

import socket
import struct
import threading
import time

import pytest

from paddle_tpu.serving.transport import (ShmTransport, TcpRing,
                                          TcpTransport, get_transport,
                                          reset_transport_stats,
                                          transport_stats)

_HDR = struct.Struct(">Q")


def _pair(capacity=1 << 20, **attach_kw):
    a = TcpRing("t", capacity, create=True)
    b = TcpRing("t", capacity, create=False,
                endpoint=("127.0.0.1", a.port),
                attach_timeout_ms=5000, **attach_kw)
    return a, b


@pytest.fixture(autouse=True)
def _fresh_stats():
    reset_transport_stats()
    yield
    reset_transport_stats()


def test_framing_round_trip_both_directions():
    a, b = _pair()
    try:
        payloads = [b"", b"x", b"hello ring", bytes(range(256)) * 400]
        for p in payloads:
            a.push(p, timeout_ms=5000)
        for p in payloads:
            assert b.pop(timeout_ms=5000) == p  # FIFO, byte-exact
        b.push(b"reply", timeout_ms=5000)
        assert a.pop(timeout_ms=5000) == b"reply"
        st = transport_stats()
        assert st["frames_sent"] == len(payloads) + 1
        assert st["frames_recv"] == len(payloads) + 1
        assert st["tcp_bytes"] > sum(len(p) for p in payloads)
        assert st["reconnects"] == 0
    finally:
        a.destroy()
        b.destroy()


def test_oversize_item_raises_value_error():
    a = TcpRing("big", capacity=128, create=True)
    try:
        with pytest.raises(ValueError):
            a.push(b"z" * 128)  # frame = header + payload > capacity
    finally:
        a.destroy()


def test_pop_deadline_raises_timeout():
    a, b = _pair()
    try:
        with pytest.raises(TimeoutError):
            b.pop(timeout_ms=50)
    finally:
        a.destroy()
        b.destroy()


def test_graceful_close_drains_then_none_then_broken_pipe():
    a, b = _pair()
    try:
        a.push(b"one", timeout_ms=5000)
        a.push(b"two", timeout_ms=5000)
        a.close()  # CLOSE sentinel queues BEHIND the data frames
        assert b.pop(timeout_ms=5000) == b"one"
        assert b.pop(timeout_ms=5000) == b"two"
        deadline = time.monotonic() + 5
        while True:  # drained + sentinel seen -> None, not TimeoutError
            try:
                assert b.pop(timeout_ms=200) is None
                break
            except TimeoutError:
                assert time.monotonic() < deadline, "CLOSE never arrived"
        with pytest.raises(BrokenPipeError):
            a.push(b"after local close")
        with pytest.raises(BrokenPipeError):
            b.push(b"after peer close")
    finally:
        a.destroy()
        b.destroy()


def test_backpressure_full_ring_times_out_never_death():
    # no peer ever connects: frames park in the bounded send queue and a
    # full ring is BACKPRESSURE (TimeoutError), not a death verdict
    a = TcpRing("bp", capacity=64, create=True)
    try:
        a.push(b"x" * 40, timeout_ms=200)  # 48B frame fits
        with pytest.raises(TimeoutError):
            a.push(b"y" * 40, timeout_ms=200)  # second would exceed 64
    finally:
        a.destroy()


def test_abrupt_peer_disconnect_is_silence_not_death():
    # a raw peer connects then vanishes WITHOUT the CLOSE sentinel (the
    # SIGKILL shape): push keeps queueing, pop times out — only the
    # failure detector may pronounce death
    a = TcpRing("silent", capacity=1 << 16, create=True)
    raw = socket.create_connection(("127.0.0.1", a.port), timeout=5)
    try:
        a.push(b"queued before drop", timeout_ms=5000)
        raw.close()  # FIN, no sentinel
        time.sleep(0.1)
        a.push(b"queued after drop", timeout_ms=5000)  # no BrokenPipeError
        with pytest.raises(TimeoutError):
            a.pop(timeout_ms=100)
    finally:
        a.destroy()


def test_torn_frames_assemble_across_segments():
    a = TcpRing("torn", capacity=1 << 16, create=True)
    raw = socket.create_connection(("127.0.0.1", a.port), timeout=5)
    try:
        payload = b"torn-frame-payload"
        frame = _HDR.pack(len(payload)) + payload
        # dribble: split inside the header, then inside the payload
        for chunk in (frame[:3], frame[3:10], frame[10:]):
            raw.sendall(chunk)
            time.sleep(0.05)
        assert a.pop(timeout_ms=5000) == payload
        # two whole frames in ONE segment -> two pops
        two = (_HDR.pack(2) + b"ab") + (_HDR.pack(3) + b"cde")
        raw.sendall(two)
        assert a.pop(timeout_ms=5000) == b"ab"
        assert a.pop(timeout_ms=5000) == b"cde"
    finally:
        raw.close()
        a.destroy()


def test_dial_before_listen_attach_retries():
    # reserve a port, then attach BEFORE the listener exists — the
    # ShmRing startup race the fresh-socket retry loop absorbs
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    box = {}

    def _attach():
        box["ring"] = TcpRing("late", create=False,
                              endpoint=("127.0.0.1", port),
                              attach_timeout_ms=8000)

    t = threading.Thread(target=_attach)
    t.start()
    time.sleep(0.3)  # the dialer is already retrying against nothing
    a = TcpRing("late", create=True, port=port)
    t.join(timeout=10)
    b = box.get("ring")
    assert b is not None, "attach never connected"
    try:
        a.push(b"made it", timeout_ms=5000)
        assert b.pop(timeout_ms=5000) == b"made it"
    finally:
        a.destroy()
        b.destroy()


def test_dial_without_listener_fails_at_deadline():
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    with pytest.raises(ConnectionError):
        TcpRing("nobody", create=False, endpoint=("127.0.0.1", port),
                attach_timeout_ms=200)


def test_attach_requires_endpoint():
    with pytest.raises(ValueError):
        TcpRing("lost", create=False)


def _pop_until(ring, expected, *, absorb=(), deadline_s=20.0):
    """Pop until `expected` arrives.  At-least-once across a drop means an
    already-delivered frame may be re-sent whole (the sender can lose the
    connection between sendall returning and the in-flight frame leaving
    its queue), so duplicates of frames in `absorb` are skipped — anything
    else is a real ordering violation."""
    end = time.monotonic() + deadline_s
    while True:
        got = ring.pop(
            timeout_ms=int(max(1, (end - time.monotonic()) * 1000)))
        if got == expected:
            return
        assert got in absorb, got


def test_reconnect_after_drop_resumes_and_redelivers():
    a, b = _pair()
    try:
        a.push(b"before", timeout_ms=5000)
        assert b.pop(timeout_ms=5000) == b"before"
        # hard-drop the live connection out from under both ends: the
        # create side must re-accept, the attach side must redial
        with a._cv:
            conn = a._conn
        conn.shutdown(socket.SHUT_RDWR)
        conn.close()
        # frames pushed across the drop boundary arrive AT LEAST once on
        # the replacement connection — silence, then resumption; a
        # duplicate of the already-delivered frame is legal redelivery
        a.push(b"across the drop", timeout_ms=5000)
        b.push(b"uphill too", timeout_ms=5000)
        _pop_until(b, b"across the drop", absorb={b"before"})
        _pop_until(a, b"uphill too")
        assert transport_stats()["reconnects"] >= 1
    finally:
        a.destroy()
        b.destroy()


def test_tcp_transport_discovers_endpoint_via_store():
    # the exact worker path: the router publishes ep:<ring> on the
    # TCPStore control tier, the (possibly remote) worker waits on the
    # key and dials under the same attach deadline
    from paddle_tpu import _native

    srv = _native.TCPStoreServer()
    store = _native.TCPStoreClient(port=srv.port)
    tr = get_transport("tcp", store)
    assert isinstance(tr, TcpTransport)
    ring = tr.create("in:w0", 1 << 16)
    try:
        worker_store = _native.TCPStoreClient(port=srv.port)
        peer = get_transport("tcp", worker_store).attach("in:w0", 5000)
        try:
            peer.push(b"hello router", timeout_ms=5000)
            assert ring.pop(timeout_ms=5000) == b"hello router"
        finally:
            peer.destroy()
    finally:
        ring.destroy()


def test_tcp_transport_attach_times_out_without_publication():
    from paddle_tpu import _native

    srv = _native.TCPStoreServer()
    store = _native.TCPStoreClient(port=srv.port)
    with pytest.raises(Exception):  # store.get deadline: key never set
        TcpTransport(store).attach("never-published", 300)


def test_get_transport_resolution_and_flag_default():
    assert isinstance(get_transport("shm"), ShmTransport)
    # "" resolves FLAGS_cluster_transport, whose baked default is shm
    assert isinstance(get_transport(""), ShmTransport)
    with pytest.raises(ValueError):
        get_transport("carrier-pigeon")
    with pytest.raises(ValueError):
        TcpTransport(None)  # tcp NEEDS the store for discovery


def test_stats_reset_zeroes_counters():
    a, b = _pair()
    try:
        a.push(b"tick", timeout_ms=5000)
        assert b.pop(timeout_ms=5000) == b"tick"
    finally:
        a.destroy()
        b.destroy()
    assert transport_stats()["frames_sent"] >= 1
    out = transport_stats(reset=True)
    assert out["frames_sent"] >= 1  # the pre-reset snapshot is returned
    assert transport_stats() == {"tcp_bytes": 0, "reconnects": 0,
                                 "frames_sent": 0, "frames_recv": 0}


def test_large_frame_outlives_send_timeout_no_reconnect():
    """REVIEW regression: the socket's 0.2s timeout bounds the TOTAL
    duration of ``sendall``, so a frame bigger than the kernel send
    buffer used to time out mid-send, get treated as a connection drop,
    and livelock (reconnect -> re-send whole -> time out again) while
    the receiver stalled.  The chunked send must ride out a stalled
    reader as BACKPRESSURE — progress resets the clock, zero drops."""
    ring = TcpRing("chunked", capacity=8 << 20, create=True)
    s_tx, s_rx = socket.socketpair()
    try:
        # a small kernel buffer + a reader parked well past the 0.2s
        # socket timeout forces multiple per-chunk timeouts
        s_tx.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 1 << 14)
        s_tx.settimeout(0.2)
        frame = bytes(range(256)) * 8192  # 2 MiB >> SNDBUF
        out = {}

        def _send():
            out["ok"] = ring._send_frame(s_tx, ring._conn_gen, frame)

        t = threading.Thread(target=_send, daemon=True)
        t.start()
        time.sleep(0.6)  # >= 2 chunk timeouts while nobody reads
        got = bytearray()
        s_rx.settimeout(10)
        while len(got) < len(frame):
            data = s_rx.recv(1 << 16)
            assert data, "sender gave up mid-frame"
            got += data
        t.join(timeout=10)
        assert not t.is_alive(), "send never completed"
        assert out["ok"] is True
        assert bytes(got) == frame  # intact, exactly once
        assert transport_stats()["reconnects"] == 0
    finally:
        s_tx.close()
        s_rx.close()
        ring.destroy()


def test_receiver_backpressure_bounds_memory_and_stalls_push():
    """REVIEW regression: the rx thread used to drain the socket into
    an UNBOUNDED queue regardless of pop() rate, so a stalled consumer
    let the producer run arbitrarily far ahead — ShmRing's capacity
    contract did not hold end-to-end.  With recv paused past capacity,
    TCP flow control must back the pipe up until push() itself times
    out, with receiver-side buffering bounded near capacity."""
    cap = 1 << 16
    a, b = _pair(capacity=cap)
    try:
        seq_size = 1 << 15  # 32 KiB payloads, each well under capacity
        pushed = 0
        stalled = False
        # 32 MiB ceiling: far beyond capacity + any autotuned kernel
        # socket buffering, so an unbounded receiver would swallow it
        # all without ever stalling the producer
        for i in range(1024):
            payload = _HDR.pack(i) + b"p" * (seq_size - _HDR.size)
            try:
                a.push(payload, timeout_ms=400)
            except TimeoutError:
                stalled = True
                break
            pushed += 1
        assert stalled, "push never felt the stalled consumer"
        with b._cv:
            buffered = b._recv_bytes + len(b._rbuf)
        assert buffered <= cap + (1 << 16), buffered  # one recv of slack
        # nothing was lost or duplicated under the stall: every accepted
        # frame arrives, in order, once the consumer drains
        for i in range(pushed):
            got = b.pop(timeout_ms=10_000)
            assert got is not None and _HDR.unpack_from(got)[0] == i
        with pytest.raises(TimeoutError):
            b.pop(timeout_ms=100)
        assert transport_stats()["reconnects"] == 0
    finally:
        a.destroy()
        b.destroy()
