"""CI smoke for benchmarks/bench_cluster.py — the CPU-falsifiable twin of
the cluster throughput + fail-over latency claims (the standing
tunnel-down constraint: every perf claim must stay checkable offline).

Runs the bench in --smoke mode as a subprocess (it forks and SIGKILLs
real cluster processes, which is also why this module rides a DEDICATED
tools/run_tier1.py isolated worker) and asserts the payload contract the
regression gate consumes: zero lost requests, bit-matching fail-over
streams across EVERY recovery mode, positive fail-over latencies, pages
actually shipped, and the warm-start acceptance floor — standby
promotion's detect->first-token beats cold respawn by at least 2x, and
the warmed respawn booted with persistent compile-cache hits > 0."""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_cluster_smoke_payload():
    env = dict(os.environ, PADDLE_TPU_BENCH_SMOKE="1",
               PADDLE_TPU_BENCH_CPU="1", JAX_PLATFORMS="cpu")
    env.setdefault("PADDLE_TPU_TEST_CACHE_DIR", "/tmp/jax_cache")
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "benchmarks",
                                      "bench_cluster.py"), "--smoke"],
        capture_output=True, text=True, timeout=560, env=env, cwd=_REPO)
    assert r.returncode == 0, (r.stdout + r.stderr)[-3000:]
    line = [ln for ln in r.stdout.strip().splitlines()
            if ln.startswith("{")][-1]
    payload = json.loads(line)
    assert payload["metric"] == "cluster_tokens_per_sec"
    assert payload["value"] > 0
    assert payload["tokens_match"] is True
    fo = payload["detail"]["failover"]
    # the acceptance criteria the bench gates on: a SIGKILLed replica
    # loses ZERO accepted requests and the recovered streams are the
    # unkilled run's bit for bit
    assert fo["lost"] == 0
    assert fo["streams_match"] is True
    assert fo["detect_ms"] > 0 and fo["recover_ms"] >= fo["detect_ms"]
    # warm-start matrix: every recovery mode measured, and the promotion
    # path's detect->first-token beats cold respawn by >= 2x (the
    # ROADMAP item-5 acceptance floor — 2x is deliberately loose next to
    # the typical ~20x so CPU scheduling jitter cannot flake it)
    ft = fo["first_token_ms"]
    for mode in ("cold", "warm_respawn", "standby"):
        assert ft[mode] > 0, ft
    assert ft["standby"] * 2 <= ft["cold"], ft
    # the standby run really promoted, and the warmed respawn really
    # booted off the persistent cache — asserted, not assumed
    assert fo["promotions"] >= 1, fo
    assert fo["respawn_compile_hits"] > 0, fo
    assert payload["detail"]["ship"]["pages"] >= 1
    assert payload["detail"]["ship"]["bytes"] > 0
