"""CI smoke for benchmarks/bench_cluster.py — the CPU-falsifiable twin of
the cluster throughput + fail-over latency claims (the standing
tunnel-down constraint: every perf claim must stay checkable offline).

Runs the bench in --smoke mode as a subprocess (it forks and SIGKILLs
real cluster processes, which is also why this module rides a DEDICATED
tools/run_tier1.py isolated worker) and asserts the payload contract the
regression gate consumes: zero lost requests, bit-matching fail-over
streams across EVERY recovery mode, positive fail-over latencies, pages
actually shipped, and the warm-start acceptance floor — standby
promotion's detect->first-token beats cold respawn by at least 2x, and
the warmed respawn booted with persistent compile-cache hits > 0.

A second run exercises --transport tcp: the same gates over the TcpRing
socket data plane (two localhost "hosts"), plus the transport counter
section the regression gate reads.

Load discipline: under run_tier1 --jobs 6 the host runs six test
workers, so (a) every internal bench wait rides a widened
PADDLE_TPU_BENCH_DEADLINE_S wall, and (b) the standby-vs-cold 2x floor
— a timing RATIO of two single-shot process recoveries — gets ONE
retry of the whole bench before failing: a real regression fails both
runs, a scheduler spike only one."""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_args=()):
    env = dict(os.environ, PADDLE_TPU_BENCH_SMOKE="1",
               PADDLE_TPU_BENCH_CPU="1", JAX_PLATFORMS="cpu",
               PADDLE_TPU_BENCH_DEADLINE_S="480")
    env.setdefault("PADDLE_TPU_TEST_CACHE_DIR", "/tmp/jax_cache")
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "benchmarks",
                                      "bench_cluster.py"), "--smoke",
         *extra_args],
        capture_output=True, text=True, timeout=840, env=env, cwd=_REPO)
    assert r.returncode == 0, (r.stdout + r.stderr)[-3000:]
    line = [ln for ln in r.stdout.strip().splitlines()
            if ln.startswith("{")][-1]
    return json.loads(line)


def _assert_payload(payload, transport):
    assert payload["metric"] == "cluster_tokens_per_sec"
    assert payload["value"] > 0
    assert payload["tokens_match"] is True
    fo = payload["detail"]["failover"]
    # the acceptance criteria the bench gates on: a SIGKILLed replica
    # loses ZERO accepted requests and the recovered streams are the
    # unkilled run's bit for bit
    assert fo["lost"] == 0
    assert fo["streams_match"] is True
    assert fo["detect_ms"] > 0 and fo["recover_ms"] >= fo["detect_ms"]
    ft = fo["first_token_ms"]
    for mode in ("cold", "warm_respawn", "standby"):
        assert ft[mode] > 0, ft
    # the standby run really promoted, and the warmed respawn really
    # booted off the persistent cache — asserted, not assumed
    assert fo["promotions"] >= 1, fo
    assert fo["respawn_compile_hits"] > 0, fo
    assert payload["detail"]["ship"]["pages"] >= 1
    assert payload["detail"]["ship"]["bytes"] > 0
    tr = payload["detail"]["transport"]
    assert tr["kind"] == transport
    if transport == "tcp":
        # the socket plane genuinely carried the cluster: bytes and
        # frames counted, and nothing needed a reconnect on localhost
        assert tr["tcp_bytes"] > 0 and tr["frames_sent"] > 0, tr
        assert tr["frames_recv"] > 0, tr
    else:
        assert tr["tcp_bytes"] == 0, tr
    return ft


def _floor_checked(extra_args, transport):
    payload = _run_bench(extra_args)
    ft = _assert_payload(payload, transport)
    # warm-start matrix: every recovery mode measured, and the promotion
    # path's detect->first-token beats cold respawn by >= 2x (the
    # ROADMAP item-5 acceptance floor — 2x is deliberately loose next to
    # the typical ~20x, but a single-shot ratio can still flake when six
    # test jobs contend for cores, hence one whole-bench retry)
    if ft["standby"] * 2 > ft["cold"]:
        payload = _run_bench(extra_args)
        ft = _assert_payload(payload, transport)
        assert ft["standby"] * 2 <= ft["cold"], ft


def test_transport_flag_missing_value_is_a_clean_error():
    # `--transport` as the LAST argument: a usage error, not an
    # IndexError traceback (the parse runs before any bench work)
    env = dict(os.environ, PADDLE_TPU_BENCH_CPU="1", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "benchmarks",
                                      "bench_cluster.py"), "--transport"],
        capture_output=True, text=True, timeout=180, env=env, cwd=_REPO)
    assert r.returncode != 0
    assert "needs a value" in (r.stdout + r.stderr)
    assert "IndexError" not in r.stderr


def test_bench_cluster_smoke_payload():
    _floor_checked((), "shm")


def test_bench_cluster_smoke_payload_tcp():
    _floor_checked(("--transport", "tcp"), "tcp")
