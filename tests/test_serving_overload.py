"""Overload discipline in the serving tier: chunked prefill interleaved
with decode (FLAGS_prefill_chunk_blocks), priority/SLO-class admission, and
preemptible LOW-priority requests (FLAGS_preempt_low_priority).

The bit-exactness backbone: a prefill chunk is one pool block, every chunk
keeps its own full-chunk geometry (the PrefillChainSpec shape-identity
rule), and the per-block pour computes the same per-block-per-head scales
the batched atomic pour computes — so the chunk boundary is pure data
movement and chunked streams are token-for-token identical to atomic
admission.  Preempted requests park their pool pages host-side verbatim
(pool_get_blocks/pool_set_blocks) and resume bit-identically because the
sampling key is derived from the submit-time nonce, folded per generated
token.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import GenerationEngine
from paddle_tpu.profiler import decode_stats


def _model(**kw):
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(41)
    cfg = llama_tiny(vocab_size=128, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=128,
                     dtype="float32", **kw)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _drain(eng, reqs, **kw):
    for rid, p in reqs:
        eng.add_request(rid, p, **kw)
    while eng.has_work():
        eng.step()
    return {rid: eng.result(rid) for rid, _ in reqs}


# --------------------------------------------- chunked == atomic, matrix
@pytest.mark.parametrize("kv_dtype", [None, "int8"])
@pytest.mark.parametrize("prefix", [False, True])
@pytest.mark.parametrize("sampling", ["greedy", "seeded"])
def test_chunked_prefill_bit_identical_to_atomic(kv_dtype, prefix, sampling):
    """Interleaved chunked prefill produces streams token-for-token equal
    to atomic-prefill admission across pool dtypes, prefix-cache modes and
    sampling modes — same submissions, same seeds, same everything."""
    m = _model()
    rng = np.random.default_rng(11)
    reqs = [("a", list(rng.integers(1, 128, 21))),
            ("b", list(rng.integers(1, 128, 9))),
            ("c", list(rng.integers(1, 128, 13)))]
    skw = ({"temperature": 0.8, "seed": 5} if sampling == "seeded" else {})
    ekw = dict(max_batch=2, block_size=8, num_blocks=32, decode_chunk=2)
    if kv_dtype:
        ekw["kv_cache_dtype"] = kv_dtype
    if prefix:
        ekw["prefix_cache"] = True

    ref = _drain(GenerationEngine(m, **ekw), reqs, max_new_tokens=8, **skw)
    stats0 = decode_stats()["prefill_chunks"]
    got = _drain(GenerationEngine(m, prefill_chunk_blocks=1, **ekw),
                 reqs, max_new_tokens=8, **skw)
    assert got == ref
    # the chunked engine actually chunked (21-token prompt = 3+ chunks)
    assert decode_stats()["prefill_chunks"] - stats0 >= 3


def test_chunked_prefill_interleaves_with_decode():
    """A long prompt admitted while short streams decode advances one
    block per macro-step (budget=1 under active decode) instead of
    stalling the decode batch for its whole prefill."""
    m = _model()
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=32,
                           decode_chunk=2, prefill_chunk_blocks=1)
    eng.add_request("s", [5, 9, 17], max_new_tokens=12)
    eng.step()  # s resident and decoding
    rng = np.random.default_rng(3)
    eng.add_request("long", list(rng.integers(1, 128, 30)),
                    max_new_tokens=4)
    eng.step()
    # after one macro-step the long request is parked mid-prefill: it has
    # poured pages but no sampled token yet, and the short stream advanced
    assert "long" in eng.prefilling_requests()
    assert eng.result("long") is None
    assert len(eng.result("s")) >= 2
    while eng.has_work():
        eng.step()
    ref = _drain(GenerationEngine(m, max_batch=1, block_size=8,
                                  num_blocks=32, decode_chunk=2),
                 [("long", list(np.random.default_rng(3)
                                .integers(1, 128, 30)))],
                 max_new_tokens=4)
    assert eng.result("long") == ref["long"]


# -------------------------------------- mid-prefill prefix hit on a chunk
def test_mid_prefill_prefix_hit_on_poured_boundary():
    """Blocks poured mid-prefill enter the radix tree immediately: a
    request sharing the long prompt's first pages hits them while the long
    prefill is still in flight — and both streams stay bit-identical to a
    cold engine's."""
    m = _model()
    rng = np.random.default_rng(7)
    head = list(rng.integers(1, 128, 16))          # 2 full blocks
    long_p = head + list(rng.integers(1, 128, 16))  # 4 blocks total
    short_p = head + [3, 44]                        # shares the 2 blocks

    cold = {}
    for rid, p in (("long", long_p), ("short", short_p)):
        cold.update(_drain(GenerationEngine(m, max_batch=1, block_size=8,
                                            num_blocks=32, decode_chunk=2),
                           [(rid, p)], max_new_tokens=6))

    eng = GenerationEngine(m, max_batch=3, block_size=8, num_blocks=32,
                           decode_chunk=2, prefill_chunk_blocks=1,
                           prefix_cache=True)
    # a resident decode row caps the prefill budget at 1 chunk/step so the
    # long prefill is genuinely mid-flight when "short" arrives
    eng.add_request("s", [5, 9], max_new_tokens=16)
    eng.step()
    eng.add_request("long", long_p, max_new_tokens=6)
    eng.step()   # pours long's first chunk -> tree holds 1 block
    eng.step()   # pours the second        -> tree holds `head` entirely
    assert "long" in eng.prefilling_requests()
    before = decode_stats()
    eng.add_request("short", short_p, max_new_tokens=6)
    while eng.has_work():
        eng.step()
    after = decode_stats()
    assert after["prefix_hits"] == before["prefix_hits"] + 1
    assert (after["prefix_hit_tokens"]
            == before["prefix_hit_tokens"] + len(head))
    assert eng.result("long") == cold["long"]
    assert eng.result("short") == cold["short"]


# ------------------------------------------------- preemption bit-parity
def test_preempt_park_readmit_bit_parity():
    """A LOW request parked mid-decode by a HIGH arrival resumes
    bit-identically: the re-admitted stream equals the never-preempted
    reference token for token (seeded sampling — the strictest mode)."""
    m = _model()
    p_low, p_high = [5, 9, 17, 33, 2], [7, 11, 3, 40]

    ref = _drain(GenerationEngine(m, max_batch=1, block_size=8,
                                  num_blocks=32, decode_chunk=2),
                 [("lo", p_low)], max_new_tokens=10, temperature=0.7,
                 seed=3)

    before = decode_stats()
    eng = GenerationEngine(m, max_batch=1, block_size=8, num_blocks=32,
                           decode_chunk=2)
    eng.add_request("lo", p_low, max_new_tokens=10, temperature=0.7,
                    seed=3, priority="low")
    eng.step()
    eng.step()
    mid = list(eng.result("lo"))
    assert 0 < len(mid) < 10  # genuinely mid-decode
    eng.add_request("hi", p_high, max_new_tokens=4, priority="high")
    eng.step()
    assert "lo" in eng.parked_requests()  # evicted, pages host-side
    while eng.has_work():
        eng.step()
    after = decode_stats()
    assert eng.result("lo") == ref["lo"]
    assert after["preemptions"] == before["preemptions"] + 1
    assert after["preempt_readmits"] == before["preempt_readmits"] + 1
    assert after["parked_requests"] == 0


def test_preempt_flag_off_disables_parking():
    """FLAGS_preempt_low_priority=False: a HIGH arrival waits for the slot
    instead of evicting the LOW resident."""
    m = _model()
    paddle.set_flags({"FLAGS_preempt_low_priority": False})
    try:
        before = decode_stats()["preemptions"]
        eng = GenerationEngine(m, max_batch=1, block_size=8, num_blocks=32,
                               decode_chunk=2)
        eng.add_request("lo", [5, 9, 17], max_new_tokens=6, priority="low")
        eng.step()
        eng.add_request("hi", [7, 11, 3], max_new_tokens=4,
                        priority="high")
        eng.step()
        assert eng.parked_requests() == []
        while eng.has_work():
            eng.step()
        assert decode_stats()["preemptions"] == before
        assert len(eng.result("hi")) == 4
    finally:
        paddle.set_flags({"FLAGS_preempt_low_priority": True})


# ----------------------------------------------------- priority ordering
def test_priority_admission_order_under_slot_exhaustion():
    """With the single slot busy, a HIGH submission queued AFTER a LOW one
    is admitted first when the slot frees — (priority, submit-seq) order,
    not FIFO."""
    m = _model()
    paddle.set_flags({"FLAGS_preempt_low_priority": False})
    try:
        eng = GenerationEngine(m, max_batch=1, block_size=8, num_blocks=32,
                               decode_chunk=2)
        eng.add_request("n", [5, 9, 17], max_new_tokens=4)
        eng.step()
        eng.add_request("lo", [7, 11], max_new_tokens=3, priority="low")
        eng.add_request("hi", [3, 40], max_new_tokens=3, priority="high")
        while eng.result("hi") is None:
            eng.step()
        # HIGH entered while LOW is still waiting
        assert eng.result("lo") is None
        while eng.has_work():
            eng.step()
        assert len(eng.result("lo")) == 3
        st = decode_stats()
        assert st["admitted_high"] >= 1 and st["admitted_low"] >= 1
    finally:
        paddle.set_flags({"FLAGS_preempt_low_priority": True})


def test_add_request_rejects_unknown_priority():
    m = _model()
    eng = GenerationEngine(m, max_batch=1, block_size=8, num_blocks=16)
    with pytest.raises(ValueError):
        eng.add_request("x", [1, 2, 3], priority="urgent")


# -------------------------------------------------------- flags plumbing
def test_prefill_chunk_flag_invalidates_and_takes_effect():
    """FLAGS_prefill_chunk_blocks is read dynamically: flipping it clears
    compiled macro-steps (flags listener) and switches an existing engine
    between atomic and interleaved admission — with identical streams."""
    m = _model()
    rng = np.random.default_rng(19)
    reqs = [("a", list(rng.integers(1, 128, 17))),
            ("b", list(rng.integers(1, 128, 6)))]

    ref = _drain(GenerationEngine(m, max_batch=2, block_size=8,
                                  num_blocks=32, decode_chunk=2),
                 reqs, max_new_tokens=6)

    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=32,
                           decode_chunk=2)
    eng.add_request("warm", [9, 5, 2], max_new_tokens=2)
    while eng.has_work():
        eng.step()  # builds + caches a compiled macro-step
    assert eng._step_fns
    paddle.set_flags({"FLAGS_prefill_chunk_blocks": 1})
    try:
        assert not eng._step_fns  # listener invalidated the cache
        chunks0 = decode_stats()["prefill_chunks"]
        got = _drain(eng, reqs, max_new_tokens=6)
        assert got == ref
        assert decode_stats()["prefill_chunks"] > chunks0
    finally:
        paddle.set_flags({"FLAGS_prefill_chunk_blocks": 0})


def test_ctor_overrides_flag_and_validates():
    m = _model()
    with pytest.raises(ValueError):
        GenerationEngine(m, num_blocks=8, prefill_chunk_blocks=-1)
    # ctor value pins the engine regardless of the global flag
    paddle.set_flags({"FLAGS_prefill_chunk_blocks": 2})
    try:
        eng = GenerationEngine(m, max_batch=1, block_size=8, num_blocks=16,
                               prefill_chunk_blocks=0)
        assert eng._prefill_chunk_blocks() == 0
    finally:
        paddle.set_flags({"FLAGS_prefill_chunk_blocks": 0})


# ------------------------------------------------ snapshot/drain interplay
def test_drain_demotes_prefilling_and_parked(tmp_path):
    """drain() demotes mid-prefill and parked requests back to pending
    submissions so a lame-duck engine hands them off instead of holding
    pool pages."""
    m = _model()
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=32,
                           decode_chunk=2, prefill_chunk_blocks=1)
    eng.add_request("s", [5, 9], max_new_tokens=8)
    eng.step()
    rng = np.random.default_rng(23)
    eng.add_request("long", list(rng.integers(1, 128, 30)),
                    max_new_tokens=4)
    eng.step()
    assert "long" in eng.prefilling_requests()
    n = eng.drain(dir=str(tmp_path))
    assert n >= 1
    assert eng.prefilling_requests() == []
    assert any(r["rid"] == "long" for r in eng._pending)
