"""Minimal PS tier: sparse tables, pull/push, SparseEmbedding layer
(reference paddle/fluid/distributed/ps/ — see scope decision in
paddle_tpu/distributed/ps/__init__.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import PsClient, PsServer, SparseEmbedding, SparseTable


def test_table_pull_push_sgd():
    t = SparseTable(dim=4, lr=0.5)
    rows = t.pull([3, 7, 3])
    assert rows.shape == (3, 4)
    np.testing.assert_array_equal(rows[0], rows[2])  # same id, same row
    before = t.pull([3])[0].copy()
    t.push([3], np.ones((1, 4), np.float32))
    after = t.pull([3])[0]
    np.testing.assert_allclose(after, before - 0.5, rtol=1e-6)
    assert t.n_rows() == 2


def test_sparse_embedding_trains():
    # learn to map id -> target vector through the PS table
    t = SparseTable(dim=8, lr=0.3)
    emb = SparseEmbedding(PsClient(table=t), dim=8)
    target = np.zeros((2, 8), np.float32)
    target[0, 0] = 1.0
    target[1, 1] = 1.0
    ids = paddle.to_tensor(np.array([5, 9], np.int32))
    losses = []
    for _ in range(60):
        e = emb(ids)
        loss = ((e - paddle.to_tensor(target)) ** 2).mean()
        loss.backward()
        losses.append(float(loss._value))
    assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])


def test_ps_state_roundtrip():
    t = SparseTable(dim=2)
    t.pull([1, 2, 3])
    sd = t.state_dict()
    t2 = SparseTable(dim=2)
    t2.set_state_dict(sd)
    np.testing.assert_array_equal(t.pull([2]), t2.pull([2]))


def test_ps_over_rpc():
    from paddle_tpu.distributed import rpc

    rpc.init_rpc("ps_worker0", rank=0, world_size=1, master_endpoint="127.0.0.1:29621")
    try:
        PsServer.register_table(SparseTable(dim=4, name="emb_rpc"))
        client = PsClient(server="ps_worker0", table_name="emb_rpc")
        rows = client.pull([11, 12])
        assert rows.shape == (2, 4)
        client.push([11], np.ones((1, 4), np.float32))
        rows2 = client.pull([11])
        assert not np.allclose(rows[0], rows2[0])
    finally:
        rpc.shutdown()


def test_fleet_ps_mode_roles():
    """fleet PS-mode surface (reference fleet.init(role_maker) +
    the_one_ps init_server/init_worker)."""
    import paddle_tpu.distributed.fleet as fleet

    rm = fleet.UserDefinedRoleMaker(current_id=0, role="PSERVER")
    fleet.init(role_maker=rm, is_collective=False)
    assert fleet.is_server() and not fleet.is_worker()

    rm2 = fleet.UserDefinedRoleMaker(current_id=1, role="TRAINER")
    fleet.init(role_maker=rm2, is_collective=True)
    assert fleet.is_worker() and not fleet.is_server()
    assert fleet.init_worker() is None


def test_fleet_ps_server_serves_tables():
    import threading
    import time

    import paddle_tpu.distributed.fleet as fleet
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import PsClient, PsServer, SparseTable

    rm = fleet.UserDefinedRoleMaker(current_id=0, role="PSERVER")
    fleet.init(role_maker=rm, is_collective=False)
    PsServer.register_table(SparseTable(dim=4, name="fleet_emb"))
    fleet.init_server(name="fleet_ps0", rank=0, world_size=1, master_endpoint="127.0.0.1:29631")
    t = threading.Thread(target=fleet.run_server, daemon=True)
    t.start()
    try:
        client = PsClient(server="fleet_ps0", table_name="fleet_emb")
        rows = client.pull([1, 2])
        assert rows.shape == (2, 4)
    finally:
        fleet.stop_worker()
        t.join(timeout=5)
        assert not t.is_alive()
