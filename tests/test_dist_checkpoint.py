"""Distributed checkpoint: sharded save + cross-topology reshard-on-load
(reference python/paddle/distributed/checkpoint/)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed.checkpoint as ckpt


def _mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def test_save_load_replicated(tmp_path):
    sd = {"w": paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4)),
          "nested": {"b": paddle.to_tensor(np.ones(5, np.float32))}}
    ckpt.save_state_dict(sd, str(tmp_path))

    sd2 = {"w": paddle.to_tensor(np.zeros((3, 4), np.float32)),
           "nested": {"b": paddle.to_tensor(np.zeros(5, np.float32))}}
    ckpt.load_state_dict(sd2, str(tmp_path))
    np.testing.assert_allclose(np.asarray(sd2["w"]._value), np.arange(12).reshape(3, 4))
    np.testing.assert_allclose(np.asarray(sd2["nested"]["b"]._value), np.ones(5))


def test_reshard_on_load_across_topologies(tmp_path):
    """Save sharded over 4 devices on axis 0; load sharded over 2x... axis 1."""
    full = np.arange(64, dtype=np.float32).reshape(8, 8)
    mesh_a = _mesh((4,), ("x",))
    arr_a = jax.device_put(jnp.asarray(full), NamedSharding(mesh_a, P("x", None)))
    ckpt.save_state_dict({"w": paddle.Tensor(arr_a)}, str(tmp_path))

    mesh_b = _mesh((2,), ("y",))
    target = jax.device_put(jnp.zeros((8, 8), jnp.float32), NamedSharding(mesh_b, P(None, "y")))
    sd = {"w": paddle.Tensor(target)}
    ckpt.load_state_dict(sd, str(tmp_path))
    out = sd["w"]._value
    assert len(out.sharding.device_set) == 2
    np.testing.assert_allclose(np.asarray(out), full)


def test_async_save(tmp_path):
    sd = {"w": paddle.to_tensor(np.ones((4, 4), np.float32) * 3)}
    th = ckpt.save_state_dict(sd, str(tmp_path), async_save=True)
    th.join(timeout=30)
    sd2 = {"w": paddle.to_tensor(np.zeros((4, 4), np.float32))}
    ckpt.load_state_dict(sd2, str(tmp_path))
    np.testing.assert_allclose(np.asarray(sd2["w"]._value), 3.0)


def test_load_missing_region_raises(tmp_path):
    import pytest

    sd = {"w": paddle.to_tensor(np.ones((4, 4), np.float32))}
    ckpt.save_state_dict(sd, str(tmp_path))
    bad = {"w": paddle.to_tensor(np.zeros((4, 5), np.float32))}
    with pytest.raises(ValueError, match="shape mismatch"):
        ckpt.load_state_dict(bad, str(tmp_path))


def test_load_into_raw_array_writes_back(tmp_path):
    sd = {"w": paddle.to_tensor(np.full((2, 2), 7.0, np.float32))}
    ckpt.save_state_dict(sd, str(tmp_path))
    target = {"w": jnp.zeros((2, 2), jnp.float32)}
    ckpt.load_state_dict(target, str(tmp_path))
    np.testing.assert_allclose(np.asarray(target["w"]), 7.0)
