"""nn.LayerStack — scan-over-layers numerics equivalence + layout round-trip.

The stack must be OBSERVATIONALLY identical to the unrolled loop: same
outputs (bit-exact on CPU f32 — the scan body runs the same op sequence),
same grads (to accumulation-order tolerance), and state_dict layouts must
interconvert so checkpoints survive flipping fuse_layer_stack.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class _Block(nn.Layer):
    def __init__(self, width=8):
        super().__init__()
        self.fc = nn.Linear(width, width)
        self.ln = nn.LayerNorm(width)

    def forward(self, h, scale):
        return h + self.fc(self.ln(h)) * scale


def _block(width=8):
    return _Block(width)


def _twin_stacks(n=4, width=8):
    paddle.seed(7)
    blocks = [_block(width) for _ in range(n)]
    loop_blocks = [_block(width) for _ in range(n)]
    for lb, b in zip(loop_blocks, blocks):
        lb.set_state_dict(b.state_dict())
    return nn.LayerStack(blocks), loop_blocks


def test_scan_matches_unrolled_forward_and_grads():
    stack, loop = _twin_stacks()
    rng = np.random.default_rng(0)
    x1 = paddle.to_tensor(rng.standard_normal((2, 3, 8)).astype(np.float32),
                          stop_gradient=False)
    x2 = paddle.to_tensor(np.asarray(x1._value), stop_gradient=False)
    s = paddle.to_tensor(np.float32(0.5))

    out = stack(x1, s)
    h = x2
    for b in loop:
        h = b(h, s)
    # same op sequence, same backend: bit-exact where the dtype allows
    assert np.array_equal(np.asarray(out._value), np.asarray(h._value))

    out.sum().backward()
    h.sum().backward()
    for key in ("fc.weight", "fc.bias", "ln.weight", "ln.bias"):
        g_stack = np.asarray(stack._parameters[key].grad._value)
        g_loop = np.stack([np.asarray(dict(b.named_parameters())[key].grad._value)
                           for b in loop])
        np.testing.assert_allclose(g_stack, g_loop, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(x1.grad._value),
                               np.asarray(x2.grad._value), rtol=1e-5, atol=1e-6)


def test_scan_under_trainstep_matches_eager_loop_losses():
    from paddle_tpu import jit
    import paddle_tpu.optimizer as opt

    def build(fuse):
        paddle.seed(3)
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

        cfg = llama_tiny(num_hidden_layers=3, hidden_size=64,
                         intermediate_size=128, num_attention_heads=4,
                         num_key_value_heads=4, vocab_size=128,
                         max_position_embeddings=32, dtype="float32",
                         fuse_layer_stack=fuse)
        m = LlamaForCausalLM(cfg)
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        return m, jit.TrainStep(m, o, lambda mm, x, y: mm(x, y)[0])

    rng = np.random.default_rng(1)
    x = paddle.to_tensor(rng.integers(0, 128, (2, 8)).astype(np.int32))
    y = paddle.to_tensor(rng.integers(0, 128, (2, 8)).astype(np.int32))
    _, step_loop = build(False)
    _, step_scan = build(True)
    losses_loop = [float(step_loop(x, y)._value) for _ in range(3)]
    losses_scan = [float(step_scan(x, y)._value) for _ in range(3)]
    np.testing.assert_allclose(losses_scan, losses_loop, rtol=2e-5)


@pytest.mark.parametrize("gran", ["full", "full_attn", "core_attn"])
def test_recompute_tiers_preserve_loss_and_grads(gran):
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    def build(recompute, fuse):
        paddle.seed(5)
        cfg = llama_tiny(num_hidden_layers=2, hidden_size=32,
                         intermediate_size=64, num_attention_heads=2,
                         num_key_value_heads=2, vocab_size=64,
                         max_position_embeddings=16, dtype="float32",
                         use_recompute=recompute, recompute_granularity=gran,
                         fuse_layer_stack=fuse)
        return LlamaForCausalLM(cfg)

    rng = np.random.default_rng(2)
    x = paddle.to_tensor(rng.integers(0, 64, (2, 8)).astype(np.int32))
    y = paddle.to_tensor(rng.integers(0, 64, (2, 8)).astype(np.int32))

    ref = build(False, False)
    loss_ref, _ = ref(x, y)
    loss_ref.backward()
    m = build(True, True)
    m.set_state_dict(ref.state_dict())
    loss, _ = m(x, y)
    np.testing.assert_allclose(float(loss._value), float(loss_ref._value),
                               rtol=1e-5)
    loss.backward()
    g = np.asarray(
        m.model.layers._parameters["self_attn.q_proj.weight"].grad._value)
    g_ref = np.stack([np.asarray(b.self_attn.q_proj.weight.grad._value)
                      for b in ref.model.layers])
    np.testing.assert_allclose(g, g_ref, rtol=1e-4, atol=1e-6)


def test_state_dict_stack_unstack_round_trip():
    from paddle_tpu.nn.layer.stack import stack_state_dict, unstack_state_dict

    stack, loop = _twin_stacks(n=3)
    keys = stack.stack_keys()
    # unstacked dict -> stacked dict -> load
    per_layer = {}
    for i, b in enumerate(loop):
        for k, v in b.state_dict().items():
            per_layer[f"layers.{i}.{k}"] = v
    stacked = stack_state_dict(per_layer, "layers", 3, keys)
    assert set(stacked) == {f"layers.{k}" for k in keys}
    back = unstack_state_dict(stacked, "layers", 3, keys)
    assert set(back) == set(per_layer)
    for k in per_layer:
        assert np.array_equal(np.asarray(per_layer[k]._value),
                              np.asarray(back[k]._value))


def test_root_level_stack_loads_per_layer_checkpoint():
    """A per-layer checkpoint loads into a LayerStack that IS the root model
    (path prefix is empty — the adapt path must not synthesize '.0.key')."""
    stack, loop = _twin_stacks(n=3)
    per_layer = {}
    for i, b in enumerate(loop):
        for k, v in b.state_dict().items():
            per_layer[f"{i}.{k}"] = v
    missing, unexpected = stack.set_state_dict(per_layer)
    assert not missing and not unexpected, (missing, unexpected)
    got = np.asarray(stack._parameters["fc.weight"]._value)
    want = np.stack([np.asarray(b.fc.weight._value) for b in loop])
    np.testing.assert_array_equal(got, want)


def test_checkpoints_cross_load_between_layouts():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    def build(fuse):
        paddle.seed(9)
        cfg = llama_tiny(num_hidden_layers=2, hidden_size=32,
                         intermediate_size=64, num_attention_heads=2,
                         num_key_value_heads=2, vocab_size=64,
                         max_position_embeddings=16, dtype="float32",
                         fuse_layer_stack=fuse)
        return LlamaForCausalLM(cfg)

    rng = np.random.default_rng(4)
    x = paddle.to_tensor(rng.integers(0, 64, (2, 8)).astype(np.int32))
    loop_model, scan_model = build(False), build(True)

    # per-layer checkpoint loads into the scanned model...
    missing, unexpected = scan_model.set_state_dict(loop_model.state_dict())
    assert not missing and not unexpected
    np.testing.assert_array_equal(np.asarray(loop_model(x)._value),
                                  np.asarray(scan_model(x)._value))
    # ...and a scanned checkpoint loads back into a fresh loop model
    loop2 = build(False)
    missing, unexpected = loop2.set_state_dict(scan_model.state_dict())
    assert not missing and not unexpected
    np.testing.assert_array_equal(np.asarray(loop_model(x)._value),
                                  np.asarray(loop2(x)._value))


def test_generate_parity_scan_vs_loop():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    def build(fuse):
        paddle.seed(11)
        cfg = llama_tiny(num_hidden_layers=2, hidden_size=32,
                         intermediate_size=64, num_attention_heads=2,
                         num_key_value_heads=2, vocab_size=64,
                         max_position_embeddings=64, dtype="float32",
                         fuse_layer_stack=fuse)
        return LlamaForCausalLM(cfg)

    loop_model, scan_model = build(False), build(True)
    scan_model.set_state_dict(loop_model.state_dict())
    rng = np.random.default_rng(6)
    prompt = paddle.to_tensor(rng.integers(0, 64, (1, 8)).astype(np.int32))
    for cache in ("naive", "paged"):
        a = loop_model.generate(prompt, max_new_tokens=4, cache=cache)
        b = scan_model.generate(prompt, max_new_tokens=4, cache=cache)
        assert np.array_equal(np.asarray(a._value), np.asarray(b._value)), cache
    # macro-step decode threads the paged pools THROUGH the scan body
    # (decode_scan): chunked scan == per-token loop, bit for bit,
    # including the max_new % D tail chunk
    c = scan_model.generate(prompt, max_new_tokens=6, cache="paged",
                            decode_chunk=4)
    d = loop_model.generate(prompt, max_new_tokens=6, cache="paged",
                            decode_chunk=1)
    assert np.array_equal(np.asarray(c._value), np.asarray(d._value))


def test_engine_on_layer_stack_matches_loop_engine():
    """GenerationEngine over a fuse_layer_stack model: the macro-step
    program scans ONE layer body with the paged pools as scan state, and
    its tokens equal the unrolled-loop engine's exactly (greedy + a
    sampled slot, request joining at a macro-step boundary)."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import GenerationEngine

    def build(fuse):
        paddle.seed(11)
        cfg = llama_tiny(num_hidden_layers=2, hidden_size=32,
                         intermediate_size=64, num_attention_heads=2,
                         num_key_value_heads=2, vocab_size=64,
                         max_position_embeddings=64, dtype="float32",
                         fuse_layer_stack=fuse)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m

    def run(fuse, D):
        eng = GenerationEngine(build(fuse), max_batch=2, block_size=8,
                               num_blocks=16, decode_chunk=D)
        eng.add_request("a", [5, 9, 17, 33, 2], max_new_tokens=8)
        eng.step()
        eng.add_request("b", [7, 11, 3], max_new_tokens=6,
                        temperature=4.0, seed=9)
        while eng.has_work():
            eng.step()
        return eng.result("a"), eng.result("b")

    ref = run(False, 1)
    assert run(True, 4) == ref
    assert run(True, 1) == ref


def test_flags_scan_layers_forces_stack():
    from paddle_tpu.models.llama import LlamaModel, llama_tiny

    paddle.set_flags({"FLAGS_scan_layers": True})
    try:
        cfg = llama_tiny(num_hidden_layers=2, dtype="float32")
        m = LlamaModel(cfg)
        assert isinstance(m.layers, nn.LayerStack)
    finally:
        paddle.set_flags({"FLAGS_scan_layers": False})
    m2 = LlamaModel(llama_tiny(num_hidden_layers=2, dtype="float32"))
    assert not isinstance(m2.layers, nn.LayerStack)


def test_heterogeneous_blocks_rejected():
    paddle.seed(0)
    with pytest.raises((TypeError, ValueError)):
        nn.LayerStack([_block(8), nn.Linear(8, 8)])

    class Wide(nn.Layer):
        def __init__(self, w):
            super().__init__()
            self.fc = nn.Linear(w, w)

        def forward(self, h):
            return self.fc(h)

    with pytest.raises(ValueError):
        nn.LayerStack([Wide(4), Wide(8)])


def test_dropout_stack_rng_and_eval_mode():
    """Stochastic stacks draw fresh per-call randomness in train mode and
    are deterministic in eval — eval() must reach the hidden template (the
    mode sync), and MHA's functional dropout must trip needs_rng."""
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny(dropout=0.1, fuse_layer_stack=True))
    assert m.gpt.h._needs_rng
    rng = np.random.default_rng(7)
    x = paddle.to_tensor(rng.integers(0, 512, (2, 8)).astype(np.int32))
    a, b = m(x), m(x)
    assert not np.array_equal(np.asarray(a._value), np.asarray(b._value)), (
        "train-mode dropout produced identical outputs across calls")
    m.eval()
    c, d = m(x), m(x)
    assert np.array_equal(np.asarray(c._value), np.asarray(d._value)), (
        "eval() did not reach the scan body (dropout still active)")


def test_gpt_scan_matches_loop():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    def build(fuse):
        paddle.seed(13)
        return GPTForCausalLM(gpt_tiny(fuse_layer_stack=fuse))

    loop_model, scan_model = build(False), build(True)
    scan_model.set_state_dict(loop_model.state_dict())
    rng = np.random.default_rng(8)
    x = paddle.to_tensor(rng.integers(0, 512, (2, 12)).astype(np.int32))
    y = paddle.to_tensor(rng.integers(0, 512, (2, 12)).astype(np.int32))
    la, _ = loop_model(x, labels=y)
    lb, _ = scan_model(x, labels=y)
    np.testing.assert_allclose(float(la._value), float(lb._value), rtol=1e-5)
