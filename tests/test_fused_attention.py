"""Fused attention tier: fused_dot_product_attention (reference
python/paddle/incubate/nn/functional/fused_dot_product_attention.py,
cuDNN layout [B, S, N, H]) and fused_gate_attention (reference
fused_gate_attention.py, AlphaFold-style gated self-attention)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as IF


def _np_sdpa(q, k, v, mask=None, causal=False, scale=None):
    """[B, S, N, H] reference attention in float64 numpy."""
    q, k, v = (np.asarray(t, np.float64) for t in (q, k, v))
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    s = np.einsum("bqnh,bknh->bnqk", q, k) * scale
    if causal:
        tri = np.tril(np.ones((q.shape[1], k.shape[1]), bool))
        s = np.where(tri[None, None], s, -1e30)
    elif mask is not None:
        s = np.where(np.asarray(mask, bool), s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bnqk,bknh->bqnh", p, v)


@pytest.mark.parametrize("causal", [True, False])
def test_fused_dot_product_attention_matches_reference(causal):
    rng = np.random.default_rng(0)
    B, S, N, H = 2, 16, 4, 8
    q, k, v = (paddle.to_tensor(rng.standard_normal((B, S, N, H)).astype("float32"))
               for _ in range(3))
    out = IF.fused_dot_product_attention(
        q, k, v, is_causal_masking=causal, is_training=False)
    ref = _np_sdpa(q.numpy(), k.numpy(), v.numpy(), causal=causal)
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=2e-4, atol=2e-5)


def test_fused_dot_product_attention_mask_and_softmax():
    rng = np.random.default_rng(1)
    B, S, N, H = 2, 8, 2, 4
    q, k, v = (paddle.to_tensor(rng.standard_normal((B, S, N, H)).astype("float32"))
               for _ in range(3))
    mask = (rng.random((B, 1, S, S)) > 0.3).astype("int32")
    mask[..., 0] = 1  # every query attends to at least one key
    out, probs = IF.fused_dot_product_attention(
        q, k, v, mask=paddle.to_tensor(mask), is_training=False,
        return_softmax=True)
    ref = _np_sdpa(q.numpy(), k.numpy(), v.numpy(), mask=mask)
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=2e-4, atol=2e-5)
    p = np.asarray(probs._value)
    np.testing.assert_allclose(p.sum(-1), np.ones(p.shape[:-1]), rtol=1e-5)
    assert np.all(p[~np.broadcast_to(mask.astype(bool), p.shape)] < 1e-12)


def test_fused_dot_product_attention_grad_flows():
    rng = np.random.default_rng(2)
    q = paddle.to_tensor(rng.standard_normal((1, 8, 2, 4)).astype("float32"),
                         stop_gradient=False)
    k = paddle.to_tensor(rng.standard_normal((1, 8, 2, 4)).astype("float32"),
                         stop_gradient=False)
    v = paddle.to_tensor(rng.standard_normal((1, 8, 2, 4)).astype("float32"),
                         stop_gradient=False)
    out = IF.fused_dot_product_attention(q, k, v, is_causal_masking=True,
                                         is_training=False)
    out.sum().backward()
    for t in (q, k, v):
        g = np.asarray(t.grad._value)
        assert np.isfinite(g).all() and np.abs(g).max() > 0


def _np_gate_attention(qd, qkv_w, gate_w, gate_b, out_w, out_b,
                       nb_bias=None, mask=None, gating=True):
    """Reference pseudo-code (fused_gate_attention.py docstring) in numpy."""
    qd = np.asarray(qd, np.float64)
    c = 1.0 / np.sqrt(qkv_w.shape[2])
    qkv = np.einsum("bmrd,snhd->sbmrnh", qd, np.asarray(qkv_w, np.float64))
    q, k, v = qkv[0] * c, qkv[1], qkv[2]
    logits = np.einsum("bmqnh,bmknh->bmnqk", q, k)
    if mask is not None:
        logits = logits + (1.0 - np.asarray(mask, np.float64)) * -1e9
    if nb_bias is not None:
        logits = logits + np.asarray(nb_bias, np.float64)[:, None]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ctx = np.einsum("bmnqk,bmknh->bmqnh", p, v)
    if gating:
        g = 1.0 / (1.0 + np.exp(-(np.einsum("bmrd,dnh->bmrnh", qd,
                                            np.asarray(gate_w, np.float64))
                                  + np.asarray(gate_b, np.float64))))
        ctx = ctx * g
    return np.einsum("bmrnh,nhd->bmrd", ctx, np.asarray(out_w, np.float64)) \
        + np.asarray(out_b, np.float64)


@pytest.mark.parametrize("gating", [True, False])
def test_fused_gate_attention_merge_qkv_matches_reference(gating):
    rng = np.random.default_rng(3)
    B, M, R, D, N, H = 1, 2, 6, 8, 2, 4
    qd = rng.standard_normal((B, M, R, D)).astype("float32")
    qkv_w = rng.standard_normal((3, N, H, D)).astype("float32") * 0.3
    gate_w = rng.standard_normal((D, N, H)).astype("float32") * 0.3
    gate_b = rng.standard_normal((N, H)).astype("float32") * 0.1
    out_w = rng.standard_normal((N, H, D)).astype("float32") * 0.3
    out_b = rng.standard_normal((D,)).astype("float32") * 0.1
    nb = rng.standard_normal((B, N, R, R)).astype("float32") * 0.2
    mask = (rng.random((B, M, 1, 1, R)) > 0.2).astype("float32")
    kw = dict(has_gating=gating)
    if gating:
        kw.update(gate_linear_weight=paddle.to_tensor(gate_w),
                  gate_linear_bias=paddle.to_tensor(gate_b))
    out = IF.fused_gate_attention(
        paddle.to_tensor(qd), qkv_weight=paddle.to_tensor(qkv_w),
        out_linear_weight=paddle.to_tensor(out_w),
        out_linear_bias=paddle.to_tensor(out_b),
        nonbatched_bias=paddle.to_tensor(nb),
        attn_mask=paddle.to_tensor(mask), **kw)
    ref = _np_gate_attention(qd, qkv_w, gate_w, gate_b, out_w, out_b,
                             nb_bias=nb, mask=mask, gating=gating)
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=3e-4, atol=3e-5)


def test_fused_gate_attention_separate_weights_cross_attention():
    rng = np.random.default_rng(4)
    B, M, R, K, D, N, H = 1, 2, 5, 7, 8, 2, 4
    qd = rng.standard_normal((B, M, R, D)).astype("float32")
    kd = rng.standard_normal((B, M, K, D)).astype("float32")
    q_w = rng.standard_normal((D, N, H)).astype("float32") * 0.3
    k_w = rng.standard_normal((D, N, H)).astype("float32") * 0.3
    v_w = rng.standard_normal((D, N, H)).astype("float32") * 0.3
    out_w = rng.standard_normal((N, H, D)).astype("float32") * 0.3
    out_b = np.zeros((D,), "float32")
    out = IF.fused_gate_attention(
        paddle.to_tensor(qd), key=paddle.to_tensor(kd),
        query_weight=paddle.to_tensor(q_w), key_weight=paddle.to_tensor(k_w),
        value_weight=paddle.to_tensor(v_w),
        out_linear_weight=paddle.to_tensor(out_w),
        out_linear_bias=paddle.to_tensor(out_b),
        has_gating=False, merge_qkv=False)
    # numpy reference for the separate-projection path
    f64 = np.float64
    q = np.einsum("bmrd,dnh->bmrnh", qd.astype(f64), q_w.astype(f64)) / np.sqrt(H)
    k = np.einsum("bmkd,dnh->bmknh", kd.astype(f64), k_w.astype(f64))
    v = np.einsum("bmkd,dnh->bmknh", kd.astype(f64), v_w.astype(f64))
    logits = np.einsum("bmqnh,bmknh->bmnqk", q, k)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ctx = np.einsum("bmnqk,bmknh->bmqnh", p, v)
    ref = np.einsum("bmrnh,nhd->bmrd", ctx, out_w.astype(f64))
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=3e-4, atol=3e-5)


def test_fused_gate_attention_loud_misconfiguration():
    q = paddle.ones([1, 1, 2, 4])
    w = paddle.ones([3, 2, 2, 4])
    with pytest.raises(ValueError, match="qkv_weight"):
        IF.fused_gate_attention(q, out_linear_weight=paddle.ones([2, 2, 4]),
                                out_linear_bias=paddle.ones([4]))
    with pytest.raises(ValueError, match="gate_linear_weight"):
        IF.fused_gate_attention(q, qkv_weight=w,
                                out_linear_weight=paddle.ones([2, 2, 4]),
                                out_linear_bias=paddle.ones([4]))


def test_fused_dot_product_attention_dropout_training_path():
    """Dropout must actually execute in training (the broken-rng-import /
    silently-skipped-on-flash-path class): zeros appear in the
    probabilities and the causal fast path is NOT taken when dropout is
    active."""
    rng = np.random.default_rng(5)
    q, k, v = (paddle.to_tensor(rng.standard_normal((1, 16, 2, 4)).astype("float32"))
               for _ in range(3))
    paddle.seed(7)
    out_a = IF.fused_dot_product_attention(
        q, k, v, is_causal_masking=True, dropout_prob=0.5, is_training=True)
    paddle.seed(8)
    out_b = IF.fused_dot_product_attention(
        q, k, v, is_causal_masking=True, dropout_prob=0.5, is_training=True)
    a, b = np.asarray(out_a._value), np.asarray(out_b._value)
    assert np.isfinite(a).all() and np.isfinite(b).all()
    assert np.abs(a - b).max() > 1e-6  # different keys -> different drops
    # and inference ignores dropout entirely (matches the clean reference)
    out_inf = IF.fused_dot_product_attention(
        q, k, v, is_causal_masking=True, dropout_prob=0.5, is_training=False)
    ref = _np_sdpa(q.numpy(), k.numpy(), v.numpy(), causal=True)
    np.testing.assert_allclose(np.asarray(out_inf._value), ref,
                               rtol=2e-4, atol=2e-5)


def test_causal_alignment_matches_between_paths_for_cross_lengths():
    """Sq != Sk causal: the flash fast path and the fallback einsum path
    must agree (bottom-right alignment) — return_softmax forces the
    fallback on an otherwise identical call."""
    rng = np.random.default_rng(6)
    q = paddle.to_tensor(rng.standard_normal((1, 4, 2, 8)).astype("float32"))
    k = paddle.to_tensor(rng.standard_normal((1, 12, 2, 8)).astype("float32"))
    v = paddle.to_tensor(rng.standard_normal((1, 12, 2, 8)).astype("float32"))
    fast = IF.fused_dot_product_attention(q, k, v, is_causal_masking=True,
                                          is_training=False)
    slow, _ = IF.fused_dot_product_attention(q, k, v, is_causal_masking=True,
                                             is_training=False,
                                             return_softmax=True)
    np.testing.assert_allclose(np.asarray(fast._value),
                               np.asarray(slow._value), rtol=2e-4, atol=2e-5)


def test_flash_attn_unpadded_blocks_cross_sequence_attention():
    """Varlen flash (reference flash_attn_unpadded): packed sequences
    must attend only within their own boundaries; per-sequence results
    equal running plain attention on each sequence separately."""
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(7)
    lens = [3, 5, 2]
    cu = np.concatenate([[0], np.cumsum(lens)]).astype("int32")
    N, H = 2, 4
    total = sum(lens)
    q = rng.standard_normal((total, N, H)).astype("float32")
    k = rng.standard_normal((total, N, H)).astype("float32")
    v = rng.standard_normal((total, N, H)).astype("float32")
    scale = 1.0 / np.sqrt(H)
    out, _ = F.flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cu), paddle.to_tensor(cu), max(lens), max(lens),
        scale, training=False)
    got = np.asarray(out._value)
    for s, e in zip(cu[:-1], cu[1:]):
        ref = _np_sdpa(q[None, s:e], k[None, s:e], v[None, s:e],
                       scale=scale)[0]
        np.testing.assert_allclose(got[s:e], ref, rtol=2e-4, atol=2e-5)


def test_flash_attn_unpadded_causal_matches_per_sequence():
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(8)
    lens = [4, 2]
    cu = np.concatenate([[0], np.cumsum(lens)]).astype("int32")
    N, H = 1, 4
    total = sum(lens)
    q, k, v = (rng.standard_normal((total, N, H)).astype("float32")
               for _ in range(3))
    scale = 1.0 / np.sqrt(H)
    out, _ = F.flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cu), paddle.to_tensor(cu), 4, 4, scale,
        causal=True, training=False)
    got = np.asarray(out._value)
    for s, e in zip(cu[:-1], cu[1:]):
        ref = _np_sdpa(q[None, s:e], k[None, s:e], v[None, s:e],
                       causal=True, scale=scale)[0]
        np.testing.assert_allclose(got[s:e], ref, rtol=2e-4, atol=2e-5)


def test_flash_attn_qkvpacked_and_sdp_kernel():
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(9)
    qkv = rng.standard_normal((1, 8, 3, 2, 4)).astype("float32")
    with F.sdp_kernel(enable_math=True, enable_flash=False,
                      enable_mem_efficient=False):
        out, _ = F.flash_attn_qkvpacked(paddle.to_tensor(qkv), causal=True,
                                        training=False)
    ref = _np_sdpa(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], causal=True)
    np.testing.assert_allclose(np.asarray(out._value), ref,
                               rtol=2e-4, atol=2e-5)
    with pytest.raises(ValueError, match="at least one backend"):
        F.sdp_kernel(enable_math=False, enable_flash=False,
                     enable_mem_efficient=False)


def test_sdp_kernel_actually_gates_flash_dispatch(monkeypatch):
    """sdp_kernel must change dispatch, not just record flags: with flash
    disabled the Pallas kernel is never invoked."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu import ops as _ops

    rng = np.random.default_rng(10)
    q, k, v = (paddle.to_tensor(rng.standard_normal((1, 8, 2, 4))
                                .astype("float32")) for _ in range(3))

    calls = []
    real = _ops.flash_attention
    monkeypatch.setattr(_ops, "flash_attention",
                        lambda *a, **kw: calls.append(1) or real(*a, **kw))
    monkeypatch.setattr(_ops, "use_pallas", lambda: True)
    with F.sdp_kernel(enable_math=True, enable_flash=False,
                      enable_mem_efficient=False):
        F.scaled_dot_product_attention(q, k, v, is_causal=True)
    assert calls == [], "flash path ran despite enable_flash=False"
    F.scaled_dot_product_attention(q, k, v, is_causal=True)
    assert calls == [1], "flash path should run by default"


def test_flash_attn_unpadded_rejects_padded_buffers():
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(11)
    q, k, v = (paddle.to_tensor(rng.standard_normal((12, 1, 4))
                                .astype("float32")) for _ in range(3))
    cu = paddle.to_tensor(np.array([0, 3, 8, 10], "int32"))  # 10 != 12 rows
    with pytest.raises(ValueError, match="cover the packed buffer"):
        F.flash_attn_unpadded(q, k, v, cu, cu, 5, 5, 0.5, training=False)


def test_flash_attn_unpadded_zero_key_rows_output_zero():
    """causal with len_k < len_q: query rows preceding every key must
    output ZEROS, never a uniform average over other sequences' values."""
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(12)
    # q: two sequences of 4; k: two sequences of 2 (packed totals differ)
    cq = paddle.to_tensor(np.array([0, 4, 8], "int32"))
    ck = paddle.to_tensor(np.array([0, 2, 4], "int32"))
    q = paddle.to_tensor(rng.standard_normal((8, 1, 4)).astype("float32"))
    k = paddle.to_tensor(rng.standard_normal((4, 1, 4)).astype("float32"))
    v = paddle.to_tensor(np.ones((4, 1, 4), "float32") * 100.0)
    out, _ = F.flash_attn_unpadded(q, k, v, cq, ck, 4, 2, 0.5, causal=True,
                                   training=False)
    got = np.asarray(out._value)
    # bottom-right alignment: q rows 0,1 (pos 0,1; len_k-len_q = -2) have
    # no visible keys in each sequence
    np.testing.assert_allclose(got[0], 0.0)
    np.testing.assert_allclose(got[1], 0.0)
    np.testing.assert_allclose(got[4], 0.0)
    np.testing.assert_allclose(got[5], 0.0)
    assert np.abs(got[[2, 3, 6, 7]]).max() > 1.0  # visible rows attend


def test_sdp_kernel_all_xla_backends_disabled_raises_on_masked_call():
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(13)
    q, k, v = (paddle.to_tensor(rng.standard_normal((1, 4, 1, 4))
                                .astype("float32")) for _ in range(3))
    mask = paddle.to_tensor(np.zeros((1, 1, 4, 4), "float32"))
    with F.sdp_kernel(enable_math=False, enable_flash=True,
                      enable_mem_efficient=False):
        with pytest.raises(RuntimeError, match="no enabled backend"):
            F.scaled_dot_product_attention(q, k, v, attn_mask=mask)


def test_fused_attention_ops_join_amp_white_list():
    """auto_cast must route the fused attention tier to bf16 (MXU ops) —
    an un-whitelisted name would silently stay fp32."""
    rng = np.random.default_rng(14)
    q, k, v = (paddle.to_tensor(rng.standard_normal((1, 8, 2, 8))
                                .astype("float32")) for _ in range(3))
    with paddle.amp.auto_cast():
        out = IF.fused_dot_product_attention(q, k, v, is_causal_masking=True,
                                             is_training=False)
    assert "bfloat16" in str(out.dtype)
