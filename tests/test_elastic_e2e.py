"""Elastic fault tolerance end-to-end: kill one worker of a 2-process CPU
job mid-training under the launcher; the job must be detected as failed,
relaunched, resume from the latest distributed checkpoint, and the loss
curve must CONTINUE (steps don't restart at 0).

Reference: python/paddle/distributed/fleet/elastic/manager.py:126 fault
detect + relaunch loop; checkpoint-resume is the framework's
distributed.checkpoint save/load (per-rank shards + metadata).
"""

import os
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    addr = os.environ["MASTER_ADDR"] + ":" + os.environ["MASTER_PORT"]
    # incarnation 2 re-binds the coordinator port the killed incarnation
    # held: retry while the OS releases it
    for attempt in range(6):
        try:
            jax.distributed.initialize(addr, num_processes=world, process_id=rank)
            break
        except Exception:
            if attempt == 5:
                raise
            time.sleep(3)
    sys.path.insert(0, "__REPO__")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import checkpoint as ckpt

    CKPT = os.environ["ELASTIC_CKPT_DIR"]
    TOTAL, KILL_AT = 8, 4

    paddle.seed(0)  # same init on both ranks
    model = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(0.05, parameters=model.parameters())
    sd = {"w": model.weight, "b": model.bias,
          "step": paddle.to_tensor(np.zeros((), np.int32))}

    start = 0
    latest = os.path.join(CKPT, "latest")
    if os.path.exists(latest):
        with open(latest) as f:
            tag = f.read().strip()
        ckpt.load_state_dict(sd, os.path.join(CKPT, tag))
        start = int(np.asarray(sd["step"]._value))
    print(f"START rank {rank} start_step {start}", flush=True)

    rng = np.random.default_rng(100 + rank)  # different data per rank
    x = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    y = paddle.to_tensor(np.zeros((8, 1), np.float32))

    def barrier():
        t = paddle.to_tensor(np.zeros(1, np.float32))
        dist.all_reduce(t)

    for step in range(start, TOTAL):
        if rank == 1 and start == 0 and step == KILL_AT:
            print(f"KILLED_SELF rank {rank} at step {step}", flush=True)
            os._exit(23)
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        for p in model.parameters():
            dist.all_reduce(p.grad, op=dist.ReduceOp.AVG)
        opt.step()
        opt.clear_grad()
        print(f"STEP rank {rank} step {step} loss {float(loss._value):.6f}",
              flush=True)
        # distributed checkpoint: per-rank shards + metadata, then the
        # `latest` marker strictly after BOTH ranks finished writing
        sd["step"] = paddle.to_tensor(np.asarray(step + 1, np.int32))
        tag = f"step_{step + 1}"
        ckpt.save_state_dict(sd, os.path.join(CKPT, tag))
        barrier()
        if rank == 0:
            tmp = latest + ".tmp"
            with open(tmp, "w") as f:
                f.write(tag)
            os.replace(tmp, latest)
        barrier()
    print(f"DONE rank {rank}", flush=True)
    """
)


@pytest.mark.slow
def test_elastic_kill_and_recover(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "elastic_worker.py"
    script.write_text(_WORKER.replace("__REPO__", repo))
    ckpt_dir = tmp_path / "ckpt"
    log_dir = tmp_path / "log"
    ckpt_dir.mkdir()
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        free_port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["MASTER_PORT"] = str(free_port)
    env["PADDLE_COORD_PORT"] = str(free_port)
    env["ELASTIC_CKPT_DIR"] = str(ckpt_dir)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--elastic_level", "2",
         "--log_dir", str(log_dir), str(script)],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    logs = {}
    for i in (0, 1):
        p = log_dir / f"workerlog.{i}"
        logs[i] = p.read_text() if p.exists() else ""
    combined = (r.stdout or "") + logs[0] + logs[1]
    assert r.returncode == 0, combined[-3000:]

    # the failure really happened and the launcher relaunched
    assert "KILLED_SELF rank 1 at step 4" in logs[1], logs[1][-2000:]
    assert "restart 1/" in r.stdout, r.stdout[-2000:]

    # both incarnations logged a START; the second resumed from the latest
    # checkpoint, NOT from zero
    starts = [int(l.split("start_step")[1]) for l in logs[0].splitlines()
              if l.startswith("START rank 0")]
    assert starts[0] == 0 and len(starts) == 2, starts
    assert starts[1] >= 3, starts  # resumed near the kill point

    # the step sequence CONTINUES: rank-0 steps across incarnations form a
    # strictly increasing walk ending at TOTAL-1, with the resume step equal
    # to the checkpointed position (no restart from 0)
    steps, losses = [], []
    for l in logs[0].splitlines():
        if l.startswith("STEP rank 0"):
            parts = l.split()
            steps.append(int(parts[4]))
            losses.append(float(parts[6]))
    # dedupe the boundary (the step interrupted mid-save may be re-run)
    assert steps[-1] == 7, steps
    assert all(b - a in (0, 1) for a, b in zip(steps, steps[1:])), steps
    assert steps[steps.index(starts[1])] == starts[1]
    # loss curve continues downward overall (training, not restarting)
    assert losses[-1] < losses[0], losses
    first_resumed = losses[len([s for s in steps if s < starts[1]])]
    assert first_resumed < losses[0], (losses, steps)
    assert "DONE rank 0" in logs[0] and "DONE rank 1" in logs[1]
