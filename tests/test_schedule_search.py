"""Cost-model-driven Pallas schedule search (ROADMAP item 2, the CINN
auto-scheduler role; docs/SCHEDULE_SEARCH.md).

Reference: paddle/cinn/auto_schedule/auto_tuner.h (measured-cost schedule
search) rebuilt TVM/Ansor-style (PAPERS.md 1802.04799) over DISCOVERED
reduction-/matmul-rooted subgraphs — the fusion-miss classes of
"Operator Fusion in XLA" (2301.13062).  Measurement is injected through
schedule_search's measure hooks so every decision here is deterministic on
CPU; the real OpCostModel.measure path is exercised by the bench when the
tunnel is up.
"""

import json
import os

import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.ops import autotune as at
from paddle_tpu.static import schedule_search as ss
from paddle_tpu.static.program import Program, program_guard
from paddle_tpu.static.rewrite import (PallasFusionPass, ProgramGraph,
                                       ScheduleSearchPass)
from paddle_tpu.static.verify import ProgramVerifier, differential_check


@pytest.fixture()
def tmp_cache(tmp_path):
    """Fresh autotune cache under a tmp dir + zeroed search counters."""
    paddle.set_flags({"FLAGS_autotune_cache_dir": str(tmp_path)})
    at._CACHES.clear()
    ss.reset_schedule_search_stats()
    yield tmp_path
    paddle.set_flags({"FLAGS_autotune_cache_dir": ""})
    at._CACHES.clear()
    ss.reset_schedule_search_stats()


def _feed(prog, name, shape, dtype=np.float32):
    return prog.add_feed(prog.new_var(jax.ShapeDtypeStruct(shape, dtype), name))


def _capture_matmul_chain(M=32, K=16, N=64):
    """matmul→bias-add→relu→mean tail: no named pattern matches it (the
    bias add between matmul and act defeats MatmulEpiloguePattern)."""
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (M, K))
        w = _feed(prog, "w", (K, N))
        b = _feed(prog, "b", (N,))
        h = paddle.matmul(x, w)
        h = h + b
        h = F.relu(h)
        out = paddle.mean(h, axis=-1, keepdim=True)
    return prog, out


def _capture_softmax_chain(B=4, S=8, H=32):
    """Manual (decomposed) softmax: reduction-rooted DAG — exp feeds both
    the sum and the divide; FlashAttentionPattern never sees it."""
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (B, S, H))
        m = paddle.max(x, axis=-1, keepdim=True)
        t = paddle.exp(x - m)
        s = paddle.sum(t, axis=-1, keepdim=True)
        out = t / s
    return prog, out


def _win_measure(fn, args, *, label, config):
    """Deterministic: every Pallas candidate wins vs XLA; larger row blocks
    slightly preferred so the chosen config is stable."""
    if config is None:
        return 1.0
    return 0.5 - 1e-4 * config["block_rows"]


def _lose_measure(fn, args, *, label, config):
    return 1.0 if config is None else 5.0


def _optypes(prog):
    return [op.type for op in prog.global_block().ops]


# ---------------------------------------------------------------- discovery


def test_discovery_matmul_rooted_chain_missed_by_named_patterns(tmp_cache):
    prog, out = _capture_matmul_chain()
    assert PallasFusionPass([out._vid]).apply(prog.clone()) == 0
    graph = ProgramGraph(prog, (out._vid,))
    specs = [s for s in (ss.match_subgraph(op, graph)
                         for op in prog.global_block().ops) if s]
    assert len(specs) == 1  # anchored ONCE, at the downstream end
    spec = specs[0]
    assert spec.kind == "matmul"
    assert [type(o).__name__ for o in spec.ops] and len(spec.ops) == 4
    assert spec.has_reduce and not spec.col_tilable
    assert sorted(e.role for e in spec.ext) == ["bcast", "weight", "xrow"]
    assert spec.out_shape == (32, 1) and spec.rows == 32 and spec.cols == 64


def test_discovery_softmax_dag(tmp_cache):
    prog, out = _capture_softmax_chain()
    graph = ProgramGraph(prog, (out._vid,))
    specs = [s for s in (ss.match_subgraph(op, graph)
                         for op in prog.global_block().ops) if s]
    assert len(specs) == 1
    spec = specs[0]
    assert spec.kind == "reduce" and len(spec.ops) == 5  # max,sub,exp,sum,div
    assert spec.rows == 32 and spec.cols == 32
    assert len(spec.ext) == 1 and spec.ext[0].role == "row"


def test_discovery_refuses_side_effect_and_collective(tmp_cache):
    # dropout (RNG side effect) interrupts the chain: ops downstream of it
    # may fuse, the dropout itself and anything upstream never join
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (16, 32))
        h = paddle.exp(x)
        h = F.dropout(h, p=0.5)
        out = paddle.sum(h * h, axis=-1, keepdim=True)
    graph = ProgramGraph(prog, (out._vid,))
    for op in prog.global_block().ops:
        spec = ss.match_subgraph(op, graph)
        if spec is None:
            continue
        assert all("dropout" not in o.type and o.type != "exp"
                   for o in spec.ops)

    # a collective op (side_effect_op_types) is never crossed either
    prog2 = Program()
    with program_guard(prog2):
        x = _feed(prog2, "x2", (16, 32))
        h = paddle.tanh(x)
        red = prog2.record("all_reduce", lambda v: v, (h,), {})
        out2 = paddle.sum(red * red, axis=-1, keepdim=True)
    graph2 = ProgramGraph(prog2, (out2._vid,))
    for op in prog2.global_block().ops:
        spec = ss.match_subgraph(op, graph2)
        if spec is None:
            continue
        assert all(o.type != "all_reduce" and o.type != "tanh"
                   for o in spec.ops)


def test_square_k_matmul_chain_fuses_with_untiled_cols(tmp_cache):
    """Regression: with K == N the matmul activation's cols equal the
    output cols, so col-tiled candidates used to slice the CONTRACTION dim
    (every build failed) and a small measure budget then persisted the
    subgraph as disabled despite valid untiled winners.  The xrow role
    keeps the activation untiled and build failures no longer burn budget
    slots."""
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (64, 512))
        w = _feed(prog, "w", (512, 512))
        h = paddle.matmul(x, w)
        out = F.relu(h + 1.0)
    reference = prog.clone()
    n = ScheduleSearchPass(
        [out._vid],
        searcher=ss.ScheduleSearcher(measure=_win_measure, budget=2)).apply(prog)
    assert n == 1, ss.schedule_search_stats()
    assert ss.schedule_search_stats()["disabled"] == 0
    assert differential_check(reference, prog, [out._vid],
                              raise_on_error=False) == []


def test_non_last_axis_reduction_on_square_dims_never_fuses(tmp_cache):
    """Regression: with square dims (S == C) an axis=1 reduction's output
    shape coincides with a last-axis reduction's — shape checks alone would
    fuse it and the kernel would replay the baked axis on the collapsed
    2-D block, reducing the WRONG dimension (max abs err ~30 observed).
    Discovery must probe the baked axis and refuse."""
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (2, 16, 16))
        out = paddle.sum(paddle.exp(x), axis=1)
    graph = ProgramGraph(prog, (out._vid,))
    assert all(ss.match_subgraph(op, graph) is None
               for op in prog.global_block().ops)
    reference = prog.clone()
    n = ScheduleSearchPass(
        [out._vid],
        searcher=ss.ScheduleSearcher(measure=_win_measure, budget=2)).apply(prog)
    assert n == 0
    assert differential_check(reference, prog, [out._vid],
                              raise_on_error=False) == []
    # the keepdim last-axis twin of the same shape still fuses fine
    prog2 = Program()
    with program_guard(prog2):
        x2 = _feed(prog2, "x2", (2, 16, 16))
        out2 = paddle.sum(paddle.exp(x2), axis=-1, keepdim=True)
    reference2 = prog2.clone()
    n2 = ScheduleSearchPass(
        [out2._vid],
        searcher=ss.ScheduleSearcher(measure=_win_measure, budget=2)).apply(prog2)
    assert n2 == 1
    assert differential_check(reference2, prog2, [out2._vid],
                              raise_on_error=False) == []


def test_fetch_frontier_interior_vid_refused_via_rollback(tmp_cache):
    """A subgraph spanning a fetched interior value must be rolled back by
    the PR-4 use-def machinery and counted in `.refused`."""
    prog, out = _capture_softmax_chain()
    graph = ProgramGraph(prog, ())
    # fetch the interior exp output alongside the final output
    exp_op = next(op for op in prog.global_block().ops if op.type == "exp")
    interior_vid = exp_op.out_vids[0]
    from paddle_tpu.static.verify import verify_stats

    before = verify_stats()["rewrites_refused"]
    pass_ = ScheduleSearchPass(
        [out._vid, interior_vid],
        searcher=ss.ScheduleSearcher(measure=_win_measure, budget=2))
    n = pass_.apply(prog)
    assert n == 0
    assert pass_.refused >= 1
    assert verify_stats()["rewrites_refused"] == before + pass_.refused
    # program untouched and still valid
    assert "sched_chain_5" not in _optypes(prog)
    assert not ProgramVerifier().verify(prog, [out._vid, interior_vid])


# ------------------------------------------------- candidates and pruning


def test_candidate_space_and_pruning_order(tmp_cache):
    prog, out = _capture_matmul_chain(M=64, K=16, N=32)
    graph = ProgramGraph(prog, (out._vid,))
    spec = next(s for s in (ss.match_subgraph(op, graph)
                            for op in prog.global_block().ops) if s)
    cands = ss.enumerate_candidates(spec)
    assert len(cands) >= 3
    assert all(spec.rows % c["block_rows"] == 0 for c in cands)
    # reduce tail present → the reduced axis is never tiled
    assert all(c["block_cols"] == spec.cols for c in cands)

    # VMEM prune: a huge working set is rejected by the generalized check
    assert at.validate_tile(ss.candidate_vmem_bytes(spec, cands[0])) is None
    assert at.validate_tile(64 << 20) is not None

    # budget caps what gets measured (FLAGS_schedule_search_budget role)
    measured = []

    def counting(fn, args, *, label, config):
        if config is not None:
            measured.append(config)
        return _win_measure(fn, args, label=label, config=config)

    searcher = ss.ScheduleSearcher(measure=counting, budget=2)
    decision = searcher.search(spec)
    assert decision.accepted and len(measured) <= 2
    stats = ss.schedule_search_stats()
    assert stats["measured"] == len(measured)
    assert stats["candidates"] == len(cands)


def test_dimension_order_changes_roofline_traffic(tmp_cache):
    """On a 2-D grid the dimension order decides which operand re-streams
    from HBM — the roofline prune must see different traffic."""
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (32, 16))
        w = _feed(prog, "w", (16, 256))
        b = _feed(prog, "b", (256,))
        out = F.relu(paddle.matmul(x, w) + b)
    graph = ProgramGraph(prog, (out._vid,))
    spec = next(s for s in (ss.match_subgraph(op, graph)
                            for op in prog.global_block().ops) if s)
    assert spec.col_tilable
    cands = ss.enumerate_candidates(spec)
    assert {c["grid_order"] for c in cands} == {"rows_first", "cols_first"}
    cfg = {"block_rows": 8, "block_cols": 128}
    a = ss.candidate_roofline_ms(spec, dict(cfg, grid_order="rows_first"))
    b_ = ss.candidate_roofline_ms(spec, dict(cfg, grid_order="cols_first"))
    assert a != b_
    # and every candidate kernel is numerically exact vs the XLA twin
    rng = np.random.default_rng(0)
    vals = [jax.numpy.asarray(rng.standard_normal(e.shape), e.dtype)
            for e in spec.ext]
    ref = np.asarray(ss.build_reference(spec)(*vals))
    for c in cands:
        got = np.asarray(ss.build_kernel(spec, c)(*vals))
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


# ------------------------------------------------ gate + cache + substitution


def test_accepted_schedule_substitutes_and_matches_numerics(tmp_cache):
    prog, out = _capture_matmul_chain()
    reference = prog.clone()
    pass_ = ScheduleSearchPass(
        [out._vid], searcher=ss.ScheduleSearcher(measure=_win_measure, budget=3))
    assert pass_.apply(prog) == 1
    assert _optypes(prog) == ["sched_chain_4"]
    assert not ProgramVerifier().verify(prog, [out._vid])
    assert differential_check(reference, prog, [out._vid],
                              raise_on_error=False) == []
    stats = ss.schedule_search_stats()
    assert stats["subgraphs_found"] == 1 and stats["accepted"] == 1
    # the winner persisted under the schedule/* namespace with its win meta
    raw = json.load(open(os.path.join(
        str(tmp_cache), at.device_kind_slug() + ".json")))
    (entry,) = raw["schedule/matmul"].values()
    assert entry["meta"]["win"] > 1.0 and "block_rows" in entry["config"]


def test_losing_schedule_disabled_persisted_never_refired(tmp_cache):
    prog, out = _capture_softmax_chain()
    calls = []

    def measure(fn, args, *, label, config):
        calls.append(config)
        return _lose_measure(fn, args, label=label, config=config)

    n = ScheduleSearchPass(
        [out._vid],
        searcher=ss.ScheduleSearcher(measure=measure, budget=2)).apply(prog)
    assert n == 0 and len(calls) > 0
    assert "sched_chain_5" not in _optypes(prog)
    stats = ss.schedule_search_stats()
    assert stats["disabled"] == 1 and stats["accepted"] == 0
    raw = json.load(open(os.path.join(
        str(tmp_cache), at.device_kind_slug() + ".json")))
    (entry,) = raw["schedule/reduce"].values()
    assert entry["config"] == {"disabled": True}
    assert entry["meta"]["win"] < 1.0

    # cold reload: fresh cache objects + fresh pass — the disabled entry
    # must stop the search before ANY measurement
    at._CACHES.clear()
    calls.clear()
    prog2, out2 = _capture_softmax_chain()
    n2 = ScheduleSearchPass(
        [out2._vid],
        searcher=ss.ScheduleSearcher(measure=measure, budget=2)).apply(prog2)
    assert n2 == 0 and calls == []
    assert ss.schedule_search_stats()["disabled_hits"] >= 1


def test_accepted_schedule_served_from_cache_without_remeasure(tmp_cache):
    prog, out = _capture_matmul_chain()
    ScheduleSearchPass(
        [out._vid],
        searcher=ss.ScheduleSearcher(measure=_win_measure, budget=2)).apply(prog)
    at._CACHES.clear()
    calls = []

    def measure(fn, args, *, label, config):
        calls.append(config)
        return 1.0

    prog2, out2 = _capture_matmul_chain()
    reference = prog2.clone()
    n = ScheduleSearchPass(
        [out2._vid],
        searcher=ss.ScheduleSearcher(measure=measure, budget=2)).apply(prog2)
    assert n == 1 and calls == []  # config reloaded, zero re-measurement
    assert ss.schedule_search_stats()["cache_hits"] >= 1
    assert differential_check(reference, prog2, [out2._vid],
                              raise_on_error=False) == []


# ------------------------------------------------------ K-tiling (phase 2)


def _capture_epilogue_chain(M, K, N):
    """matmul→bias-add→relu (col-tilable, no reduce tail): the class whose
    large-K shapes used to be auto-disabled when no whole-K candidate fit
    VMEM."""
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (M, K))
        w = _feed(prog, "w", (K, N))
        b = _feed(prog, "b", (N,))
        out = F.relu(paddle.matmul(x, w) + b)
    return prog, out


def _spec_of(prog, out):
    graph = ProgramGraph(prog, (out._vid,))
    return next(s for s in (ss.match_subgraph(op, graph)
                            for op in prog.global_block().ops) if s)


@pytest.mark.parametrize("M,K,N", [
    (32, 256, 64),    # non-square M/N/K
    (32, 256, 256),   # K == N: the xrow-aliasing twin (PR-8 class)
    (256, 256, 64),   # K == M: the weight-shape-aliasing twin
])
def test_ktiled_all_candidates_numerics_sweep(tmp_cache, M, K, N):
    """Every enumerated candidate — K-tiled ones included — must match
    the XLA twin numerically, across non-square M/N/K and both PR-8
    square-dim aliasing twins."""
    prog, out = _capture_epilogue_chain(M, K, N)
    spec = _spec_of(prog, out)
    assert spec.k_tilable
    cands = ss.enumerate_candidates(spec)
    ktiled = [c for c in cands if c.get("block_k", K) < K]
    assert ktiled, "large K must enumerate contraction splits"
    assert all(K % c["block_k"] == 0 for c in ktiled)
    # K-tiled candidates pin the contraction innermost: one outer order
    assert all(c["grid_order"] == "rows_first" for c in ktiled)
    rng = np.random.default_rng(0)
    vals = [jax.numpy.asarray(rng.standard_normal(e.shape), e.dtype)
            for e in spec.ext]
    ref = np.asarray(ss.build_reference(spec)(*vals))
    for c in cands:
        got = np.asarray(ss.build_kernel(spec, c)(*vals))
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3,
                                   err_msg=str(c))


def test_ktiled_reduce_tail_chain_numerics(tmp_cache):
    """The matmul→bias→act→reduce class K-tiles too: the accumulator
    finishes before the epilogue's reduction replays."""
    prog, out = _capture_matmul_chain(M=32, K=256, N=64)
    spec = _spec_of(prog, out)
    assert spec.k_tilable and spec.has_reduce and not spec.col_tilable
    cands = [c for c in ss.enumerate_candidates(spec)
             if c.get("block_k", 256) < 256]
    assert cands
    rng = np.random.default_rng(0)
    vals = [jax.numpy.asarray(rng.standard_normal(e.shape), e.dtype)
            for e in spec.ext]
    ref = np.asarray(ss.build_reference(spec)(*vals))
    for c in cands:
        got = np.asarray(ss.build_kernel(spec, c)(*vals))
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3,
                                   err_msg=str(c))


def test_ktile_rescues_vmem_bound_chain(tmp_cache):
    """A contraction dim too large for any whole-K candidate used to
    auto-disable the chain (every candidate VMEM-pruned).  With block_k
    in the space the search accepts a schedule — and the roofline still
    ranks the split honestly (re-streaming both operands costs more
    traffic than a whole-K candidate of the same block shape)."""
    prog, out = _capture_epilogue_chain(8, 16384, 128)
    spec = _spec_of(prog, out)
    cands = ss.enumerate_candidates(spec)
    whole_k = [c for c in cands if c.get("block_k", 0) == 16384]
    ktiled = [c for c in cands if c.get("block_k", 16384) < 16384]
    assert whole_k and ktiled
    # the whole-K working set busts the budget; the split fits
    assert all(at.validate_tile(ss.candidate_vmem_bytes(spec, c))
               is not None for c in whole_k)
    assert any(at.validate_tile(ss.candidate_vmem_bytes(spec, c)) is None
               for c in ktiled)
    reference = prog.clone()
    n = ScheduleSearchPass(
        [out._vid],
        searcher=ss.ScheduleSearcher(measure=_win_measure, budget=2)
    ).apply(prog)
    assert n == 1, ss.schedule_search_stats()
    assert differential_check(reference, prog, [out._vid],
                              raise_on_error=False) == []
    # the accepted (and persisted) config is a genuine contraction split
    raw = json.load(open(os.path.join(
        str(tmp_cache), at.device_kind_slug() + ".json")))
    entry = next(v for k, v in raw["schedule/matmul"].items()
                 if "k=16384" in k)
    assert 0 < entry["config"]["block_k"] < 16384


def test_ktiled_roofline_costs_restreaming(tmp_cache):
    """K-order honesty: at identical block shape a K-tiled candidate
    models MORE traffic (activation re-streams per column block, weight
    per row block, plus the accumulator write) — the split only ranks
    ahead when VMEM or overhead says so, never for free."""
    prog, out = _capture_epilogue_chain(64, 512, 256)
    spec = _spec_of(prog, out)
    base = {"block_rows": 32, "block_cols": 128, "grid_order": "rows_first"}
    untiled = dict(base, block_k=512)
    split = dict(base, block_k=128)
    assert (ss.candidate_roofline_ms(spec, split)
            > ss.candidate_roofline_ms(spec, untiled))
    # and the split's working set is genuinely smaller
    assert (ss.candidate_vmem_bytes(spec, split)
            < ss.candidate_vmem_bytes(spec, untiled))


def test_ktile_never_offered_when_mm_operand_feeds_elem(tmp_cache):
    """K == N aliasing twin where the matmul ACTIVATION also feeds an
    elementwise op: slicing the contraction dim would hand that op a
    (br, bk) block where it needs (br, K) — discovery must refuse the
    split (and col tiling, per PR 8)."""
    prog = Program()
    with program_guard(prog):
        x = _feed(prog, "x", (32, 256))
        w = _feed(prog, "w", (256, 256))
        h = paddle.matmul(x, w)
        out = F.relu(h + x)  # x re-enters the chain at row shape
    spec = _spec_of(prog, out)
    assert not spec.k_tilable and not spec.col_tilable
    assert all(c.get("block_k") is None
               for c in ss.enumerate_candidates(spec))


# --------------------------------------------------------- e2e + telemetry


def test_executor_flag_e2e_with_verify(tmp_cache):
    """FLAGS_schedule_search end-to-end through Executor.run: discovered,
    searched, substituted, and differentially verified on the live feed."""
    import paddle_tpu.static as static

    rng = np.random.default_rng(0)
    feed = {
        "x": rng.normal(size=(32, 16)).astype(np.float32),
        "w": rng.normal(size=(16, 64)).astype(np.float32),
        "b": rng.normal(size=(64,)).astype(np.float32),
    }
    prog_off, out_off = _capture_matmul_chain()
    ref = static.Executor().run(prog_off, feed=feed, fetch_list=[out_off])
    assert "sched_chain_4" not in _optypes(prog_off)

    from paddle_tpu.profiler import verify_stats

    before = verify_stats()
    paddle.set_flags({"FLAGS_schedule_search": True,
                      "FLAGS_verify_programs": True,
                      "FLAGS_schedule_search_budget": 2})
    try:
        with ss.measure_override(_win_measure):
            prog_on, out_on = _capture_matmul_chain()
            got = static.Executor().run(prog_on, feed=feed, fetch_list=[out_on])
        assert "sched_chain_4" in _optypes(prog_on)
        np.testing.assert_allclose(got[0], ref[0], rtol=2e-3, atol=2e-3)
        after = verify_stats()
        # the substitution WAS differentially replayed, and cleanly
        assert after["differential_checks"] > before["differential_checks"]
        assert after["differential_failures"] == before["differential_failures"]
    finally:
        paddle.set_flags({"FLAGS_schedule_search": False,
                          "FLAGS_verify_programs": False,
                          "FLAGS_schedule_search_budget": 6})


def test_profiler_summary_footer(tmp_cache):
    prog, out = _capture_matmul_chain()
    ScheduleSearchPass(
        [out._vid],
        searcher=ss.ScheduleSearcher(measure=_win_measure, budget=2)).apply(prog)
    from paddle_tpu import profiler

    stats = profiler.schedule_search_stats()
    assert stats["subgraphs_found"] == 1
    p = profiler.Profiler(timer_only=True)
    p.start()
    p.stop()
    text = p.summary()
    assert "Schedule search:" in text
    assert "pruned_roofline" in text and "disabled" in text


def test_lint_sweep_zero_violations(tmp_cache):
    """Programs rewritten with the new pass verify clean (the lint_ir bar)."""
    programs = []
    for cap in (_capture_matmul_chain, _capture_softmax_chain):
        prog, out = cap()
        ScheduleSearchPass(
            [out._vid],
            searcher=ss.ScheduleSearcher(measure=_win_measure,
                                         budget=2)).apply(prog)
        programs.append((prog, [out._vid]))
    v = ProgramVerifier()
    assert all(not v.verify(p, f) for p, f in programs)
