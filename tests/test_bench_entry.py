"""The driver's bench entry point (bench.py parent->probe->child) must
stay runnable — a syntax/import/harness regression here forfeits the
round's one driver-recorded measurement."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_bench_smoke_flag_asserts_payload_fields():
    """`bench.py --smoke` is the CPU twin of the on-chip payload: it must
    emit the full payload (per-config mfu + the simulator pipeline
    section) and self-assert the field contract (BENCH_SMOKE_OK)."""
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=840)
    assert out.returncode == 0, out.stderr[-500:]
    assert "BENCH_SMOKE_OK" in out.stdout
    line = next(ln for ln in reversed(out.stdout.splitlines())
                if ln.startswith("{"))
    payload = json.loads(line)
    assert payload["configs"] and all("mfu" in c for c in payload["configs"])
    sch = payload["detail"]["pipeline"]["schedules"]
    assert sch["ZB-H1"] < sch["1F1B"]


@pytest.mark.slow
def test_bench_parent_harness_cpu_smoke():
    env = dict(os.environ, PADDLE_TPU_BENCH_CPU="1")
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py")],
        capture_output=True, text=True, timeout=840, env=env)
    assert out.returncode == 0, out.stderr[-500:]
    line = next(ln for ln in reversed(out.stdout.splitlines())
                if ln.startswith("{"))
    payload = json.loads(line)
    assert payload["metric"] == "llama_pretrain_tokens_per_sec_per_chip"
    assert payload["value"] > 0
    assert payload["config"] == "cpu_smoke"
