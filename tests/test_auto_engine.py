"""Auto-parallel static Engine slice (reference
python/paddle/distributed/auto_parallel/static/engine.py:59): Engine.fit on
a dp x mp mesh must match single-device dygraph numerics — the Completer/
Partitioner/Resharder roles are delegated to GSPMD (see engine.py docs)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import ProcessMesh
from paddle_tpu.distributed.auto_parallel import Engine, Strategy, shard_tensor
from paddle_tpu.distributed.auto_parallel.placement import Replicate, Shard


class GptPattern(nn.Layer):
    """Embedding -> column linear -> gelu -> row linear -> head (the
    reference's get_gpt_model.py test pattern, reduced)."""

    def __init__(self, vocab=64, hidden=32, inner=64):
        super().__init__()
        self.emb = nn.Embedding(vocab, hidden)
        self.up = nn.Linear(hidden, inner)
        self.down = nn.Linear(inner, hidden)
        self.head = nn.Linear(hidden, vocab)

    def forward(self, ids):
        h = self.emb(ids)
        h = self.down(nn.functional.gelu(self.up(h)))
        return self.head(h)


def _shard_gpt(m, mesh):
    # megatron pattern: up column-sharded, down row-sharded over 'mp'
    from paddle_tpu.distributed.auto_parallel.api import _mark_dist

    _mark_dist(m.up.weight, mesh, [Replicate(), Shard(1)])
    _mark_dist(m.up.bias, mesh, [Shard(0)])
    _mark_dist(m.down.weight, mesh, [Shard(0), Replicate()])
    return m


def _data(n=32, seq=8, vocab=64):
    rng = np.random.default_rng(0)
    x = rng.integers(0, vocab, (n, seq)).astype(np.int32)
    y = rng.integers(0, vocab, (n, seq)).astype(np.int32)
    return x, y


def _loss():
    ce = nn.CrossEntropyLoss()

    def f(logits, labels):
        return ce(logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]))

    return f


@pytest.mark.slow
def test_engine_fit_matches_dygraph():
    x, y = _data()

    # dygraph single-device reference
    paddle.seed(7)
    ref = GptPattern()
    ref_opt = paddle.optimizer.AdamW(1e-3, parameters=ref.parameters(), weight_decay=0.0)
    loss_fn = _loss()
    ref_losses = []
    for i in range(0, 32, 8):
        out = ref(paddle.to_tensor(x[i : i + 8]))
        l = loss_fn(out, paddle.to_tensor(y[i : i + 8]))
        l.backward()
        ref_opt.step()
        ref_opt.clear_grad()
        ref_losses.append(float(l._value))

    # Engine on dp2 x mp4
    paddle.seed(7)
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
    model = _shard_gpt(GptPattern(), mesh)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters(), weight_decay=0.0)
    eng = Engine(model, _loss(), opt, strategy=Strategy({"sharding": {"enable": True, "stage": 1}}))
    logs = eng.fit((x, y), epochs=1, batch_size=8)

    np.testing.assert_allclose(logs["loss"], ref_losses, rtol=2e-3, atol=2e-3)


def test_engine_prepare_evaluate_predict_save():
    x, y = _data(16)
    paddle.seed(1)
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
    model = _shard_gpt(GptPattern(), mesh)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    eng = Engine(model, _loss(), opt)
    eng.prepare()
    assert eng.main_program is not None
    ev = eng.evaluate((x, y), batch_size=8)
    assert len(ev["loss"]) == 2 and all(np.isfinite(ev["loss"]))
    preds = eng.predict((x,), batch_size=8)
    assert len(preds) == 2
    eng.save("/tmp/auto_eng_test")
    eng.load("/tmp/auto_eng_test")


def _tiny_llama():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(21)
    cfg = llama_tiny(vocab_size=128, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=4, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=32,
                     dtype="float32")
    return LlamaForCausalLM(cfg)


class _LMLoss:
    def __call__(self, out, labels):
        # LlamaForCausalLM called without labels returns logits
        import paddle_tpu.nn.functional as F

        return F.cross_entropy(
            out.reshape([-1, out.shape[-1]]), labels.reshape([-1]))


def test_engine_auto_mode_selects_plan_and_matches_dygraph():
    """VERDICT r2 item 10: Engine(strategy=auto) picks dp/mp/pp for the
    8-device mesh via the tuner's grid search + pruning + HBM model, and
    fit matches the dygraph run."""
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (8, 16)).astype(np.int32)
    labels = rng.integers(0, 128, (8, 16)).astype(np.int64)

    ref_model = _tiny_llama()
    ref_opt = paddle.optimizer.AdamW(1e-3, parameters=ref_model.parameters())
    ref_losses = []
    for _ in range(3):
        loss, _ = ref_model(paddle.to_tensor(ids), labels=paddle.to_tensor(labels))
        loss.backward()
        ref_opt.step()
        ref_opt.clear_grad()
        ref_losses.append(float(loss._value))

    model = _tiny_llama()
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    eng = Engine(model, _LMLoss(), opt, strategy=Strategy({"auto_mode": "auto"}))
    logs = eng.fit((ids, labels), epochs=3, batch_size=8)
    plan = eng._plan
    degrees = plan["dp_degree"] * plan["mp_degree"] * plan["pp_degree"]
    assert degrees == 8, plan
    np.testing.assert_allclose(logs["loss"], ref_losses, rtol=2e-3, atol=2e-4)


def test_engine_auto_mode_memory_pressure_selects_model_parallel():
    """A tight per-chip HBM budget prunes the dp-heavy plans: the tuner
    must fall back to mp/pp to fit, and fit still matches dygraph."""
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 128, (8, 16)).astype(np.int32)
    labels = rng.integers(0, 128, (8, 16)).astype(np.int64)

    ref_model = _tiny_llama()
    ref_opt = paddle.optimizer.AdamW(1e-3, parameters=ref_model.parameters())
    ref_losses = []
    for _ in range(2):
        loss, _ = ref_model(paddle.to_tensor(ids), labels=paddle.to_tensor(labels))
        loss.backward()
        ref_opt.step()
        ref_opt.clear_grad()
        ref_losses.append(float(loss._value))

    model = _tiny_llama()
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    # pretend the model is 3B params on 8 GiB chips (divisibility matches
    # the real tiny model): the HBM model must prune the dp-heavy plans
    # whose unsharded optimizer state cannot fit, forcing mp/pp
    eng = Engine(model, _LMLoss(), opt,
                 strategy=Strategy({"auto_mode": "auto",
                                    "tuner": {
                                        "hbm_gb": 8,
                                        "model_cfg": {
                                            "num_params": 3e9,
                                            "hidden_size": 2048,
                                            "num_layers": 4,
                                            "num_attention_heads": 4,
                                            "vocab_size": 128,
                                            "intermediate_size": 4096,
                                            "seq_length": 32,
                                            "global_batch_size": 8,
                                        },
                                    }}))
    logs = eng.fit((ids, labels), epochs=2, batch_size=8)
    plan = eng._plan
    assert plan["mp_degree"] * plan["pp_degree"] > 1, plan
    assert plan["dp_degree"] * plan["mp_degree"] * plan["pp_degree"] == 8, plan
    np.testing.assert_allclose(logs["loss"], ref_losses, rtol=2e-3, atol=2e-4)


def test_engine_strategy_gradient_merge_and_recompute():
    """Strategy gradient_merge/recompute knobs are LIVE (reference
    engine.py Parallelizer applying the distributed passes): the optimizer
    is wrapped with the k-step merger inside the compiled step, params move
    only on boundary steps, and the model still trains."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed.auto_parallel.engine import Engine, Strategy
    from paddle_tpu.incubate.optimizer import GradientMergeOptimizer

    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    o = opt.Momentum(learning_rate=0.1, momentum=0.9, parameters=m.parameters())
    strat = Strategy({
        "gradient_merge": {"enable": True, "k_steps": 2, "avg": True},
        "recompute": {"enable": True, "layers": ["0"]},
    })
    eng = Engine(model=m, loss=nn.MSELoss(), optimizer=o, strategy=strat)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = (x[:, :1] * 0.5).astype(np.float32)

    p0 = [np.asarray(p._value).copy() for p in m.parameters()]
    logs = eng.fit((x, y), epochs=1, batch_size=16, steps_per_epoch=1)
    assert isinstance(eng._optimizer, GradientMergeOptimizer)
    # one micro-step of k=2: accumulate only, no param movement
    p1 = [np.asarray(p._value) for p in m.parameters()]
    for a, b in zip(p0, p1):
        np.testing.assert_allclose(b, a, err_msg="params moved before boundary")
    logs = eng.fit((x, y), epochs=1, batch_size=16, steps_per_epoch=3)
    p2 = [np.asarray(p._value) for p in m.parameters()]
    assert any(not np.allclose(a, b) for a, b in zip(p0, p2)), "never updated"
    assert all(np.isfinite(v) for v in logs["loss"])
