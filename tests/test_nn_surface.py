"""Numerics for the round-2 nn-surface closure: losses, pooling masks,
spatial transformers, beam search, LBFGS, saved-tensor hooks.

Reference parity targets cited per test (python/paddle/nn/...).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def test_submodule_surfaces_complete():
    import importlib
    import re

    pairs = [
        ("nn", "nn/__init__.py"),
        ("nn.functional", "nn/functional/__init__.py"),
        ("nn.initializer", "nn/initializer/__init__.py"),
        ("static", "static/__init__.py"),
        ("jit", "jit/__init__.py"),
        ("autograd", "autograd/__init__.py"),
        ("optimizer", "optimizer/__init__.py"),
        ("amp", "amp/__init__.py"),
        ("vision.ops", "vision/ops.py"),
        ("incubate.nn.functional", "incubate/nn/functional/__init__.py"),
    ]
    for name, path in pairs:
        src = open(f"/root/reference/python/paddle/{path}").read()
        m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
        if not m:
            continue
        ref = set(re.findall(r"'([^']+)'", m.group(1)))
        mod = importlib.import_module(f"paddle_tpu.{name}")
        missing = sorted(n for n in ref if not hasattr(mod, n))
        assert not missing, f"paddle.{name} missing {missing}"


def test_max_pool_mask_and_unpool_roundtrip():
    rng = np.random.default_rng(3)
    x = paddle.to_tensor(rng.standard_normal((2, 3, 8, 8)).astype(np.float32))
    out, mask = F.max_pool2d(x, 2, return_mask=True)
    ref = np.asarray(x._value).reshape(2, 3, 4, 2, 4, 2).max(axis=(3, 5))
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-6)
    # indices address the original map
    flat = np.asarray(x._value).reshape(2, 3, -1)
    gathered = np.take_along_axis(flat, np.asarray(mask._value).reshape(2, 3, -1), axis=2)
    np.testing.assert_allclose(gathered.reshape(ref.shape), ref, rtol=1e-6)
    unp = F.max_unpool2d(out, mask, 2)
    assert unp.shape == [2, 3, 8, 8]
    np.testing.assert_allclose(np.asarray(unp._value).sum(), ref.sum(), rtol=1e-5)
    # layer forms
    o1, m1 = F.max_pool1d(paddle.to_tensor(rng.standard_normal((2, 3, 8)).astype(np.float32)), 2, return_mask=True)
    assert paddle.nn.MaxUnPool1D(2)(o1, m1).shape == [2, 3, 8]


def test_affine_grid_sample_shift():
    # translation by one pixel in x (align_corners grid step = 2/(W-1))
    x = paddle.to_tensor(np.arange(16).reshape(1, 1, 4, 4).astype(np.float32))
    shift = 2.0 / 3.0
    theta = paddle.to_tensor(np.array([[[1, 0, shift], [0, 1, 0]]], np.float32))
    grid = F.affine_grid(theta, [1, 1, 4, 4], align_corners=True)
    y = np.asarray(F.grid_sample(x, grid, align_corners=True)._value)
    ref = np.asarray(x._value)
    np.testing.assert_allclose(y[0, 0, :, :3], ref[0, 0, :, 1:], atol=1e-4)
    np.testing.assert_allclose(y[0, 0, :, 3], 0.0, atol=1e-5)  # zeros padding


def test_multi_margin_and_triplet_with_distance():
    logits = paddle.to_tensor(np.array([[0.1, 0.9, 0.2]], np.float32))
    label = paddle.to_tensor(np.array([1], np.int64))
    loss = float(F.multi_margin_loss(logits, label)._value)
    ref = (max(0, 1 - 0.9 + 0.1) + max(0, 1 - 0.9 + 0.2)) / 3
    assert abs(loss - ref) < 1e-6
    a = paddle.to_tensor(np.zeros((2, 4), np.float32))
    p = paddle.to_tensor(np.ones((2, 4), np.float32) * 0.1)
    n = paddle.to_tensor(np.ones((2, 4), np.float32))
    # d_pos=0.2, d_neg=2, margin=1 -> max(0, 0.2-2+1)=0
    assert float(F.triplet_margin_with_distance_loss(a, p, n)._value) == 0.0
    # swapped roles: d_pos=2, d_neg=0.2 -> 2-0.2+1=2.8
    l1 = float(F.triplet_margin_with_distance_loss(a, n, p)._value)
    assert abs(l1 - 2.8) < 1e-5
    layer = paddle.nn.TripletMarginWithDistanceLoss()
    assert abs(float(layer(a, n, p)._value) - l1) < 1e-6


def test_hsigmoid_loss_decreases_under_training():
    paddle.seed(0)
    B, D, C = 8, 6, 5
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((B, D)).astype(np.float32))
    y = paddle.to_tensor(rng.integers(0, C, (B,)).astype(np.int64))
    layer = paddle.nn.HSigmoidLoss(D, C)
    opt = paddle.optimizer.SGD(0.5, parameters=layer.parameters())
    losses = []
    for _ in range(30):
        loss = layer(x, y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]


def test_rnnt_loss_gradient_and_value():
    paddle.seed(0)
    B, T, U, D = 2, 4, 2, 5
    rng = np.random.default_rng(1)
    logits = paddle.to_tensor(rng.standard_normal((B, T, U + 1, D)).astype(np.float32), stop_gradient=False)
    label = paddle.to_tensor(rng.integers(1, D, (B, U)).astype(np.int32))
    tl = paddle.to_tensor(np.array([T, T], np.int32))
    ul = paddle.to_tensor(np.array([U, U], np.int32))
    loss = F.rnnt_loss(logits, label, tl, ul, blank=0, fastemit_lambda=0.0)
    assert float(loss) > 0
    loss.backward()
    g = np.asarray(logits.grad._value)
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
    # degenerate exact check: T=1, U=0 -> loss = -log softmax(blank)
    lg = paddle.to_tensor(rng.standard_normal((1, 1, 1, 3)).astype(np.float32))
    l2 = F.rnnt_loss(lg, paddle.to_tensor(np.zeros((1, 0), np.int32)),
                     paddle.to_tensor(np.array([1], np.int32)),
                     paddle.to_tensor(np.array([0], np.int32)), blank=0, fastemit_lambda=0.0)
    lv = np.asarray(lg._value)[0, 0, 0]
    ref = -(lv[0] - np.log(np.exp(lv).sum()))
    assert abs(float(l2) - ref) < 1e-5


def test_npair_and_margin_cross_entropy():
    rng = np.random.default_rng(2)
    a = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
    p = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
    y = paddle.to_tensor(np.array([0, 1, 2, 3], np.int64))
    assert float(F.npair_loss(a, p, y)._value) > 0
    # margin CE with zero margins == scaled softmax CE
    cosines = paddle.to_tensor((rng.standard_normal((4, 10)) * 0.3).astype(np.float32))
    loss = F.margin_cross_entropy(cosines, y, margin1=1.0, margin2=0.0, margin3=0.0, scale=4.0)
    lv = np.asarray(cosines._value) * 4.0
    ref = -(lv[np.arange(4), [0, 1, 2, 3]] - np.log(np.exp(lv).sum(1)))
    assert abs(float(loss) - ref.mean()) < 1e-5


def test_class_center_sample():
    y = paddle.to_tensor(np.array([3, 7, 3, 1], np.int64))
    remapped, sampled = F.class_center_sample(y, 20, 6)
    sv = np.asarray(sampled._value)
    rv = np.asarray(remapped._value)
    assert len(sv) == 6 and len(set(sv.tolist())) == 6
    for orig, rm in zip([3, 7, 3, 1], rv):
        assert sv[rm] == orig


def test_beam_search_decoder_greedy_consistency():
    """Beam width 1 must equal greedy argmax decoding."""
    paddle.seed(0)
    V, E, H = 12, 8, 16
    emb = paddle.nn.Embedding(V, E)
    cell = paddle.nn.GRUCell(E, H)
    proj = paddle.nn.Linear(H, V)
    dec = paddle.nn.BeamSearchDecoder(cell, start_token=0, end_token=1, beam_size=1,
                                      embedding_fn=emb, output_fn=proj)
    h0 = paddle.zeros([2, H])
    seqs, _ = paddle.nn.dynamic_decode(dec, inits=h0, max_step_num=5)
    out = np.asarray(seqs._value)
    # greedy reference
    ids = np.zeros(2, np.int64)
    h = h0
    toks = []
    for _ in range(out.shape[1]):  # [batch, time, beam]
        x = emb(paddle.to_tensor(ids.astype(np.int64)))
        o, h = cell(x, h)
        logits = np.asarray(proj(o)._value)
        ids = logits.argmax(-1)
        toks.append(ids)
    ref = np.stack(toks, -1)
    # reference layout: [batch, time, beam]
    np.testing.assert_array_equal(out[:, :, 0], ref)


def test_gather_tree():
    # the reference's documented example (python/paddle/nn/functional/
    # extension.py gather_tree docstring)
    ids = paddle.to_tensor(np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]], np.int64))
    parents = paddle.to_tensor(np.array([[[0, 0], [1, 1]], [[1, 0], [1, 0]], [[0, 0], [0, 1]]], np.int64))
    out = np.asarray(F.gather_tree(ids, parents)._value)
    ref = np.array([[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]])
    np.testing.assert_array_equal(out, ref)


def test_sparse_attention_matches_masked_dense():
    rng = np.random.default_rng(5)
    B, H, S, D = 1, 1, 4, 8
    q = paddle.to_tensor(rng.standard_normal((B, H, S, D)).astype(np.float32))
    k = paddle.to_tensor(rng.standard_normal((B, H, S, D)).astype(np.float32))
    v = paddle.to_tensor(rng.standard_normal((B, H, S, D)).astype(np.float32))
    # causal CSR pattern
    cols, offs = [], [0]
    for i in range(S):
        cols.extend(range(i + 1))
        offs.append(len(cols))
    off = paddle.to_tensor(np.array([[offs]], np.int32))
    col = paddle.to_tensor(np.array([[cols]], np.int32))
    out = np.asarray(F.sparse_attention(q, k, v, off, col)._value)
    ref = np.asarray(F.scaled_dot_product_attention(
        paddle.transpose(q, [0, 2, 1, 3]), paddle.transpose(k, [0, 2, 1, 3]),
        paddle.transpose(v, [0, 2, 1, 3]), is_causal=True)._value).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_saved_tensors_hooks_pack_unpack():
    calls = {"pack": 0, "unpack": 0}

    def pack(t):
        calls["pack"] += 1
        return np.asarray(t._value)  # "offload to host"

    def unpack(h):
        calls["unpack"] += 1
        return paddle.to_tensor(h)

    class Sq(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, g):
            (x,) = ctx.saved_tensor
            return g * 2 * x

    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    with paddle.autograd.saved_tensors_hooks(pack, unpack):
        y = Sq.apply(x)
    y.backward()
    assert calls["pack"] == 1 and calls["unpack"] == 1
    np.testing.assert_allclose(np.asarray(x.grad._value), [6.0])


def test_lbfgs_converges_to_lstsq():
    paddle.seed(0)
    A = paddle.to_tensor(np.random.default_rng(0).standard_normal((10, 5)).astype(np.float32))
    b = paddle.to_tensor(np.random.default_rng(1).standard_normal((10,)).astype(np.float32))
    x = paddle.create_parameter([5], "float32")
    opt = paddle.optimizer.LBFGS(parameters=[x], line_search_fn="strong_wolfe")

    def closure():
        r = paddle.matmul(A, x) - b
        loss = (r * r).sum()
        loss.backward()
        return loss

    for _ in range(5):
        loss = opt.step(closure)
    ref = np.linalg.lstsq(np.asarray(A._value), np.asarray(b._value), rcond=None)[0]
    np.testing.assert_allclose(np.asarray(x._value), ref, atol=1e-3)


def test_static_compat_surface():
    bs = paddle.static.BuildStrategy()
    bs.fuse_bn_act_ops = True  # settable
    es = paddle.static.ExecutionStrategy()
    assert es.num_threads == 1
    places = paddle.static.cuda_places()
    assert len(places) >= 1
    gv = paddle.static.create_global_var([2, 2], 1.5, "float32")
    np.testing.assert_allclose(np.asarray(gv._value), np.full((2, 2), 1.5))
    with pytest.raises(RuntimeError):
        paddle.static.IpuStrategy()
    # EMA swap/restore
    p = paddle.create_parameter([2], "float32", default_initializer=paddle.nn.initializer.Constant(1.0))
    ema = paddle.static.ExponentialMovingAverage(decay=0.5)
    ema.update([p])
    p._bind((p._value * 0 + 3.0))
    ema.update([p])
    before = np.asarray(p._value).copy()
    ema.apply(need_restore=False)
    np.testing.assert_allclose(np.asarray(p._value), [2.0, 2.0])  # 0.5*1 + 0.5*3
    ema.restore()
    np.testing.assert_allclose(np.asarray(p._value), before)


def test_py_func_and_print():
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    out = paddle.static.py_func(lambda a: a * 3.0, x, paddle.zeros([2]))
    np.testing.assert_allclose(np.asarray(out._value), [3.0, 6.0])
    y = paddle.static.Print(x, message="dbg")
    np.testing.assert_allclose(np.asarray(y._value), np.asarray(x._value))


def test_py_func_custom_backward():
    # backward_func receives (x, out, out_grad) and returns dx; the custom
    # rule deliberately disagrees with the analytic grad (returns 10*g)
    # so the test proves backward_func is actually used.
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32), stop_gradient=False)
    out = paddle.static.py_func(
        lambda a: a * 3.0,
        x,
        paddle.zeros([2]),
        backward_func=lambda a, o, g: 10.0 * g,
    )
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), [10.0, 10.0])


def test_bilinear_and_global_initializer():
    init = paddle.nn.initializer.Bilinear()
    w = init._init_value((1, 1, 4, 4), np.float32)
    assert float(np.asarray(w).max()) <= 1.0 and np.asarray(w)[0, 0, 1, 1] > 0.5
    paddle.nn.initializer.set_global_initializer(paddle.nn.initializer.Constant(0.25))
    try:
        lin = paddle.nn.Linear(3, 3)
        np.testing.assert_allclose(np.asarray(lin.weight._value), np.full((3, 3), 0.25))
    finally:
        paddle.nn.initializer.set_global_initializer(None)


def test_temporal_shift_and_unflatten_layer():
    x = paddle.to_tensor(np.arange(2 * 4 * 2 * 2, dtype=np.float32).reshape(2, 4, 2, 2))
    out = F.temporal_shift(x, seg_num=2, shift_ratio=0.25)
    assert out.shape == [2, 4, 2, 2]
    u = paddle.nn.Unflatten(1, [2, 2])
    assert u(x).shape == [2, 2, 2, 2, 2]


def test_remaining_submodule_surfaces_complete():
    """Every remaining reference submodule __all__ resolves (incubate tier,
    utils, audio, vision incl. transforms, profiler, device, fleet)."""
    import importlib
    import os
    import re

    pairs = [
        ("incubate", "incubate/__init__.py"),
        ("incubate.nn", "incubate/nn/__init__.py"),
        ("incubate.optimizer", "incubate/optimizer/__init__.py"),
        ("incubate.autograd", "incubate/autograd/__init__.py"),
        ("utils", "utils/__init__.py"),
        ("audio", "audio/__init__.py"),
        ("vision", "vision/__init__.py"),
        ("vision.transforms", "vision/transforms/__init__.py"),
        ("profiler", "profiler/__init__.py"),
        ("device", "device/__init__.py"),
        ("distributed.fleet", "distributed/fleet/__init__.py"),
    ]
    for name, path in pairs:
        fp = f"/root/reference/python/paddle/{path}"
        if not os.path.exists(fp):
            continue
        m = re.search(r"__all__ = \[(.*?)\]", open(fp).read(), re.S)
        if not m:
            continue
        ref = set(re.findall(r'"([^"]+)"', m.group(1))) | set(re.findall(r"'([^']+)'", m.group(1)))
        mod = importlib.import_module(f"paddle_tpu.{name}")
        missing = sorted(n for n in ref if not hasattr(mod, n))
        assert not missing, f"paddle.{name} missing {missing}"


def test_vision_transform_numerics():
    from paddle_tpu.vision import transforms as T

    img = (np.random.default_rng(0).random((16, 16, 3)) * 255).astype(np.uint8)
    np.testing.assert_allclose(T.rotate(img, 90), np.rot90(img, 1, axes=(0, 1)))
    assert np.abs(T.adjust_hue(img, 0.0).astype(np.float32) - img).max() < 1e-2
    # hue shift by 1/3 permutes pure channels: red -> green
    red = np.zeros((2, 2, 3), np.float32)
    red[..., 0] = 1.0
    shifted = T.adjust_hue(red, 1.0 / 3.0)
    np.testing.assert_allclose(shifted[..., 1], 1.0, atol=1e-5)
    a = T.affine(img, 0, (2, 0), 1.0, (0, 0))
    assert np.array_equal(a[:, 2:], img[:, :-2])
    e = T.erase(img, 2, 3, 4, 5, 0)
    assert (e[2:6, 3:8] == 0).all() and np.array_equal(e[10:], img[10:])
    b = T.adjust_brightness(img, 2.0)
    assert b.max() <= 255.0 and b.mean() >= img.mean()
    out = T.RandomErasing(prob=1.0)(img)
    assert out.shape == img.shape
    rp = T.perspective(img, [(0, 0), (15, 0), (15, 15), (0, 15)], [(0, 0), (15, 0), (15, 15), (0, 15)])
    np.testing.assert_allclose(rp, img)  # identity homography


def test_incubate_autograd_jvp_vjp():
    import paddle_tpu.incubate.autograd as ag

    x = paddle.to_tensor(np.array([2.0], np.float32))

    def f(a):
        return a * a

    primal, tangent = ag.jvp(f, [x], [paddle.to_tensor(np.array([1.0], np.float32))])
    np.testing.assert_allclose(np.asarray(primal[0]._value), [4.0])
    np.testing.assert_allclose(np.asarray(tangent[0]._value), [4.0])  # 2x
    primal, grads = ag.vjp(f, [x])
    np.testing.assert_allclose(np.asarray(grads[0]._value), [4.0])
    assert ag.prim_enabled()


def test_incubate_top_level_ops():
    import paddle_tpu.incubate as inc

    x = paddle.to_tensor(np.random.default_rng(0).standard_normal((2, 4, 4)).astype(np.float32))
    mask = paddle.zeros([2, 4, 4])
    out = inc.softmax_mask_fuse(x, mask)
    np.testing.assert_allclose(np.asarray(out._value).sum(-1), 1.0, rtol=1e-5)
    tri = inc.softmax_mask_fuse_upper_triangle(x)
    tv = np.asarray(tri._value)
    assert tv[0, 0, 1] == 0.0 and abs(tv[0, 0, 0] - 1.0) < 1e-6  # causal row 0
    data = paddle.to_tensor(np.array([[1.0], [2.0], [3.0]], np.float32))
    seg = paddle.to_tensor(np.array([0, 0, 1], np.int32))
    np.testing.assert_allclose(np.asarray(inc.segment_sum(data, seg)._value), [[3.0], [3.0]])
    assert float(inc.identity_loss(x, "sum")._value) == pytest.approx(float(np.asarray(x._value).sum()), rel=1e-5)


def test_fused_layer_classes():
    import paddle_tpu.incubate.nn as inn

    paddle.seed(0)
    lin = inn.FusedLinear(8, 16)
    y = lin(paddle.ones([2, 8]))
    assert y.shape == [2, 16]
    da = inn.FusedDropoutAdd(p=0.0)
    z = da(paddle.ones([2, 4]), paddle.ones([2, 4]))
    np.testing.assert_allclose(np.asarray(z._value), 2.0)
    bd = inn.FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.0)
    o = bd(paddle.ones([2, 3, 8]), paddle.ones([2, 3, 8]))
    assert np.abs(np.asarray(o._value).mean()) < 1e-5  # LN zero-means
    # FusedEcMoe: reference forward contract is per-token gate logits
    moe = inn.FusedEcMoe(8, 16, 4)
    x = paddle.to_tensor(np.random.default_rng(0).standard_normal((2, 3, 8)).astype(np.float32))
    gate = paddle.to_tensor(np.random.default_rng(1).standard_normal((2, 3, 4)).astype(np.float32))
    out = moe(x, gate)
    assert out.shape == [2, 3, 8] and np.isfinite(np.asarray(out._value)).all()


def test_device_predicates_and_fleet_util():
    import paddle_tpu.device as dev

    assert dev.is_compiled_with_cuda() is False
    assert dev.is_compiled_with_distribute() is True
    assert dev.get_cudnn_version() is None
    with pytest.raises(RuntimeError):
        dev.XPUPlace(0)
    import paddle_tpu.distributed.fleet as fleet

    assert fleet.util.get_file_shard(["a", "b", "c"]) == ["a", "b", "c"]  # world 1
    f = fleet.Fleet()
    assert callable(f.init)


def test_utils_trio():
    import paddle_tpu.utils as U

    assert U.try_import("math") is not None
    with pytest.raises(ImportError):
        U.try_import("definitely_not_a_module_xyz")
    assert U.require_version("0.1.0")
    with pytest.raises(Exception):
        U.require_version("99.0.0")
    calls = []

    @U.deprecated(update_to="new_fn", since="0.2")
    def old_fn():
        calls.append(1)
        return 7

    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert old_fn() == 7
        assert any("deprecated" in str(x.message) for x in w)
