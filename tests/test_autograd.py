"""Autograd tape tests (behavioral parity with reference eager autograd,
paddle/fluid/eager/backward.cc; gradient values checked against analytic and
jax.grad references)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle


def _param(arr):
    t = paddle.to_tensor(np.asarray(arr, np.float32))
    t.stop_gradient = False
    return t


def test_simple_backward():
    x = _param([1.0, 2.0, 3.0])
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 4, 6])


def test_chain_rule():
    x = _param(2.0)
    y = paddle.exp(x * x)  # dy/dx = 2x*exp(x^2)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * 2 * np.exp(4.0), rtol=1e-5)


def test_branching_graph_accumulates():
    x = _param(3.0)
    a = x * 2.0
    b = x * 5.0
    y = a + b  # dy/dx = 7
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 7.0)


def test_diamond_graph():
    x = _param(2.0)
    a = x * x  # a = x^2
    y = (a * a).sum()  # y = x^4, dy/dx = 4x^3 = 32
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 32.0)


def test_matmul_grad():
    a_np = np.random.rand(3, 4).astype(np.float32)
    b_np = np.random.rand(4, 5).astype(np.float32)
    a, b = _param(a_np), _param(b_np)
    out = paddle.matmul(a, b).sum()
    out.backward()
    # d(sum(AB))/dA = ones @ B^T
    np.testing.assert_allclose(a.grad.numpy(), np.ones((3, 5)) @ b_np.T, rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(), a_np.T @ np.ones((3, 5)), rtol=1e-5)


def test_stop_gradient_blocks():
    x = _param(2.0)
    y = paddle.to_tensor(3.0)  # stop_gradient=True
    z = x * y
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 3.0)
    assert y.grad is None


def test_detach():
    x = _param(2.0)
    y = (x * x).detach()
    z = y * x
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 4.0)  # only through z=y*x


def test_no_grad_context():
    x = _param(2.0)
    with paddle.no_grad():
        y = x * x
    assert y._grad_node is None
    assert y.stop_gradient


def test_grad_accumulation_across_backwards():
    x = _param(2.0)
    (x * 2.0).backward()
    (x * 3.0).backward()
    np.testing.assert_allclose(x.grad.numpy(), 5.0)


def test_clear_grad():
    x = _param(2.0)
    (x * 2.0).backward()
    x.clear_grad()
    assert x.grad is None


def test_backward_with_grad_tensor():
    x = _param([1.0, 2.0])
    y = x * 3.0
    y.backward(paddle.to_tensor([1.0, 10.0]))
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])


def test_non_scalar_backward_raises():
    x = _param([1.0, 2.0])
    y = x * 2.0
    with pytest.raises(RuntimeError):
        y.backward()


def test_double_backward_without_retain_raises():
    x = _param(2.0)
    y = x * x
    z = y.sum()
    z.backward()
    with pytest.raises(RuntimeError):
        z.backward()


def test_retain_graph():
    x = _param(2.0)
    z = (x * x).sum()
    z.backward(retain_graph=True)
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 8.0)  # 4 + 4


def test_hook():
    x = _param(2.0)
    seen = []

    def hook(g):
        seen.append(g.numpy())
        return g * 2.0

    x.register_hook(hook)
    (x * 3.0).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], 3.0)
    np.testing.assert_allclose(x.grad.numpy(), 6.0)  # doubled by hook


def test_paddle_grad_api():
    x = _param(2.0)
    y = x * x
    (gx,) = paddle.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), 4.0)
    assert x.grad is None  # paddle.grad does not write .grad


def test_paddle_grad_intermediate():
    x = _param(2.0)
    a = x * x
    y = a * 3.0
    (ga,) = paddle.grad(y, a, retain_graph=True)
    np.testing.assert_allclose(ga.numpy(), 3.0)


def test_grad_matches_jax_reference():
    """Cross-check a composite function against pure jax.grad."""

    def f_jax(x):
        return jnp.sum(jnp.tanh(x @ x.T) * jnp.exp(x[:, :1]))

    x_np = np.random.rand(4, 4).astype(np.float32)
    expected = jax.grad(f_jax)(jnp.asarray(x_np))

    x = _param(x_np)
    out = (paddle.tanh(x @ x.T) * paddle.exp(x[:, :1])).sum()
    out.backward()
    np.testing.assert_allclose(x.grad.numpy(), np.asarray(expected), rtol=1e-4, atol=1e-5)


def test_backward_inside_jit():
    """The tape must compose with jax.jit — whole-step compile is the TPU hot
    path (SURVEY.md §7 design stance)."""

    def step(xv):
        x = paddle.Tensor(xv, stop_gradient=False)
        y = (x * x).sum()
        y.backward()
        return x.grad._value

    out = jax.jit(step)(jnp.asarray([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(np.asarray(out), [2, 4, 6])


def test_mean_grad():
    x = _param(np.ones((2, 8)))
    paddle.mean(x).backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((2, 8), 1 / 16))


def test_getitem_grad():
    x = _param([1.0, 2.0, 3.0, 4.0])
    y = (x[1:3] * 2.0).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [0, 2, 2, 0])


def test_concat_grad():
    a = _param([1.0, 2.0])
    b = _param([3.0, 4.0])
    y = (paddle.concat([a, b]) * paddle.to_tensor([1.0, 2.0, 3.0, 4.0])).sum()
    y.backward()
    np.testing.assert_allclose(a.grad.numpy(), [1, 2])
    np.testing.assert_allclose(b.grad.numpy(), [3, 4])


def test_grad_no_grad_vars_blocks_propagation():
    """no_grad_vars: those tensors get no gradient and block propagation
    into their producers (reference base.py grad no_grad_vars)."""
    a = _param([2.0, 3.0])
    c = _param([4.0, 5.0])
    b = c * 2.0  # producer of the boundary tensor
    y = (a * b).sum()
    (ga,) = paddle.grad(y, [a], no_grad_vars=[b], retain_graph=True)
    np.testing.assert_allclose(ga.numpy(), [8.0, 10.0])  # normal path
    # no gradient flows through the boundary into c (explicit zeros)
    (gc,) = paddle.grad(y, [c], no_grad_vars=[b], allow_unused=True)
    np.testing.assert_allclose(gc.numpy(), [0.0, 0.0])
    # without the boundary, grads flow: d y/d c = 2a
    a2, c2 = _param([2.0, 3.0]), _param([4.0, 5.0])
    y2 = (a2 * (c2 * 2.0)).sum()
    (gc2,) = paddle.grad(y2, [c2])
    np.testing.assert_allclose(gc2.numpy(), [4.0, 6.0])
