"""Op registry + codegen (reference: the YAML registry feeding four
generators, SURVEY.md:35; see paddle_tpu/framework/op_registry.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import op_registry as R


def test_registry_covers_surface():
    ops = R.all_ops()
    assert len(ops) > 150, len(ops)
    for must in ("matmul", "add", "reshape", "argmax", "unique", "svd"):
        assert must in ops


def test_amp_and_dynamic_metadata():
    assert R.get_op_info("matmul").amp_class == "white"
    assert R.get_op_info("log").amp_class == "black"
    info = R.get_op_info("unique")
    assert info.dynamic_shape
    assert R.get_op_info("add").has_tensor_method


def test_generated_inplace_tier():
    t = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    t.add_(paddle.to_tensor(np.array([1.0, 1.0], np.float32)))
    np.testing.assert_allclose(np.asarray(t._value), [2.0, 3.0])
    t.scale_(2.0)
    np.testing.assert_allclose(np.asarray(t._value), [4.0, 6.0])
    t.clip_(0.0, 5.0)
    np.testing.assert_allclose(np.asarray(t._value), [4.0, 5.0])
    # gradients flow through the in-place rebind
    x = paddle.to_tensor(np.ones(3, np.float32))
    x.stop_gradient = False
    y = (x * 2.0)
    y.exp_()
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), 2 * np.exp(2.0) * np.ones(3), rtol=1e-6)
    info = R.get_op_info("exp")
    assert info.inplace_variant == "exp_"


def test_markdown_doc_generation():
    md = R.generate_markdown()
    assert md.startswith("| op |") and "| matmul |" in md
