"""BERT/ERNIE family (BASELINE.md finetune north-stars) on the nn stack."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import BertForMaskedLM, BertForSequenceClassification, bert_tiny


def _batch(vocab, b=4, s=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, vocab, (b, s)).astype(np.int32)
    ids[:, -3:] = 0  # padding tail exercises the attention mask
    return paddle.to_tensor(ids)


def test_sequence_classification_finetune_loss_decreases():
    paddle.seed(0)
    cfg = bert_tiny()
    m = BertForSequenceClassification(cfg, num_classes=3)
    opt = paddle.optimizer.AdamW(5e-4, parameters=m.parameters())
    ids = _batch(cfg.vocab_size)
    labels = paddle.to_tensor(np.array([0, 1, 2, 1], np.int32))
    step = TrainStep(m, opt, lambda mm, i, l: mm(i, labels=l)[0])
    losses = [float(step(ids, labels)) for _ in range(10)]
    assert losses[-1] < losses[0] * 0.7, losses


def test_masked_lm_and_padding_mask():
    paddle.seed(1)
    cfg = bert_tiny()
    m = BertForMaskedLM(cfg)
    m.eval()
    ids = _batch(cfg.vocab_size, seed=1)
    with paddle.no_grad():
        logits = m(ids)
    assert list(logits.shape) == [4, 16, cfg.vocab_size]
    assert np.isfinite(np.asarray(logits._value, np.float32)).all()
    # padded positions must not influence the [CLS] pooled output
    ids2 = np.asarray(ids._value).copy()
    ids2[:, -3:] = 0  # same padding, different garbage beyond mask is absent
    clf = BertForSequenceClassification(cfg)
    clf.eval()
    with paddle.no_grad():
        mask = (ids2 != 0).astype(np.int32)
        a = np.asarray(clf(paddle.to_tensor(ids2), attention_mask=paddle.to_tensor(mask))._value)
        ids3 = ids2.copy()
        ids3[:, -1] = 7  # perturb a PADDED position; mask still marks it pad
        b = np.asarray(clf(paddle.to_tensor(ids3), attention_mask=paddle.to_tensor(mask))._value)
    # the masked position cannot reach [CLS] through attention
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_ernie_alias():
    from paddle_tpu.models import ErnieForSequenceClassification, ErnieModel

    assert ErnieModel is not None and ErnieForSequenceClassification is not None
