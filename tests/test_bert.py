"""BERT/ERNIE family (BASELINE.md finetune north-stars) on the nn stack."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import BertForMaskedLM, BertForSequenceClassification, bert_tiny


def _batch(vocab, b=4, s=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, vocab, (b, s)).astype(np.int32)
    ids[:, -3:] = 0  # padding tail exercises the attention mask
    return paddle.to_tensor(ids)


def test_sequence_classification_finetune_loss_decreases():
    paddle.seed(0)
    cfg = bert_tiny()
    m = BertForSequenceClassification(cfg, num_classes=3)
    opt = paddle.optimizer.AdamW(5e-4, parameters=m.parameters())
    ids = _batch(cfg.vocab_size)
    labels = paddle.to_tensor(np.array([0, 1, 2, 1], np.int32))
    step = TrainStep(m, opt, lambda mm, i, l: mm(i, labels=l)[0])
    losses = [float(step(ids, labels)) for _ in range(10)]
    assert losses[-1] < losses[0] * 0.7, losses


def test_masked_lm_and_padding_mask():
    paddle.seed(1)
    cfg = bert_tiny()
    m = BertForMaskedLM(cfg)
    m.eval()
    ids = _batch(cfg.vocab_size, seed=1)
    with paddle.no_grad():
        logits = m(ids)
    assert list(logits.shape) == [4, 16, cfg.vocab_size]
    assert np.isfinite(np.asarray(logits._value, np.float32)).all()
    # padded positions must not influence the [CLS] pooled output
    ids2 = np.asarray(ids._value).copy()
    ids2[:, -3:] = 0  # same padding, different garbage beyond mask is absent
    clf = BertForSequenceClassification(cfg)
    clf.eval()
    with paddle.no_grad():
        mask = (ids2 != 0).astype(np.int32)
        a = np.asarray(clf(paddle.to_tensor(ids2), attention_mask=paddle.to_tensor(mask))._value)
        ids3 = ids2.copy()
        ids3[:, -1] = 7  # perturb a PADDED position; mask still marks it pad
        b = np.asarray(clf(paddle.to_tensor(ids3), attention_mask=paddle.to_tensor(mask))._value)
    # the masked position cannot reach [CLS] through attention
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_ernie_alias():
    from paddle_tpu.models import ErnieForSequenceClassification, ErnieModel

    assert ErnieModel is not None and ErnieForSequenceClassification is not None


@pytest.mark.slow
def test_gpt_trains_and_shards():
    """GPT family: compiled pretrain step decreases loss; Megatron-sharded
    tp x dp step matches single-device numerics."""
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny, shard_gpt
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.sharded_step import ShardedTrainStep

    rng = np.random.default_rng(0)
    cfg = gpt_tiny()
    ids_np = rng.integers(0, cfg.vocab_size, (4, 32)).astype(np.int32)

    paddle.seed(3)
    m = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    step = TrainStep(m, opt, lambda mm, i: mm(i, labels=i)[0])
    ids = paddle.to_tensor(ids_np)
    losses = [float(step(ids)) for _ in range(5)]
    assert losses[-1] < losses[0], losses

    paddle.seed(3)
    mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
    m2 = shard_gpt(GPTForCausalLM(cfg), mesh)
    opt2 = paddle.optimizer.AdamW(1e-3, parameters=m2.parameters())
    step2 = ShardedTrainStep(m2, opt2, lambda mm, i: mm(i, labels=i)[0], mesh)
    losses2 = [float(step2(ids)) for _ in range(5)]
    np.testing.assert_allclose(losses2, losses, rtol=2e-3, atol=2e-3)


def test_bert_tokenizer_feeds_model():
    """WordPiece tokenizer (the strings/faster_tokenizer workload, host
    side) feeding the BERT classifier end to end."""
    from paddle_tpu.text import BertTokenizer

    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "cat", "sat", "mat",
             "un", "##able", "##happy", "on", "!"]
    tok = BertTokenizer(vocab)
    assert tok.tokenize("The cat sat!") == ["the", "cat", "sat", "!"]
    assert tok.tokenize("unhappy") == ["un", "##happy"]
    assert tok.tokenize("zebra") == ["[UNK]"]

    enc = tok(["the cat sat on the mat", "unhappy cat"], max_length=12)
    assert enc["input_ids"].shape == (2, 12)
    assert enc["attention_mask"][0].sum() == 8  # CLS + 6 toks + SEP
    # pair encoding sets token types
    enc2 = tok("the cat", text_pairs="sat on", max_length=10)
    assert enc2["token_type_ids"].max() == 1

    cfg = bert_tiny(vocab_size=len(vocab) + 10)
    m = BertForSequenceClassification(cfg)
    m.eval()
    with paddle.no_grad():
        logits = m(
            paddle.to_tensor(enc["input_ids"]),
            token_type_ids=paddle.to_tensor(enc["token_type_ids"]),
            attention_mask=paddle.to_tensor(enc["attention_mask"]),
        )
    assert np.isfinite(np.asarray(logits._value)).all()
