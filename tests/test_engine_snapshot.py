"""Live-engine snapshot / bit-exact resume (serving/snapshot.py,
docs/CHECKPOINT.md serving section, ROADMAP item 5).

Contract under test: `EngineSnapshot.save` captures a LIVE
GenerationEngine mid-flight through the CheckpointManager commit
protocol, and `restore_engine` rebuilds a fresh engine whose continued
greedy AND seeded-sampled streams are BIT-identical to an uninterrupted
engine — composed with every serving feature: queued admissions, prefix
cache, int8 pools, LoRA adapter packs, speculative decode, and flag
changes between save and restore.  The subprocess SIGKILL matrix lives in
test_engine_snapshot_crash.py; topology migration (single ↔ TP mesh) in
the isolated test_engine_snapshot_mesh.py worker."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.serving import (EngineSnapshot, GenerationEngine,
                                restore_engine, reset_snapshot_stats,
                                snapshot_stats)

_KW = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=4, max_position_embeddings=64,
           dtype="float32")


def _model(seed=41, **kw):
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(seed)
    base = dict(_KW)
    base.update(kw)
    m = LlamaForCausalLM(llama_tiny(**base))
    m.eval()
    return m


def _drain(eng):
    out = {}
    while eng.has_work():
        for rid, toks in eng.step().items():
            out.setdefault(rid, []).extend(
                toks if isinstance(toks, list) else [toks])
    return out


P1, P2 = [5, 9, 17, 33, 2], [7, 11, 3]


def _submit(eng):
    eng.add_request("g", P1, max_new_tokens=8)
    eng.add_request("s", P2, max_new_tokens=6, temperature=5.0, seed=3)


def _results(eng, rids=("g", "s")):
    return {rid: eng.result(rid) for rid in rids}


def test_mid_flight_snapshot_resumes_bit_identical(tmp_path):
    """Snapshot after one macro-step, restore onto a fresh engine, run to
    completion: greedy and seeded-sampled streams match the uninterrupted
    engine token for token (pools, slots, PRNG keys, fold counters all
    restored exactly)."""
    m = _model()
    ref = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2)
    _submit(ref)
    _drain(ref)

    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2)
    _submit(eng)
    eng.step()
    step = eng.snapshot(str(tmp_path))
    assert EngineSnapshot(str(tmp_path)).latest_step() == step

    reset_snapshot_stats()
    eng2 = restore_engine(m, str(tmp_path))
    _drain(eng2)
    assert _results(eng2) == _results(ref)
    assert snapshot_stats()["restores"] == 1
    # the source engine is untouched by the snapshot: it finishes too
    _drain(eng)
    assert _results(eng) == _results(ref)


def test_pending_queue_and_nonce_counter_survive(tmp_path):
    """A request QUEUED at snapshot time (pool pressure) is admitted by
    the restored engine with its submit-time PRNG nonce intact, and a
    request submitted only AFTER restore draws the stream the
    uninterrupted engine would give it (the nonce counter itself is
    state)."""
    m = _model()

    def run(snapshot_after=None):
        eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=2,
                               decode_chunk=2)
        p = list(range(1, 9))
        eng.add_request("a", p, max_new_tokens=7)
        assert eng.add_request("b", p, max_new_tokens=7,
                               temperature=4.0, seed=1) is None  # queued
        eng.step()
        if snapshot_after is not None:
            eng.snapshot(snapshot_after)
            eng = restore_engine(m, snapshot_after)
            assert eng.pending_requests() == ["b"]
        _drain(eng)
        # a THIRD request after the (possible) restore: distinct nonce
        eng.add_request("c", P2, max_new_tokens=5, temperature=4.0, seed=1)
        _drain(eng)
        return {r: eng.result(r) for r in ("a", "b", "c")}

    ref = run()
    got = run(snapshot_after=str(tmp_path))
    assert got == ref
    assert got["b"] != got["c"]  # same seed, distinct nonces — still true


def test_prefix_cache_tree_survives_restore(tmp_path):
    """Cached prefix pages (tree nodes, refcounts, LRU order) restore: an
    admission AFTER restore hits the pages the pre-snapshot engine
    cached, and the served stream matches an uninterrupted cache-on
    engine."""
    from paddle_tpu.serving import decode_stats, reset_decode_stats

    m = _model()
    shared = list(np.random.default_rng(0).integers(0, 128, 16))

    def run(snapshot_dir=None):
        eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                               decode_chunk=2, prefix_cache=True)
        eng.add_request("w", shared + [3], max_new_tokens=4)
        _drain(eng)  # warms the tree with the shared prefix
        if snapshot_dir is not None:
            eng.snapshot(snapshot_dir)
            eng = restore_engine(m, snapshot_dir)
            assert len(eng._prefix) > 0  # tree really came back
        reset_decode_stats()
        eng.add_request("x", shared + [9, 4], max_new_tokens=5)
        _drain(eng)
        return eng.result("x"), decode_stats()

    ref_toks, ref_st = run()
    got_toks, got_st = run(snapshot_dir=str(tmp_path))
    assert got_toks == ref_toks
    assert got_st["prefix_hits"] == ref_st["prefix_hits"] == 1
    assert got_st["prefix_hit_tokens"] == ref_st["prefix_hit_tokens"] > 0


def test_int8_pools_roundtrip_bit_exact(tmp_path):
    """Int8 engine: quantized payload AND per-block-per-head scales
    restore bit-exactly, so the resumed stream equals the uninterrupted
    int8 engine's (identical arithmetic on identical pool bytes — within
    the PR-6 drift budget by construction, bit-equal in practice)."""
    m = _model()
    ref = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2, kv_cache_dtype="int8")
    _submit(ref)
    _drain(ref)

    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2, kv_cache_dtype="int8")
    _submit(eng)
    eng.step()
    eng.snapshot(str(tmp_path))
    eng2 = restore_engine(m, str(tmp_path))
    assert eng2._kv_dtype == "int8"
    # payload and scales are bit-equal to the source engine's
    np.testing.assert_array_equal(np.asarray(eng2._kpools[0].data),
                                  np.asarray(eng._kpools[0].data))
    np.testing.assert_array_equal(np.asarray(eng2._kpools[0].scale),
                                  np.asarray(eng._kpools[0].scale))
    _drain(eng2)
    assert _results(eng2) == _results(ref)


def test_adapter_pack_slots_and_epochs_survive(tmp_path):
    """LoRA engine: registry, slot contents, LRU marks and epochs
    restore.  Mixed-tenant streams continue bit-identically, the slot map
    is intact, and a post-restore re-register bumps the restored epoch —
    the stale subtree of the OLD epoch can never cross-match."""
    from tests.test_serving_lora import _adapter_sd

    m = _model()
    sd0, sd1 = _adapter_sd(m, 7), _adapter_sd(m, 13)

    def build():
        eng = GenerationEngine(m, max_batch=3, block_size=8, num_blocks=24,
                               decode_chunk=2, adapters=4,
                               prefix_cache=True)
        eng.register_adapter("t0", sd0)
        eng.register_adapter("t1", sd1)
        eng.add_request("a", P1, max_new_tokens=7, adapter="t0")
        eng.add_request("b", P1, max_new_tokens=7, adapter="t1")
        eng.add_request("c", P2, max_new_tokens=5)
        return eng

    ref = build()
    _drain(ref)

    eng = build()
    eng.step()
    eng.snapshot(str(tmp_path))
    eng2 = restore_engine(m, str(tmp_path))
    assert eng2.adapter_slots() == eng.adapter_slots()
    assert eng2._slot_epochs == eng._slot_epochs
    _drain(eng2)
    assert ({r: eng2.result(r) for r in "abc"}
            == {r: ref.result(r) for r in "abc"})
    # post-restore hot swap: epoch advances past the restored value
    before = list(eng2._slot_epochs)
    slot = eng2.register_adapter("t0", _adapter_sd(m, 99))
    assert eng2._slot_epochs[slot] == before[slot] + 1


def test_speculative_engine_roundtrip(tmp_path):
    """Speculative engine: draft pools, per-slot draft coverage and
    acceptance counters restore; the resumed engine emits exactly the
    uninterrupted speculative engine's tokens."""
    m = _model()
    draft = _model(seed=77, hidden_size=16, intermediate_size=32,
                   num_hidden_layers=1, num_attention_heads=2,
                   num_key_value_heads=2)

    def build():
        eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=32,
                               draft_model=draft, num_speculative_tokens=3)
        eng.add_request("a", P1, max_new_tokens=9)
        eng.add_request("b", P2, max_new_tokens=6)
        return eng

    ref = build()
    _drain(ref)

    eng = build()
    eng.step()
    eng.snapshot(str(tmp_path))
    with pytest.raises(ValueError, match="draft_model"):
        restore_engine(m, str(tmp_path))  # speculative snapshot is loud
    eng2 = restore_engine(m, str(tmp_path), draft_model=draft)
    assert eng2._spec_stats["ticks"] == eng._spec_stats["ticks"]
    _drain(eng2)
    assert ({r: eng2.result(r) for r in "ab"}
            == {r: ref.result(r) for r in "ab"})


def test_restore_under_changed_decode_chunk_flags(tmp_path):
    """A snapshot taken at one FLAGS_decode_chunk restores cleanly when
    the flag differs: compiled steps rebuild for the new D, streams stay
    bit-identical (the engine's every-D contract), and a flag flip AFTER
    restore still invalidates the restored engine's executables through
    the WeakSet listener."""
    m = _model()
    ref = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=1)
    _submit(ref)
    _drain(ref)

    paddle.set_flags({"FLAGS_decode_chunk": 4})
    try:
        eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16)
        _submit(eng)
        eng.step()
        eng.snapshot(str(tmp_path))
        paddle.set_flags({"FLAGS_decode_chunk": 2})
        eng2 = restore_engine(m, str(tmp_path))
        out = eng2.step()
        assert all(len(v) <= 2 for v in out.values())  # new D is live
        # flag flip mid-serving: the restored engine's step fns drop too
        assert eng2._step_fns
        paddle.set_flags({"FLAGS_decode_chunk": 3})
        assert not eng2._step_fns
        _drain(eng2)
    finally:
        paddle.set_flags({"FLAGS_decode_chunk": 8})
    assert _results(eng2) == _results(ref)


def test_drain_closes_admissions_and_hands_off(tmp_path):
    """drain() = final snapshot + admissions closed: the drained engine
    refuses new requests, finishes ONLY its residents (the queued request
    rode the snapshot and is the restore target's to serve — a lame duck
    serving it too would double-serve it), never overwrites the handoff
    snapshot from post-drain boundaries, and the restored engine serves
    resident AND queued requests to the uninterrupted streams."""
    m = _model()
    ref = GenerationEngine(m, max_batch=1, block_size=8, num_blocks=4,
                           decode_chunk=2)
    p = list(range(1, 9))
    ref.add_request("a", p, max_new_tokens=7)
    assert ref.add_request("b", p, max_new_tokens=6) is None  # queued
    _drain(ref)

    # the flag-driven automatic path is live, to prove drain disarms it
    paddle.set_flags({"FLAGS_engine_snapshot_dir": str(tmp_path),
                      "FLAGS_engine_snapshot_interval": 1})
    try:
        eng = GenerationEngine(m, max_batch=1, block_size=8, num_blocks=4,
                               decode_chunk=2)
        eng.add_request("a", p, max_new_tokens=7)
        assert eng.add_request("b", p, max_new_tokens=6) is None
        step = eng.drain(str(tmp_path))
        assert snapshot_stats()["drains"] >= 1
        with pytest.raises(RuntimeError, match="draining"):
            eng.add_request("late", P2, max_new_tokens=3)
        # the drained engine finishes residents ONLY: "b" stays unserved
        # here, and the lame-duck boundaries write no further snapshots
        _drain(eng)
        assert eng.result("a") == ref.result("a")
        assert eng.result("b") is None
        assert not eng.has_work()  # queued "b" is not the lame duck's work
        assert EngineSnapshot(str(tmp_path)).latest_step() == step
    finally:
        paddle.set_flags({"FLAGS_engine_snapshot_dir": "",
                          "FLAGS_engine_snapshot_interval": 0})
    # the handed-off snapshot serves everything, open for business
    eng2 = EngineSnapshot(str(tmp_path)).restore(m, step=step)
    assert eng2.pending_requests() == ["b"]
    _drain(eng2)
    assert eng2.result("a") == ref.result("a")
    assert eng2.result("b") == ref.result("b")
    eng2.add_request("late", P2, max_new_tokens=3)  # restored engine admits
    _drain(eng2)


def test_drain_empty_engine_and_double_drain_idempotent(tmp_path):
    """drain() edge cases the headline test leaves uncovered: an EMPTY
    engine drains cleanly (the snapshot is still a valid handoff — empty
    clusters scale down too), a second drain() returns the SAME committed
    handoff step without writing another snapshot (an orchestrator
    retrying a timed-out drain must not hand the restore target a
    different state per retry), and the drained engine refuses
    add_request with the documented error either way."""
    m = _model()
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2)
    # idle drain: nothing resident, nothing queued
    step = eng.drain(str(tmp_path))
    store = EngineSnapshot(str(tmp_path))
    assert store.latest_step() == step
    committed = store.all_steps()
    # double-drain: same step, no new commit, drains counted once more at
    # most — the handoff state is immutable once taken
    reset_snapshot_stats()
    assert eng.drain(str(tmp_path)) == step
    assert store.all_steps() == committed
    assert snapshot_stats()["saves"] == 0  # idempotent: no re-snapshot
    # ...but only for the SAME directory: a step tag that exists nowhere
    # under the new dir must never be handed to an orchestrator
    with pytest.raises(ValueError, match="already drained"):
        eng.drain(str(tmp_path / "elsewhere"))
    with pytest.raises(RuntimeError, match="draining"):
        eng.add_request("late", P2, max_new_tokens=3)
    assert not eng.has_work()
    assert eng.step() == {}  # lame-duck stepping an empty engine is fine
    # the handoff restores to a fully OPEN empty engine
    eng2 = EngineSnapshot(str(tmp_path)).restore(m, step=step)
    assert eng2.pending_requests() == []
    eng2.add_request("fresh", P1, max_new_tokens=3)
    _drain(eng2)
    assert isinstance(eng2.result("fresh"), list)


def test_sigterm_preemption_snapshots_at_boundary(tmp_path):
    """The SIGTERM mirror of CheckpointManager's flag-flip design: the
    handler only flips a flag; the NEXT macro-step boundary writes the
    final snapshot (never mid-dispatch), preemption_saved goes true, and
    the restored engine finishes every stream bit-identically."""
    import os
    import signal

    m = _model()
    ref = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2)
    _submit(ref)
    _drain(ref)

    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2)
    _submit(eng)
    eng.step()
    paddle.set_flags({"FLAGS_engine_snapshot_dir": str(tmp_path)})
    eng.install_preemption_handler()
    try:
        os.kill(os.getpid(), signal.SIGTERM)  # handler flips the flag only
        assert eng.preemption_requested and not eng.preemption_saved
        assert EngineSnapshot(str(tmp_path)).latest_step() is None
        eng.step()  # boundary: the final snapshot commits HERE
        assert eng.preemption_saved
        st = EngineSnapshot(str(tmp_path)).latest_step()
        assert st is not None
    finally:
        eng.uninstall_preemption_handler()
        paddle.set_flags({"FLAGS_engine_snapshot_dir": ""})
    eng2 = restore_engine(m, str(tmp_path))
    _drain(eng2)
    assert _results(eng2) == _results(ref)


def test_preemption_honored_on_idle_engine(tmp_path):
    """A SIGTERM that lands while the engine has NO work must still
    commit its final snapshot at the next step() call (the idle early
    return is a boundary too) — otherwise the documented
    `while not eng.preemption_saved: eng.step()` exit loop would spin
    until the orchestrator escalates to SIGKILL."""
    import os
    import signal

    m = _model()
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2)
    eng.add_request("g", P1, max_new_tokens=4)
    _drain(eng)  # engine now idle, state worth saving (results, caches)
    paddle.set_flags({"FLAGS_engine_snapshot_dir": str(tmp_path)})
    eng.install_preemption_handler()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert eng.preemption_requested
        assert eng.step() == {}  # idle boundary: final snapshot commits
        assert eng.preemption_saved
    finally:
        eng.uninstall_preemption_handler()
        paddle.set_flags({"FLAGS_engine_snapshot_dir": ""})
    eng2 = restore_engine(m, str(tmp_path))
    assert eng2.result("g") == eng.result("g")


def test_periodic_interval_snapshots(tmp_path):
    """FLAGS_engine_snapshot_interval: step() snapshots every N
    macro-steps into the flag directory, step-tagged by the engine's
    boundary count, with retention keeping the newest valid ones."""
    m = _model()
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=1)
    eng.add_request("g", P1, max_new_tokens=8)
    paddle.set_flags({"FLAGS_engine_snapshot_dir": str(tmp_path),
                      "FLAGS_engine_snapshot_interval": 2})
    try:
        for _ in range(5):
            eng.step()
    finally:
        paddle.set_flags({"FLAGS_engine_snapshot_dir": "",
                          "FLAGS_engine_snapshot_interval": 0})
    store = EngineSnapshot(str(tmp_path))
    steps = store.all_steps()
    assert steps and all(s % 2 == 0 for s in steps)
    assert len(steps) <= 2  # default retention


def test_corrupt_snapshot_skipped_and_counted(tmp_path):
    """A snapshot damaged after commit (bit rot / truncation) fails
    checksum verification: latest_step falls back to the older valid one,
    restore serves it, and corrupt_skipped counts the torn dir once."""
    import os

    m = _model()
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2)
    _submit(eng)
    eng.step()
    store = EngineSnapshot(str(tmp_path), max_to_keep=3)
    s1 = store.save(eng)
    eng.step()
    s2 = store.save(eng)
    assert store.latest_step() == s2 > s1
    # truncate the newest snapshot's extras: manifest hash now mismatches
    victim = os.path.join(str(tmp_path), f"step_{s2:08d}", "extras.pkl")
    with open(victim, "r+b") as f:
        f.truncate(16)
    reset_snapshot_stats()
    # a FRESH store (the restart-after-damage shape) re-verifies; the
    # saving store's mtime-keyed cache deliberately trusts what it just
    # hashed, exactly like CheckpointManager's _verify_dir cache
    store = EngineSnapshot(str(tmp_path), max_to_keep=3)
    assert store.latest_step() == s1
    assert snapshot_stats()["corrupt_skipped"] == 1
    # resolving again (any number of fresh instances) never re-counts
    # the same torn dir: the health counter dedup is process-wide
    assert EngineSnapshot(str(tmp_path)).latest_step() == s1
    assert snapshot_stats()["corrupt_skipped"] == 1
    eng2 = restore_engine(m, str(tmp_path))  # lands on the valid s1
    _drain(eng2)
    assert isinstance(eng2.result("g"), list)


def test_geometry_mismatch_is_loud(tmp_path):
    """Restoring onto a DIFFERENT model is refused with the differing
    fields named — poured K/V from other weights can never silently
    serve."""
    m = _model()
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16)
    eng.add_request("g", P1, max_new_tokens=4)
    eng.snapshot(str(tmp_path))
    other = _model(hidden_size=64, intermediate_size=128)
    with pytest.raises(ValueError, match="hidden_size"):
        restore_engine(other, str(tmp_path))


def test_snapshot_stats_and_summary_footer(tmp_path, capsys):
    """profiler.snapshot_stats() schema + the 'Engine snapshot:' footer
    in Profiler.summary() (serving-owned counters, decode_stats
    contract)."""
    m = _model()
    eng = GenerationEngine(m, max_batch=2, block_size=8, num_blocks=16,
                           decode_chunk=2)
    _submit(eng)
    eng.step()
    reset_snapshot_stats()
    eng.snapshot(str(tmp_path))
    restore_engine(m, str(tmp_path))
    st = profiler.snapshot_stats()
    assert st["saves"] == 1 and st["restores"] == 1
    assert st["bytes"] > 0 and st["snapshot_seconds"] > 0
    assert st["corrupt_skipped"] == 0 and st["drains"] == 0

    prof = profiler.Profiler(timer_only=True)
    prof.start()
    prof.stop()
    out = prof.summary()
    capsys.readouterr()
    assert "Engine snapshot: saves=" in out
    assert profiler.snapshot_stats(reset=True)["saves"] == 1
    assert profiler.snapshot_stats()["saves"] == 0
