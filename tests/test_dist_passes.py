"""Distributed pass framework (reference python/paddle/distributed/passes/
PassManager + named passes; see paddle_tpu/distributed/passes/)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.passes import PassContext, PassManager, new_pass


def _ctx():
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
    return PassContext(m, opt)


def test_pass_registry_and_unknown():
    with pytest.raises(ValueError, match="unknown pass"):
        new_pass("nope")


def test_fp16_pass_casts_params():
    ctx = _ctx()
    PassManager([new_pass("auto_parallel_fp16", {"dtype": "bfloat16"})]).apply(ctx)
    import jax.numpy as jnp

    assert all(p._value.dtype == jnp.bfloat16 for p in ctx.model.parameters())
    assert ctx.attrs["amp_level"] == "O2"


def test_gradient_merge_and_clip_passes():
    ctx = _ctx()
    pm = PassManager([
        new_pass("auto_parallel_grad_clip", {"clip_norm": 0.5}),
        new_pass("auto_parallel_gradient_merge", {"k_steps": 2}),
        new_pass("auto_parallel_sharding", {"stage": 2}),
    ])
    assert pm.names == ["auto_parallel_grad_clip", "auto_parallel_gradient_merge", "auto_parallel_sharding"]
    ctx = pm.apply(ctx)
    from paddle_tpu.incubate.optimizer import GradientMergeOptimizer

    assert isinstance(ctx.optimizer, GradientMergeOptimizer)
    assert ctx.optimizer.inner._grad_clip is not None
    assert ctx.optimizer._zero_stage == 2
    # the transformed triple still trains
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    y = paddle.to_tensor(np.zeros((4, 1), np.float32))
    for _ in range(4):
        loss = ((ctx.model(x) - y) ** 2).mean()
        loss.backward()
        ctx.optimizer.step()
        ctx.optimizer.clear_grad()


def test_pipeline_scheduler_pass():
    from paddle_tpu.distributed import ProcessMesh
    from paddle_tpu.distributed.fleet.meta_parallel import PipelineStack

    class Blk(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 8)

        def forward(self, x):
            return paddle.tanh(self.fc(x))

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    paddle.seed(0)
    stack = PipelineStack([Blk() for _ in range(4)], mesh, pp_axis="pp")

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.layers = stack

        def forward(self, x):
            return self.layers(x)

    m = M()
    ctx = PassContext(m, None)
    PassManager([new_pass("pipeline_scheduler", {"schedule": "FThenB", "num_microbatches": 8})]).apply(ctx)
    assert stack._schedule == "FThenB" and stack._num_microbatches == 8
    assert ctx.attrs["pipeline_stacks"] == 1


def test_fp16_program_rewrite_pass():
    """Program-REWRITING distributed pass (reference auto_parallel_fp16.py
    transforms the ProgramDesc): white-listed ops in a captured Program are
    replaced by bf16-compute clones; numerics shift by at most bf16
    rounding, consumers/avals untouched."""
    import jax
    import numpy as np
    import paddle_tpu.static as static
    from paddle_tpu.static.program import Program, program_guard
    from paddle_tpu.distributed.passes import PassContext, new_pass

    rng = np.random.default_rng(0)
    a = rng.normal(size=(8, 16)).astype(np.float32)
    b = rng.normal(size=(16, 4)).astype(np.float32)

    def capture():
        prog = Program()
        with program_guard(prog):
            av = prog.add_feed(prog.new_var(jax.ShapeDtypeStruct((8, 16), np.float32), "a"))
            bv = prog.add_feed(prog.new_var(jax.ShapeDtypeStruct((16, 4), np.float32), "b"))
            out = paddle.tanh(paddle.matmul(av, bv)).sum()
        return prog, out

    prog_ref, out_ref = capture()
    exe = static.Executor()
    ref = exe.run(prog_ref, feed={"a": a, "b": b}, fetch_list=[out_ref])[0]

    prog, out = capture()
    ctx = new_pass("auto_parallel_fp16").apply(PassContext(main_program=prog))
    assert ctx.attrs["fp16_rewritten_ops"] == 1
    types = [op.type for op in prog.global_block().ops]
    assert "fp16::matmul" in types and "matmul" not in types
    got = exe.run(prog, feed={"a": a, "b": b}, fetch_list=[out])[0]
    # bf16 compute inside the op; output cast back to fp32
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-2)

    # also reachable through the static pass registry
    from paddle_tpu.static.passes import apply_pass

    prog2, out2 = capture()
    n = apply_pass(prog2, "auto_parallel_fp16")
    assert n == 1
