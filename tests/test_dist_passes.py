"""Distributed pass framework (reference python/paddle/distributed/passes/
PassManager + named passes; see paddle_tpu/distributed/passes/)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.passes import PassContext, PassManager, new_pass


def _ctx():
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
    return PassContext(m, opt)


def test_pass_registry_and_unknown():
    with pytest.raises(ValueError, match="unknown pass"):
        new_pass("nope")


def test_fp16_pass_casts_params():
    ctx = _ctx()
    PassManager([new_pass("auto_parallel_fp16", {"dtype": "bfloat16"})]).apply(ctx)
    import jax.numpy as jnp

    assert all(p._value.dtype == jnp.bfloat16 for p in ctx.model.parameters())
    assert ctx.attrs["amp_level"] == "O2"


def test_gradient_merge_and_clip_passes():
    ctx = _ctx()
    pm = PassManager([
        new_pass("auto_parallel_grad_clip", {"clip_norm": 0.5}),
        new_pass("auto_parallel_gradient_merge", {"k_steps": 2}),
        new_pass("auto_parallel_sharding", {"stage": 2}),
    ])
    assert pm.names == ["auto_parallel_grad_clip", "auto_parallel_gradient_merge", "auto_parallel_sharding"]
    ctx = pm.apply(ctx)
    from paddle_tpu.incubate.optimizer import GradientMergeOptimizer

    assert isinstance(ctx.optimizer, GradientMergeOptimizer)
    assert ctx.optimizer.inner._grad_clip is not None
    assert ctx.optimizer._zero_stage == 2
    # the transformed triple still trains
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    y = paddle.to_tensor(np.zeros((4, 1), np.float32))
    for _ in range(4):
        loss = ((ctx.model(x) - y) ** 2).mean()
        loss.backward()
        ctx.optimizer.step()
        ctx.optimizer.clear_grad()


def test_pipeline_scheduler_pass():
    from paddle_tpu.distributed import ProcessMesh
    from paddle_tpu.distributed.fleet.meta_parallel import PipelineStack

    class Blk(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 8)

        def forward(self, x):
            return paddle.tanh(self.fc(x))

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    paddle.seed(0)
    stack = PipelineStack([Blk() for _ in range(4)], mesh, pp_axis="pp")

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.layers = stack

        def forward(self, x):
            return self.layers(x)

    m = M()
    ctx = PassContext(m, None)
    PassManager([new_pass("pipeline_scheduler", {"schedule": "FThenB", "num_microbatches": 8})]).apply(ctx)
    assert stack._schedule == "FThenB" and stack._num_microbatches == 8
    assert ctx.attrs["pipeline_stacks"] == 1


def test_fp16_program_rewrite_pass():
    """Program-REWRITING distributed pass (reference auto_parallel_fp16.py
    transforms the ProgramDesc): white-listed ops in a captured Program are
    replaced by bf16-compute clones; numerics shift by at most bf16
    rounding, consumers/avals untouched."""
    import jax
    import numpy as np
    import paddle_tpu.static as static
    from paddle_tpu.static.program import Program, program_guard
    from paddle_tpu.distributed.passes import PassContext, new_pass

    rng = np.random.default_rng(0)
    a = rng.normal(size=(8, 16)).astype(np.float32)
    b = rng.normal(size=(16, 4)).astype(np.float32)

    def capture():
        prog = Program()
        with program_guard(prog):
            av = prog.add_feed(prog.new_var(jax.ShapeDtypeStruct((8, 16), np.float32), "a"))
            bv = prog.add_feed(prog.new_var(jax.ShapeDtypeStruct((16, 4), np.float32), "b"))
            out = paddle.tanh(paddle.matmul(av, bv)).sum()
        return prog, out

    prog_ref, out_ref = capture()
    exe = static.Executor()
    ref = exe.run(prog_ref, feed={"a": a, "b": b}, fetch_list=[out_ref])[0]

    prog, out = capture()
    ctx = new_pass("auto_parallel_fp16").apply(PassContext(main_program=prog))
    assert ctx.attrs["fp16_rewritten_ops"] == 1
    types = [op.type for op in prog.global_block().ops]
    assert "fp16::matmul" in types and "matmul" not in types
    got = exe.run(prog, feed={"a": a, "b": b}, fetch_list=[out])[0]
    # bf16 compute inside the op; output cast back to fp32
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-2)

    # also reachable through the static pass registry
    from paddle_tpu.static.passes import apply_pass

    prog2, out2 = capture()
    n = apply_pass(prog2, "auto_parallel_fp16")
    assert n == 1


# --------------------------------------------------------------------------
# Program-REWRITING passes (VERDICT r3 #4): recompute / gradient-merge /
# sharding transform a CAPTURED training-step Program and preserve numerics.


def _capture_train_step(lr=0.1, seed=0, hidden=8):
    """model fwd + loss + minimize captured as one Program; returns
    (program, loss_var, feed_builder, eager_twin_builder)."""
    import jax
    import paddle_tpu.optimizer as opt
    from paddle_tpu.static.program import Program, program_guard

    paddle.seed(seed)
    m = nn.Sequential(
        nn.Linear(hidden, 2 * hidden), nn.Tanh(), nn.Linear(2 * hidden, 1))
    o = opt.Momentum(learning_rate=lr, momentum=0.9, parameters=m.parameters())
    prog = Program()
    with program_guard(prog):
        xv = prog.add_feed(prog.new_var(
            jax.ShapeDtypeStruct((4, hidden), np.float32), "x"))
        yv = prog.add_feed(prog.new_var(
            jax.ShapeDtypeStruct((4, 1), np.float32), "y"))
        loss = ((m(xv) - yv) ** 2).mean()
        o.minimize(loss)
    return prog, loss, m, o


def _run_steps(prog, loss_var, batches):
    """Run steps; returns (losses, TRAINED state from the executor scope —
    program.state_tensors() only holds the untrained inits)."""
    import paddle_tpu.static as static

    exe = static.Executor()
    losses = []
    for x, y in batches:
        out = exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss_var])
        losses.append(float(np.asarray(out[0])))
    state = {name: np.asarray(v) for name, v in exe.state_dict(prog).items()}
    return losses, state


def _batches(n, hidden=8, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.normal(size=(4, hidden)).astype(np.float32),
             rng.normal(size=(4, 1)).astype(np.float32)) for _ in range(n)]


def test_recompute_program_rewrite_preserves_numerics():
    from paddle_tpu.static.passes import apply_pass

    batches = _batches(3)
    prog_ref, loss_ref, _, _ = _capture_train_step()
    ref_losses, ref_state = _run_steps(prog_ref, loss_ref, batches)

    prog, loss, _, _ = _capture_train_step()
    n = apply_pass(prog, "auto_parallel_recompute", segments=2,
                   fetch_vids=[loss._vid])
    assert n == 2
    types = [op.type for op in prog.global_block().ops]
    assert types.count("recompute::segment") == 2
    assert "grad" in types and "optimizer_update" in types
    # the checkpointed composites are what the GRAD op differentiates:
    # its jaxpr must contain the remat primitive
    import jax

    grad_op = next(op for op in prog.global_block().ops if op.type == "grad")
    avals = [prog._var_by_vid[s[1]]._value for s in grad_op.arg_spec if s[0] == "var"]
    jaxpr = str(jax.make_jaxpr(grad_op.fn)(*[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in avals]))
    assert "remat" in jaxpr or "checkpoint" in jaxpr

    got_losses, got_state = _run_steps(prog, loss, batches)
    np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-5, atol=1e-6)
    for name in ref_state:
        np.testing.assert_allclose(got_state[name], ref_state[name],
                                   rtol=1e-5, atol=1e-6, err_msg=name)


def test_gradient_merge_program_rewrite_matches_eager_wrapper():
    """Rewritten program over 4 batches == eager GradientMergeOptimizer(k=2)
    driving an identical model."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.incubate.optimizer import GradientMergeOptimizer
    from paddle_tpu.static.passes import apply_pass

    batches = _batches(4)

    # eager twin
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    o = GradientMergeOptimizer(
        opt.Momentum(learning_rate=0.1, momentum=0.9, parameters=m.parameters()),
        k_steps=2, avg=True)
    for x, y in batches:
        loss = ((m(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
    eager_params = {p.name: np.asarray(p._value) for p in m.parameters()}

    # rewritten static program
    prog, loss_var, m2, _ = _capture_train_step()
    n = apply_pass(prog, "auto_parallel_gradient_merge", k_steps=2, avg=True)
    assert n == 2
    types = [op.type for op in prog.global_block().ops]
    assert "gradient_merge::accumulate" in types
    assert "gradient_merge::optimizer_update" in types
    _, state = _run_steps(prog, loss_var, batches)

    # compare by parameter ORDER (name counters are global, so the two
    # models' auto-names differ); the program's final param values live in
    # its state under the static twin's names
    eager_vals = [np.asarray(p._value) for p in m.parameters()]
    static_names = [prog.param_vars[id(p)].name for p in m2.parameters()]
    assert len(eager_vals) == len(static_names) == 4
    for ev, name in zip(eager_vals, static_names):
        np.testing.assert_allclose(state[name], ev, rtol=1e-4, atol=1e-5,
                                   err_msg=name)


def test_gradient_merge_counter_and_acc_state_cycle():
    """Non-boundary steps must leave params untouched and fill the accs;
    boundary steps apply the averaged grad and reset."""
    from paddle_tpu.static.executor import global_scope
    from paddle_tpu.static.passes import apply_pass

    prog, loss_var, _, _ = _capture_train_step()
    params_before = {name: np.asarray(t._value)
                     for name, t in prog.state_tensors().items()}
    apply_pass(prog, "auto_parallel_gradient_merge", k_steps=2, avg=True)
    gm_vids = {v.name: v._vid for v in prog.list_vars()
               if v.name.startswith(("gm_counter", "gm_acc"))}

    import paddle_tpu.static as static

    exe = static.Executor()
    scope = global_scope()

    def gm_state():
        return {n: np.asarray(scope.find_var(vid)) for n, vid in gm_vids.items()
                if scope.find_var(vid) is not None}

    (x, y), = _batches(1)
    exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss_var])
    mid_params = {n: v for n, v in exe.state_dict(prog).items()
                  if n in params_before}
    mid = gm_state()
    # step 1 of 2: params unchanged, counter=1, accs nonzero
    for name, val in params_before.items():
        if name in mid_params:
            np.testing.assert_allclose(np.asarray(mid_params[name]), val,
                                       err_msg=f"{name} moved early")
    assert mid["gm_counter"] == 1
    assert any(np.abs(v).sum() > 0 for n, v in mid.items() if n.startswith("gm_acc"))
    exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss_var])
    end = gm_state()
    end_params = {n: np.asarray(v) for n, v in exe.state_dict(prog).items()}
    # boundary: params moved, counter and accs reset
    assert end["gm_counter"] == 0
    assert all(np.abs(v).sum() == 0 for n, v in end.items() if n.startswith("gm_acc"))
    moved = [n for n in params_before
             if n in end_params and not np.allclose(end_params[n], params_before[n])]
    assert moved, "no parameter moved on the boundary step"


def test_sharding_program_rewrite_constrains_and_preserves_numerics():
    import jax
    from jax.sharding import Mesh
    from paddle_tpu.static.passes import apply_pass

    batches = _batches(3)
    prog_ref, loss_ref, _, _ = _capture_train_step()
    ref_losses, ref_state = _run_steps(prog_ref, loss_ref, batches)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    prog, loss_var, _, _ = _capture_train_step()
    n = apply_pass(prog, "auto_parallel_sharding", mesh=mesh, stage=2, axis="dp")
    assert n == 2  # update op + grad op rewritten
    types = [op.type for op in prog.global_block().ops]
    assert "zero::optimizer_update" in types
    # constraint really present in the lowered grad computation (the grad
    # super-op is renamed zero::grad by the rewrite)
    grad_op = next(op for op in prog.global_block().ops
                   if op.type.endswith("grad"))
    avals = [prog._var_by_vid[s[1]]._value for s in grad_op.arg_spec if s[0] == "var"]
    jaxpr = str(jax.make_jaxpr(grad_op.fn)(
        *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in avals]))
    assert "sharding_constraint" in jaxpr

    got_losses, got_state = _run_steps(prog, loss_var, batches)
    np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-5, atol=1e-6)
    for name in ref_state:
        np.testing.assert_allclose(got_state[name], ref_state[name],
                                   rtol=1e-5, atol=1e-6, err_msg=name)


def test_gradient_merge_and_sharding_compose_in_both_orders():
    """ZeRO + grad-accumulation is a standard strategy combo: the rewrites
    must anchor on namespaced super-ops from a prior pass (either order)."""
    import jax
    from jax.sharding import Mesh
    from paddle_tpu.static.passes import apply_pass

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    batches = _batches(2)

    for order in ("merge_first", "shard_first"):
        prog, loss_var, _, _ = _capture_train_step()
        if order == "merge_first":
            apply_pass(prog, "auto_parallel_gradient_merge", k_steps=2, avg=True)
            n = apply_pass(prog, "auto_parallel_sharding", mesh=mesh, stage=1)
        else:
            apply_pass(prog, "auto_parallel_sharding", mesh=mesh, stage=1)
            n = apply_pass(prog, "auto_parallel_gradient_merge", k_steps=2, avg=True)
        assert n >= 1, order
        types = [op.type for op in prog.global_block().ops]
        assert any("gradient_merge::" in t and "optimizer_update" in t
                   or "zero::" in t for t in types), types
        losses, _ = _run_steps(prog, loss_var, batches)
        assert all(np.isfinite(losses)), order


def test_recompute_after_sharding_keeps_grad_constraints():
    """Compose order sharding -> recompute must NOT drop the ZeRO gradient
    sharding constraints when the grad super-op is rebuilt."""
    import jax
    from jax.sharding import Mesh
    from paddle_tpu.static.passes import apply_pass

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    prog, loss_var, _, _ = _capture_train_step()
    apply_pass(prog, "auto_parallel_sharding", mesh=mesh, stage=2)
    apply_pass(prog, "auto_parallel_recompute", segments=2,
               fetch_vids=[loss_var._vid])
    grad_op = next(op for op in prog.global_block().ops
                   if op.type.endswith("grad"))
    avals = [prog._var_by_vid[s[1]]._value for s in grad_op.arg_spec if s[0] == "var"]
    jaxpr = str(jax.make_jaxpr(grad_op.fn)(
        *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in avals]))
    assert "sharding_constraint" in jaxpr  # survived the rebuild
    assert "remat" in jaxpr or "checkpoint" in jaxpr  # recompute applied
    losses, _ = _run_steps(prog, loss_var, _batches(2))
    assert all(np.isfinite(losses))


@pytest.mark.slow  # the zero-rewrite + pipeline + TP 3-way compose now RUNS
# on jax 0.4.37 (shard_map_compat full-manual fallback) but XLA:CPU's
# in-process 8-device communicator intermittently SIGSEGV/SIGABRTs under it —
# a process-killing crash, not a failure, so it stays out of the tier-1 pass
# (plain pipeline tests cover the fallback deterministically; this compose
# runs on real meshes / the nightly slow lane)
def test_zero_rewrite_composes_with_pipeline_mesh():
    """VERDICT r4 item 10: the ZeRO program-rewrite composed with pp — a
    dp2 x pp2 x mp2 captured train step (pipelined trunk, TP shardings)
    rewritten by auto_parallel_sharding stage 2 reproduces the unrewritten
    program's losses on the 8-device mesh.  (Also the driver-visible
    __graft_entry__ dryrun config D.)"""
    import __graft_entry__ as ge

    ge._dryrun_hybrid_zero_rewrite(8)
