"""Disaggregated serving cluster (serving/cluster.py + serving/router.py,
docs/SERVING_CLUSTER.md, ROADMAP item 2).

Two tiers:

- **Unit tier** (no processes): every robustness decision is a plain host
  state machine in serving/router.py — chained block hashes, the cluster
  prefix index, the durable intake log (torn-tail tolerance), the
  miss-threshold failure detector (fake clock), retry_backoff deadlines,
  and the RequestRouter's per-position dedup/merge + re-dispatch sets.
  Plus the engine-side cluster surface: explicit submit-time nonces and
  pool-native page adoption (`adopt_pages` + `pool_get_blocks`).
- **E2E tier** (REAL OS processes over TCPStore + ShmRing): a live
  cluster serves greedy + sampled streams bit-identical to one local
  engine, ships prefill pages with prefix-affinity routing, and
  drain-migrates queued requests on scale-down with no double-serving.

The SIGKILL crash matrix lives in test_serving_cluster_crash.py.  Both
modules fork and kill processes, so they ride DEDICATED
tools/run_tier1.py isolated workers — never the shared shard."""

import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import GenerationEngine
from paddle_tpu.serving.router import (ClusterPrefixIndex, FailureDetector,
                                       IntakeLog, RequestRouter,
                                       block_hashes, retry_backoff)

_HERE = os.path.dirname(os.path.abspath(__file__))
_MODEL_SPEC = os.path.join(_HERE, "cluster_common.py") + ":make_model"

from tests.cluster_common import make_model, make_model_bf16  # noqa: E402

_EKW = dict(max_batch=2, block_size=8, num_blocks=32, decode_chunk=2)

# two prompts sharing one full 8-token block (the shipped/affinity unit)
# plus distinct tails, and one short sampled prompt with no full block
_SHARED = [5, 9, 17, 33, 2, 8, 7, 4]
P_G1 = _SHARED + [22, 3]
P_G2 = _SHARED + [9, 1]
P_S1 = [7, 11, 3]


# ---------------------------------------------------------------- unit tier
def test_block_hashes_are_chained_prefix_identity():
    bs = 4
    a = block_hashes([1, 2, 3, 4, 5, 6, 7, 8, 9], bs)
    assert len(a) == 2  # the partial third block never hashes
    b = block_hashes([1, 2, 3, 4, 5, 6, 7, 8], bs)
    assert a == b[:2] and len(b) == 2
    # a change in block 0 changes EVERY later hash (chaining): equal hash
    # at depth i must mean equal whole prefix, not equal chunk
    c = block_hashes([9, 2, 3, 4, 5, 6, 7, 8], bs)
    assert c[0] != a[0] and c[1] != a[1]
    # same chunk content at a different depth hashes differently
    d = block_hashes([5, 6, 7, 8], bs)
    assert d[0] != a[1]


def test_prefix_index_affinity_and_drop():
    idx = ClusterPrefixIndex(block_size=4)
    idx.record(0, [1, 2, 3, 4, 5, 6, 7, 8])
    idx.record(1, [1, 2, 3, 4])
    rank, depth = idx.best_replica([1, 2, 3, 4, 5, 6, 7, 8, 9])
    assert (rank, depth) == (0, 2)  # deepest holder wins
    rank, depth = idx.best_replica([1, 2, 3, 4, 99, 98, 97, 96])
    assert depth == 1 and rank in (0, 1)
    assert idx.best_replica([9, 9, 9, 9]) == (None, 0)
    # `among` restricts to live replicas; a dead rank's pages drop wholesale
    rank, depth = idx.best_replica([1, 2, 3, 4, 5, 6, 7, 8], among={1})
    assert (rank, depth) == (1, 1)
    idx.drop_rank(0)
    rank, depth = idx.best_replica([1, 2, 3, 4, 5, 6, 7, 8])
    assert (rank, depth) == (1, 1)


def test_intake_log_replay_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "intake.jsonl")
    log = IntakeLog(path)
    log.append({"ev": "submit", "rid": "a", "prompt": [1, 2], "opts": {},
                "nonce": 0})
    log.append({"ev": "tokens", "rid": "a", "start": 0, "toks": [7, 8]})
    log.close()
    # a SIGKILL mid-append leaves a torn trailing line: replay drops it
    with open(path, "a") as f:
        f.write('{"ev": "tok')
    recs = IntakeLog.replay(path)
    assert [r["ev"] for r in recs] == ["submit", "tokens"]
    # an INTERIOR torn line is corruption, not a crash artifact: loud
    with open(path, "w") as f:
        f.write('{"ev": "submit"}\n{"torn\n{"ev": "done"}\n')
    with pytest.raises(ValueError, match="corrupt"):
        IntakeLog.replay(path)
    assert IntakeLog.replay(str(tmp_path / "missing.jsonl")) == []


def test_retry_backoff_shared_deadline_and_counting():
    import random

    calls = {"n": 0}
    retries = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise TimeoutError("transient")
        return "ok"

    assert retry_backoff(flaky, timeout_s=5.0, base_s=0.001,
                         rng=random.Random(0),
                         on_retry=retries.append) == "ok"
    assert calls["n"] == 4 and len(retries) == 3

    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        retry_backoff(lambda: (_ for _ in ()).throw(TimeoutError("x")),
                      timeout_s=0.25, base_s=0.01, cap_s=0.05,
                      rng=random.Random(0))
    assert time.monotonic() - t0 < 1.0  # ONE deadline, not per-attempt
    # non-retryable errors propagate immediately
    with pytest.raises(ValueError):
        retry_backoff(lambda: (_ for _ in ()).throw(ValueError("real")),
                      timeout_s=5.0)


def test_retry_backoff_jitter_bounded_by_cap():
    """The sleep between attempts is full jitter on min(delay, cap_s):
    never negative, never above the cap even after the exponential
    doubling passes it — the contract that keeps N retrying callers from
    synchronizing into a thundering herd with unbounded gaps."""
    import random

    class SpyRng:
        def __init__(self):
            self.bounds = []

        def uniform(self, lo, hi):
            self.bounds.append((lo, hi))
            return 0.0  # no actual sleeping

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 6:
            raise ConnectionError("transient")
        return "ok"

    rng = SpyRng()
    assert retry_backoff(flaky, timeout_s=30.0, base_s=0.01, cap_s=0.04,
                         rng=rng) == "ok"
    # delays double 0.01, 0.02, 0.04, 0.08, 0.16 — but the jitter bound
    # saturates at cap_s
    assert [hi for _, hi in rng.bounds] == \
        [0.01, 0.02, 0.04, 0.04, 0.04]
    assert all(lo == 0.0 for lo, _ in rng.bounds)  # full jitter from 0

    # the real rng draws stay inside [0, cap_s] too
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        retry_backoff(lambda: (_ for _ in ()).throw(TimeoutError("x")),
                      timeout_s=0.1, base_s=0.001, cap_s=0.01,
                      rng=random.Random(7))
    assert time.monotonic() - t0 < 0.5


def test_prefix_index_drop_rank_shared_chain():
    """Two replicas share a chained-hash prefix; dropping one must peel
    ONLY its ranks out of the shared entries (the survivor keeps serving
    the common prefix) and drop its exclusive deeper entries wholesale."""
    idx = ClusterPrefixIndex(block_size=4)
    common = [1, 2, 3, 4, 5, 6, 7, 8]
    idx.record(0, common + [9, 10, 11, 12])  # rank 0: 3 blocks deep
    idx.record(1, common)                    # rank 1: the shared 2 blocks
    assert idx.best_replica(common + [9, 10, 11, 12]) == (0, 3)

    idx.drop_rank(0)
    # the shared chain survives via rank 1; rank 0's depth-3 page is gone
    assert idx.best_replica(common + [9, 10, 11, 12]) == (1, 2)
    assert idx.best_replica(common) == (1, 2)
    # internal maps really shrank: no orphaned hash buckets, no rank-0
    # residue to resurrect a corpse's affinity
    assert 0 not in idx._ranks
    assert all(0 not in holders for holders in idx._by_hash.values())
    assert len(idx._by_hash) == 2

    # dropping the survivor empties the index; a re-drop is a no-op
    idx.drop_rank(1)
    idx.drop_rank(1)
    assert idx._by_hash == {} and idx._ranks == {}
    assert idx.best_replica(common) == (None, 0)


def test_intake_log_replay_multi_record_torn_tail(tmp_path):
    """A SIGKILL tears at most the FINAL record: replay over a long log
    keeps every whole record and drops only a trailing partial — while a
    torn line with records AFTER it is corruption and stays loud, no
    matter how deep the log."""
    path = str(tmp_path / "intake.jsonl")
    log = IntakeLog(path)
    records = []
    for i in range(20):
        rec = {"ev": "tokens", "rid": f"r{i % 3}", "start": 4 * i,
               "toks": [i, i + 1]}
        records.append(rec)
        log.append(rec)
    log.close()
    assert IntakeLog.replay(path) == records

    with open(path, "a") as f:
        f.write('{"ev": "done", "rid": "r0", "n"')  # torn final append
    assert IntakeLog.replay(path) == records

    # interior tear: every line after it parses, but durability already
    # lied — loud, with the 1-based line number
    with open(path) as f:
        lines = f.readlines()
    lines[10] = lines[10][:9] + "\n"
    with open(path, "w") as f:
        f.writelines(lines)
    with pytest.raises(ValueError, match="line 11"):
        IntakeLog.replay(path)


def test_failure_detector_miss_threshold_and_boot_grace():
    clock = {"t": 0.0}
    missed = []
    det = FailureDetector(100, 3, clock=lambda: clock["t"],
                          on_miss=missed.append, boot_grace_s=5.0)
    det.track("r0")
    # boot window: the counter sits at its creation value (0) while the
    # worker imports jax — NOT dead until the boot grace, and no miss
    # telemetry noise from a normal boot
    clock["t"] = 0.4
    det.observe("r0", 0)
    assert det.dead_ranks() == [] and missed == []
    clock["t"] = 1.0
    det.observe("r0", 1)  # first real heartbeat: steady-state rules arm
    assert det.misses("r0") == 0
    clock["t"] = 1.25
    det.observe("r0", 1)
    assert det.dead_ranks() == []  # 2 misses < 3
    assert missed == [2]
    clock["t"] = 1.31
    assert det.dead_ranks() == ["r0"]  # 3rd missed period
    assert sum(missed) == 3  # each missed period reported exactly once
    # a beat resets the window
    det.observe("r0", 2)
    assert det.dead_ranks() == [] and det.misses("r0") == 0
    # a rank that NEVER beats dies at the boot grace
    det.track("r1")
    clock["t"] = 6.5
    assert "r1" in det.dead_ranks()
    det.forget("r1")
    assert "r1" not in det.dead_ranks()


def test_request_router_dedup_merge_and_redispatch(tmp_path):
    r = RequestRouter(block_size=4, log_path=str(tmp_path / "log.jsonl"))
    r.add_replica(0)
    r.add_replica(1)
    req = r.submit("a", [1, 2, 3, 4, 5], max_new=4, temperature=0.0, seed=0)
    assert req.nonce == 0
    # idempotent acceptance: a resubmitted rid keeps its first nonce
    assert r.submit("a", [1, 2, 3, 4, 5]).nonce == 0
    assert r.submit("b", [9, 9]).nonce == 1
    r.assign("a", 0)
    r.assign("b", 0)
    assert r.load(0) == 2
    assert r.on_tokens("a", 0, [10, 11]) == [10, 11]
    # re-emission after fail-over: overlap dedups, the tail appends
    assert r.on_tokens("a", 0, [10, 11, 12]) == [12]
    # divergence is corruption, never silently merged
    with pytest.raises(RuntimeError, match="diverge"):
        r.on_tokens("a", 1, [99])
    # a gap means a lost event: loud
    with pytest.raises(RuntimeError, match="gap|starts at"):
        r.on_tokens("b", 3, [1])
    # replica death: unfinished owned rids come back for re-dispatch
    r.on_tokens("b", 0, [20])
    r.on_done("b", 1)
    assert r.result("b") == [20]
    assert r.on_replica_dead(0) == ["a"]  # done "b" never moves
    assert r.unassigned() == ["a"]
    # the journal rebuilds the same state in a fresh router
    r2 = RequestRouter(block_size=4)
    r2.restore(IntakeLog.replay(str(tmp_path / "log.jsonl")))
    assert r2.result("b") == [20]
    assert r2.request("a").tokens == [10, 11, 12]
    assert r2.request("a").nonce == 0
    assert r2.submit("c", [1]).nonce == 2  # counter resumes PAST the log
    # drain: queued (never-started) rids migrate, residents stay
    r2.add_replica(1)
    r2.assign("a", 1)
    r2.assign("c", 1)
    assert r2.on_drained(1, ["c"]) == ["c"]
    assert r2.request("a").owner == 1 and r2.request("c").owner is None


def test_done_redelivery_counts_hit_toks_once():
    """REVIEW regression: the wire is at-least-once (TcpRing re-sends
    its in-flight frame whole after a drop), and `done` carries the
    prefix-hit watermark as a DELTA — a redelivered `done` must not
    double-count it into `prefix_hit_tokens`.  on_done returns True only
    on the FIRST completion and the handler gates the add on it."""
    from paddle_tpu.serving import cluster as cl

    r = RequestRouter(block_size=4)
    r.add_replica(0)
    r.submit("a", [1, 2, 3], max_new=1, temperature=0.0, seed=0)
    r.assign("a", 0)
    r.on_tokens("a", 0, [7])
    assert r.on_done("a", 1) is True
    assert r.on_done("a", 1) is False  # redelivered: not first
    assert r.on_done("ghost", 0) is False  # unknown rid: never counted

    class _Shell:
        router = r

    r.submit("b", [4, 5], max_new=1, temperature=0.0, seed=0)
    r.assign("b", 0)
    r.on_tokens("b", 0, [9])
    before = cl._CLUSTER_STATS["prefix_hit_tokens"]
    try:
        msg = {"rid": "b", "n": 1, "hit_toks": 8}
        cl.EngineCluster._ev_done(_Shell(), None, msg)
        cl.EngineCluster._ev_done(_Shell(), None, dict(msg))  # dup frame
        assert cl._CLUSTER_STATS["prefix_hit_tokens"] - before == 8
    finally:
        cl._CLUSTER_STATS["prefix_hit_tokens"] = before


def test_router_pick_replica_affinity_then_load():
    r = RequestRouter(block_size=4)
    for i in range(3):
        r.add_replica(i)
    p = [1, 2, 3, 4, 5, 6, 7, 8]
    r.submit("a", p)
    r.assign("a", 2)  # records the prompt's hashes for replica 2
    assert r.pick_replica(p) == 2  # affinity beats emptier replicas
    assert r.pick_replica([9, 9, 9, 9, 9]) in (0, 1)  # cold: least load
    assert r.pick_replica(p, among={0, 1}) in (0, 1)  # dead excluded


def test_explicit_nonce_reproduces_stream():
    """The bit-exact fail-over keystone: (seed, nonce) is request
    identity.  An engine given EXPLICIT nonces (the router's assignment)
    draws exactly the streams another engine produced with its local
    counter — submission order, engine instance, and admission timing
    all drop out."""
    m = make_model()
    ref = GenerationEngine(m, **_EKW)
    ref.add_request("x", P_S1, max_new_tokens=5, temperature=5.0, seed=3)
    ref.add_request("y", P_S1, max_new_tokens=5, temperature=5.0, seed=3)
    while ref.has_work():
        ref.step()

    eng = GenerationEngine(m, **_EKW)
    # reversed submission order, explicit nonces pinned to the identity
    eng.add_request("y", P_S1, max_new_tokens=5, temperature=5.0, seed=3,
                    nonce=1)
    eng.add_request("x", P_S1, max_new_tokens=5, temperature=5.0, seed=3,
                    nonce=0)
    while eng.has_work():
        eng.step()
    assert eng.result("x") == ref.result("x")
    assert eng.result("y") == ref.result("y")
    assert eng.result("x") != eng.result("y")  # distinct nonces still true
    # the local counter advanced PAST the explicit nonces: no collision
    assert eng._req_counter == 2


def _prefill_pages_for(model, prompt, kv="bf16"):
    from paddle_tpu.serving.cluster_worker import _prefill_pages

    n = (len(prompt) - 1) // _EKW["block_size"]
    return _prefill_pages(model, prompt, n, _EKW["block_size"], kv)


def test_adopt_pages_prefix_hit_bit_exact():
    """Shipped pages adopt as refcount-zero cached prefix pages, the next
    admission prefix-hits them, and the served stream is BIT-identical to
    a local-prefill engine (full-precision pools; the engine pours and
    the prefill worker pours through the same math)."""
    from paddle_tpu.serving import decode_stats, reset_decode_stats

    m = make_model()
    ref = GenerationEngine(m, prefix_cache=True, **_EKW)
    ref.add_request("g", P_G1, max_new_tokens=6)
    while ref.has_work():
        ref.step()

    eng = GenerationEngine(m, prefix_cache=True, **_EKW)
    toks, k_layers, v_layers = _prefill_pages_for(m, P_G1)
    assert eng.adopt_pages(toks, k_layers, v_layers) == 1
    # adopted pages are resident-but-reclaimable (refcount 0), exactly
    # like pages whose owning request finished
    assert len(eng._prefix) == 1
    reset_decode_stats()
    eng.add_request("g", P_G1, max_new_tokens=6)
    while eng.has_work():
        eng.step()
    st = decode_stats()
    assert st["prefix_hits"] == 1 and st["prefix_hit_tokens"] == 8
    assert eng.result("g") == ref.result("g")
    # re-adoption of a cached prefix is a no-op, not a duplicate page
    toks, k_layers, v_layers = _prefill_pages_for(m, P_G1)
    assert eng.adopt_pages(toks, k_layers, v_layers) == 0


def test_adopt_pages_int8_ship_deterministic_and_lossless():
    """The two facts bit-exact fail-over rests on for int8 shipping:
    (a) shipping is DETERMINISTIC — a re-dispatched request re-ships
    byte-identical pages (same forward, same quantization), so the new
    replica serves the same stream; (b) ship-then-place is LOSSLESS — the
    wire carries the pool's own int8 payload + f32 scales and
    `pool_set_blocks` lands them verbatim, never re-quantizing."""
    m = make_model()
    toks, k1, v1 = _prefill_pages_for(m, P_G1, kv="int8")
    _t, k2, _v2 = _prefill_pages_for(m, P_G1, kv="int8")
    for a, b in zip(k1, k2):  # (a): re-ship is bit-identical
        np.testing.assert_array_equal(a["payload"], b["payload"])
        np.testing.assert_array_equal(a["scale"], b["scale"])

    eng = GenerationEngine(m, prefix_cache=True,
                           **dict(_EKW, kv_cache_dtype="int8"))
    assert eng.adopt_pages(toks, k1, v1) == 1
    ab = eng._prefix.match(toks)[0]
    for li in range(2):  # (b): adopted pool blocks == the shipped leaves
        np.testing.assert_array_equal(
            np.asarray(eng._kpools[li].data[ab]), k1[li]["payload"][0])
        np.testing.assert_array_equal(
            np.asarray(eng._kpools[li].scale[ab]), k1[li]["scale"][0])
    # and an int8 admission over adopted pages serves a complete stream
    eng.add_request("g", P_G1, max_new_tokens=4)
    while eng.has_work():
        eng.step()
    assert len(eng.result("g")) == 4


def test_int8_ship_halves_wire_bytes_vs_bf16():
    m = make_model_bf16()
    _t, k8, v8 = _prefill_pages_for(m, P_G1, kv="int8")
    _t, kbf, vbf = _prefill_pages_for(m, P_G1, kv="bf16")

    def nbytes(layers):
        return sum(a.nbytes for lay in layers for a in lay.values())

    ratio = (nbytes(k8) + nbytes(v8)) / (nbytes(kbf) + nbytes(vbf))
    assert ratio < 0.6, ratio  # int8 payload halves bf16; scales ride along


def test_adopt_pages_loud_on_bad_shapes_and_modes():
    m = make_model()
    eng = GenerationEngine(m, prefix_cache=False, **_EKW)
    with pytest.raises(RuntimeError, match="prefix cache"):
        eng.adopt_pages(P_G1, [], [])
    eng = GenerationEngine(m, prefix_cache=True, **_EKW)
    toks, k_layers, v_layers = _prefill_pages_for(m, P_G1)
    with pytest.raises(ValueError, match="layers"):
        eng.adopt_pages(toks, k_layers[:1], v_layers)
    bad = [{k: v[:, :2] for k, v in lay.items()} for lay in k_layers]
    with pytest.raises(ValueError, match="geometry"):
        eng.adopt_pages(toks, bad, v_layers)
    # pool-kind mismatch (bf16 pages into an int8 pool) is THIS error,
    # not a KeyError deep in pool_set_blocks: the sender quantized for
    # the wrong pool kind and a respawn-retry loop cannot fix that
    eng8 = GenerationEngine(make_model(), prefix_cache=True,
                            **dict(_EKW, kv_cache_dtype="int8"))
    with pytest.raises(ValueError, match="kind|leaves"):
        eng8.adopt_pages(toks, k_layers, v_layers)


# ------------------------------------------------------- adapter namespaces
# cluster adapter specs: (name, rank, alpha, seed) — alpha 64 so the
# tiny model's greedy argmax genuinely moves under the adapter (tenant
# streams must be OBSERVABLY distinct, or isolation tests prove nothing)
_ADAPTER_SPECS = [("tenant-a", 4, 64.0, 11), ("tenant-b", 4, 64.0, 12)]


def test_cluster_adapter_table_lockstep_with_engine_registration():
    """cluster_adapter_table is a PROMISE about engine behaviour — spec i
    lands at (slot i+1, epoch 1) — kept only because every worker
    registers the specs in order on a fresh engine.  Pin the table to the
    real registration path so a slot-assignment or epoch-bump change
    breaks HERE, not as a silent cluster-wide cache mismatch."""
    from paddle_tpu.serving.cluster_worker import _register_cluster_adapters
    from paddle_tpu.serving.router import cluster_adapter_table

    table = cluster_adapter_table(_ADAPTER_SPECS)
    assert table == {"tenant-a": (1, 1), "tenant-b": (2, 1)}

    eng = GenerationEngine(make_model(), prefix_cache=True,
                           adapters={"rank": 4, "max_adapters": 2}, **_EKW)
    _register_cluster_adapters(eng, {"adapters": _ADAPTER_SPECS})
    for name, (slot, epoch) in table.items():
        got = eng._slot_of(name)
        assert got == slot, (name, got, slot)
        assert eng._slot_epochs[got] == epoch
    # re-registration (a snapshot-restored engine re-running boot) must
    # leave resident names untouched: an epoch bump here would desync
    # this engine's namespace from the rest of the fleet
    _register_cluster_adapters(eng, {"adapters": _ADAPTER_SPECS})
    assert eng._slot_epochs[1] == 1 and eng._slot_epochs[2] == 1


def test_block_hashes_adapter_namespaces_disjoint():
    # the ns seeds the hash CHAIN, so one prompt under base / tenant-a /
    # tenant-a-after-epoch-bump / tenant-b yields pairwise-disjoint
    # chains — the cluster index can never alias tenants' pages
    chains = [block_hashes(P_G1, 8),
              block_hashes(P_G1, 8, ns=(1, 1)),
              block_hashes(P_G1, 8, ns=(1, 2)),
              block_hashes(P_G1, 8, ns=(2, 1))]
    for i in range(len(chains)):
        for j in range(i + 1, len(chains)):
            assert not set(chains[i]) & set(chains[j]), (i, j)


def test_adopt_pages_adapter_namespace_isolation_and_stale_epoch():
    """Shipped adapter pages land in exactly the (slot, epoch) namespace
    pinned at SHIP time: the tenant's own admission prefix-hits them,
    no other tenant (nor the base model) ever cross-matches, a stale
    epoch strands the shipment LOUDLY, and a base engine refuses
    namespaced pages outright."""
    from paddle_tpu.nn.lora import adapter_prefill_scope
    from paddle_tpu.serving import (decode_stats, lora_stats,
                                    reset_decode_stats)
    from paddle_tpu.serving.cluster_worker import (
        _build_prefill_pack, _cluster_adapter_state, _prefill_pages,
        _register_cluster_adapters)

    m = make_model()
    spec = {"adapters": _ADAPTER_SPECS}
    # pages poured through tenant-a's weights, the prefill-worker path
    pack = _build_prefill_pack(m, spec)
    scope = adapter_prefill_scope(m.model.layers, pack, 1)
    toks, k_l, v_l = _prefill_pages(m, P_G1, 1, _EKW["block_size"],
                                    "bf16", scope=scope)

    eng = GenerationEngine(m, prefix_cache=True,
                           adapters={"rank": 4, "max_adapters": 2}, **_EKW)
    _register_cluster_adapters(eng, spec)
    assert eng.adopt_pages(toks, k_l, v_l, ns=(1, 1)) == 1
    reset_decode_stats()
    eng.add_request("qa", P_G1, max_new_tokens=4, adapter="tenant-a")
    while eng.has_work():
        eng.step()
    st = decode_stats()
    assert st["prefix_hits"] == 1 and st["prefix_hit_tokens"] == 8

    # the OTHER tenant and the base model never match tenant-a's pages
    for rid, adapter in (("qb", "tenant-b"), ("qc", None)):
        reset_decode_stats()
        eng.add_request(rid, P_G1, max_new_tokens=4, adapter=adapter)
        while eng.has_work():
            eng.step()
        assert decode_stats()["prefix_hits"] == 0, (rid, adapter)
    # and the tenants' streams are genuinely distinct computations
    assert eng.result("qa") != eng.result("qc")
    assert eng.result("qa") != eng.result("qb")

    # stale epoch: tenant-a re-registers (epoch bumps), so a shipment
    # pinned at the OLD epoch holds K/V this engine no longer serves —
    # dropped loudly, never cached
    eng.register_adapter("tenant-a", _cluster_adapter_state(m, 4, 99),
                         alpha=64.0)
    assert eng._slot_epochs[1] == 2
    drops0 = lora_stats()["ship_ns_drops"]
    assert eng.adopt_pages(toks, k_l, v_l, ns=(1, 1)) == 0
    assert lora_stats()["ship_ns_drops"] == drops0 + 1

    # a namespace this pack cannot name is a spec disagreement, not a
    # droppable race
    with pytest.raises(ValueError, match="out of range"):
        eng.adopt_pages(toks, k_l, v_l, ns=(7, 1))

    # a base engine must never accept adapter-poured K/V into its
    # un-namespaced prefix cache
    base = GenerationEngine(m, prefix_cache=True, **_EKW)
    with pytest.raises(ValueError, match="without"):
        base.adopt_pages(toks, k_l, v_l, ns=(1, 1))


# ----------------------------------------------------------------- e2e tier
def _mk_cluster(workdir, **kw):
    from paddle_tpu.serving.cluster import EngineCluster

    kw.setdefault("heartbeat_ms", 100)
    kw.setdefault("miss_threshold", 20)
    return EngineCluster(_MODEL_SPEC, engine_kwargs=_EKW,
                         workdir=str(workdir), **kw)


def _single_engine_reference(submissions, max_batch=4):
    eng = GenerationEngine(make_model(),
                           **dict(_EKW, max_batch=max_batch),
                           prefix_cache=True)
    for rid, prompt, opts in submissions:
        eng.add_request(rid, prompt, **opts)
    while eng.has_work():
        eng.step()
    return {rid: eng.result(rid) for rid, _p, _o in submissions}


_WORKLOAD = [
    ("g1", P_G1, dict(max_new_tokens=8)),
    ("g2", P_G2, dict(max_new_tokens=8)),
    ("s1", P_S1, dict(max_new_tokens=6, temperature=5.0, seed=3)),
]


def _cluster_e2e_matches_single_engine(tmp_path):
    from paddle_tpu.serving.cluster import cluster_stats

    ref = _single_engine_reference(_WORKLOAD)
    c = _mk_cluster(tmp_path / "wd", num_replicas=2, num_prefill=1)
    try:
        for rid, prompt, opts in _WORKLOAD:
            c.submit(rid, prompt,
                     max_new_tokens=opts["max_new_tokens"],
                     temperature=opts.get("temperature", 0.0),
                     seed=opts.get("seed", 0))
        c.serve(timeout_s=240)
        got = {rid: c.result(rid) for rid, _p, _o in _WORKLOAD}
        # full-precision pools: the shipped-page path reproduces the
        # local engine's streams on this workload (the GUARANTEED
        # contract — killed-vs-unkilled cluster bit-exactness — lives in
        # test_serving_cluster_crash.py; this cross-architecture match is
        # the stronger observed property for bf16/f32 pools)
        assert got == ref, (got, ref)
        # prefix affinity routed the shared-prefix pair to ONE replica
        assert (c.router.request("g1").owner
                == c.router.request("g2").owner)
        st = cluster_stats()
        assert st["replicas_alive"] == 2
        assert st["pages_shipped"] >= 2 and st["ship_bytes"] > 0
        assert st["redispatches"] == 0
        # idempotent resubmission: no duplicate serve, stream unchanged
        c.submit("g1", P_G1, max_new_tokens=8)
        c.serve(timeout_s=30)
        assert c.result("g1") == ref["g1"]
    finally:
        c.shutdown()


def _cluster_drain_scale_down(tmp_path):
    from paddle_tpu.serving.cluster import cluster_stats, \
        reset_cluster_stats

    # max_batch 1: the first request occupies replica 0's only slot, the
    # same-prefix followers QUEUE on it (affinity routes them there)
    ekw = dict(_EKW, max_batch=1)
    # "a" is long on purpose: the drain must land while it is RESIDENT
    # (so "b"/"c" are still queued on the worker and genuinely migrate)
    subs = [("a", P_G1, dict(max_new_tokens=40)),
            ("b", P_G2, dict(max_new_tokens=8)),
            ("c", _SHARED + [1, 2], dict(max_new_tokens=8))]
    ref = _single_engine_reference(subs, max_batch=1)

    from paddle_tpu.serving.cluster import EngineCluster

    reset_cluster_stats()
    c = EngineCluster(_MODEL_SPEC, engine_kwargs=ekw,
                      workdir=str(tmp_path / "wd"), num_replicas=2,
                      heartbeat_ms=100, miss_threshold=20)
    try:
        for rid, prompt, opts in subs:
            c.submit(rid, prompt, **{
                "max_new_tokens": opts["max_new_tokens"]})
        owner = c.router.request("a").owner
        assert all(c.router.request(r).owner == owner for r in "abc")
        # let replica `owner` admit "a" (first token delivered) so "b"/"c"
        # are genuinely queued on the worker when the drain lands
        deadline = time.monotonic() + 120
        while not c.router.request("a").tokens:
            c.poll()
            assert time.monotonic() < deadline
            time.sleep(0.002)
        c.scale_down(owner)
        c.serve(timeout_s=240)
        got = {rid: c.result(rid) for rid, _p, _o in subs}
        assert got == ref, (got, ref)
        st = cluster_stats()
        # the queued pair migrated; the resident finished on the lame duck
        assert st["drain_migrations"] == 2
        assert st["replicas_alive"] == 1
        survivors = {c.router.request(r).owner for r in ("b", "c")}
        assert owner not in survivors
    finally:
        c.shutdown()


def _cluster_telemetry_footer(tmp_path):
    from paddle_tpu import profiler
    from paddle_tpu.profiler.statistics import cluster_line

    st = profiler.cluster_stats()
    assert set(st) >= {"replicas_alive", "heartbeats_missed",
                       "redispatches", "pages_shipped", "ship_retries",
                       "drain_migrations"}
    line = cluster_line(dict(st, replicas_alive=2, pages_shipped=3))
    assert "Serving cluster:" in line and "pages_shipped=3" in line
    assert cluster_line({k: 0 for k in st}) == ""
    # reset zeroes traffic counters but keeps the alive gauge
    before = profiler.cluster_stats()["replicas_alive"]
    profiler.cluster_stats(reset=True)
    after = profiler.cluster_stats()
    assert after["replicas_alive"] == before
    assert after["redispatches"] == 0


def _cluster_priority_ahead_of_long(tmp_path):
    # SLO-class admission end-to-end: the replica's only free slot is
    # held by a chunk-interleaved LOW-priority long prefill when a HIGH
    # request lands — the worker engine preempts the LOW request (parks
    # or demotes it) and the HIGH stream completes FIRST, while every
    # final stream still matches an uncontended single engine's
    # (submit-time nonces make the re-admitted stream bit-identical).
    from paddle_tpu.serving.cluster import EngineCluster

    rng = np.random.default_rng(17)
    p_long = [int(t) for t in rng.integers(1, 128, 40)]
    subs = [("w", P_G1, dict(max_new_tokens=20)),
            ("long", p_long, dict(max_new_tokens=16, temperature=5.0,
                                  seed=3, priority="low")),
            ("hi", P_S1, dict(max_new_tokens=6, priority="high"))]
    ref = _single_engine_reference(subs, max_batch=4)

    ekw = dict(_EKW, prefill_chunk_blocks=1)
    c = EngineCluster(_MODEL_SPEC, engine_kwargs=ekw,
                      workdir=str(tmp_path / "wd"), num_replicas=1,
                      heartbeat_ms=100, miss_threshold=20)
    try:
        for rid, prompt, opts in subs:
            c.submit(rid, prompt,
                     max_new_tokens=opts["max_new_tokens"],
                     temperature=opts.get("temperature", 0.0),
                     seed=opts.get("seed", 0),
                     priority=opts.get("priority", "normal"))
        deadline = time.monotonic() + 120
        while c.result("hi") is None:
            assert time.monotonic() < deadline, "hi never completed"
            c.poll()
            time.sleep(0.002)
        # the HIGH request finished while the LOW long request (which
        # was submitted before it) is still in flight
        assert c.result("long") is None
        c.serve(timeout_s=240)
        got = {rid: c.result(rid) for rid, _p, _o in subs}
        assert got == ref, (got, ref)
    finally:
        c.shutdown()


def _cluster_adapter_e2e_tcp(tmp_path):
    """Adapter-aware page shipping over the TcpRing data plane: tenant
    requests prefill through their adapter's weights on the prefill
    worker, ship namespaced pages, and the decode replica's admission
    prefix-hits the ADOPTED pages — asserted through the router-side
    cluster counter (`prefix_hit_tokens`, relayed as per-`done` deltas),
    the cross-host cache contract of docs/SERVING_CLUSTER.md.  Streams
    must match a single adapter engine's, and tenants must observably
    diverge from each other and from the base model."""
    from paddle_tpu.serving.cluster import cluster_stats, \
        reset_cluster_stats
    from paddle_tpu.serving.cluster_worker import _register_cluster_adapters

    subs = [("a1", P_G1, dict(max_new_tokens=8, adapter="tenant-a")),
            ("b1", P_G1, dict(max_new_tokens=8, adapter="tenant-b")),
            ("base", P_G1, dict(max_new_tokens=8))]
    ref_eng = GenerationEngine(make_model(),
                               **dict(_EKW, max_batch=4),
                               prefix_cache=True,
                               adapters={"rank": 4, "max_adapters": 2})
    _register_cluster_adapters(ref_eng, {"adapters": _ADAPTER_SPECS})
    for rid, prompt, opts in subs:
        ref_eng.add_request(rid, prompt, **opts)
    while ref_eng.has_work():
        ref_eng.step()
    ref = {rid: ref_eng.result(rid) for rid, _p, _o in subs}

    reset_cluster_stats()
    c = _mk_cluster(tmp_path / "wd", num_replicas=2, num_prefill=1,
                    adapters=_ADAPTER_SPECS, transport="tcp")
    try:
        with pytest.raises(KeyError, match="not a cluster adapter"):
            c.submit("x", P_G1, max_new_tokens=4, adapter="tenant-z")
        for rid, prompt, opts in subs:
            c.submit(rid, prompt,
                     max_new_tokens=opts["max_new_tokens"],
                     adapter=opts.get("adapter"))
        c.serve(timeout_s=240)
        got = {rid: c.result(rid) for rid, _p, _o in subs}
        assert got == ref, (got, ref)
        # tenancy is observable: each tenant's stream diverges
        assert got["a1"] != got["base"] and got["a1"] != got["b1"]
        st = cluster_stats()
        # THE acceptance counter: shipped namespaced pages were adopted
        # and prefix-HIT by the tenant admissions on the decode replicas
        # (P_G1 carries one full 8-token block per request)
        assert st["prefix_hit_tokens"] >= 8, st
        assert st["pages_shipped"] >= 3 and st["ship_bytes"] > 0
        # and the whole exchange genuinely rode the socket plane
        assert st["tcp_bytes"] > 0 and st["frames_sent"] > 0, st
    finally:
        c.shutdown()


# The e2e payloads fork real engine processes and kill them; each runs in
# tier-1 through the dedicated isolated worker for this module, and the
# pieces run as separate pytest cases for attribution.
def test_cluster_e2e_matches_single_engine(tmp_path):
    _cluster_e2e_matches_single_engine(tmp_path)


def test_cluster_adapter_tenants_prefix_hit_shipped_pages_tcp(tmp_path):
    _cluster_adapter_e2e_tcp(tmp_path)


def test_cluster_priority_completes_ahead_of_long_prefill(tmp_path):
    _cluster_priority_ahead_of_long(tmp_path)


def test_cluster_drain_scale_down_no_double_serve(tmp_path):
    _cluster_drain_scale_down(tmp_path)


def test_cluster_telemetry_schema_and_footer(tmp_path):
    _cluster_telemetry_footer(tmp_path)
