"""Tier-1 smoke of benchmarks/bench_schedule_search.py + regression-gate
wiring.

The --smoke twin must keep emitting the one-line JSON payload the driver
parses, with the deterministic decision set intact: the matmul chain's
searched schedule accepted with a >1x recorded win, the K-tiled twin
accepted through a genuinely contraction-split config (phase 2), the
softmax chain's schedule disabled by the measured-win gate, the decode
hot chain accepted for bf16 and disabled-persisted for int8, the 2-device
mesh engine adopting a fused decode-chain verdict (mesh_fused > 0) keyed
by (device kind, mesh shape) with streams bit-identical to the search-off
sharded twin, the K-tiled prefill-attention candidate accepted, the
disabled entries never re-measured on a cold reload, and the fused paths
matching XLA-only numerics.  Plus: the payload must flow through
tools/check_bench_regression.py (the CI bench gate), including the new
decode-chain section's win-to-win gate with disabled sides skipped
honestly.

The smoke subprocess dispatches GSPMD-partitioned decode programs over
the in-process multi-device XLA:CPU communicator (the intermittent
SIGSEGV class tools/run_tier1.py contains) — this module rides a
DEDICATED isolated worker (ISOLATED_DEFAULT), never a round-robin shard.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_smoke():
    env = dict(os.environ, PADDLE_TPU_BENCH_SMOKE="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "bench_schedule_search.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert out.returncode == 0, (out.stderr or out.stdout)[-800:]
    line = next(ln for ln in reversed(out.stdout.splitlines())
                if ln.startswith("{"))
    return json.loads(line)


def test_bench_schedule_search_smoke_decisions():
    payload = _run_smoke()
    assert payload["metric"] == "schedule_search_measured_win"
    assert payload["unit"] == "x"
    assert payload["value"] > 1.0  # best accepted schedule's recorded win
    assert payload["numerics_identical"] is True
    detail = payload["detail"]
    # the gate accepted a known-good tiling...
    mm = detail["matmul_chain"]
    assert mm["substituted"] == 1 and mm["fused_op"] == "sched_chain_4"
    assert mm["cache_entry"]["meta"]["win"] > 1.0
    assert "block_rows" in mm["cache_entry"]["config"]
    # ...the large-K twin only through a genuinely K-tiled schedule...
    kt = detail["ktiled_matmul"]
    assert kt["substituted"] == 1 and kt["fused_op"] == "sched_chain_3"
    assert 0 < kt["cache_entry"]["config"]["block_k"] < 256
    assert kt["cache_entry"]["meta"]["win"] > 1.0
    # ...and disabled the deliberately-bad one, persistently
    sm = detail["softmax_chain"]
    assert sm["substituted"] == 0
    assert sm["cache_entry"]["config"] == {"disabled": True}
    assert detail["disabled_persisted"] is True
    assert detail["never_refired"] is True
    # decode hot chain (phase 2): bf16 accepted, int8 disabled-persisted
    dec = detail["decode_chain"]
    assert dec["bf16"]["accepted"] and dec["bf16"]["win"] > 1.0
    assert dec["bf16"]["config"]["layout"] == "batch"
    assert not dec["int8"]["accepted"]
    assert dec["int8"]["disabled_persisted"] is True
    # schedule search over the mesh: the 2-device engine ADOPTED a fused
    # verdict, keyed by mesh shape, with streams matching the sharded twin
    mesh = dec["mesh"]
    assert mesh["mesh_fused"] >= 1 and mesh["mesh_skipped"] == 0
    assert mesh["streams_identical"] is True
    assert mesh["win"] > 1.0
    assert "mesh=mp2" in mesh["cache_key_mesh"]
    # the K-tiled prefill-attention candidate joined the same search
    pf = dec["prefill"]
    assert pf["accepted"] and pf["win"] > 1.0
    assert pf["config"]["block_q"] >= 2
    counters = detail["counters"]
    assert counters["accepted"] == 5 and counters["disabled"] == 2
    assert counters["measured"] > 0 and counters["disabled_hits"] >= 2
    assert counters["cache_hits"] >= 1  # accepted decode config re-served


def test_bench_payload_flows_through_regression_gate(tmp_path):
    """tools/check_bench_regression.py must parse the new bench JSON: same
    value -> ok (rc 0); a big drop -> REGRESSION (rc 1)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_bench_regression as gate
    finally:
        sys.path.pop(0)

    payload = {"metric": "schedule_search_measured_win", "value": 2.5,
               "unit": "x"}
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(payload))
    new.write_text(json.dumps(payload))
    assert gate.main([str(old), str(new)]) == 0
    new.write_text(json.dumps(dict(payload, value=1.0)))
    assert gate.main([str(old), str(new)]) == 1
    # an all-disabled run (value 0 — honest loss, e.g. CPU interpret mode)
    # is never counted as a regression
    new.write_text(json.dumps(dict(payload, value=0.0)))
    assert gate.main([str(old), str(new)]) == 0


def test_decode_chain_payload_gated(tmp_path):
    """The decode-chain section gates win-to-win per kv variant; a
    disabled side (win 0) skips that variant honestly instead of being
    recorded — or compared — as value=0."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_bench_regression as gate
    finally:
        sys.path.pop(0)

    def payload(**wins):
        return json.dumps({
            "metric": "schedule_search_measured_win", "value": 2.5,
            "unit": "x",
            "detail": {"decode_chain": {
                kv: {"win": w, "disabled_persisted": w == 0.0}
                for kv, w in wins.items()
            }},
        })

    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    # same wins -> ok (the loop is generic over variant names, so the
    # mesh and prefill variants ride the same gate)
    old.write_text(payload(bf16=1.8, int8=1.4, mesh=2.5))
    new.write_text(payload(bf16=1.8, int8=1.4, mesh=2.5))
    assert gate.main([str(old), str(new)]) == 0
    # one variant's win collapses beyond the threshold -> regression
    new.write_text(payload(bf16=1.8, int8=1.0, mesh=2.5))
    assert gate.main([str(old), str(new)]) == 1
    # the MESH variant's win collapsing regresses too
    new.write_text(payload(bf16=1.8, int8=1.4, mesh=1.0))
    assert gate.main([str(old), str(new)]) == 1
    # the variant going DISABLED (honest measured loss) skips, not fails
    new.write_text(payload(bf16=1.8, int8=0.0, mesh=2.5))
    assert gate.main([str(old), str(new)]) == 0
    # a side missing a variant entirely (pre-mesh round) skips it
    new.write_text(payload(bf16=1.8, int8=1.4))
    assert gate.main([str(old), str(new)]) == 0
    # both sides pre-phase-2 (no section) skip silently
    base = json.dumps({"metric": "schedule_search_measured_win",
                       "value": 2.5, "unit": "x"})
    old.write_text(base)
    new.write_text(base)
    assert gate.main([str(old), str(new)]) == 0
