"""Tier-1 smoke of benchmarks/bench_schedule_search.py + regression-gate
wiring.

The --smoke twin must keep emitting the one-line JSON payload the driver
parses, with the deterministic decision set intact: the matmul chain's
searched schedule accepted with a >1x recorded win, the softmax chain's
schedule disabled by the measured-win gate, the disabled entry persisted
in the per-device cache and never re-measured on a cold reload, and the
fused path matching XLA-only numerics.  Plus: the payload must flow
through tools/check_bench_regression.py (the CI bench gate).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_smoke():
    env = dict(os.environ, PADDLE_TPU_BENCH_SMOKE="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "bench_schedule_search.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert out.returncode == 0, (out.stderr or out.stdout)[-800:]
    line = next(ln for ln in reversed(out.stdout.splitlines())
                if ln.startswith("{"))
    return json.loads(line)


def test_bench_schedule_search_smoke_decisions():
    payload = _run_smoke()
    assert payload["metric"] == "schedule_search_measured_win"
    assert payload["unit"] == "x"
    assert payload["value"] > 1.0  # accepted schedule's recorded win
    assert payload["numerics_identical"] is True
    detail = payload["detail"]
    # the gate accepted a known-good tiling...
    mm = detail["matmul_chain"]
    assert mm["substituted"] == 1 and mm["fused_op"] == "sched_chain_4"
    assert mm["cache_entry"]["meta"]["win"] > 1.0
    assert "block_rows" in mm["cache_entry"]["config"]
    # ...and disabled the deliberately-bad one, persistently
    sm = detail["softmax_chain"]
    assert sm["substituted"] == 0
    assert sm["cache_entry"]["config"] == {"disabled": True}
    assert detail["disabled_persisted"] is True
    assert detail["never_refired"] is True
    counters = detail["counters"]
    assert counters["accepted"] == 1 and counters["disabled"] == 1
    assert counters["measured"] > 0 and counters["disabled_hits"] >= 1


def test_bench_payload_flows_through_regression_gate(tmp_path):
    """tools/check_bench_regression.py must parse the new bench JSON: same
    value -> ok (rc 0); a big drop -> REGRESSION (rc 1)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_bench_regression as gate
    finally:
        sys.path.pop(0)

    payload = {"metric": "schedule_search_measured_win", "value": 2.5,
               "unit": "x"}
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(payload))
    new.write_text(json.dumps(payload))
    assert gate.main([str(old), str(new)]) == 0
    new.write_text(json.dumps(dict(payload, value=1.0)))
    assert gate.main([str(old), str(new)]) == 1
    # an all-disabled run (value 0 — honest loss, e.g. CPU interpret mode)
    # is never counted as a regression
    new.write_text(json.dumps(dict(payload, value=0.0)))
    assert gate.main([str(old), str(new)]) == 0
