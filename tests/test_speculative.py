"""Speculative decoding (draft-and-verify greedy; serving tier) and the
chunked multi-token-on-cache attention path it rides on."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, _model_forward_cached, llama_tiny


def _model(seed):
    paddle.seed(seed)
    m = LlamaForCausalLM(llama_tiny(dtype="float32"))
    m.eval()
    return m


def _prompt():
    return paddle.to_tensor(
        np.random.default_rng(0).integers(0, 1024, (1, 9)).astype(np.int32))


def test_chunked_prefill_matches_full_prefill():
    """Feeding the prompt in two chunks over a growing cache must produce
    the same final hidden state as one full prefill (the bottom-right
    cross-length attention path; previously raised NotImplementedError)."""
    m = _model(0)
    ids = _prompt()
    empty = [
        (paddle.zeros([1, 0, m.config.num_key_value_heads,
                       m.config.hidden_size // m.config.num_attention_heads]),
         paddle.zeros([1, 0, m.config.num_key_value_heads,
                       m.config.hidden_size // m.config.num_attention_heads]))
        for _ in range(m.config.num_hidden_layers)
    ]
    h_full, _ = _model_forward_cached(m.model, ids, empty, 0)

    a = paddle.to_tensor(np.asarray(ids._value)[:, :4])
    b = paddle.to_tensor(np.asarray(ids._value)[:, 4:])
    _, caches = _model_forward_cached(m.model, a, empty, 0)
    h_b, _ = _model_forward_cached(m.model, b, caches, 4)
    np.testing.assert_allclose(
        np.asarray(h_b._value)[:, -1], np.asarray(h_full._value)[:, -1],
        rtol=2e-5, atol=2e-6)


def test_self_speculation_is_exact_and_saves_target_forwards():
    """Draft == target: every proposal accepted, output EXACTLY the plain
    greedy decode, target forwards ~ N/(K+1)."""
    m = _model(1)
    ids = _prompt()
    ref = np.asarray(m.generate(ids, max_new_tokens=12, cache="naive")._value)
    out = np.asarray(m.generate(ids, max_new_tokens=12, draft_model=m,
                                num_speculative_tokens=3)._value)
    np.testing.assert_array_equal(out, ref)
    st = m._spec_stats
    assert st["accepted"] == st["proposed"], st  # self-draft never rejected
    # 12 tokens at K=3: prefill + ceil(11/4) = 4 verify forwards
    assert st["target_forwards"] == 1 + -(-11 // 4), st


def test_cross_model_speculation_matches_plain_greedy():
    """An UNRELATED draft still yields exactly the target's greedy output
    — acceptance only changes the step count, never the tokens."""
    target, draft = _model(2), _model(3)
    ids = _prompt()
    ref = np.asarray(target.generate(ids, max_new_tokens=10,
                                     cache="naive")._value)
    out = np.asarray(target.generate(ids, max_new_tokens=10,
                                     draft_model=draft,
                                     num_speculative_tokens=4)._value)
    np.testing.assert_array_equal(out, ref)
    st = target._spec_stats
    assert st["proposed"] >= st["accepted"] >= 0


def test_speculative_rejects_sampling_and_batch():
    m = _model(4)
    with pytest.raises(ValueError, match="greedy-only"):
        m.generate(_prompt(), draft_model=m, do_sample=True)
    two = paddle.to_tensor(np.zeros((2, 4), np.int32))
    with pytest.raises(ValueError, match="batch size 1"):
        m.generate(two, draft_model=m)


def test_paged_chunk_layer_matches_single_token_steps():
    """A T-token chunk through _decode_layer_paged_chunk must equal T
    successive single-token _decode_layer_paged steps (same pools, same
    tables) — the primitive under engine speculative verify."""
    import jax.numpy as jnp

    from paddle_tpu.models.llama import (
        _decode_layer_paged,
        _decode_layer_paged_chunk,
    )
    from paddle_tpu.ops import paged_attention as pa

    m = _model(7)
    layer = m.model.layers[0]
    cos, sin = m.model.rope_cos._value, m.model.rope_sin._value
    nkv = m.config.num_key_value_heads
    hd = m.config.hidden_size // m.config.num_attention_heads
    B, T, bs = 2, 3, 4
    kc, vc = pa.alloc_paged_cache(8, nkv, bs, hd, jnp.float32)
    kc2, vc2 = kc, vc
    tables = jnp.asarray(np.arange(8, dtype=np.int32).reshape(B, 4))
    rng = np.random.default_rng(7)
    hs = paddle.to_tensor(rng.standard_normal(
        (B, T, m.config.hidden_size)).astype("float32"))
    # warm the pools with 2 pre-existing positions per sequence
    pre = paddle.to_tensor(rng.standard_normal(
        (B, 1, m.config.hidden_size)).astype("float32"))
    for j in range(2):
        _, kc, vc = _decode_layer_paged(layer, pre, cos, sin, kc, vc,
                                        tables, jnp.full((B,), j + 1, jnp.int32))
        _, kc2, vc2 = _decode_layer_paged(layer, pre, cos, sin, kc2, vc2,
                                          tables, jnp.full((B,), j + 1, jnp.int32))
    # path A: chunk
    hA, kcA, vcA = _decode_layer_paged_chunk(
        layer, hs, cos, sin, kc, vc, tables, jnp.full((B,), 2 + T, jnp.int32))
    # path B: token by token
    outs = []
    for j in range(T):
        hj, kc2, vc2 = _decode_layer_paged(
            layer, paddle.to_tensor(np.asarray(hs._value)[:, j:j + 1]),
            cos, sin, kc2, vc2, tables, jnp.full((B,), 3 + j, jnp.int32))
        outs.append(np.asarray(hj._value))
    np.testing.assert_allclose(np.asarray(hA._value),
                               np.concatenate(outs, 1), rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(kcA), np.asarray(kc2),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(vcA), np.asarray(vc2),
                               rtol=1e-6, atol=1e-7)
