"""Speculative decoding (draft-and-verify greedy; serving tier) and the
chunked multi-token-on-cache attention path it rides on."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, _model_forward_cached, llama_tiny


def _model(seed):
    paddle.seed(seed)
    m = LlamaForCausalLM(llama_tiny(dtype="float32"))
    m.eval()
    return m


def _prompt():
    return paddle.to_tensor(
        np.random.default_rng(0).integers(0, 1024, (1, 9)).astype(np.int32))


def test_chunked_prefill_matches_full_prefill():
    """Feeding the prompt in two chunks over a growing cache must produce
    the same final hidden state as one full prefill (the bottom-right
    cross-length attention path; previously raised NotImplementedError)."""
    m = _model(0)
    ids = _prompt()
    empty = [
        (paddle.zeros([1, 0, m.config.num_key_value_heads,
                       m.config.hidden_size // m.config.num_attention_heads]),
         paddle.zeros([1, 0, m.config.num_key_value_heads,
                       m.config.hidden_size // m.config.num_attention_heads]))
        for _ in range(m.config.num_hidden_layers)
    ]
    h_full, _ = _model_forward_cached(m.model, ids, empty, 0)

    a = paddle.to_tensor(np.asarray(ids._value)[:, :4])
    b = paddle.to_tensor(np.asarray(ids._value)[:, 4:])
    _, caches = _model_forward_cached(m.model, a, empty, 0)
    h_b, _ = _model_forward_cached(m.model, b, caches, 4)
    np.testing.assert_allclose(
        np.asarray(h_b._value)[:, -1], np.asarray(h_full._value)[:, -1],
        rtol=2e-5, atol=2e-6)


def test_self_speculation_is_exact_and_saves_target_forwards():
    """Draft == target: every proposal accepted, output EXACTLY the plain
    greedy decode, target forwards ~ N/(K+1)."""
    m = _model(1)
    ids = _prompt()
    ref = np.asarray(m.generate(ids, max_new_tokens=12, cache="naive")._value)
    out = np.asarray(m.generate(ids, max_new_tokens=12, draft_model=m,
                                num_speculative_tokens=3)._value)
    np.testing.assert_array_equal(out, ref)
    st = m._spec_stats
    assert st["accepted"] == st["proposed"], st  # self-draft never rejected
    # 12 tokens at K=3: prefill + ceil(11/4) = 4 verify forwards
    assert st["target_forwards"] == 1 + -(-11 // 4), st


def test_cross_model_speculation_matches_plain_greedy():
    """An UNRELATED draft still yields exactly the target's greedy output
    — acceptance only changes the step count, never the tokens."""
    target, draft = _model(2), _model(3)
    ids = _prompt()
    ref = np.asarray(target.generate(ids, max_new_tokens=10,
                                     cache="naive")._value)
    out = np.asarray(target.generate(ids, max_new_tokens=10,
                                     draft_model=draft,
                                     num_speculative_tokens=4)._value)
    np.testing.assert_array_equal(out, ref)
    st = target._spec_stats
    assert st["proposed"] >= st["accepted"] >= 0


def test_speculative_rejects_sampling_and_batch():
    m = _model(4)
    with pytest.raises(ValueError, match="greedy-only"):
        m.generate(_prompt(), draft_model=m, do_sample=True)
    two = paddle.to_tensor(np.zeros((2, 4), np.int32))
    with pytest.raises(ValueError, match="batch size 1"):
        m.generate(two, draft_model=m)
