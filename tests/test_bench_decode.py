"""Tier-1 smoke of benchmarks/bench_decode.py.

Like test_bench_compile / test_bench_dispatch: the macro-step decode
benchmark must keep emitting the one-line JSON payload the driver parses,
and its built-in greedy-parity gate (chunked macro-step == per-token token
streams, bit for bit) must hold — so the chunked decode path can't bitrot
unexercised between measured rounds.
"""

import json
import os
import subprocess
import sys


def test_bench_decode_smoke_emits_valid_json():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PADDLE_TPU_BENCH_SMOKE="1",
               PADDLE_TPU_BENCH_CPU="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "benchmarks", "bench_decode.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo)
    assert out.returncode == 0, (out.stderr or out.stdout)[-800:]
    line = next(ln for ln in reversed(out.stdout.splitlines()) if ln.startswith("{"))
    payload = json.loads(line)
    assert payload["metric"] == "serving_decode_chunked_speedup"
    assert payload["unit"] == "x"
    assert payload["value"] > 0
    assert "vs_baseline" in payload
    # the acceptance direction: chunked streams must equal per-token ones
    assert payload["tokens_match"] is True
    detail = payload["detail"]
    assert detail["chunk"] > 1
    assert detail["per_token_tokens_per_sec"] > 0
    assert detail["chunked_tokens_per_sec"] > 0
    # depth sweep ran under the LayerStack scan and stayed depth-constant-ish
    sweep = detail["depth_sweep"]
    assert sweep["scan_layers"] is True
    assert sweep["deep_layers"] > sweep["shallow_layers"]
    assert sweep["shallow_first_step_s"] > 0 and sweep["deep_first_step_s"] > 0
    # macro-stepping really amortized dispatches: tokens >> dispatches
    st = detail["decode_stats"]
    assert st["tokens"] > st["dispatches"]
    # shared-prefix workload: cache-on streams equal cache-off streams,
    # prefill really was avoided, and the latency percentiles are sane
    sp = detail["shared_prefix"]
    assert sp["tokens_match"] is True
    assert sp["prefill_avoided_tokens"] > 0
    assert sp["prefix_speedup"] > 0
    for side in ("off", "on"):
        assert sp[side]["latency_p95_ms"] >= sp[side]["latency_p50_ms"] > 0
    # int8 capacity: at identical pool-block bytes the quantized pool
    # admits >= 1.8x the resident requests (allocator arithmetic)
    cap = detail["int8_kv_capacity"]
    assert cap["int8_resident_requests"] >= 1.8 * cap["bf16_resident_requests"]
    # SLO load section: percentile keys exist and are ORDERED
    # (p50 <= p95 <= p99) for TTFT and inter-token latency, and the
    # TP-sharded twin (2 virtual CPU devices) emitted bit-identical
    # greedy streams
    slo = detail["slo"]
    assert slo["requests"] == 2 * slo["max_batch"]  # oversubscribed
    assert slo["tp_tokens_match"] is True
    assert slo["tp"] is not None
    for side in ("single", "tp"):
        for section in ("ttft_ms", "itl_ms"):
            pcts = slo[side][section]
            assert 0 < pcts["p50"] <= pcts["p95"] <= pcts["p99"]
    # snapshot/restore section: a live mid-flight engine snapshotted and
    # restored with bit-identical continued streams, timings positive
    # (check_bench_regression's snapshot gate consumes these)
    snap = detail["snapshot"]
    assert snap["resume_tokens_match"] is True
    assert snap["save_ms"] > 0 and snap["restore_ms"] > 0
    assert snap["bytes"] > 0
    # overload discipline: the long prompt really was chunk-interleaved
    # (>= 2 prefill chunks), ALL streams bit-identical chunked vs atomic,
    # and the preemption sub-scenario parked + re-admitted the LOW stream
    # with a token-for-token resume (check_bench_regression's overload
    # gate consumes the p99 ITL numbers)
    ov = detail["overload"]
    assert ov["streams_identical"] is True
    assert ov["prefill_chunks"] >= 2
    assert ov["preemptions"] >= 1 and ov["preempt_readmits"] >= 1
    assert ov["preempted_stream_identical"] is True
    assert ov["itl_p99_ms_chunked"] > 0 and ov["itl_p99_ms_atomic"] > 0
    assert ov["tokens_per_sec_chunked"] > 0 and ov["tokens_per_sec_atomic"] > 0
