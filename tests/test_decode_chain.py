"""Schedule search, phase 2: the decode hot chain (ops/decode_chain.py +
serving adoption; docs/SCHEDULE_SEARCH.md).

The contract under test: the serving macro-step's per-token chain — paged
gather → dequant → sdpa core → running-max quant-write — is a searchable
subgraph.  Candidates must pass a numerics PARITY gate vs the unfused XLA
twin before they may even be measured (bf16 bit-exact, int8 pools
bit-exact + attention inside the PR-6 drift budget); accepted verdicts
persist per device kind under schedule/decode_* and serve cold reloads
with ZERO re-measurement; an engine whose verdict is accepted emits token
streams BIT-IDENTICAL to the unfused engine; mixed-dtype QuantPool
chains are costed per-leaf by the roofline (int8 payload bytes + f32
scale bytes, never one dtype for the whole subgraph).  Measurement is
injected through schedule_search.measure_override so every decision here
is deterministic on CPU; the real path is exercised by the bench when
the tunnel is up.
"""

import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops import autotune as at
from paddle_tpu.ops import decode_chain as dc
from paddle_tpu.static import schedule_search as ss
from paddle_tpu import serving


@pytest.fixture()
def tmp_cache(tmp_path):
    """Fresh autotune cache under a tmp dir + zeroed search counters."""
    paddle.set_flags({"FLAGS_autotune_cache_dir": str(tmp_path)})
    at._CACHES.clear()
    ss.reset_schedule_search_stats()
    serving.reset_schedule_decode_stats()
    yield tmp_path
    paddle.set_flags({"FLAGS_autotune_cache_dir": ""})
    at._CACHES.clear()
    ss.reset_schedule_search_stats()
    serving.reset_schedule_decode_stats()


def _spec(kv="bf16", **kw):
    base = dict(batch=2, num_heads=4, num_kv_heads=2, head_dim=8,
                block_size=4, max_blocks=2, num_blocks=8, kv=kv,
                dtype=np.float32)
    base.update(kw)
    return dc.DecodeChainSpec(**base)


def _win(fn, args, *, label, config):
    return 0.4 if config is not None else 1.0


def _lose(fn, args, *, label, config):
    return 4.0 if config is not None else 1.0


# ------------------------------------------------------------ spec tier


def test_candidate_space_by_kv_kind():
    """bf16 chains enumerate the bit-exact 'batch' layout only; int8
    chains add the tolerance-gated 'rows' layout; loop-gather unrolls
    divide the table width."""
    bf16 = _spec("bf16").enumerate_configs()
    assert {c["layout"] for c in bf16} == {"batch"}
    int8 = _spec("int8").enumerate_configs()
    assert {c["layout"] for c in int8} == {"batch", "rows"}
    for c in bf16 + int8:
        if c["gather"] == "loop":
            assert 2 % c["unroll"] == 0  # max_blocks == 2
    # rows layout never builds for bf16 — the einsum re-association
    # would break the bit-exact contract
    with pytest.raises(ValueError):
        _spec("bf16").build({"layout": "rows", "gather": "take"})


def test_mixed_dtype_roofline_bytes_hand_computed():
    """The satellite fix: QuantPool chains cost int8 payload bytes AND
    f32 scale bytes per leaf.  Hand-computed for B=2 N=4 Nkv=2 H=8 bs=4
    W=2 NB=8 f32 compute dtype:

      int8 pools:  payload 8*2*4*8*1 = 512 B, scales 8*2*4 = 64 B
                   reads  = 2*(512+64)        = 1152
                   writes = 2*(2*2*4*8 + 2*2*4) = 288  (touched blocks
                            rewritten by the running-max rescale + scales)
      f32 pools:   payload 8*2*4*8*4 = 2048 B -> reads 4096
                   writes = 2*(2*2*8*4) = 256  (one token slot per row)
      both:        q 256 + k_new/v_new 256 + tables 16 + lens 8 + out 256
    """
    fixed = 256 + 256 + 16 + 8 + 256
    cfg = {"layout": "batch", "gather": "take"}
    assert _spec("int8").traffic_bytes(cfg) == 1152 + 288 + fixed
    assert _spec("bf16").traffic_bytes(cfg) == 4096 + 256 + fixed
    # the 'rows' layout re-stages the pool leaves once per batch row
    rows_cfg = {"layout": "rows", "gather": "take"}
    assert (_spec("int8").traffic_bytes(rows_cfg)
            == 2 * 1152 + 288 + fixed)
    # per-leaf honesty is what makes the int8 gather traffic ~a quarter
    # of the f32 twin's instead of "one dtype for the whole subgraph"
    assert _spec("int8").traffic_bytes(cfg) < _spec("bf16").traffic_bytes(cfg)


@pytest.mark.parametrize("kv", ["bf16", "int8"])
def test_all_candidates_parity_vs_unfused_twin(kv):
    """Every candidate passes the parity gate: pools bit-exact for both
    kinds, attention bit-exact for bf16 (whole-batch replay of the exact
    unfused ops) and drift-bounded for int8's per-row layout."""
    spec = _spec(kv)
    args = spec.synthetic_args()
    ref = jax.jit(spec.reference())(*args)
    for cfg in spec.enumerate_configs():
        fn = jax.jit(spec.build(cfg))
        assert spec.parity_ok(fn, args, ref), cfg
        if kv == "bf16":
            # the batch layout's contract is BIT-exactness, leaf for leaf
            got = fn(*args)
            for r, g in zip(jax.tree_util.tree_leaves(ref),
                            jax.tree_util.tree_leaves(got)):
                assert bool((r == g).all()), cfg


def test_parity_gate_blocks_wrong_candidates(tmp_cache):
    """A candidate whose numerics differ must never be measured, however
    fast: the gate rejects it before the stopwatch starts."""
    spec = _spec("bf16")

    class LyingSpec(dc.DecodeChainSpec):
        def build(self, config):
            inner = dc.DecodeChainSpec.build(self, config)

            def wrong(*args):
                o, kc, vc = inner(*args)
                return o + 1e-3, kc, vc  # fast and wrong

            return wrong

    lying = LyingSpec(**spec.__dict__)
    calls = []

    def counting(fn, args, *, label, config):
        if config is not None:
            calls.append(config)
        return 0.1

    with ss.measure_override(counting):
        decision = ss.ScheduleSearcher(budget=3).search(lying)
    assert calls == []  # nothing measured
    assert not decision.accepted
    assert ss.schedule_search_stats()["pruned_parity"] > 0


def test_search_persists_and_cold_reload_never_remeasures(tmp_cache):
    """Accepted AND disabled decode verdicts persist under the
    schedule/decode_* namespaces; a cold reload serves both with zero
    re-measurement (the accepted config still parity-re-gates — a cache
    file is trusted about speed, never numerics)."""
    with ss.measure_override(_win):
        d1 = dc.ensure_decision(_spec("bf16"))
    with ss.measure_override(_lose):
        d2 = dc.ensure_decision(_spec("int8"))
    assert d1.status == "accepted" and d1.win > 1.0
    assert d2.status == "disabled"
    raw = json.load(open(os.path.join(
        str(tmp_cache), at.device_kind_slug() + ".json")))
    (entry,) = raw["schedule/decode_bf16"].values()
    assert entry["meta"]["win"] > 1.0
    assert entry["config"]["layout"] == "batch"
    (dentry,) = raw["schedule/decode_int8"].values()
    assert dentry["config"] == {"disabled": True}

    at._CACHES.clear()
    calls = []

    def counting(fn, args, *, label, config):
        calls.append(config)
        return 1.0

    with ss.measure_override(counting):
        d3 = dc.ensure_decision(_spec("bf16"))
        d4 = dc.ensure_decision(_spec("int8"))
    assert calls == []
    assert d3.status == "cache" and d3.config == entry["config"]
    assert d4.status == "cache_disabled"
    assert ss.schedule_search_stats()["disabled_hits"] >= 1


def test_chunk_paths_refuse_chain_cfg():
    """The fused chain covers the single-token step only: the chunked /
    speculative-verify path must refuse a config loudly, never silently
    ignore it."""
    from paddle_tpu.models.llama import _decode_layers_paged

    with pytest.raises(ValueError, match="single-token"):
        _decode_layers_paged(None, None, None, None, [], [], None, None,
                             chunk=True,
                             chain_cfg={"layout": "batch",
                                        "gather": "take"})


# ------------------------------------------------------------ engine tier


def _model(seed=41):
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(seed)
    m = LlamaForCausalLM(llama_tiny(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, dtype="float32"))
    m.eval()
    return m


def _workload(eng):
    """Greedy + mid-flight seeded-sampling join — the stream shape every
    fused-vs-unfused comparison replays identically."""
    eng.add_request("g", [5, 9, 17, 33, 2], max_new_tokens=8)
    eng.step()
    eng.add_request("s", [7, 11, 3], max_new_tokens=6, temperature=3.0,
                    seed=42)
    while eng.has_work():
        eng.step()
    return {"g": eng.result("g"), "s": eng.result("s")}


def _engine(kv="bf16"):
    from paddle_tpu.serving import GenerationEngine

    return GenerationEngine(_model(), max_batch=2, block_size=8,
                            num_blocks=16, kv_cache_dtype=kv)


@pytest.fixture()
def sched_flags(tmp_cache):
    yield tmp_cache
    paddle.set_flags({"FLAGS_schedule_search": False})


@pytest.mark.parametrize("kv", ["bf16", "int8"])
def test_engine_fused_streams_match_unfused(sched_flags, kv):
    """The acceptance crux: a macro-step that adopted an accepted fused
    decode-chain config emits token streams BIT-IDENTICAL to the unfused
    engine — greedy and seeded sampling, bf16 and int8 pools (the int8
    winner is the bit-exact batch layout; even its drift budget goes
    unspent)."""
    ref = _workload(_engine(kv))
    paddle.set_flags({"FLAGS_schedule_search": True})
    with ss.measure_override(_win):
        eng = _engine(kv)
        got = _workload(eng)
    assert got == ref
    stats = serving.schedule_decode_stats()
    assert stats["decode_chains_found"] == 1
    assert stats["decode_chains_accepted"] == 1
    assert stats["decode_chains_mesh_skipped"] == 0
    # the verdict persisted under this engine's geometry
    raw = json.load(open(os.path.join(
        str(sched_flags), at.device_kind_slug() + ".json")))
    assert f"schedule/decode_{kv}" in raw


def test_engine_disabled_verdict_keeps_unfused_path(sched_flags):
    """A measured loss keeps the unfused ops and counts as disabled —
    streams unchanged, nothing faked."""
    ref = _workload(_engine())
    paddle.set_flags({"FLAGS_schedule_search": True})
    with ss.measure_override(_lose):
        got = _workload(_engine())
    assert got == ref
    stats = serving.schedule_decode_stats()
    assert stats["decode_chains_found"] == 1
    assert stats["decode_chains_accepted"] == 0
    assert stats["decode_chains_disabled"] == 1


def test_engine_cold_reload_serves_with_zero_remeasures(sched_flags):
    """The satellite proof: after one engine's accepted verdict persists,
    a cold process (fresh cache objects, fresh engine) serves the fused
    step with ZERO measure calls — and the streams still match."""
    ref = _workload(_engine())
    paddle.set_flags({"FLAGS_schedule_search": True})
    with ss.measure_override(_win):
        _workload(_engine())
    # "new process": drop the in-memory cache objects and counters
    at._CACHES.clear()
    serving.reset_schedule_decode_stats()
    ss.reset_schedule_search_stats()
    calls = []

    def counting(fn, args, *, label, config):
        calls.append(config)
        return 1.0

    with ss.measure_override(counting):
        got = _workload(_engine())
    assert calls == []
    assert got == ref
    stats = serving.schedule_decode_stats()
    assert stats["decode_chains_accepted"] == 1
    assert ss.schedule_search_stats()["cache_hits"] >= 1


def test_flag_change_rearms_engine_verdict(sched_flags):
    """set_flags invalidates the compiled steps AND the decode-chain
    verdict together: flipping the search off mid-life re-resolves to the
    unfused path at the next step."""
    from paddle_tpu.serving import GenerationEngine

    paddle.set_flags({"FLAGS_schedule_search": True})
    with ss.measure_override(_win):
        eng = GenerationEngine(_model(), max_batch=2, block_size=8,
                               num_blocks=16, decode_chunk=2)
        eng.add_request("a", [5, 9, 17], max_new_tokens=6)
        eng.step()
        assert eng._decode_chain_cfg is not None  # adopted
        paddle.set_flags({"FLAGS_schedule_search": False})
        assert eng._decode_chain_cfg is serving._CHAIN_UNSET
        while eng.has_work():
            eng.step()
        assert eng._decode_chain_cfg is None  # re-resolved: unfused
    assert len(eng.result("a")) == 6


def test_engine_prefill_chain_adopted_streams_match(sched_flags):
    """Long-prompt pours stop being a pure XLA chain: an engine with a
    fixed prefill_chunk searches the fused prefill-attention candidate at
    the canonical chunk geometry, and an adoption runs every divisible
    chunk's attention core as one Pallas dispatch — with the poured
    stream BIT-IDENTICAL to the search-off engine.  A measured loss
    keeps the XLA pour and counts as disabled, streams unchanged."""
    from paddle_tpu.serving import GenerationEngine

    def run():
        eng = GenerationEngine(_model(), max_batch=2, block_size=8,
                               num_blocks=16, prefill_chunk=4)
        eng.add_request("p", list(range(1, 21)), max_new_tokens=6)
        while eng.has_work():
            eng.step()
        return eng.result("p")

    ref = run()
    paddle.set_flags({"FLAGS_schedule_search": True})
    with ss.measure_override(_win):
        got = run()
    assert got == ref
    stats = serving.schedule_decode_stats()
    assert stats["prefill_chains_found"] == 1
    assert stats["prefill_chains_accepted"] == 1
    # the measured-loss twin: honest disable, same stream
    serving.reset_schedule_decode_stats()
    at._CACHES.clear()
    paddle.set_flags({"FLAGS_autotune_cache_dir":
                      str(sched_flags / "lose")})
    with ss.measure_override(_lose):
        got2 = run()
    assert got2 == ref
    stats = serving.schedule_decode_stats()
    assert stats["prefill_chains_disabled"] == 1
    assert stats["prefill_chains_accepted"] == 0


def test_profiler_merges_decode_counters_and_footer(sched_flags):
    paddle.set_flags({"FLAGS_schedule_search": True})
    with ss.measure_override(_win):
        _workload(_engine())
    from paddle_tpu import profiler

    stats = profiler.schedule_search_stats()
    assert stats["decode_chains_found"] == 1
    assert stats["decode_chains_accepted"] == 1
    assert stats["subgraphs_found"] >= 1  # search-tier keys still merged
    p = profiler.Profiler(timer_only=True)
    p.start()
    p.stop()
    text = p.summary()
    assert "Schedule search:" in text
    assert "Decode chains: found=1 accepted=1" in text
