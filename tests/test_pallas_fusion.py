"""Pattern-rewrite infra + Pallas fusion pass (VERDICT r2 items 4+5).

Reference: paddle/pir/pattern_rewrite/pattern_match.h (greedy rewrite
driver) + paddle/fluid/pir/transforms/build_cinn_pass.cc (fusible-subgraph
substitution).  Here: a captured vanilla-jnp attention / rms-norm / swiglu
subgraph gets the Pallas kernel substituted, numerics preserved, via the
Executor's default pipeline.
"""

import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.static as static
from paddle_tpu.static.program import Program, program_guard
from paddle_tpu.static.rewrite import PallasFusionPass


def _feed(prog, name, shape, dtype=np.float32):
    return prog.add_feed(prog.new_var(jax.ShapeDtypeStruct(shape, dtype), name))


def _capture_vanilla(B=2, N=4, S=128, D=16, H=32, F_=64):
    """One program holding vanilla attention + rms-norm + swiglu."""
    prog = Program()
    with program_guard(prog):
        q = _feed(prog, "q", (B, N, S, D))
        k = _feed(prog, "k", (B, N, S, D))
        v = _feed(prog, "v", (B, N, S, D))
        x = _feed(prog, "x", (B, S, H))
        w = _feed(prog, "w", (H,))
        g = _feed(prog, "g", (B, S, F_))
        u = _feed(prog, "u", (B, S, F_))
        scores = paddle.matmul(q, k, transpose_y=True) / (D ** 0.5)
        probs = F.softmax(scores, axis=-1)
        attn = paddle.matmul(probs, v)
        var = (x * x).mean(axis=-1, keepdim=True)
        normed = x * paddle.rsqrt(var + 1e-6) * w
        sw = F.silu(g) * u
    return prog, (attn, normed, sw)


def _optypes(prog):
    return [op.type for op in prog.global_block().ops]


def test_fusion_pass_substitutes_all_three_patterns():
    prog, (attn, normed, sw) = _capture_vanilla()
    n = PallasFusionPass([attn._vid, normed._vid, sw._vid]).apply(prog)
    assert n == 3
    types = _optypes(prog)
    assert "flash_attention" in types
    assert "fused_rms_norm" in types
    assert "swiglu" in types
    assert "softmax" not in [
        op.type
        for op in prog.global_block().ops
        if any(vid in (attn._vid,) for vid in op.out_vids)
    ]


def test_fusion_preserves_numerics_via_executor():
    rng = np.random.default_rng(0)
    B, N, S, D, H, F_ = 2, 4, 128, 16, 32, 64
    feed = {
        "q": rng.normal(size=(B, N, S, D)).astype(np.float32),
        "k": rng.normal(size=(B, N, S, D)).astype(np.float32),
        "v": rng.normal(size=(B, N, S, D)).astype(np.float32),
        "x": rng.normal(size=(B, S, H)).astype(np.float32),
        "w": rng.normal(size=(H,)).astype(np.float32),
        "g": rng.normal(size=(B, S, F_)).astype(np.float32),
        "u": rng.normal(size=(B, S, F_)).astype(np.float32),
    }

    paddle.set_flags({"FLAGS_use_pallas_fusion": False})
    try:
        prog, fetches = _capture_vanilla()
        exe = static.Executor()
        ref = exe.run(prog, feed=feed, fetch_list=list(fetches))
        assert "flash_attention" not in _optypes(prog)

        paddle.set_flags({"FLAGS_use_pallas_fusion": True})
        prog2, fetches2 = _capture_vanilla()
        exe2 = static.Executor()
        got = exe2.run(prog2, feed=feed, fetch_list=list(fetches2))
        assert "flash_attention" in _optypes(prog2)  # pass ran inside run()
        assert "fused_rms_norm" in _optypes(prog2)
        assert "swiglu" in _optypes(prog2)
    finally:
        paddle.set_flags({"FLAGS_use_pallas_fusion": True})

    for r, g_ in zip(ref, got):
        np.testing.assert_allclose(r, g_, rtol=2e-3, atol=2e-3)


def test_fusion_bails_when_intermediate_is_fetched():
    """Fetching attention probs keeps the pattern unfused (externally
    visible intermediates make substitution unsound)."""
    prog = Program()
    with program_guard(prog):
        q = _feed(prog, "q", (2, 4, 128, 16))
        k = _feed(prog, "k", (2, 4, 128, 16))
        v = _feed(prog, "v", (2, 4, 128, 16))
        scores = paddle.matmul(q, k, transpose_y=True) / 4.0
        probs = F.softmax(scores, axis=-1)
        out = paddle.matmul(probs, v)
    n = PallasFusionPass([out._vid, probs._vid]).apply(prog)
    assert n == 0
    assert "flash_attention" not in _optypes(prog)


def test_fusion_handles_untransposed_k_layout():
    prog = Program()
    with program_guard(prog):
        q = _feed(prog, "q", (2, 2, 128, 16))
        kT = _feed(prog, "kT", (2, 2, 16, 128))  # [B,N,D,S]: plain matmul
        v = _feed(prog, "v", (2, 2, 128, 16))
        probs = F.softmax(paddle.matmul(q, kT) * (1 / 4.0), axis=-1)
        out = paddle.matmul(probs, v)
    n = PallasFusionPass([out._vid]).apply(prog)
    assert n == 1

    rng = np.random.default_rng(1)
    qv = rng.normal(size=(2, 2, 128, 16)).astype(np.float32)
    kv = rng.normal(size=(2, 2, 16, 128)).astype(np.float32)
    vv = rng.normal(size=(2, 2, 128, 16)).astype(np.float32)
    exe = static.Executor()
    got = exe.run(prog, feed={"q": qv, "kT": kv, "v": vv}, fetch_list=[out])[0]
    s = qv @ kv / 4.0
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, p @ vv, rtol=2e-3, atol=2e-3)


class VanillaLlamaBlock(paddle.nn.Layer):
    """A LLaMA decoder block written in VANILLA paddle ops only — no calls
    into paddle_tpu.ops — so fusion must come from the rewrite pass."""

    def __init__(self, hidden, heads, inter):
        super().__init__()
        self.h, self.n = hidden, heads
        self.d = hidden // heads
        self.wq = paddle.nn.Linear(hidden, hidden, bias_attr=False)
        self.wk = paddle.nn.Linear(hidden, hidden, bias_attr=False)
        self.wv = paddle.nn.Linear(hidden, hidden, bias_attr=False)
        self.wo = paddle.nn.Linear(hidden, hidden, bias_attr=False)
        self.gate = paddle.nn.Linear(hidden, inter, bias_attr=False)
        self.up = paddle.nn.Linear(hidden, inter, bias_attr=False)
        self.down = paddle.nn.Linear(inter, hidden, bias_attr=False)
        self.norm_w1 = paddle.create_parameter([hidden], "float32")
        self.norm_w2 = paddle.create_parameter([hidden], "float32")

    def _rms(self, x, w):
        var = (x * x).mean(axis=-1, keepdim=True)
        return x * paddle.rsqrt(var + 1e-6) * w

    def forward(self, x):
        B, S, _ = x.shape
        h = self._rms(x, self.norm_w1)
        q = self.wq(h).reshape([B, S, self.n, self.d]).transpose([0, 2, 1, 3])
        k = self.wk(h).reshape([B, S, self.n, self.d]).transpose([0, 2, 1, 3])
        v = self.wv(h).reshape([B, S, self.n, self.d]).transpose([0, 2, 1, 3])
        scores = paddle.matmul(q, k, transpose_y=True) / (self.d ** 0.5)
        probs = F.softmax(scores, axis=-1)
        o = paddle.matmul(probs, v).transpose([0, 2, 1, 3]).reshape([B, S, self.h])
        x = x + self.wo(o)
        h2 = self._rms(x, self.norm_w2)
        return x + self.down(F.silu(self.gate(h2)) * self.up(h2))


def test_vanilla_llama_block_gets_flash_substituted():
    """The VERDICT's done-criterion: a vanilla-jnp LLaMA block captured as
    a Program shows flash-attention substitution and matches numerics."""
    paddle.seed(5)
    blk = VanillaLlamaBlock(hidden=64, heads=4, inter=128)
    x_np = np.random.default_rng(2).normal(size=(2, 128, 64)).astype(np.float32)

    with paddle.no_grad():
        ref = np.asarray(blk(paddle.to_tensor(x_np))._value)

    prog = Program()
    with program_guard(prog):
        xv = _feed(prog, "x", (2, 128, 64))
        out = blk(xv)
    exe = static.Executor()
    got = exe.run(prog, feed={"x": x_np}, fetch_list=[out])[0]
    types = _optypes(prog)
    assert "flash_attention" in types
    # the residual-stream norm upgrades further to add_rms_norm
    assert types.count("fused_rms_norm") + types.count("add_rms_norm") == 2
    assert "swiglu" in types
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_causal_mask_attention_fuses_with_causal_flag():
    """Vanilla causal attention — scores/sqrt(d) + triangular -inf mask —
    fuses to flash_attention(causal=True) and matches the unfused numerics."""
    B, N, S, D = 2, 2, 128, 16
    mask = np.triu(np.full((S, S), -1e9, np.float32), k=1)[None, None]

    prog = Program()
    with program_guard(prog):
        q = _feed(prog, "q", (B, N, S, D))
        k = _feed(prog, "k", (B, N, S, D))
        v = _feed(prog, "v", (B, N, S, D))
        scores = paddle.matmul(q, k, transpose_y=True) / (D ** 0.5)
        scores = scores + paddle.to_tensor(mask)
        probs = F.softmax(scores, axis=-1)
        out = paddle.matmul(probs, v)
    from paddle_tpu.static.rewrite import PallasFusionPass

    n = PallasFusionPass([out._vid]).apply(prog)
    assert n == 1
    assert "flash_attention" in _optypes(prog)

    rng = np.random.default_rng(4)
    qv = rng.normal(size=(B, N, S, D)).astype(np.float32)
    kv = rng.normal(size=(B, N, S, D)).astype(np.float32)
    vv = rng.normal(size=(B, N, S, D)).astype(np.float32)
    exe = static.Executor()
    got = exe.run(prog, feed={"q": qv, "k": kv, "v": vv}, fetch_list=[out])[0]
    s = qv @ np.swapaxes(kv, -1, -2) / np.sqrt(D) + mask
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, p @ vv, rtol=2e-3, atol=2e-3)


def test_non_causal_arbitrary_mask_blocks_fusion():
    """An arbitrary additive mask has no kernel parameter: must NOT fuse."""
    B, N, S, D = 1, 2, 128, 16
    mask = np.random.default_rng(0).normal(size=(1, 1, S, S)).astype(np.float32)

    prog = Program()
    with program_guard(prog):
        q = _feed(prog, "q", (B, N, S, D))
        k = _feed(prog, "k", (B, N, S, D))
        v = _feed(prog, "v", (B, N, S, D))
        scores = paddle.matmul(q, k, transpose_y=True) + paddle.to_tensor(mask)
        out = paddle.matmul(F.softmax(scores, axis=-1), v)
    from paddle_tpu.static.rewrite import PallasFusionPass

    n = PallasFusionPass([out._vid]).apply(prog)
    assert n == 0
    assert "flash_attention" not in _optypes(prog)


def test_fp16_rewrite_then_fusion_still_substitutes_in_low_dtype():
    """ADVICE r3: the fp16 program rewrite renames matmul -> fp16::matmul;
    the fusion pass must still anchor, and the substituted flash kernel must
    keep the low-dtype compute the user asked for (fp16::flash_attention)."""
    from paddle_tpu.static.passes import apply_pass

    rng = np.random.default_rng(1)
    B, N, S, D, H, F_ = 2, 4, 128, 16, 32, 64
    feed = {
        "q": rng.normal(size=(B, N, S, D)).astype(np.float32),
        "k": rng.normal(size=(B, N, S, D)).astype(np.float32),
        "v": rng.normal(size=(B, N, S, D)).astype(np.float32),
        "x": rng.normal(size=(B, S, H)).astype(np.float32),
        "w": rng.normal(size=(H,)).astype(np.float32),
        "g": rng.normal(size=(B, S, F_)).astype(np.float32),
        "u": rng.normal(size=(B, S, F_)).astype(np.float32),
    }
    prog, fetches = _capture_vanilla()
    ref_exe = static.Executor()
    paddle.set_flags({"FLAGS_use_pallas_fusion": False})
    try:
        ref = ref_exe.run(prog, feed=feed, fetch_list=list(fetches))
    finally:
        paddle.set_flags({"FLAGS_use_pallas_fusion": True})

    prog2, fetches2 = _capture_vanilla()
    n16 = apply_pass(prog2, "auto_parallel_fp16", dtype="bfloat16")
    assert n16 >= 2  # both attention matmuls rewritten
    assert "fp16::matmul" in _optypes(prog2)
    n = PallasFusionPass([f._vid for f in fetches2]).apply(prog2)
    assert n == 3, f"fusion defeated after fp16 rewrite: {_optypes(prog2)}"
    assert "fp16::flash_attention" in _optypes(prog2)  # low dtype preserved
    exe = static.Executor()
    got = exe.run(prog2, feed=feed, fetch_list=list(fetches2))
    # bf16-tolerance match against the fp32 unfused program
    for r, g_ in zip(ref, got):
        np.testing.assert_allclose(r, g_, rtol=3e-2, atol=3e-2)


# ------------------------------------------------- matmul epilogue / add-norm

def _capture(fn, *feed_shapes):
    from paddle_tpu import static

    main = static.Program()
    with static.program_guard(main):
        feeds = [static.data(f"x{i}", list(s), "float32")
                 for i, s in enumerate(feed_shapes)]
        out = fn(*feeds)
    return main, feeds, out


def test_matmul_epilogue_pattern_fires_and_matches():
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu import static
    from paddle_tpu.static.rewrite import PallasFusionPass

    paddle.seed(0)
    lin = nn.Linear(64, 128)

    main, (x,), out = _capture(lambda v: F.gelu(lin(v)), (8, 64))
    exe = static.Executor()
    xv = np.random.default_rng(0).standard_normal((8, 64)).astype(np.float32)
    (ref,) = exe.run(main, feed={"x0": xv}, fetch_list=[out])

    n = PallasFusionPass([out._vid]).apply(main)
    types = [op.type for op in main.global_block().ops]
    assert "matmul_epilogue" in types, (n, types)
    (got,) = static.Executor().run(main, feed={"x0": xv}, fetch_list=[out])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_matmul_epilogue_gelu_tanh_variant():
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu import static
    from paddle_tpu.static.rewrite import PallasFusionPass

    paddle.seed(1)
    lin = nn.Linear(64, 128)
    main, (x,), out = _capture(lambda v: F.gelu(lin(v), approximate=True), (8, 64))
    exe = static.Executor()
    xv = np.random.default_rng(1).standard_normal((8, 64)).astype(np.float32)
    (ref,) = exe.run(main, feed={"x0": xv}, fetch_list=[out])
    PallasFusionPass([out._vid]).apply(main)
    ep = next(op for op in main.global_block().ops
              if op.type == "matmul_epilogue")
    (got,) = static.Executor().run(main, feed={"x0": xv}, fetch_list=[out])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_add_norm_pattern_fuses_residual_stream():
    """norm(x + residual) with the sum ALSO consumed later (the transformer
    residual stream) — the fused op must emit both outputs."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu import static
    from paddle_tpu.static.rewrite import PallasFusionPass

    paddle.seed(2)
    wv = np.random.default_rng(2).standard_normal(32).astype(np.float32)

    def body(a, b):
        w = paddle.to_tensor(wv)
        h = a + b
        normed = F.rms_norm(h, weight=w, epsilon=1e-5)
        return normed * 2.0 + h  # h reused: the residual stream

    main, feeds, out = _capture(body, (4, 32), (4, 32))
    rng = np.random.default_rng(3)
    av = rng.standard_normal((4, 32)).astype(np.float32)
    bv = rng.standard_normal((4, 32)).astype(np.float32)
    (ref,) = static.Executor().run(main, feed={"x0": av, "x1": bv},
                                   fetch_list=[out])
    n = PallasFusionPass([out._vid]).apply(main)
    types = [op.type for op in main.global_block().ops]
    assert "add_rms_norm" in types, (n, types)
    (got,) = static.Executor().run(main, feed={"x0": av, "x1": bv},
                                   fetch_list=[out])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_add_layer_norm_pattern():
    import paddle_tpu.nn.functional as F
    from paddle_tpu import static
    from paddle_tpu.static.rewrite import PallasFusionPass

    paddle.seed(3)
    rng = np.random.default_rng(4)
    wv = rng.standard_normal(32).astype(np.float32)
    bv_ = rng.standard_normal(32).astype(np.float32)

    def body(a, b):
        w = paddle.to_tensor(wv)
        bb = paddle.to_tensor(bv_)
        return F.layer_norm(a + b, 32, weight=w, bias=bb, epsilon=1e-5)

    main, feeds, out = _capture(body, (4, 32), (4, 32))
    av = rng.standard_normal((4, 32)).astype(np.float32)
    bv = rng.standard_normal((4, 32)).astype(np.float32)
    (ref,) = static.Executor().run(main, feed={"x0": av, "x1": bv},
                                   fetch_list=[out])
    PallasFusionPass([out._vid]).apply(main)
    types = [op.type for op in main.global_block().ops]
    assert "add_layer_norm" in types, types
    (got,) = static.Executor().run(main, feed={"x0": av, "x1": bv},
                                   fetch_list=[out])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_epilogue_patterns_fire_on_bert_program():
    """The reference criterion: the new patterns fire on a captured
    real-model program (BERT: gelu FFN + residual layer-norms)."""
    from paddle_tpu import static
    from paddle_tpu.models import BertForSequenceClassification, bert_tiny
    from paddle_tpu.static.rewrite import PallasFusionPass

    paddle.seed(0)
    m = BertForSequenceClassification(bert_tiny(), num_classes=2)
    m.eval()
    main = static.Program()
    with static.program_guard(main):
        ids = static.data("ids", [2, 16], "int32")
        out = m(ids)
        out = out[0] if isinstance(out, (tuple, list)) else out
    ids_v = np.random.default_rng(0).integers(1, 500, (2, 16)).astype(np.int32)
    (ref,) = static.Executor().run(main, feed={"ids": ids_v}, fetch_list=[out])
    PallasFusionPass([out._vid]).apply(main)
    types = [op.type for op in main.global_block().ops]
    assert "matmul_epilogue" in types, set(types)
    assert "add_layer_norm" in types, set(types)
    (got,) = static.Executor().run(main, feed={"ids": ids_v}, fetch_list=[out])
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=5e-4)


def test_generic_elementwise_chain_fusion():
    """The CINN-discovery role: an arbitrary elementwise chain (not one of
    the fixed patterns) collapses to ONE generated VPU kernel op with
    numerics preserved (opt-in pass)."""
    from paddle_tpu import static
    from paddle_tpu.static.passes import apply_pass

    def body(a, b):
        t = paddle.tanh(a * b + a)
        u = paddle.exp(t * 0.5)
        return paddle.sqrt(u + 1.0) * b

    main, feeds, out = _capture(body, (8, 128), (8, 128))
    rng = np.random.default_rng(0)
    av = rng.standard_normal((8, 128)).astype(np.float32)
    bv = rng.standard_normal((8, 128)).astype(np.float32)
    (ref,) = static.Executor().run(main, feed={"x0": av, "x1": bv},
                                   fetch_list=[out])
    before = len(main.global_block().ops)
    n = apply_pass(main, "generic_elementwise_fusion",
                   fetch_vids=[out._vid])
    after = len(main.global_block().ops)
    types = [op.type for op in main.global_block().ops]
    assert n >= 1 and after < before, (n, types)
    assert any(t.startswith("vpu_chain_") for t in types), types
    (got,) = static.Executor().run(main, feed={"x0": av, "x1": bv},
                                   fetch_list=[out])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_generic_fusion_respects_fetch_and_multi_use():
    """Intermediates that are fetched or multiply-consumed stay
    materialized (not swallowed into a chain)."""
    from paddle_tpu import static
    from paddle_tpu.static.passes import apply_pass

    main = static.Program()
    from paddle_tpu.static.program import program_guard

    with program_guard(main):
        a = static.data("a", [4, 32], "float32")
        t = paddle.tanh(a * 2.0)      # fetched below: must survive
        u = paddle.exp(t + 1.0)
        v = paddle.sqrt(u * u + 1.0)
    rng = np.random.default_rng(1)
    av = rng.standard_normal((4, 32)).astype(np.float32)
    ref_t, ref_v = static.Executor().run(main, feed={"a": av},
                                         fetch_list=[t, v])
    apply_pass(main, "generic_elementwise_fusion",
               fetch_vids=[t._vid, v._vid])
    got_t, got_v = static.Executor().run(main, feed={"a": av},
                                         fetch_list=[t, v])
    np.testing.assert_allclose(got_t, ref_t, rtol=1e-6)
    np.testing.assert_allclose(got_v, ref_v, rtol=1e-5, atol=1e-6)


def test_epilogue_pattern_skips_quantized_linear():
    """A weight-only-quantized linear (wq:: namespace; int8 weight + scale
    appended) must NOT be epilogue-fused — the pattern would read the
    scale as a bias and produce garbage."""
    import paddle_tpu.nn as nn
    from paddle_tpu.static.passes import apply_pass

    paddle.seed(0)
    lin = paddle.nn.Linear(64, 128, bias_attr=False)  # 3-arg wq form
    main = static.Program()
    with program_guard(main):
        x = static.data("x", [8, 64], "float32")
        out = F.gelu(lin(x))
    xv = np.random.default_rng(0).standard_normal((8, 64)).astype(np.float32)
    paddle.set_flags({"FLAGS_use_pallas_fusion": False})
    try:
        (ref,) = static.Executor().run(main, feed={"x": xv}, fetch_list=[out])
        apply_pass(main, "weight_only_quant")
        n = PallasFusionPass([out._vid]).apply(main)
        types = [op.type for op in main.global_block().ops]
        assert "matmul_epilogue" not in types, (n, types)
        (got,) = static.Executor().run(main, feed={"x": xv}, fetch_list=[out])
    finally:
        paddle.set_flags({"FLAGS_use_pallas_fusion": True})
    # int8 weight quantization error only — no structural corruption
    assert np.abs(got - ref).max() < 0.05 * max(1.0, np.abs(ref).max())


def test_epilogue_fusion_keeps_fp16_compute():
    """fp16-rewritten linear + gelu must fuse into an fp16:: epilogue op
    that computes in the low dtype (not silently revert to fp32)."""
    from paddle_tpu.static.passes import apply_pass

    paddle.seed(4)
    lin = paddle.nn.Linear(64, 128)
    main = static.Program()
    with program_guard(main):
        x = static.data("x", [8, 64], "float32")
        out = F.gelu(lin(x))
    xv = np.random.default_rng(2).standard_normal((8, 64)).astype(np.float32)
    paddle.set_flags({"FLAGS_use_pallas_fusion": False})
    try:
        (ref,) = static.Executor().run(main, feed={"x": xv}, fetch_list=[out])
        apply_pass(main, "auto_parallel_fp16", dtype="bfloat16")
        PallasFusionPass([out._vid]).apply(main)
        types = [op.type for op in main.global_block().ops]
        assert "fp16::matmul_epilogue" in types, types
        (got,) = static.Executor().run(main, feed={"x": xv}, fetch_list=[out])
    finally:
        paddle.set_flags({"FLAGS_use_pallas_fusion": True})
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-2)  # bf16
    # bf16 compute really happened: outputs differ from exact fp32
    assert np.abs(got - ref).max() > 0
