"""Shared model factory for the serving-cluster tests and bench.

NOT a test module (no test_ prefix): cluster worker PROCESSES import this
file by PATH (`EngineCluster(model_spec="<this file>:make_model")`), so
every process in a cluster — router, decode replicas, prefill workers,
and the in-test reference engine — builds the SAME deterministically
seeded tiny llama.  Weights never ride the wire; identical construction
is the cluster's weight-distribution story at test scale (production
weights ride the training checkpoint tier)."""


def make_model():
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(41)
    cfg = llama_tiny(vocab_size=128, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=64,
                     dtype="float32")
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def make_model_bf16():
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(43)
    cfg = llama_tiny(vocab_size=128, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=64,
                     dtype="bfloat16")
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m
