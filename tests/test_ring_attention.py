"""Ring + Ulysses sequence-parallel attention vs single-device oracle
(long-context SEP axis — SURVEY.md §5)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from paddle_tpu.distributed.shard_map_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.ops.flash_attention import flash_attention_reference
from paddle_tpu.ops.ring_attention import ring_attention, ulysses_attention


def _qkv(b=1, s=64, n=4, h=16, seed=0):
    rng = np.random.default_rng(seed)
    return [jnp.asarray(rng.standard_normal((b, s, n, h)).astype(np.float32)) for _ in range(3)]


def _mesh(w=4):
    return Mesh(np.array(jax.devices()[:w]), ("sep",))


def _run_sharded(fn, q, k, v, w=4):
    mesh = _mesh(w)
    body = lambda ql, kl, vl: fn(ql, kl, vl, "sep")
    return shard_map(
        body, mesh=mesh, in_specs=(P(None, "sep"), P(None, "sep"), P(None, "sep")),
        out_specs=P(None, "sep"), check_vma=False,
    )(q, k, v)


@pytest.mark.slow
def test_ring_attention_causal_matches_reference():
    q, k, v = _qkv()
    out = _run_sharded(lambda a, b, c, ax: ring_attention(a, b, c, ax, causal=True), q, k, v)
    ref = flash_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ring_attention_noncausal_matches_reference():
    q, k, v = _qkv(seed=1)
    out = _run_sharded(lambda a, b, c, ax: ring_attention(a, b, c, ax, causal=False), q, k, v)
    ref = flash_attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ring_attention_grads_match():
    q, k, v = _qkv(s=32, seed=2)
    mesh = _mesh(4)

    def loss_ring(q, k, v):
        f = shard_map(
            lambda a, b, c: ring_attention(a, b, c, "sep", causal=True),
            mesh=mesh, in_specs=(P(None, "sep"),) * 3, out_specs=P(None, "sep"),
            check_vma=False,
        )
        return jnp.sum(f(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(flash_attention_reference(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_ulysses_attention_matches_reference():
    q, k, v = _qkv(s=64, n=4, seed=3)
    out = _run_sharded(lambda a, b, c, ax: ulysses_attention(a, b, c, ax, causal=True), q, k, v)
    ref = flash_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_sep_attention_world1_fallback():
    from paddle_tpu.distributed.fleet.meta_parallel import sep_attention

    q, k, v = _qkv(s=32, seed=4)
    out = sep_attention(paddle.Tensor(q), paddle.Tensor(k), paddle.Tensor(v), causal=True)
    ref = flash_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # 45s (VERDICT #5 named it): the 4-dev shard_map compile
# dominates regardless of shape; op-level ring/Ulysses parity stays in the
# fast tier via the reference-matching tests below
def test_context_parallel_llama_matches_replicated():
    """Model-level context parallelism: full LlamaForCausalLM with the
    sequence sharded over a 4-way 'sep' axis (ring attention + rank-offset
    rope) produces the same logits as the unsharded model."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.communication import collective_axis_scope
    from paddle_tpu.models.llama import (
        LlamaForCausalLM, context_parallel_llama, llama_tiny,
    )
    from paddle_tpu._core.tensor import Tensor

    paddle.seed(17)
    cfg = llama_tiny(vocab_size=96, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=64,
                     dtype="float32")
    model = LlamaForCausalLM(cfg)
    B, S, W = 2, 32, 4
    ids = np.random.default_rng(5).integers(0, 96, (B, S)).astype(np.int32)

    model.eval()
    with paddle.no_grad():
        ref = np.asarray(model(paddle.to_tensor(ids))._value)

    context_parallel_llama(model, mode="ring")
    state = list(model.state_dict().values())

    mesh = Mesh(np.array(jax.devices()[:W]), ("sep",))

    def body(ids_local, *vals):
        originals = [t._value for t in state]
        try:
            for t, v in zip(state, vals):
                t._bind(v)
            with paddle.no_grad(), collective_axis_scope({"sep": "sep"}):
                out = model(Tensor(ids_local))
            return out._value
        finally:
            for t, v in zip(state, originals):
                t._bind(v)

    f = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, "sep"),) + tuple(P() for _ in state),
        out_specs=P(None, "sep", None), check_vma=False,
    )
    got = np.asarray(f(jnp.asarray(ids), *[t._value for t in state]))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    # and the SAME model object still works without a sep scope
    with paddle.no_grad():
        again = np.asarray(model(paddle.to_tensor(ids))._value)
    np.testing.assert_allclose(again, ref, rtol=1e-5, atol=1e-5)
