"""Protocol lint (serving/protocol.py + static/protocol_lint.py,
docs/PROTOCOL_LINT.md).

Three tiers, each with failing fixtures AND passing twins (the verifier
discipline applied to a wire protocol):

- the spec itself: protocol-as-data tables validate, dispatch binds to
  them bidirectionally (a spec message without a handler and a handler
  without a spec message each raise ProtocolSpecError), and the
  generated wire table is byte-identical to the committed doc block;
- the model checker: the REAL spec explores every reachable state of
  the abstract 5-process cluster clean on BOTH transport semantics,
  while each seeded protocol bug yields a minimal counterexample trace
  naming the violated invariant (the tier-1 acceptance sweep;
  tools/lint_protocol.py battery is the standalone twin);
- the blocking-call AST lint: the real serving/ + collective/ trees are
  clean, and each seeded deadlock shape is flagged.

Everything is abstract — no process forks, no ring is created — so this
module rides an ordinary round-robin tier-1 shard.
"""

import os

import pytest

from paddle_tpu.serving import protocol
from paddle_tpu.serving.protocol import ProtocolSpecError
from paddle_tpu.static.protocol_lint import (
    ProtocolLintError,
    SCENARIOS,
    check_model,
    lint_blocking_calls,
    lint_cluster_protocol,
    lint_source,
    protocol_lint_stats,
    render_trace,
    reset_protocol_lint_stats,
)

_DOCS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs")


def _codes(violations):
    return {v.code for v in violations}


# ------------------------------------------------- tier 1: the spec as data
def test_spec_validates_and_alphabets_are_exact():
    # import already ran validate_spec(); run it again explicitly — the
    # tables must be internally consistent (states declared, recv/send
    # alphabets matching MESSAGES exactly)
    protocol.validate_spec()
    assert len(protocol.MESSAGES) == 21
    assert set(protocol.INVARIANTS) == {
        "journal-before-dispatch", "no-double-serve", "no-lost-request",
        "nonce-before-first-token", "backpressure-not-death",
        "promotion-claims-once", "warmed-ends-boot-grace"}
    # every role's full inbound surface is reachable through its machine
    for role in protocol.ROLES:
        recvs = {ev[5:] for (_, ev) in protocol.TRANSITIONS[role]
                 if ev.startswith("recv:")}
        assert recvs == {m.name for m in protocol.messages_to(role)}, role


def test_bind_handlers_is_bidirectional():
    handlers = {"_h_" + m.name: (lambda msg: msg)
                for m in protocol.messages_to("prefill")}
    bound = protocol.bind_handlers("prefill", handlers, prefix="_h_")
    assert set(bound) == {m.name for m in protocol.messages_to("prefill")}

    # direction 1: a spec row nobody implements fails loudly
    missing = dict(handlers)
    del missing["_h_prefill"]
    with pytest.raises(ProtocolSpecError, match="'prefill'.*no.*handler"):
        protocol.bind_handlers("prefill", missing, prefix="_h_")

    # direction 2: a handler the spec no longer names is dead code
    # wearing a live wire's uniform
    extra = dict(handlers)
    extra["_h_warp"] = lambda msg: msg
    with pytest.raises(ProtocolSpecError, match="_h_warp.*spec"):
        protocol.bind_handlers("prefill", extra, prefix="_h_")


def test_real_dispatch_binds_through_the_tables():
    """EngineCluster's _ev_* surface and cluster_worker's three role
    tables bind against the spec — the same construction-/import-time
    assertion the cluster itself runs before any fork."""
    from paddle_tpu.serving import cluster_worker
    from paddle_tpu.serving.cluster import EngineCluster

    bound = protocol.bind_handlers(
        "router", protocol.handler_lookup(EngineCluster, "_ev_"),
        prefix="_ev_")
    assert set(bound) == {m.name for m in protocol.messages_to("router")}

    decode, prefill, standby = cluster_worker.handler_tables()
    assert set(decode) == {m.name for m in protocol.messages_to("decode")}
    assert set(prefill) == {m.name for m in protocol.messages_to("prefill")}
    assert set(standby) == {m.name for m in protocol.messages_to("standby")}


def test_wire_table_doc_is_generated_not_written():
    """docs/SERVING_CLUSTER.md embeds wire_table_markdown() between the
    wire-protocol markers byte-for-byte — edit the spec, not the doc."""
    with open(os.path.join(_DOCS, "SERVING_CLUSTER.md"),
              encoding="utf-8") as f:
        text = f.read()
    begin = text.index("wire-protocol:begin")
    begin = text.index("\n", begin) + 1
    end = text.index("<!-- wire-protocol:end -->")
    assert text[begin:end].strip("\n") == protocol.wire_table_markdown()


# --------------------------------------- tier 2: exhaustive model checking
@pytest.mark.parametrize("scenario,floor", [
    ("clean-shmring", 50_000),
    ("clean-tcp", 50_000),
    # the real TcpRing arms frame DUPLICATION instead of a death, which
    # prunes the whole post-mortem recovery subgraph — a smaller but
    # still six-figure-transition graph
    ("clean-tcp-ring", 40_000),
])
def test_real_spec_explores_clean(scenario, floor):
    """The REAL protocol, exhaustively: every reachable state of the
    abstract 5-process cluster (crash/conn-drop/frame-duplication armed
    at every state) satisfies every named invariant and no non-terminal
    state is quiescent.  `complete` proves frontier exhaustion — this is
    a proof over the abstract model, not a sample."""
    res = check_model(scenario)
    assert res.complete
    assert res.violations == []
    assert res.deadlocks == 0
    # exhaustiveness floor: shrinking the model (dropping the crash or
    # respawn transitions, say) would collapse the state count long
    # before it stopped being "complete"
    assert res.states > floor
    assert res.transitions > res.states


def test_seeded_bugs_yield_minimal_named_counterexamples():
    """Each seeded protocol bug produces a counterexample trace naming
    exactly the invariant it was seeded to break — the checker's flags
    are causal, not coincidental."""
    for name, sc in SCENARIOS.items():
        if not sc.expect:
            continue
        res = check_model(name)
        assert set(sc.expect) <= _codes(res.violations), name
        for v in res.violations:
            if v.code not in sc.expect:
                continue
            assert v.site == f"model:{name}"
            # BFS order makes the first hit minimal-depth: a readable
            # interleaving, not a 10k-step soup
            assert 0 < len(v.trace) <= 12, (name, v.trace)
            rendered = render_trace(v)
            assert f"VIOLATED {v.code}" in rendered
            assert f"{len(v.trace)} steps" in rendered


def test_lint_cluster_protocol_raises_with_traces():
    """The raising entry point: a spec that breaks an invariant fails
    loudly with every counterexample in the message."""
    import paddle_tpu.static.protocol_lint as pl

    broken = dict(SCENARIOS)
    broken["clean-shmring"] = SCENARIOS["two-routers"]
    orig = pl.SCENARIOS
    pl.SCENARIOS = broken
    try:
        with pytest.raises(ProtocolLintError, match="no-double-serve"):
            lint_cluster_protocol("shmring")
    finally:
        pl.SCENARIOS = orig


# ------------------------------------------ tier 3: blocking-call AST lint
def test_blocking_lint_real_trees_are_clean():
    """Every blocking call in serving/ + distributed/collective/ carries
    a deadline or rides retry_backoff's shared one."""
    reset_protocol_lint_stats()
    assert lint_blocking_calls() == []
    stats = protocol_lint_stats()
    assert stats["files_linted"] >= 7
    assert stats["blocking_calls_checked"] >= 5
    assert stats["violations"] == 0


def test_blocking_lint_flags_each_deadlock_shape():
    fixtures = [
        ("def poll(ring_in):\n"
         "    return ring_in.pop()\n", {"unbounded-blocking"}),
        ("def sync(store, key):\n"
         "    store.wait(key)\n", {"unbounded-blocking"}),
        ("def forward(self, data):\n"
         "    with self._state_lock:\n"
         "        self.ring_out.push(data, timeout_ms=250)\n",
         {"lock-held-blocking"}),
        ("def exchange(ring_in, ring_out, data):\n"
         "    ring_out.push(data)\n"
         "    return ring_in.pop()\n",
         # both direction waits are themselves unbounded AND together
         # they form the two-party circular-wait shape
         {"unbounded-blocking", "circular-wait"}),
    ]
    for src, codes in fixtures:
        got = _codes(lint_source(src, "<fixture>"))
        assert codes <= got, src
    # passing twins: an explicit deadline, and retry_backoff's shared one
    assert lint_source(
        "def poll(ring_in):\n"
        "    return ring_in.pop(timeout_ms=100)\n") == []
    assert lint_source(
        "def forward(worker, data):\n"
        "    def _push():\n"
        "        worker.ring_in.push(data)\n"
        "    retry_backoff(_push, timeout_s=5.0)\n") == []
    # a dict's .pop / str.join never classify as channel waits
    assert lint_source(
        "def tidy(cache, parts):\n"
        "    cache.pop('k', None)\n"
        "    return ', '.join(parts)\n") == []


def test_timeout_positional_is_kind_aware():
    # proc.join(5) is timed; store.wait(key)'s positional is the KEY
    assert lint_source("def w(child_proc):\n"
                       "    child_proc.join(5)\n") == []
    assert _codes(lint_source("def w(store, key):\n"
                              "    store.wait(key)\n")) \
        == {"unbounded-blocking"}
    # lock.acquire(True, 5) is timed; lock.acquire(True) is not
    assert lint_source("def w(run_lock):\n"
                       "    run_lock.acquire(True, 5)\n") == []
    assert _codes(lint_source("def w(run_lock):\n"
                              "    run_lock.acquire(True)\n")) \
        == {"unbounded-blocking"}


# ------------------------------------------------- stats + profiler footer
def test_stats_and_summary_footer(capsys):
    reset_protocol_lint_stats()
    res = check_model("drop-intake-fsync")  # stops at first expected hits
    assert res.violations
    lint_source("def poll(ring_in):\n"
                "    return ring_in.pop(timeout_ms=50)\n")
    stats = protocol_lint_stats()
    assert stats["scenarios_checked"] == 1
    assert stats["model_states"] == res.states
    assert stats["model_transitions"] == res.transitions
    assert stats["invariant_checks"] > 0
    assert stats["violations"] == len(res.violations)
    assert stats["files_linted"] == 1
    assert stats["blocking_calls_checked"] == 1

    from paddle_tpu import profiler

    assert profiler.protocol_lint_stats() == stats
    prof = profiler.Profiler(timer_only=True)
    prof.start()
    prof.stop()
    out = prof.summary()
    assert "Protocol lint:" in out
    assert f"states={stats['model_states']}" in out
    capsys.readouterr()

    # reset semantics mirror the other static-tier passes
    assert protocol_lint_stats(reset=True) == stats
    assert protocol_lint_stats()["scenarios_checked"] == 0


def test_docs_exist_and_cross_reference():
    with open(os.path.join(_DOCS, "PROTOCOL_LINT.md"),
              encoding="utf-8") as f:
        doc = f.read()
    for needle in ("protocol_lint", "invariant", "counterexample",
                   "tools/lint_protocol.py"):
        assert needle in doc, needle
    with open(os.path.join(_DOCS, "COMPONENTS.md"), encoding="utf-8") as f:
        assert "protocol_lint" in f.read()
