"""paddle.sparse parity tests (reference model: test/legacy_test/
test_sparse_*_op.py — COO/CSR creation, unary/binary, matmul family,
sparse conv/pool/softmax/attention), checked against dense numpy."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.sparse as sparse


def npv(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


def rand_coo(rng, shape, density=0.3):
    dense = rng.normal(size=shape).astype(np.float32)
    dense[rng.random(shape) > density] = 0.0
    return dense


class TestCreation:
    def test_coo_roundtrip(self):
        idx = [[0, 1, 2], [1, 2, 0]]
        vals = [1.0, 2.0, 3.0]
        s = sparse.sparse_coo_tensor(idx, vals, [3, 3])
        d = s.to_dense()
        expected = np.zeros((3, 3), np.float32)
        expected[0, 1], expected[1, 2], expected[2, 0] = 1, 2, 3
        np.testing.assert_allclose(npv(d), expected)
        assert s.nnz() == 3

    def test_coo_duplicate_indices_coalesce(self):
        s = sparse.sparse_coo_tensor([[0, 0], [1, 1]], [2.0, 5.0], [2, 2])
        np.testing.assert_allclose(npv(s.to_dense())[0, 1], 7.0)
        assert s.nnz() == 1

    def test_csr_roundtrip(self):
        crows = [0, 2, 3, 5]
        cols = [1, 3, 2, 0, 1]
        vals = [1.0, 2.0, 3.0, 4.0, 5.0]
        s = sparse.sparse_csr_tensor(crows, cols, vals, [3, 4])
        d = npv(s.to_dense())
        expected = np.zeros((3, 4), np.float32)
        expected[0, 1], expected[0, 3], expected[1, 2], expected[2, 0], expected[2, 1] = 1, 2, 3, 4, 5
        np.testing.assert_allclose(d, expected)

    def test_dense_to_sparse_and_back(self):
        rng = np.random.default_rng(0)
        dense = rand_coo(rng, (5, 6))
        t = paddle.to_tensor(dense)
        coo = t.to_sparse_coo(2)
        np.testing.assert_allclose(npv(coo.to_dense()), dense)
        csr = t.to_sparse_csr()
        np.testing.assert_allclose(npv(csr.to_dense()), dense)
        coo2 = csr.to_sparse_coo()
        np.testing.assert_allclose(npv(coo2.to_dense()), dense)

    def test_coo_with_dense_dim(self):
        idx = [[0, 2]]
        vals = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        s = sparse.sparse_coo_tensor(idx, vals, [3, 2])
        d = npv(s.to_dense())
        np.testing.assert_allclose(d[0], [1, 2])
        np.testing.assert_allclose(d[2], [3, 4])
        np.testing.assert_allclose(d[1], [0, 0])


class TestUnary:
    def test_value_ops_match_dense(self):
        rng = np.random.default_rng(1)
        dense = np.abs(rand_coo(rng, (4, 5))) * 0.5
        s = paddle.to_tensor(dense).to_sparse_coo(2)
        for name in ["sin", "tanh", "sqrt", "square", "log1p", "abs", "expm1", "neg"]:
            out = getattr(sparse, name)(s)
            ref = getattr(np, name if name != "neg" else "negative")(dense)
            # zero-preserving ops keep zeros at empty sites
            ref_sparse = np.where(dense != 0, ref, 0)
            np.testing.assert_allclose(npv(out.to_dense()), ref_sparse, rtol=1e-5, atol=1e-6)

    def test_pow_cast(self):
        dense = np.array([[0.0, 2.0], [3.0, 0.0]], np.float32)
        s = paddle.to_tensor(dense).to_sparse_coo(2)
        np.testing.assert_allclose(npv(sparse.pow(s, 2).to_dense()), dense**2)
        # float64 narrows to float32 (TPU-native width policy)
        c = sparse.cast(s, value_dtype="float64")
        assert str(c.dtype) == "float32"

    def test_transpose(self):
        rng = np.random.default_rng(2)
        dense = rand_coo(rng, (3, 5))
        s = paddle.to_tensor(dense).to_sparse_coo(2)
        np.testing.assert_allclose(npv(sparse.transpose(s, [1, 0]).to_dense()), dense.T)

    def test_sum(self):
        rng = np.random.default_rng(3)
        dense = rand_coo(rng, (4, 6))
        s = paddle.to_tensor(dense).to_sparse_coo(2)
        np.testing.assert_allclose(npv(sparse.sum(s)), dense.sum(), rtol=1e-5)
        np.testing.assert_allclose(
            npv(sparse.sum(s, axis=0).to_dense()), dense.sum(0), rtol=1e-5
        )

    def test_reshape(self):
        rng = np.random.default_rng(4)
        dense = rand_coo(rng, (4, 6))
        s = paddle.to_tensor(dense).to_sparse_coo(2)
        r = sparse.reshape(s, [2, 12])
        np.testing.assert_allclose(npv(r.to_dense()), dense.reshape(2, 12))

    def test_slice(self):
        rng = np.random.default_rng(5)
        dense = rand_coo(rng, (5, 7))
        s = paddle.to_tensor(dense).to_sparse_coo(2)
        out = sparse.slice(s, [0, 1], [1, 2], [4, 6])
        np.testing.assert_allclose(npv(out.to_dense()), dense[1:4, 2:6])

    def test_isnan(self):
        dense = np.array([[0.0, np.nan], [1.0, 0.0]], np.float32)
        s = paddle.to_tensor(dense).to_sparse_coo(2)
        out = sparse.isnan(s)
        assert npv(out.to_dense())[0, 1]


class TestBinary:
    @pytest.mark.parametrize("op,ref", [
        ("add", np.add), ("subtract", np.subtract), ("multiply", np.multiply),
    ])
    def test_elementwise_union_pattern(self, op, ref):
        rng = np.random.default_rng(6)
        a, b = rand_coo(rng, (4, 5)), rand_coo(rng, (4, 5))
        sa = paddle.to_tensor(a).to_sparse_coo(2)
        sb = paddle.to_tensor(b).to_sparse_coo(2)
        out = getattr(sparse, op)(sa, sb)
        np.testing.assert_allclose(npv(out.to_dense()), ref(a, b), rtol=1e-5, atol=1e-6)

    def test_csr_add(self):
        rng = np.random.default_rng(7)
        a, b = rand_coo(rng, (3, 4)), rand_coo(rng, (3, 4))
        out = sparse.add(paddle.to_tensor(a).to_sparse_csr(), paddle.to_tensor(b).to_sparse_csr())
        assert out.is_sparse_csr
        np.testing.assert_allclose(npv(out.to_dense()), a + b, rtol=1e-5)

    def test_is_same_shape(self):
        a = paddle.to_tensor(np.eye(3, dtype=np.float32)).to_sparse_coo(2)
        b = paddle.to_tensor(np.eye(3, dtype=np.float32)).to_sparse_coo(2)
        assert sparse.is_same_shape(a, b)


class TestMatmul:
    def test_spmm_coo(self):
        rng = np.random.default_rng(8)
        a = rand_coo(rng, (5, 7))
        b = rng.normal(size=(7, 3)).astype(np.float32)
        s = paddle.to_tensor(a).to_sparse_coo(2)
        np.testing.assert_allclose(npv(sparse.matmul(s, paddle.to_tensor(b))), a @ b, rtol=1e-4, atol=1e-5)

    def test_spmm_csr(self):
        rng = np.random.default_rng(9)
        a = rand_coo(rng, (4, 6))
        b = rng.normal(size=(6, 2)).astype(np.float32)
        s = paddle.to_tensor(a).to_sparse_csr()
        np.testing.assert_allclose(npv(sparse.matmul(s, paddle.to_tensor(b))), a @ b, rtol=1e-4, atol=1e-5)

    def test_mv(self):
        rng = np.random.default_rng(10)
        a = rand_coo(rng, (5, 5))
        v = rng.normal(size=5).astype(np.float32)
        s = paddle.to_tensor(a).to_sparse_coo(2)
        np.testing.assert_allclose(npv(sparse.mv(s, paddle.to_tensor(v))), a @ v, rtol=1e-4, atol=1e-5)

    def test_masked_matmul(self):
        rng = np.random.default_rng(11)
        x = rng.normal(size=(4, 6)).astype(np.float32)
        y = rng.normal(size=(6, 5)).astype(np.float32)
        mask_dense = (rng.random((4, 5)) < 0.4).astype(np.float32)
        mask = paddle.to_tensor(mask_dense).to_sparse_coo(2)
        out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y), mask)
        np.testing.assert_allclose(npv(out.to_dense()), (x @ y) * mask_dense, rtol=1e-4, atol=1e-5)

    def test_addmm(self):
        rng = np.random.default_rng(12)
        a = rand_coo(rng, (3, 4))
        y = rng.normal(size=(4, 2)).astype(np.float32)
        inp = rng.normal(size=(3, 2)).astype(np.float32)
        s = paddle.to_tensor(a).to_sparse_coo(2)
        out = sparse.addmm(paddle.to_tensor(inp), s, paddle.to_tensor(y), beta=0.5, alpha=2.0)
        np.testing.assert_allclose(npv(out), 0.5 * inp + 2.0 * (a @ y), rtol=1e-4, atol=1e-5)

    def test_pca_lowrank(self):
        rng = np.random.default_rng(13)
        a = rand_coo(rng, (20, 8), density=0.5)
        s = paddle.to_tensor(a).to_sparse_coo(2)
        u, sig, v = sparse.pca_lowrank(s, q=4)
        assert npv(u).shape == (20, 4) and npv(sig).shape == (4,) and npv(v).shape == (8, 4)


class TestSparseNN:
    def test_relu_softmax(self):
        rng = np.random.default_rng(14)
        dense = rand_coo(rng, (4, 5))
        s = paddle.to_tensor(dense).to_sparse_coo(2)
        out = sparse.nn.functional.relu(s)
        np.testing.assert_allclose(npv(out.to_dense()), np.maximum(dense, 0), rtol=1e-6)

        sm = sparse.nn.functional.softmax(s)
        d = npv(sm.to_dense())
        # stored entries per row sum to 1
        for r in range(4):
            nz = dense[r] != 0
            if nz.any():
                np.testing.assert_allclose(d[r][nz].sum(), 1.0, rtol=1e-5)
                ref = np.exp(dense[r][nz] - dense[r][nz].max())
                np.testing.assert_allclose(d[r][nz], ref / ref.sum(), rtol=1e-5)

    def test_conv3d_matches_dense(self):
        import jax

        rng = np.random.default_rng(15)
        x = rand_coo(rng, (1, 4, 4, 4, 2), density=0.4)
        w = rng.normal(size=(3, 3, 3, 2, 5)).astype(np.float32) * 0.1
        s = paddle.to_tensor(x).to_sparse_coo(4)
        out = sparse.nn.functional.conv3d(s, paddle.to_tensor(w), padding=1)
        ref = jax.lax.conv_general_dilated(
            x, w, (1, 1, 1), [(1, 1)] * 3,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        )
        np.testing.assert_allclose(npv(out.to_dense()), np.asarray(ref), rtol=1e-3, atol=1e-4)

    def test_subm_conv3d_preserves_pattern(self):
        rng = np.random.default_rng(16)
        x = rand_coo(rng, (1, 4, 4, 4, 2), density=0.3)
        s = paddle.to_tensor(x).to_sparse_coo(4)
        layer = sparse.nn.SubmConv3D(2, 6, 3, padding=1)
        out = layer(s)
        assert out.nnz() == s.nnz()
        np.testing.assert_array_equal(np.asarray(out._indices), np.asarray(s._indices))

    def test_maxpool3d(self):
        rng = np.random.default_rng(17)
        x = np.abs(rand_coo(rng, (1, 4, 4, 4, 3), density=0.5))
        s = paddle.to_tensor(x).to_sparse_coo(4)
        out = sparse.nn.functional.max_pool3d(s, 2, stride=2)
        d = npv(out.to_dense())
        assert d.shape == (1, 2, 2, 2, 3)
        ref2 = np.zeros_like(d)
        for i in range(2):
            for j in range(2):
                for k in range(2):
                    ref2[0, i, j, k] = x[0, 2*i:2*i+2, 2*j:2*j+2, 2*k:2*k+2].max(axis=(0, 1, 2))
        np.testing.assert_allclose(d, ref2, rtol=1e-6)

    def test_maxpool3d_negative_values_survive(self):
        # a lone negative active site must win its window (empty sites are
        # not zeros)
        x = np.zeros((1, 2, 2, 2, 1), np.float32)
        x[0, 0, 0, 0, 0] = -5.0
        s = sparse.sparse_coo_tensor(
            np.array([[0], [0], [0], [0]]), np.array([[-5.0]], np.float32), [1, 2, 2, 2, 1]
        )
        out = sparse.nn.functional.max_pool3d(s, 2, stride=2)
        assert out.nnz() == 1
        np.testing.assert_allclose(npv(out.values()), [[-5.0]])

    def test_batchnorm(self):
        rng = np.random.default_rng(18)
        x = rand_coo(rng, (1, 3, 3, 3, 4), density=0.6)
        s = paddle.to_tensor(x).to_sparse_coo(4)
        bn = sparse.nn.BatchNorm(4)
        out = bn(s)
        vals = npv(out.values())
        np.testing.assert_allclose(vals.mean(0), 0.0, atol=1e-4)
        np.testing.assert_allclose(vals.std(0), 1.0, atol=1e-2)

    def test_attention(self):
        rng = np.random.default_rng(19)
        b, h, n, d = 1, 2, 8, 4
        q = rng.normal(size=(b, h, n, d)).astype(np.float32)
        k = rng.normal(size=(b, h, n, d)).astype(np.float32)
        v = rng.normal(size=(b, h, n, d)).astype(np.float32)
        mask_dense = np.ones((n, n), np.float32)  # full mask → dense attention
        mask = paddle.to_tensor(mask_dense).to_sparse_coo(2)
        out = sparse.nn.functional.attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v), mask
        )
        # reference: dense softmax attention
        scores = q @ k.transpose(0, 1, 3, 2) / np.sqrt(d)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        ref = p @ v
        np.testing.assert_allclose(npv(out), ref, rtol=1e-3, atol=1e-4)
