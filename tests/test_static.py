"""Static graph subsystem: Program capture, Executor, append_backward,
optimizer.minimize training, inference save/load (SURVEY.md §2.2 parity)."""

import os

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static


def test_program_capture_and_run():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 3], "float32")
        y = static.data("y", [2, 3], "float32")
        z = paddle.add(x, y)
        w = paddle.sum(z * 2.0)
    assert isinstance(z, static.Variable)
    assert len(main.global_block().ops) >= 2

    exe = static.Executor()
    xv = np.arange(6, dtype=np.float32).reshape(2, 3)
    yv = np.ones((2, 3), np.float32)
    (zv, wv) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[z, w])
    np.testing.assert_allclose(zv, xv + yv)
    np.testing.assert_allclose(wv, (xv + yv).sum() * 2.0)


def test_layer_capture_registers_params():
    paddle.seed(0)
    layer = nn.Linear(4, 2)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3, 4], "float32")
        out = layer(x)
    assert len(main.all_parameters()) == 2  # weight + bias

    exe = static.Executor()
    xv = np.random.default_rng(0).standard_normal((3, 4)).astype(np.float32)
    (ov,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    ref = xv @ np.asarray(layer.weight._value) + np.asarray(layer.bias._value)
    np.testing.assert_allclose(ov, ref, atol=1e-6)


def test_append_backward_grads():
    main = static.Program()
    w_init = np.array([[2.0, 0.0], [0.0, 3.0]], np.float32)
    layer = nn.Linear(2, 2)
    layer.weight.set_value(w_init)
    layer.bias.set_value(np.zeros(2, np.float32))
    with static.program_guard(main):
        x = static.data("x", [1, 2], "float32")
        loss = paddle.sum(layer(x) ** 2)
        p_g = static.append_backward(loss, parameter_list=[layer.weight, layer.bias])

    exe = static.Executor()
    xv = np.array([[1.0, 1.0]], np.float32)
    fetches = exe.run(main, feed={"x": xv}, fetch_list=[loss] + [g for _, g in p_g])
    # out = [2, 3]; loss = 4+9=13; dloss/dW = 2*out*x -> [[4,6],[4,6]]; db = [4,6]
    np.testing.assert_allclose(fetches[0], 13.0, rtol=1e-6)
    np.testing.assert_allclose(fetches[1], np.array([[4.0, 6.0], [4.0, 6.0]]), rtol=1e-5)
    np.testing.assert_allclose(fetches[2], np.array([4.0, 6.0]), rtol=1e-5)


def test_static_training_minimize_loss_decreases():
    paddle.seed(1)
    rng = np.random.default_rng(2)
    true_w = rng.standard_normal((4, 1)).astype(np.float32)
    X = rng.standard_normal((64, 4)).astype(np.float32)
    Y = X @ true_w

    layer = nn.Linear(4, 1)
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=layer.parameters())

    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [64, 4], "float32")
        y = static.data("y", [64, 1], "float32")
        pred = layer(x)
        loss = paddle.mean((pred - y) ** 2)
        opt.minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    losses = []
    with static.scope_guard(static.Scope()):
        for _ in range(20):
            (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.2, losses
    # live dygraph objects must be untouched by capture/execution:
    # concrete value (no leaked tracer), identical to its pre-training state
    import jax as _jax

    assert isinstance(layer.weight._value, _jax.Array)
    assert not isinstance(layer.weight._value, _jax.core.Tracer)
    w_now = np.asarray(layer.weight._value)
    init_val = np.asarray(main.param_inits[main.param_vars[id(layer.weight)]._vid])
    np.testing.assert_array_equal(w_now, init_val)


def test_program_clone_for_test_drops_writes():
    layer = nn.Linear(2, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=layer.parameters())
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [1, 2], "float32")
        loss = paddle.sum(layer(x))
        opt.minimize(loss)
    assert main.writes
    test_prog = main.clone(for_test=True)
    assert not test_prog.writes


def test_save_load_inference_model(tmp_path):
    paddle.seed(3)
    layer = nn.Linear(3, 2)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 3], "float32")
        out = paddle.tanh(layer(x))

    exe = static.Executor()
    prefix = str(tmp_path / "model" / "net")
    static.save_inference_model(prefix, [x], [out], exe, program=main)
    assert os.path.exists(prefix + ".pdmodel")
    assert os.path.exists(prefix + ".json")

    pred, feed_names, fetch_names = static.load_inference_model(prefix, exe)
    assert feed_names == ["x"]
    xv = np.random.default_rng(5).standard_normal((2, 3)).astype(np.float32)
    (ov,) = pred.run([xv])
    ref = np.tanh(xv @ np.asarray(layer.weight._value) + np.asarray(layer.bias._value))
    np.testing.assert_allclose(ov, ref, atol=1e-5)

    # handle-style API (reference zero-copy handles)
    h = pred.get_input_handle("x")
    h.copy_from_cpu(xv)
    pred.run()
    out_h = pred.get_output_handle(pred.get_output_names()[0])
    np.testing.assert_allclose(out_h.copy_to_cpu(), ref, atol=1e-5)


def test_enable_disable_static():
    assert static.in_dynamic_mode()
    static.enable_static()
    try:
        assert not static.in_dynamic_mode()
        x = static.data("xs", [2, 2], "float32")
        y = paddle.exp(x)
        assert isinstance(y, static.Variable)
    finally:
        static.disable_static()
    assert static.in_dynamic_mode()
    t = paddle.exp(paddle.ones([2]))
    assert not isinstance(t, static.Variable)


def test_static_gradients_api():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        y = paddle.sum(x * x)
        (gx,) = static.gradients([y], [x])
    exe = static.Executor()
    xv = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    (gv,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(gv, 2 * xv, rtol=1e-6)


def test_jit_save_load_predictor(tmp_path):
    paddle.seed(7)
    layer = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = str(tmp_path / "jit" / "net")
    paddle.jit.save(layer, path, input_spec=[static.InputSpec([2, 4], "float32", name="inp")])

    pred = paddle.jit.load(path)
    xv = np.random.default_rng(11).standard_normal((2, 4)).astype(np.float32)
    (ov,) = pred.run([xv])
    layer.eval()
    ref = np.asarray(layer(paddle.to_tensor(xv))._value)
    np.testing.assert_allclose(ov, ref, atol=1e-5)


def test_jit_save_dynamic_batch(tmp_path):
    paddle.seed(9)
    layer = nn.Linear(4, 2)
    path = str(tmp_path / "dynb" / "net")
    paddle.jit.save(layer, path, input_spec=[static.InputSpec([None, 4], "float32", name="x")])
    pred = paddle.jit.load(path)
    layer.eval()
    for bs in (1, 3, 16):
        xv = np.random.default_rng(bs).standard_normal((bs, 4)).astype(np.float32)
        (ov,) = pred.run([xv])
        ref = np.asarray(layer(paddle.to_tensor(xv))._value)
        np.testing.assert_allclose(ov, ref, atol=1e-5)


def test_static_program_cond_and_while():
    """cond/while_loop recorded into a static Program (reference
    if_instruction.cc / while_instruction.cc sub-interpreters; here ONE
    operator replaying the branches under lax control flow)."""
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4], "float32")
            y = static.nn.cond(x.sum() > 0, lambda: x * 2, lambda: x - 1)
            i0 = paddle.zeros([], dtype="int32")
            s0 = paddle.ones([])
            iv, sv = static.nn.while_loop(
                lambda i, s: s < x.sum() + 10, lambda i, s: (i + 1, s * 2.0), [i0, s0]
            )
        exe = static.Executor()
        out = exe.run(main, feed={"x": np.ones(4, np.float32)}, fetch_list=[y, sv])
        np.testing.assert_allclose(out[0], 2 * np.ones(4, np.float32))
        assert float(out[1]) == 16.0
        out2 = exe.run(main, feed={"x": -np.ones(4, np.float32)}, fetch_list=[y, sv])
        np.testing.assert_allclose(out2[0], -2 * np.ones(4, np.float32))
        assert float(out2[1]) == 8.0
    finally:
        paddle.disable_static()


def test_program_dce_pass():
    """Program-level DCE (reference dead_code_elimination_pass.cc): ops
    unreachable from the fetch/write frontier are pruned."""
    from paddle_tpu.static.passes import dead_code_elimination

    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4], "float32")
            used = x * 2
            dead1 = x + 100.0     # never fetched
            dead2 = dead1 * dead1  # depends only on dead
            y = used + 1.0
        n_before = len(main.global_block().ops)
        removed = dead_code_elimination(main, [y])
        assert removed >= 2, (n_before, removed)
        exe = static.Executor()
        out = exe.run(main, feed={"x": np.ones(4, np.float32)}, fetch_list=[y])
        np.testing.assert_allclose(out[0], 3 * np.ones(4, np.float32))
    finally:
        paddle.disable_static()


def test_static_programs_pass_ir_verification():
    """Property: every Program this module's canonical paths build —
    capture, layer capture, append_backward, minimize-train, cond/while,
    DCE — passes the IR verifier with the fusion pipeline on
    (static/verify.py; sweep the full suite with tools/lint_ir.py)."""
    from paddle_tpu.static.verify import ProgramVerifier, track_programs

    paddle.seed(0)
    with track_programs() as programs:
        test_program_capture_and_run()
        test_layer_capture_registers_params()
        test_append_backward_grads()
        test_static_training_minimize_loss_decreases()
        test_static_program_cond_and_while()
        test_program_dce_pass()

    assert len(programs) >= 6
    verifier = ProgramVerifier()
    for prog in programs:
        violations = verifier.verify(prog)
        assert not violations, (
            f"program {[op.type for op in prog.global_block().ops]}: "
            f"{[str(v) for v in violations]}")


def test_bert_jit_save_predictor_roundtrip(tmp_path):
    """Serving integration: jit.save a BERT classifier -> inference
    Predictor reproduces eager logits (reference save_inference_model +
    AnalysisPredictor path)."""
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.models import BertForSequenceClassification, bert_tiny
    import paddle_tpu.jit as jit

    paddle.seed(0)
    m = BertForSequenceClassification(bert_tiny(), num_classes=2)
    m.eval()
    ids = np.random.default_rng(0).integers(1, 1000, (2, 16)).astype(np.int32)
    with paddle.no_grad():
        ref = np.asarray(m(paddle.to_tensor(ids))._value)

    path = str(tmp_path / "bert_clf")
    jit.save(m, path, input_spec=[static.InputSpec([2, 16], "int32", "ids")])
    cfg = Config(path + ".pdmodel", path + ".pdparams")
    pred = create_predictor(cfg)
    in_names = pred.get_input_names()
    h = pred.get_input_handle(in_names[0])
    h.copy_from_cpu(ids)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)
