"""MoE layer + gates + EP all-to-all dispatch (reference:
python/paddle/incubate/distributed/models/moe/ and
test/collective MoE worker scripts)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.communication import collective_axis_scope
from paddle_tpu.incubate.distributed.models.moe import MoELayer, GShardGate, SwitchGate


def _expert(d, seed):
    lin = nn.Linear(d, d, bias_attr=False)
    w = np.random.default_rng(seed).standard_normal((d, d)).astype(np.float32) * 0.1
    lin.weight._bind(jnp.asarray(w))
    return lin


def test_gate_dispatch_shapes_and_weights():
    d, e = 16, 4
    gate = GShardGate(d, e)
    x = paddle.to_tensor(np.random.default_rng(0).standard_normal((12, d)).astype(np.float32))
    combine, dispatch, aux = gate.dispatch(x)
    t, cap = 12, combine.shape[-1]
    assert combine.shape == [t, e, cap] and dispatch.shape == [t, e, cap]
    cw = np.asarray(combine._value)
    # per-token combine weights sum to 1 (two experts, normalized) or 0 (dropped)
    sums = cw.sum(axis=(1, 2))
    assert np.all((np.abs(sums - 1.0) < 1e-5) | (np.abs(sums) < 1e-6))
    assert float(aux._value) > 0.0


def test_moe_layer_world1_forward_backward():
    d = 16
    layer = MoELayer(d, [_expert(d, i) for i in range(4)], gate="gshard", capacity_factor=8.0)
    x = paddle.to_tensor(np.random.default_rng(1).standard_normal((2, 6, d)).astype(np.float32))
    x.stop_gradient = False
    out = layer(x)
    assert out.shape == [2, 6, d]
    (out.sum() + layer.aux_loss).backward()
    assert x.grad is not None
    assert layer.gate.linear.weight.grad is not None
    assert layer.experts[0].weight.grad is not None


def test_moe_layer_matches_dense_topk_with_high_capacity():
    """With capacity >= tokens, MoE output == sum of gate-weighted expert outs."""
    d = 8
    experts = [_expert(d, 10 + i) for i in range(2)]
    layer = MoELayer(d, experts, gate="switch", capacity_factor=32.0)
    x_np = np.random.default_rng(2).standard_normal((1, 5, d)).astype(np.float32)
    x = paddle.to_tensor(x_np)
    out = np.asarray(layer(x)._value).reshape(5, d)

    logits = x_np.reshape(5, d) @ np.asarray(layer.gate.linear.weight._value)
    gates = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    top1 = np.argmax(np.asarray(gates), axis=-1)
    ref = np.zeros((5, d), np.float32)
    for t in range(5):
        e = int(top1[t])
        w = np.asarray(experts[e].weight._value)
        ref[t] = (x_np.reshape(5, d)[t] @ w) * 1.0  # switch: weight normalized to 1
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_moe_expert_parallel_matches_world1():
    """EP over 4 ranks == same computation at world 1 (batch gathered)."""
    d, n_exp = 8, 4
    tokens = 16
    np.random.seed(3)
    x_np = np.random.default_rng(3).standard_normal((tokens, d)).astype(np.float32)

    def build():
        experts = [_expert(d, 50 + i) for i in range(n_exp)]
        layer = MoELayer(d, experts, gate="switch", capacity_factor=float(tokens))
        gw = np.random.default_rng(99).standard_normal((d, n_exp)).astype(np.float32)
        layer.gate.linear.weight._bind(jnp.asarray(gw))
        return layer

    ref_layer = build()
    ref = np.asarray(ref_layer(paddle.to_tensor(x_np))._value)

    # EP: 4 ranks, 1 local expert each; every rank sees the same tokens but
    # dispatch capacity is per-rank; replicate tokens over ranks and compare.
    mesh = Mesh(np.array(jax.devices()[:4]), ("ep",))
    ep_layer = build()
    # distribute experts: rank r owns expert r (bind same weights)
    for i in range(n_exp):
        ep_layer.experts[i].weight._bind(ref_layer.experts[i].weight._value)

    group = dist.new_group(ranks=list(range(4)), axis="ep")

    def body(xl):
        with collective_axis_scope({"ep": "ep"}):
            local = MoELayer.__new__(MoELayer)
            local.__dict__.update(ep_layer.__dict__)
            local.moe_group = group
            local.ep_world = 4
            local.num_local_experts = 1
            # rank picks its expert by axis index
            idx = jax.lax.axis_index("ep")
            # materialize stacked weights and select this rank's expert
            stacked = jnp.stack([np.asarray(e.weight._value) for e in ep_layer.experts])
            w_local = jax.lax.dynamic_index_in_dim(stacked, idx, 0, keepdims=False)
            exp = nn.Linear(d, d, bias_attr=False)
            exp.weight._bind(w_local)
            local.experts = nn.LayerList([exp])
            out = local(paddle.to_tensor(xl))
            return out._value

    out = shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)(jnp.asarray(x_np))
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)
