"""MoE layer + gates + EP all-to-all dispatch (reference:
python/paddle/incubate/distributed/models/moe/ and
test/collective MoE worker scripts)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from paddle_tpu.distributed.shard_map_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.communication import collective_axis_scope
from paddle_tpu.incubate.distributed.models.moe import MoELayer, GShardGate, SwitchGate


def _expert(d, seed):
    lin = nn.Linear(d, d, bias_attr=False)
    w = np.random.default_rng(seed).standard_normal((d, d)).astype(np.float32) * 0.1
    lin.weight._bind(jnp.asarray(w))
    return lin


def test_gate_dispatch_shapes_and_weights():
    d, e = 16, 4
    gate = GShardGate(d, e)
    x = paddle.to_tensor(np.random.default_rng(0).standard_normal((12, d)).astype(np.float32))
    combine, dispatch, aux = gate.dispatch(x)
    t, cap = 12, combine.shape[-1]
    assert combine.shape == [t, e, cap] and dispatch.shape == [t, e, cap]
    cw = np.asarray(combine._value)
    # per-token combine weights sum to 1 (two experts, normalized) or 0 (dropped)
    sums = cw.sum(axis=(1, 2))
    assert np.all((np.abs(sums - 1.0) < 1e-5) | (np.abs(sums) < 1e-6))
    assert float(aux._value) > 0.0


def test_moe_layer_world1_forward_backward():
    d = 16
    layer = MoELayer(d, [_expert(d, i) for i in range(4)], gate="gshard", capacity_factor=8.0)
    x = paddle.to_tensor(np.random.default_rng(1).standard_normal((2, 6, d)).astype(np.float32))
    x.stop_gradient = False
    out = layer(x)
    assert out.shape == [2, 6, d]
    (out.sum() + layer.aux_loss).backward()
    assert x.grad is not None
    assert layer.gate.linear.weight.grad is not None
    assert layer.experts[0].weight.grad is not None


def test_moe_layer_matches_dense_topk_with_high_capacity():
    """With capacity >= tokens, MoE output == sum of gate-weighted expert outs."""
    d = 8
    experts = [_expert(d, 10 + i) for i in range(2)]
    layer = MoELayer(d, experts, gate="switch", capacity_factor=32.0)
    x_np = np.random.default_rng(2).standard_normal((1, 5, d)).astype(np.float32)
    x = paddle.to_tensor(x_np)
    out = np.asarray(layer(x)._value).reshape(5, d)

    logits = x_np.reshape(5, d) @ np.asarray(layer.gate.linear.weight._value)
    gates = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    top1 = np.argmax(np.asarray(gates), axis=-1)
    ref = np.zeros((5, d), np.float32)
    for t in range(5):
        e = int(top1[t])
        w = np.asarray(experts[e].weight._value)
        ref[t] = (x_np.reshape(5, d)[t] @ w) * 1.0  # switch: weight normalized to 1
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_moe_expert_parallel_matches_world1():
    """EP over 4 ranks == same computation at world 1 (batch gathered)."""
    d, n_exp = 8, 4
    tokens = 16
    np.random.seed(3)
    x_np = np.random.default_rng(3).standard_normal((tokens, d)).astype(np.float32)

    def build():
        experts = [_expert(d, 50 + i) for i in range(n_exp)]
        layer = MoELayer(d, experts, gate="switch", capacity_factor=float(tokens))
        gw = np.random.default_rng(99).standard_normal((d, n_exp)).astype(np.float32)
        layer.gate.linear.weight._bind(jnp.asarray(gw))
        return layer

    ref_layer = build()
    ref = np.asarray(ref_layer(paddle.to_tensor(x_np))._value)

    # EP: 4 ranks, 1 local expert each; every rank sees the same tokens but
    # dispatch capacity is per-rank; replicate tokens over ranks and compare.
    mesh = Mesh(np.array(jax.devices()[:4]), ("ep",))
    ep_layer = build()
    # distribute experts: rank r owns expert r (bind same weights)
    for i in range(n_exp):
        ep_layer.experts[i].weight._bind(ref_layer.experts[i].weight._value)

    group = dist.new_group(ranks=list(range(4)), axis="ep")

    def body(xl):
        with collective_axis_scope({"ep": "ep"}):
            local = MoELayer.__new__(MoELayer)
            local.__dict__.update(ep_layer.__dict__)
            local.moe_group = group
            local.ep_world = 4
            local.num_local_experts = 1
            # rank picks its expert by axis index
            idx = jax.lax.axis_index("ep")
            # materialize stacked weights and select this rank's expert
            stacked = jnp.stack([np.asarray(e.weight._value) for e in ep_layer.experts])
            w_local = jax.lax.dynamic_index_in_dim(stacked, idx, 0, keepdims=False)
            exp = nn.Linear(d, d, bias_attr=False)
            exp.weight._bind(w_local)
            local.experts = nn.LayerList([exp])
            out = local(paddle.to_tensor(xl))
            return out._value

    out = shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)(jnp.asarray(x_np))
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)


@pytest.mark.slow  # XLA:CPU aborts (SIGSEGV/SIGABRT) compiling this 8-way
# sharded MoE train step on jax 0.4.37 — a process-killing crash, not a
# failure, so it must stay out of the tier-1 pass; runs on real meshes
def test_moe_transformer_trains_semi_auto():
    """ERNIE-MoE-shaped end-to-end (BASELINE stretch row, track level): a
    tiny transformer whose FFN is a MoELayer trains under the semi-auto
    sharded step on the 8-device mesh — aux (load-balance) loss included,
    losses decrease, dp batch sharding via GSPMD."""
    from jax.sharding import PartitionSpec
    from paddle_tpu.distributed import ProcessMesh, ShardedTrainStep

    d, n_exp, V, S = 16, 4, 64, 8

    class MoEBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            self.norm = nn.LayerNorm(d)
            self.attn = nn.MultiHeadAttention(d, 2)
            self.norm2 = nn.LayerNorm(d)
            self.moe = MoELayer(d, [_expert(d, 70 + i) for i in range(n_exp)],
                                gate="gshard", capacity_factor=2.0)

        def forward(self, h):
            h = h + self.attn(self.norm(h))
            return h + self.moe(self.norm2(h))

    class MoELM(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(V, d)
            self.blocks = nn.LayerList([MoEBlock(), MoEBlock()])
            self.head = nn.Linear(d, V)

        def forward(self, ids):
            h = self.emb(ids)
            for b in self.blocks:
                h = b(h)
            return self.head(h)

        def aux_loss(self):
            import functools
            losses = [b.moe.aux_loss for b in self.blocks if b.moe.aux_loss is not None]
            if not losses:
                return None
            return functools.reduce(lambda a, c: a + c, losses)

    paddle.seed(31)
    model = MoELM()
    opt = paddle.optimizer.AdamW(5e-3, parameters=model.parameters())
    mesh = ProcessMesh(np.arange(8), ["dp"])

    def loss_fn(m, ids, labels):
        import paddle_tpu.nn.functional as F

        logits = m(ids)
        loss = F.cross_entropy(logits.reshape([-1, V]), labels.reshape([-1]))
        aux = m.aux_loss()
        return loss + 0.01 * aux if aux is not None else loss

    step = ShardedTrainStep(model, opt, loss_fn, mesh,
                            batch_spec=PartitionSpec("dp"), zero_stage=1)
    rng = np.random.default_rng(7)
    ids = paddle.to_tensor(rng.integers(0, V, (8, S)).astype(np.int32))
    labels = paddle.to_tensor(rng.integers(0, V, (8, S)).astype(np.int64))
    losses = [float(step(ids, labels)._value) for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
